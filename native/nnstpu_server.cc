// nnstpu_server — GIL-free query-server transport core.
//
// Native equivalent of the reference's server halves of
// gst/nnstreamer/tensor_query/tensor_query_common.c + tensor_query_server.c:
// listen, accept, per-client framed TCP reassembly, handshake
// (REQUEST_INFO → APPROVE + CLIENT_ID), PING, and result routing by client
// id. One epoll thread owns all sockets — no per-client Python threads, no
// GIL churn per frame; Python pops complete TRANSFER payloads and pushes
// RESULT frames through ctypes (nnstreamer_tpu/query/server.py).
//
// Concurrency contract:
// - the epoll thread is the ONLY thread that creates/destroys connections;
//   foreign threads request closes via the to_close list + wake eventfd
// - per-connection write mutex serializes epoll-thread replies (handshake,
//   ping) against Python-thread result sends, so frames never interleave
// - nnstpu_server_take is the single wait+copy+pop primitive (atomic under
//   the server mutex — no wait/pop pairing races)
// - nnstpu_server_stop drains blocked takers (waiters counter) before the
//   Server is freed
//
// Framing (little-endian, shared with nnstpu.cc / query/protocol.py):
//   u32 magic 'NTQ1'  u32 command  u64 payload_len  payload…
//
// Wire modes (nnstpu_server_start2): 0 = NTQ1 above; 1/2 = the
// REFERENCE query wire (tensor_query_common.c:320-450 raw host
// structs: i32 cmd, then u64 size+bytes / 176-byte DataInfo / i64
// client id). Mode 1 plays the server-src port (CLIENT_ID on accept,
// REQUEST_INFO→APPROVE, TRANSFER_START/DATA/END assembly → queue);
// mode 2 plays the server-sink port (CLIENT_ID claim remaps the
// connection so nnstpu_server_send_raw routes results by claimed id).

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <poll.h>
#include <unistd.h>
#include <fcntl.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x4E545131;  // 'NTQ1'
enum Cmd : uint32_t {
  kRequestInfo = 1,
  kApprove = 2,
  kTransfer = 4,
  kResult = 5,
  kClientId = 6,
  kPing = 7,
  kBye = 8,
};

struct Frame {
  uint32_t client_id;
  std::vector<uint8_t> payload;
};

// reference TensorQueryCommand values (tensor_query_common.h:46-56)
enum RefCmd : int32_t {
  kRefRequestInfo = 0,
  kRefApprove = 1,
  kRefDeny = 2,
  kRefTransferStart = 3,
  kRefTransferData = 4,
  kRefTransferEnd = 5,
  kRefClientId = 6,
};
constexpr size_t kRefDataInfoSize = 176;  // sizeof(TensorQueryDataInfo)

struct Conn {
  int fd = -1;
  uint32_t id = 0;
  std::vector<uint8_t> inbuf;
  // reference-wire TRANSFER assembly (wire mode 1): DataInfo + mems
  // accumulated until TRANSFER_END completes the buffer
  std::vector<uint8_t> ref_asm;
  uint32_t ref_mems_left = 0;
  bool ref_in_transfer = false;
  // serializes writers to this socket: epoll-thread replies vs Python-
  // thread result sends (shared_ptr: senders may outlive the Conn)
  std::shared_ptr<std::mutex> wmu = std::make_shared<std::mutex>();
};

int set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  return fl < 0 ? -1 : fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

// blocking send of a whole frame on a possibly-nonblocking fd; caller must
// hold the connection's write mutex. stall_ms caps each EAGAIN wait:
// result sends from Python threads tolerate slow readers (10 s); the epoll
// thread uses a short cap so one unresponsive client cannot stall accept
// and every other connection — a client that cannot drain a 16-byte reply
// within it is closed instead.
int send_frame_all(int fd, uint32_t cmd, const uint8_t* payload,
                   uint64_t len, int stall_ms = 10000) {
  uint8_t hdr[16];
  memcpy(hdr, &kMagic, 4);
  memcpy(hdr + 4, &cmd, 4);
  memcpy(hdr + 8, &len, 8);
  const uint8_t* bufs[2] = {hdr, payload};
  size_t lens[2] = {sizeof(hdr), (size_t)len};
  for (int part = 0; part < 2; part++) {
    size_t off = 0;
    while (off < lens[part]) {
      ssize_t n = send(fd, bufs[part] + off, lens[part] - off, MSG_NOSIGNAL);
      if (n < 0) {
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          struct pollfd p = {fd, POLLOUT, 0};
          if (poll(&p, 1, stall_ms) <= 0) return -1;  // write stall cap
          continue;
        }
        return -1;
      }
      off += (size_t)n;
    }
  }
  return 0;
}

// epoll-thread reply budget (handshake/ping frames are tiny)
constexpr int kLoopSendStallMs = 1000;

struct Server {
  int listen_fd = -1;
  int epoll_fd = -1;
  int wake_fd = -1;  // eventfd: stop / queue-drain re-arm / deferred close
  uint16_t port = 0;
  std::string caps;
  size_t max_queue = 64;
  int wire = 0;  // 0 NTQ1, 1 reference src-port, 2 reference sink-port

  std::thread loop;
  std::atomic<bool> stopping{false};

  std::mutex mu;  // guards all fields below
  std::condition_variable cv;
  std::unordered_map<int, Conn> conns;  // by fd; epoll thread only mutates
  std::unordered_map<uint32_t, std::pair<int, std::shared_ptr<std::mutex>>>
      by_id;  // id → (fd, write mutex)
  std::deque<Frame> queue;
  // foreign-thread close requests, by CLIENT ID — fds can be closed and
  // reused by a new accept before the epoll thread processes the request;
  // ids are monotonic and never reused
  std::vector<uint32_t> to_close;
  uint32_t next_id = 1;
  bool paused = false;  // EPOLLIN de-registered while queue is full
  int waiters = 0;      // threads blocked in nnstpu_server_take

  void run();
  void close_conn_locked(int fd);
  void handle_readable(int fd);
  bool parse_frames(Conn& c);      // false → close the connection
  bool parse_ref_frames(Conn& c);  // reference-wire parser (modes 1/2)
  void set_reads_enabled_locked(bool on);
  void wake() {
    uint64_t v = 1;
    ssize_t r = write(wake_fd, &v, 8);
    (void)r;
  }
};

void Server::close_conn_locked(int fd) {
  auto it = conns.find(fd);
  if (it == conns.end()) return;
  // erase the routing entry only if it still points at THIS socket — a
  // reconnecting client may have re-claimed the id onto a new fd
  auto bi = by_id.find(it->second.id);
  if (bi != by_id.end() && bi->second.first == fd) by_id.erase(bi);
  conns.erase(it);
  epoll_ctl(epoll_fd, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
}

void Server::set_reads_enabled_locked(bool on) {
  if (paused == !on) return;
  paused = !on;
  for (auto& [fd, c] : conns) {
    struct epoll_event ev {};
    ev.data.fd = fd;
    ev.events = on ? (uint32_t)EPOLLIN : 0u;
    epoll_ctl(epoll_fd, EPOLL_CTL_MOD, fd, &ev);
  }
}

bool Server::parse_frames(Conn& c) {
  size_t off = 0;
  while (c.inbuf.size() - off >= 16) {
    uint32_t magic, cmd;
    uint64_t len;
    memcpy(&magic, c.inbuf.data() + off, 4);
    memcpy(&cmd, c.inbuf.data() + off + 4, 4);
    memcpy(&len, c.inbuf.data() + off + 8, 8);
    if (magic != kMagic || len > (1ULL << 33)) return false;
    if (c.inbuf.size() - off - 16 < len) break;  // incomplete
    const uint8_t* payload = c.inbuf.data() + off + 16;
    off += 16 + len;
    switch (cmd) {
      case kRequestInfo: {
        std::lock_guard<std::mutex> w(*c.wmu);
        if (send_frame_all(c.fd, kApprove, (const uint8_t*)caps.data(),
                           caps.size(), kLoopSendStallMs) != 0)
          return false;
        char idbuf[16];
        int n = snprintf(idbuf, sizeof(idbuf), "%u", c.id);
        if (send_frame_all(c.fd, kClientId, (const uint8_t*)idbuf,
                           (uint64_t)n, kLoopSendStallMs) != 0)
          return false;
        break;
      }
      case kPing: {
        std::lock_guard<std::mutex> w(*c.wmu);
        if (send_frame_all(c.fd, kPing, nullptr, 0, kLoopSendStallMs) != 0)
          return false;
        break;
      }
      case kBye:
        return false;  // orderly close
      case kTransfer: {
        std::lock_guard<std::mutex> g(mu);
        queue.push_back({c.id, std::vector<uint8_t>(payload, payload + len)});
        if (queue.size() >= max_queue) set_reads_enabled_locked(false);
        cv.notify_all();
        break;
      }
      default:
        return false;  // unknown command: drop the connection
    }
  }
  if (off) c.inbuf.erase(c.inbuf.begin(), c.inbuf.begin() + off);
  return true;
}

// raw (unframed) blocking send; caller holds the write mutex. Used for
// reference-wire replies/results whose framing Python (or this parser)
// already laid out byte-exactly.
int send_raw_all(int fd, const uint8_t* data, uint64_t len,
                 int stall_ms = 10000) {
  size_t off = 0;
  while (off < len) {
    ssize_t n = send(fd, data + off, len - off, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        struct pollfd p = {fd, POLLOUT, 0};
        if (poll(&p, 1, stall_ms) <= 0) return -1;
        continue;
      }
      return -1;
    }
    off += (size_t)n;
  }
  return 0;
}

// Incremental parser for the reference query wire
// (tensor_query_common.c:320-391 receive logic, byte-for-byte). Every
// message: i32 cmd, then a cmd-specific body. Wire mode 1 (src port)
// accepts REQUEST_INFO + TRANSFER sequences; mode 2 (sink port)
// accepts only the CLIENT_ID claim.
bool Server::parse_ref_frames(Conn& c) {
  size_t off = 0;
  for (;;) {
    if (c.inbuf.size() - off < 4) break;
    int32_t cmd;
    memcpy(&cmd, c.inbuf.data() + off, 4);
    size_t pos = off + 4;
    if (cmd == kRefRequestInfo || cmd == kRefTransferData) {
      if (c.inbuf.size() - pos < 8) break;
      uint64_t len;
      memcpy(&len, c.inbuf.data() + pos, 8);
      if (len > (1ULL << 33)) return false;
      pos += 8;
      if (c.inbuf.size() - pos < len) break;
      const uint8_t* body = c.inbuf.data() + pos;
      pos += len;
      if (cmd == kRefRequestInfo) {
        if (wire != 1) return false;
        // client caps in body (ignored: the server pipeline's caps
        // gate); reply APPROVE with our caps, NUL-terminated
        std::lock_guard<std::mutex> w(*c.wmu);
        uint8_t hdr[12];
        int32_t ap = kRefApprove;
        uint64_t clen = caps.size() + 1;
        memcpy(hdr, &ap, 4);
        memcpy(hdr + 4, &clen, 8);
        if (send_raw_all(c.fd, hdr, 12, kLoopSendStallMs) != 0 ||
            send_raw_all(c.fd, (const uint8_t*)caps.c_str(), clen,
                         kLoopSendStallMs) != 0)
          return false;
      } else {  // TRANSFER_DATA
        if (wire != 1 || !c.ref_in_transfer || c.ref_mems_left == 0)
          return false;
        c.ref_asm.insert(c.ref_asm.end(), body, body + len);
        c.ref_mems_left--;
      }
    } else if (cmd == kRefTransferStart || cmd == kRefTransferEnd) {
      if (c.inbuf.size() - pos < kRefDataInfoSize) break;
      const uint8_t* info = c.inbuf.data() + pos;
      pos += kRefDataInfoSize;
      if (wire != 1) return false;
      if (cmd == kRefTransferStart) {
        if (c.ref_in_transfer) return false;
        uint32_t num_mems;
        memcpy(&num_mems, info + 40, 4);
        if (num_mems > 16) return false;
        c.ref_asm.assign(info, info + kRefDataInfoSize);
        c.ref_mems_left = num_mems;
        c.ref_in_transfer = true;
      } else {  // TRANSFER_END completes the buffer
        if (!c.ref_in_transfer || c.ref_mems_left != 0) return false;
        c.ref_in_transfer = false;
        std::lock_guard<std::mutex> g(mu);
        queue.push_back({c.id, std::move(c.ref_asm)});
        c.ref_asm = {};
        if (queue.size() >= max_queue) set_reads_enabled_locked(false);
        cv.notify_all();
      }
    } else if (cmd == kRefClientId) {
      if (c.inbuf.size() - pos < 8) break;
      int64_t claimed;
      memcpy(&claimed, c.inbuf.data() + pos, 8);
      pos += 8;
      if (wire != 2) return false;
      // sink-port claim: route results for `claimed` to this socket
      // (ids are assigned by our src-port server, so they fit u32).
      // The accept-order id was never registered (see accept), so this
      // cannot clobber another client's routing entry; a re-claim of
      // the same id (client reconnect) replaces the stale entry.
      std::lock_guard<std::mutex> g(mu);
      c.id = (uint32_t)claimed;
      by_id[c.id] = {c.fd, c.wmu};
    } else {
      return false;  // unknown command: drop the connection
    }
    off = pos;
  }
  if (off) c.inbuf.erase(c.inbuf.begin(), c.inbuf.begin() + off);
  return true;
}

void Server::handle_readable(int fd) {
  Conn* c;
  {
    std::lock_guard<std::mutex> g(mu);
    auto it = conns.find(fd);
    if (it == conns.end()) return;
    c = &it->second;  // stable: only this (epoll) thread erases conns
  }
  uint8_t tmp[1 << 16];
  for (;;) {
    ssize_t n = recv(fd, tmp, sizeof(tmp), 0);
    if (n > 0) {
      c->inbuf.insert(c->inbuf.end(), tmp, tmp + n);
      if (!(wire == 0 ? parse_frames(*c) : parse_ref_frames(*c))) {
        std::lock_guard<std::mutex> g(mu);
        close_conn_locked(fd);
        return;
      }
      // stop pulling more once the queue paused reads
      std::lock_guard<std::mutex> g(mu);
      if (paused) return;
      continue;
    }
    if (n == 0 || (errno != EINTR && errno != EAGAIN &&
                   errno != EWOULDBLOCK)) {
      std::lock_guard<std::mutex> g(mu);
      close_conn_locked(fd);
      return;
    }
    if (errno == EAGAIN || errno == EWOULDBLOCK) return;
  }
}

void Server::run() {
  constexpr int kMaxEvents = 64;
  struct epoll_event evs[kMaxEvents];
  while (!stopping.load(std::memory_order_relaxed)) {
    {  // deferred closes requested by foreign threads (kick)
      std::lock_guard<std::mutex> g(mu);
      for (uint32_t id : to_close) {
        auto it = by_id.find(id);
        if (it != by_id.end()) close_conn_locked(it->second.first);
      }
      to_close.clear();
    }
    int n = epoll_wait(epoll_fd, evs, kMaxEvents, 200);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n; i++) {
      int fd = evs[i].data.fd;
      if (fd == wake_fd) {
        uint64_t v;
        ssize_t r = read(wake_fd, &v, 8);
        (void)r;  // drained; purpose is the wakeup itself
        continue;
      }
      if (fd == listen_fd) {
        for (;;) {
          int cfd = accept(listen_fd, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          uint32_t cid;
          std::shared_ptr<std::mutex> cwmu;
          {
            std::lock_guard<std::mutex> g(mu);
            Conn c;
            c.fd = cfd;
            c.id = cid = next_id++;
            cwmu = c.wmu;
            // a sink-port (wire 2) connection routes by the id it CLAIMS,
            // not its accept-order id — registering the auto id here
            // would collide with another client's claimed id and
            // misroute its results
            if (wire != 2) by_id[c.id] = {cfd, c.wmu};
            conns.emplace(cfd, std::move(c));
            struct epoll_event ev {};
            ev.data.fd = cfd;
            ev.events = paused ? 0u : (uint32_t)EPOLLIN;
            epoll_ctl(epoll_fd, EPOLL_CTL_ADD, cfd, &ev);
          }
          if (wire == 1) {
            // reference serversrc sends the assigned client id
            // immediately on accept (tensor_query_client.c:393-401)
            uint8_t msg[12];
            int32_t cc = kRefClientId;
            int64_t cid64 = (int64_t)cid;
            memcpy(msg, &cc, 4);
            memcpy(msg + 4, &cid64, 8);
            std::lock_guard<std::mutex> w(*cwmu);
            if (send_raw_all(cfd, msg, 12, kLoopSendStallMs) != 0) {
              std::lock_guard<std::mutex> g(mu);
              close_conn_locked(cfd);
            }
          }
        }
        continue;
      }
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        std::lock_guard<std::mutex> g(mu);
        close_conn_locked(fd);
        continue;
      }
      handle_readable(fd);
    }
  }
}

}  // namespace

extern "C" {

void* nnstpu_server_start2(const char* host, int port, const char* caps,
                           int max_queue, int wire) {
  auto* s = new Server();
  s->caps = caps ? caps : "";
  if (max_queue > 0) s->max_queue = (size_t)max_queue;
  s->wire = (wire >= 0 && wire <= 2) ? wire : 0;
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (!host || !*host) {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else {
    // resolve like the Python transport does ("localhost" must NOT widen
    // to all interfaces)
    struct addrinfo hints {};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) {
      close(s->listen_fd);
      delete s;
      return nullptr;
    }
    addr.sin_addr = ((sockaddr_in*)res->ai_addr)->sin_addr;
    freeaddrinfo(res);
  }
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(s->listen_fd, 16) != 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  s->port = ntohs(addr.sin_port);
  set_nonblock(s->listen_fd);

  s->epoll_fd = epoll_create1(0);
  s->wake_fd = eventfd(0, EFD_NONBLOCK);
  struct epoll_event ev {};
  ev.data.fd = s->listen_fd;
  ev.events = EPOLLIN;
  // fd exhaustion etc. must fail loudly here (→ pure-Python fallback), not
  // hand back a live-looking server whose event loop is dead
  if (s->epoll_fd < 0 || s->wake_fd < 0 ||
      epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->listen_fd, &ev) != 0) {
    if (s->epoll_fd >= 0) close(s->epoll_fd);
    if (s->wake_fd >= 0) close(s->wake_fd);
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  ev.data.fd = s->wake_fd;
  if (epoll_ctl(s->epoll_fd, EPOLL_CTL_ADD, s->wake_fd, &ev) != 0) {
    close(s->epoll_fd);
    close(s->wake_fd);
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  s->loop = std::thread([s] { s->run(); });
  return s;
}

void* nnstpu_server_start(const char* host, int port, const char* caps,
                          int max_queue) {
  return nnstpu_server_start2(host, port, caps, max_queue, 0);
}

int nnstpu_server_port(void* h) {
  return h ? ((Server*)h)->port : -1;
}

// Atomically wait for, copy out, and pop one TRANSFER frame.
//   0 → *out_client/*out_len filled, payload copied into out
//  -1 → timeout            -2 → server stopping
//  -3 → head frame larger than cap; *out_len = required size (frame stays
//       queued — retry with a bigger buffer)
int nnstpu_server_take(void* h, int timeout_ms, uint8_t* out, uint64_t cap,
                       uint32_t* out_client, uint64_t* out_len) {
  auto* s = (Server*)h;
  bool rearm = false;
  int rc;
  {
    std::unique_lock<std::mutex> g(s->mu);
    s->waiters++;
    bool got = s->cv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                              [s] {
                                return !s->queue.empty() ||
                                       s->stopping.load();
                              });
    s->waiters--;
    if (s->stopping.load() && s->queue.empty()) {
      s->cv.notify_all();  // let stop() observe the waiter count drop
      return -2;
    }
    if (!got || s->queue.empty()) return -1;
    auto& f = s->queue.front();
    *out_client = f.client_id;
    *out_len = f.payload.size();
    if (f.payload.size() > cap) {
      rc = -3;
    } else {
      if (!f.payload.empty()) memcpy(out, f.payload.data(),
                                     f.payload.size());
      s->queue.pop_front();
      if (s->paused && s->queue.size() < s->max_queue / 2) {
        s->set_reads_enabled_locked(true);
        rearm = true;
      }
      rc = 0;
    }
  }
  if (rearm) s->wake();  // kick epoll so re-armed fds are polled promptly
  return rc;
}

// Send a framed message to one client. 0 ok, -1 unknown client, -2 error.
int nnstpu_server_send(void* h, uint32_t client_id, uint32_t cmd,
                       const uint8_t* payload, uint64_t len) {
  auto* s = (Server*)h;
  int dupfd;
  std::shared_ptr<std::mutex> wmu;
  {
    std::lock_guard<std::mutex> g(s->mu);
    auto it = s->by_id.find(client_id);
    if (it == s->by_id.end()) return -1;
    // dup under the lock: the epoll thread may close the original fd at
    // any time, and a raw fd number could be reused — the dup stays valid
    dupfd = dup(it->second.first);
    if (dupfd < 0) return -2;
    wmu = it->second.second;
  }
  int rc;
  {
    std::lock_guard<std::mutex> w(*wmu);
    rc = send_frame_all(dupfd, cmd, payload, len);
  }
  close(dupfd);
  return rc == 0 ? 0 : -2;
}

// Send pre-framed raw bytes to one client (reference-wire results whose
// framing Python laid out). 0 ok, -1 unknown client, -2 error.
int nnstpu_server_send_raw(void* h, uint32_t client_id,
                           const uint8_t* payload, uint64_t len) {
  auto* s = (Server*)h;
  int dupfd;
  std::shared_ptr<std::mutex> wmu;
  {
    std::lock_guard<std::mutex> g(s->mu);
    auto it = s->by_id.find(client_id);
    if (it == s->by_id.end()) return -1;
    dupfd = dup(it->second.first);
    if (dupfd < 0) return -2;
    wmu = it->second.second;
  }
  int rc;
  {
    std::lock_guard<std::mutex> w(*wmu);
    rc = send_raw_all(dupfd, payload, len);
  }
  close(dupfd);
  return rc == 0 ? 0 : -2;
}

// Request disconnect of one client (processed by the epoll thread).
int nnstpu_server_kick(void* h, uint32_t client_id) {
  auto* s = (Server*)h;
  std::lock_guard<std::mutex> g(s->mu);
  if (s->by_id.find(client_id) == s->by_id.end()) return -1;
  s->to_close.push_back(client_id);
  s->wake();
  return 0;
}

// Make blocked/future takes return -2 without freeing anything (callers
// drain their in-flight calls between signal_stop and stop).
void nnstpu_server_signal_stop(void* h) {
  auto* s = (Server*)h;
  s->stopping.store(true);
  s->wake();
  std::lock_guard<std::mutex> g(s->mu);
  s->cv.notify_all();
}

void nnstpu_server_stop(void* h) {
  auto* s = (Server*)h;
  s->stopping.store(true);
  s->wake();
  // drain threads blocked in nnstpu_server_take before freeing: they hold
  // (or are about to re-acquire) s->mu / s->cv
  {
    std::unique_lock<std::mutex> g(s->mu);
    s->cv.notify_all();
    while (s->waiters > 0) {
      s->cv.notify_all();
      g.unlock();
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
      g.lock();
    }
  }
  if (s->loop.joinable()) s->loop.join();
  for (auto& [fd, c] : s->conns) close(fd);
  close(s->listen_fd);
  close(s->epoll_fd);
  close(s->wake_fd);
  delete s;
}

}  // extern "C"
