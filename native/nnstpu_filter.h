// nnstpu_filter.h — C ABI for native custom filter subplugins.
//
// The reference's native extension points are tensor_filter_custom (user
// .so with a C vtable, gst/nnstreamer/tensor_filter/tensor_filter_custom.c
// + include/tensor_filter_custom.h) and the header-only C++ class API
// (include/nnstreamer_cppplugin_api_filter.hh). This header is the TPU
// framework's equivalent contract: a shared object exports
//
//     const nnstpu_filter_vtable* nnstpu_filter_get_vtable(void);
//
// and the Python runtime (nnstreamer_tpu/filters/native_filter.py) dlopens
// it and drives open → info negotiation → invoke×N → close. Tensors cross
// the boundary as raw host pointers (caller-allocated outputs), so invoke
// runs entirely outside the GIL.

#ifndef NNSTPU_FILTER_H_
#define NNSTPU_FILTER_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define NNSTPU_FILTER_ABI 1
#define NNSTPU_MAX_TENSORS 16
#define NNSTPU_MAX_RANK 8

// dtype codes follow the framework's TensorType declaration order
// (nnstreamer_tpu/tensors/types.py; matches the reference's tensor_type,
// tensor_typedef.h): int32, uint32, int16, uint16, int8, uint8, float64,
// float32, int64, uint64, float16, bfloat16 (TPU addition).
typedef struct {
  uint32_t rank;
  uint32_t dims[NNSTPU_MAX_RANK];  // row-major (numpy shape order)
  int32_t dtype;
} nnstpu_tensor_info;

typedef struct {
  uint32_t num_tensors;
  nnstpu_tensor_info info[NNSTPU_MAX_TENSORS];
} nnstpu_tensors_info;

typedef struct {
  int abi_version;  // must be NNSTPU_FILTER_ABI

  // Instantiate with the element's `custom` property string (may be NULL).
  // Returns an opaque handle, or NULL on failure.
  void* (*open)(const char* custom_props);

  void (*close)(void* handle);

  // Fill static model info. Either side may be left with num_tensors == 0
  // meaning "adapts to the negotiated stream" (then set_input_info runs).
  int (*get_model_info)(void* handle, nnstpu_tensors_info* in_info,
                        nnstpu_tensors_info* out_info);

  // Given negotiated input shapes, fill output shapes. Optional (NULL) if
  // get_model_info is fully static.
  int (*set_input_info)(void* handle, const nnstpu_tensors_info* in_info,
                        nnstpu_tensors_info* out_info);

  // Run one frame. inputs/outputs are arrays of num_tensors raw pointers;
  // output buffers are caller-allocated per the negotiated out info.
  int (*invoke)(void* handle, const void* const* inputs, void* const* outputs);
} nnstpu_filter_vtable;

// Every filter .so exports exactly this symbol.
typedef const nnstpu_filter_vtable* (*nnstpu_filter_get_vtable_fn)(void);

#ifdef __cplusplus
}
#endif

#endif  // NNSTPU_FILTER_H_
