// nnstpu — native runtime core for nnstreamer_tpu.
//
// The reference's runtime is C (GLib/GStreamer): typed buffers, an aligned
// allocator (gst/nnstreamer/tensor_allocator.c), CPU SIMD detection
// (hw_accel.c), framed TCP transport (tensor_query/tensor_query_common.c),
// and sparse transcoding (elements/gsttensorsparseutil.c). This library is
// the native-speed equivalent for the host-side hot paths of the TPU
// framework — everything device-side is XLA's job, but wire
// packing/unpacking, sparse codec, checksums and socket framing are
// CPU-bound and GIL-free here. Python binds via ctypes
// (nnstreamer_tpu/native.py) with pure-Python fallbacks.
//
// Build: make -C native   (→ native/libnnstpu.so)

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cerrno>

#include <sys/socket.h>
#include <sys/uio.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// version / capability probe
// ---------------------------------------------------------------------------
int nnstpu_abi_version() { return 1; }

// CPU feature detect (reference hw_accel.c: cpu_neon_accel_available).
// On x86 report AVX2/AVX512; on aarch64 NEON is baseline.
int nnstpu_cpu_features() {
  int feats = 0;
#if defined(__aarch64__)
  feats |= 1;  // NEON baseline on aarch64
#elif defined(__x86_64__)
  unsigned eax, ebx, ecx, edx;
  __asm__ volatile("cpuid"
                   : "=a"(eax), "=b"(ebx), "=c"(ecx), "=d"(edx)
                   : "a"(7), "c"(0));
  if (ebx & (1u << 5)) feats |= 2;   // AVX2
  if (ebx & (1u << 16)) feats |= 4;  // AVX512F
#endif
  return feats;
}

// ---------------------------------------------------------------------------
// aligned allocator (reference tensor_allocator.c: custom GstAllocator with
// configurable alignment — TPU host staging buffers want 64B+ alignment)
// ---------------------------------------------------------------------------
void* nnstpu_aligned_alloc(size_t size, size_t alignment) {
  if (alignment < sizeof(void*)) alignment = sizeof(void*);
  void* ptr = nullptr;
  if (posix_memalign(&ptr, alignment, size) != 0) return nullptr;
  return ptr;
}

void nnstpu_aligned_free(void* ptr) { free(ptr); }

// ---------------------------------------------------------------------------
// fnv1a checksum — integrity tag for wire frames (the reference's protocol
// trusts TCP; we add an end-to-end check the way its MQTT path timestamps
// do, cheap enough to be always-on)
// ---------------------------------------------------------------------------
uint64_t nnstpu_fnv1a(const uint8_t* data, size_t len) {
  uint64_t h = 1469598103934665603ULL;
  for (size_t i = 0; i < len; i++) {
    h ^= data[i];
    h *= 1099511628211ULL;
  }
  return h;
}

// ---------------------------------------------------------------------------
// sparse codec (reference gsttensorsparseutil.c: COO nnz indices + values)
// Dense -> (indices u32[], values[]) and back, elem_size in {1,2,4,8}.
// Returns nnz, or -1 on error. GIL-free: operates on raw buffers.
// ---------------------------------------------------------------------------
static inline bool is_zero(const uint8_t* p, size_t elem) {
  for (size_t i = 0; i < elem; i++)
    if (p[i]) return false;
  return true;
}

int64_t nnstpu_sparse_count(const uint8_t* dense, size_t n_elems,
                            size_t elem_size) {
  int64_t nnz = 0;
  switch (elem_size) {
    case 4: {
      const uint32_t* d = (const uint32_t*)dense;
      for (size_t i = 0; i < n_elems; i++) nnz += d[i] != 0;
      break;
    }
    case 1: {
      for (size_t i = 0; i < n_elems; i++) nnz += dense[i] != 0;
      break;
    }
    case 2: {
      const uint16_t* d = (const uint16_t*)dense;
      for (size_t i = 0; i < n_elems; i++) nnz += d[i] != 0;
      break;
    }
    case 8: {
      const uint64_t* d = (const uint64_t*)dense;
      for (size_t i = 0; i < n_elems; i++) nnz += d[i] != 0;
      break;
    }
    default:
      return -1;
  }
  return nnz;
}

int64_t nnstpu_sparse_encode(const uint8_t* dense, size_t n_elems,
                             size_t elem_size, uint32_t* out_indices,
                             uint8_t* out_values) {
  int64_t nnz = 0;
  for (size_t i = 0; i < n_elems; i++) {
    const uint8_t* p = dense + i * elem_size;
    if (!is_zero(p, elem_size)) {
      out_indices[nnz] = (uint32_t)i;
      memcpy(out_values + nnz * elem_size, p, elem_size);
      nnz++;
    }
  }
  return nnz;
}

int nnstpu_sparse_decode(const uint32_t* indices, const uint8_t* values,
                         int64_t nnz, size_t elem_size, uint8_t* out_dense,
                         size_t n_elems) {
  memset(out_dense, 0, n_elems * elem_size);
  for (int64_t i = 0; i < nnz; i++) {
    if (indices[i] >= n_elems) return -1;
    memcpy(out_dense + (size_t)indices[i] * elem_size,
           values + (size_t)i * elem_size, elem_size);
  }
  return 0;
}

// ---------------------------------------------------------------------------
// framed socket transport (reference tensor_query_common.c framing)
// Frame: u32 magic, u32 command, u64 length, payload[length].
// Scatter-gather send of header+payload in one writev; blocking recv of
// exactly one frame. Returns 0 ok, -1 error, -2 closed.
// ---------------------------------------------------------------------------
static int send_all_iov(int fd, struct iovec* iov, int iovcnt) {
  while (iovcnt > 0) {
    ssize_t n = writev(fd, iov, iovcnt);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    size_t left = (size_t)n;
    while (iovcnt > 0 && left >= iov->iov_len) {
      left -= iov->iov_len;
      iov++;
      iovcnt--;
    }
    if (iovcnt > 0) {
      iov->iov_base = (uint8_t*)iov->iov_base + left;
      iov->iov_len -= left;
    }
  }
  return 0;
}

static int recv_all(int fd, uint8_t* buf, size_t len) {
  size_t got = 0;
  while (got < len) {
    ssize_t n = recv(fd, buf + got, len - got, 0);
    if (n == 0) return -2;
    if (n < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    got += (size_t)n;
  }
  return 0;
}

int nnstpu_send_frame(int fd, uint32_t magic, uint32_t command,
                      const uint8_t* payload, uint64_t length) {
  uint8_t hdr[16];
  memcpy(hdr, &magic, 4);
  memcpy(hdr + 4, &command, 4);
  memcpy(hdr + 8, &length, 8);
  struct iovec iov[2];
  iov[0].iov_base = hdr;
  iov[0].iov_len = sizeof(hdr);
  iov[1].iov_base = (void*)payload;
  iov[1].iov_len = (size_t)length;
  return send_all_iov(fd, iov, length ? 2 : 1);
}

// recv header into out_header[16]; then caller allocs and calls
// nnstpu_recv_payload. Split so Python owns the payload buffer.
int nnstpu_recv_header(int fd, uint8_t* out_header) {
  return recv_all(fd, out_header, 16);
}

int nnstpu_recv_payload(int fd, uint8_t* out, uint64_t length) {
  return recv_all(fd, out, (size_t)length);
}

int nnstpu_set_nodelay(int fd) {
  int one = 1;
  return setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // extern "C"
