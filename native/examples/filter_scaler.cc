// Example native custom filter: elementwise scale (+passthrough).
//
// The reference ships custom-filter .so scaffolding as its fake-NN test
// backbone (tests/nnstreamer_example/custom_example_scaler/
// nnscustom_example_scaler.c); this is the same role for the TPU
// framework's native filter ABI. `custom` property grammar: "scale:<f>"
// (default 1.0 — passthrough). float32 tensors are scaled; any other
// dtype passes through unchanged.
//
// Build: make -C native examples  (→ libnnstpu_filter_scaler.so)

#include <cstdlib>
#include <cstring>
#include <string>

#include "../nnstpu_filter.h"

namespace {

struct Scaler {
  float scale = 1.0f;
  nnstpu_tensors_info in_info{};  // captured at set_input_info
};

void* scaler_open(const char* custom_props) {
  auto* s = new Scaler();
  if (custom_props != nullptr) {
    std::string props(custom_props);
    auto pos = props.find("scale:");
    if (pos != std::string::npos)
      s->scale = std::strtof(props.c_str() + pos + 6, nullptr);
  }
  return s;
}

void scaler_close(void* h) { delete static_cast<Scaler*>(h); }

int scaler_get_model_info(void*, nnstpu_tensors_info* in_info,
                          nnstpu_tensors_info* out_info) {
  in_info->num_tensors = 0;   // adapts to any stream
  out_info->num_tensors = 0;
  return 0;
}

int scaler_set_input_info(void* h, const nnstpu_tensors_info* in_info,
                          nnstpu_tensors_info* out_info) {
  auto* s = static_cast<Scaler*>(h);
  s->in_info = *in_info;
  *out_info = *in_info;  // shape/type preserving
  return 0;
}

size_t elem_count(const nnstpu_tensor_info& ti) {
  size_t n = 1;
  for (uint32_t d = 0; d < ti.rank; d++) n *= ti.dims[d];
  return n;
}

size_t dtype_size(int32_t dtype) {
  switch (dtype) {
    case 4: case 5: return 1;               // int8/uint8
    case 2: case 3: case 10: case 11: return 2;  // int16/uint16/f16/bf16
    case 0: case 1: case 7: return 4;       // int32/uint32/float32
    default: return 8;                      // 64-bit types
  }
}

int scaler_invoke(void* h, const void* const* inputs, void* const* outputs) {
  auto* s = static_cast<Scaler*>(h);
  for (uint32_t t = 0; t < s->in_info.num_tensors; t++) {
    const nnstpu_tensor_info& ti = s->in_info.info[t];
    size_t n = elem_count(ti);
    if (ti.dtype == 7) {  // float32: scale
      const float* in = static_cast<const float*>(inputs[t]);
      float* out = static_cast<float*>(outputs[t]);
      for (size_t i = 0; i < n; i++) out[i] = in[i] * s->scale;
    } else {  // other dtypes: passthrough
      std::memcpy(outputs[t], inputs[t], n * dtype_size(ti.dtype));
    }
  }
  return 0;
}

const nnstpu_filter_vtable kVtable = {
    NNSTPU_FILTER_ABI,    scaler_open,           scaler_close,
    scaler_get_model_info, scaler_set_input_info, scaler_invoke,
};

}  // namespace

extern "C" const nnstpu_filter_vtable* nnstpu_filter_get_vtable(void) {
  return &kVtable;
}
