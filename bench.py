"""Benchmark — MobileNetV2 224×224 classification pipeline on TPU.

The north-star metric (BASELINE.json): pipeline FPS + p50 per-frame latency
for the stock image-classification pipeline. This drives the REAL pipeline
(videotestsrc → tensor_converter → tensor_transform → tensor_filter[jax]
→ tensor_decoder[image_labeling] → tensor_sink) end to end — source frame
synthesis, caps negotiation, per-element stats, XLA invoke — exactly how
the reference measures itself (runtime latency/throughput around invoke,
tensor_filter.c:325-423).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "fps", "vs_baseline": N, ...}

``vs_baseline``: ratio vs the reference's TFLite CPU path on this host if
tflite is importable, else vs the driver-recorded baseline constant.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_FRAMES = int(os.environ.get("BENCH_FRAMES", "200"))
WARMUP = int(os.environ.get("BENCH_WARMUP", "10"))
IMAGE = 224

# Reference baseline: measured TFLite CPU (xnnpack) MobileNetV2 fp32 FPS on
# this class of host when tflite isn't available to measure live.
FALLBACK_BASELINE_FPS = 40.0


def build_pipeline(batch: int = 1):
    import jax.numpy as jnp

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.filters.jax_backend import register_jax_model
    from nnstreamer_tpu.models.mobilenet_v2 import mobilenet_v2

    apply_fn, params, in_info, out_info = mobilenet_v2(
        image_size=IMAGE, batch=batch, dtype=jnp.bfloat16
    )
    register_jax_model("mobilenet_v2_bench", apply_fn, params,
                       in_info=in_info, out_info=out_info)
    pipe = parse_launch(
        f"videotestsrc num-buffers={N_FRAMES} width={IMAGE} height={IMAGE} "
        "pattern=gradient ! tensor_converter ! "
        "tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-127.5,div:127.5 ! "
        "tensor_filter framework=jax model=mobilenet_v2_bench name=filter ! "
        "tensor_decoder mode=image_labeling ! "
        "queue max-size-buffers=32 prefetch-host=true ! "
        "tensor_sink name=sink to-host=true"
    )
    return pipe


def measure_pipeline() -> dict:
    lat = []
    pipe = build_pipeline()
    sink = pipe.get("sink")
    t_start = [None]
    frame_t = []

    def on_data(buf):
        frame_t.append(time.monotonic())

    sink.connect(on_data)
    t0 = time.monotonic()
    msg = pipe.run(timeout=600)
    t1 = time.monotonic()
    if msg is None or msg.kind != "eos":
        raise RuntimeError(f"bench pipeline failed: {msg}")
    # drop warmup (includes the jit compile). Sustained fps = frames/span
    # over the steady window — NOT median inter-arrival, which overstates
    # rate when arrivals are bursty (device→host syncs batch up frames).
    steady = frame_t[WARMUP:]
    if len(steady) >= 2:
        span = steady[-1] - steady[0]
        fps = (len(steady) - 1) / span
        deltas = np.diff(steady)
        p50_ms = float(np.percentile(deltas, 50)) * 1e3
        p90_ms = float(np.percentile(deltas, 90)) * 1e3
    else:
        fps = N_FRAMES / (t1 - t0)
        p50_ms = p90_ms = (t1 - t0) / N_FRAMES * 1e3
    filt = pipe.get("filter")
    return dict(fps=fps, p50_ms=p50_ms, p90_ms=p90_ms,
                invoke_latency_us=filt.get_property("latency"),
                frames=len(frame_t))


def measure_tflite_baseline() -> float | None:
    """Reference path: TFLite CPU MobileNetV2, if an interpreter exists."""
    try:
        from nnstreamer_tpu.filters.tflite_backend import _interpreter_cls

        if _interpreter_cls() is None:
            return None
    except Exception:
        return None
    return None  # no bundled .tflite model file; driver baseline applies


def _probe_accelerator(timeout_s: float = None) -> bool:
    """Check that jax device init doesn't hang (a wedged TPU tunnel blocks
    forever in PJRT client creation). Probe in a subprocess so the main
    process stays clean; fall back to CPU when unavailable."""
    import subprocess

    if timeout_s is None:
        # tunneled TPU backends can take minutes to initialize; real local
        # chips answer in seconds
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "300"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=timeout_s, text=True,
        )
        return proc.returncode == 0 and "cpu" not in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    if not _probe_accelerator():
        print("bench: accelerator unavailable/wedged; falling back to CPU",
              file=sys.stderr)
        import jax

        jax.config.update("jax_platforms", "cpu")
    stats = measure_pipeline()
    baseline = measure_tflite_baseline() or FALLBACK_BASELINE_FPS
    result = {
        "metric": "mobilenetv2_224_pipeline_fps",
        "value": round(stats["fps"], 2),
        "unit": "fps",
        "vs_baseline": round(stats["fps"] / baseline, 3),
        "p50_interarrival_ms": round(stats["p50_ms"], 3),
        "p90_interarrival_ms": round(stats["p90_ms"], 3),
        "invoke_latency_us": stats["invoke_latency_us"],
        "frames": stats["frames"],
        "baseline_fps": baseline,
        "platform": _platform(),
    }
    print(json.dumps(result))


def _platform() -> str:
    try:
        import jax

        return str(jax.devices()[0].platform)
    except Exception:  # noqa: BLE001
        return "unknown"


if __name__ == "__main__":
    main()
