"""Benchmark — MobileNetV2 224×224 classification pipeline on TPU.

The north-star metric (BASELINE.json): pipeline FPS + p50 per-frame latency
for the stock image-classification pipeline. This drives the REAL pipeline
(videotestsrc → tensor_converter → tensor_transform → tensor_filter[jax]
→ tensor_decoder[image_labeling] → tensor_sink) end to end — source frame
synthesis, caps negotiation, per-element stats, XLA invoke — exactly how
the reference measures itself (runtime latency/throughput around invoke,
tensor_filter.c:325-423).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "fps", "vs_baseline": N, ...}

``vs_baseline``: ratio vs the reference's TFLite CPU path on this host if
tflite is importable, else vs the driver-recorded baseline constant.

How to read the bound fields (the report's own limiter analysis):

- ``value`` is the steady-state (warm) median; ``fps_cold`` and the
  chronological ``fps_runs`` expose compile/tunnel warm-up separately.
- ``device_fps_ceiling`` (model dispatch alone) bounds what the CHIP
  sustains; ``pipeline_efficiency = fps_median/ceiling`` (the gated
  median-of-k statistic, not the single headline run).
- ``ingest_bound_fps`` re-runs the IDENTICAL topology with a free
  model: the ceiling the host+link+framework impose with zero model
  cost. ``vs_ingest_bound`` near 1 is the written proof that a wall
  number is transfer/framework-bound, not model- or scheduler-bound;
  above 1 means the link was slower in the probe's windows than across
  the flagship's median-of-N (volatile link, treat the bound as
  inconclusive for that session). On a tunneled dev chip the link is
  usually the governor; on-host PCIe deployments sit near
  ``device_fps_ceiling`` instead.
- ``value_norm`` / ``norm_runs`` / ``spread_norm``: weather-normalized
  score. Each flagship repeat is paired with an ingest-ceiling sample
  from the same weather window; the ratio fps/ceiling cancels tunnel
  drift, so round-over-round comparisons should use ``value_norm``
  (spread target <0.2 where raw fps can spread 0.5+). Caveat: when the
  link flips WITHIN a pair (~10 s apart) individual ratios can exceed 1
  and ``spread_norm`` blows up — that is the honest signal that the
  session's weather was oscillating faster than any pairing can cancel;
  the ``value_norm`` median is still the most comparable number.
- ``latency_p50/p99_ms`` is end-to-end per-frame latency under 30 fps
  realtime pacing (create→sink materialization, window wait included)
  with the ``latency_budget_ms`` adaptive-batching budget active: the
  aggregator flushes partial padded windows rather than holding frames
  for the full batch window (elements/aggregator.py latency-budget-ms).
  ``latency_sat_*`` is the same stat inside the saturated throughput
  runs, sampled only for frames the leaky ingress queue ADMITTED and
  measured from the admission stamp (service latency of served traffic
  — the pre-admission wait of a free-running source is backlog depth,
  not pipeline latency); ``latency_dropped_frames`` counts what the
  queue shed instead.
- ``fps_median`` / ``spread_mad``: robust companions to ``value`` /
  ``spread_warm`` — true median of the warm runs and median absolute
  deviation over it. The max−min ``spread_warm`` moves by a wild run's
  full excursion; the MAD barely notices it, so perf GATES should
  compare medians and read ``spread_mad`` for stability.
- ``slo_budget_ms`` / ``admitted_fps`` / ``shed_ratio``: the SLO
  scheduler's report card (``BENCH_SLO_BUDGET_MS`` > 0 attaches
  serving/scheduler.py to the saturated runs). ``admitted_fps`` is the
  served ADMITTED population per wall second; ``shed_ratio`` the share
  of offered traffic turned away (door rejections + post-stamp sheds).
  The SLO contract to check: ``latency_sat_p99_ms`` ≤ 2x budget while
  ``admitted_fps`` stays ≥80% of the unscheduled saturation rate.
- ``d2h_per_frame`` / ``resident_ratio``: device-residency health.
  Explicit device→host materializations per frame (sink-only
  materialization in the stock topology ⇒ one grouped fetch per
  sink-bound buffer = 1/batch; 0 once the drain-side batched fetch
  carries them) and the share of DeviceBuffer pad crossings forwarded
  without a host copy. See "Device residency" in docs/profiling.md;
  NNSTPU_RESIDENT=0 turns the layer off.
- ``h2d_batched_uploads`` / ``h2d_batched_frames`` /
  ``d2h_batched_fetches``: staged multi-frame transfer batching (one
  ``device_put``/``device_get`` per drained run — "Whole-graph fusion &
  transfer batching" in docs/profiling.md). Frames carried by these
  paid no per-frame transfer round trip.
- ``mfu_*`` use XLA's own flop count over the chip's public bf16 peak.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time

# absl/oneDNN boot banners are emitted once per process by TF/XLA's C++
# logging — and then AGAIN by every child that imports jax (the
# accelerator probe subprocess), duplicating them in the captured output
# tail. Quiet them before anything can import jax; children inherit the
# env, so the duplicate copy goes too. setdefault keeps an operator's
# explicit verbosity choice.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
os.environ.setdefault("TF_ENABLE_ONEDNN_OPTS", "0")
os.environ.setdefault("GRPC_VERBOSITY", "ERROR")

import numpy as np

#: 800 frames (100 batch-8 buffers) — long enough that the fixed per-run
#: costs (first grouped flush, trailing drain RTT) amortize below ~3% of
#: the span; shorter runs let single ~100 ms tunnel round trips dominate
#: run-to-run spread
N_FRAMES = int(os.environ.get("BENCH_FRAMES", "800"))
WARMUP = int(os.environ.get("BENCH_WARMUP", "10"))
#: tunnel throughput varies heavily run-to-run; the flagship reports the
#: median of this many runs (first run also pays the compile) — on bad
#: tunnel days single-session runs span 3x (46..141 fps observed), so 9
#: samples keep the median from landing on an outlier
REPEATS = int(os.environ.get("BENCH_REPEATS", "9"))
IMAGE = 224

# Reference baseline: measured TFLite CPU (xnnpack) MobileNetV2 fp32 FPS on
# this class of host when tflite isn't available to measure live.
FALLBACK_BASELINE_FPS = 40.0


#: flagship micro-batch: the aggregator packs this many frames into one
#: MXU dispatch. On a tunneled chip the per-dispatch RPC (~11 ms measured
#: on a bad day) is the throughput floor for batch=1 — amortizing it over
#: 8 frames is what makes the number tunnel-insensitive (the BASELINE.json
#: north-star's own mux/merge-batching prescription, applied in-stream).
BATCH = int(os.environ.get("BENCH_BATCH", "8"))

#: dispatch-window depth for the flagship filter (pipeline/dispatch.py):
#: K device batches may be outstanding before the producer fences, so the
#: host prepares batch N+1 while the chip runs batch N. 0 = synchronous.
INFLIGHT = int(os.environ.get("BENCH_INFLIGHT", "2"))

#: parallel ingest lanes (pipeline/lanes.py): the replicable pre-queue
#: host segment runs across N worker lanes with in-order reassembly.
#: Applies to the flagship AND the interleaved ingest-ceiling probe
#: (identical topology contract), so ingest_bound_fps is recomputed
#: under the same lane count the flagship runs with. NNSTPU_LANES
#: overrides; 1 restores the serial ingest path.
LANES = int(os.environ.get("BENCH_LANES", "4"))

#: fixed-length warmup drain (buffers of `batch` frames) run once before
#: the measured repeats: absorbs the jit compile, tunnel stream setup,
#: pool/lane-arena priming and the first fused-region trace so run 1 of
#: the repeat loop starts from the same steady state as run N — the
#: other half (with the gc fence in _collect) of taming spread_warm
WARMUP_DRAIN = int(os.environ.get("BENCH_WARMUP_DRAIN", "4"))

#: SLO budget in ms for the saturated runs (serving/scheduler.py): >0
#: attaches the deadline scheduler — admission control at the leaky
#: ingress, EDF ordering, shed-late-first, feedback-tuned batch cap —
#: and the JSON grows admitted_fps / shed_ratio / slo_budget_ms. 0
#: (default) is the kill switch: no scheduler object is built and the
#: pipeline runs the exact pre-scheduler path.
SLO_BUDGET_MS = float(os.environ.get("BENCH_SLO_BUDGET_MS", "0") or 0)

#: mesh-sharded serving plane (parallel/serve.py): BENCH_MESH=dp8 runs
#: the flagship with `mesh=dp8` on the tensor_filter and the JSON grows
#: `mesh` / `shard_scaling` (warm median over a single-device reference
#: run from the same weather window) / `reshard_bytes_per_frame`
#: (matched-sharding boundaries move zero bytes, so this should be 0).
#: Unset (the default) leaves the single-device path — and the JSON's
#: mesh fields are null.
MESH_SPEC = os.environ.get("BENCH_MESH", "").strip()

#: perf gates (the determinism item): the JSON grows a `gates` field
#: judging fps_median, spread_mad, and saturation p99 against these
#: thresholds. spread_mad defaults ON (warm spread under 0.15 of the
#: median); the other two arm via env / the SLO budget.
#: BENCH_ENFORCE_GATES=1 turns a failing gate into a nonzero exit.
GATE_FPS_MEDIAN_MIN = float(
    os.environ.get("BENCH_GATE_FPS_MEDIAN_MIN", "0") or 0)
GATE_SPREAD_MAD_MAX = float(
    os.environ.get("BENCH_GATE_SPREAD_MAD_MAX", "0.15") or 0)
GATE_SAT_P99_MS_MAX = float(
    os.environ.get("BENCH_GATE_SAT_P99_MS_MAX", "0")
    or (2.0 * SLO_BUDGET_MS if SLO_BUDGET_MS > 0 else 0))
ENFORCE_GATES = os.environ.get(
    "BENCH_ENFORCE_GATES", "").strip().lower() in ("1", "true", "yes", "on")

#: last measured run's flight-recorder harvest (obs/flight.py): the
#: always-on attribution/SLO snapshot, captured before the pipeline
#: object is discarded so the JSON can name the dominant-variance stage
#: without a traced run
_LAST_FLIGHT: dict = {}


def _device_fence() -> None:
    """Block until ALL previously dispatched device work retired.

    With a dispatch window (inflight>0) run N's trailing async work —
    the drained window's D2H copies, XLA donation cleanup — can still
    occupy the device when ``run()`` returns; without a fence it bleeds
    into run N+1's measurement window and into the interleaved ingest
    probe, which is exactly the warm-spread noise the per-run pairing
    exists to cancel. A trivial op enqueued now completes only after
    everything already queued on the device stream."""
    try:
        import jax
        import jax.numpy as jnp

        jnp.zeros((), jnp.int32).block_until_ready()
    except Exception:  # noqa: BLE001 — fence is best-effort on cpu-only
        pass


def _register_mnv2(batch: int) -> str:
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.jax_backend import (
        is_jax_model_registered,
        register_jax_model,
    )

    model_name = f"mobilenet_v2_bench_b{batch}"
    if not is_jax_model_registered(model_name):
        from nnstreamer_tpu.models.mobilenet_v2 import mobilenet_v2

        apply_fn, params, in_info, out_info = mobilenet_v2(
            image_size=IMAGE, batch=batch, dtype=jnp.bfloat16
        )
        register_jax_model(model_name, apply_fn, params,
                           in_info=in_info, out_info=out_info)
    return model_name


_ARTIFACT_CACHE: dict = {}


def _artifact_path(batch: int) -> str:
    """Export the flagship model as a compiled StableHLO artifact once and
    run the pipeline from the FILE (BENCH_ARTIFACT=1): proves the
    opaque-model-file path end to end at benchmark scale."""
    if batch not in _ARTIFACT_CACHE:
        import tempfile

        import jax.numpy as jnp

        from nnstreamer_tpu.filters.artifact import save_artifact
        from nnstreamer_tpu.models.mobilenet_v2 import mobilenet_v2

        apply_fn, params, in_info, _ = mobilenet_v2(
            image_size=IMAGE, batch=batch, dtype=jnp.bfloat16)
        path = os.path.join(tempfile.gettempdir(),
                            f"bench_mnv2_b{batch}.jaxexp")
        platform = "cpu"
        try:
            import jax

            platform = jax.default_backend()
        except Exception:  # noqa: BLE001
            pass
        save_artifact(path, apply_fn, params, in_info=in_info,
                      platforms=(platform,))
        _ARTIFACT_CACHE[batch] = path
    return _ARTIFACT_CACHE[batch]


def build_pipeline(batch: int = BATCH, live_fps: int = 0,
                   n_frames: int = None, model_override: str = None,
                   latency_budget_ms: int = 0):
    from nnstreamer_tpu import parse_launch

    if model_override is not None:
        model_name = model_override
    elif os.environ.get("BENCH_ARTIFACT", "").strip() in ("1", "true",
                                                          "yes"):
        model_name = _artifact_path(batch)
    else:
        model_name = _register_mnv2(batch)
    # a partial trailing window never leaves the aggregator: round the
    # frame count to a batch multiple so the configured workload is what
    # actually gets measured
    if n_frames is None:
        n_frames = N_FRAMES
    n_frames = ((n_frames + batch - 1) // batch) * batch
    live = (f"is-live=true framerate={live_fps}/1 " if live_fps else "")
    # micro-batch stage BEFORE the transform: frames cross the tunnel as
    # uint8 (4x fewer bytes than float32 — the tunnel's effective
    # bandwidth, not compute, is the bad-day ceiling) and the typecast/
    # normalize runs on-device inside the fused region with the model
    # latency-budget adaptive batching (aggregator latency-budget-ms):
    # live runs bound each frame's admission wait — a window short of
    # `batch` flushes early, padded to the compiled shape, and the sink
    # trims the padding (elements/aggregator.py). Saturated runs fill
    # windows faster than any budget fires, so throughput is untouched.
    # pad-device: partial windows ship only their real frames; the
    # staging queue zero-pads on device (a padded uint8 batch-8 window
    # is 1.2 MB — on a 6-60 MB/s tunnel, wiring pad rows is real money)
    budget = (f"latency-budget-ms={latency_budget_ms} pad-device=true "
              if latency_budget_ms else "")
    agg = (f"tensor_aggregator frames-in=1 frames-out={batch} "
           f"frames-flush={batch} frames-dim=3 concat=true {budget}! "
           if batch > 1 else "")
    # queue after the converter decouples host frame synthesis from device
    # dispatch (source thread fills frame N+1 while the fused region runs N)
    # H2D staging queue between the aggregator and the fused XLA region:
    # prefetch-device issues an async device_put on the producer side, so
    # the uint8 batch's upload overlaps the PREVIOUS batch's compute and
    # the dispatch thread never blocks on an implicit per-call transfer
    # (the pipeline analog of the serving engine's one-block-behind
    # overlap, serving/engine.py _inflight)
    # latency mode shrinks the in-flight windows (staging 4, drain 4 vs
    # 8/64): backpressure then reaches the aggregator's budget gate
    # (accepts_now) within ~8 windows, so on a saturated link budget
    # mode degrades to plain batching instead of stacking seconds of
    # queue wait; throughput mode keeps the deep queues (backlog absorb)
    stage_n, drain_n = (4, 4) if latency_budget_ms else (8, 64)
    stage = (f"queue max-size-buffers={stage_n} prefetch-device=true ! "
             if os.environ.get("BENCH_STAGE", "1").strip() not in
             ("0", "false", "no") else "")
    # saturation (non-live) runs: the source free-runs, so a blocking
    # ingress queue lets an unbounded create→sink backlog build and the
    # reported saturated p99 measures queue depth (5 s observed), not
    # service latency. leaky=downstream bounds the standing backlog to
    # the queue's capacity — frames that DO reach the sink carry a
    # bounded wait — while the delivered rate stays the bottleneck rate.
    # Live runs are already paced by the source clock and stay blocking
    # (dropping paced frames would corrupt the latency population).
    # stamp-admission marks each frame the leaky queue ACCEPTS: the sink
    # then reports a served-traffic latency population (admitted→sink)
    # next to the create-based one, and the drop counter's delta becomes
    # latency_dropped_frames — the saturated p99 stops measuring the
    # free-running source's pre-admission backlog wait
    ingress = ("queue max-size-buffers=16 ! " if live_fps else
               "queue name=q_ingress max-size-buffers=16 "
               "leaky=downstream stamp-admission=true ! ")
    pipe = parse_launch(
        f"videotestsrc num-buffers={n_frames} width={IMAGE} height={IMAGE} "
        f"pattern=gradient {live}! "
        f"tensor_converter ! {ingress}"
        f"{agg}{stage}"
        "tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-127.5,div:127.5 ! "
        f"tensor_filter framework=jax model={model_name} name=filter "
        f"{f'mesh={MESH_SPEC} ' if MESH_SPEC else ''}"
        f"inflight={INFLIGHT} ! "
        f"tensor_decoder mode=image_labeling "
        f"{'option2=batched ' if batch > 1 else ''}! "
        # a device→host flush costs ~100 ms on a tunneled chip regardless
        # of size; materialize-host drains in GROUPS (one overlapped
        # flush covers the whole backlog, pipeline/pipeline.py _drain)
        f"queue max-size-buffers={drain_n} materialize-host=true ! "
        "tensor_sink name=sink to-host=true"
    )
    pipe.lanes = LANES
    # saturation-only knob: live runs are paced by the source clock and
    # never shed, so a budget there would only add admission bookkeeping
    if SLO_BUDGET_MS > 0 and not live_fps:
        pipe.slo_budget_ms = SLO_BUDGET_MS
    return pipe


def device_probe(batch: int = BATCH, iters: int = 30) -> dict:
    """Separate the chip from the weather: time the flagship model as pure
    device dispatches (one end sync) and as blocking round trips. The gap
    between ``pipeline fps`` and ``device_fps_ceiling`` is framework
    overhead; the gap between dispatch and roundtrip is the tunnel."""
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.jax_backend import _registered

    # reuse the flagship's registered model (same weights, no re-init)
    entry = _registered.get(_register_mnv2(batch))
    apply_fn, params = entry["fn"], entry["params"]
    jf = jax.jit(apply_fn)
    params = jax.device_put(params)
    x = jax.device_put(jnp.zeros((batch, IMAGE, IMAGE, 3), jnp.float32))
    np.asarray(jf(params, x))  # compile + warm
    t0 = time.perf_counter()
    outs = [jf(params, x) for _ in range(iters)]
    np.asarray(outs[-1])
    dispatch_ms = (time.perf_counter() - t0) / iters * 1e3
    t0 = time.perf_counter()
    for _ in range(3):
        np.asarray(jf(params, x))
    roundtrip_ms = (time.perf_counter() - t0) / 3 * 1e3
    return dict(
        device_dispatch_ms_per_batch=round(dispatch_ms, 3),
        device_compute_ms_per_frame=round(dispatch_ms / batch, 4),
        device_roundtrip_ms=round(roundtrip_ms, 2),
        device_fps_ceiling=round(batch * 1e3 / dispatch_ms, 1),
    )


#: public bf16 peak TFLOP/s per chip by device kind — the MFU denominator
_TPU_PEAK_BF16 = {
    "v6": 918e12, "v5p": 459e12, "v5e": 197e12, "v5 lite": 197e12,
    "v4": 275e12, "v3": 123e12, "v2": 45e12,
}


def _peak_flops():
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001
        return None
    for key, peak in _TPU_PEAK_BF16.items():
        if key in kind:
            return peak
    return None


def _model_flops(batch: int):
    """XLA's own flop count for one flagship invoke (cost analysis on the
    lowered computation — no second compile)."""
    try:
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.filters.jax_backend import _registered

        entry = _registered.get(_register_mnv2(batch))
        x = jax.ShapeDtypeStruct((batch, IMAGE, IMAGE, 3), jnp.float32)
        lowered = jax.jit(entry["fn"]).lower(entry["params"], x)
        cost = lowered.cost_analysis()
        if cost is None:  # some backends only report post-compile
            cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = (cost or {}).get("flops")
        return float(flops) if flops else None
    except Exception as e:  # noqa: BLE001 — MFU is informative only
        print(f"bench: cost analysis unavailable ({e})", file=sys.stderr)
        return None


def ingest_probe(batch: int = BATCH) -> dict:
    """Transfer+framework ceiling measured by the pipeline itself: the
    EXACT flagship topology (same build_pipeline call — source
    synthesis, conversion, aggregation, H2D staging, transform, decoder,
    grouped D2H drain) with only the model swapped for a near-zero-FLOP
    checksum. ``ingest_bound_fps`` is therefore the fps this
    host/link/framework combination could deliver if the model were
    free; ``value/ingest_bound_fps`` close to 1 proves the flagship
    number is transfer/framework-bound, not model- or scheduler-bound.
    (Synthetic serial device_put probes are NOT used: on a tunneled
    chip their per-call RTT structure understates achievable
    throughput severalfold.)"""
    # the EXACT flagship topology (build_pipeline), model swapped only.
    # A ceiling estimate must not read LOW on a volatile link (that
    # would put the flagship "above" its own ceiling): take the best of
    # two runs.
    fps = max(ingest_run_once(batch) for _ in range(2))
    return dict(ingest_bound_fps=round(fps, 1))


def _register_ingest_model():
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.jax_backend import (
        is_jax_model_registered,
        register_jax_model,
    )

    if not is_jax_model_registered("bench_ingest_probe"):
        # [B, 16] pseudo-logits so the image_labeling decoder stage runs
        # exactly as in the flagship; compute is a reduction + broadcast
        register_jax_model(
            "bench_ingest_probe",
            lambda x: (jnp.stack(
                [jnp.sum(x, axis=(1, 2, 3)).astype(jnp.float32)] * 16,
                axis=1),),
            None)


def ingest_run_once(batch: int = BATCH) -> float:
    """One ingest-ceiling sample (see :func:`ingest_probe`). Interleaved
    with the flagship repeats so each run can be normalized by the
    link/framework ceiling measured in ITS OWN weather window —
    ``value_norm`` survives tunnel drift that swings raw fps 2-3x."""
    _register_ingest_model()
    pipe = build_pipeline(batch, model_override="bench_ingest_probe")
    return _steady_fps(_collect(pipe), frames_per_buffer=batch)


#: live-run latency budget (ms) for the aggregator's adaptive batching —
#: 50 ms ≈ a 1-2 frame window at 30 fps, chosen so p50 (window wait +
#: dispatch + grouped D2H) lands under ~100 ms on a healthy link while
#: the saturated throughput path still dispatches full batches
LAT_BUDGET_MS = int(os.environ.get("BENCH_LAT_BUDGET_MS", "50"))


def measure_latency_live(batch: int = BATCH, fps: int = 30,
                         seconds: int = 10,
                         budget_ms: int = None) -> dict:
    """Per-frame end-to-end latency under realtime pacing — the
    north-star latency half (BASELINE.md). The saturated throughput runs
    report latency too, but there it is dominated by deep-queue wait (a
    throughput-mode artifact); a 30 fps live source measures the service
    latency a realtime stream actually sees. With the latency budget
    active (default) the aggregator flushes partial padded windows, so
    the admission wait is bounded by the budget instead of the full
    batch window (batch/fps — 267 ms for batch=8 at 30 fps)."""
    if budget_ms is None:
        budget_ms = LAT_BUDGET_MS
    # warm the compile/tunnel path off the clock (a tunneled chip defers
    # compilation to first execution — without this, frames queue behind
    # the first dispatch and the percentiles measure the backlog drain)
    _collect(build_pipeline(batch, n_frames=2 * batch))
    attempts = 0
    while True:
        attempts += 1
        pipe = build_pipeline(batch, live_fps=fps, n_frames=fps * seconds,
                              latency_budget_ms=budget_ms)
        _collect(pipe)
        # drop the first two batch windows: they carry one-time pipeline
        # warm-up (first dispatch, tunnel stream setup), not steady service
        lat = pipe.get("sink").latency_percentiles(50, 99, skip=2 * batch)
        if lat is None:
            return dict(latency_p50_ms=None, latency_p99_ms=None,
                        latency_budget_ms=budget_ms,
                        latency_reruns=attempts - 1)
        # a p99 in the tens of seconds is a tunnel COLLAPSE (the link
        # stalls for 15-30 s mid-run), not a property of the pipeline:
        # one rerun, flagged so the JSON shows the measurement was
        # repeated rather than silently cherry-picked
        if lat[1] < 10_000 or attempts >= 2:
            return dict(latency_p50_ms=round(lat[0], 2),
                        latency_p99_ms=round(lat[1], 2),
                        latency_budget_ms=budget_ms,
                        latency_reruns=attempts - 1)


def _ingress_drops(pipe) -> float:
    """Cumulative leaky-ingress drop count for this pipeline's metric
    labels. The obs counter is registry-global and every bench run reuses
    the same {pipeline, element} labels, so callers diff two reads for a
    per-run number."""
    from nnstreamer_tpu.obs import get_registry

    c = get_registry().get("nns_queue_drops_total",
                           pipeline=getattr(pipe, "name", "") or "",
                           element="q_ingress")
    return float(c.value) if c is not None else 0.0


def _sched_counts(pipe) -> dict:
    """Cumulative scheduler + admission counters for this pipeline's
    labels (same diff-two-reads contract as :func:`_ingress_drops` — the
    obs registry is global and repeats reuse the labels)."""
    from nnstreamer_tpu.obs import get_registry

    reg = get_registry()
    name = getattr(pipe, "name", "") or ""

    def val(metric, **labels):
        c = reg.get(metric, **labels)
        return float(c.value) if c is not None else 0.0

    return {
        "rejected": val("nns_sched_rejected_total", pipeline=name),
        "shed": (val("nns_sched_shed_total", pipeline=name, reason="late")
                 + val("nns_sched_shed_total", pipeline=name,
                       reason="capacity")),
        "stamped": val("nns_queue_admitted_total", pipeline=name,
                       element="q_ingress"),
        "revoked": val("nns_queue_admitted_revoked_total", pipeline=name,
                       element="q_ingress"),
    }


def measure_pipeline(batch: int = BATCH) -> dict:
    from nnstreamer_tpu.tensors.buffer import transfer_snapshot

    pipe = build_pipeline(batch)
    drops0 = _ingress_drops(pipe)
    sched0 = _sched_counts(pipe)
    xfer0 = transfer_snapshot()
    frame_t = _collect(pipe)
    xfer1 = transfer_snapshot()
    drops = _ingress_drops(pipe) - drops0
    sched = {k: v - sched0[k] for k, v in _sched_counts(pipe).items()}
    warmup_arrivals = max(1, WARMUP // batch) if batch > 1 else WARMUP
    steady = frame_t[warmup_arrivals:]
    if len(steady) >= 2:
        deltas = np.diff(steady)
        # inter-ARRIVAL of sink buffers (one buffer = `batch` frames);
        # honest name — a frame's true end-to-end latency under
        # micro-batching includes waiting for its batch window, which
        # this does NOT measure
        p50_ms = float(np.percentile(deltas, 50)) * 1e3
        p90_ms = float(np.percentile(deltas, 90)) * 1e3
    elif len(frame_t) >= 2:
        p50_ms = p90_ms = \
            (frame_t[-1] - frame_t[0]) / (len(frame_t) - 1) * 1e3
    else:
        p50_ms = p90_ms = 0.0
    filt = pipe.get("filter")
    sink = pipe.get("sink")
    # served-traffic latency: frames the leaky ingress ADMITTED, measured
    # from the admission stamp. The create-based population still counts
    # the source's free-running pre-admission wait — under saturation
    # that's backlog depth, not pipeline service time (5017 ms observed).
    # Falls back to create-based when no admission stamps arrived.
    lat = sink.latency_percentiles(50, 99, base="admitted") or \
        sink.latency_percentiles(50, 99)
    # invoke tail from the same registry histogram the /metrics endpoint
    # and the post-EOS table read (obs nns_tensor_filter_invoke_seconds);
    # the windowed `latency` property alone hides compile-spike outliers
    inv_p99 = filt._obs_invoke()["invoke"].percentile(99)
    frames = len(frame_t) * batch
    d2h_events = xfer1["d2h_events"] - xfer0["d2h_events"]
    # scheduler-facing accounting over the same first-arrival→EOS window
    # _steady_fps uses: admitted_fps is the SERVED admitted population
    # (stamped frames that reached the sink) per wall second; shed_ratio
    # is the offered traffic the admission point turned away — door
    # rejections plus post-stamp sheds/drops over everything offered.
    eos_t = getattr(frame_t, "eos_t", None)
    span = (((eos_t if eos_t is not None else frame_t[-1]) - frame_t[0])
            if len(frame_t) >= 2 else 0.0)
    fr = getattr(pipe, "_flight", None)
    if fr is not None:
        _LAST_FLIGHT["attribution"] = fr.attribution()
        _LAST_FLIGHT["slo"] = fr.slo_snapshot()
    served_admitted = int(sink.admitted_latencies.count)
    offered = sched["stamped"] + sched["rejected"]
    return dict(fps=_steady_fps(frame_t, frames_per_buffer=batch),
                p50_ms=p50_ms, p90_ms=p90_ms,
                latency_p50_ms=round(lat[0], 2) if lat else None,
                latency_p99_ms=round(lat[1], 2) if lat else None,
                latency_dropped_frames=int(drops),
                admitted_fps=(round(served_admitted / span, 2)
                              if span > 0 and served_admitted else None),
                shed_ratio=(round((sched["rejected"] + sched["revoked"])
                                  / offered, 4) if offered else None),
                sched_rejected=int(sched["rejected"]),
                sched_shed=int(sched["shed"]),
                # explicit host materializations per frame — sink-only
                # materialization in the stock pipeline means one grouped
                # fetch per sink-bound buffer (= 1/batch per frame); 0
                # when the drain-side batched fetch carried every frame
                d2h_per_frame=(round(d2h_events / frames, 4)
                               if frames else None),
                d2h_bytes=int(xfer1["d2h_bytes"] - xfer0["d2h_bytes"]),
                # staged multi-frame window transfers (one device_put /
                # device_get per drained run — tensors/buffer.py): these
                # carried frames with zero per-frame round trips
                h2d_batched=int(xfer1["h2d_batched_events"]
                                - xfer0["h2d_batched_events"]),
                h2d_batched_frames=int(xfer1["h2d_batched_frames"]
                                       - xfer0["h2d_batched_frames"]),
                d2h_batched=int(xfer1["d2h_batched_events"]
                                - xfer0["d2h_batched_events"]),
                invoke_latency_us=filt.get_property("latency"),
                invoke_latency_p99_us=(round(inv_p99 * 1e6, 1)
                                       if inv_p99 is not None else None),
                frames=frames)


def measure_traced(batch: int = BATCH) -> dict:
    """One flagship run with the frame-ledger timeline active
    (obs/timeline.py): returns the run's fps plus the per-stage
    ``stage_breakdown`` and ``variance_report`` aggregations. Kept to a
    single run — the ledger's cost is the thing being measured
    (``trace_overhead_pct``), so it must not contaminate the warm
    repeats above it."""
    from nnstreamer_tpu.obs import timeline as _timeline

    _timeline.activate()
    try:
        run = measure_pipeline(batch)
        tl = _timeline.ACTIVE
        skip = max(1, WARMUP // batch) if batch > 1 else WARMUP
        breakdown = tl.stage_breakdown(skip_frames=skip)
        variance = tl.variance_report(skip_frames=skip)
    finally:
        _timeline.deactivate()
    return dict(fps=run["fps"], breakdown=breakdown, variance=variance)


def _steady_fps(frame_t, frames_per_buffer: int = 1):
    """Sustained fps = frames after the first arrival / (first arrival →
    EOS).

    The first arrival is the warmup anchor (compile + first flush land
    before it); anchoring the window END at EOS (recorded by
    :func:`_collect`) rather than the last arrival keeps the estimate
    honest under bursty arrivals: grouped D2H flushes can deliver a whole
    backlog within milliseconds, and frames/(last−first arrival) would
    then exclude the very processing time being measured."""
    eos_t = getattr(frame_t, "eos_t", None)
    if len(frame_t) < 2:
        print("bench: too few frames for a rate estimate", file=sys.stderr)
        return 0.0
    # anchor at the FIRST arrival (the post-compile instant) and EOS:
    # these bracket all remaining work, so a grouped flush delivering the
    # whole backlog in one burst cannot shrink the measured span
    span = (eos_t if eos_t is not None else frame_t[-1]) - frame_t[0]
    if span <= 0:
        return 0.0
    return (len(frame_t) - 1) * frames_per_buffer / span


class _Arrivals(list):
    """Arrival timestamps + the EOS instant (set by _collect)."""

    eos_t = None


def _collect(pipe, sink_name="sink", timeout=600):
    frame_t = _Arrivals()
    pipe.get(sink_name).connect(lambda b: frame_t.append(time.monotonic()))
    # gc fence around the timed region: collect the inter-run garbage NOW
    # (previous pipeline graphs, drained buffers) and keep the cyclic
    # collector from firing mid-run — observed warm-run spread (1.19)
    # correlates with collector pauses landing inside some windows and
    # not others. Refcount-driven finalizers (pool slab recycling) are
    # unaffected. gc.enable() unconditionally is correct here: the bench
    # process never runs with the collector deliberately off.
    gc.collect()
    gc.disable()
    try:
        msg = pipe.run(timeout=timeout)
    finally:
        gc.enable()
    if msg is None or msg.kind != "eos":
        raise RuntimeError(f"bench pipeline failed: {msg}")
    # end-of-run device fence + per-run interleave guard: EOS drains the
    # dispatch window in order, but trailing async device work may still
    # be retiring; the fence pins eos_t to actual completion (fps spans
    # all work) and guarantees the NEXT interleaved run/probe starts on
    # an idle device instead of inheriting this run's dispatch tail
    _device_fence()
    frame_t.eos_t = time.monotonic()
    return frame_t


def measure_ssd() -> dict:
    """Config #2 (BASELINE.md): SSD-MobileNet + bounding-box decode. The
    whole post-process — anchor decode, sigmoid, per-class NMS — runs inside
    the fused XLA program (decoders/bounding_boxes.py device_kernel)."""
    import jax.numpy as jnp

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.filters.jax_backend import register_jax_model
    from nnstreamer_tpu.models.ssd_mobilenet import ssd_mobilenet

    apply_fn, params, in_info, out_info = ssd_mobilenet(
        image_size=300, batch=1, dtype=jnp.bfloat16)
    register_jax_model("ssd_bench", apply_fn, params,
                       in_info=in_info, out_info=out_info)
    pipe = parse_launch(
        f"videotestsrc num-buffers={N_FRAMES} width=300 height=300 "
        "pattern=gradient ! tensor_converter ! queue max-size-buffers=8 ! "
        "tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-127.5,div:127.5 ! "
        "tensor_filter framework=jax model=ssd_bench name=filter ! "
        "tensor_decoder mode=bounding_boxes option1=mobilenet-ssd "
        "option4=300:300 option7=meta ! "
        "queue max-size-buffers=64 materialize-host=true ! "
        "tensor_sink name=sink to-host=true")
    frame_t = _collect(pipe)
    return dict(metric="ssd_mobilenet_300_pipeline_fps",
                fps=_steady_fps(frame_t), frames=len(frame_t))


def measure_pose_mux() -> dict:
    """Config #3: 4 sources → tensor_mux → ONE batched PoseNet invoke on
    the chip (the reference fans streams out to parallel CPU branches; the
    TPU way is mux → batch dim → single MXU-friendly program)."""
    import jax.numpy as jnp

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.filters.jax_backend import register_jax_model
    from nnstreamer_tpu.models.posenet import posenet

    apply_fn, params, _, _ = posenet(image_size=257, batch=4,
                                     dtype=jnp.bfloat16)

    def batched4(p, a, b, c, d):
        x = jnp.concatenate([a, b, c, d], axis=0).astype(jnp.float32)
        x = (x - 127.5) / 127.5
        heat, offs = apply_fn(p, x)
        return heat, offs

    register_jax_model("pose4_bench", batched4, params)

    def desc(n, live=""):
        srcs = " ".join(
            f"videotestsrc num-buffers={n} width=257 height=257 "
            f"pattern=gradient {live}! tensor_converter ! mux. "
            for _ in range(4))
        return (
            "tensor_mux name=mux sync-mode=slowest ! "
            "tensor_filter framework=jax model=pose4_bench name=filter ! "
            # keypoint decode fuses onto the device: [K,3] rows cross
            # the link, not full heatmaps; completion-proven via the
            # host sink
            "tensor_decoder mode=pose_estimation option2=meta ! "
            "queue max-size-buffers=64 materialize-host=true ! "
            "tensor_sink name=sink to-host=true " + srcs)

    n = max(N_FRAMES // 4, 30)
    pipe = parse_launch(desc(n))
    frame_t = _collect(pipe)
    sat = pipe.get("sink").latency_percentiles(50, 99)
    # realtime-paced latency (the saturated run's latency is deep-queue
    # wait by design): 15 fps per source (60 fps offered across 4) stays
    # under even bad-link capacity so the stat is service latency, not
    # overload queueing. A fresh pipeline re-traces its fused region on
    # the first buffer (~1-2 s) — frames paced in behind it queue up —
    # so run ~8 s and score only the steady second half
    n_srcs, live_n = 4, 120
    live_pipe = parse_launch(desc(live_n,
                                  live="is-live=true framerate=15/1 "))
    _collect(live_pipe)
    lat = live_pipe.get("sink").latency_percentiles(
        50, 99, skip=live_n // 2 * n_srcs)
    return dict(metric="posenet_mux4_batched_fps",
                fps=_steady_fps(frame_t, frames_per_buffer=4),
                latency_p50_ms=round(lat[0], 2) if lat else None,
                latency_p99_ms=round(lat[1], 2) if lat else None,
                latency_sat_p50_ms=round(sat[0], 2) if sat else None,
                latency_sat_p99_ms=round(sat[1], 2) if sat else None,
                frames=len(frame_t) * 4)


def measure_query() -> dict:
    """Config #4: tensor_query offload loopback — client pipeline sends
    frames over the framed-TCP query protocol to a server pipeline running
    the MobileNetV2 filter, results return by client id."""
    import jax.numpy as jnp

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.filters.jax_backend import register_jax_model
    from nnstreamer_tpu.models.mobilenet_v2 import mobilenet_v2

    apply_fn, params, in_info, out_info = mobilenet_v2(
        image_size=IMAGE, batch=1, dtype=jnp.bfloat16)

    def net(p, x):
        xf = (x.astype(jnp.float32) - 127.5) / 127.5
        return apply_fn(p, xf)

    register_jax_model("mnv2_query_bench", net, params)
    server = parse_launch(
        "tensor_query_serversrc name=ssrc port=0 ! "
        "tensor_filter framework=jax model=mnv2_query_bench ! "
        # serversink needs host bytes per result: grouped materialization
        # turns one ~100ms link flush per FRAME into one per backlog
        "queue max-size-buffers=64 materialize-host=true ! "
        "tensor_query_serversink")
    server.start()
    try:
        port = server.get("ssrc").port
        client = parse_launch(
            f"videotestsrc num-buffers={N_FRAMES} width={IMAGE} "
            f"height={IMAGE} pattern=gradient ! tensor_converter ! "
            f"tensor_query_client dest-host=127.0.0.1 dest-port={port} "
            "timeout=120 max-in-flight=16 ! "  # pipelined offload; long
            # timeout covers the first server-side jit compile
            "tensor_sink name=sink to-host=true")
        frame_t = _collect(client)
        lat = client.get("sink").latency_percentiles(50, 99)
    finally:
        server.stop()
    return dict(metric="query_offload_mobilenetv2_fps",
                fps=_steady_fps(frame_t),
                latency_p50_ms=round(lat[0], 2) if lat else None,
                latency_p99_ms=round(lat[1], 2) if lat else None,
                frames=len(frame_t))


def _run_repo_loop(desc_fn, slot: str, n: int, reset=None):
    """Shared completion-proof protocol for tensor_repo loop configs:
    a 2-buffer warm run first (tunneled chips defer compilation to first
    execution), then the measured run, then the final loop state
    materializes INSIDE the timed window — the returned arrivals prove
    the whole dependent chain executed, not just that dispatches were
    enqueued."""
    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.elements.repo import GLOBAL_REPO

    if reset is not None:
        reset()
    warm = parse_launch(desc_fn(2))
    warm.run(timeout=300)
    wbuf = GLOBAL_REPO.get(slot, consume=True)
    if wbuf is not None:
        np.asarray(wbuf.tensors[0])
    if reset is not None:
        reset()
    pipe = parse_launch(desc_fn(n))
    frame_t = _collect(pipe)
    final = GLOBAL_REPO.get(slot)
    if final is None:
        raise RuntimeError(
            f"bench: repo slot {slot!r} empty after the run — cannot "
            "prove completion")
    np.asarray(final.tensors[0])
    frame_t.eos_t = time.monotonic()
    return frame_t


def measure_lstm() -> dict:
    """Config #5: tensor_repo recurrence — LSTM state circulates through a
    repo slot as device-resident arrays; one filter invoke per step."""
    import jax.numpy as jnp

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.filters.jax_backend import register_jax_model
    from nnstreamer_tpu.models.lstm import lstm_cell

    hidden = 128
    apply_fn, params, _, _ = lstm_cell(input_dim=hidden, hidden=hidden,
                                       batch=1)

    def step(p, state):
        s = state.reshape(1, 2 * hidden).astype(jnp.float32)
        h, c = s[:, :hidden], s[:, hidden:]
        y, h2, c2 = apply_fn(p, h, h, c)  # self-feeding recurrence
        return jnp.concatenate([h2, c2], axis=1).reshape(2 * hidden)

    register_jax_model("lstm_bench", step, params)

    def loop_desc(num):
        return (f"tensor_reposrc slot=lstm_bench num-buffers={num} "
                f"initial-dim={2 * hidden} initial-type=float32 "
                "initial-value=0.01 timeout=30 ! "
                "tensor_filter framework=jax model=lstm_bench name=filter ! "
                "tee name=t  t. ! tensor_reposink slot=lstm_bench  "
                "t. ! tensor_sink name=sink to-host=false")

    frame_t = _run_repo_loop(loop_desc, "lstm_bench", N_FRAMES)
    return dict(metric="lstm_repo_recurrence_steps_per_s",
                fps=_steady_fps(frame_t), frames=len(frame_t))


def measure_attention() -> dict:
    """Long-context path: Pallas flash attention vs the XLA reference at
    seq 4096 (ops/flash_attention.py; layout [batch, seq, heads, dim])."""
    import time as _t

    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(0, 1, (1, 4096, 8, 128)), jnp.float32)
    k, v = q + 0.1, q - 0.1
    force = "pallas" if jax.default_backend() == "tpu" else None

    @jax.jit
    def step(q, k, v):
        # scalar checksum keeps the full attention on the device but lets
        # completion be proven by fetching 4 bytes — a remote-tunnel
        # block_until_ready can ack before execution finishes, so a host
        # fetch is the only trustworthy sync
        return jnp.sum(flash_attention(q, k, v, causal=True, force=force))

    np.asarray(step(q, k, v))
    iters = 20
    t0 = _t.perf_counter()
    outs = [step(q, k, v) for _ in range(iters)]
    for o in outs:
        o.copy_to_host_async()
    for o in outs:
        np.asarray(o)
    dt = (_t.perf_counter() - t0) / iters
    return dict(metric="flash_attention_seq4096_iters_per_s",
                fps=1.0 / dt, frames=iters)


def measure_batch4() -> dict:
    """Micro-batched throughput: tensor_aggregator packs 4 frames into one
    batch-4 invoke (the reference's aggregator micro-batching, SURVEY
    §2.4.3). Same model as the flagship; one dispatch serves 4 frames, so
    per-dispatch overhead amortizes — the TPU-native way to push a
    single stream past the per-call latency floor."""
    import jax.numpy as jnp

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.filters.jax_backend import register_jax_model
    from nnstreamer_tpu.models.mobilenet_v2 import mobilenet_v2

    apply_fn, params, _, _ = mobilenet_v2(
        image_size=IMAGE, batch=4, dtype=jnp.bfloat16)

    def net(p, x):  # [4,H,W,C] uint8 → [4,classes]
        xf = (x.astype(jnp.float32) - 127.5) / 127.5
        return apply_fn(p, xf)

    register_jax_model("mnv2_b4_bench", net, params)
    pipe = parse_launch(
        f"videotestsrc num-buffers={N_FRAMES} width={IMAGE} height={IMAGE} "
        "pattern=gradient ! tensor_converter ! queue max-size-buffers=8 ! "
        "tensor_aggregator frames-in=1 frames-out=4 frames-flush=4 "
        "frames-dim=3 concat=true ! "
        "tensor_filter framework=jax model=mnv2_b4_bench name=filter ! "
        "queue max-size-buffers=64 materialize-host=true ! "
        "tensor_sink name=sink to-host=true")
    frame_t = _collect(pipe)
    return dict(metric="mobilenetv2_224_batch4_fps",
                fps=_steady_fps(frame_t, frames_per_buffer=4),
                frames=len(frame_t) * 4)


def measure_decode() -> dict:
    """LM token streaming: KV-cached transformer decode through the
    tensor_repo loop (examples/llm_stream.py topology). The cache lives in
    HBM as loop state; only token ids circulate host-side. Metric:
    sustained decode steps (tokens) per second."""
    import jax.numpy as jnp

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.elements.repo import GLOBAL_REPO
    from nnstreamer_tpu.filters.jax_backend import register_jax_model
    from nnstreamer_tpu.models.transformer import (
        TransformerConfig,
        build_greedy_stream_step,
        init_cache,
        init_params,
    )
    from nnstreamer_tpu.tensors.buffer import TensorBuffer

    cfg = TransformerConfig(vocab=32000, d_model=512, n_heads=8,
                            n_layers=8, d_ff=2048, max_seq=1024,
                            dtype=jnp.bfloat16)
    params = init_params(cfg)
    # 16 decode steps per invoke (lax.scan inside the program): the token
    # chain is inherently sequential, so the only throughput lever is
    # amortizing per-dispatch overhead across a block — the serving
    # engine's K-step dispatch, repo-loop flavored
    K = 16
    register_jax_model("lm_decode_bench",
                       build_greedy_stream_step(cfg, steps=K), params)
    n = max(1, min(N_FRAMES, 1000) // K)

    def seed():
        # seed with the device-resident cache directly: np.asarray here
        # would bounce ~16 MB through the host just to re-upload on the
        # first invoke
        GLOBAL_REPO.set("lm_bench", TensorBuffer(
            [np.asarray([1], np.int32),
             init_cache(cfg, batch=1),
             np.asarray(0, np.int32)], pts=0))

    def loop_desc(num):
        return (f"tensor_reposrc slot=lm_bench num-buffers={num} "
                "timeout=120 ! "
                "tensor_filter framework=jax model=lm_decode_bench "
                "name=filter input-combination=i0,i1,i2 ! "
                "tee name=t  t. ! tensor_reposink slot=lm_bench  "
                "t. ! tensor_sink name=sink to-host=false")

    frame_t = _run_repo_loop(loop_desc, "lm_bench", n, reset=seed)
    return dict(metric="lm_decode_tokens_per_s_d512_l8_kv1024",
                fps=_steady_fps(frame_t, frames_per_buffer=K),
                frames=len(frame_t) * K)


def _hbm_bandwidth_probe(mb: int = 256, iters: int = 10):
    """Measured HBM read bandwidth (bytes/s): a reduction over a
    device-resident array is memory-bound, so bytes/time is the
    achievable stream rate — the roofline denominator for decode."""
    try:
        import jax
        import jax.numpy as jnp

        from jax import lax

        n = mb * (1 << 20) // 2  # bf16 elements
        passes = 50  # in-program passes amortize the per-dispatch RPC
        x = jax.device_put(jnp.ones((n,), jnp.bfloat16))

        @jax.jit
        def f(a):
            # each pass re-reads the full array: the elementwise max
            # against the evolving accumulator cannot be hoisted or
            # factored out of the reduction, and max+reduce fuse, so the
            # loop body is a pure streaming read
            return lax.fori_loop(
                0, passes,
                lambda i, acc: acc + jnp.sum(jnp.maximum(
                    a, acc.astype(jnp.bfloat16)).astype(jnp.float32)),
                jnp.float32(0.0))

        np.asarray(f(x))  # compile + warm
        t0 = time.perf_counter()
        outs = [f(x) for _ in range(iters)]
        np.asarray(outs[-1])
        dt = time.perf_counter() - t0
        return 2.0 * n * passes * iters / dt
    except Exception as e:  # noqa: BLE001 — roofline is informative
        print(f"bench: hbm probe failed ({e})", file=sys.stderr)
        return None


def measure_serve() -> dict:
    """Continuous-batching serving: 8 concurrent streams share one batched
    KV-cached decode program (serving/engine.py). Metric: aggregate
    generated tokens/s across streams — the serving-throughput counterpart
    of the single-stream ``decode`` config."""
    import time as _t

    import jax.numpy as jnp

    from nnstreamer_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from nnstreamer_tpu.serving import ContinuousBatchingEngine

    cfg = TransformerConfig(vocab=32000, d_model=512, n_heads=8, n_layers=8,
                            d_ff=2048, max_seq=512, dtype=jnp.bfloat16)
    # steps_per_dispatch="auto": the engine measures the link RTT and
    # per-step decode time at start() and sizes K so the per-dispatch
    # sync amortizes (engine._calibrate_k) — on the tunnel it lands
    # 32-128, on PCIe it would land small; these length-bound greedy
    # streams never waste steps on early EOS
    serve_params = init_params(cfg)
    engine = ContinuousBatchingEngine(
        cfg, serve_params, max_streams=8, steps_per_dispatch="auto",
        temperature=0.0).start()
    try:
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, cfg.vocab, n).tolist()
                   for n in (8, 17, 33, 12, 25, 9, 40, 14, 21, 30, 11, 19)]
        # warm the compile caches off the clock: the dispatch program plus
        # ONE prefill per padding bucket the prompt set will hit (16/32/64)
        for warm_len in (8, 17, 33):
            engine.generate(rng.integers(1, cfg.vocab, warm_len).tolist(),
                            max_new_tokens=engine.K, timeout=600)
        t0 = _t.monotonic()
        streams = [engine.submit(p, max_new_tokens=128) for p in prompts]
        total = sum(len(s.result(timeout=600)) for s in streams)
        dt = _t.monotonic() - t0
    finally:
        engine.stop()
    tps = total / dt

    # ---- roofline: the decode ceiling this config could ever reach ----
    # every decode step streams all params plus the full static KV cache
    # from HBM and yields max_streams tokens, so
    #   bytes/token = (params_bytes + cache_bytes) / max_streams
    # and tokens_per_s_ceiling = measured HBM bandwidth / bytes_per_token
    # (jax-ml.github.io/scaling-book's bandwidth-bound decode recipe)
    import jax

    from nnstreamer_tpu.models.transformer import init_cache

    # bytes from the ACTUAL leaf dtypes (init_params stores f32 master
    # weights; assuming cfg.dtype here would halve params_bytes and
    # inflate the ceiling)
    param_leaves = jax.tree_util.tree_leaves(
        jax.eval_shape(lambda: init_params(cfg)))
    n_params = sum(int(np.prod(v.shape)) for v in param_leaves)
    params_bytes = sum(int(np.prod(v.shape)) * v.dtype.itemsize
                       for v in param_leaves)
    cache_bytes = sum(
        int(np.prod(a.shape)) * a.dtype.itemsize
        for a in jax.tree_util.tree_leaves(
            jax.eval_shape(lambda: init_cache(cfg, batch=8))))
    bytes_per_token = (params_bytes + cache_bytes) / 8
    bw = _hbm_bandwidth_probe()
    peak = _peak_flops()
    ceiling = bw / bytes_per_token if bw else None

    # ---- prefill throughput (flash-attention path, VERDICT r4 #4) ----
    # full-length prompts through the engine's own prefill program
    # (attention="auto" → Pallas flash kernel on TPU for these tileable
    # [4, 512] shapes); tokens/s over the O(s²) prompt pass
    from nnstreamer_tpu.models.transformer import build_prefill
    from nnstreamer_tpu.ops import flash_attention as _flash

    pf = jax.jit(build_prefill(cfg, cfg.max_seq, attention_fn=_flash))
    pparams = jax.device_put(serve_params)
    ptoks = jnp.asarray(
        np.random.default_rng(1).integers(1, cfg.vocab, (4, cfg.max_seq)),
        jnp.int32)
    jax.block_until_ready(pf(pparams, ptoks))  # compile+warm off clock
    samples = []
    for _ in range(3):
        t0 = _t.monotonic()
        jax.block_until_ready(pf(pparams, ptoks))
        samples.append(ptoks.size / (_t.monotonic() - t0))
    prefill_tok_s = sorted(samples)[1]

    return dict(metric="serving_aggregate_tokens_per_s_d512_l8_x8streams",
                fps=tps, frames=total,
                prefill_tok_s=round(prefill_tok_s, 1),
                hbm_bandwidth_gbps=round(bw / 1e9, 1) if bw else None,
                model_mbytes=round(params_bytes / 1e6, 1),
                kv_cache_mbytes=round(cache_bytes / 1e6, 1),
                tokens_per_s_ceiling=round(ceiling, 1) if ceiling else None,
                vs_ceiling=round(tps / ceiling, 4) if ceiling else None,
                mfu_serve=round(tps * 2 * n_params / peak, 5)
                if peak else None)


def measure_spec() -> dict:
    """Speculative decoding: same target model as the ``decode`` config
    (d512 l8) with a depth-pruned self-speculative draft (first 2 of 8
    layers, shared embedding), γ=4, 8 rounds fused per dispatch — tokens/s
    should beat plain single-token decode by roughly the mean acceptance
    length (models/speculative.py)."""
    import time as _t

    import jax.numpy as jnp

    from nnstreamer_tpu.models.speculative import (
        SpeculativeDecoder,
        draft_from_target,
    )
    from nnstreamer_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )

    target = TransformerConfig(vocab=32000, d_model=512, n_heads=8,
                               n_layers=8, d_ff=2048, max_seq=1024,
                               dtype=jnp.bfloat16)
    params = init_params(target, seed=0)
    # damp layer outputs → a LOW-ENTROPY model (random-init argmax over a
    # 32k vocab is chaotic; trained LMs are locally predictable, which is
    # the regime speculation exists for). mean_accepted ≈ 4.7 here —
    # printed below so the regime is visible next to the number.
    params = {**params, "proj": params["proj"] * 0.3,
              "w_out": params["w_out"] * 0.3}
    draft, draft_params = draft_from_target(target, params, 2)
    dec = SpeculativeDecoder(target, params, draft, draft_params, gamma=4)
    prompt = np.random.default_rng(0).integers(1, 32000, 32).tolist()
    n = min(N_FRAMES, 800)
    dec.generate(prompt, max_new_tokens=n, fused=True)  # compile off clock
    dec.stats.update(rounds=0, tokens=0, dispatches=0)  # report timed run
    t0 = _t.monotonic()
    out = dec.generate(prompt, max_new_tokens=n, fused=True)
    dt = _t.monotonic() - t0
    print(f"bench spec: mean_accepted={dec.mean_accepted:.2f} "
          f"rounds={dec.stats['rounds']}", file=sys.stderr)
    return dict(metric="speculative_decode_tokens_per_s_d512_l8_g4",
                fps=len(out) / dt, frames=len(out))


def measure_lm() -> dict:
    """Paged-KV LM serving (``BENCH_LM=1``): more concurrent streams
    than decode lanes time-share an 8-lane batch over a block pool
    (serving/kvpool.py), so concurrency is bounded by free KV blocks,
    not batch slots. Metric: aggregate generated tokens/s; the report
    adds the interactive split (TTFT vs inter-token p99 from the flight
    recorder's LMTokenStats), the concurrency high-water mark, and the
    arena's HBM cost per token slot. ``BENCH_LM_BLOCK=0`` (or
    ``NNSTPU_PAGED_KV=0``) reruns the same load on the monolithic cache
    for an apples-to-apples comparison."""
    import time as _t

    import jax.numpy as jnp

    from nnstreamer_tpu.models.transformer import (
        TransformerConfig,
        init_params,
    )
    from nnstreamer_tpu.serving import ContinuousBatchingEngine

    cfg = TransformerConfig(vocab=32000, d_model=512, n_heads=8, n_layers=8,
                            d_ff=2048, max_seq=512, dtype=jnp.bfloat16)
    block = int(os.environ.get("BENCH_LM_BLOCK", "16") or 0)
    n_streams = int(os.environ.get("BENCH_LM_STREAMS", "32"))
    max_new = int(os.environ.get("BENCH_LM_MAX_NEW", "64"))
    engine = ContinuousBatchingEngine(
        cfg, init_params(cfg), max_streams=8, steps_per_dispatch=8,
        temperature=0.0, block_tokens=block).start()
    try:
        rng = np.random.default_rng(0)
        # compile warmup off the clock: the dispatch program plus one
        # prefill per padding bucket this prompt-length range will hit
        for warm in (8, 17, 33):
            engine.generate(rng.integers(1, cfg.vocab, warm).tolist(),
                            max_new_tokens=engine.K, timeout=600)
        lens = rng.integers(8, 48, n_streams)
        t0 = _t.monotonic()
        streams = [engine.submit(rng.integers(1, cfg.vocab, n).tolist(),
                                 max_new_tokens=max_new) for n in lens]
        total = sum(len(s.result(timeout=600)) for s in streams)
        dt = _t.monotonic() - t0
        q = engine._lm_stats._q
        ttft_p99 = (q["ttft"]["p99"].quantile() or 0.0) * 1e3
        tok_p99 = (q["token"]["p99"].quantile() or 0.0) * 1e3
        conc = int(engine.stats.get("concurrent_streams_max", 0))
        sheds = int(engine.stats.get("kv_sheds", 0))
        if engine.paged:
            pool = engine._pool
            kv_per_tok = pool.nbytes / (pool.num_blocks
                                        * pool.block_tokens)
        else:
            import jax

            kv_per_tok = sum(
                leaf.nbytes for leaf in
                jax.tree_util.tree_leaves(engine._cache)) / (
                    engine.B * engine.S)
    finally:
        engine.stop()
    return dict(metric="lm_serving_tokens_per_s_paged" if engine.paged
                else "lm_serving_tokens_per_s_monolithic",
                fps=total / dt, frames=total,
                ttft_p99_ms=round(ttft_p99, 2),
                intertoken_p99_ms=round(tok_p99, 3),
                concurrent_streams_max=conc,
                kv_sheds=sheds,
                kv_hbm_bytes_per_token=round(kv_per_tok, 1))


def measure_fleet() -> dict:
    """Replicated-fleet scaling (``BENCH_FLEET=N``): N echo replicas
    (serving/fleet.py, CPU-bound ``--spin-ms`` service so added
    replicas buy real process parallelism) behind one discovery
    operation, fronted by a single ``balance=shortest-slack`` client.
    The run measures admitted fps at every fleet size 1..N from the
    same machine/weather window; ``fleet_scaling`` =
    fps_N / (N * fps_1) is the near-linear-throughput score gated by
    ``BENCH_GATE_FLEET_SCALING_MIN`` (CI: 0.75 at N=3 on loopback
    CPU)."""
    import time as _t

    from nnstreamer_tpu.registry import ELEMENT, get_subplugin
    from nnstreamer_tpu.serving.fleet import FleetLauncher
    from nnstreamer_tpu.tensors.buffer import TensorBuffer

    n = max(1, int(os.environ.get("BENCH_FLEET", "3") or 3))
    spin_ms = float(os.environ.get("BENCH_FLEET_SPIN_MS", "20"))
    frames = int(os.environ.get("BENCH_FLEET_FRAMES", "120"))
    warmup = 8

    def run_once(k: int) -> float:
        fleet = FleetLauncher(replicas=k, operation=f"bench-fleet{k}",
                              spin_ms=spin_ms).start()
        try:
            eps = fleet.endpoints(timeout=30.0)
            if len(eps) < k:
                raise RuntimeError(
                    f"fleet of {k} never fully advertised ({eps})")
            Client = get_subplugin(ELEMENT, "tensor_query_client")
            cl = Client(operation=f"bench-fleet{k}",
                        broker_port=fleet.broker_port, reliable=True,
                        balance="shortest-slack",
                        max_in_flight=4 * k, timeout=10.0)
            outs = []
            cl.srcpad.push = lambda b: outs.append(b)
            try:
                for i in range(warmup):  # connects + RTT priming
                    cl.chain(cl.sinkpad, TensorBuffer(
                        [np.full((4,), i, dtype=np.float32)], pts=i))
                t0 = _t.monotonic()
                for i in range(warmup, warmup + frames):
                    cl.chain(cl.sinkpad, TensorBuffer(
                        [np.full((4,), i, dtype=np.float32)], pts=i))
                cl.handle_eos()
                dt = _t.monotonic() - t0
            finally:
                cl.stop()
            if len(outs) != warmup + frames:
                raise RuntimeError(
                    f"fleet of {k} lost frames: {len(outs)} of "
                    f"{warmup + frames}")
            return frames / dt
        finally:
            fleet.stop()

    fps = [run_once(k) for k in range(1, n + 1)]
    scaling = fps[-1] / (n * fps[0]) if n > 1 and fps[0] else 1.0
    gate_min = float(
        os.environ.get("BENCH_GATE_FLEET_SCALING_MIN", "0") or 0)
    gates = {
        "fleet_scaling": {
            "value": round(scaling, 3),
            "min": gate_min or None,
            "ok": not gate_min or scaling >= gate_min,
        },
    }
    gates["ok"] = gates["fleet_scaling"]["ok"]
    return dict(metric="fleet_admitted_fps", fps=fps[-1], frames=frames,
                fleet_replicas=n,
                fleet_admitted_fps=[round(f, 1) for f in fps],
                fleet_scaling=round(scaling, 3),
                fleet_spin_ms=spin_ms, gates=gates)


EXTRA_CONFIGS = {
    "ssd": measure_ssd,
    "pose4": measure_pose_mux,
    "query": measure_query,
    "lstm": measure_lstm,
    "attn": measure_attention,
    "batch4": measure_batch4,
    "decode": measure_decode,
    "serve": measure_serve,
    "spec": measure_spec,
    "lm": measure_lm,
    "fleet": measure_fleet,
}


def measure_tflite_baseline() -> float | None:
    """Reference path: TFLite CPU MobileNetV2, if an interpreter exists."""
    try:
        from nnstreamer_tpu.filters.tflite_backend import _interpreter_cls

        if _interpreter_cls() is None:
            return None
    except Exception:
        return None
    return None  # no bundled .tflite model file; driver baseline applies


def _probe_accelerator(timeout_s: float = None) -> bool:
    """True when a non-CPU accelerator initializes healthily (a wedged TPU
    tunnel blocks forever in PJRT client creation — the shared subprocess
    probe guards against that)."""
    from nnstreamer_tpu.utils.platform import probe_jax_platform

    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "300"))
    platform = probe_jax_platform(timeout_s)
    return platform is not None and platform != "cpu"


def _enable_compile_cache():
    """Persistent XLA compilation cache: the flagship model's ~30s TPU
    compile happens once per machine, not once per bench run. Routed
    through the serving-continuity layer (pipeline/continuity.py) so
    the bench shares the serving cache and its hit/miss counters
    (nns_compile_cache_hits/misses_total) feed the report footer."""
    try:
        from nnstreamer_tpu.pipeline.continuity import enable_compile_cache

        cache_dir = os.environ.get(
            "NNSTPU_COMPILE_CACHE",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache"))
        enable_compile_cache(cache_dir)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        print(f"bench: compile cache unavailable ({e})", file=sys.stderr)


def main():
    _enable_compile_cache()
    if not _probe_accelerator():
        print("bench: accelerator unavailable/wedged; falling back to CPU",
              file=sys.stderr)
        import jax

        jax.config.update("jax_platforms", "cpu")

    # secondary configs (BASELINE.md #2-#5): `python bench.py ssd|pose4|
    # query|lstm` or BENCH_CONFIG env. Default (driver contract): flagship
    # MobileNetV2 pipeline, ONE JSON line.
    config = (sys.argv[1] if len(sys.argv) > 1 else
              os.environ.get("BENCH_CONFIG", "")).strip()
    if not config and os.environ.get(
            "BENCH_LM", "").strip().lower() in ("1", "true", "yes", "on"):
        config = "lm"  # BENCH_LM=1 — the paged LM-serving report
    if not config and os.environ.get("BENCH_FLEET", "").strip():
        config = "fleet"  # BENCH_FLEET=N — replicated-fleet scaling
    if config and config != "mobilenet":
        def _emit(r):
            extra = {k: v for k, v in r.items()
                     if k not in ("metric", "fps", "frames") and
                     v is not None}
            print(json.dumps({"metric": r["metric"],
                              "value": round(r["fps"], 2),
                              "unit": "fps", "frames": r["frames"],
                              **extra, "platform": _platform()}))

        if config == "all":
            for name, fn in EXTRA_CONFIGS.items():
                _emit(fn())
            return
        if config not in EXTRA_CONFIGS:
            print(f"bench: unknown config {config!r} "
                  f"(choose from {', '.join(EXTRA_CONFIGS)})",
                  file=sys.stderr)
            sys.exit(2)
        r = EXTRA_CONFIGS[config]()
        _emit(r)
        g = r.get("gates")
        if ENFORCE_GATES and isinstance(g, dict) and not g.get("ok", True):
            sys.exit(1)
        return

    # fixed-length warmup drain (WARMUP_DRAIN buffers): compile, tunnel
    # stream setup, fused-region trace and pool/lane-arena priming all
    # land here, off the clock, so the repeat loop below measures only
    # steady state. fps_cold still reports run 1 separately — after this
    # drain its remaining "coldness" is link weather, not compile.
    _collect(build_pipeline(BATCH, n_frames=WARMUP_DRAIN * BATCH))
    # each flagship run is paired with an ingest-ceiling sample from the
    # SAME weather window: norm_runs = fps/ceiling is the
    # tunnel-insensitive score (spread target <0.2 where raw fps spreads
    # 0.5+ — see the "weather-normalized" note in the module docstring)
    runs, ingest_seq = [], []
    for _ in range(max(1, REPEATS)):
        runs.append(measure_pipeline())
        ingest_seq.append(ingest_run_once())
    # one traced run adjacent to the repeats (same weather window, never
    # counted among them): its ledger produces the report's
    # stage_breakdown, and its fps against the untraced warm median is
    # the measured cost of tracing (trace_overhead_pct)
    traced = measure_traced()
    fps_seq = [round(r["fps"], 2) for r in runs]  # chronological
    norm_seq = [round(r["fps"] / i, 3) if i else None
                for r, i in zip(runs, ingest_seq)]
    # warm/cold split: the first run pays compile + tunnel warm-up and is
    # reported separately as fps_cold; the headline value is the
    # steady-state (warm) median so one cold run cannot drag it
    warm = runs[1:] if len(runs) > 1 else runs
    warm_sorted = sorted(warm, key=lambda r: r["fps"])
    # lower-middle run: the median for odd counts, the conservative
    # middle (never the best run) for even
    stats = warm_sorted[(len(warm_sorted) - 1) // 2]
    warm_fps = [round(r["fps"], 2) for r in warm_sorted]
    spread = ((warm_fps[-1] - warm_fps[0]) / stats["fps"]
              if stats["fps"] else 0.0)
    # robust spread companions (used by the perf gates): fps_median is
    # the true median of the warm runs (interpolated for even counts —
    # `value` stays the conservative lower-middle RUN so the headline
    # keeps its full stats row), and spread_mad is the median absolute
    # deviation over the median — one wild warm run moves the max-min
    # spread_warm by its full excursion but barely dents the MAD
    fps_median = float(np.median([r["fps"] for r in warm]))
    mad = float(np.median([abs(r["fps"] - fps_median) for r in warm]))
    spread_mad = round(mad / fps_median, 3) if fps_median else 0.0
    # weather-normalized score: median of the warm per-run fps/ceiling
    # ratios (each ratio uses the ingest sample adjacent to its run)
    warm_norm = sorted(n for n in norm_seq[1:] or norm_seq if n)
    value_norm = warm_norm[(len(warm_norm) - 1) // 2] if warm_norm else None
    spread_norm = (round((warm_norm[-1] - warm_norm[0]) / value_norm, 3)
                   if value_norm else None)
    # probe AFTER the repeats: device_roundtrip_ms / device_fps_ceiling
    # are recomputed in the same link-weather window the runs just used,
    # so pipeline_efficiency compares like with like (with residency on,
    # the pipeline no longer pays that roundtrip per frame — the probe
    # keeps the link number honest rather than inherited from a colder
    # pre-run measurement)
    probe = device_probe()
    # the r01/r02-comparable single-frame pipeline rides along as a
    # secondary (median of 3): it shows the per-dispatch tunnel floor the
    # micro-batched flagship amortizes away
    single = sorted(measure_pipeline(batch=1)["fps"] for _ in range(3))[1]
    baseline = measure_tflite_baseline() or FALLBACK_BASELINE_FPS
    flops = _model_flops(BATCH)
    peak = _peak_flops()
    # the ceiling for vs_ingest_bound must not read LOW on a volatile
    # link: best sample across the interleaved probes
    ingest = {"ingest_bound_fps": round(max(ingest_seq), 1)
              if any(ingest_seq) else None}
    lat_live = measure_latency_live()
    mesh_fields = _measure_mesh_fields(fps_median, runs)
    result = {
        "metric": "mobilenetv2_224_pipeline_fps",
        "value": round(stats["fps"], 2),
        "unit": "fps",
        "vs_baseline": round(stats["fps"] / baseline, 3),
        "batch": BATCH,
        "inflight": INFLIGHT,
        "lanes": _effective_lanes(),
        "pool_hit_rate": _pool_hit_rate(),
        # end-to-end per-frame latency under 30 fps realtime pacing (the
        # north-star latency); the *_sat_* fields are the same measurement
        # inside the saturated throughput runs, where deep-queue wait
        # dominates by design
        **lat_live,
        # *_sat_* now reports the ADMITTED population (frames the leaky
        # ingress accepted, measured from the admission stamp) — service
        # latency of delivered traffic; the frames the queue shed instead
        # are counted separately
        "latency_sat_p50_ms": stats["latency_p50_ms"],
        "latency_sat_p99_ms": stats["latency_p99_ms"],
        "latency_dropped_frames": stats["latency_dropped_frames"],
        # SLO scheduler (BENCH_SLO_BUDGET_MS > 0): throughput of the
        # SERVED admitted population and the share of offered traffic
        # the admission point turned away (door rejections + sheds).
        # Without a budget shed_ratio still reports the leaky ingress's
        # blind tail-drop ratio under saturation.
        "slo_budget_ms": SLO_BUDGET_MS if SLO_BUDGET_MS > 0 else None,
        "admitted_fps": stats["admitted_fps"],
        "shed_ratio": stats["shed_ratio"],
        # residency: explicit D2H materializations per frame (sink-only
        # materialization ⇒ 1/batch) and the session-wide share of
        # DeviceBuffer pad crossings that stayed resident
        "d2h_per_frame": stats["d2h_per_frame"],
        "resident_ratio": _resident_ratio(),
        # staged multi-frame transfer batching: window uploads / grouped
        # fetches the headline run used, and the frames they carried
        "h2d_batched_uploads": stats["h2d_batched"],
        "h2d_batched_frames": stats["h2d_batched_frames"],
        "d2h_batched_fetches": stats["d2h_batched"],
        "p50_interarrival_ms": round(stats["p50_ms"], 3),
        "invoke_latency_us": stats["invoke_latency_us"],
        "frames": stats["frames"],
        "fps_cold": fps_seq[0],
        "fps_runs": fps_seq,
        "fps_median": round(fps_median, 2),
        "spread_warm": round(spread, 3),
        "spread_mad": spread_mad,
        # weather-normalized: fps over the SAME-window ingest ceiling —
        # the cross-round comparison that survives tunnel drift
        "value_norm": value_norm,
        "norm_runs": norm_seq,
        "spread_norm": spread_norm,
        "single_frame_fps": round(single, 2),
        # frame-ledger report (obs/timeline.py, one traced run): mean
        # per-frame ms by stage — reconciliation ~1.0 means the stages
        # tile the frame's whole e2e life; trace_overhead_pct is the
        # traced run's fps deficit vs the untraced warm median (negative
        # = the traced run caught better link weather, not a speedup)
        "stage_breakdown": traced["breakdown"],
        "trace_dominant_stage": traced["variance"]["dominant_stage"],
        "trace_overhead_pct": (
            round((1 - traced["fps"] / fps_median) * 100, 2)
            if fps_median and traced["fps"] else None),
        **probe,
        **ingest,
        # gated statistic: the MEDIAN-of-k warm fps over the same-window
        # ceiling — a single lucky (or unlucky) run cannot move a perf
        # gate built on this the way the lower-middle `value` run could
        "pipeline_efficiency": round(
            fps_median / probe["device_fps_ceiling"], 3)
        if probe["device_fps_ceiling"] and fps_median else None,
        # ≥0.7 means the wall number IS the transfer link's ceiling —
        # the pipeline itself is not the limiter (see ingest_probe)
        "vs_ingest_bound": round(
            stats["fps"] / ingest["ingest_bound_fps"], 3)
        if ingest.get("ingest_bound_fps") else None,
        "model_gflops_per_frame": round(flops / BATCH / 1e9, 3)
        if flops else None,
        # MFU at the pipeline level (delivered frames × model flops over
        # peak) and at the dispatch level (what the chip sustains on the
        # model alone — the gap between the two is framework+tunnel)
        "mfu_pipeline": round(stats["fps"] * flops / BATCH / peak, 4)
        if flops and peak else None,
        "mfu_dispatch": round(
            flops / (probe["device_dispatch_ms_per_batch"] / 1e3) / peak, 4)
        if flops and peak and probe["device_dispatch_ms_per_batch"]
        else None,
        "baseline_fps": baseline,
        # mesh-sharded serving (BENCH_MESH=dp8): spec, warm median over
        # the single-device reference, resharded bytes per measured
        # frame (0 = every boundary hand-off was a matched zero-copy)
        **mesh_fields,
        "platform": _platform(),
    }
    # flight recorder (obs/flight.py): the always-on attribution from
    # the last UNtraced measured run — unlike trace_dominant_stage it
    # costs no dedicated run and reflects the gated repeats themselves
    fa = _LAST_FLIGHT.get("attribution")
    result["flight_dominant_stage"] = (fa or {}).get("dominant_stage")
    result["flight_dominant_share"] = (fa or {}).get("dominant_share")
    result["gates"] = gates = _perf_gates(
        fps_median=fps_median, spread_mad=spread_mad,
        sat_p99_ms=stats["latency_p99_ms"])
    print(json.dumps(result))
    if ENFORCE_GATES and not gates["ok"]:
        sys.exit(1)


def _perf_gates(fps_median, spread_mad, sat_p99_ms) -> dict:
    """Judge the run against the determinism gates: the headline median
    AND the two tail statistics (warm spread as MAD/median, saturation
    p99 of the admitted population). A threshold of 0/None means that
    gate is unarmed and passes."""
    gates = {
        "fps_median": {
            "value": round(fps_median, 2),
            "min": GATE_FPS_MEDIAN_MIN or None,
            "ok": (not GATE_FPS_MEDIAN_MIN
                   or fps_median >= GATE_FPS_MEDIAN_MIN),
        },
        "spread_mad": {
            "value": spread_mad,
            "max": GATE_SPREAD_MAD_MAX or None,
            "ok": (not GATE_SPREAD_MAD_MAX
                   or spread_mad <= GATE_SPREAD_MAD_MAX),
        },
        "latency_sat_p99_ms": {
            "value": sat_p99_ms,
            "max": GATE_SAT_P99_MS_MAX or None,
            "ok": (not GATE_SAT_P99_MS_MAX or sat_p99_ms is None
                   or sat_p99_ms <= GATE_SAT_P99_MS_MAX),
        },
    }
    gates["ok"] = all(g["ok"] for g in gates.values()
                      if isinstance(g, dict))
    return gates


def _resident_ratio():
    """Session-wide nns_buffer_resident_ratio (tensors/buffer.py); None
    when no DeviceBuffer ever crossed a pad (NNSTPU_RESIDENT=0)."""
    try:
        from nnstreamer_tpu.tensors.buffer import resident_ratio

        r = resident_ratio()
        return None if r is None else round(r, 3)
    except Exception:  # noqa: BLE001 — informative field only
        return None


def _effective_lanes() -> int:
    """The lane count the runs actually used (NNSTPU_LANES overrides
    BENCH_LANES — pipeline/lanes.py)."""
    try:
        from nnstreamer_tpu.pipeline.lanes import effective_lanes

        return effective_lanes(LANES)
    except Exception:  # noqa: BLE001 — informative field only
        return LANES


def _pool_hit_rate():
    """Cumulative ingest-pool hit rate across the session's runs
    (tensors/pool.py); None when the pool saw no traffic or is disabled
    via NNSTPU_POOL=0."""
    try:
        from nnstreamer_tpu.tensors.pool import get_pool, pool_enabled

        if not pool_enabled():
            return None
        snap = get_pool().snapshot()
        if not (snap["hits"] or snap["misses"]):
            return None
        return round(snap["hit_rate"], 3)
    except Exception:  # noqa: BLE001 — informative field only
        return None


def _measure_mesh_fields(fps_median, runs) -> dict:
    """Mesh-sharded run report (BENCH_MESH=dp8): the spec, the warm
    median over a single-device reference run taken in the SAME weather
    window with the kill switch thrown (NNSTPU_MESH=0 is the
    byte-identical dp1 path, so the ratio isolates the mesh), and the
    session's resharded bytes per measured frame — 0 when every
    device-passthrough hand-off between sharded regions was a matched
    zero-copy. All three are null without BENCH_MESH."""
    if not MESH_SPEC:
        return {"mesh": None, "shard_scaling": None,
                "reshard_bytes_per_frame": None}
    from nnstreamer_tpu.parallel import serve as _serve

    frames = sum(int(r.get("frames") or 0) for r in runs)
    per_frame = (round(_serve.reshard_bytes_total() / frames, 1)
                 if frames else None)
    prev = os.environ.get("NNSTPU_MESH")
    os.environ["NNSTPU_MESH"] = "0"
    try:
        # the reference pays its own compile off the clock, like the
        # flagship's warmup drain, so the ratio compares steady states
        _collect(build_pipeline(BATCH, n_frames=WARMUP_DRAIN * BATCH))
        ref_fps = measure_pipeline()["fps"]
    finally:
        if prev is None:
            os.environ.pop("NNSTPU_MESH", None)
        else:
            os.environ["NNSTPU_MESH"] = prev
    return {"mesh": MESH_SPEC,
            "shard_scaling": (round(fps_median / ref_fps, 3)
                              if ref_fps and fps_median else None),
            "reshard_bytes_per_frame": per_frame}


def _platform() -> str:
    try:
        import jax

        return str(jax.devices()[0].platform)
    except Exception:  # noqa: BLE001
        return "unknown"


if __name__ == "__main__":
    main()
