"""nns-lint: static pipeline verifier + project AST lint.

Covers both halves of nnstreamer_tpu/analysis/ — the NNS0xx graph
diagnostics produced without constructing any runtime state, the NNS1xx
AST rules with pragma suppression, description extraction from shipped
files, the CLI contract (exit codes, JSON schema), positional parse
errors, and the Pipeline.verify() pre-flight.
"""

import json

import pytest

from nnstreamer_tpu.analysis import (
    CODE_TABLE,
    ERROR,
    WARNING,
    lint_source,
    verify_description,
)
from nnstreamer_tpu.analysis.extract import (
    extract_from_markdown,
    extract_from_python,
)
from nnstreamer_tpu.pipeline.parse import ParseError, parse_launch


def codes(diags):
    return [d.code for d in diags]


def by_code(diags, code):
    return [d for d in diags if d.code == code]


class TestVerifierGraph:
    def test_clean_pipeline_no_diagnostics(self):
        diags = verify_description(
            "videotestsrc num-buffers=4 ! tensor_converter ! "
            "tensor_filter framework=auto model=m.tflite ! tensor_sink")
        assert diags == []

    def test_unknown_factory_with_suggestion(self):
        diags = verify_description("videotestsrc ! tensor_convertr "
                                   "! tensor_sink")
        errs = by_code(diags, "NNS001")
        assert errs and errs[0].severity == ERROR
        assert "tensor_convertr" in errs[0].message
        assert "tensor_converter" in (errs[0].hint or "")

    def test_unknown_property_names_known_ones(self):
        diags = verify_description("videotestsrc ! fakesink bogus=1")
        errs = by_code(diags, "NNS002")
        assert errs and "bogus" in errs[0].message
        assert "sync" in (errs[0].hint or "")

    def test_duplicate_name(self):
        diags = verify_description(
            "videotestsrc name=a ! fakesink videotestsrc name=a "
            "! fakesink")
        assert by_code(diags, "NNS003")

    def test_unknown_reference(self):
        diags = verify_description("videotestsrc ! tee name=t "
                                   "nosuch. ! fakesink")
        errs = by_code(diags, "NNS004")
        assert errs and "nosuch" in errs[0].message

    def test_sink_pad_exhaustion(self):
        # fakesink has exactly one sink pad; a second feed must be
        # rejected statically, same as parse_launch would at build time
        diags = verify_description(
            "videotestsrc ! fakesink name=s videotestsrc ! s.")
        errs = by_code(diags, "NNS004")
        assert errs and "no free sink pad" in errs[0].message

    def test_media_type_mismatch_suggests_converter(self):
        diags = verify_description(
            "videotestsrc ! tensor_filter framework=auto ! fakesink")
        errs = by_code(diags, "NNS005")
        assert errs and "video/x-raw" in errs[0].message
        assert "tensor_converter" in (errs[0].hint or "")

    def test_capsfilter_empty_intersection(self):
        diags = verify_description(
            "videotestsrc format=RGB ! video/x-raw,format=GRAY8 "
            "! fakesink")
        assert by_code(diags, "NNS005")

    def test_capsfilter_compatible_is_clean(self):
        diags = verify_description(
            "videotestsrc format=RGB ! video/x-raw,format=RGB "
            "! fakesink")
        assert by_code(diags, "NNS005") == []

    def test_unlinked_sink_is_error(self):
        diags = verify_description("queue ! fakesink")
        errs = by_code(diags, "NNS006")
        assert any(d.severity == ERROR and "never linked" in d.message
                   for d in errs)

    def test_implied_mux_pads_unfed(self):
        # m.sink_2 implies sink_0/sink_1 exist too; a sync policy would
        # wait on them forever — parse_launch rejects this at build time
        diags = verify_description(
            "videotestsrc ! tensor_converter ! m.sink_2 "
            "tensor_mux name=m ! fakesink")
        errs = by_code(diags, "NNS006")
        assert any(d.severity == ERROR and "implied" in d.message
                   for d in errs)

    def test_dropped_output_is_warning(self):
        diags = verify_description(
            "videotestsrc ! tensor_converter")
        warns = by_code(diags, "NNS006")
        assert any(d.severity == WARNING and "dropped" in d.message
                   for d in warns)

    def test_cycle_detected(self):
        diags = verify_description(
            "tensor_mux name=m sync-mode=nosync ! "
            "tensor_transform name=t ! m.sink_1")
        errs = by_code(diags, "NNS007")
        assert errs and "cycle" in errs[0].message.lower()

    def test_sync_mode_unknown(self):
        diags = verify_description(
            "tensor_mux name=m sync-mode=bogus ! fakesink "
            "videotestsrc ! tensor_converter ! m.sink_0")
        errs = by_code(diags, "NNS008")
        assert errs and errs[0].severity == ERROR

    def test_sync_option_ignored_warns(self):
        diags = verify_description(
            "tensor_mux name=m sync-mode=slowest sync-option=1:33 "
            "! fakesink videotestsrc ! tensor_converter ! m.sink_0")
        warns = by_code(diags, "NNS008")
        assert warns and warns[0].severity == WARNING

    def test_basepad_option_malformed(self):
        diags = verify_description(
            "tensor_mux name=m sync-mode=basepad sync-option=oops "
            "! fakesink videotestsrc ! tensor_converter ! m.sink_0")
        errs = by_code(diags, "NNS008")
        assert errs and errs[0].severity == ERROR

    def test_tee_branch_without_queue(self):
        diags = verify_description(
            "videotestsrc ! tee name=t t. ! fakesink t. ! "
            "queue ! fakesink")
        warns = by_code(diags, "NNS009")
        # exactly the queue-less branch is named
        assert len(warns) == 1 and "fakesink" in warns[0].message

    def test_leaky_queue_without_name(self):
        diags = verify_description(
            "videotestsrc ! queue leaky=downstream ! fakesink")
        assert by_code(diags, "NNS010")
        named = verify_description(
            "videotestsrc ! queue name=q leaky=downstream ! fakesink")
        assert by_code(named, "NNS010") == []

    def test_unknown_framework_is_error(self):
        # the acceptance pipeline from the issue: exits non-zero with an
        # NNS0xx code naming the bad element
        diags = verify_description(
            "videotestsrc ! tensor_converter ! tensor_filter "
            "framework=bogus")
        errs = by_code(diags, "NNS011")
        assert errs and errs[0].severity == ERROR
        assert "bogus" in errs[0].message

    def test_unknown_decoder_mode(self):
        diags = verify_description(
            "videotestsrc ! tensor_converter ! tensor_decoder "
            "mode=nope ! fakesink")
        assert by_code(diags, "NNS011")

    def test_syntax_error_carries_column(self):
        diags = verify_description('videotestsrc ! "unterminated')
        errs = by_code(diags, "NNS012")
        assert errs and errs[0].loc.column > 1

    def test_every_emitted_code_is_documented(self):
        # any diagnostic the verifier can emit has a CODE_TABLE row
        # (docs/linting.md renders from the same table)
        assert {"NNS001", "NNS005", "NNS011", "NNS101", "NNS109",
                "NNS110", "NNS111", "NNS112", "NNS114", "NNS115",
                "NNS199"} <= set(CODE_TABLE)


class TestParsePositionalErrors:
    def test_unknown_element_reports_column(self):
        desc = "videotestsrc ! bogus_element ! fakesink"
        with pytest.raises(ParseError) as ei:
            parse_launch(desc)
        assert ei.value.pos == desc.index("bogus_element")
        assert "column" in str(ei.value)

    def test_unknown_property_reports_column(self):
        desc = "videotestsrc ! fakesink nope=1"
        with pytest.raises(ParseError) as ei:
            parse_launch(desc)
        assert ei.value.pos == desc.index("nope=1")

    def test_unterminated_quote_reports_column(self):
        with pytest.raises(ParseError) as ei:
            parse_launch('videotestsrc ! fakesink name="x')
        assert ei.value.pos is not None


class TestAstLint:
    def test_nns101_wall_clock(self):
        diags = lint_source("import time\nd = time.time()\n", "x.py")
        assert codes(diags) == ["NNS101"]

    def test_nns101_wall_binding_allowed(self):
        diags = lint_source("import time\nwall_ts = time.time()\n",
                            "x.py")
        assert diags == []

    def test_nns102_sleep_under_lock(self):
        src = ("import threading, time\n"
               "lock = threading.Lock()\n"
               "def f():\n"
               "    with lock:\n"
               "        time.sleep(1)\n")
        assert "NNS102" in codes(lint_source(src, "x.py"))

    def test_nns102_thread_join_vs_str_join(self):
        src = ("def f(self):\n"
               "    with self._lock:\n"
               "        self._t.join(timeout=1)\n"
               "        s = ','.join(['a'])\n")
        diags = by_code(lint_source(src, "x.py"), "NNS102")
        assert len(diags) == 1  # the thread join, not the str join

    def test_nns102_outside_lock_ok(self):
        src = "import time\ndef f():\n    time.sleep(1)\n"
        assert by_code(lint_source(src, "x.py"), "NNS102") == []

    def test_nns103_print_in_library(self):
        assert "NNS103" in codes(
            lint_source("def f():\n    print('x')\n", "lib.py"))

    def test_nns103_print_in_main_ok(self):
        assert by_code(lint_source(
            "def main():\n    print('x')\n", "lib.py"), "NNS103") == []

    def test_nns104_bare_except(self):
        src = "try:\n    f()\nexcept:\n    pass\n"
        assert "NNS104" in codes(lint_source(src, "x.py"))

    def test_nns104_blind_swallow(self):
        src = "try:\n    f()\nexcept Exception:\n    pass\n"
        assert "NNS104" in codes(lint_source(src, "x.py"))

    def test_nns104_logged_broad_except_ok(self):
        src = ("try:\n    f()\nexcept Exception as e:\n"
               "    log.debug('%s', e)\n")
        assert by_code(lint_source(src, "x.py"), "NNS104") == []

    def test_nns105_thread_without_daemon(self):
        src = "import threading\nt = threading.Thread(target=f)\n"
        assert "NNS105" in codes(lint_source(src, "x.py"))
        ok = ("import threading\n"
              "t = threading.Thread(target=f, daemon=True)\n")
        assert by_code(lint_source(ok, "x.py"), "NNS105") == []

    def test_nns106_metric_naming(self):
        src = "c = reg.counter('queue_drops')\n"
        assert "NNS106" in codes(lint_source(src, "x.py"))
        ok = "c = reg.counter('nns_queue_drops_total')\n"
        assert by_code(lint_source(ok, "x.py"), "NNS106") == []

    def test_nns107_sync_in_chain(self):
        src = ("import numpy as np\n"
               "class E:\n"
               "    def chain(self, pad, buf):\n"
               "        x = np.asarray(buf.tensors[0])\n")
        assert "NNS107" in codes(lint_source(src, "x.py"))

    def test_nns107_block_until_ready_and_scalar_pull(self):
        src = ("def chain_list(self, pad, bufs):\n"
               "    out.block_until_ready()\n"
               "    v = float(out[0])\n")
        assert codes(lint_source(src, "x.py")) == ["NNS107", "NNS107"]

    def test_nns107_outside_hot_path_ok(self):
        src = ("import numpy as np\n"
               "def to_host(buf):\n"
               "    return np.asarray(buf.tensors[0])\n")
        assert by_code(lint_source(src, "x.py"), "NNS107") == []

    def test_nns107_nested_in_device_stage(self):
        src = ("import numpy as np\n"
               "def device_stage(self):\n"
               "    def run(x):\n"
               "        return np.asarray(x)\n"
               "    return run\n")
        assert "NNS107" in codes(lint_source(src, "x.py"))

    def test_nns107_pragma_suppressible(self):
        src = ("import numpy as np\n"
               "def chain(self, pad, buf):\n"
               "    x = np.asarray(  # nns-lint: disable=NNS107 -- host\n"
               "        buf.tensors[0])\n")
        assert by_code(lint_source(src, "x.py"), "NNS107") == []

    def test_nns108_direct_tensor_materialization(self):
        src = ("import numpy as np\n"
               "def render(buf):\n"
               "    return np.asarray(buf.tensors[0])\n")
        assert "NNS108" in codes(lint_source(src, "x.py"))

    def test_nns108_device_get_and_addressable_data(self):
        src = ("import jax\n"
               "def render(buf):\n"
               "    a = jax.device_get(buf.tensors)\n"
               "    b = buf.tensors[0].addressable_data(0)\n")
        assert by_code(lint_source(src, "x.py"), "NNS108") != []
        assert len(by_code(lint_source(src, "x.py"), "NNS108")) == 2

    def test_nns108_loose_array_ok(self):
        # np.asarray on a plain local array is NNS107's business (hot
        # paths only), never NNS108's
        src = ("import numpy as np\n"
               "def render(x):\n"
               "    return np.asarray(x)\n")
        assert by_code(lint_source(src, "x.py"), "NNS108") == []

    def test_nns108_sanctioned_to_host_ok(self):
        src = ("import numpy as np\n"
               "def to_host(self):\n"
               "    return np.asarray(self.tensors[0])\n")
        assert by_code(lint_source(src, "x.py"), "NNS108") == []

    def test_nns108_pragma_suppressible(self):
        src = ("import numpy as np\n"
               "def render(buf):\n"
               "    return np.asarray(  # nns-lint: disable=NNS108 -- "
               "host payload by construction\n"
               "        buf.tensors[0])\n")
        assert by_code(lint_source(src, "x.py"), "NNS108") == []

    def test_nns109_stateful_chain_with_flag(self):
        src = ("class BadElement:\n"
               "    REORDER_SAFE = True\n"
               "    def chain(self, pad, buf):\n"
               "        self.count += 1\n"
               "        self.acc.append(buf)\n"
               "        return buf\n")
        assert codes(lint_source(src, "x.py")) == ["NNS109", "NNS109"]

    def test_nns109_subscript_store_counts(self):
        src = ("class BadElement:\n"
               "    REORDER_SAFE = True\n"
               "    def chain_list(self, pad, bufs):\n"
               "        self.seen[bufs[0].pts] = True\n")
        assert "NNS109" in codes(lint_source(src, "x.py"))

    def test_nns109_no_flag_ok(self):
        # stateful chain without the declaration is the normal case —
        # the planner simply won't replicate it
        src = ("class Stateful:\n"
               "    def chain(self, pad, buf):\n"
               "        self.count += 1\n"
               "        return buf\n")
        assert by_code(lint_source(src, "x.py"), "NNS109") == []

    def test_nns109_flag_with_clean_chain_ok(self):
        # locals and reads of self are fine; only per-frame self
        # mutations break lane replication
        src = ("class PureElement:\n"
               "    REORDER_SAFE = True\n"
               "    def chain(self, pad, buf):\n"
               "        scale = self.get_property('scale')\n"
               "        out = buf.tensors[0] * scale\n"
               "        return out\n"
               "    def start(self):\n"
               "        self.warm = True\n")
        assert by_code(lint_source(src, "x.py"), "NNS109") == []

    def test_nns109_pragma_suppressible(self):
        src = ("class Counted:\n"
               "    REORDER_SAFE = True\n"
               "    def chain(self, pad, buf):\n"
               "        self.n += 1  # nns-lint: disable=NNS109 -- "
               "stats only, never touches payload\n"
               "        return buf\n")
        assert by_code(lint_source(src, "x.py"), "NNS109") == []

    def test_nns110_sleep_in_sched_hot_path(self):
        src = ("import time\n"
               "def _drain_sched(self):\n"
               "    time.sleep(0.01)\n")
        assert "NNS110" in codes(lint_source(src, "x.py"))

    def test_nns110_unbounded_waits_flagged_bounded_ok(self):
        src = ("def _drain_sched(self):\n"
               "    item = self._q.get()\n"
               "def admit(self, buf):\n"
               "    self._ev.wait()\n"
               "    self._cv.wait_for(self._pred)\n")
        assert len(by_code(lint_source(src, "x.py"), "NNS110")) == 3
        src_ok = ("def _drain_sched(self):\n"
                  "    item = self._q.get(timeout=0.1)\n"
                  "def admit(self, buf):\n"
                  "    self._ev.wait(0.5)\n"
                  "    self._cv.wait_for(self._pred, 1.0)\n")
        assert by_code(lint_source(src_ok, "x.py"), "NNS110") == []

    def test_nns110_dict_get_and_cold_paths_ok(self):
        # d.get(key) is not a blocking call, and the same forever-wait
        # outside the scheduler/dispatch hot-path set is NNS102's (lock)
        # or nobody's business
        src = ("def admit(self, buf):\n"
               "    t = buf.meta.get('deadline_t')\n"
               "def shutdown(self):\n"
               "    self._q.get()\n"
               "    self._ev.wait()\n")
        assert by_code(lint_source(src, "x.py"), "NNS110") == []

    def test_nns110_pragma_suppressible(self):
        src = ("def _flush_edf(self):\n"
               "    self._ev.wait()  # nns-lint: disable=NNS110 -- "
               "teardown-only flush, no admission live\n")
        assert by_code(lint_source(src, "x.py"), "NNS110") == []

    def test_nns111_swallowed_except_in_worker_loop(self):
        src = ("def _worker(self, k):\n"
               "    try:\n"
               "        step()\n"
               "    except Exception as e:\n"
               "        log.warning('oops %s', e)\n")
        assert "NNS111" in codes(lint_source(src, "x.py"))

    def test_nns111_reraise_or_bus_post_ok(self):
        src = ("def chain(self, pad, buf):\n"
               "    try:\n"
               "        step()\n"
               "    except Exception:\n"
               "        raise\n"
               "def _drain(self):\n"
               "    try:\n"
               "        step()\n"
               "    except Exception as e:\n"
               "        self.post_error(e)\n"
               "def run_loop(self):\n"
               "    try:\n"
               "        step()\n"
               "    except Exception:\n"
               "        self.post_warning('degraded')\n")
        assert by_code(lint_source(src, "x.py"), "NNS111") == []

    def test_nns111_narrow_or_cold_path_ok(self):
        # a narrow except is a deliberate, typed decision; the same
        # swallow outside the chain/worker set is not this rule's concern
        src = ("def _worker(self, k):\n"
               "    try:\n"
               "        step()\n"
               "    except KeyError as e:\n"
               "        log.warning('oops %s', e)\n"
               "def helper(self):\n"
               "    try:\n"
               "        step()\n"
               "    except Exception as e:\n"
               "        log.warning('oops %s', e)\n")
        assert by_code(lint_source(src, "x.py"), "NNS111") == []

    def test_nns111_bare_and_pass_left_to_nns104(self):
        src = ("def chain(self, pad, buf):\n"
               "    try:\n"
               "        step()\n"
               "    except:\n"
               "        pass\n"
               "    try:\n"
               "        step()\n"
               "    except Exception:\n"
               "        pass\n")
        assert by_code(lint_source(src, "x.py"), "NNS111") == []
        assert len(by_code(lint_source(src, "x.py"), "NNS104")) == 2

    def test_nns111_pragma_suppressible(self):
        src = ("def _drain(self):\n"
               "    try:\n"
               "        step()\n"
               "    except Exception as e:  # nns-lint: disable=NNS111 "
               "-- error response goes out in-band\n"
               "        respond(e)\n")
        assert by_code(lint_source(src, "x.py"), "NNS111") == []

    def test_nns114_unbounded_deque_in_obs_record_func(self):
        src = ("import collections\n"
               "def observe(self, x):\n"
               "    q = collections.deque()\n"
               "    q.append(x)\n")
        assert "NNS114" in codes(
            lint_source(src, "nnstreamer_tpu/obs/q.py"))
        # same source outside obs/ is out of scope for this rule
        assert by_code(
            lint_source(src, "nnstreamer_tpu/pipeline/q.py"),
            "NNS114") == []

    def test_nns114_bounded_deque_ok(self):
        src = ("from collections import deque\n"
               "def record_frame(self, x):\n"
               "    self._ring = deque(maxlen=64)\n")
        assert by_code(
            lint_source(src, "nnstreamer_tpu/obs/q.py"), "NNS114") == []

    def test_nns114_append_to_unbounded_init_attr(self):
        src = ("class Rec:\n"
               "    def __init__(self):\n"
               "        self.frames = []\n"
               "        self.ring = __import__('collections')\n"
               "    def observe(self, seq):\n"
               "        self.frames.append(seq)\n"
               "    def configure(self, opts):\n"
               "        self.frames.append(opts)\n")
        # only the recording function is a hot path; configure() is
        # setup-time and stays out of scope
        assert len(by_code(
            lint_source(src, "nnstreamer_tpu/obs/rec.py"),
            "NNS114")) == 1

    def test_nns114_pragma_suppressible(self):
        src = ("from collections import deque\n"
               "def observe(self, x):\n"
               "    q = deque()  # nns-lint: disable=NNS114 -- drained "
               "and discarded before return\n"
               "    q.append(x)\n")
        assert by_code(
            lint_source(src, "nnstreamer_tpu/obs/q.py"), "NNS114") == []

    def test_nns115_key_drift_both_directions(self):
        src = ("class C:\n"
               "    def snapshot(self):\n"
               "        return {'a': 1, 'b': 2}\n"
               "    def restore(self, state):\n"
               "        self.a = state['a']\n"
               "        self.c = state.get('c', 0)\n")
        errs = by_code(lint_source(src, "x.py"), "NNS115")
        assert len(errs) == 1
        assert "'b'" in errs[0].message and "'c'" in errs[0].message

    def test_nns115_symmetric_pair_ok(self):
        src = ("class C:\n"
               "    def checkpoint_state(self):\n"
               "        out = {'a': 1}\n"
               "        out['b'] = 2\n"
               "        return out\n"
               "    def restore_state(self, state):\n"
               "        self.a = state.pop('a')\n"
               "        self.b = state.get('b', 0)\n")
        assert by_code(lint_source(src, "x.py"), "NNS115") == []

    def test_nns115_dynamic_schema_skipped(self):
        # TensorRepo-style: save side has no literal keys, so there
        # is no evidence of drift
        src = ("class Repo:\n"
               "    def snapshot(self):\n"
               "        return {k: v.data for k, v in self.s.items()}\n"
               "    def restore(self, state):\n"
               "        self.magic = state['magic']\n")
        assert by_code(lint_source(src, "x.py"), "NNS115") == []

    def test_nns115_save_only_class_not_checked(self):
        # reporting-only snapshot() with no restore() is not a
        # checkpoint pair
        src = ("class Gauge:\n"
               "    def snapshot(self):\n"
               "        return {'value': self.v}\n")
        assert by_code(lint_source(src, "x.py"), "NNS115") == []

    def test_nns115_pragma_suppressible(self):
        src = ("class C:\n"
               "    def snapshot(self):  # nns-lint: disable=NNS115 -- "
               "legacy key kept for old readers\n"
               "        return {'a': 1, 'legacy': 0}\n"
               "    def restore(self, state):\n"
               "        self.a = state['a']\n")
        assert by_code(lint_source(src, "x.py"), "NNS115") == []

    def test_nns116_pack_arity_mismatch(self):
        src = ("import struct\n"
               "_HDR = struct.Struct('<IIQ')\n"
               "def f(a, b):\n"
               "    return _HDR.pack(a, b)\n")
        errs = by_code(lint_source(src, "x.py"), "NNS116")
        assert len(errs) == 1
        assert "2 value(s)" in errs[0].message
        assert "3 field(s)" in errs[0].message

    def test_nns116_unpack_arity_mismatch(self):
        src = ("import struct\n"
               "_EXT = struct.Struct('<QdQd')\n"
               "def f(payload):\n"
               "    req_id, slack = _EXT.unpack_from(payload)\n"
               "    return req_id, slack\n")
        errs = by_code(lint_source(src, "x.py"), "NNS116")
        assert len(errs) == 1
        assert "4 field(s)" in errs[0].message

    def test_nns116_matching_sites_ok(self):
        # pad bytes count zero fields, 's' is one field, repeat counts
        # expand — the struct module itself is the arbiter
        src = ("import struct\n"
               "_H = struct.Struct('<I4x2H8s')\n"
               "def f(a, b, c, d, blob):\n"
               "    w = _H.pack(a, b, c, d)\n"
               "    p, q, r, s = _H.unpack(w)\n"
               "    vals = _H.unpack(w)\n"
               "    return p, q, r, s, vals, blob\n")
        assert by_code(lint_source(src, "x.py"), "NNS116") == []

    def test_nns116_dynamic_arity_skipped(self):
        src = ("import struct\n"
               "_H = struct.Struct('<II')\n"
               "def f(args, blob):\n"
               "    a = _H.pack(*args)\n"
               "    first, *rest = _H.unpack(blob)\n"
               "    return a, first, rest\n")
        assert by_code(lint_source(src, "x.py"), "NNS116") == []

    def test_nns116_pack_into_offsets_excluded(self):
        src = ("import struct\n"
               "_H = struct.Struct('<II')\n"
               "def f(buf, a, b):\n"
               "    _H.pack_into(buf, 0, a, b)\n"
               "    _H.pack_into(buf, 0, a)\n")
        errs = by_code(lint_source(src, "x.py"), "NNS116")
        assert len(errs) == 1 and errs[0].loc.line == 5

    def test_nns116_rebound_name_ambiguous_skipped(self):
        src = ("import struct\n"
               "_H = struct.Struct('<II')\n"
               "_H = struct.Struct('<IIQ')\n"
               "def f(a, b):\n"
               "    return _H.pack(a, b)\n")
        assert by_code(lint_source(src, "x.py"), "NNS116") == []

    def test_nns116_pragma_suppressible(self):
        src = ("import struct\n"
               "_H = struct.Struct('<II')\n"
               "def f(a):\n"
               "    return _H.pack(a)  # nns-lint: disable=NNS116 -- "
               "second field appended by caller\n")
        assert by_code(lint_source(src, "x.py"), "NNS116") == []

    def test_nns116_protocol_headers_clean(self):
        # the real wire headers this rule exists for must lint clean
        from pathlib import Path

        from nnstreamer_tpu.analysis.astlint import lint_file
        root = Path(__file__).resolve().parent.parent
        for mod in ("query/protocol.py", "query/refwire.py",
                    "query/mqtt.py"):
            diags = [d for d in lint_file(root / "nnstreamer_tpu" / mod)
                     if d.code == "NNS116"]
            assert diags == [], diags

    def test_nns117_sharding_ctor_outside_parallel(self):
        src = ("from jax.sharding import NamedSharding, PartitionSpec\n"
               "def f(mesh, x):\n"
               "    s = NamedSharding(mesh, PartitionSpec('dp'))\n"
               "    return s\n")
        assert "NNS117" in codes(lint_source(src, "elements/foo.py"))

    def test_nns117_dotted_forms_and_pjit(self):
        src = ("import jax\n"
               "from jax.experimental import pjit\n"
               "def f(mesh, fn):\n"
               "    a = jax.sharding.NamedSharding(mesh, None)\n"
               "    b = pjit.pjit(fn)\n"
               "    return a, b\n")
        assert codes(lint_source(src, "serving/x.py")) == ["NNS117",
                                                          "NNS117"]

    def test_nns117_inside_parallel_package_exempt(self):
        src = ("from jax.sharding import NamedSharding\n"
               "def f(mesh, spec):\n"
               "    return NamedSharding(mesh, spec)\n")
        assert by_code(
            lint_source(src, "nnstreamer_tpu/parallel/serve.py"),
            "NNS117") == []

    def test_nns117_pragma_suppressible(self):
        src = ("from jax.sharding import NamedSharding\n"
               "def f(mesh, spec):\n"
               "    return NamedSharding(  # nns-lint: disable=NNS117 -- "
               "one-off placement in a test harness\n"
               "        mesh, spec)\n")
        assert by_code(lint_source(src, "elements/foo.py"),
                       "NNS117") == []

    def test_nns119_hardcoded_endpoint_literal(self):
        src = ("def connect():\n"
               "    ep = '127.0.0.1:3000'\n"
               "    return ep\n")
        assert "NNS119" in codes(lint_source(src, "elements/foo.py"))

    def test_nns119_hostname_form_flagged(self):
        src = "BROKER = 'edge-broker.local:1883'\n"
        assert "NNS119" in codes(lint_source(src, "serving/x.py"))

    def test_nns119_non_endpoints_pass(self):
        # times, ratios, short ports, and plain hosts must not match
        src = ("a = '12:30'\n"          # clock time: no letter/dot host
               "b = 'C:1'\n"           # 1-digit port
               "c = 'host:port'\n"     # no numeric port
               "d = '127.0.0.1'\n"     # no port at all
               "e = 'a label: 42 things'\n")
        assert by_code(lint_source(src, "elements/foo.py"),
                       "NNS119") == []

    def test_nns119_discovery_config_and_tests_exempt(self):
        src = "DEFAULT = '127.0.0.1:1883'\n"
        for rel in ("query/discovery.py", "config.py",
                    "tests/test_x.py", "test_foo.py"):
            assert by_code(lint_source(src, rel), "NNS119") == [], rel

    def test_nns119_pragma_suppressible(self):
        src = ("WELL_KNOWN = '127.0.0.1:1883'  # nns-lint: "
               "disable=NNS119 -- the MQTT standard port default\n")
        assert by_code(lint_source(src, "elements/foo.py"),
                       "NNS119") == []

    def test_pragma_suppresses_with_reason(self):
        src = ("import time\n"
               "d = time.time()  # nns-lint: disable=NNS101 -- epoch "
               "for the wire\n")
        assert lint_source(src, "x.py") == []

    def test_pragma_without_reason_is_nns199(self):
        src = ("import time\n"
               "d = time.time()  # nns-lint: disable=NNS101\n")
        assert codes(lint_source(src, "x.py")) == ["NNS199"]


class TestExtract:
    def test_python_literal_and_fstring(self):
        src = ("from nnstreamer_tpu import parse_launch\n"
               "p = parse_launch('videotestsrc ! fakesink')\n"
               "q = parse_launch(f'videotestsrc num-buffers={n} "
               "! fakesink')\n"
               "r = parse_launch('videotestsrc ! ... ! fakesink')\n")
        snips = extract_from_python(src, "x.py")
        assert len(snips) == 2  # the '...' placeholder is skipped
        assert snips[0].description == "videotestsrc ! fakesink"
        assert "num-buffers=0" in snips[1].description

    def test_markdown_fences(self):
        md = ("# Doc\n"
              "```bash\n"
              'nns-launch "videotestsrc ! fakesink"\n'
              "```\n"
              "```python\n"
              "parse_launch('audiotestsrc ! fakesink')\n"
              "```\n"
              "```bash\n"
              'nns-launch "videotestsrc ! ... ! fakesink"\n'
              "```\n")
        snips = extract_from_markdown(md, "doc.md")
        assert [s.description for s in snips] == [
            "videotestsrc ! fakesink", "audiotestsrc ! fakesink"]
        assert snips[0].line == 3


class TestCli:
    def test_error_exits_nonzero(self, capsys):
        from nnstreamer_tpu.analysis.cli import main

        rc = main(["videotestsrc ! tensor_converter ! tensor_filter "
                   "framework=bogus"])
        assert rc == 1
        assert "NNS011" in capsys.readouterr().out

    def test_clean_exits_zero(self, capsys):
        from nnstreamer_tpu.analysis.cli import main

        assert main(["videotestsrc ! tensor_converter ! tensor_sink"]) \
            == 0

    def test_usage_error_exits_two(self, capsys):
        from nnstreamer_tpu.analysis.cli import main

        assert main([]) == 2

    def test_json_schema(self, capsys):
        from nnstreamer_tpu.analysis.cli import main

        rc = main(["--format", "json",
                   "videotestsrc ! tensor_converter ! tensor_filter "
                   "framework=bogus"])
        assert rc == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["version"] == 1
        assert set(doc["summary"]) == {"error", "warning", "info"}
        assert doc["summary"]["error"] >= 1
        d = doc["diagnostics"][0]
        assert set(d) == {"code", "severity", "message", "hint", "loc"}
        assert set(d["loc"]) == {"source", "line", "column"}
        assert all(x["code"] in CODE_TABLE for x in doc["diagnostics"])

    def test_strict_fails_on_warnings(self, capsys):
        from nnstreamer_tpu.analysis.cli import main

        desc = ("videotestsrc ! tee name=t t. ! tensor_sink t. ! "
                "tensor_sink")
        assert main([desc]) == 0          # warnings only
        assert main(["--strict", desc]) == 1

    def test_launch_check_flag(self, capsys):
        from nnstreamer_tpu.cli import main as launch_main

        assert launch_main(
            ["--check", "videotestsrc ! tensor_converter ! "
             "tensor_filter framework=bogus"]) == 1
        assert launch_main(
            ["--check", "videotestsrc num-buffers=2 ! "
             "tensor_converter ! tensor_sink"]) == 0


class TestPipelineVerify:
    def test_parsed_pipeline_verifies_clean(self):
        pipe = parse_launch("videotestsrc num-buffers=2 ! "
                            "tensor_converter ! tensor_sink")
        assert pipe.verify() == []

    def test_programmatic_dangling_sink(self):
        from nnstreamer_tpu.pipeline.pipeline import Pipeline, Queue

        pipe = Pipeline("p")
        pipe.add(Queue(name="q"))
        diags = pipe.verify()
        assert "NNS006" in [d.code for d in diags]
        assert any(d.severity == ERROR for d in diags)


class TestConcurrencyLint:
    """NNS2xx whole-program fixtures (concurrency.py)."""

    def _lint(self, src, rel="x.py"):
        from nnstreamer_tpu.analysis.concurrency import (
            lint_concurrency_source)
        return lint_concurrency_source(src, rel)

    # -- NNS201: guarded-attribute inference ------------------------------

    def test_nns201_unguarded_write(self):
        src = ("import threading\n"
               "class Counter:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._n = 0\n"
               "    def bump(self):\n"
               "        with self._lock:\n"
               "            self._n += 1\n"
               "    def reset_fast(self):\n"
               "        self._n = 0\n")
        diags = by_code(self._lint(src), "NNS201")
        assert len(diags) == 1
        assert "_n" in diags[0].message

    def test_nns201_unguarded_read_with_strong_guard_evidence(self):
        # reads are only flagged under the stricter bar: no unlocked
        # writes anywhere, >=3 locked accesses, and the read minority
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._n = 0\n"
               "    def a(self):\n"
               "        with self._lock:\n"
               "            self._n += 1\n"
               "    def b(self):\n"
               "        with self._lock:\n"
               "            self._n += 1\n"
               "    def c(self):\n"
               "        with self._lock:\n"
               "            return self._n\n"
               "    def peek(self):\n"
               "        return self._n\n")
        assert len(by_code(self._lint(src), "NNS201")) == 1

    def test_nns201_all_guarded_clean(self):
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._n = 0\n"
               "    def bump(self):\n"
               "        with self._lock:\n"
               "            self._n += 1\n"
               "    def read(self):\n"
               "        with self._lock:\n"
               "            return self._n\n")
        assert by_code(self._lint(src), "NNS201") == []

    def test_nns201_locked_suffix_assumed_held(self):
        # ``*_locked`` naming convention: the method is assumed to run
        # with the guard held, so its accesses are locked evidence, not
        # violations
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._n = 0\n"
               "    def bump(self):\n"
               "        with self._lock:\n"
               "            self._bump_locked()\n"
               "    def _bump_locked(self):\n"
               "        self._n += 1\n")
        assert by_code(self._lint(src), "NNS201") == []

    def test_nns201_held_on_entry_inference(self):
        # a private helper whose every call site holds the lock is
        # inferred lock-held even without the naming convention
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._n = 0\n"
               "    def bump(self):\n"
               "        with self._lock:\n"
               "            self._incr()\n"
               "    def bump2(self):\n"
               "        with self._lock:\n"
               "            self._incr()\n"
               "    def _incr(self):\n"
               "        self._n += 1\n")
        assert by_code(self._lint(src), "NNS201") == []

    def test_nns201_lifecycle_methods_exempt(self):
        # single-owner phases: stop() runs after the worker is joined,
        # so its unlocked mutation is not a data race
        src = ("import threading\n"
               "class Engine:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._work = []\n"
               "    def submit(self, item):\n"
               "        with self._lock:\n"
               "            self._work.append(item)\n"
               "    def stop(self):\n"
               "        self._work = []\n")
        assert by_code(self._lint(src), "NNS201") == []

    def test_nns201_sync_safe_attrs_exempt(self):
        src = ("import threading\n"
               "import queue\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._q = queue.Queue()\n"
               "        self._ev = threading.Event()\n"
               "    def put(self, x):\n"
               "        with self._lock:\n"
               "            self._q.put(x)\n"
               "            self._ev.set()\n"
               "    def drain(self):\n"
               "        self._ev.wait(0.1)\n"
               "        return self._q.get(timeout=0.1)\n")
        assert by_code(self._lint(src), "NNS201") == []

    def test_nns201_condition_counts_as_guard(self):
        src = ("import threading\n"
               "class C:\n"
               "    def __init__(self):\n"
               "        self._idle = threading.Condition()\n"
               "        self._busy = 0\n"
               "    def enter(self):\n"
               "        with self._idle:\n"
               "            self._busy += 1\n"
               "    def leak(self):\n"
               "        self._busy -= 1\n")
        assert len(by_code(self._lint(src), "NNS201")) == 1

    def test_nns201_pragma_suppressible(self):
        src = ("import threading\n"
               "class Counter:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._n = 0\n"
               "    def bump(self):\n"
               "        with self._lock:\n"
               "            self._n += 1\n"
               "    def reset_fast(self):\n"
               "        self._n = 0  # nns-lint: disable=NNS201 -- "
               "monotonic reset, torn read is benign\n")
        assert by_code(self._lint(src), "NNS201") == []

    # -- NNS202: lock-ordering graph --------------------------------------

    def test_nns202_two_lock_inversion(self):
        src = ("import threading\n"
               "A = threading.Lock()\n"
               "B = threading.Lock()\n"
               "def f():\n"
               "    with A:\n"
               "        with B:\n"
               "            pass\n"
               "def g():\n"
               "    with B:\n"
               "        with A:\n"
               "            pass\n")
        diags = by_code(self._lint(src), "NNS202")
        assert diags
        assert "cycle" in diags[0].message.lower()

    def test_nns202_consistent_order_clean(self):
        src = ("import threading\n"
               "A = threading.Lock()\n"
               "B = threading.Lock()\n"
               "def f():\n"
               "    with A:\n"
               "        with B:\n"
               "            pass\n"
               "def g():\n"
               "    with A:\n"
               "        with B:\n"
               "            pass\n")
        assert by_code(self._lint(src), "NNS202") == []

    def test_nns202_self_nest_plain_lock(self):
        src = ("import threading\n"
               "L = threading.Lock()\n"
               "def f():\n"
               "    with L:\n"
               "        with L:\n"
               "            pass\n")
        assert by_code(self._lint(src), "NNS202")

    def test_nns202_self_nest_rlock_clean(self):
        src = ("import threading\n"
               "L = threading.RLock()\n"
               "def f():\n"
               "    with L:\n"
               "        with L:\n"
               "            pass\n")
        assert by_code(self._lint(src), "NNS202") == []

    def test_nns202_cross_file_inversion(self):
        from nnstreamer_tpu.analysis.concurrency import (
            lint_concurrency_sources)
        srcs = {
            "a.py": ("import threading\n"
                     "LOCK_A = threading.Lock()\n"
                     "LOCK_B = threading.Lock()\n"
                     "def f():\n"
                     "    with LOCK_A:\n"
                     "        with LOCK_B:\n"
                     "            pass\n"),
            "b.py": ("from a import LOCK_A, LOCK_B\n"
                     "def g():\n"
                     "    with LOCK_B:\n"
                     "        with LOCK_A:\n"
                     "            pass\n"),
        }
        assert by_code(lint_concurrency_sources(srcs), "NNS202")

    # -- NNS203: check-then-act -------------------------------------------

    def test_nns203_check_then_act(self):
        src = ("import threading\n"
               "class Cache:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._d = {}\n"
               "    def put(self, k, v):\n"
               "        with self._lock:\n"
               "            self._d[k] = v\n"
               "    def ensure(self, k):\n"
               "        if k not in self._d:\n"
               "            self._d[k] = object()\n")
        diags = self._lint(src)
        assert by_code(diags, "NNS203")
        # the unguarded mutation itself is also NNS201 — both fire
        assert by_code(diags, "NNS201")

    def test_nns203_locked_check_then_act_clean(self):
        src = ("import threading\n"
               "class Cache:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._d = {}\n"
               "    def put(self, k, v):\n"
               "        with self._lock:\n"
               "            self._d[k] = v\n"
               "    def ensure(self, k):\n"
               "        with self._lock:\n"
               "            if k not in self._d:\n"
               "                self._d[k] = object()\n")
        assert by_code(self._lint(src), "NNS203") == []

    # -- NNS204: foreign calls under lock ---------------------------------

    def test_nns204_callback_under_lock(self):
        src = ("import threading\n"
               "class Emitter:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._callbacks = []\n"
               "    def add(self, cb):\n"
               "        with self._lock:\n"
               "            self._callbacks.append(cb)\n"
               "    def fire(self, evt):\n"
               "        with self._lock:\n"
               "            for cb in list(self._callbacks):\n"
               "                cb(evt)\n")
        assert by_code(self._lint(src), "NNS204")

    def test_nns204_copy_then_dispatch_clean(self):
        src = ("import threading\n"
               "class Emitter:\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n"
               "        self._callbacks = []\n"
               "    def add(self, cb):\n"
               "        with self._lock:\n"
               "            self._callbacks.append(cb)\n"
               "    def fire(self, evt):\n"
               "        with self._lock:\n"
               "            cbs = list(self._callbacks)\n"
               "        for cb in cbs:\n"
               "            cb(evt)\n")
        assert by_code(self._lint(src), "NNS204") == []

    # -- static graph export + CLI ----------------------------------------

    def test_static_lock_graph_shape(self, tmp_path):
        (tmp_path / "m.py").write_text(
            "import threading\n"
            "A = threading.Lock()\n"
            "B = threading.Lock()\n"
            "def f():\n"
            "    with A:\n"
            "        with B:\n"
            "            pass\n")
        from nnstreamer_tpu.analysis.concurrency import static_lock_graph
        g = static_lock_graph(tmp_path)
        assert g["version"] == 1
        assert len(g["edges"]) == 1
        assert set(g["edges"][0]) == {"from", "to", "site"}
        assert len(g["sites"]) == 2

    def test_cli_concurrency_flag(self, capsys):
        from nnstreamer_tpu.analysis.cli import main

        assert main(["--concurrency"]) == 0
        capsys.readouterr()  # drain the text-mode output
        assert main(["--concurrency", "--format", "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["version"] == 1
        assert doc["diagnostics"] == []
