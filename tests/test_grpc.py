"""gRPC TensorService bridge: loopback tests over 127.0.0.1 (the
reference's tests/nnstreamer_grpc pattern — free local ports, client and
server pipelines in one process)."""

import numpy as np
import pytest

pytest.importorskip("grpc")

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.query.grpc_bridge import (
    TensorServiceClient,
    TensorServiceServer,
)
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def _frames(n=4, shape=(2, 3)):
    return [TensorBuffer([np.full(shape, i, np.float32),
                          np.arange(4, dtype=np.int32)])
            for i in range(n)]


@pytest.mark.parametrize("idl", ["protobuf", "flexbuf", "flatbuf"])
def test_service_send_roundtrip(idl):
    got = []
    server = TensorServiceServer(port=0, idl=idl, on_recv=got.append).start()
    try:
        client = TensorServiceClient(port=server.port, idl=idl).wait_ready()
        client.send_stream(iter(_frames()))
        client.close()
        assert len(got) == 4
        # all three reference codecs (protobuf/flexbuf/flatbuf) are
        # rank-4 normalizing on the wire; only nnstpu-flex keeps rank
        np.testing.assert_array_equal(
            got[2].tensors[0].reshape(2, 3),
            np.full((2, 3), 2, np.float32))
        np.testing.assert_array_equal(
            got[0].tensors[1].reshape(4),
            np.arange(4, dtype=np.int32))
    finally:
        server.stop()


def test_service_recv_stream():
    server = TensorServiceServer(port=0).start()
    try:
        for f in _frames(3):
            server.send(f)
        client = TensorServiceClient(port=server.port).wait_ready()
        it = client.recv_stream()
        out = [next(it) for _ in range(3)]
        client.close()
        assert [float(b.tensors[0].reshape(-1)[0]) for b in out] == \
            [0.0, 1.0, 2.0]
    finally:
        server.stop()


def test_grpc_elements_pipeline_loopback():
    """sink(client) pipeline streams into src(server) pipeline."""
    recv_pipe = parse_launch(
        "tensor_src_grpc name=rx server=true port=0 num-buffers=5 ! "
        "tensor_sink name=out")
    rx = recv_pipe.get("rx")
    out = recv_pipe.get("out")
    recv_pipe.start()
    try:
        send_pipe = parse_launch(
            f"videotestsrc num-buffers=5 width=4 height=4 ! "
            f"tensor_converter ! "
            f"tensor_sink_grpc name=tx server=false port={rx.port}")
        msg = send_pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        bufs = out.wait(5, timeout=30)
        assert len(bufs) == 5
        assert bufs[0].tensors[0].shape == (1, 4, 4, 3)
    finally:
        recv_pipe.stop()


def test_grpc_elements_pull_mode():
    """src(client) pulls the stream a sink(server) pipeline publishes."""
    pub_pipe = parse_launch(
        "videotestsrc num-buffers=3 width=4 height=4 ! tensor_converter ! "
        "tensor_sink_grpc name=tx server=true port=0")
    tx = pub_pipe.get("tx")
    pub_pipe.start()
    try:
        sub_pipe = parse_launch(
            f"tensor_src_grpc name=rx server=false port={tx.port} "
            f"num-buffers=3 ! tensor_sink name=out")
        out = sub_pipe.get("out")
        sub_pipe.start()
        try:
            bufs = out.wait(3, timeout=30)
            assert len(bufs) == 3
        finally:
            sub_pipe.stop()
        assert pub_pipe.wait(timeout=30).kind == "eos"
    finally:
        pub_pipe.stop()
