"""Model zoo + SPMD parallel tests on the 8-device virtual CPU mesh
(the multi-chip path the driver separately dry-runs via __graft_entry__)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models.transformer import (
    TransformerConfig,
    build_forward,
    init_params,
)
from nnstreamer_tpu.parallel.mesh import make_mesh
from nnstreamer_tpu.parallel.ring import attention_reference, ring_attention
from nnstreamer_tpu.parallel.sharded import (
    make_sharded_forward,
    make_train_step,
    shard_params,
)

TINY = TransformerConfig(vocab=128, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, dtype=jnp.float32)


class TestMesh:
    def test_make_mesh_infer(self):
        mesh = make_mesh([("dp", -1), ("tp", 2)])
        assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2

    def test_bad_sizes(self):
        with pytest.raises(ValueError):
            make_mesh([("dp", 3), ("tp", 3)])


class TestRingAttention:
    def test_matches_reference(self):
        """Ring attention over sp=4 must equal single-device attention."""
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh([("sp", 4)])
        rng = np.random.default_rng(0)
        b, s, h, d = 2, 32, 4, 16
        q, k, v = (rng.standard_normal((b, s, h, d)).astype(np.float32)
                   for _ in range(3))
        ref = attention_reference(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=True)
        ring = shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"),
        )
        out = jax.jit(ring)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_non_causal(self):
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh([("sp", 2)])
        rng = np.random.default_rng(1)
        q, k, v = (rng.standard_normal((1, 16, 2, 8)).astype(np.float32)
                   for _ in range(3))
        ref = attention_reference(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=False)
        out = jax.jit(shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp", causal=False),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"),
        ))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


class TestTransformer:
    def test_forward_shapes(self):
        params = init_params(TINY)
        fwd = build_forward(TINY)
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = jax.jit(fwd)(params, tokens)
        assert logits.shape == (2, 16, 128)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        params = init_params(TINY)
        fwd = jax.jit(build_forward(TINY))
        t1 = jnp.zeros((1, 16), jnp.int32)
        t2 = t1.at[0, 10].set(5)
        l1, l2 = fwd(params, t1), fwd(params, t2)
        np.testing.assert_allclose(np.asarray(l1[0, :10]),
                                   np.asarray(l2[0, :10]), atol=1e-5)
        assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))

    def test_moe_forward(self):
        cfg = TransformerConfig(vocab=64, d_model=16, n_heads=2, n_layers=1,
                                d_ff=32, dtype=jnp.float32, num_experts=4)
        params = init_params(cfg)
        logits = jax.jit(build_forward(cfg))(params,
                                             jnp.zeros((2, 8), jnp.int32))
        assert logits.shape == (2, 8, 64)


class TestShardedTrainStep:
    def test_dp_tp_sp_step_runs_and_learns(self):
        mesh = make_mesh([("dp", 2), ("tp", 2), ("sp", 2)])
        params = shard_params(init_params(TINY), mesh, TINY)
        step = make_train_step(TINY, mesh, learning_rate=1e-2)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 128, (4, 32)), jnp.int32)
        params, loss0 = step(params, tokens)
        for _ in range(5):
            params, loss = step(params, tokens)
        assert float(loss) < float(loss0)  # memorizing one batch

    def test_sharded_forward_matches_unsharded(self):
        mesh = make_mesh([("dp", 2), ("tp", 2), ("sp", 2)])
        params = init_params(TINY)
        fwd_ref = jax.jit(build_forward(TINY))
        tokens = jnp.asarray(
            np.random.default_rng(3).integers(0, 128, (2, 32)), jnp.int32
        )
        ref = fwd_ref(params, tokens)
        fwd_sh = make_sharded_forward(TINY, mesh)
        sh_params = shard_params(params, mesh, TINY)
        from jax.sharding import NamedSharding, PartitionSpec as P

        out = jax.jit(fwd_sh)(
            sh_params, jax.device_put(tokens,
                                      NamedSharding(mesh, P("dp", "sp")))
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-4, atol=5e-4)

    def test_ep_moe_step(self):
        cfg = TransformerConfig(vocab=64, d_model=16, n_heads=2, n_layers=1,
                                d_ff=32, dtype=jnp.float32, num_experts=4)
        mesh = make_mesh([("dp", 2), ("tp", 1), ("ep", 4)])
        params = shard_params(init_params(cfg), mesh, cfg)
        step = make_train_step(cfg, mesh)
        tokens = jnp.zeros((2, 16), jnp.int32)
        params, loss = step(params, tokens)
        assert np.isfinite(float(loss))


class TestVisionModels:
    def test_mobilenet_v2_forward(self):
        from nnstreamer_tpu.models import mobilenet_v2

        fn, params, in_info, out_info = mobilenet_v2(
            image_size=64, dtype=jnp.float32
        )
        x = jnp.zeros(in_info[0].shape, jnp.float32)
        out = jax.jit(fn)(params, x)
        assert out.shape == out_info[0].shape

    def test_ssd_outputs(self):
        from nnstreamer_tpu.models import ssd_mobilenet
        from nnstreamer_tpu.models.ssd_mobilenet import anchor_grid

        fn, params, in_info, out_info = ssd_mobilenet(
            image_size=96, dtype=jnp.float32
        )
        boxes, scores = jax.jit(fn)(params,
                                    jnp.zeros(in_info[0].shape, jnp.float32))
        anchors = anchor_grid(96)
        assert boxes.shape[1] == anchors.shape[0]
        assert scores.shape[1] == anchors.shape[0]

    def test_posenet_outputs(self):
        from nnstreamer_tpu.models import posenet

        fn, params, in_info, out_info = posenet(image_size=65,
                                                dtype=jnp.float32)
        heat, offs = jax.jit(fn)(params,
                                 jnp.zeros(in_info[0].shape, jnp.float32))
        assert heat.shape[-1] == 17
        assert offs.shape[-1] == 34

    def test_lstm_state_evolution(self):
        from nnstreamer_tpu.models import lstm_cell

        fn, params, _, _ = lstm_cell(input_dim=8, hidden=8)
        x = jnp.ones((1, 8))
        h = c = jnp.zeros((1, 8))
        y1, h1, c1 = jax.jit(fn)(params, x, h, c)
        y2, h2, c2 = jax.jit(fn)(params, x, h1, c1)
        assert not np.allclose(np.asarray(h1), np.asarray(h2))


class TestPipelineParallel:
    """GPipe microbatch pipelining over pp (parallel.pipeline), composed
    with sp ring attention, tp, ep, dp in one program."""

    def _mesh(self):
        return make_mesh([("dp", 1), ("pp", 2), ("sp", 2), ("tp", 2),
                          ("ep", 1)])

    def test_pp_forward_matches_dense(self):
        from nnstreamer_tpu.parallel.pipeline import build_pipelined_forward

        mesh = self._mesh()
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, dtype=jnp.float32)
        params = init_params(cfg)
        num_mb, mb, seq = 2, 2, 8
        tokens = np.random.default_rng(0).integers(
            0, cfg.vocab, (num_mb, mb, seq)).astype(np.int32)
        ref = build_forward(cfg)(
            params, jnp.asarray(tokens.reshape(num_mb * mb, seq)))
        pp_params = shard_params(params, mesh, cfg, pipelined=True)
        with jax.set_mesh(mesh):
            got = jax.jit(build_pipelined_forward(cfg, mesh, num_mb))(
                pp_params, jnp.asarray(tokens))
        got = np.asarray(got).reshape(num_mb * mb, seq, -1)
        np.testing.assert_allclose(got, np.asarray(ref), atol=2e-5)

    def test_pp_moe_train_step(self):
        from nnstreamer_tpu.parallel.sharded import make_pp_train_step

        mesh = self._mesh()
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, dtype=jnp.float32,
                                num_experts=2)
        params = shard_params(init_params(cfg), mesh, cfg, pipelined=True)
        step = make_pp_train_step(cfg, mesh, num_microbatches=2)
        tokens = jnp.asarray(np.random.default_rng(1).integers(
            0, cfg.vocab, (2, 2, 8)), jnp.int32)
        params, loss0 = step(params, tokens)
        params, loss1 = step(params, tokens)
        assert np.isfinite(float(loss0)) and float(loss1) < float(loss0)


def test_yolo_detector_pipeline():
    """YOLO model output must flow through the yolov5 decoder mode (fused
    device NMS) end-to-end."""
    import jax.numpy as jnp

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.filters.jax_backend import (
        register_jax_model,
        unregister_jax_model,
    )
    from nnstreamer_tpu.models.yolo import yolo_detector

    size = 64
    apply_fn, params, in_info, out_info = yolo_detector(
        num_classes=4, image_size=size, batch=1)
    assert out_info[0].shape[-1] == 9  # 5 + 4 classes

    def net(p, x):
        return apply_fn(p, (x.astype(jnp.float32) - 127.5) / 127.5)

    register_jax_model("yolo_t", net, params)
    try:
        pipe = parse_launch(
            f"videotestsrc num-buffers=2 width={size} height={size} "
            "pattern=gradient ! tensor_converter ! "
            "tensor_filter framework=jax model=yolo_t ! "
            "tensor_decoder mode=bounding_boxes option1=yolov5 "
            "option3=0.9 option7=meta ! tensor_sink name=out to-host=true")
        msg = pipe.run(timeout=120)
        assert msg is not None and msg.kind == "eos", msg
        outs = pipe.get("out").buffers
        assert len(outs) == 2
        # untrained model: detections list exists (possibly empty), every
        # entry carries normalized boxes
        for d in outs[0].meta["detections"]:
            assert 0 <= d["score"] <= 1
    finally:
        unregister_jax_model("yolo_t")


def test_segmenter_pipeline():
    """Segmenter model → image_segment decoder end-to-end: per-pixel
    logits argmax on device, RGBA overlay + label map on host."""
    import jax.numpy as jnp
    import pytest

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.filters.jax_backend import (
        register_jax_model,
        unregister_jax_model,
    )
    from nnstreamer_tpu.models.segmenter import segmenter

    size, classes = 32, 5
    apply_fn, params, in_info, out_info = segmenter(
        num_classes=classes, base=8, image_size=size, batch=1,
        dtype=jnp.float32)
    assert tuple(out_info[0].shape) == (1, size, size, classes)

    def net(p, x):
        return apply_fn(p, (x.astype(jnp.float32) - 127.5) / 127.5)

    register_jax_model("seg_t", net, params)
    try:
        pipe = parse_launch(
            f"videotestsrc num-buffers=2 width={size} height={size} "
            "pattern=gradient ! tensor_converter ! "
            "tensor_filter framework=jax model=seg_t ! "
            "tensor_decoder mode=image_segment ! "
            "tensor_sink name=out to-host=true")
        msg = pipe.run(timeout=120)
        assert msg is not None and msg.kind == "eos", msg
        outs = pipe.get("out").buffers
        assert len(outs) == 2
        rgba = np.asarray(outs[0].tensors[0])
        assert rgba.shape == (size, size, 4)
        labels = outs[0].meta["segment_labels"]
        assert labels.shape == (size, size)
        assert int(labels.max()) < classes
    finally:
        unregister_jax_model("seg_t")
    with pytest.raises(ValueError):
        segmenter(image_size=30)  # not divisible by 8


class TestMultihost:
    """Single-process behavior of the multi-host bootstrap (the real
    multi-process path reuses jax.distributed; here we pin the no-op and
    mesh/slicing semantics every host relies on)."""

    def test_initialize_noop_single_process(self, monkeypatch):
        from nnstreamer_tpu.parallel import multihost

        for var in ("NNSTPU_COORDINATOR", "NNSTPU_NUM_PROCESSES",
                    "NNSTPU_PROCESS_ID", "JAX_COORDINATOR_ADDRESS"):
            monkeypatch.delenv(var, raising=False)
        assert multihost.initialize() is False
        assert multihost.process_info() == (0, 1)

    def test_global_mesh_wildcard(self):
        from nnstreamer_tpu.parallel import multihost

        mesh = multihost.global_mesh([("dp", -1), ("tp", 2)])
        assert mesh.shape["tp"] == 2
        assert mesh.shape["dp"] * 2 == 8  # conftest: 8 virtual devices

    def test_global_mesh_indivisible(self):
        from nnstreamer_tpu.parallel import multihost

        import pytest
        with pytest.raises(ValueError):
            multihost.global_mesh([("dp", -1), ("tp", 3)])

    def test_local_batch_slice(self):
        from nnstreamer_tpu.parallel import multihost

        assert multihost.local_batch_slice(32) == slice(0, 32)

    def test_host_local_to_global_roundtrip(self):
        import jax
        import numpy as np
        from jax.sharding import PartitionSpec as P

        from nnstreamer_tpu.parallel import multihost

        mesh = multihost.global_mesh([("dp", -1)])
        data = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
        arr = multihost.host_local_to_global(data, mesh, P("dp"))
        assert isinstance(arr, jax.Array)
        np.testing.assert_array_equal(np.asarray(arr), data)


class TestIncrementalDecode:
    """KV-cached decode must match the full forward (models/transformer
    build_decode_step) — the LM-streaming correctness contract."""

    def _cfg(self, experts=0):
        from nnstreamer_tpu.models.transformer import TransformerConfig
        import jax.numpy as jnp

        return TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                 n_layers=2, d_ff=64, max_seq=16,
                                 dtype=jnp.float32, num_experts=experts)

    @pytest.mark.parametrize("experts", [0, 2])
    def test_matches_full_forward(self, experts):
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import (
            build_decode_step, build_forward, init_cache, init_params)

        cfg = self._cfg(experts)
        params = init_params(cfg)
        full = build_forward(cfg)
        step = jax.jit(build_decode_step(cfg))

        rng = np.random.default_rng(3)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (2, 9)), jnp.int32)
        ref_logits = full(params, tokens)               # [b, s, vocab]

        cache = init_cache(cfg, batch=2)
        for t in range(tokens.shape[1]):
            logits, cache = step(params, tokens[:, t], cache,
                                 jnp.int32(t))
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(ref_logits[:, t]),
                rtol=1e-4, atol=1e-4)

    def test_greedy_generation_streams(self):
        """Greedy decode loop with the cache as a device-resident carry —
        the autoregressive peer of the LSTM repo recurrence."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import (
            build_decode_step, init_cache, init_params)

        cfg = self._cfg()
        params = init_params(cfg)
        step = jax.jit(build_decode_step(cfg), donate_argnums=(2,))
        cache = init_cache(cfg, batch=1)
        tok = jnp.asarray([1], jnp.int32)
        out = []
        for t in range(8):
            logits, cache = step(params, tok, cache, jnp.int32(t))
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out.append(int(tok[0]))
        assert len(out) == 8
        assert all(0 <= t < cfg.vocab for t in out)

    def test_repo_loop_pipeline_matches_direct_loop(self):
        """The tensor_repo streaming pipeline must produce the exact token
        sequence of a hand-written decode loop (examples/llm_stream.py
        topology: device-resident KV cache circulating through the slot)."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu import parse_launch
        from nnstreamer_tpu.elements.repo import GLOBAL_REPO
        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model, unregister_jax_model)
        from nnstreamer_tpu.models.transformer import (
            build_decode_step, build_greedy_stream_step, init_cache,
            init_params)
        from nnstreamer_tpu.tensors.buffer import TensorBuffer

        cfg = self._cfg()
        params = init_params(cfg)

        # direct loop
        step_j = jax.jit(build_decode_step(cfg))
        cache = init_cache(cfg, batch=1)
        tok = jnp.asarray([3], jnp.int32)
        want = []
        for t in range(6):
            logits, cache = step_j(params, tok, cache, jnp.int32(t))
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            want.append(int(tok[0]))

        # repo-loop pipeline
        register_jax_model("lm_loop_test", build_greedy_stream_step(cfg),
                           params)
        try:
            GLOBAL_REPO.set("lm_t", TensorBuffer(
                [np.asarray([3], np.int32),
                 np.asarray(init_cache(cfg, batch=1)),
                 np.asarray(0, np.int32)], pts=0))
            pipe = parse_launch(
                "tensor_reposrc slot=lm_t num-buffers=6 timeout=30 ! "
                "tensor_filter framework=jax model=lm_loop_test ! "
                "tee name=t  t. ! tensor_reposink slot=lm_t  "
                "t. ! tensor_sink name=out to-host=false")
            got = []
            pipe.get("out").connect(
                lambda b: got.append(int(np.asarray(b[0]).reshape(-1)[0])))
            msg = pipe.run(timeout=120)
            assert msg is not None and msg.kind == "eos", msg
            assert got == want
        finally:
            unregister_jax_model("lm_loop_test")
            GLOBAL_REPO.remove("lm_t")

    def test_decode_past_cache_length_is_bounded(self):
        """pos beyond max_seq clamps to the last slot (documented
        contract): logits stay finite, no unmasked-garbage attention."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import (
            build_decode_step, init_cache, init_params)

        cfg = self._cfg()
        params = init_params(cfg)
        step = jax.jit(build_decode_step(cfg, max_seq=4))
        cache = init_cache(cfg, batch=1, max_seq=4)
        tok = jnp.asarray([2], jnp.int32)
        for t in range(7):  # 3 steps past the cache length
            logits, cache = step(params, tok, cache, jnp.int32(t))
            assert bool(jnp.all(jnp.isfinite(logits)))
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def test_prefill_then_decode_matches_full_forward(self):
        """prefill(prompt) must hand decode a cache indistinguishable from
        stepping the prompt token by token: the continuation logits equal
        the full forward's."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import (
            build_decode_step, build_forward, build_prefill, init_params)

        cfg = self._cfg()
        params = init_params(cfg)
        rng = np.random.default_rng(7)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab, (2, 5)), jnp.int32)
        nxt = jnp.asarray(rng.integers(0, cfg.vocab, (2,)), jnp.int32)

        logits_p, cache = jax.jit(build_prefill(cfg))(params, prompt)
        step = jax.jit(build_decode_step(cfg))
        logits_d, _ = step(params, nxt, cache, jnp.int32(prompt.shape[1]))

        full = jax.jit(build_forward(cfg))
        ref = full(params, jnp.concatenate([prompt, nxt[:, None]], axis=1))
        np.testing.assert_allclose(np.asarray(logits_p),
                                   np.asarray(ref[:, 4]),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(logits_d),
                                   np.asarray(ref[:, 5]),
                                   rtol=1e-4, atol=1e-4)

    def test_bf16_greedy_parity_with_full_forward(self):
        """In bfloat16 (the shipped decode config's dtype) the cached loop
        must pick the same greedy tokens as running the full forward on
        the growing sequence — attention accumulates in fp32 on both
        paths (code-review regression)."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import (
            TransformerConfig, build_decode_step, build_forward,
            init_cache, init_params)

        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, max_seq=16,
                                dtype=jnp.bfloat16)
        params = init_params(cfg)
        step = jax.jit(build_decode_step(cfg))
        full = build_forward(cfg)

        seq = [5]
        cache = init_cache(cfg, batch=1)
        tok = jnp.asarray([5], jnp.int32)
        for t in range(6):
            logits, cache = step(params, tok, cache, jnp.int32(t))
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            seq.append(int(tok[0]))
        want = [5]
        for t in range(6):
            ref = full(params, jnp.asarray([want], jnp.int32))
            want.append(int(jnp.argmax(ref[0, -1])))
        assert seq == want

    def test_per_stream_positions_continuous_batching(self):
        """pos as a [b] vector: streams at different depths decode in ONE
        dispatch, each matching its own single-stream run (the
        continuous-batching shape)."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import (
            build_decode_step, init_cache, init_params)

        cfg = self._cfg()
        params = init_params(cfg)
        step = jax.jit(build_decode_step(cfg))
        rng = np.random.default_rng(9)

        # two independent streams with different prefix depths
        caches, toks, depths = [], [], (3, 6)
        for d in depths:
            cache = init_cache(cfg, batch=1)
            tok = jnp.asarray([2], jnp.int32)
            for t in range(d):
                logits, cache = step(params, tok, cache, jnp.int32(t))
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            caches.append(cache)
            toks.append(tok)
        ref = [step(params, toks[i], caches[i], jnp.int32(depths[i]))[0]
               for i in range(2)]

        # same two streams, one batched dispatch with per-stream positions
        batched_cache = jnp.concatenate(caches, axis=2)   # [L,2,b,S,h,dh]
        batched_tok = jnp.concatenate(toks)
        logits_b, _ = step(params, batched_tok, batched_cache,
                           jnp.asarray(depths, jnp.int32))
        for i in range(2):
            np.testing.assert_allclose(np.asarray(logits_b[i]),
                                       np.asarray(ref[i][0]),
                                       rtol=1e-4, atol=1e-4)

    def test_sampled_stream_step(self):
        """Temperature sampling through the repo-loop state tuple:
        deterministic for a fixed seed, greedy at temperature 0 and at
        top_k=1, and runnable as a pipeline filter."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu import parse_launch
        from nnstreamer_tpu.elements.repo import GLOBAL_REPO
        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model, unregister_jax_model)
        from nnstreamer_tpu.models.transformer import (
            build_greedy_stream_step, build_sample_stream_step, init_cache,
            init_params)
        from nnstreamer_tpu.tensors.buffer import TensorBuffer

        cfg = self._cfg()
        params = init_params(cfg)
        key0 = jax.random.key_data(jax.random.PRNGKey(0))

        def run(step, with_key):
            cache = init_cache(cfg, batch=1)
            tok = jnp.asarray([3], jnp.int32)
            key = key0
            out = []
            sj = jax.jit(step)
            for t in range(6):
                if with_key:
                    tok, cache, _, key = sj(params, tok, cache,
                                            jnp.int32(t), key)
                else:
                    tok, cache, _ = sj(params, tok, cache, jnp.int32(t))
                out.append(int(tok.reshape(-1)[0]))
            return out

        sampled = build_sample_stream_step(cfg, temperature=1.0)
        a = run(sampled, True)
        b = run(sampled, True)
        assert a == b  # same seed → same stream
        greedy = run(build_greedy_stream_step(cfg), False)
        assert run(build_sample_stream_step(cfg, temperature=0.0),
                   True) == greedy
        assert run(build_sample_stream_step(cfg, temperature=0.5,
                                            top_k=1), True) == greedy

        # as a pipeline filter with the key in the circulating state
        register_jax_model("lm_sample_test", sampled, params)
        try:
            GLOBAL_REPO.set("lm_s", TensorBuffer(
                [np.asarray([3], np.int32),
                 init_cache(cfg, batch=1),
                 np.asarray(0, np.int32),
                 np.asarray(key0)], pts=0))
            pipe = parse_launch(
                "tensor_reposrc slot=lm_s num-buffers=6 timeout=30 ! "
                "tensor_filter framework=jax model=lm_sample_test ! "
                "tee name=t  t. ! tensor_reposink slot=lm_s  "
                "t. ! tensor_sink name=out to-host=false")
            got = []
            pipe.get("out").connect(
                lambda bf: got.append(int(np.asarray(bf[0]).reshape(-1)[0])))
            msg = pipe.run(timeout=120)
            assert msg is not None and msg.kind == "eos", msg
            assert got == a  # pipeline stream equals the direct loop
        finally:
            unregister_jax_model("lm_sample_test")
            GLOBAL_REPO.remove("lm_s")


def test_greedy_stream_step_multi_matches_single():
    """steps=K scan chain must be token-exact vs K single steps."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nnstreamer_tpu.models.transformer import (
        TransformerConfig,
        build_greedy_stream_step,
        init_cache,
        init_params,
    )

    cfg = TransformerConfig(vocab=61, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=32, dtype=jnp.float32)
    params = init_params(cfg, seed=5)
    one = jax.jit(build_greedy_stream_step(cfg))
    multi = jax.jit(build_greedy_stream_step(cfg, steps=6))

    tok1, cache1 = jnp.asarray([3], jnp.int32), init_cache(cfg, batch=1)
    pos1 = jnp.asarray(0, jnp.int32)
    singles = []
    for _ in range(6):
        tok1, cache1, pos1 = one(params, tok1, cache1, pos1)
        singles.append(int(tok1[0]))

    tok2, cache2 = jnp.asarray([3], jnp.int32), init_cache(cfg, batch=1)
    pos2 = jnp.asarray(0, jnp.int32)
    tok2, cache2, pos2, toks = multi(params, tok2, cache2, pos2)
    assert np.asarray(toks).tolist() == singles
    assert int(tok2[0]) == singles[-1]
    assert int(pos2) == 6


# -- mesh-sharded streaming pipeline (parallel/serve.py) ----------------------
#
# Promotion of __graft_entry__.dryrun_multichip's fourth pass to a CI
# gate: N live sources → merge-batch → one dpN-sharded XLA invoke via the
# first-class `mesh=` tensor_filter property → device-side label decode →
# host sink. The sharded run's labels must equal the single-device run's
# exactly, and the hand-offs must not reshard a single byte.


class TestMeshShardedPipeline:
    N_SRC = 8
    PATS = ["gradient", "ball", "black", "smpte"]

    @pytest.fixture
    def cls_model(self):
        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model,
            unregister_jax_model,
        )

        w = jnp.asarray(np.random.default_rng(7).standard_normal(
            (16 * 16 * 3, 10)).astype(np.float32))

        def classify(x):  # [N,16,16,3] uint8 → [N,10] logits
            xf = (x.astype(jnp.float32) - 127.5) / 127.5
            return (xf.reshape(x.shape[0], -1) @ w,)

        register_jax_model("mesh_pipe_cls", classify, None)
        yield "mesh_pipe_cls"
        unregister_jax_model("mesh_pipe_cls")

    def _desc(self, model, extra=""):
        srcs = "".join(
            f"videotestsrc num-buffers=4 width=16 height=16 "
            f"pattern={self.PATS[i % len(self.PATS)]} ! "
            f"tensor_converter ! m. "
            for i in range(self.N_SRC))
        return (srcs +
                "tensor_merge name=m mode=linear option=3 "
                "sync-mode=slowest ! "
                f"tensor_filter framework=jax model={model} {extra}! "
                "tensor_decoder mode=image_labeling option2=batched ! "
                "tensor_sink name=sink to-host=true")

    def _labels(self, model, extra=""):
        from nnstreamer_tpu import parse_launch

        pipe = parse_launch(self._desc(model, extra))
        msg = pipe.run(timeout=600)
        assert msg is not None and msg.kind == "eos", f"pipeline: {msg}"
        return [np.asarray(b.tensors[0]).tolist()
                for b in pipe.get("sink").buffers]

    def test_dp8_labels_match_single_device(self, cls_model):
        from nnstreamer_tpu.parallel import serve

        reshard0 = serve.reshard_bytes_total()
        sharded = self._labels(cls_model, "mesh=dp8 ")
        single = self._labels(cls_model)
        assert len(sharded) == 4, sharded
        assert sharded == single, (
            f"mesh pipeline labels diverged: {sharded} vs {single}")
        # merge hands the batch to the one sharded invoker straight from
        # host — nothing in this graph may reshard
        assert serve.reshard_bytes_total() == reshard0

    def test_elementwise_dp8_byte_identical(self):
        """Golden byte-identity: an elementwise model's dp8 outputs are
        bit-equal to single-device (matmul contraction order varies with
        the per-shard batch on CPU XLA, elementwise does not — this is
        the strongest cross-mesh determinism CPU XLA can promise)."""
        from nnstreamer_tpu import parse_launch
        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model,
            unregister_jax_model,
        )

        def norm(x):
            return ((x.astype(jnp.float32) - 127.5) / 127.5 * 0.977
                    + 0.003,)

        register_jax_model("mesh_pipe_elt", norm, None)
        try:
            outs = {}
            for key, extra in (("dp8", "mesh=dp8 "), ("single", "")):
                srcs = "".join(
                    f"videotestsrc num-buffers=2 width=8 height=8 "
                    f"pattern={self.PATS[i % len(self.PATS)]} ! "
                    f"tensor_converter ! m. "
                    for i in range(self.N_SRC))
                pipe = parse_launch(
                    srcs +
                    "tensor_merge name=m mode=linear option=3 "
                    "sync-mode=slowest ! "
                    "tensor_filter framework=jax model=mesh_pipe_elt "
                    f"{extra}! tensor_sink name=sink to-host=true")
                msg = pipe.run(timeout=600)
                assert msg is not None and msg.kind == "eos", msg
                outs[key] = [np.asarray(b.tensors[0])
                             for b in pipe.get("sink").buffers]
        finally:
            unregister_jax_model("mesh_pipe_elt")
        assert len(outs["dp8"]) == len(outs["single"]) == 2
        for a, b in zip(outs["dp8"], outs["single"]):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(a, b), "dp8 not byte-identical"

    def test_kill_switch_single_device_path(self, cls_model, monkeypatch):
        """NNSTPU_MESH=0 with a mesh= property still present must take
        the byte-identical single-device path: no plan on the backend,
        labels equal the plain run."""
        from nnstreamer_tpu import parse_launch

        monkeypatch.setenv("NNSTPU_MESH", "0")
        pipe = parse_launch(self._desc(cls_model, "mesh=dp8 name=filter "))
        pipe.start()
        try:
            assert pipe.get("filter").fw._mesh_plan is None, \
                "kill switch must keep the backend planless"
            msg = pipe.wait(timeout=600)
            assert msg is not None and msg.kind == "eos", msg
        finally:
            pipe.stop()
        killed = [np.asarray(b.tensors[0]).tolist()
                  for b in pipe.get("sink").buffers]
        monkeypatch.delenv("NNSTPU_MESH")
        assert killed == self._labels(cls_model)
