"""Native C-ABI custom filter (.so) path: build the example scaler filter
and run it inside a pipeline (reference tests/nnstreamer_example custom
.so scaffolding + tensor_filter_custom loading)."""

import os
import shutil
import subprocess

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
SCALER_SO = os.path.join(NATIVE_DIR, "libnnstpu_filter_scaler.so")


@pytest.fixture(scope="module")
def scaler_so():
    if shutil.which("g++") is None and shutil.which("make") is None:
        pytest.skip("no native toolchain")
    subprocess.run(["make", "-C", NATIVE_DIR, "examples"], check=True,
                   capture_output=True)
    assert os.path.isfile(SCALER_SO)
    return SCALER_SO


def test_native_scaler_pipeline(scaler_so):
    pipe = parse_launch(
        f"appsrc name=src ! tensor_transform mode=typecast option=float32 ! "
        f"tensor_filter framework=native model={scaler_so} "
        f"custom=scale:3.0 ! tensor_sink name=out")
    src, out = pipe.get("src"), pipe.get("out")
    pipe.start()
    try:
        src.push([np.arange(12, dtype=np.uint8).reshape(3, 4)])
        src.push([np.ones((3, 4), np.uint8)])
        src.end_of_stream()
        msg = pipe.wait(timeout=30)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    assert len(out.buffers) == 2
    np.testing.assert_allclose(
        out.buffers[0].tensors[0],
        np.arange(12, dtype=np.float32).reshape(3, 4) * 3.0)
    np.testing.assert_allclose(out.buffers[1].tensors[0],
                               np.full((3, 4), 3.0, np.float32))


def test_native_scaler_passthrough_ints(scaler_so):
    """Non-float dtypes pass through untouched."""
    pipe = parse_launch(
        f"appsrc name=src ! tensor_filter framework=native "
        f"model={scaler_so} custom=scale:5.0 ! tensor_sink name=out")
    src, out = pipe.get("src"), pipe.get("out")
    pipe.start()
    try:
        src.push([np.arange(6, dtype=np.int32)])
        src.end_of_stream()
        assert pipe.wait(timeout=30).kind == "eos"
    finally:
        pipe.stop()
    np.testing.assert_array_equal(out.buffers[0].tensors[0],
                                  np.arange(6, dtype=np.int32))


def test_framework_auto_detects_native(scaler_so):
    """framework=auto resolves .so to the native backend."""
    pipe = parse_launch(
        f"appsrc name=src ! tensor_transform mode=typecast option=float32 ! "
        f"tensor_filter framework=auto model={scaler_so} name=f "
        f"custom=scale:2.0 ! tensor_sink name=out")
    src, out = pipe.get("src"), pipe.get("out")
    pipe.start()
    try:
        src.push([np.ones((2, 2), np.uint8)])
        src.end_of_stream()
        assert pipe.wait(timeout=30).kind == "eos"
    finally:
        pipe.stop()
    np.testing.assert_allclose(out.buffers[0].tensors[0],
                               np.full((2, 2), 2.0, np.float32))
