"""SLO scheduler (serving/scheduler.py + Queue scheduler mode).

The contract under test:

- admission rejects work whose deadline is unmeetable under the
  service-rate estimate (and admits everything while cold);
- the admission queue delivers in EDF order when frames carry jittered
  deadlines, and sheds already-late frames first under overflow — with
  every shed frame's admission stamp revoked so the admitted population
  nets out (the PR's saturation-pacing fix);
- budget unset is a kill switch: no scheduler object exists and the
  pipeline's output is byte-identical to the pre-scheduler FIFO path;
  budget set but unloaded must also be byte-identical (uniform budget
  ⇒ monotone deadlines ⇒ EDF pop order == FIFO);
- the serving engine's request path raises SloRejected instead of
  queueing doomed requests.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.pipeline.element import Element, EosEvent, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import Pipeline, Queue, SourceElement
from nnstreamer_tpu.serving.scheduler import (
    FeedbackController,
    ServiceRateEstimator,
    SloRejected,
    SloScheduler,
)
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def _buf(i: int, deadline_t=None) -> TensorBuffer:
    buf = TensorBuffer([np.array([float(i)], np.float32)], pts=i * 1000)
    if deadline_t is not None:
        buf.meta["deadline_t"] = deadline_t
    return buf


class _NumSrc(SourceElement):
    ELEMENT_NAME = "_sched_numsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 5}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig

        cfg = TensorsConfig.from_arrays([np.zeros((1,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        buf = _buf(self.i)
        self.i += 1
        return buf


class _Gate(Element):
    """Blocks the queue worker inside chain() until released — lets a
    test park the drain loop while it stacks frames into the EDF heap."""

    ELEMENT_NAME = "_sched_gate"
    PROPERTIES = {}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self.entered = threading.Event()
        self.release = threading.Event()

    def chain(self, pad, buf):
        self.entered.set()
        assert self.release.wait(timeout=10)
        return self.srcpads[0].push(buf)


class _Collect(Element):
    ELEMENT_NAME = "_sched_collect"
    PROPERTIES = {}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.buffers = []
        self.got_eos = False

    def chain(self, pad, buf):
        self.buffers.append(buf)
        return FlowReturn.OK

    def sink_event(self, pad, event):
        if isinstance(event, EosEvent):
            self.got_eos = True


# -- estimator / controller / admission units ---------------------------------


class TestServiceRateEstimator:
    def test_cold_admits_all(self):
        est = ServiceRateEstimator()
        assert est.service_time_s() == 0.0
        assert est.service_fps() == 0.0

    def test_slower_witness_governs(self):
        est = ServiceRateEstimator()
        est.observe_invoke(0.010)          # invoke says 10 ms/frame
        est.observe_completion(100.0)
        est.observe_completion(100.05)     # drain says 50 ms/frame
        assert est.service_time_s() == pytest.approx(0.05)

    def test_stall_gap_excluded(self):
        est = ServiceRateEstimator()
        est.observe_completion(10.0)
        est.observe_completion(20.0)       # 10 s gap: warmup artifact
        assert est.service_time_s() == 0.0
        est.observe_completion(20.02)      # but the clock did advance
        assert est.service_time_s() == pytest.approx(0.02)


class TestAdmission:
    def test_rejects_unmeetable_deadline(self):
        sched = SloScheduler(budget_ms=50)
        sched.observe_service(0.1)         # 100 ms/frame
        ok, _dl, slack = sched.decide(now=10.0, backlog=0)
        assert not ok and slack < 0
        # backlog makes it worse, not better
        ok, _dl, slack5 = sched.decide(now=10.0, backlog=5)
        assert not ok and slack5 < slack

    def test_admits_with_headroom_and_stamps(self):
        sched = SloScheduler(budget_ms=500)
        sched.observe_service(0.01)
        buf = _buf(0)
        assert sched.admit(buf, now=10.0, backlog=3)
        assert buf.meta["admitted_t"] == 10.0
        assert buf.meta["deadline_t"] == pytest.approx(10.5)

    def test_request_path_raises_slo_rejected(self):
        sched = SloScheduler(budget_ms=50)
        sched.observe_service(0.1)
        with pytest.raises(SloRejected) as ei:
            sched.admit_request(now=10.0, backlog=2)
        assert ei.value.slack_s < 0

    def test_note_shed_revokes_stamp_and_counts_reason(self):
        sched = SloScheduler(budget_ms=1000, name="shed-unit")
        late = _buf(0)
        ontime = _buf(1)
        assert sched.admit(late, now=10.0, backlog=0)
        assert sched.admit(ontime, now=10.0, backlog=0)
        sched.note_shed(late, now=12.0)    # deadline 11.0 < now: late
        sched.note_shed(ontime, now=10.5)  # still had slack: capacity
        assert "admitted_t" not in late.meta
        assert "deadline_t" not in late.meta
        snap = sched.snapshot()
        assert snap["shed_late"] == 1
        assert snap["shed_capacity"] == 1


class TestFeedbackController:
    def test_aimd_steps_and_power_of_two_cap(self):
        # window=16 so the recovery phase fully replaces the overload
        # samples the p99 reads
        ctl = FeedbackController(budget_s=0.05, batch_cap=8, inflight=2,
                                 window=16)
        for _ in range(16):                # p99 far past 2x budget
            ctl.record_completion(0.5)
        assert ctl.maybe_step(now=1.0)
        assert ctl.batch_cap == 4 and ctl.inflight == 1
        for _ in range(16):                # healthy again
            ctl.record_completion(0.01)
        assert ctl.maybe_step(now=2.0)
        assert ctl.batch_cap == 8 and ctl.inflight == 2
        # every value the controller visits stays a power of two
        assert ctl.batch_cap & (ctl.batch_cap - 1) == 0

    def test_dead_band_holds(self):
        ctl = FeedbackController(budget_s=0.05, batch_cap=8, inflight=2)
        for _ in range(64):                # between budget and 2x budget
            ctl.record_completion(0.07)
        assert not ctl.maybe_step(now=1.0)
        assert ctl.batch_cap == 8 and ctl.inflight == 2

    def test_interval_rate_limits_steps(self):
        ctl = FeedbackController(budget_s=0.05, interval_s=0.25)
        for _ in range(16):
            ctl.record_completion(0.5)
        assert ctl.maybe_step(now=1.0)
        for _ in range(16):
            ctl.record_completion(0.5)
        assert not ctl.maybe_step(now=1.1)  # inside the interval


# -- queue scheduler mode (EDF / shedding) ------------------------------------


def _sched_pipe(name, budget_ms=10_000.0, max_size=32):
    pipe = Pipeline(name=name, fuse=False, slo_budget_ms=budget_ms)
    q = Queue(name="q", stamp_admission=True, max_size_buffers=max_size)
    gate = _Gate(name="gate")
    col = _Collect(name="col")
    pipe.add_linked(q, gate, col)
    pipe.start()
    assert pipe._slo_scheduler is not None
    assert q._sched is pipe._slo_scheduler
    return pipe, q, gate, col


class TestEdfQueue:
    def test_edf_order_under_deadline_jitter(self):
        pipe, q, gate, col = _sched_pipe("edf-jitter")
        try:
            now = time.monotonic()
            # plug: parks the worker inside the gate with frame 0
            q.chain(None, _buf(0, deadline_t=now + 9.0))
            assert gate.entered.wait(timeout=5)
            # jittered deadlines, arrival order != deadline order
            q.chain(None, _buf(1, deadline_t=now + 3.0))
            q.chain(None, _buf(2, deadline_t=now + 1.0))
            q.chain(None, _buf(3, deadline_t=now + 2.0))
            gate.release.set()
            q.sink_event(None, EosEvent())  # blocks until drained
            assert [b.pts for b in col.buffers] == [0, 2000, 3000, 1000]
            assert col.got_eos
        finally:
            gate.release.set()
            pipe.stop()

    def test_shed_late_first_then_least_urgent(self):
        from nnstreamer_tpu.obs import get_registry

        pipe, q, gate, col = _sched_pipe("edf-shed", max_size=2)
        try:
            def revoked():
                c = get_registry().get("nns_queue_admitted_revoked_total",
                                       pipeline="edf-shed", element="q")
                return float(c.value) if c is not None else 0.0

            r0 = revoked()
            now = time.monotonic()
            q.chain(None, _buf(0, deadline_t=now + 9.0))  # plug
            assert gate.entered.wait(timeout=5)
            q.chain(None, _buf(1, deadline_t=now + 0.05))
            q.chain(None, _buf(2, deadline_t=now + 5.0))
            time.sleep(0.12)  # frame 1's deadline passes IN the heap
            # overflow: the late frame sheds first, on-time ones survive
            q.chain(None, _buf(3, deadline_t=time.monotonic() + 6.0))
            snap = pipe._slo_scheduler.snapshot()
            assert snap["shed_late"] == 1
            # overflow with nothing late: least-urgent (latest deadline)
            q.chain(None, _buf(4, deadline_t=time.monotonic() + 7.0))
            snap = pipe._slo_scheduler.snapshot()
            assert snap["shed_capacity"] == 1
            # every shed revoked its admission stamp (population nets out)
            assert revoked() - r0 == 2
            gate.release.set()
            q.sink_event(None, EosEvent())
            # survivors in EDF order: plug, then 2 then 3 (4 was shed)
            assert [b.pts for b in col.buffers] == [0, 2000, 3000]
            for b in col.buffers:
                assert "admitted_t" in b.meta
        finally:
            gate.release.set()
            pipe.stop()

    def test_cold_queue_rejects_once_estimator_says_unmeetable(self):
        pipe, q, gate, col = _sched_pipe("edf-reject", budget_ms=50)
        try:
            pipe._slo_scheduler.observe_service(0.1)  # 100 ms/frame
            gate.release.set()
            q.chain(None, _buf(0))  # no override: budget deadline
            q.sink_event(None, EosEvent())
            assert col.buffers == []
            assert pipe._slo_scheduler.snapshot()["rejected"] == 1
        finally:
            gate.release.set()
            pipe.stop()


# -- kill switch / byte-identical ---------------------------------------------


def _run_numeric(budget_ms, n=6):
    pipe = Pipeline(name=f"ident-{int(budget_ms)}", fuse=False,
                    slo_budget_ms=budget_ms)
    src = _NumSrc(num_buffers=n)
    q = Queue(name="q", stamp_admission=True, max_size_buffers=16)
    col = _Collect(name="col")
    pipe.add_linked(src, q, col)
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos", msg
    vals = [np.asarray(b.tensors[0]).tobytes() for b in col.buffers]
    return pipe, vals


class TestKillSwitch:
    def test_budget_unset_builds_no_scheduler(self):
        pipe, vals = _run_numeric(0.0)
        assert pipe._slo_scheduler is None
        assert pipe.get("q")._sched is None
        assert len(vals) == 6

    def test_unloaded_output_byte_identical_to_fifo(self):
        pipe0, base = _run_numeric(0.0)
        pipe1, sched = _run_numeric(60_000.0)
        assert pipe1._slo_scheduler is not None
        assert sched == base
        snap = pipe1._slo_scheduler.snapshot()
        assert snap["admitted"] == 6
        assert snap["rejected"] == 0
        assert snap["shed_late"] == snap["shed_capacity"] == 0

    def test_sched_series_exported(self):
        from nnstreamer_tpu.obs import get_registry

        _pipe, _vals = _run_numeric(60_000.0)
        body = get_registry().render_prometheus()
        for series in ("nns_sched_admitted_total",
                       "nns_sched_batch_cap",
                       "nns_sched_inflight_target",
                       "nns_sched_service_time_ms",
                       "nns_sched_lanes_hint",
                       "nns_queue_admitted_total"):
            assert series in body, f"{series} missing from registry"


class TestAdmissionStampsSurviveAggregation:
    """The bench's admitted-population accounting (admitted_fps /
    latency_sat) reads admission stamps AT THE SINK — with a
    tensor_aggregator between the stamping queue and the sink, the
    stamps must ride the window (meta["admitted_ts"], one per
    constituent frame, lockstep with create_ts)."""

    def test_admitted_population_counted_through_aggregator(self):
        from nnstreamer_tpu import parse_launch

        pipe = parse_launch(
            "videotestsrc num-buffers=16 width=8 height=8 ! "
            "tensor_converter ! "
            "queue name=q max-size-buffers=32 stamp-admission=true ! "
            "tensor_aggregator frames-in=1 frames-out=4 frames-flush=4 "
            "frames-dim=3 concat=true ! "
            "tensor_sink name=sink to-host=true")
        msg = pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos"
        sink = pipe.get("sink")
        # every constituent frame of every window is one admitted sample
        assert sink.admitted_latencies.count == 16
        assert sink.latency_percentiles(99.0, base="admitted") is not None


# -- serving engine request path ----------------------------------------------


class TestEngineAdmission:
    def test_submit_raises_when_unmeetable(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import (
            TransformerConfig,
            init_params,
        )
        from nnstreamer_tpu.serving.engine import ContinuousBatchingEngine

        cfg = TransformerConfig(vocab=97, d_model=64, n_heads=4,
                                n_layers=2, d_ff=128, max_seq=64,
                                dtype=jnp.float32)
        params = init_params(cfg, seed=3)
        eng = ContinuousBatchingEngine(cfg, params, max_streams=2,
                                       steps_per_dispatch=4,
                                       temperature=0.0,
                                       slo_budget_ms=50).start()
        try:
            assert eng._slo is not None
            # the estimate says 1 s/request against a 50 ms budget
            eng._slo.estimator.observe_invoke(1.0)
            with pytest.raises(SloRejected):
                eng.submit([1, 2, 3], max_new_tokens=4)
        finally:
            eng.stop()

    def test_no_budget_no_scheduler(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import (
            TransformerConfig,
            init_params,
        )
        from nnstreamer_tpu.serving.engine import ContinuousBatchingEngine

        cfg = TransformerConfig(vocab=97, d_model=64, n_heads=4,
                                n_layers=2, d_ff=128, max_seq=64,
                                dtype=jnp.float32)
        params = init_params(cfg, seed=3)
        eng = ContinuousBatchingEngine(cfg, params, max_streams=2,
                                       steps_per_dispatch=4)
        assert eng._slo is None
