"""Pub/sub broker + elements + discovery tests (reference: unittest_mqtt
with the GstMqttTestHelper broker fake, tests/gstreamer_mqtt/; here the
broker itself ships in-tree so tests run the real thing on loopback)."""

import time

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.query.discovery import ServerAdvertiser, ServerDiscovery
from nnstreamer_tpu.query.pubsub import Broker, Client


@pytest.fixture
def broker():
    b = Broker(port=0).start()
    yield b
    b.stop()


class TestBroker:
    def test_pub_sub_roundtrip(self, broker):
        got = []
        sub = Client("127.0.0.1", broker.port)
        sub.subscribe("a/b", lambda t, p: got.append((t, p)))
        time.sleep(0.1)
        pub = Client("127.0.0.1", broker.port)
        pub.publish("a/b", b"hello")
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [("a/b", b"hello")]
        sub.close()
        pub.close()

    def test_retained_delivered_to_late_subscriber(self, broker):
        pub = Client("127.0.0.1", broker.port)
        pub.publish("cfg/x", b"v1", retain=True)
        time.sleep(0.1)
        got = []
        sub = Client("127.0.0.1", broker.port)
        sub.subscribe("cfg/#", lambda t, p: got.append((t, p)))
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [("cfg/x", b"v1")]
        sub.close()
        pub.close()

    def test_wildcard(self, broker):
        got = []
        sub = Client("127.0.0.1", broker.port)
        sub.subscribe("ns/#", lambda t, p: got.append(t))
        time.sleep(0.1)
        pub = Client("127.0.0.1", broker.port)
        pub.publish("ns/one", b"1")
        pub.publish("other/two", b"2")
        pub.publish("ns/three", b"3")
        deadline = time.time() + 5
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert got == ["ns/one", "ns/three"]
        sub.close()
        pub.close()


class TestPubSubElements:
    def test_stream_over_broker(self, broker):
        recv = parse_launch(
            f"tensor_pubsub_src host=127.0.0.1 port={broker.port} "
            "sub-topic=t/video num-buffers=3 ! tensor_sink name=out"
        )
        recv.start()
        time.sleep(0.2)  # let the subscription land
        send = parse_launch(
            "videotestsrc num-buffers=3 width=8 height=8 ! tensor_converter ! "
            f"tensor_pubsub_sink host=127.0.0.1 port={broker.port} "
            "pub-topic=t/video"
        )
        send.run(timeout=20)
        msg = recv.wait(timeout=20)
        recv.stop()
        assert msg is not None and msg.kind == "eos"
        outs = recv.get("out").buffers
        assert len(outs) == 3
        assert outs[0][0].shape == (1, 8, 8, 3)
        assert outs[0].pts is not None  # rebased timestamps

    def test_mqtt_alias_names(self):
        from nnstreamer_tpu.registry import ELEMENT, get_subplugin

        assert get_subplugin(ELEMENT, "mqttsink") is not None
        assert get_subplugin(ELEMENT, "mqttsrc") is not None


class TestDiscovery:
    def test_advertise_and_discover(self, broker):
        adv = ServerAdvertiser("127.0.0.1", broker.port, "detect",
                               "10.0.0.5", 4242)
        adv.publish()
        time.sleep(0.1)
        disco = ServerDiscovery("127.0.0.1", broker.port, "detect")
        servers = disco.wait_servers(timeout=5)
        assert ("10.0.0.5", 4242) in servers
        disco.close()
        adv.retract()

    def test_advertise_and_discover_over_real_mqtt(self):
        """Discovery through a real MQTT 3.1.1 broker: broker_host
        spelled mqtt://h:p routes the advertiser/discovery through
        MqttClient (reference tensor_query_hybrid publishes via paho to
        exactly such a broker)."""
        from nnstreamer_tpu.query.mqtt import MqttBroker

        b = MqttBroker(port=0)
        try:
            adv = ServerAdvertiser("mqtt://127.0.0.1", b.port, "seg",
                                   "10.0.0.9", 7777)
            adv.publish()
            time.sleep(0.1)
            # late subscriber: the RETAINED endpoint must reach it
            disco = ServerDiscovery("mqtt://127.0.0.1", b.port, "seg")
            servers = disco.wait_servers(timeout=5)
            assert ("10.0.0.9", 7777) in servers
            # tombstone retracts the endpoint for new subscribers
            adv.retract()
            time.sleep(0.1)
            disco2 = ServerDiscovery("mqtt://127.0.0.1", b.port, "seg")
            assert disco2.wait_servers(timeout=0.5) == []
            disco.close()
            disco2.close()
        finally:
            b.close()

    def test_mqtt_discovery_failover_to_live_server(self):
        """Server dies (endpoint retracted / unreachable) → the client
        walks the discovered list to the live candidate, all through the
        real MQTT broker (VERDICT r4 #3 done-criterion)."""
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.query.mqtt import MqttBroker
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("4", "float32")
        register_custom_easy("mq5", lambda ins: [np.asarray(ins[0]) * 5],
                             info, info)
        b = MqttBroker(port=0)
        server = None
        ghost = None
        try:
            # candidate 1: advertised but DEAD (listener closed right
            # away — connect must fail and the client must advance)
            import socket as _s

            probe = _s.socket(_s.AF_INET, _s.SOCK_STREAM)
            probe.bind(("127.0.0.1", 0))
            dead_port = probe.getsockname()[1]
            probe.close()
            ghost = ServerAdvertiser("mqtt://127.0.0.1", b.port, "five",
                                     "127.0.0.1", dead_port)
            ghost.publish()
            # candidate 2: live server pipeline advertising over MQTT
            server = parse_launch(
                "tensor_query_serversrc name=s port=0 operation=five "
                f"broker-host=mqtt://127.0.0.1 broker-port={b.port} ! "
                "tensor_filter framework=custom-easy model=mq5 ! "
                "tensor_query_serversink")
            server.start()
            time.sleep(0.3)
            from nnstreamer_tpu.elements.sink import TensorSink
            from nnstreamer_tpu.elements.source import AppSrc

            client = parse_launch(
                "tensor_query_client name=c operation=five "
                f"broker-host=mqtt://127.0.0.1 broker-port={b.port} "
                "timeout=5 max-retry=2")
            src, sink = AppSrc(name="src"), TensorSink(name="out")
            client.add(src, sink)
            src.link(client.get("c"))
            client.get("c").link(sink)
            client.start()
            src.push([np.arange(4, dtype=np.float32)], pts=0)
            src.end_of_stream()
            msg = client.wait(timeout=30)
            client.stop()
            assert msg is not None and msg.kind == "eos", str(msg)
            np.testing.assert_array_equal(
                sink.buffers[0][0], np.arange(4, dtype=np.float32) * 5)
        finally:
            if ghost is not None:
                ghost.retract()  # also closes its MqttClient
            if server is not None:
                server.stop()
            b.close()

    def test_query_client_discovers_live_server(self, broker):
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("4", "float32")
        register_custom_easy("p4", lambda ins: [np.asarray(ins[0]) * 3],
                             info, info)
        server = parse_launch(
            f"tensor_query_serversrc name=s port=0 operation=triple "
            f"broker-host=127.0.0.1 broker-port={broker.port} ! "
            "tensor_filter framework=custom-easy model=p4 ! "
            "tensor_query_serversink"
        )
        server.start()
        time.sleep(0.2)
        try:
            from nnstreamer_tpu.elements.sink import TensorSink
            from nnstreamer_tpu.elements.source import AppSrc

            client = parse_launch(
                "tensor_query_client name=c operation=triple "
                f"broker-host=127.0.0.1 broker-port={broker.port} timeout=5"
            )
            src, sink = AppSrc(name="src"), TensorSink(name="out")
            client.add(src, sink)
            src.link(client.get("c"))
            client.get("c").link(sink)
            client.start()
            src.push([np.arange(4, dtype=np.float32)], pts=0)
            src.end_of_stream()
            msg = client.wait(timeout=20)
            client.stop()
            assert msg is not None and msg.kind == "eos", str(msg)
            np.testing.assert_array_equal(
                sink.buffers[0][0], np.arange(4, dtype=np.float32) * 3
            )
        finally:
            server.stop()
