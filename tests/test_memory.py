"""HBM budget accounting, weight residency, and the memory-pressure
ladder (tensors/memory.py, pipeline/supervise.py, serving/scheduler.py).

The contract under test, per docs/profiling.md ("HBM budget") and
docs/robustness.md ("Memory-pressure ladder"):

- ``NNSTPU_HBM_BUDGET`` unset means ``memory.ACTIVE is None`` and every
  hook is a single module-attribute read — the pipeline is
  byte-identical to a build without the accountant;
- every pool slab, H2D frame upload, and backend weight load registers
  its bytes against the budget; the high-water mark is the pipeline's
  true HBM footprint;
- under a budget smaller than the summed weights, two models
  time-share HBM through the residency manager (LRU evict to host,
  prefetch-on-route back) and the output stays byte-identical;
- an injected ``kind=oom`` fault under ``error-policy=degrade`` climbs
  the pressure ladder (evict -> pool -> shed -> cpu) and recovers with
  zero frame loss, without reaching the cpu rung;
- frames shed by the scheduler (or revoked at admission) release their
  device payload and pool pins immediately, not at GC;
- repeated degrade cycles in one run reopen the backend exactly once
  per fault and leave no dispatch window entries behind.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.jax_backend import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.pipeline import faults
from nnstreamer_tpu.pipeline.dispatch import (
    H2D_EXCLUSIVE_META,
    POOL_STASH_META,
    release_shed_payload,
)
from nnstreamer_tpu.serving.scheduler import SloScheduler
from nnstreamer_tpu.tensors import memory
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.pool import BufferPool, get_pool

# -- helpers ------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.deactivate()
    memory.deactivate()
    yield
    faults.deactivate()
    memory.deactivate()


def _cval(name, **labels):
    m = get_registry().get(name, **labels)
    return 0.0 if m is None else m.value


def _register_ballast_model(name, scale, shape=(128, 128)):
    """A jax model carrying ``shape`` float32 ballast params (64 KiB at
    the default) whose output depends on the params — an eviction that
    lost or corrupted the weights would show up in the bytes."""
    ballast = jnp.ones(shape, jnp.float32) * scale
    register_jax_model(
        name, lambda p, x: (x.astype(jnp.float32) * p["w"][0, 0],),
        {"w": ballast})
    return int(np.prod(shape)) * 4


def _run_video_pipe(desc, policy="halt", timeout=120):
    pipe = parse_launch(desc, error_policy=policy)
    outs = []
    pipe.get("out").connect(
        lambda b: outs.append(np.asarray(b.tensors[0]).copy()))
    msg = pipe.run(timeout=timeout)
    assert msg is not None and msg.kind == "eos", msg
    return pipe, outs


def _assert_streams_equal(base, outs):
    assert len(base) == len(outs), (len(base), len(outs))
    for i, (a, b) in enumerate(zip(base, outs)):
        assert a.dtype == b.dtype and np.array_equal(a, b), \
            f"frame {i} diverged"


# -- parse_bytes --------------------------------------------------------------


class TestParseBytes:
    @pytest.mark.parametrize("text,expect", [
        ("512", 512),
        ("512b", 512),
        ("4k", 4 << 10),
        ("16K", 16 << 10),
        ("6m", 6 << 20),
        ("2g", 2 << 30),
        (" 8M ", 8 << 20),
    ])
    def test_suffixes(self, text, expect):
        assert memory.parse_bytes(text) == expect

    @pytest.mark.parametrize("text", ["", "cat", "12q", "-4k", "0"])
    def test_rejects(self, text):
        with pytest.raises(ValueError):
            memory.parse_bytes(text)


# -- the accountant -----------------------------------------------------------


class TestBudgetAccounting:
    def test_register_unregister_and_high_water(self):
        acct = memory.activate(1000)
        acct.register(400, "pool", reclaim=False)
        acct.register(300, "frames", reclaim=False)
        assert acct.used_bytes() == 700
        assert acct.headroom() == 300
        assert not acct.breached()
        acct.register(500, "weights", reclaim=False)
        assert acct.breached()
        assert acct.overage() == 200
        assert acct.high_water == 1200
        acct.unregister(300, "frames")
        acct.unregister(500, "weights")
        assert acct.used_bytes() == 400
        # high water never retreats
        assert acct.high_water == 1200
        snap = acct.snapshot()
        assert snap["budget_bytes"] == 1000
        assert snap["used_bytes"] == 400
        assert snap["used_by_category"] == {"pool": 400}
        assert snap["high_water_bytes"] == 1200

    def test_underflow_warns_but_never_goes_negative(self):
        acct = memory.activate(1000)
        acct.register(100, "pool", reclaim=False)
        acct.unregister(250, "pool")  # over-release: clamp, don't raise
        assert acct.used_bytes() == 0
        assert "pool" not in acct.snapshot()["used_by_category"]

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.delenv("NNSTPU_HBM_BUDGET", raising=False)
        assert memory.maybe_activate_env() is None
        assert memory.ACTIVE is None
        monkeypatch.setenv("NNSTPU_HBM_BUDGET", "64k")
        acct = memory.maybe_activate_env()
        assert acct is memory.ACTIVE and acct.limit == 64 << 10
        # an explicitly installed accountant wins over the env
        explicit = memory.activate(123)
        monkeypatch.setenv("NNSTPU_HBM_BUDGET", "1g")
        assert memory.maybe_activate_env() is explicit
        assert memory.ACTIVE.limit == 123

    def test_pool_slabs_register_and_release(self):
        acct = memory.activate(1 << 20)
        pool = BufferPool(name="membudget-test")
        a = pool.acquire((1024,), np.uint8)
        held = acct.snapshot()["used_by_category"].get("pool", 0)
        assert held >= 1024
        pool.release(a)
        # a free-listed slab is still device-addressable memory: it
        # stays registered until the pool actually drops it
        assert acct.snapshot()["used_by_category"].get("pool", 0) == held
        del a
        pool.clear()
        assert acct.snapshot()["used_by_category"].get("pool", 0) == 0

    def test_h2d_bytes_track_the_wrapper_lifetime(self):
        acct = memory.activate(1 << 20)

        class Owner:
            pass

        o = Owner()
        acct.note_h2d(4096, owner=o)
        assert acct.snapshot()["used_by_category"].get("frames", 0) == 4096
        del o
        import gc

        gc.collect()
        assert acct.snapshot()["used_by_category"].get("frames", 0) == 0


# -- residency ----------------------------------------------------------------


class TestResidencyManager:
    @staticmethod
    def _loader(host):
        # stand-in for jax.device_put: a distinct object wrapping host
        return [np.asarray(h).copy() for h in host]

    def test_lru_evicts_coldest_and_prefetches_back(self):
        acct = memory.activate(10_000)
        res = acct.residency
        a = res.register("a", [np.arange(8)], 4000, self._loader)
        b = res.register("b", [np.arange(8) * 2], 4000, self._loader)
        c = res.register("c", [np.arange(8) * 3], 4000, self._loader)
        # register does not load
        assert res.resident_count() == 0
        va, vb = a.value(), b.value()
        assert a.resident and b.resident and res.resident_count() == 2
        assert np.array_equal(va[0], np.arange(8))
        # loading c must evict the coldest (a), not b
        c.value()
        assert not a.resident and b.resident and c.resident
        assert acct.used_bytes() == 8000
        # a LRU touch protects b: reload a -> b is now coldest, evicted
        va2 = a.value()
        assert a.resident and not b.resident and c.resident
        assert np.array_equal(va2[0], np.arange(8)), \
            "reloaded weights diverged from host staging"
        snap = acct.snapshot()
        assert snap["evictions"] == 2
        assert snap["prefetches"] == 1  # a's second load; c's first isn't
        assert a.loads == 2 and a.evictions == 1

    def test_unregister_frees_budget(self):
        acct = memory.activate(10_000)
        res = acct.residency
        u = res.register("u", [np.zeros(4)], 4000, self._loader)
        u.value()
        assert acct.used_bytes() == 4000
        res.unregister("u")
        assert acct.used_bytes() == 0
        assert res.resident_count() == 0

    def test_breach_reclaims_cold_units_inline(self):
        acct = memory.activate(8000)
        res = acct.residency
        u = res.register("u", [np.zeros(4)], 4000, self._loader)
        u.value()
        # a non-weight registration that breaches the budget evicts the
        # cold unit inline (pressure rung 1, no supervisor involved)
        acct.register(6000, "frames")
        assert not u.resident
        assert acct.snapshot()["pressure_events"] >= 1
        acct.unregister(6000, "frames")


# -- oom fault kind -----------------------------------------------------------


class TestInjectedOom:
    @pytest.mark.parametrize("site", [
        "pool.alloc", "transfer.h2d", "filter.open", "filter.invoke"])
    def test_oom_raises_at_every_contract_site(self, site):
        fi = faults.activate(f"{site}:nth=1,kind=oom", seed=3)
        with pytest.raises(faults.InjectedOom) as ei:
            fi.check(site)
        assert ei.value.kind == "oom"
        assert site in str(ei.value)
        faults.deactivate()

    def test_oom_is_classified_as_memory_pressure(self):
        from nnstreamer_tpu.pipeline.supervise import _is_memory_pressure

        assert _is_memory_pressure(faults.InjectedOom("pool.alloc", 1))
        assert _is_memory_pressure(RuntimeError("RESOURCE_EXHAUSTED: ..."))
        assert _is_memory_pressure(
            RuntimeError("jaxlib: ran out of memory allocating 1g"))
        assert not _is_memory_pressure(RuntimeError("shape mismatch"))

    def test_pool_alloc_site_fires_on_slab_miss(self):
        fi = faults.activate("pool.alloc:nth=1,kind=oom", seed=3)
        pool = BufferPool(name="oomsite-test")
        with pytest.raises(faults.InjectedOom):
            pool.acquire((64,), np.uint8)
        # the nth=1 rule is spent; a retry allocates fine (and a free-
        # list hit never re-enters the allocator site at all)
        a = pool.acquire((64,), np.uint8)
        pool.release(a)
        b = pool.acquire((64,), np.uint8)
        assert fi.injected("pool.alloc") == 1
        pool.release(b)
        pool.clear()
        faults.deactivate()


# -- shed/revoked frames free their payload now (satellite) -------------------


class TestShedReleasesPayload:
    def test_pool_stash_returns_to_pool(self):
        pool = get_pool()
        arr = pool.acquire((256,), np.uint8)
        assert id(arr) in pool._out
        buf = TensorBuffer([np.zeros(4, np.float32)])
        buf.meta[POOL_STASH_META] = [arr]
        release_shed_payload(buf)
        assert POOL_STASH_META not in buf.meta
        assert id(arr) not in pool._out
        del arr
        pool.clear()

    def test_exclusive_device_payload_is_dropped(self):
        dev = jnp.ones((4,), jnp.float32)
        buf = TensorBuffer([dev])
        buf.meta[H2D_EXCLUSIVE_META] = True
        release_shed_payload(buf)
        assert len(buf.tensors) == 0
        assert H2D_EXCLUSIVE_META not in buf.meta

    def test_shared_payload_is_left_alone(self):
        host = np.ones(4, np.float32)
        buf = TensorBuffer([host])  # no exclusivity claim, host tensor
        release_shed_payload(buf)
        assert len(buf.tensors) == 1

    def test_scheduler_shed_path_releases(self):
        sched = SloScheduler(budget_ms=100.0, name="memshed-test")
        dev = jnp.ones((4,), jnp.float32)
        buf = TensorBuffer([dev])
        buf.meta.update({"admitted_t": 0.0, "deadline_t": 0.0,
                         H2D_EXCLUSIVE_META: True})
        sched.note_shed(buf, now=1.0)
        assert "admitted_t" not in buf.meta
        assert len(buf.tensors) == 0


# -- scheduler memory term ----------------------------------------------------


class TestSchedulerMemoryTerm:
    def test_admission_backlog_from_overage(self):
        acct = memory.activate(1000)
        assert acct.admission_backlog() == 0
        acct.register(1500, "weights", reclaim=False)
        # overage with a cold frame-size estimate: minimum one frame
        assert acct.admission_backlog() == 1
        acct._frame_bytes_ewma = 100.0
        assert acct.admission_backlog() == 5  # 500 over / 100 per frame

    def test_decide_sheds_under_pressure_and_self_heals(self):
        sched = SloScheduler(budget_ms=50.0, name="memterm-test")
        sched.observe_service(0.010)  # 10ms per frame, 50ms budget
        admit, _, _ = sched.decide(now=0.0, backlog=0)
        assert admit
        acct = memory.activate(1000)
        acct.register(2000, "weights", reclaim=False)
        acct._frame_bytes_ewma = 100.0  # 10 phantom frames of overage
        admit, _, slack = sched.decide(now=0.0, backlog=0)
        assert not admit and slack < 0
        # releasing the overage heals admission with no further action
        acct.unregister(2000, "weights")
        admit, _, _ = sched.decide(now=0.0, backlog=0)
        assert admit

    def test_pressure_hold_decays_per_decision(self):
        sched = SloScheduler(budget_ms=50.0, name="memhold-test")
        # 30ms/frame against a 50ms budget: one frame fits, any synthetic
        # backlog does not
        sched.observe_service(0.030)
        sched.note_memory_pressure(frames=2)
        assert sched.snapshot()["memory_hold"] == 2
        a1, _, _ = sched.decide(now=0.0, backlog=0)
        a2, _, _ = sched.decide(now=0.0, backlog=0)
        assert not a1 and not a2  # held down while the ladder reclaims
        a3, _, _ = sched.decide(now=0.0, backlog=0)
        assert a3  # hold consumed: admission self-heals
        assert sched.snapshot()["memory_hold"] == 0


# -- pipelines ----------------------------------------------------------------


N_FRAMES = 24


def _two_model_desc(n=N_FRAMES):
    return (f"videotestsrc pattern=ball num-buffers={n} "
            "width=8 height=8 ! tensor_converter ! "
            "queue name=q max-size-buffers=8 ! "
            "tensor_filter framework=jax model=mem_a name=fa ! "
            "tensor_filter framework=jax model=mem_b name=fb ! "
            "queue materialize-host=true ! tensor_sink name=out")


class TestPipelineUnderBudget:
    def test_two_models_time_share_hbm_byte_identically(self):
        wa = _register_ballast_model("mem_a", 2.0)
        wb = _register_ballast_model("mem_b", 3.0)
        try:
            _, base = _run_video_pipe(_two_model_desc())
            assert len(base) == N_FRAMES
            assert memory.ACTIVE is None  # baseline ran unbudgeted

            # budget < summed weights: the models cannot both stay
            # resident, yet the pipeline must serve byte-identically
            acct = memory.activate(wa + wb - (wb // 2))
            _, outs = _run_video_pipe(_two_model_desc())
            snap = acct.snapshot()
            assert snap["evictions"] > 0, \
                "models never time-shared HBM under the budget"
            assert snap["prefetches"] > 0
            assert snap["high_water_bytes"] < wa + wb
            _assert_streams_equal(base, outs)
        finally:
            unregister_jax_model("mem_a")
            unregister_jax_model("mem_b")

    def test_budget_unset_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("NNSTPU_HBM_BUDGET", raising=False)
        _register_ballast_model("mem_a", 2.0)
        _register_ballast_model("mem_b", 3.0)
        try:
            _, outs = _run_video_pipe(_two_model_desc())
            assert memory.ACTIVE is None
            assert len(outs) == N_FRAMES
        finally:
            unregister_jax_model("mem_a")
            unregister_jax_model("mem_b")


class TestOomPressureLadder:
    def _desc(self, n=N_FRAMES):
        return (f"videotestsrc pattern=ball num-buffers={n} "
                "width=8 height=8 ! tensor_converter ! "
                "queue name=q max-size-buffers=8 ! "
                "tensor_filter framework=jax model=mem_l name=f ! "
                "queue materialize-host=true ! tensor_sink name=out")

    def test_injected_oom_recovers_zero_loss(self):
        _register_ballast_model("mem_l", 2.5)
        labels = dict(pipeline="pipeline", element="f")
        try:
            _, base = _run_video_pipe(self._desc())

            memory.activate(1 << 20)
            fi = faults.activate("filter.invoke:nth=5,kind=oom", seed=7)
            rec0 = _cval("nns_fault_recovered_total", **labels)
            evict0 = _cval("nns_mem_pressure_events_total", rung="evict")
            cpu0 = _cval("nns_mem_pressure_events_total", rung="cpu")
            pipe, outs = _run_video_pipe(self._desc(), policy="degrade")
            assert fi.injected("filter.invoke") == 1
            _assert_streams_equal(base, outs)
            assert _cval("nns_fault_recovered_total", **labels) == rec0 + 1
            # the first rung (evict) absorbed it: cpu never reached
            assert _cval("nns_mem_pressure_events_total",
                         rung="evict") > evict0
            assert _cval("nns_mem_pressure_events_total",
                         rung="cpu") == cpu0
            assert pipe.get("f")._props.get("accelerator") != "cpu"
        finally:
            unregister_jax_model("mem_l")

    def test_oom_without_budget_still_recovers(self):
        # the ladder must not require the accountant: with no budget the
        # evict rung is a no-op and the pool/shed rungs do the work
        _register_ballast_model("mem_l", 2.5)
        labels = dict(pipeline="pipeline", element="f")
        try:
            _, base = _run_video_pipe(self._desc())
            fi = faults.activate("filter.invoke:nth=5,kind=oom", seed=7)
            rec0 = _cval("nns_fault_recovered_total", **labels)
            pipe, outs = _run_video_pipe(self._desc(), policy="degrade")
            assert fi.injected("filter.invoke") == 1
            _assert_streams_equal(base, outs)
            assert _cval("nns_fault_recovered_total", **labels) > rec0
            assert pipe.get("f")._props.get("accelerator") != "cpu"
        finally:
            unregister_jax_model("mem_l")


class TestRepeatedDegradeCycles:
    """Two faults in one run (satellite): each must reopen the backend
    exactly once, and neither may leak a dispatch window entry or leave
    the element on the cpu fallback."""

    N = 120

    def _desc(self):
        return (f"videotestsrc pattern=ball num-buffers={self.N} "
                "width=8 height=8 ! tensor_converter ! "
                "queue name=q max-size-buffers=8 ! "
                "tensor_filter framework=jax model=mem_r name=f ! "
                "queue materialize-host=true ! tensor_sink name=out")

    def test_two_faults_one_run_no_double_reopen_no_window_leak(self):
        _register_ballast_model("mem_r", 4.0)
        labels = dict(pipeline="pipeline", element="f")
        try:
            _, base = _run_video_pipe(self._desc())

            fi = faults.activate("filter.invoke:every=50,kind=raise",
                                 seed=5)
            opens0 = _cval("nns_tensor_filter_opens_total", **labels)
            deg0 = _cval("nns_fault_degraded_total", **labels)
            rec0 = _cval("nns_fault_recovered_total", **labels)
            pipe, outs = _run_video_pipe(self._desc(), policy="degrade")
            fired = fi.injected("filter.invoke")
            assert fired == 2, fired
            _assert_streams_equal(base, outs)

            el = pipe.get("f")
            opens = _cval("nns_tensor_filter_opens_total",
                          **labels) - opens0
            # initial open + one reload per fault — a double-reopen per
            # cycle would show up as 5
            assert opens == 3, opens
            assert _cval("nns_fault_degraded_total", **labels) == deg0 + 2
            assert _cval("nns_fault_recovered_total", **labels) == rec0 + 2
            assert el._props.get("accelerator") != "cpu"
            assert len(el._window) == 0, "leaked dispatch window entries"
        finally:
            unregister_jax_model("mem_r")
