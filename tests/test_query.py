"""Distributed tensor_query tests — server+client pipelines in one process
over 127.0.0.1 (the reference's loopback multi-node pattern,
tests/nnstreamer_query/unittest_query.cc:21-175)."""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.tensors.buffer import TensorBuffer


class TestProtocol:
    def test_buffer_roundtrip(self, rng):
        buf = TensorBuffer(
            [rng.standard_normal((2, 3)).astype(np.float32),
             np.arange(5, dtype=np.uint8)],
            pts=123, duration=456,
        )
        back = P.unpack_buffer(P.pack_buffer(buf))
        assert back.pts == 123 and back.duration == 456
        assert back.num_tensors == 2
        np.testing.assert_array_equal(back[0], buf[0])
        np.testing.assert_array_equal(back[1], buf[1])

    def test_unset_timestamps(self):
        back = P.unpack_buffer(P.pack_buffer(TensorBuffer([np.zeros(1)])))
        assert back.pts is None and back.dts is None


class TestQueryLoopback:
    def test_offload_roundtrip(self):
        """Server pipeline doubles values; client offloads and receives."""
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("3:8:8:1", "uint8")
        register_custom_easy(
            "double_u8",
            lambda ins: [(np.asarray(ins[0]) * 2).astype(np.uint8)],
            info, info,
        )
        server = parse_launch(
            "tensor_query_serversrc name=ssrc port=0 ! "
            "tensor_filter framework=custom-easy model=double_u8 ! "
            "tensor_query_serversink"
        )
        server.start()
        try:
            port = server.get("ssrc").port
            client = parse_launch(
                "videotestsrc num-buffers=4 width=8 height=8 pattern=gradient ! "
                "tensor_converter ! "
                f"tensor_query_client dest-host=127.0.0.1 dest-port={port} ! "
                "tensor_sink name=out"
            )
            msg = client.run(timeout=30)
            assert msg.kind == "eos"
            outs = client.get("out").buffers
            assert len(outs) == 4
            # verify the server actually transformed the data
            ref = parse_launch(
                "videotestsrc num-buffers=1 width=8 height=8 pattern=gradient ! "
                "tensor_converter ! tensor_sink name=out"
            )
            ref.run(timeout=15)
            expected = (np.asarray(ref.get("out").buffers[0][0]) * 2).astype(
                np.uint8
            )
            np.testing.assert_array_equal(outs[0][0], expected)
        finally:
            server.stop()

    def test_client_failover_to_live_server(self):
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("4", "float32")
        register_custom_easy("passf", lambda ins: [np.asarray(ins[0])],
                             info, info)
        server = parse_launch(
            "tensor_query_serversrc name=ssrc port=0 ! "
            "tensor_filter framework=custom-easy model=passf ! "
            "tensor_query_serversink"
        )
        server.start()
        try:
            port = server.get("ssrc").port
            # first server in the list is dead; client must fail over
            client = parse_launch(
                "tensor_query_client name=c "
                f"servers=127.0.0.1:1,127.0.0.1:{port} timeout=2"
            )
            from nnstreamer_tpu.elements.sink import TensorSink
            from nnstreamer_tpu.elements.source import AppSrc

            src, sink = AppSrc(name="src"), TensorSink(name="out")
            client.add(src, sink)
            src.link(client.get("c"))
            client.get("c").link(sink)
            client.start()
            src.push([np.arange(4, dtype=np.float32)], pts=0)
            src.end_of_stream()
            msg = client.wait(timeout=30)
            assert msg is not None and msg.kind == "eos", str(msg)
            assert len(sink.buffers) == 1
            np.testing.assert_array_equal(sink.buffers[0][0],
                                          np.arange(4, dtype=np.float32))
        finally:
            client.stop()
            server.stop()

    def test_client_all_servers_down(self):
        from nnstreamer_tpu.pipeline.element import FlowError
        from nnstreamer_tpu.elements.sink import TensorSink
        from nnstreamer_tpu.elements.source import AppSrc

        client = parse_launch(
            "tensor_query_client name=c servers=127.0.0.1:1 timeout=0.3 "
            "max-retry=1"
        )
        src, sink = AppSrc(name="src"), TensorSink(name="out")
        client.add(src, sink)
        src.link(client.get("c"))
        client.get("c").link(sink)
        client.start()
        src.push([np.zeros(2, np.float32)], pts=0)
        src.end_of_stream()
        msg = client.wait(timeout=30)
        client.stop()
        assert msg is not None and msg.kind == "error"
        assert "unreachable" in str(msg.error)


class TestFlexibleFilterNegotiation:
    def test_jax_filter_downstream_of_serversrc(self):
        """A shape-polymorphic jax model must negotiate from the first
        buffer when input caps are flexible (serversrc output) — the
        reference's flexible-tensor stream behavior."""
        import jax.numpy as jnp

        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model("flex_double",
                           lambda x: x.astype(jnp.float32) * 2.0)
        server = parse_launch(
            "tensor_query_serversrc name=ssrc port=0 ! "
            "tensor_filter framework=jax model=flex_double ! "
            "tensor_query_serversink")
        server.start()
        try:
            port = server.get("ssrc").port
            client = parse_launch(
                "videotestsrc num-buffers=3 width=8 height=8 "
                "pattern=gradient ! tensor_converter ! "
                f"tensor_query_client dest-host=127.0.0.1 dest-port={port} ! "
                "tensor_sink name=out")
            msg = client.run(timeout=60)
            assert msg is not None and msg.kind == "eos", msg
            outs = client.get("out").buffers
            assert len(outs) == 3
            ref = parse_launch(
                "videotestsrc num-buffers=1 width=8 height=8 "
                "pattern=gradient ! tensor_converter ! tensor_sink name=out")
            ref.run(timeout=30)
            expected = np.asarray(ref.get("out").buffers[0][0], np.float32) * 2
            np.testing.assert_allclose(np.asarray(outs[0][0]), expected)
        finally:
            server.stop()
            unregister_jax_model("flex_double")


class TestPipelinedOffload:
    def test_pipelined_matches_sync(self):
        """max-in-flight>1 must deliver the same results in the same order
        as the synchronous round trip."""
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.filters.custom import unregister_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("3:8:8:1", "uint8")
        register_custom_easy(
            "triple_u8",
            lambda ins: [(np.asarray(ins[0]) * 3).astype(np.uint8)],
            info, info,
        )
        server = parse_launch(
            "tensor_query_serversrc name=ssrc port=0 ! "
            "tensor_filter framework=custom-easy model=triple_u8 ! "
            "tensor_query_serversink")
        server.start()
        try:
            port = server.get("ssrc").port
            outs = {}
            for label, extra in (("sync", ""), ("pipe", "max-in-flight=6")):
                client = parse_launch(
                    "videotestsrc num-buffers=10 width=8 height=8 "
                    "pattern=gradient ! tensor_converter ! "
                    f"tensor_query_client dest-host=127.0.0.1 "
                    f"dest-port={port} {extra} ! tensor_sink name=out")
                msg = client.run(timeout=60)
                assert msg is not None and msg.kind == "eos", (label, msg)
                outs[label] = [np.asarray(b[0])
                               for b in client.get("out").buffers]
            assert len(outs["sync"]) == len(outs["pipe"]) == 10
            for a, b in zip(outs["sync"], outs["pipe"]):
                np.testing.assert_array_equal(a, b)
        finally:
            server.stop()
            unregister_custom_easy("triple_u8")

    def test_pipelined_dead_server_errors(self):
        """An unreachable server must surface an error in pipelined mode
        too, not silently drop the stream (code-review regression)."""
        from nnstreamer_tpu.pipeline.element import FlowError
        from nnstreamer_tpu.elements.sink import TensorSink
        from nnstreamer_tpu.elements.source import AppSrc

        client = parse_launch(
            "tensor_query_client name=c servers=127.0.0.1:1 timeout=0.3 "
            "max-retry=1 max-in-flight=4")
        src, sink = AppSrc(name="src"), TensorSink(name="out")
        client.add(src, sink)
        src.link(client.get("c"))
        client.get("c").link(sink)
        client.start()
        src.push([np.zeros(2, np.float32)], pts=0)
        src.end_of_stream()
        msg = client.wait(timeout=30)
        client.stop()
        assert msg is not None and msg.kind == "error"
        assert not sink.buffers

    def test_disconnect_drops_are_counted(self):
        """A mid-stream disconnect with max-in-flight>1 drops the in-flight
        window (streaming semantics) and the run can still end in a clean
        EOS — the client's frames-dropped counter must record the loss so
        callers don't need to scrape logs (ADVICE r1)."""
        import socket
        import threading

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def server():
            conn, _ = srv.accept()
            P.recv_msg(conn)                      # REQUEST_INFO
            P.send_msg(conn, P.Cmd.APPROVE, b"")
            P.send_msg(conn, P.Cmd.CLIENT_ID, b"1")
            for _ in range(3):                    # absorb the frames...
                P.recv_msg(conn)
            conn.close()                          # ...then die unanswered

        t = threading.Thread(target=server, daemon=True)
        t.start()
        try:
            pipe = parse_launch(
                "videotestsrc num-buffers=3 width=8 height=8 ! "
                "tensor_converter ! "
                f"tensor_query_client name=c dest-host=127.0.0.1 "
                f"dest-port={port} timeout=5 max-in-flight=4 ! "
                "tensor_sink name=out")
            pipe.start()
            msg = pipe.wait(timeout=60)
            client = pipe.get("c")
            dropped = int(client.get_property("frames_dropped"))
            pipe.stop()
            assert msg is not None and msg.kind == "eos", msg
            assert not pipe.get("out").buffers
            assert dropped == 3
        finally:
            srv.close()

    def test_stalling_server_surfaces_error_through_queue(self):
        """Server that handshakes then never answers: the receive timeout
        must surface as a pipeline error even with a queue (thread
        boundary) ahead of the pipelined client (code-review scenario)."""
        import socket
        import threading

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def server():
            conn, _ = srv.accept()
            P.recv_msg(conn)                      # REQUEST_INFO
            P.send_msg(conn, P.Cmd.APPROVE, b"")
            P.send_msg(conn, P.Cmd.CLIENT_ID, b"1")
            while True:                           # read frames, never reply
                try:
                    if P.recv_msg(conn) == (None, None):
                        break
                except Exception:
                    break

        t = threading.Thread(target=server, daemon=True)
        t.start()
        try:
            pipe = parse_launch(
                "videotestsrc num-buffers=4 width=8 height=8 ! "
                "tensor_converter ! queue max-size-buffers=2 ! "
                f"tensor_query_client dest-host=127.0.0.1 dest-port={port} "
                "timeout=1.5 max-in-flight=3 ! tensor_sink name=out")
            pipe.start()
            msg = pipe.wait(timeout=60)
            pipe.stop()
            assert msg is not None and msg.kind == "error", msg
            assert "timed out" in str(msg.error)
        finally:
            srv.close()


class TestDistributedSharded:
    def test_offload_into_sharded_filter(self):
        """SURVEY §2.4 TPU mapping end-to-end: frames arrive over the query
        protocol (the DCN ingress role) and the server's filter shards the
        batch over the full device mesh (the ICI role) — XLA inserts the
        collectives."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model,
            unregister_jax_model,
        )

        n_dev = len(jax.devices())
        assert n_dev >= 2  # conftest forces an 8-device CPU mesh

        register_jax_model(
            "sharded_scale",
            lambda p, x: x.astype(jnp.float32) * p, jnp.float32(2.0))
        server = parse_launch(
            "tensor_query_serversrc name=ssrc port=0 ! "
            "tensor_filter framework=jax model=sharded_scale "
            "custom=sharding:batch ! "
            "tensor_query_serversink")
        server.start()
        client = None
        try:
            port = server.get("ssrc").port
            client = parse_launch(
                "appsrc name=src ! "
                f"tensor_query_client dest-host=127.0.0.1 dest-port={port} "
                "max-in-flight=4 ! tensor_sink name=out")
            src, sink = client.get("src"), client.get("out")
            client.start()
            frames = [np.full((n_dev, 4), j, np.float32) for j in range(6)]
            for f in frames:
                src.push([f.copy()])
            src.end_of_stream()
            msg = client.wait(timeout=60)
            assert msg is not None and msg.kind == "eos", msg
            assert len(sink.buffers) == 6
            for j, b in enumerate(sink.buffers):
                np.testing.assert_allclose(
                    np.asarray(b[0]), np.full((n_dev, 4), j * 2.0))
        finally:
            if client is not None:
                client.stop()
            server.stop()
            unregister_jax_model("sharded_scale")


class TestServerTransports:
    """Same behavior from the native epoll core and the pure-Python
    fallback (native/nnstpu_server.cc vs query/server.py threads)."""

    @pytest.fixture(params=["native", "purepy"])
    def server(self, request, monkeypatch):
        from nnstreamer_tpu.query.server import QueryServer

        if request.param == "purepy":
            monkeypatch.setenv("NNSTPU_PURE_PY_SERVER", "1")
        srv = QueryServer(host="127.0.0.1", port=0,
                          caps_str="other/tensors").start()
        if request.param == "native" and not srv.native:
            srv.stop()
            pytest.skip("native library not built")
        assert srv.native == (request.param == "native")
        yield srv
        srv.stop()

    def _handshake(self, port):
        sock = P.connect("127.0.0.1", port, timeout=10)
        P.send_msg(sock, P.Cmd.REQUEST_INFO, b"caps")
        cmd, payload = P.recv_msg(sock)
        assert cmd is P.Cmd.APPROVE and payload == b"other/tensors"
        cmd, payload = P.recv_msg(sock)
        assert cmd is P.Cmd.CLIENT_ID
        return sock, int(payload.decode())

    def test_handshake_transfer_result(self, server, rng):
        sock, cid = self._handshake(server.port)
        buf = TensorBuffer([rng.standard_normal((3, 4)).astype(np.float32)],
                           pts=7)
        P.send_buffer(sock, buf)
        got = server.get_buffer(timeout=10)
        assert got is not None and got.meta["query_client_id"] == cid
        np.testing.assert_array_equal(got[0], buf[0])
        assert server.send_result(cid, got)
        cmd, payload = P.recv_msg(sock)
        assert cmd is P.Cmd.RESULT
        back = P.unpack_buffer(payload)
        np.testing.assert_array_equal(back[0], buf[0])
        sock.close()

    def test_ping_and_bye(self, server):
        sock, cid = self._handshake(server.port)
        P.send_msg(sock, P.Cmd.PING)
        assert P.recv_msg(sock)[0] is P.Cmd.PING
        P.send_msg(sock, P.Cmd.BYE)
        sock.close()
        # after BYE the client is gone: results are undeliverable
        import time
        deadline = time.monotonic() + 5
        while server.send_result(cid, TensorBuffer([np.zeros(1)])):
            assert time.monotonic() < deadline, "BYE never processed"
            time.sleep(0.02)

    def test_many_clients_routing(self, server):
        socks = {}
        for _ in range(8):
            sock, cid = self._handshake(server.port)
            socks[cid] = sock
        for cid, sock in socks.items():
            P.send_buffer(sock, TensorBuffer(
                [np.full((2,), cid, np.int32)], pts=cid))
        for _ in range(len(socks)):
            got = server.get_buffer(timeout=10)
            assert got is not None
            cid = got.meta["query_client_id"]
            assert int(got[0][0]) == cid  # payload matches its client
            assert server.send_result(cid, got)
        for cid, sock in socks.items():
            cmd, payload = P.recv_msg(sock)
            assert cmd is P.Cmd.RESULT
            assert int(P.unpack_buffer(payload)[0][0]) == cid
            sock.close()

    def test_large_frame_growth(self, server, rng):
        """Frames bigger than the take buffer's initial capacity (64 KiB)
        exercise the grow-and-retry path."""
        sock, cid = self._handshake(server.port)
        big = rng.standard_normal((512, 600)).astype(np.float32)  # ~1.2 MB
        P.send_buffer(sock, TensorBuffer([big]))
        got = server.get_buffer(timeout=10)
        assert got is not None and got.meta["query_client_id"] == cid
        np.testing.assert_array_equal(got[0], big)
        sock.close()

    def test_bad_frame_disconnects_client(self, server):
        """A TRANSFER payload that fails buffer unpack must disconnect the
        sender on both transports (not stall the consumer)."""
        sock, cid = self._handshake(server.port)
        P.send_msg(sock, P.Cmd.TRANSFER, b"\x01garbage-not-a-buffer")
        assert server.get_buffer(timeout=2) is None
        # connection is closed server-side: recv sees EOF (possibly after
        # a short delay while the close is processed)
        sock.settimeout(5)
        with pytest.raises((P.QueryProtocolError, OSError)):
            while True:
                P.recv_msg(sock)
        sock.close()

    def test_stop_while_consumer_blocked(self, server):
        """stop() must unblock a thread waiting in get_buffer and never
        crash (native core frees only after in-flight calls drain)."""
        import threading
        import time

        results = []

        def consumer():
            results.append(server.get_buffer(timeout=30))

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.2)  # let it block inside the wait
        server.stop()
        t.join(timeout=10)
        assert not t.is_alive()
        assert results == [None]


class TestServerSoak:
    def test_sustained_offload_500_frames(self):
        """Sustained pipelined load through the native transport: every
        frame accounted for, zero drops, orderly EOS."""
        server, port = self._make_server("soak_inc", 77, "16")
        client = None
        try:
            client, src, sink = self._make_client(port, window=8)
            n = 500
            for i in range(n):
                src.push([np.full(16, float(i), np.float32)], pts=i)
            src.end_of_stream()
            msg = client.wait(timeout=120)
            assert msg is not None and msg.kind == "eos", msg
            assert len(sink.buffers) == n
            assert int(client.get("c").get_property("frames_dropped")) == 0
            # spot-check ordering + content across the run
            for i in (0, 123, 499):
                np.testing.assert_allclose(
                    np.asarray(sink.buffers[i][0]), float(i) + 1.0)
        finally:
            if client is not None:
                client.stop()
            server.stop()

    @staticmethod
    def _make_server(model, pair_id, dim):
        """serversrc → custom-easy(+1) filter → serversink, started."""
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str(dim, "float32")
        register_custom_easy(model,
                             lambda ins: [np.asarray(ins[0]) + 1.0],
                             info, info)
        server = parse_launch(
            f"tensor_query_serversrc name=ss port=0 id={pair_id} ! "
            f"tensor_filter framework=custom-easy model={model} ! "
            f"tensor_query_serversink id={pair_id}")
        server.start()
        assert server.get("ss").server.native
        return server, server.get("ss").port

    @staticmethod
    def _make_client(port, window):
        """appsrc → query client → sink pipeline, started."""
        from nnstreamer_tpu.elements.sink import TensorSink
        from nnstreamer_tpu.elements.source import AppSrc

        client = parse_launch(
            f"tensor_query_client name=c dest-host=127.0.0.1 "
            f"dest-port={port} max-in-flight={window} timeout=30")
        src, sink = AppSrc(name="src"), TensorSink(name="out")
        client.add(src, sink)
        src.link(client.get("c"))
        client.get("c").link(sink)
        client.start()
        return client, src, sink

    def test_concurrent_clients_native_core(self):
        """Four clients hammering the native transport from separate
        threads: per-connection write mutexes and the atomic take keep
        every stream intact."""
        import threading

        server, port = self._make_server("conc_inc", 78, "8")
        try:
            results = {}

            def client_run(tag):
                c = None
                try:
                    c, src, sink = self._make_client(port, window=4)
                    n = 60
                    for i in range(n):
                        src.push([np.full(8, tag * 1000.0 + i, np.float32)],
                                 pts=i)
                    src.end_of_stream()
                    msg = c.wait(timeout=60)
                    vals = [float(np.asarray(b[0])[0])
                            for b in sink.buffers]
                    results[tag] = (msg.kind if msg else None, vals)
                except Exception as e:  # surface in the main thread
                    results[tag] = ("exception", repr(e))
                finally:
                    if c is not None:
                        c.stop()

            threads = [threading.Thread(target=client_run, args=(t,))
                       for t in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert all(not t.is_alive() for t in threads)
            for tag in range(4):
                kind, vals = results[tag]
                assert kind == "eos", (tag, kind)
                assert vals == [tag * 1000.0 + i + 1.0 for i in range(60)]
        finally:
            server.stop()
