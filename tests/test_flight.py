"""Flight recorder (obs/flight.py) + streaming quantiles
(obs/quantiles.py) — the always-on tail-telemetry contract:

- P² streaming quantiles track p50/p99 of seeded uniform / lognormal /
  bimodal distributions within tolerance of the exact order statistics,
  with NO sample storage, and stay correct under concurrent feeding;
- the recorder detects tail events (e2e > k× rolling median), defers
  the dump until the post-offender window completes, writes ONE
  rate-limited JSON dump containing the offending frame's spans, and
  suppresses the next trigger inside the interval;
- SLO burn-rate windows (fast + slow) read breach fractions over their
  trailing windows, raise the scheduler's overload signal, and post a
  rate-limited bus warning;
- the attribution engine names the dominant-variance stage and turns it
  into advisory hints the FeedbackController folds into lanes_hint;
- NNSTPU_FLIGHT=0 is a true kill switch (no recorder, no stamps), and
  the always-on default changes no output byte;
- the streaming gauges export through the registry in BOTH Prometheus
  text and the JSON snapshot.
"""

import glob
import json
import threading

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.obs import flight as _flight
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.obs.flight import FlightRecorder
from nnstreamer_tpu.obs.quantiles import BurnRateWindow, P2Quantile
from nnstreamer_tpu.pipeline.pipeline import Pipeline

GOLDEN = ("videotestsrc pattern=ball num-buffers=24 width=16 height=16 ! "
          "tensor_converter ! queue ! tensor_sink name=sink")


class TestP2Quantile:
    @pytest.mark.parametrize("p,tol", [(0.5, 0.05), (0.99, 0.08)])
    def test_uniform(self, rng, p, tol):
        data = rng.uniform(0.0, 1.0, 4000)
        q = P2Quantile(p)
        for x in data:
            q.observe(x)
        exact = float(np.percentile(data, p * 100))
        assert abs(q.quantile() - exact) <= tol * max(exact, 0.1)

    @pytest.mark.parametrize("p,tol", [(0.5, 0.05), (0.99, 0.10)])
    def test_lognormal(self, rng, p, tol):
        data = rng.lognormal(0.0, 0.5, 4000)
        q = P2Quantile(p)
        for x in data:
            q.observe(x)
        exact = float(np.percentile(data, p * 100))
        assert abs(q.quantile() - exact) <= tol * exact

    def test_bimodal(self, rng):
        # two well-separated modes (fast path vs stall): p50 must land
        # in the fast mode, p99 in the slow one — the separation the
        # tail detector depends on
        fast = rng.normal(0.010, 0.001, 3600)
        slow = rng.normal(0.500, 0.020, 400)
        data = rng.permutation(np.concatenate([fast, slow]))
        p50, p99 = P2Quantile(0.5), P2Quantile(0.99)
        for x in data:
            p50.observe(x)
            p99.observe(x)
        assert abs(p50.quantile()
                   - float(np.percentile(data, 50))) <= 0.005
        assert abs(p99.quantile()
                   - float(np.percentile(data, 99))) <= 0.08

    def test_small_counts_are_exact(self):
        q = P2Quantile(0.5)
        assert q.quantile() is None
        for x in (5.0, 1.0, 3.0):
            q.observe(x)
        assert q.quantile() == 3.0  # exact order statistic while n<=5

    def test_concurrent_observers_merge(self, rng):
        """Feeding one estimator from several threads must neither lose
        observations nor corrupt the marker invariants."""
        data = rng.uniform(0.0, 1.0, 4000)
        q = P2Quantile(0.5)
        chunks = np.array_split(data, 8)

        def feed(chunk):
            for x in chunk:
                q.observe(x)

        threads = [threading.Thread(target=feed, args=(c,), daemon=True)
                   for c in chunks]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert q.count == len(data)
        exact = float(np.percentile(data, 50))
        assert abs(q.quantile() - exact) <= 0.05


class TestP2MarkerMerge:
    """Fleet federation merges replica P² marker states (never raw
    samples) via merge_p2_snapshots — the merged quantile must track
    the pooled-exact one across distributions (obs/distributed.py)."""

    @staticmethod
    def _split_observe(data, p, replicas):
        from nnstreamer_tpu.obs.quantiles import merge_p2_snapshots

        snaps = []
        for chunk in np.array_split(data, replicas):
            q = P2Quantile(p)
            for x in chunk:
                q.observe(float(x))
            snaps.append(q.snapshot())
        return merge_p2_snapshots(snaps, p)

    @pytest.mark.parametrize("p,tol", [(0.5, 0.06), (0.99, 0.12)])
    def test_uniform(self, rng, p, tol):
        data = rng.uniform(0.0, 1.0, 4000)
        merged = self._split_observe(data, p, replicas=4)
        exact = float(np.percentile(data, p * 100))
        assert abs(merged - exact) <= tol * max(exact, 0.1)

    @pytest.mark.parametrize("p,tol", [(0.5, 0.06), (0.99, 0.15)])
    def test_lognormal(self, rng, p, tol):
        data = rng.lognormal(0.0, 0.5, 4000)
        merged = self._split_observe(data, p, replicas=4)
        exact = float(np.percentile(data, p * 100))
        assert abs(merged - exact) <= tol * exact

    def test_bimodal(self, rng):
        # a fleet where some replicas are healthy and some stall: the
        # merged p99 must land in the slow mode even though no single
        # replica's markers were built from the pooled stream
        fast = rng.normal(0.010, 0.001, 3600)
        slow = rng.normal(0.500, 0.020, 400)
        data = rng.permutation(np.concatenate([fast, slow]))
        p50 = self._split_observe(data, 0.5, replicas=4)
        p99 = self._split_observe(data, 0.99, replicas=4)
        assert abs(p50 - float(np.percentile(data, 50))) <= 0.01
        assert abs(p99 - float(np.percentile(data, 99))) <= 0.10

    def test_uneven_replica_weights(self, rng):
        # counts weight the mixture: a replica with 10x the traffic
        # must dominate the merged estimate
        from nnstreamer_tpu.obs.quantiles import merge_p2_snapshots

        heavy = rng.normal(0.100, 0.005, 3000)
        light = rng.normal(0.900, 0.005, 300)
        snaps = []
        for chunk in (heavy, light):
            q = P2Quantile(0.5)
            for x in chunk:
                q.observe(float(x))
            snaps.append(q.snapshot())
        merged = merge_p2_snapshots(snaps, 0.5)
        pooled = float(np.percentile(np.concatenate([heavy, light]), 50))
        assert abs(merged - pooled) <= 0.02

    def test_warmup_snapshots_exact(self):
        # replicas still in the n<=5 exact-heights phase merge on the
        # raw order statistics
        from nnstreamer_tpu.obs.quantiles import merge_p2_snapshots

        snaps = []
        for chunk in ((1.0, 2.0), (3.0, 4.0)):
            q = P2Quantile(0.5)
            for x in chunk:
                q.observe(x)
            snaps.append(q.snapshot())
        merged = merge_p2_snapshots(snaps, 0.5)
        assert 2.0 <= merged <= 3.0

    def test_empty_and_invalid(self):
        from nnstreamer_tpu.obs.quantiles import merge_p2_snapshots

        q = P2Quantile(0.5)
        assert merge_p2_snapshots([], 0.5) is None
        assert merge_p2_snapshots([q.snapshot()], 0.5) is None
        with pytest.raises(ValueError):
            merge_p2_snapshots([], 1.5)


class TestBurnRateWindow:
    def test_rate_is_breach_fraction_over_budget(self):
        b = BurnRateWindow(window_s=10.0, error_budget=0.1)
        for i in range(100):
            b.add(i * 0.05, breached=(i % 2 == 0))
        # 50% breached / 10% budget = 5x burn
        assert b.rate(5.0) == pytest.approx(5.0, abs=0.5)

    def test_old_events_evict(self):
        b = BurnRateWindow(window_s=1.0, error_budget=0.5)
        b.add(0.0, True)
        b.add(0.1, True)
        assert b.rate(0.5) == pytest.approx(2.0)
        assert b.rate(10.0) == 0.0
        assert b.sample_count(10.0) == 0

    def test_cap_eviction_keeps_count_honest(self):
        b = BurnRateWindow(window_s=1e9, error_budget=1.0, cap=10)
        for i in range(50):
            b.add(float(i), breached=True)
        assert b.sample_count(50.0) == 10
        assert b.rate(50.0) == pytest.approx(1.0)


def _feed_frame(fr, seq, e2e_s, device_s=None, t0=None):
    """Synthetic frame: one device span + the sink completion span."""
    t = float(seq) if t0 is None else t0
    d = device_s if device_s is not None else e2e_s / 2
    fr.span("device", seq, t, t + d)
    fr.span("sink", seq, t + d, t + e2e_s, e2e_s=e2e_s)


class TestTailDump:
    def test_tail_event_dumps_window_once(self, tmp_path):
        fr = FlightRecorder(dump_dir=str(tmp_path), min_samples=5,
                            window_frames=2, min_interval_s=3600.0,
                            tail_k=4.0)
        for seq in range(10):
            _feed_frame(fr, seq, 0.002)
        _feed_frame(fr, 10, 0.500)          # the offender: 250x median
        assert fr.last_trigger["kind"] == "tail"
        assert fr.last_trigger["seq"] == 10
        assert not list(tmp_path.glob("*.json")), \
            "dump must wait for the post-offender window"
        _feed_frame(fr, 11, 0.002)
        _feed_frame(fr, 12, 0.002)          # seq 12 >= 10+2: flush
        files = list(tmp_path.glob("*.json"))
        assert len(files) == 1
        doc = json.loads(files[0].read_text())
        assert doc["trigger"]["kind"] == "tail"
        assert doc["trigger"]["seq"] == 10
        # the dump's window contains the offending frame's full spans
        offender = [s for s in doc["spans"] if s["seq"] == 10]
        assert {s["kind"] for s in offender} >= {"device", "sink"}
        assert doc["window"]["seq_lo"] == 8
        assert doc["window"]["seq_hi"] == 12
        assert "10" in doc["frames_ms"]
        # a second offender inside the rate-limit interval is counted
        # but produces no second file
        _feed_frame(fr, 13, 0.500)
        _feed_frame(fr, 14, 0.002)
        _feed_frame(fr, 15, 0.002)
        _feed_frame(fr, 16, 0.002)
        assert len(list(tmp_path.glob("*.json"))) == 1
        assert fr.suppressed_dumps == 1

    def test_fault_mark_triggers_and_watchdog_flushes_immediately(
            self, tmp_path):
        fr = FlightRecorder(dump_dir=str(tmp_path), min_samples=5,
                            window_frames=4, min_interval_s=3600.0)
        for seq in range(6):
            _feed_frame(fr, seq, 0.002)
        fr.mark("watchdog_trip", None, track="faults", idle_s=1.5)
        # watchdog may mean no more completions ever arrive: the dump
        # must not wait for the post-window
        files = list(tmp_path.glob("*.json"))
        assert len(files) == 1
        doc = json.loads(files[0].read_text())
        assert doc["trigger"]["kind"] == "watchdog"
        assert doc["trigger"]["detail"]["mark"] == "watchdog_trip"

    def test_deadline_breach_triggers(self, tmp_path):
        fr = FlightRecorder(dump_dir=str(tmp_path), min_samples=5,
                            window_frames=1, min_interval_s=3600.0,
                            slo_budget_s=0.010)
        _feed_frame(fr, 0, 0.050)
        assert fr.last_trigger["kind"] == "deadline"
        assert fr.trigger_counts["deadline"] == 1

    def test_no_dump_dir_counts_but_writes_nothing(self, tmp_path):
        fr = FlightRecorder(dump_dir=None, min_samples=5,
                            window_frames=1, min_interval_s=0.0)
        for seq in range(8):
            _feed_frame(fr, seq, 0.002)
        _feed_frame(fr, 8, 0.500)
        _feed_frame(fr, 9, 0.002)
        _feed_frame(fr, 10, 0.002)
        assert fr.trigger_counts["tail"] >= 1
        assert fr.dump_count == 0

    def test_retire_flushes_pending(self, tmp_path):
        fr = FlightRecorder(dump_dir=str(tmp_path), min_samples=5,
                            window_frames=50, min_interval_s=3600.0)
        for seq in range(10):
            _feed_frame(fr, seq, 0.002)
        _feed_frame(fr, 10, 0.500)  # offender right before EOS
        assert not list(tmp_path.glob("*.json"))
        _timeline.ACTIVE = fr
        _flight.retire(fr)
        assert _timeline.ACTIVE is None
        assert len(list(tmp_path.glob("*.json"))) == 1


class TestBurnAndAttribution:
    def test_burn_overload_and_bus_warning(self):
        pipe = Pipeline(name="flight-burn-unit")
        fr = FlightRecorder(slo_budget_s=0.010, min_samples=5,
                            pipeline=pipe)
        for seq in range(20):
            _feed_frame(fr, seq, 0.050, t0=seq * 0.1)  # all breach
        now = 19 * 0.1 + 0.05
        fast, slow = fr.burn_rates(now)
        assert fast > 2.0 and slow > 2.0
        assert fr.burn_overload(now)
        kinds = []
        while True:
            msg = pipe.pop_message(timeout=0)
            if msg is None:
                break
            kinds.append(msg.kind)
        assert "warning" in kinds

    def test_attribution_names_dominant_stage_and_hints(self):
        fr = FlightRecorder(min_samples=5)
        # ingest owns the spread: half the frames pay a 50 ms ingest
        # stall, everything else is constant
        for seq in range(20):
            t = float(seq)
            ing = 0.050 if seq % 2 else 0.001
            fr.span("ingest", seq, t, t + ing)
            fr.span("device", seq, t + ing, t + ing + 0.002)
            fr.span("sink", seq, t + ing + 0.002, t + ing + 0.003,
                    e2e_s=ing + 0.003)
        attr = fr.attribution()
        assert attr["dominant_stage"] == "ingest"
        assert attr["dominant_share"] > 0
        assert attr["hints"] == {"lanes_hint_delta": 1}

    def test_attribution_pressure_hints(self):
        fr = FlightRecorder(min_samples=5)
        for seq in range(20):
            t = float(seq)
            fw = 0.040 if seq % 2 else 0.001
            fr.span("fence_wait", seq, t, t + fw)
            fr.span("sink", seq, t + fw, t + fw + 0.001,
                    e2e_s=fw + 0.001)
        assert fr.attribution()["hints"] == {"inflight_pressure": True}

    def test_slo_snapshot_has_stage_quantiles(self):
        fr = FlightRecorder(min_samples=5)
        for seq in range(32):
            _feed_frame(fr, seq, 0.004, device_s=0.002)
        slo = fr.slo_snapshot()
        assert slo["completed"] == 32
        assert slo["stages"]["e2e"]["p50_ms"] == pytest.approx(4.0,
                                                               rel=0.2)
        assert slo["stages"]["device"]["p50_ms"] == pytest.approx(
            2.0, rel=0.2)
        assert slo["stages"]["device"]["count"] == 32


class _FakeFlight:
    def __init__(self, hints=None, overload=False):
        self._hints = hints or {}
        self._overload = overload

    def attribution(self):
        return {"hints": dict(self._hints)}

    def burn_overload(self, now=None):
        return self._overload


class TestSchedulerIntegration:
    def test_overload_forces_multiplicative_decrease(self):
        from nnstreamer_tpu.serving.scheduler import FeedbackController

        c = FeedbackController(budget_s=1.0, interval_s=0.0,
                               batch_cap=8, inflight=4)
        for _ in range(16):
            c.record_completion(0.01)  # p99 well under budget
        # healthy p99 would normally additive-increase; the burn-rate
        # overload must force the decrease branch instead
        assert c.maybe_step(now=100.0, overload=True)
        assert c.batch_cap == 4
        assert c.inflight == 3

    def test_attribution_hint_raises_lanes_hint(self):
        from nnstreamer_tpu.serving.scheduler import SloScheduler

        pipe = Pipeline(name="flight-hint-unit")
        sched = SloScheduler(budget_ms=100.0, pipeline=pipe,
                             name="flight-hint-unit")
        pipe._flight = _FakeFlight()
        sched._apply_knobs()
        base = sched._lanes_hint
        pipe._flight = _FakeFlight(hints={"lanes_hint_delta": 1})
        sched._apply_knobs()
        assert sched._lanes_hint == base + 1


class TestPipelineWiring:
    def test_kill_switch_disables_recorder(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_FLIGHT", "0")
        assert not _flight.flight_enabled()
        pipe = parse_launch(GOLDEN)
        msg = pipe.run(timeout=120)
        assert msg is not None and msg.kind == "eos"
        assert pipe._flight is None
        assert "slo" not in pipe.metrics_snapshot()

    def test_always_on_recorder_fills_snapshot_and_keeps_bytes(
            self, monkeypatch):
        monkeypatch.delenv("NNSTPU_FLIGHT", raising=False)
        pipe_on = parse_launch(GOLDEN)
        assert pipe_on.run(timeout=120).kind == "eos"
        assert pipe_on._flight is not None
        assert _timeline.ACTIVE is None, "retired at stop"
        snap = pipe_on.metrics_snapshot()
        assert snap["slo"]["completed"] == 24
        assert "e2e" in snap["slo"]["stages"]
        assert "attribution" in snap
        monkeypatch.setenv("NNSTPU_FLIGHT", "0")
        pipe_off = parse_launch(GOLDEN)
        assert pipe_off.run(timeout=120).kind == "eos"
        on = [b.tensors[0].tobytes()
              for b in pipe_on.get("sink").buffers]
        off = [b.tensors[0].tobytes()
               for b in pipe_off.get("sink").buffers]
        assert on == off, "always-on recorder changed output bytes"

    def test_explicit_timeline_wins_over_flight(self, monkeypatch):
        monkeypatch.delenv("NNSTPU_FLIGHT", raising=False)
        tl = _timeline.activate()
        try:
            pipe = parse_launch(GOLDEN)
            assert pipe.run(timeout=120).kind == "eos"
            assert pipe._flight is None
            assert _timeline.ACTIVE is tl
        finally:
            _timeline.deactivate()

    def test_env_dump_dir_produces_dump_on_stall(self, tmp_path,
                                                 monkeypatch):
        """The acceptance path: NNSTPU_FLIGHT=<dir> + an injected stall
        ⇒ exactly one dump whose window contains the offender."""
        from nnstreamer_tpu.pipeline import faults

        monkeypatch.setenv("NNSTPU_FLIGHT", str(tmp_path))
        monkeypatch.setenv("NNSTPU_FLIGHT_MIN_SAMPLES", "6")
        faults.activate("queue.push:nth=16,kind=stall,ms=250", seed=3)
        try:
            pipe = parse_launch(GOLDEN)
            assert pipe.run(timeout=120).kind == "eos"
        finally:
            faults.deactivate()
        files = glob.glob(str(tmp_path / "*.json"))
        assert len(files) == 1, files
        doc = json.loads(open(files[0]).read())
        assert doc["trigger"]["kind"] in ("fault", "tail")
        seqs = {s["seq"] for s in doc["spans"] if s["seq"] is not None}
        assert doc["trigger"]["seq"] is None or \
            doc["trigger"]["seq"] in seqs


class TestGaugeExport:
    def test_stage_and_burn_gauges_export_text_and_json(self):
        fr = FlightRecorder(slo_budget_s=0.010, min_samples=5,
                            pipeline=None)
        fr.pipeline_name = "flight-gauge-unit"
        for seq in range(16):
            _feed_frame(fr, seq, 0.004)
        fr.register_gauges()
        reg = get_registry()
        text = reg.render_prometheus()
        assert 'nns_stage_p50_ms{' in text
        assert 'nns_stage_p99_ms{' in text
        assert 'nns_slo_burn_rate{' in text
        line = [ln for ln in text.splitlines()
                if ln.startswith("nns_stage_p50_ms")
                and 'stage="e2e"' in ln
                and 'pipeline="flight-gauge-unit"' in ln]
        assert line and float(line[0].rsplit(None, 1)[1]) > 0
        snap = reg.snapshot()
        blob = json.dumps(snap)
        assert "nns_stage_p50_ms" in blob
        assert "nns_slo_burn_rate" in blob
