"""Tests for utils/trace.py (chrome-trace export) and utils/stats.py
(InvokeStats edge cases) — the host-side profiling instruments the obs
registry builds on."""

import json

import numpy as np

from nnstreamer_tpu.pipeline.element import Element, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import Pipeline, SourceElement
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.utils.stats import InvokeStats
from nnstreamer_tpu.utils.trace import Tracer


class _NumSrc(SourceElement):
    ELEMENT_NAME = "_trcnumsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 5}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig

        cfg = TensorsConfig.from_arrays([np.zeros((1,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        buf = TensorBuffer([np.array([float(self.i)], np.float32)],
                           pts=self.i * 1000)
        self.i += 1
        return buf


class _CountSink(Element):
    ELEMENT_NAME = "_trccountsink"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.count = 0

    def chain(self, pad, buf):
        self.count += 1
        return FlowReturn.OK


class TestTracerChromeExport:
    def _run_traced(self, n=6):
        from nnstreamer_tpu.pipeline.pipeline import Queue

        # a queue between source and sink gives every frame ≥2 traced
        # hops, so the export's flow-event chains have something to link
        src = _NumSrc(name="tsrc", num_buffers=n)
        sink = _CountSink(name="tsink")
        pipe = Pipeline(name=f"trace-{n}", fuse=False).add_linked(
            src, Queue(name="tq"), sink)
        tracer = Tracer()
        with tracer.attach(pipe):
            assert pipe.run(timeout=10) is not None
        return tracer, sink

    def test_export_is_valid_chrome_trace(self, tmp_path):
        tracer, sink = self._run_traced(n=6)
        path = tmp_path / "trace.json"
        tracer.export_chrome(str(path))
        with open(path) as f:
            doc = json.load(f)  # must parse — the Perfetto load contract
        events = doc["traceEvents"]
        assert events, "traced run produced no events"
        slices = [ev for ev in events if ev["ph"] == "X"]
        assert slices, "no complete events"
        for ev in slices:
            # one COMPLETE event per invoke: phase X with ts + dur
            assert ev["cat"] == "element"
            assert ev["ts"] >= 0 and ev["dur"] >= 0
            assert isinstance(ev["pid"], int)
            assert isinstance(ev["tid"], int)
            # pts + interlatency ride along as args (followable frames)
            assert "pts" in ev["args"]
        # flow events follow a frame across element tracks: each pts seen
        # by >1 element starts with `s` and finishes with `f` (bp="e")
        flow = [ev for ev in events if ev["ph"] in ("s", "t", "f")]
        assert flow, "no flow events in a multi-element trace"
        by_id = {}
        for ev in flow:
            by_id.setdefault(ev["id"], []).append(ev["ph"])
        for phases in by_id.values():
            assert phases[0] == "s" and phases[-1] == "f"
        assert all(ev.get("bp") == "e"
                   for ev in flow if ev["ph"] == "f")

    def test_one_complete_event_per_element_invoke(self, tmp_path):
        tracer, sink = self._run_traced(n=7)
        path = tmp_path / "trace.json"
        tracer.export_chrome(str(path))
        with open(path) as f:
            events = json.load(f)["traceEvents"]
        slices = [ev for ev in events if ev["ph"] == "X"]
        per_el = {}
        for ev in slices:
            per_el[ev["name"]] = per_el.get(ev["name"], 0) + 1
        assert per_el["tsink"] == sink.count == 7
        # distinct elements get distinct tids (one lane per element)
        tids = {ev["name"]: ev["tid"] for ev in slices}
        assert len(set(tids.values())) == len(tids)

    def test_detach_restores_chain_entry(self):
        src = _NumSrc(name="dsrc", num_buffers=2)
        sink = _CountSink(name="dsink")
        pipe = Pipeline(name="trace-detach",
                        fuse=False).add_linked(src, sink)
        tracer = Tracer()
        with tracer.attach(pipe):
            pass
        # the wrapper must not shadow the class method after detach
        assert "_chain_entry" not in sink.__dict__
        assert pipe.run(timeout=10) is not None
        assert len(tracer.events) == 0  # nothing recorded outside attach


class TestInvokeStatsEdgeCases:
    def test_empty_window_reads_zero(self):
        s = InvokeStats()
        assert s.latency_us == 0
        assert s.throughput_milli == 0
        snap = s.snapshot()
        assert snap["latency_us"] == 0
        assert snap["total_invokes"] == 0

    def test_single_sample_throughput_zero(self):
        s = InvokeStats()
        s.record(0.001, now=100.0)
        assert s.latency_us == 1000
        assert s.throughput_milli == 0  # a rate needs two stamps

    def test_stale_samples_pruned_from_throughput(self):
        s = InvokeStats(max_age_s=10.0)
        s.record(0.001, now=100.0)
        s.record(0.001, now=150.0)  # 50 s later: the first stamp is stale
        assert s.throughput_milli == 0  # only one live stamp remains
        s.record(0.001, now=150.5)
        s.record(0.001, now=151.0)
        # 3 live stamps over 1 s → 2 intervals/s → 2000 milli-out/s
        assert s.throughput_milli == 2000
        assert s.total_invokes == 4  # cumulative count never prunes

    def test_latency_window_bounded(self):
        s = InvokeStats(window=3)
        for lat in (1.0, 1.0, 0.001, 0.001, 0.001):
            s.record(lat, now=100.0)
        # only the last `window` samples feed the average
        assert s.latency_us == 1000
        assert s.total_invokes == 5
        assert abs(s.total_latency_s - 2.003) < 1e-9

    def test_measure_context_manager(self):
        s = InvokeStats()
        with s.measure():
            pass
        assert s.total_invokes == 1
        assert s.latency_us >= 0
