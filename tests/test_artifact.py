"""Compiled-model artifact loading on the TPU backend.

The reference's headline capability is loading an opaque model *file* and
running it on the accelerator (tensor_filter_tensorflow_lite.cc:154-238 —
TFLiteInterpreter loads any .tflite). These tests prove the TPU-native
equivalent end to end: artifacts are produced in a *separate process*
(truly external), loaded by extension via framework=auto, self-describe
their caps, and run through SingleShot and full gst-launch pipelines.
Raw StableHLO modules — what torch_xla / TF toolchains emit — load too.
"""

import subprocess
import sys

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.artifact import (
    artifact_tensors_info,
    export_model,
    load_artifact,
    save_artifact,
)
from nnstreamer_tpu.single import SingleShot
from nnstreamer_tpu.tensors.types import TensorsInfo

# Exporter script run out-of-process: a linear model with baked weights.
# JAX_PLATFORMS=cpu keeps the child off any accelerator tunnel.
_EXPORT_SCRIPT = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
import jax, jax.numpy as jnp, numpy as np
import jax.export

w = np.arange(12, dtype=np.float32).reshape(4, 3) / 10.0
b = np.array([1.0, 2.0, 3.0], dtype=np.float32)

def model(x):
    return jnp.dot(x, w) + b

exp = jax.export.export(jax.jit(model), platforms=["cpu", "tpu"])(
    jax.ShapeDtypeStruct((2, 4), jnp.float32))
with open(sys.argv[1], "wb") as f:
    f.write(bytes(exp.serialize()))
"""


def _golden(x):
    w = np.arange(12, dtype=np.float32).reshape(4, 3) / 10.0
    b = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    return x @ w + b


@pytest.fixture(scope="module")
def external_artifact(tmp_path_factory):
    """An artifact produced by a separate python process."""
    path = tmp_path_factory.mktemp("artifact") / "linear.jaxexp"
    subprocess.run([sys.executable, "-c", _EXPORT_SCRIPT, str(path)],
                   check=True, capture_output=True, timeout=300)
    return str(path)


class TestExternalArtifact:
    def test_self_describing_info(self, external_artifact):
        exp = load_artifact(external_artifact)
        in_info, out_info = artifact_tensors_info(exp)
        assert in_info[0].shape == (2, 4)
        assert out_info[0].shape == (2, 3)
        assert out_info[0].type.np_dtype == np.float32

    def test_singleshot_auto_framework(self, external_artifact):
        x = np.random.default_rng(0).normal(size=(2, 4)).astype(np.float32)
        with SingleShot(model=external_artifact) as s:  # framework=auto
            assert s.get_input_info()[0].shape == (2, 4)
            (out,) = s.invoke([x])
        np.testing.assert_allclose(np.asarray(out), _golden(x),
                                   rtol=1e-5, atol=1e-5)

    def test_gst_launch_pipeline(self, external_artifact):
        """The reference's one-liner story: opaque file in a launch string,
        no input/output properties — caps come from the artifact."""
        pipe = parse_launch(
            f"appsrc name=in ! tensor_filter model={external_artifact} ! "
            "tensor_sink name=out to-host=true"
        )
        outs = []
        pipe.get("out").connect(lambda b: outs.append(b))
        x = np.full((2, 4), 0.5, dtype=np.float32)
        pipe.start()
        pipe.get("in").push([x])
        pipe.get("in").end_of_stream()
        assert pipe.wait(timeout=120).kind == "eos"
        pipe.stop()
        assert len(outs) == 1
        np.testing.assert_allclose(np.asarray(outs[0].tensors[0]),
                                   _golden(x), rtol=1e-5, atol=1e-5)


class TestSaveLoadRoundTrip:
    def test_params_baked_as_constants(self, tmp_path):
        import jax.numpy as jnp

        params = {"w": np.full((3, 3), 2.0, np.float32)}

        def fn(p, x):
            return x @ p["w"]

        info = TensorsInfo.from_str("3:5", "float32")
        path = tmp_path / "m.jaxexp"
        save_artifact(str(path), fn, params, in_info=info,
                      platforms=("cpu",))
        exp = load_artifact(str(path))
        x = np.ones((5, 3), np.float32)
        out = np.asarray(exp.call(x))
        np.testing.assert_allclose(out, x @ params["w"])

    def test_multi_output(self, tmp_path):
        import jax.numpy as jnp

        def fn(x):
            return jnp.tanh(x), x.sum(axis=1)

        info = TensorsInfo.from_str("4:2", "float32")
        path = tmp_path / "multi.stablehlo"
        save_artifact(str(path), fn, None, in_info=info, platforms=("cpu",))
        with SingleShot(framework="jax", model=str(path)) as s:
            out_info = s.get_output_info()
            assert len(out_info) == 2
            outs = s.invoke([np.ones((2, 4), np.float32)])
        np.testing.assert_allclose(np.asarray(outs[0]),
                                   np.tanh(np.ones((2, 4))), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(outs[1]), [4.0, 4.0])


class TestRawStableHLO:
    """Raw MLIR modules — the torch_xla / TF export interchange format."""

    def _mlir_text(self):
        import jax
        import jax.export
        import jax.numpy as jnp

        exp = jax.export.export(
            jax.jit(lambda x: jnp.maximum(x, 0.0) * 3.0),
            platforms=["cpu"],
        )(jax.ShapeDtypeStruct((2, 5), jnp.float32))
        return exp.mlir_module()

    def test_mlir_text_module(self, tmp_path):
        path = tmp_path / "relu3.mlir"
        path.write_text(self._mlir_text())
        with SingleShot(model=str(path)) as s:
            in_info = s.get_input_info()
            assert in_info[0].shape == (2, 5)
            x = np.linspace(-1, 1, 10, dtype=np.float32).reshape(2, 5)
            (out,) = s.invoke([x])
        np.testing.assert_allclose(np.asarray(out), np.maximum(x, 0) * 3.0,
                                   rtol=1e-6)

    def test_portable_artifact_bytes(self, tmp_path):
        import jaxlib.mlir.dialects.stablehlo as shlo

        data = shlo.serialize_portable_artifact_str(
            self._mlir_text(), shlo.get_minimum_version())
        path = tmp_path / "relu3.mlirbc"
        path.write_bytes(bytes(data))
        with SingleShot(model=str(path)) as s:
            x = np.full((2, 5), -2.0, np.float32)
            (out,) = s.invoke([x])
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_ingested_artifact_has_no_vjp(self, tmp_path):
        path = tmp_path / "m.mlir"
        path.write_text(self._mlir_text())
        exp = load_artifact(str(path))
        assert not exp.has_vjp()


class TestExportTool:
    def test_export_model_from_py(self, tmp_path):
        src = tmp_path / "double.py"
        src.write_text(
            "import jax.numpy as jnp\n"
            "from nnstreamer_tpu.tensors.types import TensorsInfo\n"
            "IN_INFO = TensorsInfo.from_str('4:2', 'float32')\n"
            "def get_model():\n"
            "    return lambda x: x * 2.0\n"
        )
        out = tmp_path / "double.jaxexp"
        out_info = export_model(str(src), str(out), platforms=("cpu",))
        assert out_info[0].shape == (2, 4)
        with SingleShot(model=str(out)) as s:
            (y,) = s.invoke([np.ones((2, 4), np.float32)])
        np.testing.assert_allclose(np.asarray(y), 2.0)

    def test_cli_export(self, tmp_path):
        from nnstreamer_tpu.cli import main

        src = tmp_path / "half.py"
        src.write_text(
            "def get_model():\n"
            "    return lambda x: x * 0.5\n"
        )
        out = tmp_path / "half.stablehlo"
        rc = main(["--export", str(src), str(out), "--platforms", "cpu",
                   "--input", "3:2", "--inputtype", "float32"])
        assert rc == 0
        with SingleShot(model=str(out)) as s:
            (y,) = s.invoke([np.full((2, 3), 4.0, np.float32)])
        np.testing.assert_allclose(np.asarray(y), 2.0)


class TestRejections:
    def test_savedmodel_pb_pointed_error(self, tmp_path, monkeypatch):
        """Without tensorflow importable, TF model paths get the
        offline-recipe error; with it, they go to in-process ingestion
        (tests/test_tf_backend.py)."""
        import nnstreamer_tpu.filters.tf_backend as tfb

        monkeypatch.setattr(tfb, "have_tensorflow", lambda: False)
        pb = tmp_path / "frozen.pb"
        pb.write_bytes(b"\x08\x01")
        with pytest.raises(ValueError, match="StableHLO"):
            SingleShot(framework="jax", model=str(pb))

    def test_savedmodel_dir_pointed_error(self, tmp_path, monkeypatch):
        import nnstreamer_tpu.filters.tf_backend as tfb

        monkeypatch.setattr(tfb, "have_tensorflow", lambda: False)
        d = tmp_path / "sm"
        d.mkdir()
        (d / "saved_model.pb").write_bytes(b"\x08\x01")
        with pytest.raises(ValueError, match="model-artifacts"):
            SingleShot(framework="jax", model=str(d))

    def test_garbage_artifact(self, tmp_path):
        bad = tmp_path / "bad.jaxexp"
        bad.write_bytes(b"not an artifact at all")
        with pytest.raises(Exception):
            SingleShot(framework="jax", model=str(bad))


def test_bench_artifact_mode(tmp_path, monkeypatch):
    """BENCH_ARTIFACT=1 runs the flagship pipeline from an exported
    artifact file (VERDICT r2 #1 done-criterion)."""
    import bench

    monkeypatch.setenv("BENCH_ARTIFACT", "1")
    monkeypatch.setattr(bench, "N_FRAMES", 16)
    monkeypatch.setattr(bench, "_ARTIFACT_CACHE", {})
    pipe = bench.build_pipeline(batch=8)
    outs = []
    pipe.get("sink").connect(lambda b: outs.append(b))
    msg = pipe.run(timeout=300)
    assert msg is not None and msg.kind == "eos"
    assert len(outs) == 2  # 16 frames / batch 8
    assert len(outs[0].meta["label_index"]) == 8
    filt = pipe.get("filter")
    assert str(filt.get_property("model")).endswith(".jaxexp")


def test_sharded_artifact_round_trip():
    """Multi-chip artifacts: a pjit'd fn exported with mesh shardings
    round-trips and its call distributes over a matching mesh (the
    conftest 8-device virtual CPU mesh stands in for a TPU slice)."""
    import jax
    import jax.export
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    w = jnp.ones((8, 16))
    sharded = jax.jit(lambda x: x @ w,
                      in_shardings=NamedSharding(mesh, P("dp", None)),
                      out_shardings=NamedSharding(mesh, P("dp", "tp")))
    exp = jax.export.export(sharded)(
        jax.ShapeDtypeStruct((4, 8), jnp.float32))
    assert exp.nr_devices == 4

    exp2 = jax.export.deserialize(bytes(exp.serialize()))
    x = jax.device_put(np.ones((4, 8), np.float32),
                       NamedSharding(mesh, P("dp", None)))
    out = exp2.call(x)
    assert float(np.asarray(out).sum()) == 4 * 16 * 8
    assert out.sharding.spec == P("dp", "tp")
