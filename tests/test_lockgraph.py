"""Runtime lock-order witness (obs/lockgraph.py).

Three contracts under test:

- **kill switch**: with ``NNSTPU_LOCKGRAPH`` unset the module is a
  byte-identical no-op — the ``threading`` factories are untouched and
  the graph records zero acquisitions (subprocess-verified, since this
  test process itself must not be armed);
- **witness**: a seeded two-lock inversion across two threads is
  detected online (one violation carrying the cycle path), while
  consistent orderings, RLock reentrancy, and Condition wait/notify
  stay clean;
- **cross-check**: :func:`lockgraph.cross_check` reports a cycle when
  the union of the observed and static graphs is cyclic (runtime B→A
  against static A→B) and stays silent when they agree.

The factory only instruments locks whose creating frame lives under
the package root, so the scenarios are written to a real file and the
root is pointed at it — an inline ``exec`` would be filtered out.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading

import pytest

from nnstreamer_tpu.obs import lockgraph

_SCENARIO = '''\
"""Lock-acquisition scenarios driven by test_lockgraph.py."""
import threading


def make_locks():
    a = threading.Lock()
    b = threading.Lock()
    return a, b


def run_inversion():
    """Two threads take the same two locks in opposite orders.

    Sequential (join between them) on purpose: the witness flags the
    *order* contradiction, no actual deadlock interleaving needed."""
    a, b = make_locks()

    def fwd():
        with a:
            with b:
                pass

    def rev():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=fwd, name="lg-fwd")
    t1.start()
    t1.join()
    t2 = threading.Thread(target=rev, name="lg-rev")
    t2.start()
    t2.join()


def run_ordered():
    a, b = make_locks()

    def fwd():
        with a:
            with b:
                pass

    for name in ("lg-one", "lg-two"):
        t = threading.Thread(target=fwd, name=name)
        t.start()
        t.join()


def run_rlock():
    r = threading.RLock()
    with r:
        with r:
            pass


def run_condition():
    lk = threading.Lock()
    cv = threading.Condition(lk)
    done = []

    def waiter():
        with cv:
            while not done:
                cv.wait(1.0)

    t = threading.Thread(target=waiter, name="lg-wait")
    t.start()
    with cv:
        done.append(1)
        cv.notify_all()
    t.join()
'''


@pytest.fixture
def armed(tmp_path, monkeypatch):
    """Arm the witness with the creator-frame filter pointed at a
    scenario module written to tmp_path; restore everything after."""
    scen = tmp_path / "scenario.py"
    scen.write_text(_SCENARIO)
    monkeypatch.setattr(lockgraph, "_PKG_ROOT", str(tmp_path))
    monkeypatch.setattr(lockgraph, "_REL_BASE", str(tmp_path))
    lockgraph.reset()
    lockgraph.activate()
    try:
        spec = importlib.util.spec_from_file_location("lg_scenario", scen)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        yield mod
    finally:
        lockgraph.deactivate()
        lockgraph.reset()
    assert threading.Lock is lockgraph._REAL_LOCK
    assert threading.RLock is lockgraph._REAL_RLOCK


def test_locks_are_instrumented_and_site_keyed(armed):
    a, b = armed.make_locks()
    assert type(a).__name__ == "_InstrumentedLock"
    assert type(b).__name__ == "_InstrumentedLock"
    assert a._site.startswith("scenario.py:")
    snap = lockgraph.snapshot()
    assert set(snap["nodes"].values()) == {"lock"}


def test_seeded_inversion_detected(armed):
    armed.run_inversion()
    snap = lockgraph.snapshot()
    assert len(snap["violations"]) == 1
    v = snap["violations"][0]
    # the second thread's reversed order closes the cycle
    assert v["thread"] == "lg-rev"
    assert len(set(v["cycle"])) == 2
    assert all(s.startswith("scenario.py:") for s in v["cycle"])
    # both directions were recorded as edges
    pairs = {(e["from"], e["to"]) for e in snap["edges"]}
    assert len(pairs) == 2
    assert {(b, a) for a, b in pairs} == pairs


def test_consistent_order_clean(armed):
    armed.run_ordered()
    snap = lockgraph.snapshot()
    assert snap["violations"] == []
    assert len(snap["edges"]) == 1
    assert snap["edges"][0]["count"] == 2
    assert snap["acquisitions"] == 4


def test_rlock_reentrancy_adds_no_edge(armed):
    armed.run_rlock()
    snap = lockgraph.snapshot()
    assert snap["violations"] == []
    assert snap["edges"] == []
    assert set(snap["nodes"].values()) == {"rlock"}


def test_condition_wait_notify_balanced(armed):
    armed.run_condition()
    snap = lockgraph.snapshot()
    assert snap["violations"] == []
    # wait() released and re-took the one lock; the per-thread stacks
    # must have drained (an unbalanced stack would leave phantom holds
    # that manufacture bogus edges on the next acquisition)
    a, _ = armed.make_locks()
    with a:
        pass
    assert lockgraph.snapshot()["edges"] == []


def test_dump_roundtrip(armed, tmp_path):
    armed.run_inversion()
    out = tmp_path / "graph.json"
    lockgraph.dump(str(out))
    doc = json.loads(out.read_text())
    assert doc["version"] == 1
    assert doc["nodes"] and doc["edges"] and doc["violations"]
    assert not out.with_suffix(".json.tmp").exists()


# -- kill switch (subprocess: this process must stay unarmed) -------------

def _run(code, env_extra):
    env = {k: v for k, v in os.environ.items()
           if k != lockgraph.ENV}
    env.update(env_extra)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, env=env,
                          timeout=120)


def test_env_unset_is_byte_identical_noop():
    proc = _run(
        "import threading\n"
        "import nnstreamer_tpu\n"
        "from nnstreamer_tpu.obs import lockgraph\n"
        "assert threading.Lock is lockgraph._REAL_LOCK\n"
        "assert threading.RLock is lockgraph._REAL_RLOCK\n"
        "assert not lockgraph.is_active()\n"
        "assert lockgraph.graph().acquisitions == 0\n"
        "assert lockgraph.graph().nodes == {}\n",
        {})
    assert proc.returncode == 0, proc.stderr


def test_env_armed_instruments_package_locks():
    proc = _run(
        "import json\n"
        "import nnstreamer_tpu\n"
        "from nnstreamer_tpu.obs import lockgraph\n"
        "assert lockgraph.is_active()\n"
        "snap = lockgraph.snapshot()\n"
        "assert snap['nodes'], 'import-time locks not instrumented'\n"
        "assert snap['violations'] == []\n"
        "print(json.dumps(len(snap['nodes'])))\n",
        {lockgraph.ENV: "1"})
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout) >= 5   # the tree has ~35 lock sites


def test_env_path_dumps_at_exit(tmp_path):
    out = tmp_path / "observed.json"
    proc = _run("import nnstreamer_tpu\n", {lockgraph.ENV: str(out)})
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(out.read_text())
    assert doc["version"] == 1
    assert doc["violations"] == []


# -- static/runtime cross-check -------------------------------------------

def _static(edges, sites):
    return {"version": 1,
            "nodes": sorted({n for e in edges for n in e}),
            "edges": [{"from": a, "to": b, "site": "s"} for a, b in edges],
            "sites": sites}


def _runtime(edges, violations=()):
    return {"version": 1,
            "nodes": {n: "lock" for e in edges for n in e},
            "edges": [{"from": a, "to": b, "count": 1} for a, b in edges],
            "acquisitions": 2 * len(edges),
            "violations": list(violations)}


def test_cross_check_agreement_is_silent():
    sites = {"m.py:1": "m:A", "m.py:2": "m:B"}
    static = _static([("m:A", "m:B")], sites)
    runtime = _runtime([("m.py:1", "m.py:2")])
    assert lockgraph.cross_check(runtime, static) == []


def test_cross_check_flags_union_cycle():
    # statically A is taken before B; at runtime a path took B then A —
    # neither graph alone is cyclic, the union is the deadlock
    sites = {"m.py:1": "m:A", "m.py:2": "m:B"}
    static = _static([("m:A", "m:B")], sites)
    runtime = _runtime([("m.py:2", "m.py:1")])
    problems = lockgraph.cross_check(runtime, static)
    assert len(problems) == 1
    assert "contradiction" in problems[0]
    assert "m:A" in problems[0] and "m:B" in problems[0]


def test_cross_check_reports_observed_violations():
    sites = {"m.py:1": "m:A", "m.py:2": "m:B"}
    runtime = _runtime(
        [("m.py:1", "m.py:2"), ("m.py:2", "m.py:1")],
        violations=[{"cycle": ["m.py:1", "m.py:2", "m.py:1"],
                     "thread": "t2",
                     "edge": ["m.py:2", "m.py:1"]}])
    problems = lockgraph.cross_check(runtime, _static([], sites))
    assert any("observed lock-order cycle" in p and "m:A" in p
               for p in problems)
