"""Supervision layer + deterministic fault injection (pipeline/faults.py,
pipeline/supervise.py).

The contract under test, per docs/robustness.md:

- ``NNSTPU_FAULTS`` unset means ``faults.ACTIVE is None`` and the hot
  path is byte-identical to a build without the injector;
- the same spec + seed reproduces the same fired occurrence set across
  runs and regardless of thread interleaving (pure function of
  ``(seed, site, n)``);
- ``error-policy=retry`` recovers injected failures with ZERO frame
  loss and byte-identical output; ``skip-frame`` loses exactly the
  injected count with survivor order preserved; ``degrade`` reloads the
  tensor_filter backend and keeps serving; ``halt`` is the unchanged
  default (wrap, raise, bus error);
- a crashed lane worker restarts under supervision with surviving
  frames delivered in order;
- the watchdog detects a stalled pipeline within its deadline, fails it
  on the bus, and teardown leaves no live threads;
- every injected fault/recovery is visible from three independent
  witnesses that must agree: the injector's fired log, the
  ``nns_fault_*`` counters, and the frame-ledger ``faults`` track.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.pipeline import faults
from nnstreamer_tpu.pipeline import supervise
from nnstreamer_tpu.pipeline.element import Element, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import (
    FlowError,
    Pipeline,
    Queue,
    SourceElement,
)
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import TensorsConfig

# -- helpers ------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _no_active_injector():
    faults.deactivate()
    yield
    faults.deactivate()


def _cval(name, **labels):
    m = get_registry().get(name, **labels)
    return 0.0 if m is None else m.value


def _live_threads():
    return set(threading.enumerate())


def _extra_threads(before, timeout=5.0):
    """Threads alive now that were not alive at ``before`` — polled,
    because worker joins race the assertion."""
    deadline = time.monotonic() + timeout
    while True:
        extra = [t for t in threading.enumerate()
                 if t not in before and t.is_alive()]
        if not extra or time.monotonic() >= deadline:
            return extra
        time.sleep(0.05)


class _SeqSrc(SourceElement):
    """Index-stamped scalar tensors 1..n."""

    ELEMENT_NAME = "_supseqsrc"
    REORDER_SAFE = True
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 16}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        cfg = TensorsConfig.from_arrays([np.zeros((4,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        self.i += 1
        return TensorBuffer(
            [np.full((4,), float(self.i), np.float32)],
            pts=self.i * 1000)


class _Hook(Element):
    """Pure transform (x*2+1) that runs the ``filter.invoke`` fault hook
    per frame — the generic stand-in for a backend invoke."""

    ELEMENT_NAME = "_suphook"
    REORDER_SAFE = True

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def chain(self, pad, buf):
        fi = faults.ACTIVE
        if fi is not None:
            fi.check("filter.invoke",
                     seq=buf.meta.get(_timeline.TRACE_SEQ_META))
        self.srcpad.push(buf.with_tensors(
            [t * 2.0 + 1.0 for t in buf.tensors]))
        return FlowReturn.OK


class _Boom(Element):
    """Raises ValueError on the ``fail_at``-th frame; forwards others."""

    ELEMENT_NAME = "_supboom"
    PROPERTIES = {**Element.PROPERTIES, "fail_at": 5}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self.n = 0

    def chain(self, pad, buf):
        self.n += 1
        if self.n == int(self.get_property("fail_at")):
            raise ValueError(f"boom on frame {self.n}")
        self.srcpad.push(buf)
        return FlowReturn.OK


def _build(name, *mids, n=20, **pipe_kw):
    """src(n) ! mids... ! tensor_sink, returning (pipe, outs list of
    first-scalar floats appended at the sink)."""
    from nnstreamer_tpu.elements.sink import TensorSink

    pipe = Pipeline(name=name, fuse=False, **pipe_kw)
    src = _SeqSrc(num_buffers=n)
    sink = TensorSink(name="out")
    pipe.add_linked(src, *mids, sink)
    outs = []
    sink.connect(lambda b: outs.append(float(np.asarray(b.tensors[0])[0])))
    return pipe, outs


# -- spec grammar and activation ----------------------------------------------


class TestSpecGrammar:
    def test_parse_multi_clause_spec(self):
        rules = faults.parse_faults(
            "filter.invoke:rate=0.01,kind=raise;"
            "lane.worker:nth=37,kind=crash;"
            "dispatch.fence:kind=stall,ms=500")
        by_site = {r.site: r for r in rules}
        assert by_site["filter.invoke"].rate == 0.01
        assert by_site["filter.invoke"].kind == "raise"
        assert by_site["lane.worker"].nth == 37
        assert by_site["lane.worker"].kind == "crash"
        assert by_site["dispatch.fence"].kind == "stall"
        assert by_site["dispatch.fence"].ms == 500.0

    def test_unknown_site_kind_key_all_raise(self):
        with pytest.raises(ValueError, match="unknown site"):
            faults.parse_faults("bogus.site:rate=1")
        with pytest.raises(ValueError, match="unknown kind"):
            faults.parse_faults("filter.invoke:kind=bogus")
        with pytest.raises(ValueError, match="unknown key"):
            faults.parse_faults("filter.invoke:frequency=2")

    def test_env_activation_and_idempotence(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_FAULTS", "filter.invoke:nth=2")
        monkeypatch.setenv("NNSTPU_FAULTS_SEED", "5")
        inj = faults.maybe_activate_env()
        assert inj is not None and faults.ACTIVE is inj
        assert inj.seed == 5
        assert faults.maybe_activate_env() is inj  # idempotent

    def test_explicit_injector_wins_over_env(self, monkeypatch):
        inj = faults.activate("filter.invoke:nth=1")
        monkeypatch.setenv("NNSTPU_FAULTS", "queue.push:nth=1")
        assert faults.maybe_activate_env() is inj

    def test_unset_env_leaves_active_none(self, monkeypatch):
        monkeypatch.delenv("NNSTPU_FAULTS", raising=False)
        assert faults.maybe_activate_env() is None
        assert faults.ACTIVE is None

    def test_bad_seed_env_falls_back_to_zero(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_FAULTS", "filter.invoke:nth=9999")
        monkeypatch.setenv("NNSTPU_FAULTS_SEED", "not-a-number")
        inj = faults.maybe_activate_env()
        assert inj is not None and inj.seed == 0


# -- determinism --------------------------------------------------------------


def _drive(inj, site, n):
    fired = []
    for _ in range(n):
        try:
            inj.check(site)
        except faults.InjectedFault as e:
            fired.append(e.n)
    return fired


class TestDeterminism:
    def test_same_spec_seed_same_fired_set(self):
        a = faults.FaultInjector(
            faults.parse_faults("filter.invoke:rate=0.3"), seed=11)
        b = faults.FaultInjector(
            faults.parse_faults("filter.invoke:rate=0.3"), seed=11)
        fired_a = _drive(a, "filter.invoke", 200)
        fired_b = _drive(b, "filter.invoke", 200)
        assert fired_a == fired_b
        assert len(fired_a) > 0
        assert a.fired_set("filter.invoke") == sorted(fired_a)

    def test_decision_independent_of_thread_interleaving(self):
        serial = faults.FaultInjector(
            faults.parse_faults("queue.push:rate=0.3"), seed=3)
        _drive(serial, "queue.push", 200)
        threaded = faults.FaultInjector(
            faults.parse_faults("queue.push:rate=0.3"), seed=3)

        def worker():
            for _ in range(50):
                try:
                    threaded.check("queue.push")
                except faults.InjectedFault:
                    pass

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # the occurrence counter hands out a different interleaving, but
        # the decision per occurrence index is the same pure function
        assert threaded.fired_set("queue.push") \
            == serial.fired_set("queue.push")

    def test_nth_and_every_triggers(self):
        inj = faults.FaultInjector(
            faults.parse_faults("filter.invoke:nth=3"), seed=0)
        assert _drive(inj, "filter.invoke", 10) == [3]
        inj = faults.FaultInjector(
            faults.parse_faults("filter.invoke:every=4"), seed=0)
        assert _drive(inj, "filter.invoke", 12) == [4, 8, 12]

    def test_crash_kind_raises_injected_crash(self):
        inj = faults.FaultInjector(
            faults.parse_faults("lane.worker:nth=1,kind=crash"))
        with pytest.raises(faults.InjectedCrash):
            inj.check("lane.worker")
        assert inj.fired == [("lane.worker", 1, "crash")]

    def test_pipeline_runs_reproduce_fired_set(self):
        def once(tag):
            inj = faults.activate("filter.invoke:rate=0.2", seed=7)
            pipe, outs = _build(f"sup-det-{tag}", _Hook(),
                                error_policy="retry")
            msg = pipe.run(timeout=30)
            assert msg is not None and msg.kind == "eos"
            return inj.fired_set("filter.invoke"), outs

        fired1, outs1 = once("a")
        fired2, outs2 = once("b")
        assert fired1 == fired2 and len(fired1) > 0
        assert outs1 == outs2


# -- error policies -----------------------------------------------------------


class TestRetryPolicy:
    def test_zero_loss_byte_identical_no_hang(self):
        inj = faults.activate("filter.invoke:rate=0.2", seed=7)
        pipe, outs = _build("sup-retry",
                            _Hook(name="hook", retry_backoff_ms=1.0),
                            error_policy="retry")
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        assert outs == [i * 2.0 + 1.0 for i in range(1, 21)]
        assert inj.injected("filter.invoke") > 0
        labels = pipe.get("hook")._obs_labels()
        assert _cval("nns_fault_recovered_total", **labels) >= 1
        assert _cval("nns_fault_retries_total", **labels) >= 1

    def test_exhausted_retries_halt_with_flow_error(self):
        faults.activate("filter.invoke:every=1")  # every attempt fails
        pipe, _ = _build("sup-retry-exhaust",
                         _Hook(retry_max=2, retry_backoff_ms=1.0),
                         n=4, error_policy="retry")
        with pytest.raises(FlowError, match="retry exhausted"):
            pipe.run(timeout=30)

    def test_element_policy_overrides_pipeline_default(self):
        faults.activate("filter.invoke:nth=2")
        # pipeline says halt (default); the element itself opts into
        # skip-frame and must win
        pipe, outs = _build("sup-override",
                            _Hook(error_policy="skip_frame"), n=6)
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        assert len(outs) == 5


class TestSkipFramePolicy:
    def test_loss_equals_injected_order_preserved(self):
        inj = faults.activate("filter.invoke:rate=0.2", seed=7)
        pipe, outs = _build("sup-skip", _Hook(name="hook"),
                            error_policy="skip-frame")
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        lost = inj.injected("filter.invoke")
        assert lost > 0
        assert len(outs) == 20 - lost
        assert outs == sorted(outs)  # survivors in order
        survivors = {(v - 1.0) / 2.0 for v in outs}
        fired = {float(n) for n in inj.fired_set("filter.invoke")}
        assert survivors == set(range(1, 21)) - \
            {float(i) for i in range(1, 21) if float(i) in fired}
        assert _cval("nns_fault_skipped_frames_total",
                     **pipe.get("hook")._obs_labels()) == lost

    def test_halt_is_unchanged_default(self):
        faults.activate("filter.invoke:nth=3")
        pipe, outs = _build("sup-halt", _Hook(), n=6)
        with pytest.raises(FlowError, match="injected fault"):
            pipe.run(timeout=30)
        assert outs == [3.0, 5.0]  # frames before the failure delivered


class TestDegradePolicy:
    def test_filter_backend_reload_keeps_serving(self):
        import jax.numpy as jnp

        from nnstreamer_tpu import parse_launch
        from nnstreamer_tpu.filters.jax_backend import register_jax_model

        register_jax_model("sup_degrade",
                           lambda x: (x.astype(jnp.float32) * 2.0,), None)
        faults.activate("filter.invoke:nth=3")
        pipe = parse_launch(
            "videotestsrc num-buffers=6 width=4 height=4 ! "
            "tensor_converter ! "
            "tensor_filter framework=jax model=sup_degrade name=filter ! "
            "queue materialize-host=true ! tensor_sink name=out",
            error_policy="degrade")
        outs = []
        pipe.get("out").connect(lambda b: outs.append(b))
        msg = pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos"
        assert len(outs) == 6  # zero loss: reload + retry served frame 3
        el = pipe.get("filter")
        labels = el._obs_labels()
        assert _cval("nns_fault_degraded_total", **labels) >= 1
        assert _cval("nns_fault_recovered_total", **labels) >= 1
        # the first rung (in-place reload) recovered — the CPU-fallback
        # rung never ran, so the accelerator property is untouched
        assert el._props.get("accelerator") != "cpu"

    def test_non_filter_element_gets_retry_semantics(self):
        faults.activate("filter.invoke:nth=2")
        pipe, outs = _build("sup-degrade-nonfilter",
                            _Hook(retry_backoff_ms=1.0), n=6,
                            error_policy="degrade")
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        assert len(outs) == 6  # recovered by retry, no backend involved


# -- lane-worker supervision --------------------------------------------------


class TestLaneSupervision:
    def _run(self, policy, spec, n=20, lanes=4):
        inj = faults.activate(spec)
        pipe, outs = _build(f"sup-lane-{policy}", _Hook(),
                            n=n, lanes=lanes, error_policy=policy)
        msg = pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos"
        return inj, pipe, outs

    def test_crashed_worker_restarts_zero_loss_in_order(self):
        inj, pipe, outs = self._run(
            "retry", "lane.worker:nth=5,kind=crash")
        assert inj.injected("lane.worker") == 1
        assert outs == [i * 2.0 + 1.0 for i in range(1, 21)]
        ex = pipe._lane_execs[0]
        assert _cval("nns_fault_lane_restarts_total",
                     **ex._obs_labels()) >= 1
        assert ex._delivered == ex._seq  # nothing stranded

    def test_crashed_worker_skip_frame_counts_loss(self):
        inj, pipe, outs = self._run(
            "skip-frame", "lane.worker:nth=5,kind=crash")
        assert inj.injected("lane.worker") == 1
        assert len(outs) == 19  # exactly the in-flight frame lost
        assert outs == sorted(outs)
        ex = pipe._lane_execs[0]
        assert ex._delivered == ex._seq


# -- watchdog -----------------------------------------------------------------


class TestWatchdog:
    def test_detects_stall_within_deadline_clean_shutdown(self):
        before = _live_threads()
        trips0 = _cval("nns_fault_watchdog_trips_total",
                       pipeline="sup-wd-stall")
        faults.activate("filter.invoke:nth=2,kind=stall,ms=2500")
        pipe, _ = _build("sup-wd-stall", _Hook(), n=6, watchdog_s=0.4)
        t0 = time.monotonic()
        pipe.start()
        msg = pipe.wait(timeout=10)
        detect_s = time.monotonic() - t0
        assert msg is not None and msg.kind == "error"
        assert "watchdog" in str(msg.error)
        assert detect_s < 2.0  # detected well inside the stall
        pipe.stop()
        assert _cval("nns_fault_watchdog_trips_total",
                     pipeline="sup-wd-stall") == trips0 + 1
        assert _extra_threads(before) == []

    def test_quiescent_pipeline_never_trips(self):
        trips0 = _cval("nns_fault_watchdog_trips_total",
                       pipeline="sup-wd-idle")
        pipe, outs = _build("sup-wd-idle", _Hook(), n=4, watchdog_s=0.2)
        pipe.start()
        msg = pipe.wait(timeout=10)
        assert msg is not None and msg.kind == "eos"
        time.sleep(0.8)  # 4x the deadline of post-EOS idle
        pipe.stop()
        assert len(outs) == 4
        assert _cval("nns_fault_watchdog_trips_total",
                     pipeline="sup-wd-idle") == trips0

    def test_env_arms_watchdog(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_WATCHDOG_S", "5.0")
        pipe, _ = _build("sup-wd-env", _Hook(), n=2)
        pipe.start()
        try:
            assert pipe._watchdog is not None
            assert pipe._watchdog.deadline_s == 5.0
        finally:
            pipe.stop()
        assert pipe._watchdog is None

    def test_off_by_default_zero_threads(self, monkeypatch):
        monkeypatch.delenv("NNSTPU_WATCHDOG_S", raising=False)
        pipe, _ = _build("sup-wd-off", _Hook(), n=2)
        pipe.start()
        try:
            assert pipe._watchdog is None
            assert not any("watchdog" in t.name
                           for t in threading.enumerate())
        finally:
            pipe.stop()


# -- three-witness agreement: injector log, metrics, timeline -----------------


class TestMetricsAndMarksAgree:
    def test_fault_counts_agree_across_witnesses(self):
        m0 = _cval("nns_fault_injected_total",
                   site="filter.invoke", kind="raise")
        tl = _timeline.activate()
        try:
            inj = faults.activate("filter.invoke:rate=0.3", seed=3)
            pipe, _ = _build("sup-witness", _Hook(),
                             error_policy="skip-frame")
            msg = pipe.run(timeout=30)
            assert msg is not None and msg.kind == "eos"
            injected = inj.injected("filter.invoke")
            assert injected > 0
            marks = [r for r in tl._snapshot()
                     if r[1] == "fault" and r[5] == "faults"]
            skips = [r for r in tl._snapshot()
                     if r[1] == "fault_skip" and r[5] == "faults"]
        finally:
            _timeline.deactivate()
        assert len(marks) == injected
        assert len(skips) == injected
        assert _cval("nns_fault_injected_total",
                     site="filter.invoke", kind="raise") == m0 + injected
        assert inj.snapshot() == {"filter.invoke": injected}


# -- kill switch --------------------------------------------------------------


class TestKillSwitch:
    def test_unset_env_is_byte_identical_off_path(self, monkeypatch):
        monkeypatch.delenv("NNSTPU_FAULTS", raising=False)
        pipe, outs = _build("sup-off", _Hook(), n=8)
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        assert faults.ACTIVE is None  # never activated by start()
        assert outs == [i * 2.0 + 1.0 for i in range(1, 9)]

    def test_unknown_policy_is_a_flow_error(self):
        pipe, _ = _build("sup-badpol",
                         _Hook(name="hook", error_policy="bogus"), n=2)
        with pytest.raises(FlowError, match="unknown error-policy"):
            supervise.effective_policy(pipe.get("hook"))


# -- bus error path (pre-existing machinery the supervisor builds on) ---------


class _RawEntryBoom(Element):
    """Raises a PLAIN RuntimeError from the chain-entry boundary itself,
    bypassing the element-level FlowError wrap — exercising the queue
    drain workers' own wrap-to-FlowError handlers."""

    ELEMENT_NAME = "_suprawboom"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def chain(self, pad, buf):  # pragma: no cover - never reached
        return FlowReturn.OK

    def _chain_entry(self, pad, buf):
        raise RuntimeError("raw entry boom")


class TestBusErrorPath:
    def test_error_posts_after_prefailure_frames(self):
        before = _live_threads()
        pipe, outs = _build("sup-bus-order",
                            Queue(name="q", max_size_buffers=8),
                            _Boom(fail_at=5), n=8)
        pipe.start()
        msg = pipe.wait(timeout=30)
        assert msg is not None and msg.kind == "error"
        assert isinstance(msg.error, FlowError)
        assert "boom on frame 5" in str(msg.error)
        # every pre-failure frame was delivered before the error posted
        assert outs == [1.0, 2.0, 3.0, 4.0]
        pipe.stop()
        assert _extra_threads(before) == []

    def test_queue_drain_wraps_raw_exception_in_flow_error(self):
        pipe, _ = _build("sup-bus-wrap", Queue(name="q"),
                         _RawEntryBoom(), n=4)
        pipe.start()
        msg = pipe.wait(timeout=30)
        pipe.stop()
        assert msg is not None and msg.kind == "error"
        assert isinstance(msg.error, FlowError)
        # the queue's _drain handler names ITSELF as the wrap site
        assert str(msg.error).startswith("q: ")
        assert "raw entry boom" in str(msg.error)

    def test_sched_drain_wraps_and_stops_clean(self):
        before = _live_threads()
        pipe, _ = _build(
            "sup-bus-sched",
            Queue(name="q", stamp_admission=True, max_size_buffers=16),
            _RawEntryBoom(), n=4, slo_budget_ms=10_000.0)
        pipe.start()
        assert pipe.get("q")._sched is not None  # scheduler path active
        msg = pipe.wait(timeout=30)
        pipe.stop()
        assert msg is not None and msg.kind == "error"
        assert isinstance(msg.error, FlowError)
        assert str(msg.error).startswith("q: ")
        assert _extra_threads(before) == []

    def test_stop_after_error_leaves_no_live_threads(self):
        before = _live_threads()
        pipe, _ = _build("sup-bus-threads",
                         Queue(name="q", max_size_buffers=4),
                         _Boom(fail_at=2), n=16, lanes=1)
        with pytest.raises(FlowError):
            pipe.run(timeout=30)
        assert _extra_threads(before) == []
