"""Fused device kernels for bounding_boxes / pose_estimation must match the
host decode() paths (fused pipelines indistinguishable except for speed)."""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.jax_backend import (
    register_jax_model,
    unregister_jax_model,
)


def _run_pipe(model, dec_opts, frame, fuse):
    pipe = parse_launch(
        "appsrc name=src ! tensor_transform mode=typecast option=float32 ! "
        f"tensor_filter framework=jax model={model} ! "
        f"tensor_decoder mode={dec_opts} ! tensor_sink name=sink to-host=true")
    pipe._fuse = fuse
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    try:
        src.push([frame.copy()])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    if fuse:
        assert pipe._regions
        members = [m.ELEMENT_NAME for m in pipe._regions[0].members]
        assert "tensor_decoder" in members, members
    else:
        assert not pipe._regions
    return sink.buffers[0]


def _det_key(d):
    return (d["class"], round(d["score"], 5), tuple(round(v, 4) for v in d["box"]))


@pytest.fixture
def ssd_model():
    import jax.numpy as jnp

    from nnstreamer_tpu.models.ssd_mobilenet import anchor_grid

    anchors = anchor_grid(300)
    A = anchors.shape[0]
    rng = np.random.default_rng(3)
    box_enc = jnp.asarray(rng.normal(0, 0.5, (A, 4)), jnp.float32)
    # a few strong detections, rest background
    logits = np.full((A, 5), -6.0, np.float32)
    for a, c in ((10, 1), (500, 2), (1200, 3), (11, 1)):
        logits[a, c] = 4.0
    logits = jnp.asarray(logits)

    def fn(x):
        return box_enc, logits

    register_jax_model("ssd_toy", fn, None)
    yield "ssd_toy"
    unregister_jax_model("ssd_toy")


def test_fused_ssd_matches_host(ssd_model):
    frame = np.zeros((4,), np.uint8)
    opts = "bounding_boxes option1=mobilenet-ssd option3=0.5 option7=meta"
    f = _run_pipe(ssd_model, opts, frame, fuse=True)
    u = _run_pipe(ssd_model, opts, frame, fuse=False)
    df, du = f.meta["detections"], u.meta["detections"]
    assert len(df) == len(du) > 0
    assert {_det_key(d) for d in df} == {_det_key(d) for d in du}
    np.testing.assert_allclose(np.asarray(f[0]), np.asarray(u[0]), atol=1e-4)


@pytest.fixture
def postproc_model():
    import jax.numpy as jnp

    boxes = jnp.asarray([[0.1, 0.1, 0.4, 0.4],
                         [0.5, 0.5, 0.9, 0.9],
                         [0.2, 0.2, 0.3, 0.3]], jnp.float32)
    scores = jnp.asarray([0.9, 0.2, 0.7], jnp.float32)
    classes = jnp.asarray([1, 2, 3], jnp.float32)

    def fn(x):
        return boxes, scores, classes

    register_jax_model("postproc_toy", fn, None)
    yield "postproc_toy"
    unregister_jax_model("postproc_toy")


def test_fused_postprocess_matches_host(postproc_model):
    frame = np.zeros((4,), np.uint8)
    opts = "bounding_boxes option1=mobilenet-ssd-postprocess option3=0.5 option7=meta"
    f = _run_pipe(postproc_model, opts, frame, fuse=True)
    u = _run_pipe(postproc_model, opts, frame, fuse=False)
    # host path preserves anchor order — fused must too
    assert [_det_key(d) for d in f.meta["detections"]] == \
        [_det_key(d) for d in u.meta["detections"]]
    assert len(f.meta["detections"]) == 2


@pytest.fixture
def zero_score_model():
    import jax.numpy as jnp

    boxes = jnp.asarray([[0.1, 0.1, 0.4, 0.4],
                         [0.5, 0.5, 0.9, 0.9]], jnp.float32)
    scores = jnp.asarray([0.0, 0.6], jnp.float32)  # legit 0-score row
    classes = jnp.asarray([1, 2], jnp.float32)

    def fn(x):
        return boxes, scores, classes

    register_jax_model("zeroscore_toy", fn, None)
    yield "zeroscore_toy"
    unregister_jax_model("zeroscore_toy")


def test_fused_postprocess_keeps_zero_score_at_thresh_zero(zero_score_model):
    """option3=0: a row whose score is exactly 0 passes the host filter
    (score >= thresh) and must not be conflated with device-path padding
    (PAD_SCORE sentinel, not score==0)."""
    frame = np.zeros((4,), np.uint8)
    opts = "bounding_boxes option1=mobilenet-ssd-postprocess option3=0 option7=meta"
    f = _run_pipe(zero_score_model, opts, frame, fuse=True)
    u = _run_pipe(zero_score_model, opts, frame, fuse=False)
    assert [_det_key(d) for d in f.meta["detections"]] == \
        [_det_key(d) for d in u.meta["detections"]]
    assert len(f.meta["detections"]) == 2  # 0-score row kept on both paths


@pytest.fixture
def yolo_model():
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    pred = np.full((40, 9), -6.0, np.float32)  # 4 box + obj + 4 classes
    pred[:, :4] = rng.uniform(0.2, 0.8, (40, 4)).astype(np.float32)
    for a, c in ((3, 0), (17, 2), (30, 3)):
        pred[a, 4] = 5.0          # objectness
        pred[a, 5 + c] = 5.0      # class logit
    pred = jnp.asarray(pred)

    def fn(x):
        return pred

    register_jax_model("yolo_toy", fn, None)
    yield "yolo_toy"
    unregister_jax_model("yolo_toy")


def test_fused_yolov5_matches_host(yolo_model):
    frame = np.zeros((4,), np.uint8)
    opts = "bounding_boxes option1=yolov5 option3=0.5 option7=meta"
    f = _run_pipe(yolo_model, opts, frame, fuse=True)
    u = _run_pipe(yolo_model, opts, frame, fuse=False)
    assert {_det_key(d) for d in f.meta["detections"]} == \
        {_det_key(d) for d in u.meta["detections"]}
    assert len(f.meta["detections"]) == 3


@pytest.fixture
def pose_model():
    import jax.numpy as jnp

    rng = np.random.default_rng(9)
    H = W = 9
    K = 5
    heat = rng.uniform(0, 0.2, (H, W, K)).astype(np.float32)
    for k in range(K):
        heat[1 + k, 2 + k, k] = 0.9
    offs = rng.uniform(-0.4, 0.4, (H, W, 2 * K)).astype(np.float32)
    heat, offs = jnp.asarray(heat), jnp.asarray(offs)

    def fn(x):
        return heat, offs

    register_jax_model("pose_toy", fn, None)
    yield "pose_toy"
    unregister_jax_model("pose_toy")


def test_fused_pose_matches_host(pose_model):
    frame = np.zeros((4,), np.uint8)
    opts = "pose_estimation option2=meta option3=0.3"
    f = _run_pipe(pose_model, opts, frame, fuse=True)
    u = _run_pipe(pose_model, opts, frame, fuse=False)
    kf, ku = f.meta["keypoints"], u.meta["keypoints"]
    assert len(kf) == len(ku) == 5
    for a, b in zip(kf, ku):
        assert a["keypoint"] == b["keypoint"] and a["visible"] == b["visible"]
        np.testing.assert_allclose([a["y"], a["x"], a["score"]],
                                   [b["y"], b["x"], b["score"]], atol=1e-5)
    np.testing.assert_allclose(np.asarray(f[0]), np.asarray(u[0]), atol=1e-5)


def test_fused_overlay_output_matches(pose_model):
    """Overlay (video) output path also goes through finalize identically."""
    frame = np.zeros((4,), np.uint8)
    opts = "pose_estimation option1=64:64 option3=0.3"
    f = _run_pipe(pose_model, opts, frame, fuse=True)
    u = _run_pipe(pose_model, opts, frame, fuse=False)
    np.testing.assert_array_equal(np.asarray(f[0]), np.asarray(u[0]))


def test_trace_failure_falls_back_to_member_chain(postproc_model):
    """A fused program that fails at trace/execute time must unsplice and
    resume through the member chain, not kill the stream (fusion is an
    optimization, never a failure)."""
    pipe = parse_launch(
        "appsrc name=src ! tensor_transform mode=typecast option=float32 ! "
        f"tensor_filter framework=jax model={postproc_model} ! "
        "tensor_decoder mode=bounding_boxes "
        "option1=mobilenet-ssd-postprocess option3=0.5 option7=meta ! "
        "tensor_sink name=sink to-host=true")
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    try:
        region = pipe._regions[0]
        # sabotage the compiled program: a jit that always explodes
        def boom(consts, tensors):
            raise RuntimeError("trace bomb")
        region._compiled = (None, boom, None)
        src.push([np.zeros((4,), np.uint8)])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        assert region._dead  # unspliced
        assert len(sink.buffers[0].meta["detections"]) == 2  # host path ran
    finally:
        pipe.stop()


@pytest.fixture
def seg_model():
    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    logits = jnp.asarray(rng.normal(0, 1, (1, 12, 10, 6)), jnp.float32)

    def fn(x):
        return logits

    register_jax_model("seg_toy", fn, None)
    yield "seg_toy"
    unregister_jax_model("seg_toy")


def test_fused_segment_matches_host(seg_model):
    frame = np.zeros((4,), np.uint8)
    f = _run_pipe(seg_model, "image_segment", frame, fuse=True)
    u = _run_pipe(seg_model, "image_segment", frame, fuse=False)
    np.testing.assert_array_equal(f.meta["segment_labels"],
                                  u.meta["segment_labels"])
    np.testing.assert_array_equal(np.asarray(f[0]), np.asarray(u[0]))
    assert np.asarray(f[0]).shape == (12, 10, 4)


def test_mode_aliases_match_reference(postproc_model):
    """Legacy names tflite-ssd/tf-ssd and ov-face-detection resolve to
    their modern equivalents (reference bb_modes[],
    tensordec-boundingbox.c:157-166)."""
    frame = np.zeros((4,), np.uint8)
    new = _run_pipe(postproc_model,
                    "bounding_boxes option1=mobilenet-ssd-postprocess "
                    "option3=0.5 option7=meta", frame, fuse=False)
    old = _run_pipe(postproc_model,
                    "bounding_boxes option1=tf-ssd option3=0.5 option7=meta",
                    frame, fuse=False)
    assert [_det_key(d) for d in new.meta["detections"]] == \
        [_det_key(d) for d in old.meta["detections"]]


def test_pose_batched_heatmaps_all_frames_decoded():
    """[B,H,W,K] heatmaps (mux'd multi-stream invoke) yield per-frame
    keypoints — no silent truncation to frame 0."""
    from nnstreamer_tpu.decoders.pose_estimation import PoseEstimation
    from nnstreamer_tpu.tensors.buffer import TensorBuffer

    B, H, W, K = 3, 8, 8, 2
    heat = np.zeros((B, H, W, K), np.float32)
    peaks = [(1, 2), (4, 5), (6, 0)]
    for b, (y, x) in enumerate(peaks):
        heat[b, y, x, :] = 5.0
    dec = PoseEstimation()
    out = dec.decode(TensorBuffer([heat]), None, {"option2": "meta"})
    kps = out.meta["keypoints"]
    assert len(kps) == B and all(len(fr) == K for fr in kps)
    for b, (y, x) in enumerate(peaks):
        assert abs(kps[b][0]["y"] - y / (H - 1)) < 1e-6
        assert abs(kps[b][0]["x"] - x / (W - 1)) < 1e-6
    assert np.asarray(out[0]).shape == (B, K, 3)

    # device kernel path agrees
    _, fn = dec.device_kernel({"option2": "meta"})
    import jax.numpy as jnp

    (rows,) = fn(None, [jnp.asarray(heat)])
    assert rows.shape == (B, K, 3)
    finalized = dec.host_finalize(
        TensorBuffer([np.asarray(rows)]), None, {"option2": "meta"})
    assert len(finalized.meta["keypoints"]) == B
