"""Fleet launcher tests (serving/fleet.py): replica supervision, the
balanced client against real replica *processes*, crash restart, and
the rolling-restart continuity contract (checkpoint → kill → restore,
zero double-invokes via the restored dedup windows).
"""

import time

import numpy as np
import pytest

from nnstreamer_tpu.registry import ELEMENT, get_subplugin
from nnstreamer_tpu.serving.fleet import FleetLauncher
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def _fleet_invokes(fleet):
    """All (instance:req_id) witness lines across the fleet's replica
    logs — each line is one actual worker invoke."""
    lines = []
    for i in range(fleet.replicas):
        p = fleet.state_dir / f"replica{i}" / "invokes.log"
        if p.exists():
            lines.extend(p.read_text().splitlines())
    return lines


def _client_for(fleet, operation, window=8):
    Client = get_subplugin(ELEMENT, "tensor_query_client")
    cl = Client(operation=operation, broker_port=fleet.broker_port,
                reliable=True, balance="shortest-slack",
                max_in_flight=window, timeout=5.0,
                discovery_stale_s=5.0)
    outs = []
    cl.srcpad.push = lambda b: outs.append(b)
    return cl, outs


def _send_range(cl, lo, hi):
    for i in range(lo, hi):
        cl.chain(cl.sinkpad, TensorBuffer(
            [np.full((4,), i, dtype=np.float32)], pts=i))


class TestFleetLauncher:
    def test_round_trip_balanced_exactly_once(self):
        fleet = FleetLauncher(replicas=2, operation="tf-rt", spin_ms=1.0,
                              log_invokes=True).start()
        try:
            eps = fleet.endpoints(timeout=20.0)
            assert len(eps) == 2
            assert fleet.replicas_up() == 2
            cl, outs = _client_for(fleet, "tf-rt")
            try:
                _send_range(cl, 0, 40)
                cl.handle_eos()
            finally:
                cl.stop()
            assert len(outs) == 40
            # in-order, byte-identical (echo doubles each value)
            assert [int(o.to_host().tensors[0][0]) for o in outs] == \
                [2 * i for i in range(40)]
            invokes = _fleet_invokes(fleet)
            assert len(invokes) == 40
            assert len(set(invokes)) == 40  # zero double-invokes
        finally:
            fleet.stop()

    def test_crash_restart_supervision(self):
        fleet = FleetLauncher(replicas=2, operation="tf-crash",
                              spin_ms=1.0).start()
        try:
            fleet.endpoints(timeout=20.0)
            fleet.kill_replica(0, graceful=False)
            assert fleet.replicas_up() == 1
            deadline = time.monotonic() + 20.0
            while fleet.replicas_up() < 2:
                assert time.monotonic() < deadline, \
                    "supervisor never relaunched the crashed replica"
                time.sleep(0.1)
        finally:
            fleet.stop()

    def test_rolling_restart_exactly_once(self):
        """The deploy contract: frames streamed across a rolling
        restart all arrive, in order, with every request invoked
        exactly once — the SIGTERM checkpoint carries each replica's
        dedup windows over to its successor (stable base_port keeps
        the endpoints, so the client's sticky reconnect replays into
        the restored windows)."""
        import socket as _socket

        with _socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            base = probe.getsockname()[1] + 1000
        fleet = FleetLauncher(replicas=2, operation="tf-roll",
                              spin_ms=1.0, base_port=base,
                              log_invokes=True).start()
        try:
            fleet.endpoints(timeout=20.0)
            cl, outs = _client_for(fleet, "tf-roll", window=4)
            try:
                _send_range(cl, 0, 30)
                fleet.rolling_restart()
                _send_range(cl, 30, 60)
                cl.handle_eos()
            finally:
                cl.stop()
            assert len(outs) == 60
            assert [int(o.to_host().tensors[0][0]) for o in outs] == \
                [2 * i for i in range(60)]
            invokes = _fleet_invokes(fleet)
            assert len(set(invokes)) == len(invokes) == 60
        finally:
            fleet.stop()

    def test_replicas_validate(self):
        with pytest.raises(ValueError):
            FleetLauncher(replicas=0)
