"""Static gates — the reference's per-PR CI checks, in-tree.

The reference gates every PR on clang-format, cppcheck, and a doxygen
header audit (/root/reference/.TAOS-CI/config/
config-plugins-prebuild.sh:34-78). Equivalents here, runnable as plain
pytest so `python -m pytest tests/` IS the CI:

- every module byte-compiles (syntax gate);
- every module and public element/builder carries a docstring (the
  doxygen-tag audit);
- no stray debugging artifacts (pdb traces, print() in the hot paths of
  library code — logging goes through log.py);
- the project's own static analyzer comes back clean: ``nns-lint --self``
  (monotonic clocks, no blocking under locks, explicit thread daemonism,
  metric naming — docs/linting.md) reports zero findings, and every
  pipeline description shipped in examples/ and the docs passes the
  static verifier with no error-severity diagnostics.
"""

import ast
import pathlib
import py_compile

import pytest

PKG = pathlib.Path(__file__).resolve().parent.parent / "nnstreamer_tpu"
MODULES = sorted(PKG.rglob("*.py"))


def test_package_has_expected_shape():
    assert len(MODULES) > 60  # sanity: the glob found the real package


@pytest.mark.parametrize("path", MODULES, ids=lambda p: str(
    p.relative_to(PKG)))
def test_module_compiles_and_documented(path, tmp_path):
    py_compile.compile(str(path), cfile=str(tmp_path / "c.pyc"),
                       doraise=True)
    tree = ast.parse(path.read_text())
    # the reference audits FILE-level doxyen tags (@file/@brief etc.,
    # config-plugins-prebuild.sh) — the analog is the module docstring,
    # which here carries the component's design rationale and reference
    # file:line citations
    assert ast.get_docstring(tree), f"{path} lacks a module docstring"


def test_no_debug_artifacts():
    offenders = []
    for path in MODULES:
        text = path.read_text()
        if "pdb.set_trace" in text or "breakpoint()" in text:
            offenders.append(str(path))
    assert not offenders, offenders


def _print_calls(tree):
    class V(ast.NodeVisitor):
        def __init__(self):
            self.hits, self._in_main = [], 0

        def visit_FunctionDef(self, node):
            bump = node.name == "main"  # CLI entry points may print
            self._in_main += bump
            self.generic_visit(node)
            self._in_main -= bump

        def visit_Call(self, node):
            if (not self._in_main and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                self.hits.append(node.lineno)
            self.generic_visit(node)

    v = V()
    v.visit(tree)
    return v.hits


def test_no_stray_prints_in_library_code():
    """Library output goes through log.py; print() is reserved for CLI
    surfaces (cli.py, `main()` entry points)."""
    offenders = []
    for path in MODULES:
        if path.name == "cli.py":
            continue
        for lineno in _print_calls(ast.parse(path.read_text())):
            offenders.append(f"{path}:{lineno}")
    assert not offenders, offenders


def test_self_lint_clean():
    """`nns-lint --self` gate: the NNS1xx AST rules report nothing on
    the package itself (any deliberate exception carries a justified
    pragma, which the linter verifies via NNS199)."""
    from nnstreamer_tpu.analysis.astlint import lint_tree

    diags = lint_tree(PKG)
    assert not diags, "\n".join(d.render() for d in diags)


def test_concurrency_lint_clean():
    """`nns-lint --concurrency` gate: the whole-program NNS2xx pass
    (guarded attributes, lock ordering, check-then-act, foreign calls
    under lock) reports zero unsuppressed findings on the tree, and the
    static lock-ordering graph it exports is non-trivial (the runtime
    witness cross-checks against it, so an accidentally-empty graph
    would turn that check into a no-op)."""
    from nnstreamer_tpu.analysis.concurrency import (
        lint_concurrency,
        static_lock_graph,
    )

    diags = lint_concurrency(PKG)
    assert not diags, "\n".join(d.render() for d in diags)
    graph = static_lock_graph(PKG)
    assert len(graph["sites"]) >= 20   # the lock census is ~35+ locks
    assert graph["nodes"]


def test_shipped_pipelines_verify():
    """Every pipeline description shipped in examples/ and the
    getting-started doc passes the static verifier with no
    error-severity diagnostics (warnings are allowed — e.g. the
    recurrence examples tee into a reposink without a queue, which is
    deliberate)."""
    from nnstreamer_tpu.analysis.diagnostics import ERROR
    from nnstreamer_tpu.analysis.extract import extract_from_file
    from nnstreamer_tpu.analysis.verify import verify_description

    root = PKG.parent
    targets = sorted((root / "examples").glob("*.py"))
    targets.append(root / "docs" / "getting-started.md")
    snippets = [s for t in targets for s in extract_from_file(t)]
    assert len(snippets) >= 5  # the extractor actually found the demos
    errors = []
    for snip in snippets:
        for d in verify_description(snip.description,
                                    source=f"{snip.source}:{snip.line}"):
            if d.severity == ERROR:
                errors.append(d.render())
    assert not errors, "\n".join(errors)
