"""Unit tests for the L1 tensor type system (reference: unittest_common's
caps/config coverage, tests/unittest_common.cc)."""

import numpy as np
import pytest

from nnstreamer_tpu.tensors.types import (
    Fraction,
    TensorFormat,
    TensorInfo,
    TensorsConfig,
    TensorsInfo,
    TensorType,
    NNS_TENSOR_SIZE_LIMIT,
)
from nnstreamer_tpu.tensors.meta import (
    HEADER_SIZE,
    TensorMetaInfo,
    pack_tensor,
    unpack_tensor,
)
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors import data as tdata


class TestTensorType:
    def test_all_dtypes_roundtrip_numpy(self):
        for t in TensorType:
            assert TensorType.from_any(t.np_dtype) is t

    def test_sizes(self):
        assert TensorType.UINT8.size == 1
        assert TensorType.FLOAT32.size == 4
        assert TensorType.BFLOAT16.size == 2
        assert TensorType.FLOAT64.size == 8

    def test_from_string(self):
        assert TensorType.from_any("float32") is TensorType.FLOAT32
        assert TensorType.from_any("UINT8") is TensorType.UINT8


class TestTensorInfo:
    def test_dim_vs_shape_reversal(self):
        # NNStreamer dim C:W:H:N == numpy shape (N,H,W,C)
        info = TensorInfo.from_str("3:224:224:1", "uint8")
        assert info.shape == (1, 224, 224, 3)
        assert info.size == 3 * 224 * 224

    def test_from_array(self):
        a = np.zeros((1, 224, 224, 3), np.uint8)
        info = TensorInfo.from_array(a)
        assert info.dim == (3, 224, 224, 1)
        assert info.type is TensorType.UINT8

    def test_equality_mod_trailing_ones(self):
        a = TensorInfo.from_str("3:224:224:1", "uint8")
        b = TensorInfo.from_str("3:224:224", "uint8")
        assert a.is_equal(b)
        c = TensorInfo.from_str("3:224:225", "uint8")
        assert not a.is_equal(c)

    def test_invalid_dims(self):
        with pytest.raises(ValueError):
            TensorInfo.from_str("0:2", "uint8")
        with pytest.raises(ValueError):
            TensorInfo.from_str(":".join(["2"] * 9), "uint8")


class TestTensorsInfo:
    def test_parse_multi(self):
        ti = TensorsInfo.from_str("3:224:224:1,1001:1", "uint8,float32")
        assert ti.num_tensors == 2
        assert ti.dims_str() == "3:224:224:1,1001:1"
        assert ti.types_str() == "uint8,float32"

    def test_limit(self):
        with pytest.raises(ValueError):
            TensorsInfo([TensorInfo((1,), "uint8")] * (NNS_TENSOR_SIZE_LIMIT + 1))

    def test_mismatched_counts(self):
        with pytest.raises(ValueError):
            TensorsInfo.from_str("3:4,5:6", "uint8")


class TestTensorsConfig:
    def test_caps_roundtrip(self):
        cfg = TensorsConfig(
            info=TensorsInfo.from_str("3:224:224:1", "uint8"),
            rate=Fraction(30, 1),
        )
        caps = cfg.to_caps()
        back = TensorsConfig.from_caps(caps)
        assert back.is_equal(cfg)
        assert back.rate.fps == 30.0

    def test_flexible_always_valid(self):
        cfg = TensorsConfig(format=TensorFormat.FLEXIBLE)
        assert cfg.is_valid()
        assert not TensorsConfig().is_valid()  # static w/o info


class TestMetaHeader:
    def test_pack_unpack(self):
        m = TensorMetaInfo(TensorType.FLOAT32, (3, 224, 224),
                           TensorFormat.FLEXIBLE)
        m2 = TensorMetaInfo.unpack(m.pack())
        assert m2.type is TensorType.FLOAT32
        assert m2.dim == (3, 224, 224)
        assert m2.format is TensorFormat.FLEXIBLE

    def test_tensor_roundtrip(self, rng):
        a = rng.standard_normal((2, 3, 4)).astype(np.float32)
        blob = pack_tensor(a)
        assert len(blob) == HEADER_SIZE + a.nbytes
        b, end = unpack_tensor(blob)
        assert end == len(blob)
        np.testing.assert_array_equal(a, b)

    def test_bad_magic(self):
        with pytest.raises(ValueError):
            TensorMetaInfo.unpack(b"\x00" * HEADER_SIZE)


class TestTensorBuffer:
    def test_basic(self, rng):
        a = rng.standard_normal((4, 4)).astype(np.float32)
        buf = TensorBuffer.from_arrays([a], pts=123)
        assert buf.num_tensors == 1
        assert buf.pts == 123
        assert not buf.on_device()
        assert buf.nbytes() == a.nbytes

    def test_replace_does_not_alias_meta(self):
        buf = TensorBuffer(tensors=[np.zeros(3)], meta={"k": 1})
        b2 = buf.replace(pts=5)
        b2.meta["k"] = 2
        assert buf.meta["k"] == 1
        assert b2.pts == 5 and buf.pts is None

    def test_device_roundtrip(self):
        import jax

        buf = TensorBuffer(tensors=[np.arange(8, dtype=np.float32)])
        dev = buf.to_device()
        assert dev.on_device()
        host = dev.to_host()
        np.testing.assert_array_equal(host[0], buf[0])


class TestTypedData:
    def test_saturating_typecast(self):
        a = np.array([300.0, -300.0, 5.5])
        out = tdata.typecast(a, TensorType.UINT8)
        assert out.dtype == np.uint8
        assert list(out) == [255, 0, 5]

    def test_average(self):
        assert tdata.average(np.array([1, 2, 3], np.int8)) == 2.0
