"""Fault-injection sweep for the non-serving paths (VERDICT r3 item 9).

The serving engine has ``_recover``; these chaos tests pin down what the
OTHER paths guarantee when things break mid-stream — per-element
recovery semantics documented in ``docs/recovery.md``:

- a dispatch failure inside a fused XLA region surfaces on the bus as a
  pipeline error at the materialization point (never a hang, never a
  silent drop of the error), with pre-failure frames delivered;
- a query server killed mid-stream: the sync client (max-in-flight=1)
  transparently reconnects down its server list and RESENDS the current
  frame (zero loss); the pipelined client drops the in-flight window,
  counts the loss, and continues on the next server;
- a wedged tensor_repo loop (producer died, slot never refills) fails
  via the reposrc timeout with a bus error naming the element, and the
  slot is reusable after reseeding.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.pipeline.pipeline import FlowError


class TestFusedRegionDispatchFailure:
    def test_runtime_failure_reaches_bus_not_hang(self):
        """An XLA runtime failure (io_callback raising inside the jitted
        region — the shape of a device-side abort) must surface as a bus
        error when the deferred result materializes; buffers computed
        before the failure are delivered."""
        import jax
        import jax.numpy as jnp
        from jax.experimental import io_callback

        from nnstreamer_tpu.filters.jax_backend import register_jax_model

        calls = {"n": 0}

        def boom(x):
            calls["n"] += 1
            if calls["n"] > 3:
                raise RuntimeError("injected dispatch failure")
            return x

        def fn(x):
            y = io_callback(boom, jax.ShapeDtypeStruct(x.shape, x.dtype),
                            x)
            return (y.astype(jnp.float32) * 2.0,)

        register_jax_model("chaos_fused", fn, None)
        pipe = parse_launch(
            "videotestsrc num-buffers=8 width=4 height=4 ! "
            "tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_filter framework=jax model=chaos_fused name=filter ! "
            "queue max-size-buffers=8 materialize-host=true ! "
            "tensor_sink name=out to-host=true")
        outs = []
        pipe.get("out").connect(lambda b: outs.append(b))
        with pytest.raises(FlowError, match="injected|callback|CpuCallback"):
            pipe.run(timeout=120)
        # pre-failure frames made it through before the abort
        assert 1 <= len(outs) <= 4


class TestQueryServerKilledMidStream:
    def _server(self, pair_id: int):
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("4", "float32")
        register_custom_easy("chaos_pass",
                            lambda ins: [np.asarray(ins[0])], info, info)
        # distinct `id` per server pipeline: serversrc/serversink pair
        # through it (reference id property) — two pairs on id=0 would
        # cross-deliver
        srv = parse_launch(
            f"tensor_query_serversrc name=ssrc port=0 id={pair_id} ! "
            "tensor_filter framework=custom-easy model=chaos_pass ! "
            f"tensor_query_serversink id={pair_id}")
        srv.start()
        return srv, srv.get("ssrc").port

    def test_sync_client_fails_over_with_resend(self):
        """Kill the connected server between frames: the max-in-flight=1
        client reconnects down its list and resends — every frame gets a
        result, zero loss."""
        from nnstreamer_tpu.elements.sink import TensorSink
        from nnstreamer_tpu.elements.source import AppSrc

        s1, p1 = self._server(11)
        s2, p2 = self._server(12)
        client = parse_launch(
            "tensor_query_client name=c "
            f"servers=127.0.0.1:{p1},127.0.0.1:{p2} timeout=5 max-retry=2")
        src, sink = AppSrc(name="src"), TensorSink(name="out")
        client.add(src, sink)
        src.link(client.get("c"))
        client.get("c").link(sink)
        client.start()
        try:
            src.push([np.full(4, 1, np.float32)], pts=0)
            deadline = time.monotonic() + 20
            while not sink.buffers and time.monotonic() < deadline:
                time.sleep(0.02)
            assert len(sink.buffers) == 1
            s1.stop()  # the connected server dies mid-stream
            src.push([np.full(4, 2, np.float32)], pts=1)
            src.push([np.full(4, 3, np.float32)], pts=2)
            src.end_of_stream()
            msg = client.wait(timeout=30)
            assert msg is not None and msg.kind == "eos", str(msg)
            # zero loss: the frame in flight when the link died was
            # resent to the next server
            assert len(sink.buffers) == 3
            np.testing.assert_array_equal(sink.buffers[1][0],
                                          np.full(4, 2, np.float32))
        finally:
            client.stop()
            s2.stop()
            try:
                s1.stop()
            except Exception:  # noqa: BLE001 — already stopped
                pass

    def test_pipelined_client_drops_window_and_continues(self):
        """Pipelined mode (max-in-flight>1): frames in flight when the
        server dies are dropped and COUNTED; the stream continues on the
        surviving server and still ends in clean EOS."""
        from nnstreamer_tpu.elements.sink import TensorSink
        from nnstreamer_tpu.elements.source import AppSrc

        s1, p1 = self._server(13)
        s2, p2 = self._server(14)
        client = parse_launch(
            "tensor_query_client name=c "
            f"servers=127.0.0.1:{p1},127.0.0.1:{p2} timeout=5 "
            "max-retry=2 max-in-flight=4")
        src, sink = AppSrc(name="src"), TensorSink(name="out")
        client.add(src, sink)
        src.link(client.get("c"))
        client.get("c").link(sink)
        client.start()
        try:
            src.push([np.full(4, 1, np.float32)], pts=0)
            deadline = time.monotonic() + 20
            while not sink.buffers and time.monotonic() < deadline:
                time.sleep(0.02)
            s1.stop()
            for i in range(2, 8):
                src.push([np.full(4, i, np.float32)], pts=i)
            src.end_of_stream()
            msg = client.wait(timeout=30)
            assert msg is not None and msg.kind == "eos", str(msg)
            dropped = int(client.get("c").get_property("frames_dropped"))
            assert len(sink.buffers) + dropped == 7
        finally:
            client.stop()
            s2.stop()


class TestWedgedRepoLoop:
    def test_wedged_loop_times_out_with_bus_error_then_recovers(self):
        """A repo loop whose producer died (slot never refills) must not
        hang: reposrc's timeout posts a bus error naming the element.
        After reseeding the slot, the loop runs again."""
        from nnstreamer_tpu.elements.repo import GLOBAL_REPO
        from nnstreamer_tpu.tensors.buffer import TensorBuffer

        GLOBAL_REPO.set("chaos_slot", TensorBuffer(
            [np.zeros(4, np.float32)], pts=0))
        # sink only — nothing writes the slot back, so iteration 2 wedges
        pipe = parse_launch(
            "tensor_reposrc slot=chaos_slot num-buffers=3 timeout=0.5 ! "
            "tensor_sink name=out")
        with pytest.raises(FlowError, match="chaos_slot|timeout|repo"):
            pipe.run(timeout=30)

        # recovery: reseed and run a healthy loop on the SAME slot
        GLOBAL_REPO.set("chaos_slot", TensorBuffer(
            [np.zeros(4, np.float32)], pts=0))
        pipe2 = parse_launch(
            "tensor_reposrc slot=chaos_slot num-buffers=3 timeout=5 ! "
            "tee name=t  t. ! tensor_reposink slot=chaos_slot  "
            "t. ! tensor_sink name=out")
        msg = pipe2.run(timeout=30)
        assert msg is not None and msg.kind == "eos", str(msg)
        assert len(pipe2.get("out").buffers) == 3
