"""Perf smoke (CI job `perf-smoke`): the overlap layer must be free.

Run explicitly — `python -m pytest tests/perf_smoke.py` — against a tiny
CPU pipeline (the EdgeTPU `device_type:dummy` pattern). Gates:

- enabling the dispatch window (`inflight=2`) changes NOTHING observable:
  same fused-region count, same region re-trace count
  (``nns_fuse_retraces_total`` — each re-trace is one XLA compile), and
  byte-identical per-frame outputs in the same order;
- the metrics endpoint exports the overlap series
  (``nns_filter_inflight``, ``nns_filter_fence_wait_seconds``,
  ``nns_pool_*``, ``nns_queue_drain_size``) and the residency series
  (``nns_transfer_h2d_bytes_total``, ``nns_transfer_d2h_bytes_total``,
  ``nns_buffer_resident_ratio``);
- the device-resident tensor plane keeps the smoke pipeline's D2H
  traffic at its floor: at most one materialization per sink-delivered
  frame (``d2h_per_frame`` ≤ number of sinks);
- the whole-graph steady state batches transfers: staged multi-frame
  slab uploads (``nns_transfer_batched_h2d_total``), grouped result
  fetches, and ZERO per-frame D2H events on the golden pipeline;
- parallel ingest lanes (`--lanes`, pipeline/lanes.py) are correct AND
  profitable: ``lanes=2`` reproduces the serial run byte-for-byte in the
  same order while exporting the ``nns_lane_*`` series, and on a
  blocking-bound ingest segment 4 lanes beat 1 lane by >1.3× (the
  overlap gate is deliberately built on GIL-releasing blocking work so
  it holds on any host core count, including single-vCPU runners —
  CPU-bound numpy scaling depends on cores the gate can't assume);
- the always-on flight recorder (obs/flight.py) exports its streaming
  ``nns_stage_p50_ms``/``nns_stage_p99_ms`` gauges through BOTH
  ``/metrics`` and ``/metrics.json``, and costs <2% fps on a
  blocking-bound pipeline (median-of-3 vs ``NNSTPU_FLIGHT=0``).
"""

import re
import time
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.pipeline.element import Element
from nnstreamer_tpu.filters.jax_backend import (
    is_jax_model_registered,
    register_jax_model,
)
from nnstreamer_tpu.tensors.buffer import transfer_snapshot

DESC = (
    "videotestsrc pattern=ball num-buffers=12 width=16 height=16 ! "
    "tensor_converter ! "
    "tensor_aggregator frames-in=1 frames-out=4 frames-flush=4 "
    "frames-dim=3 concat=true ! "
    "queue max-size-buffers=4 prefetch-device=true ! "
    "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
    "tensor_filter framework=jax model=perf_smoke_sum name=filter "
    "inflight={k} ! "
    "queue max-size-buffers=8 materialize-host=true ! "
    "tensor_sink name=sink to-host=true"
)


def _register_model():
    import jax.numpy as jnp

    if not is_jax_model_registered("perf_smoke_sum"):
        register_jax_model(
            "perf_smoke_sum",
            lambda x: (jnp.sum(x, axis=(1, 2, 3))[:, None],),
            None)


def _retraces_total() -> float:
    """Sum of every ``nns_fuse_retraces_total`` series in the registry —
    label-agnostic, so run-to-run deltas are comparable."""
    from nnstreamer_tpu.obs import get_registry

    text = get_registry().render_prometheus()
    total = 0.0
    for line in text.splitlines():
        m = re.match(r"nns_fuse_retraces_total\{[^}]*\}\s+(\S+)", line)
        if m:
            total += float(m.group(1))
    return total


def _run(inflight: int, lanes: int = 1):
    _register_model()
    pipe = parse_launch(DESC.format(k=inflight), lanes=lanes)
    msg = pipe.run(timeout=120)
    assert msg is not None and msg.kind == "eos", msg
    outs = [np.asarray(b.tensors[0]).copy()
            for b in pipe.get("sink").buffers]
    return pipe, outs


def test_inflight_window_is_observably_free():
    r0 = _retraces_total()
    pipe1, out1 = _run(inflight=1)
    r1 = _retraces_total()
    pipe2, out2 = _run(inflight=2)
    r2 = _retraces_total()

    # same topology decisions: fused-region count unchanged
    n_regions1 = len(pipe1._regions or [])
    n_regions2 = len(pipe2._regions or [])
    assert n_regions1 == n_regions2 and n_regions1 >= 1

    # no extra XLA compiles: each run re-traces its fresh region the same
    # number of times; inflight=2 must not add any
    assert (r1 - r0) == (r2 - r1) > 0

    # byte-identical per-frame outputs, same order
    assert len(out1) == len(out2) == 3  # 12 frames / batch 4
    for a, b in zip(out1, out2):
        assert a.shape == b.shape and a.dtype == b.dtype
        assert a.tobytes() == b.tobytes()


def test_metrics_endpoint_exports_overlap_series(monkeypatch):
    from nnstreamer_tpu.obs import MetricsServer

    monkeypatch.delenv("NNSTPU_FLIGHT", raising=False)
    _pipe, outs = _run(inflight=2)
    assert outs
    srv = MetricsServer(port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            body = r.read().decode()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics.json",
                timeout=10) as r:
            blob = r.read().decode()
    finally:
        srv.stop()
    for series in ("nns_filter_inflight",
                   "nns_filter_fence_wait_seconds",
                   "nns_pool_hits_total",
                   "nns_pool_misses_total",
                   "nns_pool_bytes_held",
                   "nns_queue_drain_size",
                   "nns_fuse_retraces_total",
                   "nns_fuse_whole_graph",
                   "nns_transfer_h2d_bytes_total",
                   "nns_transfer_d2h_bytes_total",
                   "nns_transfer_batched_h2d_total",
                   "nns_transfer_batched_d2h_total",
                   "nns_buffer_resident_ratio"):
        assert series in body, f"{series} missing from /metrics"
    # the flight recorder's streaming SLO gauges ride the same registry:
    # both the Prometheus text and the JSON snapshot must carry them
    # (the always-on recorder installs whenever no trace timeline is
    # active, so the run above fed them)
    for series in ("nns_stage_p50_ms", "nns_stage_p99_ms"):
        assert series in body, f"{series} missing from /metrics"
        assert series in blob, f"{series} missing from /metrics.json"


def test_d2h_per_frame_at_floor():
    """The residency plane's whole point: with every element between the
    upload queue and the sink device-passthrough, the ONLY D2H events a
    run may add are the sink's per-frame materializations — one per
    delivered frame per sink (this pipeline has exactly one sink)."""
    before = transfer_snapshot()
    _pipe, outs = _run(inflight=2)
    after = transfer_snapshot()
    frames = len(outs)
    assert frames == 3
    d2h_per_frame = (after["d2h_events"] - before["d2h_events"]) / frames
    assert d2h_per_frame <= 1.0, d2h_per_frame
    # and the run actually exercised the resident path
    assert after["resident_entries"] > before["resident_entries"]


def test_whole_graph_batched_transfers_and_zero_d2h():
    """The transfer-batching gate (CI `perf-smoke` whole-graph step).

    On the golden device-decodable smoke pipeline the steady state must
    be: per-frame H2D copies coalesced into staged multi-frame slab
    uploads (one ``device_put`` per drained window), sink-bound results
    carried by ONE grouped ``device_get`` per drained run, and — the
    headline number — ZERO per-frame D2H events
    (``d2h_per_frame == 0``; the bench reports the same field).
    Deterministic counter deltas, no timing involved, so no median/MAD
    gating is needed here — raw-value perf comparisons (lanes scaling,
    bench fps) are the ones that gate on the median."""
    before = transfer_snapshot()
    _pipe, outs = _run(inflight=2)
    after = transfer_snapshot()
    assert len(outs) == 3
    # staged multi-frame H2D engaged: the first window's XLA compile
    # backs up the upload queue, so the next drain gathers >= 2 windows
    # and coalesces them into one slab upload
    assert after["h2d_batched_events"] > before["h2d_batched_events"]
    assert after["h2d_batched_frames"] - before["h2d_batched_frames"] >= 2
    # the materialize-host queue fetched results as grouped D2H runs
    assert after["d2h_batched_events"] > before["d2h_batched_events"]
    # the gate itself: not one per-frame D2H round trip in the whole run
    assert after["d2h_events"] == before["d2h_events"]


def test_retrace_counter_keys_on_batch_shape():
    """A second input batch shape (the aggregator's unpadded flush tail
    vs the full window) is a real XLA compile and must be counted as
    exactly ONE re-trace — and alternating between the two shapes
    afterwards must add none (the region reuses one jit object whose
    per-shape executable cache absorbs both; a silent per-frame retrace
    here was the failure mode this counter exists to expose)."""
    _register_model()
    pipe = parse_launch(
        "appsrc name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
        "tensor_filter framework=jax model=perf_smoke_sum name=filter ! "
        "tensor_sink name=sink to-host=true")
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    try:
        assert pipe._regions, "transform+filter run did not fuse"
        full = np.arange(8 * 16 * 16 * 3, dtype=np.uint8).reshape(8, 16, 16, 3)
        tail = full[:4].copy()
        r0 = _retraces_total()
        src.push([full.copy()])
        sink.wait(1)
        r1 = _retraces_total()
        assert r1 - r0 == 1, "first shape: exactly one compile"
        src.push([tail.copy()])
        sink.wait(2)
        r2 = _retraces_total()
        assert r2 - r1 == 1, "tail batch shape: exactly one more compile"
        for _ in range(3):
            src.push([full.copy()])
            src.push([tail.copy()])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        r3 = _retraces_total()
        assert r3 - r2 == 0, "alternating known shapes must not retrace"
        assert len(sink.buffers) == 8
    finally:
        pipe.stop()


def test_lanes_byte_identical_and_series_exported():
    """Ingest lanes on the full smoke pipeline: ``lanes=2`` must change
    nothing observable about the outputs (byte-identical frames, same
    order — the tentpole's correctness contract) while the lane
    telemetry appears in the Prometheus exposition."""
    from nnstreamer_tpu.obs import get_registry

    _pipe1, out1 = _run(inflight=1, lanes=1)
    pipe2, out2 = _run(inflight=1, lanes=2)
    # the laned run really spliced an executor over the ingest segment
    assert pipe2._lane_execs, "lanes=2 did not splice an ingest executor"
    assert len(out1) == len(out2) == 3
    for a, b in zip(out1, out2):
        assert a.shape == b.shape and a.dtype == b.dtype
        assert a.tobytes() == b.tobytes()
    body = get_registry().render_prometheus()
    for series in ("nns_lane_occupancy",
                   "nns_ingest_fps",
                   "nns_lane_reorder_stall_seconds"):
        assert series in body, f"{series} missing from registry"


class _BlockingPre(Element):
    """Per-frame blocking preprocessing stand-in (think JPEG decode
    offload or a DMA wait): a fixed GIL-releasing sleep plus a trivial
    transform. Pure function of its input, so lane replication is safe."""

    ELEMENT_NAME = "_perf_blocking_pre"
    REORDER_SAFE = True
    PROPERTIES = {}

    def __init__(self, name=None, delay_s: float = 0.002, **props):
        super().__init__(name, **props)
        self.delay_s = delay_s
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def chain(self, pad, buf):
        time.sleep(self.delay_s)
        return self.srcpads[0].push(
            buf.with_tensors([t.astype(np.float32) for t in buf.tensors]))


@pytest.mark.slow
def test_ingest_scaling_with_lanes():
    """The acceptance gate: on an ingest-bound pipeline, 4 lanes must
    beat 1 lane by >1.3× frames/s (median of 3 runs each — warm-run fps
    spreads past 1.6× on shared runners, so a single-run or best-of
    comparison flakes where the median holds; same rationale as the
    bench's ``fps_median``/``spread_mad`` fields)."""
    from nnstreamer_tpu.elements.sink import FakeSink
    from nnstreamer_tpu.elements.source import VideoTestSrc
    from nnstreamer_tpu.elements.converter import TensorConverter
    from nnstreamer_tpu.pipeline.pipeline import Pipeline

    n_frames = 60

    def fps(lanes: int) -> float:
        pipe = Pipeline(name=f"scaling-l{lanes}", lanes=lanes)
        src = VideoTestSrc(pattern="gradient", num_buffers=n_frames,
                           width=64, height=64)
        conv = TensorConverter()
        pre = _BlockingPre(delay_s=0.005)
        sink = FakeSink(name="sink")
        pipe.add_linked(src, conv, pre, sink)
        t0 = time.monotonic()
        msg = pipe.run(timeout=120)
        dt = time.monotonic() - t0
        assert msg is not None and msg.kind == "eos", msg
        assert sink.count == n_frames, sink.count
        if lanes > 1:
            assert pipe._lane_execs, "segment did not replicate"
        return n_frames / dt

    def median3(lanes: int) -> float:
        return sorted(fps(lanes) for _ in range(3))[1]

    serial = median3(1)
    laned = median3(4)
    assert laned > 1.3 * serial, (serial, laned)


@pytest.mark.slow
def test_flight_recorder_overhead_under_budget(monkeypatch):
    """The always-on acceptance gate: with NNSTPU_FLIGHT unset the
    flight recorder runs on every frame, and its fps cost on a
    realistic (blocking-bound) pipeline must stay under 2%. Measured as
    median-of-3 flight-off vs flight-on on the same sleep-dominated
    workload the lanes gate uses — wall-clock there is pinned by the
    per-frame sleep, so the recorder's per-span cost is the only moving
    part and the 2% budget is a real bound, not scheduler noise."""
    from nnstreamer_tpu.elements.sink import FakeSink
    from nnstreamer_tpu.elements.source import VideoTestSrc
    from nnstreamer_tpu.elements.converter import TensorConverter
    from nnstreamer_tpu.pipeline.pipeline import Pipeline

    n_frames = 60

    def fps() -> float:
        pipe = Pipeline(name="flight-overhead")
        src = VideoTestSrc(pattern="gradient", num_buffers=n_frames,
                           width=32, height=32)
        conv = TensorConverter()
        pre = _BlockingPre(delay_s=0.005)
        sink = FakeSink(name="sink")
        pipe.add_linked(src, conv, pre, sink)
        t0 = time.monotonic()
        msg = pipe.run(timeout=120)
        dt = time.monotonic() - t0
        assert msg is not None and msg.kind == "eos", msg
        assert sink.count == n_frames
        return n_frames / dt

    def median5() -> float:
        fps()  # warm-up: first run pays import/alloc noise
        return sorted(fps() for _ in range(5))[2]

    monkeypatch.setenv("NNSTPU_FLIGHT", "0")
    off = median5()
    monkeypatch.delenv("NNSTPU_FLIGHT")
    on = median5()
    assert on >= 0.98 * off, (off, on)
