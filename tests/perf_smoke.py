"""Perf smoke (CI job `perf-smoke`): the overlap layer must be free.

Run explicitly — `python -m pytest tests/perf_smoke.py` — against a tiny
CPU pipeline (the EdgeTPU `device_type:dummy` pattern). Gates:

- enabling the dispatch window (`inflight=2`) changes NOTHING observable:
  same fused-region count, same region re-trace count
  (``nns_fuse_retraces_total`` — each re-trace is one XLA compile), and
  byte-identical per-frame outputs in the same order;
- the metrics endpoint exports the overlap series
  (``nns_filter_inflight``, ``nns_filter_fence_wait_seconds``,
  ``nns_pool_*``, ``nns_queue_drain_size``) and the residency series
  (``nns_transfer_h2d_bytes_total``, ``nns_transfer_d2h_bytes_total``,
  ``nns_buffer_resident_ratio``);
- the device-resident tensor plane keeps the smoke pipeline's D2H
  traffic at its floor: at most one materialization per sink-delivered
  frame (``d2h_per_frame`` ≤ number of sinks).
"""

import re
import urllib.request

import numpy as np

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.jax_backend import (
    is_jax_model_registered,
    register_jax_model,
)
from nnstreamer_tpu.tensors.buffer import transfer_snapshot

DESC = (
    "videotestsrc pattern=ball num-buffers=12 width=16 height=16 ! "
    "tensor_converter ! "
    "tensor_aggregator frames-in=1 frames-out=4 frames-flush=4 "
    "frames-dim=3 concat=true ! "
    "queue max-size-buffers=4 prefetch-device=true ! "
    "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
    "tensor_filter framework=jax model=perf_smoke_sum name=filter "
    "inflight={k} ! "
    "queue max-size-buffers=8 materialize-host=true ! "
    "tensor_sink name=sink to-host=true"
)


def _register_model():
    import jax.numpy as jnp

    if not is_jax_model_registered("perf_smoke_sum"):
        register_jax_model(
            "perf_smoke_sum",
            lambda x: (jnp.sum(x, axis=(1, 2, 3))[:, None],),
            None)


def _retraces_total() -> float:
    """Sum of every ``nns_fuse_retraces_total`` series in the registry —
    label-agnostic, so run-to-run deltas are comparable."""
    from nnstreamer_tpu.obs import get_registry

    text = get_registry().render_prometheus()
    total = 0.0
    for line in text.splitlines():
        m = re.match(r"nns_fuse_retraces_total\{[^}]*\}\s+(\S+)", line)
        if m:
            total += float(m.group(1))
    return total


def _run(inflight: int):
    _register_model()
    pipe = parse_launch(DESC.format(k=inflight))
    msg = pipe.run(timeout=120)
    assert msg is not None and msg.kind == "eos", msg
    outs = [np.asarray(b.tensors[0]).copy()
            for b in pipe.get("sink").buffers]
    return pipe, outs


def test_inflight_window_is_observably_free():
    r0 = _retraces_total()
    pipe1, out1 = _run(inflight=1)
    r1 = _retraces_total()
    pipe2, out2 = _run(inflight=2)
    r2 = _retraces_total()

    # same topology decisions: fused-region count unchanged
    n_regions1 = len(pipe1._regions or [])
    n_regions2 = len(pipe2._regions or [])
    assert n_regions1 == n_regions2 and n_regions1 >= 1

    # no extra XLA compiles: each run re-traces its fresh region the same
    # number of times; inflight=2 must not add any
    assert (r1 - r0) == (r2 - r1) > 0

    # byte-identical per-frame outputs, same order
    assert len(out1) == len(out2) == 3  # 12 frames / batch 4
    for a, b in zip(out1, out2):
        assert a.shape == b.shape and a.dtype == b.dtype
        assert a.tobytes() == b.tobytes()


def test_metrics_endpoint_exports_overlap_series():
    from nnstreamer_tpu.obs import MetricsServer

    _pipe, outs = _run(inflight=2)
    assert outs
    srv = MetricsServer(port=0).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            body = r.read().decode()
    finally:
        srv.stop()
    for series in ("nns_filter_inflight",
                   "nns_filter_fence_wait_seconds",
                   "nns_pool_hits_total",
                   "nns_pool_misses_total",
                   "nns_queue_drain_size",
                   "nns_fuse_retraces_total",
                   "nns_transfer_h2d_bytes_total",
                   "nns_transfer_d2h_bytes_total",
                   "nns_buffer_resident_ratio"):
        assert series in body, f"{series} missing from /metrics"


def test_d2h_per_frame_at_floor():
    """The residency plane's whole point: with every element between the
    upload queue and the sink device-passthrough, the ONLY D2H events a
    run may add are the sink's per-frame materializations — one per
    delivered frame per sink (this pipeline has exactly one sink)."""
    before = transfer_snapshot()
    _pipe, outs = _run(inflight=2)
    after = transfer_snapshot()
    frames = len(outs)
    assert frames == 3
    d2h_per_frame = (after["d2h_events"] - before["d2h_events"]) / frames
    assert d2h_per_frame <= 1.0, d2h_per_frame
    # and the run actually exercised the resident path
    assert after["resident_entries"] > before["resident_entries"]
