"""tensor_src_iio buffered capture against a mock sysfs tree (reference
tests/nnstreamer_source/unittest_src_iio.cc builds exactly this kind of
fake /sys/bus/iio layout)."""

import os
import struct

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.elements.source import IIOChannel


def test_channel_format_parse():
    ch = IIOChannel("accel_x", 0, "le:s12/16>>4", scale=0.5, offset=1.0)
    assert ch.storage_bytes == 2 and ch.bits == 12 and ch.shift == 4
    # -3 stored as 12-bit two's complement, shifted left 4 in 16-bit word
    word = struct.pack("<H", ((-3) & 0xFFF) << 4)
    out = ch.extract(np.frombuffer(word, np.uint8))
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, [(-3 + 1.0) * 0.5])


def test_channel_format_unsigned_be():
    ch = IIOChannel("light", 1, "be:u10/16>>0")
    word = struct.pack(">H", 1023)
    np.testing.assert_allclose(ch.extract(np.frombuffer(word, np.uint8)),
                               [1023.0])


def _mock_tree(tmp_path, scans, payload=None):
    """Build iio:device0 with two channels: accel_x le:s16/16>>0 scale=0.01
    and accel_y le:s16/16>>0 scale=0.02; device node holds packed scans
    (``payload`` overrides — the kernel packs only *enabled* channels)."""
    base = tmp_path / "sys"
    dev = base / "iio:device0"
    scan = dev / "scan_elements"
    os.makedirs(scan)
    os.makedirs(dev / "buffer")
    (dev / "name").write_text("mock_accel\n")
    (dev / "sampling_frequency").write_text("100\n")
    (dev / "buffer" / "length").write_text("1\n")
    (dev / "buffer" / "enable").write_text("0\n")
    for i, ch in enumerate(("accel_x", "accel_y")):
        (scan / f"in_{ch}_en").write_text("0\n")
        (scan / f"in_{ch}_index").write_text(f"{i}\n")
        (scan / f"in_{ch}_type").write_text("le:s16/16>>0\n")
    (dev / "in_accel_x_scale").write_text("0.01\n")
    (dev / "in_accel_y_scale").write_text("0.02\n")
    node_dir = tmp_path / "dev"
    os.makedirs(node_dir)
    if payload is None:
        payload = b"".join(struct.pack("<hh", x, y) for x, y in scans)
    (node_dir / "iio:device0").write_bytes(payload)
    return str(base), str(node_dir)


def test_iio_device_capture(tmp_path):
    scans = [(100, -200), (300, -400), (500, -600), (700, -800)]
    base, dev = _mock_tree(tmp_path, scans)
    pipe = parse_launch(
        f"tensor_src_iio name=src mode=device device-number=0 "
        f"base-dir={base} dev-dir={dev} buffer-capacity=2 num-buffers=2 ! "
        f"tensor_sink name=out")
    out = pipe.get("out")
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos", msg
    assert len(out.buffers) == 2
    t0 = out.buffers[0].tensors[0]
    assert t0.shape == (2, 2)  # [capacity, channels]
    np.testing.assert_allclose(t0[:, 0], [1.0, 3.0])        # x * 0.01
    np.testing.assert_allclose(t0[:, 1], [-4.0, -8.0])      # y * 0.02
    t1 = out.buffers[1].tensors[0]
    np.testing.assert_allclose(t1[:, 0], [5.0, 7.0])
    # sysfs side effects: channels enabled, buffer configured
    assert (tmp_path / "sys/iio:device0/scan_elements/in_accel_x_en"
            ).read_text() == "1"
    assert (tmp_path / "sys/iio:device0/buffer/length").read_text() == "2"


def test_iio_device_by_name_and_channel_select(tmp_path):
    # only accel_y will be enabled → the node carries y samples alone
    base, dev = _mock_tree(tmp_path, [],
                           payload=struct.pack("<hhh", 20, 20, 20))
    pipe = parse_launch(
        f"tensor_src_iio name=src mode=device device=mock_accel "
        f"base-dir={base} dev-dir={dev} channels=accel_y "
        f"buffer-capacity=1 num-buffers=3 ! tensor_sink name=out")
    out = pipe.get("out")
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos", msg
    assert len(out.buffers) == 3
    assert out.buffers[0].tensors[0].shape == (1, 1)
    np.testing.assert_allclose(out.buffers[0].tensors[0], [[0.4]])
    # the unselected channel was explicitly disabled
    assert (tmp_path / "sys/iio:device0/scan_elements/in_accel_x_en"
            ).read_text() == "0"


def test_iio_kernel_scan_alignment(tmp_path):
    """Mixed-width scans follow the kernel layout: each element aligned to
    its own storage size, scan padded to the widest element (2x s16 accel
    + s64 timestamp → ts at offset 8, scan size 16)."""
    base = tmp_path / "sys"
    dev = base / "iio:device0"
    scan = dev / "scan_elements"
    os.makedirs(scan)
    os.makedirs(dev / "buffer")
    (dev / "name").write_text("mixed\n")
    for i, (ch, fmt) in enumerate((("accel_x", "le:s16/16>>0"),
                                   ("accel_y", "le:s16/16>>0"),
                                   ("timestamp", "le:s64/64>>0"))):
        (scan / f"in_{ch}_en").write_text("0\n")
        (scan / f"in_{ch}_index").write_text(f"{i}\n")
        (scan / f"in_{ch}_type").write_text(f"{fmt}\n")
    node_dir = tmp_path / "dev"
    os.makedirs(node_dir)
    # scan: s16 s16 [4B pad] s64  → 16 bytes
    payload = b"".join(
        struct.pack("<hh4xq", 10 * i, -10 * i, 10 ** 12 + i)
        for i in range(3))
    (node_dir / "iio:device0").write_bytes(payload)
    pipe = parse_launch(
        f"tensor_src_iio mode=device device-number=0 base-dir={base} "
        f"dev-dir={node_dir} buffer-capacity=3 num-buffers=1 ! "
        f"tensor_sink name=out")
    out = pipe.get("out")
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos", msg
    t = out.buffers[0].tensors[0]
    assert t.shape == (3, 3)
    np.testing.assert_allclose(t[:, 0], [0.0, 10.0, 20.0])
    np.testing.assert_allclose(t[:, 1], [0.0, -10.0, -20.0])
    np.testing.assert_allclose(t[:, 2], [1e12, 1e12 + 1, 1e12 + 2])


def test_iio_numeric_channel_count_device_mode(tmp_path):
    """channels=<int> keeps the original contract: first N by index."""
    base, dev = _mock_tree(tmp_path, [],
                           payload=struct.pack("<hh", 5, 7))
    pipe = parse_launch(
        f"tensor_src_iio mode=device device-number=0 base-dir={base} "
        f"dev-dir={dev} channels=1 buffer-capacity=1 num-buffers=1 ! "
        f"tensor_sink name=out")
    out = pipe.get("out")
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos", msg
    # only accel_x enabled → scan is one s16; 5 * 0.01
    np.testing.assert_allclose(out.buffers[0].tensors[0], [[0.05]])
    assert (tmp_path / "sys/iio:device0/scan_elements/in_accel_y_en"
            ).read_text() == "0"


def test_iio_mock_mode_still_works():
    pipe = parse_launch(
        "tensor_src_iio mode=mock channels=3 buffer-capacity=4 "
        "num-buffers=2 ! tensor_sink name=out")
    out = pipe.get("out")
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos"
    assert out.buffers[0].tensors[0].shape == (4, 3)


class TestMalformedSysfs:
    """Negative coverage for mode=device against broken sysfs trees —
    each malformation must fail with a pointed error at start(), never
    a hang or a silently wrong tensor (VERDICT r3 weak item 7)."""

    def _pipe(self, base, dev):
        return parse_launch(
            f"tensor_src_iio mode=device device-number=0 base-dir={base} "
            f"dev-dir={dev} buffer-capacity=2 num-buffers=2 ! "
            "tensor_sink name=out")

    def test_missing_device_dir(self, tmp_path):
        pipe = parse_launch(
            f"tensor_src_iio mode=device device-number=3 "
            f"base-dir={tmp_path} dev-dir={tmp_path} num-buffers=1 ! "
            "tensor_sink name=out")
        with pytest.raises(Exception, match="iio:device3|not found"):
            pipe.start()
        pipe.stop()

    def test_garbage_type_descriptor(self, tmp_path):
        base, dev = _mock_tree(tmp_path, [(1, 2)])
        scan = os.path.join(base, "iio:device0", "scan_elements")
        with open(os.path.join(scan, "in_accel_x_type"), "w") as f:
            f.write("not-a-descriptor\n")
        pipe = self._pipe(base, dev)
        with pytest.raises(Exception, match="type|descriptor|format"):
            pipe.start()
        pipe.stop()

    def test_non_numeric_index(self, tmp_path):
        base, dev = _mock_tree(tmp_path, [(1, 2)])
        scan = os.path.join(base, "iio:device0", "scan_elements")
        with open(os.path.join(scan, "in_accel_y_index"), "w") as f:
            f.write("banana\n")
        pipe = self._pipe(base, dev)
        with pytest.raises(Exception, match="banana|invalid literal|index"):
            pipe.start()
        pipe.stop()

    def test_non_numeric_scale(self, tmp_path):
        base, dev = _mock_tree(tmp_path, [(1, 2)])
        with open(os.path.join(base, "iio:device0",
                               "in_accel_x_scale"), "w") as f:
            f.write("abc\n")
        pipe = self._pipe(base, dev)
        with pytest.raises(Exception, match="abc|could not convert|scale"):
            pipe.start()
        pipe.stop()

    def test_channel_selection_matches_nothing(self, tmp_path):
        base, dev = _mock_tree(tmp_path, [(1, 2)])
        pipe = parse_launch(
            f"tensor_src_iio mode=device device-number=0 base-dir={base} "
            f"dev-dir={dev} channels=gyro_z num-buffers=1 ! "
            "tensor_sink name=out")
        with pytest.raises(Exception, match="no scan channels"):
            pipe.start()
        pipe.stop()

    def test_missing_scan_elements_dir(self, tmp_path):
        base, dev = _mock_tree(tmp_path, [(1, 2)])
        import shutil

        shutil.rmtree(os.path.join(base, "iio:device0", "scan_elements"))
        pipe = self._pipe(base, dev)
        with pytest.raises(Exception, match="no scan channels"):
            pipe.start()
        pipe.stop()

    def test_truncated_device_node(self, tmp_path):
        """Device node holds two full scans plus a fragment (capacity 2
        → the first buffer completes, the trailing fragment cannot):
        exactly one full-shaped buffer arrives, then EOS — never a hang,
        never a padded/garbage partial tensor."""
        full = (struct.pack("<hh", 100, -200) +
                struct.pack("<hh", 300, -400))
        base, dev = _mock_tree(tmp_path, [], payload=full + full[:3])
        pipe = self._pipe(base, dev)
        outs = []
        pipe.get("out").connect(lambda b: outs.append(b))
        msg = pipe.run(timeout=30)
        assert msg is not None  # completed, no hang
        assert len(outs) == 1  # the fragment never became a tensor
        arr = np.asarray(outs[0].tensors[0])
        assert arr.shape == (2, 2)  # [capacity, channels], full scans
        np.testing.assert_allclose(arr[:, 0], [1.0, 3.0])    # x * 0.01
        np.testing.assert_allclose(arr[:, 1], [-4.0, -8.0])  # y * 0.02
