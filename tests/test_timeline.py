"""Frame-ledger timeline (obs/timeline.py) — the PR-7 observability
contract:

- with no active timeline nothing is recorded, no frame carries a
  trace stamp, and outputs are byte-identical to a traced run;
- on the golden pipeline the canonical stages TILE a frame's life:
  stage_breakdown sums reconcile with the sink's e2e record;
- the Chrome export is Perfetto-loadable — named thread tracks, X
  slices carrying the frame seq, s/t/f flow chains per frame;
- scheduler decisions are events WITH matching counters: every
  admission-reject / shed / revoked-admission increments its
  ``nns_sched_*`` / ``nns_queue_admitted_revoked_total`` series and
  lands in the timeline, and the two accountings must agree.
"""

import threading
import time

import numpy as np

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.obs.timeline import STAGES, TRACE_SEQ_META, Timeline
from nnstreamer_tpu.pipeline.element import Element, EosEvent, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import Pipeline, Queue
from nnstreamer_tpu.serving.scheduler import SloScheduler
from nnstreamer_tpu.tensors.buffer import TensorBuffer

GOLDEN = ("videotestsrc pattern=ball num-buffers=24 width=16 height=16 ! "
          "tensor_converter ! queue ! tensor_sink name=sink")


def _run_golden():
    pipe = parse_launch(GOLDEN)
    msg = pipe.run(timeout=120)
    assert msg is not None and msg.kind == "eos", msg
    return pipe


def _instants(tl: Timeline, name: str):
    return [ev for ev in tl.to_chrome()["traceEvents"]
            if ev.get("ph") == "i" and ev["name"] == name]


def _counter(name, **labels):
    c = get_registry().get(name, **labels)
    return float(c.value) if c is not None else 0.0


class TestRecorderUnits:
    def test_breakdown_tiles_synthetic_frames(self):
        tl = Timeline()
        for seq in range(4):
            t = 100.0 + seq
            tl.span("ingest", seq, t, t + 0.010)
            tl.span("queue_wait", seq, t + 0.010, t + 0.030)
            # repeated same-stage spans must SUM, not overwrite
            tl.span("queue_wait", seq, t + 0.030, t + 0.040)
            tl.span("sink", seq, t + 0.040, t + 0.050, e2e_s=0.050)
        bd = tl.stage_breakdown()
        assert bd["frames"] == 4
        assert set(bd["stages_ms"]) == set(STAGES)
        assert bd["stages_ms"]["queue_wait"] == 30.0
        assert bd["reconciliation"] == 1.0
        var = tl.variance_report()
        assert var["dominant_stage"] is None  # identical frames: no spread

    def test_skip_frames_drops_warmup(self):
        tl = Timeline()
        tl.span("sink", 0, 0.0, 10.0, e2e_s=10.0)      # cold outlier
        tl.span("sink", 1, 20.0, 20.001, e2e_s=0.001)
        tl.span("sink", 2, 30.0, 30.001, e2e_s=0.001)
        assert tl.stage_breakdown(skip_frames=1)["e2e_mean_ms"] == 1.0

    def test_dead_thread_rings_unregister_but_keep_records(self):
        """The PR-8 supervised-restart leak: every crashed lane worker
        used to leave its ring registered forever. A dead thread's ring
        must leave the registry (bounded growth across restart cycles)
        while its recorded spans survive in the retired store."""
        import gc

        tl = Timeline(capacity=64)

        def _record(i: int):
            tl.span("device", i, float(i), float(i) + 0.5)

        # simulate crash/restart cycles: one short-lived worker each
        for i in range(20):
            t = threading.Thread(target=_record, args=(i,), daemon=True)
            t.start()
            t.join(timeout=10)
        gc.collect()  # finalizers on the thread-local anchors
        deadline = time.monotonic() + 5.0
        while len(tl._rings) > 0 and time.monotonic() < deadline:
            gc.collect()
            time.sleep(0.01)
        assert len(tl._rings) == 0, \
            f"{len(tl._rings)} dead-thread rings still registered"
        # the records outlive their threads (export-after-join contract)
        seqs = {r[2] for r in tl._snapshot() if r[1] == "device"}
        assert seqs == set(range(20))
        # bounded: the retired store is one ring's capacity, not 20
        assert tl._retired.maxlen == 64
        tl.clear()
        assert len(tl._snapshot()) == 0


class TestGoldenPipeline:
    def test_breakdown_reconciles_with_sink_e2e(self):
        tl = _timeline.activate()
        try:
            _run_golden()
            bd = tl.stage_breakdown(skip_frames=2)
        finally:
            _timeline.deactivate()
        assert bd["frames"] >= 10
        assert set(bd["stages_ms"]) == set(STAGES)
        # the stages must tile a frame's life: covered within 10% of
        # e2e (0.5 ms floor — on a fast CPU run 10% of e2e is noise)
        gap = abs(bd["e2e_mean_ms"] - bd["covered_ms"])
        assert gap <= max(0.10 * bd["e2e_mean_ms"], 0.5), bd

    def test_chrome_export_is_perfetto_loadable(self):
        tl = _timeline.activate()
        try:
            _run_golden()
            doc = tl.to_chrome()
        finally:
            _timeline.deactivate()
        evs = doc["traceEvents"]
        meta = [e for e in evs if e["ph"] == "M"]
        assert any(e["name"] == "process_name" for e in meta)
        named = {e["tid"] for e in meta if e["name"] == "thread_name"}
        used = {e["tid"] for e in evs if e["ph"] != "M"}
        assert used and used <= named, "unnamed thread track"
        slices = [e for e in evs if e["ph"] == "X"]
        assert slices
        for e in slices:
            assert e["dur"] >= 0 and "seq" in e["args"]
        # flow chains: every frame crossing ≥2 tracks starts with `s`
        # and finishes with `f` so Perfetto can follow it end to end
        flows = {}
        for e in evs:
            if e.get("cat") == "frame":
                flows.setdefault(e["id"], []).append(e["ph"])
        assert flows, "no flow events"
        for phases in flows.values():
            assert phases[0] == "s" and phases[-1] == "f"

    def test_off_records_nothing_and_output_matches_traced(
            self, monkeypatch):
        # the always-on flight recorder (obs/flight.py) would otherwise
        # claim the ledger slot and stamp trace seqs; NNSTPU_FLIGHT=0 is
        # the kill switch that restores the historical zero-footprint
        # off path this test pins down
        monkeypatch.setenv("NNSTPU_FLIGHT", "0")
        assert _timeline.ACTIVE is None
        pipe_off = _run_golden()
        off = [b for b in pipe_off.get("sink").buffers]
        # zero footprint: no frame carries a trace stamp when off
        assert all(TRACE_SEQ_META not in b.meta for b in off)
        tl = _timeline.activate()
        try:
            pipe_on = _run_golden()
        finally:
            _timeline.deactivate()
        on = [b for b in pipe_on.get("sink").buffers]
        assert tl.stage_breakdown()["frames"] > 0
        assert len(off) == len(on) == 24
        for a, b in zip(off, on):
            assert a.tensors[0].tobytes() == b.tensors[0].tobytes()


def _buf(i: int, deadline_t=None, seq=None) -> TensorBuffer:
    buf = TensorBuffer([np.array([float(i)], np.float32)], pts=i * 1000)
    if deadline_t is not None:
        buf.meta["deadline_t"] = deadline_t
    if seq is not None:
        buf.meta[TRACE_SEQ_META] = seq
    return buf


class _Gate(Element):
    """Parks the queue worker inside chain() until released."""

    ELEMENT_NAME = "_tl_gate"
    PROPERTIES = {}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")
        self.entered = threading.Event()
        self.release = threading.Event()

    def chain(self, pad, buf):
        self.entered.set()
        assert self.release.wait(timeout=10)
        return self.srcpads[0].push(buf)


class _Collect(Element):
    ELEMENT_NAME = "_tl_collect"
    PROPERTIES = {}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.buffers = []

    def chain(self, pad, buf):
        self.buffers.append(buf)
        return FlowReturn.OK


class TestSchedulerTimeline:
    def test_reject_and_shed_marks_match_counters(self):
        tl = _timeline.activate()
        try:
            sched = SloScheduler(budget_ms=50, name="tl-sched-unit")
            rej0 = _counter("nns_sched_rejected_total",
                            pipeline="tl-sched-unit")
            late0 = _counter("nns_sched_shed_total",
                             pipeline="tl-sched-unit", reason="late")
            cap0 = _counter("nns_sched_shed_total",
                            pipeline="tl-sched-unit", reason="capacity")
            sched.observe_service(0.1)  # 100 ms/frame: 50 ms unmeetable
            for i in range(3):
                assert not sched.admit(_buf(i, seq=i), now=10.0, backlog=0)
            late = _buf(10, seq=10)
            ontime = _buf(11, seq=11)
            assert sched.admit(late, now=10.0, backlog=0, budget_ms=10_000)
            assert sched.admit(ontime, now=10.0, backlog=0,
                               budget_ms=10_000)
            sched.note_shed(late, now=30.0)    # deadline 20.0 < 30: late
            sched.note_shed(ontime, now=10.5)  # had slack: capacity
            rejects = _instants(tl, "sched_reject")
            sheds = _instants(tl, "sched_shed")
        finally:
            _timeline.deactivate()
        # every counted decision is a timeline event, and vice versa
        assert len(rejects) == _counter("nns_sched_rejected_total",
                                        pipeline="tl-sched-unit") - rej0 == 3
        shed_late = _counter("nns_sched_shed_total",
                             pipeline="tl-sched-unit", reason="late") - late0
        shed_cap = _counter("nns_sched_shed_total",
                            pipeline="tl-sched-unit",
                            reason="capacity") - cap0
        assert len(sheds) == shed_late + shed_cap == 2
        assert sum(1 for e in sheds if e["args"]["late"]) == shed_late == 1
        # events carry the diagnosis: frame seq + decision slack
        assert {e["args"]["seq"] for e in rejects} == {0, 1, 2}
        assert all(e["args"]["slack_ms"] < 0 for e in rejects)

    def test_queue_shed_revokes_admission_and_marks(self):
        tl = _timeline.activate()
        pipe = Pipeline(name="tl-edf-shed", fuse=False,
                        slo_budget_ms=10_000.0)
        q = Queue(name="q", stamp_admission=True, max_size_buffers=2)
        gate = _Gate(name="gate")
        col = _Collect(name="col")
        pipe.add_linked(q, gate, col)
        try:
            pipe.start()
            r0 = _counter("nns_queue_admitted_revoked_total",
                          pipeline="tl-edf-shed", element="q")
            now = time.monotonic()
            q.chain(None, _buf(0, deadline_t=now + 9.0, seq=0))  # plug
            assert gate.entered.wait(timeout=5)
            q.chain(None, _buf(1, deadline_t=now + 0.05, seq=1))
            q.chain(None, _buf(2, deadline_t=now + 5.0, seq=2))
            time.sleep(0.12)  # frame 1's deadline passes IN the heap
            q.chain(None, _buf(3, deadline_t=time.monotonic() + 6.0,
                               seq=3))   # overflow: sheds late frame 1
            q.chain(None, _buf(4, deadline_t=time.monotonic() + 7.0,
                               seq=4))   # overflow: sheds least-urgent 4
            gate.release.set()
            q.sink_event(None, EosEvent())
            revoked = _counter("nns_queue_admitted_revoked_total",
                               pipeline="tl-edf-shed", element="q") - r0
            sheds = _instants(tl, "sched_shed")
        finally:
            _timeline.deactivate()
            pipe.stop()
        # every revoked admission is a timeline shed event with the
        # frame's identity — the ledger and the counter must agree
        assert revoked == len(sheds) == 2
        assert {e["args"]["seq"] for e in sheds} == {1, 4}
        assert [e["args"]["late"] for e in sorted(
            sheds, key=lambda e: e["args"]["seq"])] == [True, False]
