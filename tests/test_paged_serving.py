"""Paged-KV continuous batching (serving/engine.py + serving/kvpool.py).

The correctness bar, per ISSUE 19's acceptance criteria:

- ``NNSTPU_PAGED_KV=0`` (or ``block_tokens=0``) keeps the monolithic
  cache — the engine never builds a pool and outputs are byte-identical
  to the unpaged engine (pinned here);
- with paging ON, greedy outputs are byte-identical to the monolithic
  cache for the same prompts — single stream, concurrent streams,
  ``kv_quant=int8``, chunked prefill, and oversubscription (more
  streams than decode lanes) alike;
- the decode loop stays ONE jitted program (retrace count pinned);
- under a starved pool the evict -> shed ladder fires, shed streams'
  blocks return to the free list, and surviving streams stay exact;
- copy-on-write prefix sharing retains blocks once across streams;
- paging x int8 x mesh=dp2 composes byte-identically (satellite 4).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu.serving import ContinuousBatchingEngine  # noqa: E402
from tests.test_serving import CFG, PARAMS, reference_greedy  # noqa: E402

T = 8


def paged_engine(**kw):
    kw.setdefault("max_streams", 3)
    kw.setdefault("steps_per_dispatch", 4)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("block_tokens", T)
    return ContinuousBatchingEngine(CFG, PARAMS, **kw).start()


PROMPTS = [[5, 11, 23, 42, 7], [4, 8, 15], [16, 23], [42, 7, 9, 1],
           [2, 2, 2, 2, 2], [31, 59, 26, 53], [9] * 17, [13, 2]]


# -- kill switch ----------------------------------------------------------


def test_env_kill_switch_keeps_monolithic_path(monkeypatch):
    monkeypatch.setenv("NNSTPU_PAGED_KV", "0")
    eng = paged_engine()  # block_tokens set, env wins
    try:
        assert not eng.paged
        assert eng._cache is not None          # monolithic cache built
        assert not hasattr(eng, "_pool") or eng._pool is None
        got = eng.generate(PROMPTS[0], max_new_tokens=9, timeout=120)
    finally:
        eng.stop()
    assert got == reference_greedy(PROMPTS[0], 9)


def test_block_tokens_zero_is_monolithic():
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0).start()
    try:
        assert not eng.paged and eng._cache is not None
    finally:
        eng.stop()


# -- greedy byte-parity vs the monolithic cache ---------------------------


def test_single_stream_matches_reference():
    eng = paged_engine()
    try:
        assert eng.paged
        for p in PROMPTS[:4]:
            assert eng.generate(p, max_new_tokens=9, timeout=120) == \
                reference_greedy(p, 9), f"prompt={p}"
    finally:
        eng.stop()


def test_concurrent_streams_match_isolated_runs():
    eng = paged_engine()
    try:
        streams = [eng.submit(p, max_new_tokens=9) for p in PROMPTS[:5]]
        results = [s.result(timeout=240) for s in streams]
    finally:
        eng.stop()
    for p, got in zip(PROMPTS, results):
        assert got == reference_greedy(p, 9), f"prompt={p}"


def test_int8_paged_matches_int8_monolithic():
    """The per-block int8 codec must equal the monolithic int8 cache
    bit for bit — same quantization grid, different storage layout."""
    mono = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, kv_quant="int8").start()
    try:
        want = [mono.generate(p, max_new_tokens=9, timeout=120)
                for p in PROMPTS[:3]]
    finally:
        mono.stop()
    eng = paged_engine(kv_quant="int8")
    try:
        got = [eng.generate(p, max_new_tokens=9, timeout=120)
               for p in PROMPTS[:3]]
    finally:
        eng.stop()
    assert got == want


def test_chunked_prefill_composes_with_paging():
    eng = paged_engine(prefill_chunk=16)
    try:
        for p in (PROMPTS[6], list(range(1, 30))):
            assert eng.generate(p, max_new_tokens=6, timeout=120) == \
                reference_greedy(p, 6), f"len={len(p)}"
    finally:
        eng.stop()


# -- one jitted decode program --------------------------------------------


def test_decode_loop_stays_one_jitted_program():
    eng = paged_engine()
    try:
        streams = [eng.submit(p, max_new_tokens=7) for p in PROMPTS[:5]]
        for s in streams:
            s.result(timeout=240)
        # every dispatch reuses the single traced program: block tables
        # and positions are data, not shape, so stream churn and block
        # growth never retrace
        assert eng._dispatch._cache_size() == 1
    finally:
        eng.stop()


# -- oversubscription: more streams than decode lanes ---------------------


def test_oversubscribed_streams_stay_exact():
    """12 streams over 2 decode lanes: EDF time-sharing parks and
    rebinds lanes at block granularity, and every stream's output is
    still byte-identical to its isolated run."""
    eng = paged_engine(max_streams=2, kv_blocks=64)
    try:
        prompts = [PROMPTS[i % len(PROMPTS)] for i in range(12)]
        streams = [eng.submit(p, max_new_tokens=8) for p in prompts]
        results = [s.result(timeout=480) for s in streams]
        assert eng.stats["concurrent_streams_max"] > eng.B
    finally:
        eng.stop()
    for p, got in zip(prompts, results):
        assert got == reference_greedy(p, 8), f"prompt={p}"


def test_starved_pool_sheds_and_recycles_blocks():
    """A pool too small for the offered load must shed (most-late
    stream first), count it, and return every block to the free list —
    never wedge admission or leak."""
    eng = paged_engine(max_streams=2, kv_blocks=6, prefix_cache=0)
    try:
        streams = [eng.submit(PROMPTS[i % len(PROMPTS)],
                              max_new_tokens=24) for i in range(8)]
        done = [s.result(timeout=480) for s in streams]
        reasons = [s.finish_reason for s in streams]
        assert eng.stats["kv_sheds"] > 0
        assert all(r in ("length", "shed", "eos") for r in reasons)
        # shed streams still returned their partial output
        assert all(done[i] is not None for i in range(len(done)))
        assert eng._pool.live_blocks() == 0
        # non-shed streams remained exact despite the churn
        for s, p, got in zip(streams, [PROMPTS[i % len(PROMPTS)]
                                       for i in range(8)], done):
            if s.finish_reason == "length":
                assert got == reference_greedy(p, 24), f"prompt={p}"
    finally:
        eng.stop()


# -- copy-on-write prefix sharing -----------------------------------------


def test_prefix_cache_shares_blocks_copy_on_write():
    base = [7, 3, 9, 1, 4, 6, 2, 8, 5, 11, 13, 17, 19, 23, 29, 27, 25]
    eng = paged_engine(prefix_cache=4, kv_blocks=64)
    try:
        cold = eng.generate(base, max_new_tokens=6, timeout=120)
        live_after_cold = eng._pool.live_blocks()
        assert live_after_cold > 0      # the entry retains its blocks
        hit = eng.generate(base, max_new_tokens=6, timeout=120)
        ext = eng.generate(base + [31, 37], max_new_tokens=6, timeout=120)
        assert eng.stats["prefix_hits"] >= 2
        assert eng.stats["prefix_tokens_reused"] >= len(base) + 16
    finally:
        eng.stop()
    assert hit == cold == reference_greedy(base, 6)
    assert ext == reference_greedy(base + [31, 37], 6)


def test_prefix_entry_blocks_survive_donor_stream_exit():
    """The cached prefix must stay valid after the stream that created
    it finishes and its private blocks are recycled — the refcount is
    what keeps the shared full blocks alive."""
    base = list(range(1, 18))
    eng = paged_engine(prefix_cache=8, kv_blocks=64)
    try:
        eng.generate(base, max_new_tokens=4, timeout=120)
        # churn the pool: unrelated streams recycle the donor's blocks
        for p in PROMPTS[:4]:
            eng.generate(p, max_new_tokens=6, timeout=120)
        got = eng.generate(base, max_new_tokens=9, timeout=120)
        assert eng.stats["prefix_hits"] >= 1
    finally:
        eng.stop()
    assert got == reference_greedy(base, 9)


# -- satellite 4: paging x int8 x mesh=dp2 --------------------------------


def test_paged_int8_dp2_mesh_matches_single_device():
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mono = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, kv_quant="int8").start()
    try:
        want = [mono.generate(p, max_new_tokens=8, timeout=240)
                for p in PROMPTS[:3]]
    finally:
        mono.stop()

    mesh = make_mesh([("dp", 2)])
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, kv_quant="int8", block_tokens=T,
        mesh=mesh).start()
    try:
        assert eng.paged
        # the arena (incl. zero block) divides over dp ranks
        assert eng._pool.ntot % 2 == 0
        got = [eng.generate(p, max_new_tokens=8, timeout=240)
               for p in PROMPTS[:3]]
        streams = [eng.submit(p, max_new_tokens=8) for p in PROMPTS[:3]]
        conc = [s.result(timeout=240) for s in streams]
    finally:
        eng.stop()
    assert got == want
    assert conc == want
