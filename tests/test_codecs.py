"""Serialization codec subplugins (flexbuf / protobuf / flatbuf):
encode→decode round trips and decoder→converter pipeline loops
(reference: ext/nnstreamer/tensor_decoder/tensordec-{flexbuf,protobuf,
flatbuf} + matching converters)."""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.tensors.buffer import TensorBuffer

CODECS = {}

from nnstreamer_tpu.decoders.flexbuf import decode_flex, encode_flex  # noqa: E402

CODECS["flexbuf"] = (encode_flex, decode_flex)
from nnstreamer_tpu.decoders.protobuf_codec import (  # noqa: E402
    decode_protobuf,
    encode_protobuf,
)

CODECS["protobuf"] = (encode_protobuf, decode_protobuf)
from nnstreamer_tpu.decoders import flatbuf_codec  # noqa: E402

if flatbuf_codec._HAVE_FLATBUFFERS:  # skip (not fail) without the package
    CODECS["flatbuf"] = (flatbuf_codec.encode_flatbuf,
                         flatbuf_codec.decode_flatbuf)


def _buf():
    return TensorBuffer([
        np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        np.array([[1, 2], [3, 4]], np.uint8),
        np.array([7], np.int64),
    ])


@pytest.mark.parametrize("name", sorted(CODECS))
def test_codec_roundtrip(name):
    enc, dec = CODECS[name]
    out = dec(enc(_buf()))
    assert out.num_tensors == 3
    for a, b in zip(_buf().tensors, out.tensors):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("name", sorted(set(CODECS) & {"flatbuf",
                                                       "protobuf",
                                                       "flexbuf"}))
def test_codec_pipeline_loop(name):
    """tensor_decoder mode=<codec> ! tensor_converter mode=<codec> is an
    identity transform over the wire format."""
    pipe = parse_launch(
        f"videotestsrc num-buffers=3 width=4 height=4 ! tensor_converter ! "
        f"tensor_decoder mode={name} ! "
        f"tensor_converter mode={name} ! tensor_sink name=out")
    out = pipe.get("out")
    msg = pipe.run(timeout=60)
    assert msg is not None and msg.kind == "eos", msg
    assert len(out.buffers) == 3
    assert out.buffers[0].tensors[0].shape == (1, 4, 4, 3)
    assert out.buffers[0].tensors[0].dtype == np.uint8


def test_flatbuf_rate_field():
    """frame_rate struct encodes without corrupting the table."""
    if "flatbuf" not in CODECS:
        pytest.skip("flatbuffers unavailable")
    from fractions import Fraction

    enc, dec = CODECS["flatbuf"]
    blob = enc(_buf(), rate=Fraction(30, 1))
    out = dec(blob)
    assert out.num_tensors == 3
    np.testing.assert_array_equal(out.tensors[0], _buf().tensors[0])


def test_python3_converter_conf_driven(tmp_path, monkeypatch):
    """mode=custom-code:python3 resolves its script from the config system
    (reference conf-driven python subplugin paths)."""
    import numpy as np

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.config import get_conf

    script = tmp_path / "conv.py"
    script.write_text(
        "import numpy as np\n"
        "class Converter:\n"
        "    def convert(self, buf, in_caps):\n"
        "        return buf.with_tensors("
        "[np.asarray(t).astype(np.float32) * 2 for t in buf.tensors])\n")
    monkeypatch.setenv("NNSTREAMER_TPU_CONVERTER_PYTHON3_SCRIPT",
                       str(script))
    get_conf(refresh=True)
    pipe = parse_launch(
        "videotestsrc num-buffers=2 width=4 height=4 ! "
        "tensor_converter mode=custom-code:python3 ! tensor_sink name=out")
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos", msg
    out = np.asarray(pipe.get("out").buffers[0][0])
    assert out.dtype == np.float32 and out.max() > 0
