"""Serialization codec subplugins (flexbuf / protobuf / flatbuf):
encode→decode round trips and decoder→converter pipeline loops
(reference: ext/nnstreamer/tensor_decoder/tensordec-{flexbuf,protobuf,
flatbuf} + matching converters)."""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.tensors.buffer import TensorBuffer

CODECS = {}

from nnstreamer_tpu.decoders.flexbuf import (  # noqa: E402
    decode_flex,
    decode_flexbuf,
    encode_flex,
    encode_flexbuf,
)

CODECS["flexbuf"] = (encode_flexbuf, decode_flexbuf)
CODECS["nnstpu-flex"] = (encode_flex, decode_flex)
from nnstreamer_tpu.decoders.protobuf_codec import (  # noqa: E402
    decode_protobuf,
    encode_protobuf,
)

CODECS["protobuf"] = (encode_protobuf, decode_protobuf)
from nnstreamer_tpu.decoders import flatbuf_codec  # noqa: E402

if flatbuf_codec._HAVE_FLATBUFFERS:  # skip (not fail) without the package
    CODECS["flatbuf"] = (flatbuf_codec.encode_flatbuf,
                         flatbuf_codec.decode_flatbuf)


def _buf():
    return TensorBuffer([
        np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        np.array([[1, 2], [3, 4]], np.uint8),
        np.array([7], np.int64),
    ])


@pytest.mark.parametrize("name", sorted(CODECS))
def test_codec_roundtrip(name):
    enc, dec = CODECS[name]
    out = dec(enc(_buf()))
    assert out.num_tensors == 3
    for a, b in zip(_buf().tensors, out.tensors):
        assert a.dtype == b.dtype
        if name in ("protobuf", "flexbuf", "flatbuf"):
            # wire-parity with the reference rank-4 format: shapes come
            # back 1-padded to rank 4 (see decoders/protobuf_codec.py)
            assert b.shape == (1,) * (4 - a.ndim) + a.shape
        else:
            assert a.shape == b.shape
        np.testing.assert_array_equal(a.reshape(b.shape), b)


@pytest.mark.parametrize("name", sorted(set(CODECS) & {"flatbuf",
                                                       "protobuf",
                                                       "flexbuf",
                                                       "nnstpu-flex"}))
def test_codec_pipeline_loop(name):
    """tensor_decoder mode=<codec> ! tensor_converter mode=<codec> is an
    identity transform over the wire format."""
    pipe = parse_launch(
        f"videotestsrc num-buffers=3 width=4 height=4 ! tensor_converter ! "
        f"tensor_decoder mode={name} ! "
        f"tensor_converter mode={name} ! tensor_sink name=out")
    out = pipe.get("out")
    msg = pipe.run(timeout=60)
    assert msg is not None and msg.kind == "eos", msg
    assert len(out.buffers) == 3
    assert out.buffers[0].tensors[0].shape == (1, 4, 4, 3)
    assert out.buffers[0].tensors[0].dtype == np.uint8


def test_flatbuf_rate_field():
    """frame_rate struct encodes without corrupting the table."""
    if "flatbuf" not in CODECS:
        pytest.skip("flatbuffers unavailable")
    from fractions import Fraction

    enc, dec = CODECS["flatbuf"]
    blob = enc(_buf(), rate=Fraction(30, 1))
    out = dec(blob)
    assert out.num_tensors == 3
    assert str(out.meta["framerate"]) == "30/1"
    np.testing.assert_array_equal(out.tensors[0].reshape(2, 3, 4),
                                  _buf().tensors[0])


def test_python3_converter_conf_driven(tmp_path, monkeypatch):
    """mode=custom-code:python3 resolves its script from the config system
    (reference conf-driven python subplugin paths)."""
    import numpy as np

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.config import get_conf

    script = tmp_path / "conv.py"
    script.write_text(
        "import numpy as np\n"
        "class Converter:\n"
        "    def convert(self, buf, in_caps):\n"
        "        return buf.with_tensors("
        "[np.asarray(t).astype(np.float32) * 2 for t in buf.tensors])\n")
    monkeypatch.setenv("NNSTREAMER_TPU_CONVERTER_PYTHON3_SCRIPT",
                       str(script))
    get_conf(refresh=True)
    pipe = parse_launch(
        "videotestsrc num-buffers=2 width=4 height=4 ! "
        "tensor_converter mode=custom-code:python3 ! tensor_sink name=out")
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos", msg
    out = np.asarray(pipe.get("out").buffers[0][0])
    assert out.dtype == np.float32 and out.max() > 0


# ---------------------------------------------------------------------------
# Wire compatibility with the reference nnstreamer.proto
# ---------------------------------------------------------------------------

_REF_PROTO = "/root/reference/ext/nnstreamer/include/nnstreamer.proto"


@pytest.fixture(scope="module")
def ref_pb2(tmp_path_factory):
    """pb2 module protoc-generates from the reference's own .proto —
    the ground truth for wire compatibility."""
    import importlib.util
    import os
    import shutil
    import subprocess

    if shutil.which("protoc") is None or not os.path.isfile(_REF_PROTO):
        pytest.skip("protoc or reference .proto unavailable")
    d = tmp_path_factory.mktemp("refproto")
    shutil.copy(_REF_PROTO, d / "nnstreamer.proto")
    subprocess.run(["protoc", "--python_out=.", "nnstreamer.proto"],
                   cwd=d, check=True, capture_output=True)
    spec = importlib.util.spec_from_file_location(
        "ref_nnstreamer_pb2", d / "nnstreamer_pb2.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestProtobufWireCompat:
    def test_reference_parses_our_payload(self, ref_pb2):
        from nnstreamer_tpu.tensors.types import Fraction

        blob = CODECS["protobuf"][0](_buf(), rate=Fraction(30, 1))
        msg = ref_pb2.Tensors.FromString(blob)
        assert msg.num_tensor == 3
        assert (msg.fr.rate_n, msg.fr.rate_d) == (30, 1)
        assert msg.format == ref_pb2.Tensors.NNS_TENSOR_FORAMT_STATIC
        t0 = msg.tensor[0]
        assert t0.type == ref_pb2.Tensor.NNS_FLOAT32
        assert list(t0.dimension) == [4, 3, 2, 1]  # rank-4, 1-padded
        np.testing.assert_array_equal(
            np.frombuffer(t0.data, np.float32).reshape(2, 3, 4),
            _buf().tensors[0])
        assert msg.tensor[1].type == ref_pb2.Tensor.NNS_UINT8
        assert msg.tensor[2].type == ref_pb2.Tensor.NNS_INT64

    def test_we_parse_reference_payload(self, ref_pb2):
        msg = ref_pb2.Tensors(num_tensor=2)
        msg.fr.rate_n = 25
        msg.fr.rate_d = 1
        msg.format = ref_pb2.Tensors.NNS_TENSOR_FORAMT_STATIC
        a = np.arange(12, dtype=np.int16).reshape(3, 4)
        t = msg.tensor.add()
        t.name = "scores"
        t.type = ref_pb2.Tensor.NNS_INT16
        t.dimension.extend([4, 3, 1, 1])
        t.data = a.tobytes()
        b = np.array([1.5, -2.5], np.float64)
        t = msg.tensor.add()
        t.type = ref_pb2.Tensor.NNS_FLOAT64
        t.dimension.extend([2, 1, 1, 1])
        t.data = b.tobytes()

        out = CODECS["protobuf"][1](msg.SerializeToString())
        assert out.num_tensors == 2
        assert out.tensors[0].shape == (1, 1, 3, 4)
        np.testing.assert_array_equal(out.tensors[0].reshape(3, 4), a)
        assert out.tensors[1].dtype == np.float64
        np.testing.assert_array_equal(out.tensors[1].reshape(2), b)
        assert str(out.meta["framerate"]) == "25/1"
        assert out.meta["format"] == "static"
        assert out.meta["tensor_names"] == ["scores", None]

    def test_byte_identical_serialization(self, ref_pb2):
        """Same logical frame → byte-identical wire bytes from both
        implementations (both serialize fields in number order)."""
        from nnstreamer_tpu.tensors.types import Fraction

        ours = CODECS["protobuf"][0](_buf(), rate=Fraction(15, 2))
        theirs = ref_pb2.Tensors.FromString(ours).SerializeToString()
        assert ours == theirs

    def test_fp16_refused(self):
        buf = TensorBuffer([np.zeros((2, 2), np.float16)])
        with pytest.raises(ValueError, match="tensor_type"):
            CODECS["protobuf"][0](buf)

    def test_rank5_refused(self):
        buf = TensorBuffer([np.zeros((1, 2, 3, 4, 5), np.float32)])
        with pytest.raises(ValueError, match="nnstpu-flex"):
            CODECS["protobuf"][0](buf)

    def test_bad_wire_values_refused(self, ref_pb2):
        msg = ref_pb2.Tensors(num_tensor=1)
        t = msg.tensor.add()
        t.type = -1
        t.dimension.extend([1, 1, 1, 1])
        t.data = b"\x00\x00"
        with pytest.raises(ValueError, match="tensor_type"):
            CODECS["protobuf"][1](msg.SerializeToString())
        msg.tensor[0].type = ref_pb2.Tensor.NNS_INT16
        msg.format = -1
        with pytest.raises(ValueError, match="tensor_format"):
            CODECS["protobuf"][1](msg.SerializeToString())

    def test_converter_keeps_wire_meta(self, ref_pb2):
        """pipeline converter path surfaces framerate/names from the wire."""
        msg = ref_pb2.Tensors(num_tensor=1)
        msg.fr.rate_n = 10
        msg.fr.rate_d = 1
        t = msg.tensor.add()
        t.name = "probs"
        t.type = ref_pb2.Tensor.NNS_FLOAT32
        t.dimension.extend([2, 1, 1, 1])
        t.data = np.zeros(2, np.float32).tobytes()
        blob = np.frombuffer(msg.SerializeToString(), np.uint8)

        from nnstreamer_tpu.converters.protobuf_codec import ProtobufConverter

        out = ProtobufConverter().convert(TensorBuffer([blob]), None)
        assert str(out.meta["framerate"]) == "10/1"
        assert out.meta["tensor_names"] == ["probs"]


# ---------------------------------------------------------------------------
# Wire compatibility with the reference flexbuf layout
# (tensordec-flexbuf.cc:26-35 / tensor_converter_flexbuf.cc:107-141)
# ---------------------------------------------------------------------------


def _ref_peer_encode(tensors, names=None, rate=(30, 1), fmt=0):
    """Build a payload exactly the way the reference decoder does
    (tensordec-flexbuf.cc:138-168) — same call sequence on a flexbuffers
    Builder — standing in for a reference peer."""
    from flatbuffers import flexbuffers

    fbb = flexbuffers.Builder()
    type_order = ["int32", "uint32", "int16", "uint16", "int8", "uint8",
                  "float64", "float32", "int64", "uint64"]
    with fbb.Map():
        fbb.Key("num_tensors")
        fbb.UInt(len(tensors))
        fbb.Key("rate_n")
        fbb.Int(rate[0])
        fbb.Key("rate_d")
        fbb.Int(rate[1])
        fbb.Key("format")
        fbb.Int(fmt)
        for i, t in enumerate(tensors):
            fbb.Key(f"tensor_{i}")
            dims = list(reversed(t.shape)) if t.ndim else [1]
            with fbb.Vector():
                fbb.String(names[i] if names and names[i] else "")
                fbb.Int(type_order.index(str(t.dtype)))
                fbb.TypedVectorFromElements(dims + [1] * (4 - len(dims)))
                fbb.Blob(np.ascontiguousarray(t).tobytes())
    return bytes(fbb.Finish())


class TestFlexbufWireCompat:
    def test_reference_parses_our_payload(self):
        """A reference peer reads our bytes with plain flexbuffers calls
        (the exact reads tensor_converter_flexbuf.cc:107-141 makes)."""
        from flatbuffers import flexbuffers

        from nnstreamer_tpu.tensors.types import Fraction

        blob = encode_flexbuf(_buf(), rate=Fraction(30, 1))
        m = flexbuffers.GetRoot(blob).AsMap
        assert m["num_tensors"].AsInt == 3
        assert (m["rate_n"].AsInt, m["rate_d"].AsInt) == (30, 1)
        assert m["format"].AsInt == 0
        t0 = m["tensor_0"].AsVector
        assert t0[0].AsString == ""
        assert t0[1].AsInt == 7  # _NNS_FLOAT32
        assert [d.AsInt for d in t0[2].AsTypedVector] == [4, 3, 2, 1]
        np.testing.assert_array_equal(
            np.frombuffer(bytes(t0[3].AsBlob), np.float32).reshape(2, 3, 4),
            _buf().tensors[0])
        assert m["tensor_1"].AsVector[1].AsInt == 5  # _NNS_UINT8
        assert m["tensor_2"].AsVector[1].AsInt == 8  # _NNS_INT64

    def test_we_parse_reference_payload(self):
        a = np.arange(12, dtype=np.int16).reshape(3, 4)
        b = np.array([1.5, -2.5], np.float64)
        blob = _ref_peer_encode([a, b], names=["scores", None],
                                rate=(25, 1))
        out = decode_flexbuf(blob)
        assert out.num_tensors == 2
        assert out.tensors[0].shape == (1, 1, 3, 4)
        np.testing.assert_array_equal(out.tensors[0].reshape(3, 4), a)
        assert out.tensors[1].dtype == np.float64
        np.testing.assert_array_equal(out.tensors[1].reshape(2), b)
        assert str(out.meta["framerate"]) == "25/1"
        assert out.meta["format"] == "static"
        assert out.meta["tensor_names"] == ["scores", None]

    def test_byte_identical_serialization(self):
        """Same logical frame → byte-identical output from our codec and
        the reference call sequence (proves we make exactly the builder
        calls tensordec-flexbuf.cc:138-168 makes)."""
        from nnstreamer_tpu.tensors.types import Fraction

        frame = _buf()
        ours = encode_flexbuf(frame, rate=Fraction(15, 2))
        theirs = _ref_peer_encode(list(frame.tensors), rate=(15, 2))
        assert ours == theirs

    def test_fp16_refused(self):
        buf = TensorBuffer([np.zeros((2, 2), np.float16)])
        with pytest.raises(ValueError, match="tensor_type"):
            encode_flexbuf(buf)

    def test_rank5_goes_to_native_framing(self):
        buf = TensorBuffer([np.zeros((1, 2, 3, 4, 5), np.float32)])
        with pytest.raises(ValueError, match="nnstpu-flex"):
            encode_flexbuf(buf)
        out = decode_flex(encode_flex(buf))  # native framing handles it
        assert out.tensors[0].shape == (1, 2, 3, 4, 5)

    def test_bad_wire_values_refused(self):
        a = np.zeros((2,), np.float32)
        blob = _ref_peer_encode([a], fmt=9)
        with pytest.raises(ValueError, match="tensor_format"):
            decode_flexbuf(blob)
        from flatbuffers import flexbuffers

        fbb = flexbuffers.Builder()
        with fbb.Map():
            fbb.Key("num_tensors")
            fbb.UInt(1)
            fbb.Key("rate_n")
            fbb.Int(0)
            fbb.Key("rate_d")
            fbb.Int(1)
            fbb.Key("format")
            fbb.Int(0)
            fbb.Key("tensor_0")
            with fbb.Vector():
                fbb.String("")
                fbb.Int(99)  # not a tensor_type
                fbb.TypedVectorFromElements([1, 1, 1, 1])
                fbb.Blob(b"\x00")
        with pytest.raises(ValueError, match="tensor_type"):
            decode_flexbuf(bytes(fbb.Finish()))

    def test_converter_keeps_wire_meta(self):
        """pipeline converter path surfaces framerate/names from the wire."""
        from nnstreamer_tpu.converters.flexbuf import FlexBufConverter

        blob = _ref_peer_encode([np.zeros(2, np.float32)], names=["probs"],
                                rate=(10, 1))
        out = FlexBufConverter().convert(
            TensorBuffer([np.frombuffer(blob, np.uint8)]), None)
        assert str(out.meta["framerate"]) == "10/1"
        assert out.meta["tensor_names"] == ["probs"]


# ---------------------------------------------------------------------------
# Wire compatibility with the reference flatbuf schema (nnstreamer.fbs)
# ---------------------------------------------------------------------------

_REF_FBS = "/root/reference/ext/nnstreamer/include/nnstreamer.fbs"


@pytest.fixture(scope="module")
def ref_fbs():
    """Field/enum layout parsed from the reference's own .fbs text — the
    ground truth for slot ids and enum values (flatc-free)."""
    import os
    import re

    if not os.path.isfile(_REF_FBS):
        pytest.skip("reference .fbs unavailable")
    text = open(_REF_FBS).read()
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    text = re.sub(r"//[^\n]*", "", text)
    enums, tables = {}, {}
    for m in re.finditer(r"enum\s+(\w+)\s*:\s*\w+\s*\{([^}]*)\}", text):
        names = [e.split("=")[0].strip()
                 for e in m.group(2).split(",") if e.strip()]
        enums[m.group(1)] = names
    for m in re.finditer(r"table\s+(\w+)\s*\{([^}]*)\}", text):
        fields = [(f.split(":")[0].strip(),
                   f.split(":")[1].split("=")[0].strip())
                  for f in m.group(2).split(";") if f.strip()]
        tables[m.group(1)] = fields
    return {"enums": enums, "tables": tables}


def _fb_read_table(data, pos):
    """Independent raw-bytes flatbuffer table reader (no flatbuffers
    runtime, no shared code with the codec under test)."""
    import struct as _s

    soff = _s.unpack_from("<i", data, pos)[0]
    vt = pos - soff
    vt_size = _s.unpack_from("<H", data, vt)[0]

    def field(slot):
        vo = 4 + 2 * slot
        if vo >= vt_size:
            return 0
        rel = _s.unpack_from("<H", data, vt + vo)[0]
        return pos + rel if rel else 0

    return field


class TestFlatbufWireCompat:
    def test_schema_layout_matches_codec_constants(self, ref_fbs):
        """Our hardcoded slot ids / enum order come straight from the
        reference schema declaration order."""
        from nnstreamer_tpu.tensors import wire

        assert [f[0] for f in ref_fbs["tables"]["Tensors"]] == \
            ["num_tensor", "fr", "tensor", "format"]
        assert [f[0] for f in ref_fbs["tables"]["Tensor"]] == \
            ["name", "type", "dimension", "data"]
        ref_types = ref_fbs["enums"]["Tensor_type"]
        assert ref_types[-1] == "NNS_END"
        assert len(ref_types) - 1 == wire.REF_TYPE_COUNT
        ours = [t.value for t in wire.TYPE_ORDER[:wire.REF_TYPE_COUNT]]
        theirs = [n.replace("NNS_", "").lower() for n in ref_types[:-1]]
        assert ours == theirs
        fmts = ref_fbs["enums"]["Tensor_format"][:3]
        assert [f.split("_")[-1].lower() for f in fmts] == \
            [f.value for f in wire.FORMAT_ORDER]

    def test_reference_parses_our_payload(self, ref_fbs):
        """Read our bytes with an independent raw reader driven by the
        schema's declaration order (slot n ↦ voffset 4+2n)."""
        import struct as _s

        from nnstreamer_tpu.tensors.types import Fraction

        slots = {f[0]: i
                 for i, f in enumerate(ref_fbs["tables"]["Tensors"])}
        tslots = {f[0]: i
                  for i, f in enumerate(ref_fbs["tables"]["Tensor"])}
        blob = flatbuf_codec.encode_flatbuf(_buf(), rate=Fraction(30, 1))
        root = _s.unpack_from("<I", blob, 0)[0]
        field = _fb_read_table(blob, root)
        num_off = field(slots["num_tensor"])
        assert _s.unpack_from("<i", blob, num_off)[0] == 3
        fr_off = field(slots["fr"])
        assert _s.unpack_from("<ii", blob, fr_off) == (30, 1)
        assert field(slots["format"]) == 0  # STATIC = schema default,
        # omitted exactly like flatc-generated add_format would
        vec_off = field(slots["tensor"])
        vec = vec_off + _s.unpack_from("<I", blob, vec_off)[0]
        assert _s.unpack_from("<I", blob, vec)[0] == 3  # vector length
        t0 = vec + 4 + _s.unpack_from("<I", blob, vec + 4)[0]
        tf = _fb_read_table(blob, t0)
        ty_off = tf(tslots["type"])
        assert _s.unpack_from("<i", blob, ty_off)[0] == 7  # NNS_FLOAT32
        d_off = tf(tslots["dimension"])
        dvec = d_off + _s.unpack_from("<I", blob, d_off)[0]
        dn = _s.unpack_from("<I", blob, dvec)[0]
        dims = _s.unpack_from(f"<{dn}I", blob, dvec + 4)
        assert dims == (4, 3, 2, 1)  # rank-4, 1-padded, innermost-first
        b_off = tf(tslots["data"])
        bvec = b_off + _s.unpack_from("<I", blob, b_off)[0]
        bn = _s.unpack_from("<I", blob, bvec)[0]
        np.testing.assert_array_equal(
            np.frombuffer(blob, np.float32, count=bn // 4,
                          offset=bvec + 4).reshape(2, 3, 4),
            _buf().tensors[0])
        n_off = tf(tslots["name"])  # name is always present — the
        # reference converter calls name()->str() unconditionally
        assert n_off != 0

    def test_we_parse_reference_payload(self):
        """A payload built by an independent flatbuffers Builder session
        mimicking tensordec-flatbuf.cc:115-149 decodes in our codec."""
        import flatbuffers as fb

        a = np.arange(12, dtype=np.int16).reshape(3, 4)
        b = fb.Builder(256)
        data_off = b.CreateByteVector(a.tobytes())
        b.StartVector(4, 4, 4)
        for d in reversed([4, 3, 1, 1]):
            b.PrependUint32(d)
        dim_off = b.EndVector()
        name_off = b.CreateString("scores")
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(0, name_off, 0)
        b.PrependInt32Slot(1, 2, 10)  # NNS_INT16, default NNS_END
        b.PrependUOffsetTRelativeSlot(2, dim_off, 0)
        b.PrependUOffsetTRelativeSlot(3, data_off, 0)
        t_off = b.EndObject()
        b.StartVector(4, 1, 4)
        b.PrependUOffsetTRelative(t_off)
        vec_off = b.EndVector()
        b.StartObject(4)
        b.PrependInt32Slot(0, 1, 0)
        b.Prep(4, 8)
        b.PrependInt32(1)   # rate_d
        b.PrependInt32(25)  # rate_n
        b.PrependStructSlot(1, b.Offset(), 0)
        b.PrependUOffsetTRelativeSlot(2, vec_off, 0)
        b.Finish(b.EndObject())

        out = flatbuf_codec.decode_flatbuf(bytes(b.Output()))
        assert out.num_tensors == 1
        assert out.tensors[0].shape == (1, 1, 3, 4)
        np.testing.assert_array_equal(out.tensors[0].reshape(3, 4), a)
        assert str(out.meta["framerate"]) == "25/1"
        assert out.meta["format"] == "static"
        assert out.meta["tensor_names"] == ["scores"]

    def test_fp16_refused(self):
        buf = TensorBuffer([np.zeros((2, 2), np.float16)])
        with pytest.raises(ValueError, match="tensor_type"):
            flatbuf_codec.encode_flatbuf(buf)

    def test_rank5_refused(self):
        buf = TensorBuffer([np.zeros((1, 2, 3, 4, 5), np.float32)])
        with pytest.raises(ValueError, match="nnstpu-flex"):
            flatbuf_codec.encode_flatbuf(buf)

    def test_flatc_generated_cross_proof(self, tmp_path):
        """Full generated-code cross-proof when flatc is installed
        (skip-gated; the schema-text proof above always runs)."""
        import shutil
        import subprocess
        import sys

        if shutil.which("flatc") is None:
            pytest.skip("flatc unavailable")
        subprocess.run(["flatc", "--python", "-o", str(tmp_path), _REF_FBS],
                       check=True, capture_output=True)
        sys.path.insert(0, str(tmp_path))
        try:
            from nnstreamer.flatbuf.Tensors import Tensors  # noqa: E501

            from nnstreamer_tpu.tensors.types import Fraction

            blob = flatbuf_codec.encode_flatbuf(_buf(),
                                                rate=Fraction(30, 1))
            msg = Tensors.GetRootAs(blob, 0)
            assert msg.NumTensor() == 3
            assert (msg.Fr().RateN(), msg.Fr().RateD()) == (30, 1)
            t0 = msg.Tensor(0)
            assert t0.Type() == 7  # NNS_FLOAT32
            assert [t0.Dimension(j) for j in range(4)] == [4, 3, 2, 1]
            np.testing.assert_array_equal(
                t0.DataAsNumpy().view(np.float32).reshape(2, 3, 4),
                _buf().tensors[0])
        finally:
            sys.path.remove(str(tmp_path))
