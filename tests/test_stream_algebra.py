"""Tests for mux/demux/merge/split/tee/join + sync policies, aggregator,
rate, tensor_if, crop, repo recurrence, sparse enc/dec (reference test
groups: nnstreamer_mux, nnstreamer_demux, nnstreamer_merge, nnstreamer_split,
nnstreamer_if, nnstreamer_repo_*, transform_*, unittest_rate)."""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.pipeline.pipeline import Pipeline


def run_pipeline(desc, timeout=30):
    pipe = parse_launch(desc)
    msg = pipe.run(timeout=timeout)
    assert msg is not None and msg.kind == "eos", f"pipeline failed: {msg}"
    return pipe


class TestMuxDemux:
    def test_mux_two_sources(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=4 width=8 height=8 ! tensor_converter ! mux.  "
            "videotestsrc num-buffers=4 width=4 height=4 ! tensor_converter ! mux.  "
            "tensor_mux name=mux ! tensor_sink name=out"
        )
        bufs = pipe.get("out").buffers
        assert len(bufs) == 4
        assert bufs[0].num_tensors == 2
        assert bufs[0][0].shape == (1, 8, 8, 3)
        assert bufs[0][1].shape == (1, 4, 4, 3)

    def test_mux_caps_announced(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! mux.  "
            "videotestsrc num-buffers=2 width=4 height=4 ! tensor_converter ! mux.  "
            "tensor_mux name=mux ! tensor_sink name=out"
        )
        caps = pipe.get("out").sinkpad.caps
        assert caps["num_tensors"] == 2
        assert caps["dimensions"] == "3:8:8:1,3:4:4:1"

    def test_demux_tensorpick(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=3 width=8 height=8 ! tensor_converter ! mux.  "
            "videotestsrc num-buffers=3 width=4 height=4 ! tensor_converter ! mux.  "
            "tensor_mux name=mux ! tensor_demux name=d tensorpick=1 ! "
            "tensor_sink name=out"
        )
        bufs = pipe.get("out").buffers
        assert len(bufs) == 3
        assert bufs[0].num_tensors == 1
        assert bufs[0][0].shape == (1, 4, 4, 3)

    def test_demux_two_branches(self):
        from nnstreamer_tpu.pipeline.parse import parse_launch as pl

        pipe = pl(
            "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! mux.  "
            "audiotestsrc num-buffers=2 samplesperbuffer=64 ! tensor_converter ! mux.  "
            "tensor_mux name=mux ! tensor_demux name=d  "
            "d. ! tensor_sink name=video_out  "
            "d. ! tensor_sink name=audio_out"
        )
        msg = pipe.run(timeout=30)
        assert msg.kind == "eos"
        assert pipe.get("video_out").buffers[0][0].dtype == np.uint8
        assert pipe.get("audio_out").buffers[0][0].dtype == np.int16


class TestMergeSplit:
    def test_merge_batches_on_dim(self):
        # two 8x8 frames merged along dim 3 (outermost/N) -> batch of 2
        pipe = run_pipeline(
            "videotestsrc num-buffers=3 width=8 height=8 ! tensor_converter ! m.  "
            "videotestsrc num-buffers=3 width=8 height=8 pattern=black ! "
            "tensor_converter ! m.  "
            "tensor_merge name=m mode=linear option=3 ! tensor_sink name=out"
        )
        bufs = pipe.get("out").buffers
        assert len(bufs) == 3
        assert bufs[0][0].shape == (2, 8, 8, 3)  # batched!

    def test_split_inverse_of_merge(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! "
            "tensor_split name=s tensorseg=4,4 dimension=1 ! "
            "tensor_sink name=o1  s. ! tensor_sink name=o2"
        )
        o1, o2 = pipe.get("o1").buffers, pipe.get("o2").buffers
        assert o1[0][0].shape == (1, 8, 4, 3)
        assert o2[0][0].shape == (1, 8, 4, 3)

    def test_split_bad_seg_errors(self):
        from nnstreamer_tpu.pipeline.element import FlowError

        pipe = parse_launch(
            "videotestsrc num-buffers=1 width=8 height=8 ! tensor_converter ! "
            "tensor_split tensorseg=3,3 dimension=1 ! fakesink"
        )
        with pytest.raises(FlowError, match="tensorseg sums"):
            pipe.run(timeout=15)


class TestTeeJoin:
    def test_tee_fanout(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=3 width=8 height=8 ! tensor_converter ! "
            "tee name=t  t. ! tensor_sink name=a  t. ! tensor_sink name=b"
        )
        assert len(pipe.get("a").buffers) == 3
        assert len(pipe.get("b").buffers) == 3

    def test_join_interleaves(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! j.  "
            "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! j.  "
            "join name=j ! tensor_sink name=out"
        )
        assert len(pipe.get("out").buffers) == 4


class TestAggregator:
    def test_sliding_window(self):
        # 8 frames of 16 samples -> windows of 32 samples, flush 16 (overlap)
        pipe = run_pipeline(
            "audiotestsrc num-buffers=8 samplesperbuffer=16 ! "
            "tensor_converter ! "
            "tensor_aggregator frames-in=16 frames-out=32 frames-flush=16 "
            "frames-dim=1 ! tensor_sink name=out"
        )
        bufs = pipe.get("out").buffers
        assert len(bufs) == 7  # sliding: (128-32)/16 + 1
        assert bufs[0][0].shape == (32, 1)

    def test_disaggregate(self):
        pipe = run_pipeline(
            "audiotestsrc num-buffers=2 samplesperbuffer=64 ! "
            "tensor_converter ! "
            "tensor_aggregator frames-in=64 frames-out=16 frames-dim=1 ! "
            "tensor_sink name=out"
        )
        bufs = pipe.get("out").buffers
        assert len(bufs) == 8
        assert bufs[0][0].shape == (16, 1)


class TestAggregatorMultiTensor:
    def test_all_tensors_aggregated(self):
        """2-tensor frames: both positions window and concat (nothing
        silently dropped, tensor_aggregator.c parity)."""
        from nnstreamer_tpu.pipeline.pipeline import Pipeline
        from nnstreamer_tpu.elements.source import AppSrc
        from nnstreamer_tpu.elements.aggregator import TensorAggregator
        from nnstreamer_tpu.elements.sink import TensorSink

        src = AppSrc(name="a")
        agg = TensorAggregator(frames_out=3, frames_dim=1)
        sink = TensorSink()
        pipe = Pipeline().add(src, agg, sink)
        src.link(agg).link(sink)
        pipe.start()
        for k in range(3):
            src.push([np.full((1, 4), k, np.float32),
                      np.full((2, 2), 10 + k, np.int32)])
        src.end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        assert len(sink.buffers) == 1
        out = sink.buffers[0]
        assert out.num_tensors == 2
        assert out[0].shape == (3, 4)
        assert out[1].shape == (6, 2)
        np.testing.assert_array_equal(out[0][:, 0], [0, 1, 2])

    def test_tensor_count_change_raises(self):
        from nnstreamer_tpu.elements.aggregator import TensorAggregator
        from nnstreamer_tpu.tensors.buffer import TensorBuffer
        import pytest as _pytest

        agg = TensorAggregator(frames_out=4)
        agg.chain(agg.sinkpads[0],
                  TensorBuffer([np.zeros((1, 2)), np.zeros((1, 2))]))
        with _pytest.raises(Exception, match="tensors"):
            agg._chain_entry(agg.sinkpads[0],
                             TensorBuffer([np.zeros((1, 2))]))


class TestRate:
    def test_downsample(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=30 width=4 height=4 framerate=30/1 ! "
            "tensor_converter ! tensor_rate name=r framerate=10/1 ! "
            "tensor_sink name=out"
        )
        n = len(pipe.get("out").buffers)
        assert 9 <= n <= 11
        assert pipe.get("r").dropped > 0
        caps = pipe.get("out").sinkpad.caps
        assert caps["framerate"] == "10/1"


class TestIf:
    def test_average_branch(self):
        # smpte bars have high average; black is 0 → then=passthrough for
        # bright frames only
        pipe = run_pipeline(
            "videotestsrc num-buffers=4 width=8 height=8 pattern=black ! "
            "tensor_converter ! "
            "tensor_if name=i compared-value=TENSOR_AVERAGE_VALUE "
            "compared-value-option=0 operator=gt supplied-value=10 "
            "then=PASSTHROUGH else=SKIP ! tensor_sink name=bright"
        )
        assert len(pipe.get("bright").buffers) == 0  # black never passes

        pipe2 = run_pipeline(
            "videotestsrc num-buffers=4 width=8 height=8 pattern=smpte ! "
            "tensor_converter ! "
            "tensor_if compared-value=TENSOR_AVERAGE_VALUE "
            "compared-value-option=0 operator=gt supplied-value=10 "
            "then=PASSTHROUGH else=SKIP ! tensor_sink name=bright"
        )
        assert len(pipe2.get("bright").buffers) == 4

    def test_custom_condition(self):
        from nnstreamer_tpu.elements.cond import register_if_condition

        register_if_condition("every_other",
                              lambda buf: (buf.pts or 0) % 2 == 0)
        pipe = run_pipeline(
            "videotestsrc num-buffers=4 width=4 height=4 ! tensor_converter ! "
            "tensor_if compared-value=CUSTOM compared-value-option=every_other "
            "then=PASSTHROUGH else=SKIP ! tensor_sink name=out"
        )
        assert len(pipe.get("out").buffers) == 2


class TestRepoRecurrence:
    def test_loop_accumulates(self):
        """RNN-style loop: state' = state + 1 each iteration via repo
        (reference tests/nnstreamer_repo_rnn pattern with a trivial model)."""
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("2", "float32")
        register_custom_easy(
            "inc", lambda ins: [np.asarray(ins[0]) + 1.0], info, info
        )
        pipe = run_pipeline(
            "tensor_reposrc slot=loop0 num-buffers=5 initial-dim=2 "
            "initial-type=float32 initial-value=0 timeout=5 ! "
            "tensor_filter framework=custom-easy model=inc ! "
            "tee name=t  t. ! tensor_reposink slot=loop0  "
            "t. ! tensor_sink name=out"
        )
        outs = pipe.get("out").buffers
        assert len(outs) == 5
        np.testing.assert_array_equal(outs[-1][0],
                                      np.full((2,), 5.0, np.float32))


class TestSparse:
    def test_roundtrip_pipeline(self):
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("3:8:8:1", "uint8")
        register_custom_easy(
            "sparsify",
            lambda ins: [np.where(np.asarray(ins[0]) > 200,
                                  np.asarray(ins[0]), 0)],
            info, info,
        )
        pipe = run_pipeline(
            "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! "
            "tensor_filter framework=custom-easy model=sparsify ! "
            "tee name=t  t. ! tensor_sink name=ref  "
            "t. ! tensor_sparse_enc ! tensor_sparse_dec ! tensor_sink name=out"
        )
        ref = pipe.get("ref").buffers
        out = pipe.get("out").buffers
        for r, o in zip(ref, out):
            np.testing.assert_array_equal(np.asarray(r[0]), o[0])

    def test_sparse_smaller_for_sparse_data(self):
        from nnstreamer_tpu.elements.sparse import sparse_encode

        dense = np.zeros((100, 100), np.float32)
        dense[3, 7] = 1.0
        assert len(sparse_encode(dense)) < dense.nbytes // 10


class TestCrop:
    def test_crop_regions(self):
        from nnstreamer_tpu.pipeline.pipeline import Pipeline
        from nnstreamer_tpu.elements.source import AppSrc
        from nnstreamer_tpu.elements.crop import TensorCrop
        from nnstreamer_tpu.elements.sink import TensorSink

        img_src, info_src = AppSrc(name="img"), AppSrc(name="info")
        crop, sink = TensorCrop(), TensorSink()
        pipe = Pipeline().add(img_src, info_src, crop, sink)
        img_src.srcpad.link(crop.raw_pad)
        info_src.srcpad.link(crop.info_pad)
        crop.link(sink)

        img = np.arange(16 * 16 * 3, dtype=np.uint8).reshape(1, 16, 16, 3)
        regions = np.array([[2, 3, 4, 5], [0, 0, 8, 8]], np.int32)
        pipe.start()
        img_src.push([img], pts=0)
        info_src.push([regions], pts=0)
        img_src.end_of_stream()
        info_src.end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        out = sink.buffers[0]
        assert out.num_tensors == 2
        assert out[0].shape == (5, 4, 3)
        assert out[1].shape == (8, 8, 3)
        np.testing.assert_array_equal(out[1], img[0, :8, :8])

    def _crop_pipe(self, **props):
        from nnstreamer_tpu.pipeline.pipeline import Pipeline
        from nnstreamer_tpu.elements.source import AppSrc
        from nnstreamer_tpu.elements.crop import TensorCrop
        from nnstreamer_tpu.elements.sink import TensorSink

        img_src, info_src = AppSrc(name="img"), AppSrc(name="info")
        crop, sink = TensorCrop(**props), TensorSink()
        pipe = Pipeline().add(img_src, info_src, crop, sink)
        img_src.srcpad.link(crop.raw_pad)
        info_src.srcpad.link(crop.info_pad)
        crop.link(sink)
        return pipe, img_src, info_src, sink

    def test_multi_tensor_frames(self):
        """every data tensor is cropped per region (tensor_crop.c parity:
        multi-tensor raw frames are not silently truncated)."""
        pipe, img_src, info_src, sink = self._crop_pipe()
        a = np.arange(16 * 16 * 3, dtype=np.uint8).reshape(1, 16, 16, 3)
        b = np.arange(16 * 16, dtype=np.float32).reshape(16, 16)
        regions = np.array([[0, 0, 4, 4], [8, 8, 2, 2]], np.int32)
        pipe.start()
        img_src.push([a, b], pts=0)
        info_src.push([regions], pts=0)
        img_src.end_of_stream()
        info_src.end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        out = sink.buffers[0]
        # region-major: r0(a, b), r1(a, b)
        assert out.num_tensors == 4
        assert out[0].shape == (4, 4, 3)
        assert out[1].shape == (4, 4)
        assert out[2].shape == (2, 2, 3)
        np.testing.assert_array_equal(out[1], b[:4, :4])
        np.testing.assert_array_equal(out[3], b[8:10, 8:10])
        assert out.meta["crop_num_tensors"] == 2

    def test_lateness_drops_old_info(self):
        """|pts diff| > lateness drops the older buffer and pairs the
        newer one with the next arrival (tensor_crop.c:734-759)."""
        pipe, img_src, info_src, sink = self._crop_pipe(lateness=10)
        img = np.zeros((1, 8, 8, 3), np.uint8)
        r = np.array([[0, 0, 2, 2]], np.int32)
        pipe.start()
        # info frame way older than raw (1s vs 0): dropped, next info pairs
        info_src.push([r], pts=0)
        img_src.push([img], pts=1_000_000_000)
        info_src.push([np.array([[0, 0, 3, 3]], np.int32)],
                      pts=1_000_000_000)
        img_src.end_of_stream()
        info_src.end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        assert len(sink.buffers) == 1
        assert sink.buffers[0][0].shape == (3, 3, 3)  # the NEWER info won

    def test_lateness_disabled_by_default(self):
        pipe, img_src, info_src, sink = self._crop_pipe()
        img = np.zeros((1, 8, 8, 3), np.uint8)
        pipe.start()
        info_src.push([np.array([[0, 0, 2, 2]], np.int32)], pts=0)
        img_src.push([img], pts=5_000_000_000)  # 5s apart: still pairs
        img_src.end_of_stream()
        info_src.end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        assert len(sink.buffers) == 1
        assert sink.buffers[0][0].shape == (2, 2, 3)



class TestRepoDynamicity:
    """Runtime slot switching (reference nnstreamer_repo_dynamicity:
    tensor_repo_dynamic_test.c flips reposink's slot mid-stream)."""

    def test_switch_slot_mid_stream(self):
        from nnstreamer_tpu.elements.repo import GLOBAL_REPO, TensorRepoSink

        sink = TensorRepoSink(slot="dyn_a")
        from nnstreamer_tpu.elements.source import AppSrc

        pipe = Pipeline()
        src = AppSrc(name="src")
        pipe.add(src, sink)
        src.link(sink)
        pipe.start()
        try:
            src.push([np.full(4, 1.0, np.float32)], pts=0)
            # AppSrc delivers on its own thread — wait until frame 0 has
            # landed before switching (the reference flips the property
            # from a pad probe, i.e. also after delivery)
            assert GLOBAL_REPO.get("dyn_a", timeout=10) is not None
            sink.set_property("slot", "dyn_b")  # runtime switch
            src.push([np.full(4, 2.0, np.float32)], pts=1)
            src.end_of_stream()
            pipe.wait(timeout=30)
            a = GLOBAL_REPO.get("dyn_a", timeout=5, consume=True)
            b = GLOBAL_REPO.get("dyn_b", timeout=5, consume=True)
            np.testing.assert_array_equal(np.asarray(a[0]),
                                          np.full(4, 1.0, np.float32))
            np.testing.assert_array_equal(np.asarray(b[0]),
                                          np.full(4, 2.0, np.float32))
        finally:
            pipe.stop()


class TestQuantEncDec:
    """int8 stream transcoding — the dense-activation peer of sparse
    enc/dec (elements/quant.py; device kernels in ops/quantize.py)."""

    def test_roundtrip_accuracy_and_size(self):
        rng = np.random.default_rng(11)
        x = rng.normal(0, 1, (64, 32)).astype(np.float32)
        from nnstreamer_tpu.elements.quant import quant_decode, quant_encode

        blob = quant_encode(x)
        assert len(blob) < x.nbytes / 2  # ~4x smaller than float32
        back, _ = quant_decode(blob)
        assert back.shape == x.shape and back.dtype == x.dtype
        # absmax int8: error bounded by scale/2
        scale = np.abs(x).max() / 127.0
        assert np.abs(back - x).max() <= scale * 0.5 + 1e-6

    def test_pipeline_roundtrip(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=3 width=8 height=8 pattern=gradient ! "
            "tensor_converter ! tensor_transform mode=arithmetic "
            "option=typecast:float32,div:255 ! "
            "tensor_quant_enc ! tensor_quant_dec ! tensor_sink name=out")
        ref = run_pipeline(
            "videotestsrc num-buffers=3 width=8 height=8 pattern=gradient ! "
            "tensor_converter ! tensor_transform mode=arithmetic "
            "option=typecast:float32,div:255 ! tensor_sink name=out")
        outs = pipe.get("out").buffers
        refs = ref.get("out").buffers
        assert len(outs) == len(refs) == 3
        for o, r in zip(outs, refs):
            a, b = np.asarray(o[0]), np.asarray(r[0])
            assert a.shape == b.shape
            assert np.abs(a - b).max() <= (np.abs(b).max() / 127.0) * 0.5 + 1e-6

    def test_offload_with_quant_transport(self):
        """query offload with int8-compressed payloads: enc on the client,
        dec server-side before the filter."""
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("4", "float32")
        register_custom_easy("qpass", lambda ins: [np.asarray(ins[0]) + 1.0],
                             info, info)
        server = parse_launch(
            "tensor_query_serversrc name=ss port=0 id=41 ! tensor_quant_dec ! "
            "tensor_filter framework=custom-easy model=qpass ! "
            "tensor_query_serversink id=41")
        server.start()
        try:
            port = server.get("ss").port
            from nnstreamer_tpu.elements.sink import TensorSink
            from nnstreamer_tpu.elements.source import AppSrc

            client = parse_launch(
                f"tensor_quant_enc name=enc ! tensor_query_client "
                f"dest-host=127.0.0.1 dest-port={port}")
            src, sink = AppSrc(name="src"), TensorSink(name="out")
            client.add(src, sink)
            src.link(client.get("enc"))
            qc = [e for e in client.elements
                  if e.ELEMENT_NAME == "tensor_query_client"][0]
            qc.link(sink)
            client.start()
            src.push([np.array([1.0, -2.0, 3.0, 0.5], np.float32)], pts=0)
            src.end_of_stream()
            msg = client.wait(timeout=60)
            assert msg is not None and msg.kind == "eos", msg
            out = np.asarray(sink.buffers[0][0])
            np.testing.assert_allclose(
                out, [2.0, -1.0, 4.0, 1.5], atol=3 / 127.0)
        finally:
            client.stop()
            server.stop()

    def test_enc_consumes_deferred_finalize_once(self):
        """A buffer carrying a deferred finalize (fused-decoder output)
        must have it applied exactly once by the transcoder, never leaked
        downstream (code-review regression)."""
        from nnstreamer_tpu.elements.quant import TensorQuantEnc
        from nnstreamer_tpu.elements.sparse import TensorSparseEnc
        from nnstreamer_tpu.tensors.buffer import TensorBuffer

        calls = []

        def finalize(host_buf):
            calls.append(1)
            return host_buf.with_tensors(
                [np.asarray(host_buf[0]) * 2.0])

        for enc_cls in (TensorQuantEnc, TensorSparseEnc):
            calls.clear()
            enc = enc_cls()
            got = []
            enc.srcpad.push = lambda b: got.append(b)  # capture output
            buf = TensorBuffer([np.ones(4, np.float32)], pts=0,
                               finalize=finalize)
            enc.chain(enc.sinkpads[0], buf)
            assert calls == [1], enc_cls.__name__
            assert got[0].finalize is None  # not leaked downstream
            got[0].to_host()
            assert calls == [1]  # still once

    def test_decode_rejects_non_quant_payload(self):
        """Mis-wired streams (sparse blob, random bytes, truncation) must
        raise a protocol error, not emit garbage (code-review regression)."""
        from nnstreamer_tpu.elements.quant import quant_decode, quant_encode
        from nnstreamer_tpu.elements.sparse import sparse_encode

        with pytest.raises(ValueError, match="magic"):
            quant_decode(sparse_encode(np.zeros((4, 4), np.float32)))
        blob = quant_encode(np.ones((8,), np.float32))
        with pytest.raises(ValueError, match="truncated"):
            quant_decode(blob[:-3])

    def test_integer_roundtrip_rounds_to_nearest(self):
        from nnstreamer_tpu.elements.quant import quant_decode, quant_encode

        x = np.arange(0, 256, 1, dtype=np.uint8)
        back, _ = quant_decode(quant_encode(x))
        assert back.dtype == np.uint8
        scale = 255.0 / 127.0
        # nearest-rounding: error bounded by scale/2 + 0.5 cast rounding
        assert np.abs(back.astype(int) - x.astype(int)).max() <= \
            int(np.ceil(scale / 2 + 0.5))


class TestRateThrottleQos:
    """tensor_rate throttle=true posts QoS upstream so the *filter* skips
    invokes for frames that would be dropped (gsttensorrate.c:27-36)."""

    DESC = (
        "videotestsrc num-buffers=20 width=4 height=4 framerate=1000/1 ! "
        "tensor_converter ! tensor_transform mode=typecast option=float32 ! "
        "tensor_filter framework=jax model=qos_id name=f ! "
        "tensor_rate name=r framerate=2/1 throttle={throttle} ! "
        "tensor_sink name=out"
    )

    def setup_method(self):
        from nnstreamer_tpu.filters.jax_backend import register_jax_model

        register_jax_model("qos_id", lambda x: x * 1.0)

    def teardown_method(self):
        from nnstreamer_tpu.filters.jax_backend import unregister_jax_model

        unregister_jax_model("qos_id")

    @staticmethod
    def _invokes():
        from nnstreamer_tpu.filters.jax_backend import JaxFilter

        return JaxFilter.global_stats().snapshot()["total_invokes"]

    def test_throttled_filter_skips_invokes(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_FUSE", "0")  # count fw.invoke directly
        before = self._invokes()
        run_pipeline(self.DESC.format(throttle="true"))
        # 20 frames arrive within milliseconds; QoS demands >=500ms between
        # invokes, so the filter must have run only a handful of times
        assert self._invokes() - before <= 3

    def test_unthrottled_filter_runs_every_frame(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_FUSE", "0")
        before = self._invokes()
        run_pipeline(self.DESC.format(throttle="false"))
        assert self._invokes() - before == 20

    def test_qos_throttles_fused_region_too(self):
        """with fusion on, the filter is spliced into a FusedRegion — the
        QoS must throttle the region's dispatch instead."""
        pipe = run_pipeline(self.DESC.format(throttle="true"))
        outs = len(pipe.get("out").buffers)
        assert outs <= 3, outs

    def test_fused_region_passes_all_without_throttle(self):
        pipe = run_pipeline(self.DESC.format(throttle="false"))
        # rate alone still drops by pts (1000fps -> 2fps over 20ms of
        # stream time: ~1 frame), but nothing upstream is skipped; the
        # filter's QoS state stays unset
        assert getattr(pipe.get("f"), "_qos_interval_s", 0.0) == 0.0

    def test_qos_event_reaches_filter_directly(self):
        from nnstreamer_tpu import parse_launch
        from nnstreamer_tpu.pipeline.element import QosEvent

        pipe = parse_launch(
            "appsrc name=a ! "
            "tensor_filter framework=jax model=qos_id name=f ! "
            "tensor_sink name=s")
        pipe.get("s").sinkpad.push_upstream_event(
            QosEvent(target_interval_ns=250_000_000))
        assert pipe.get("f")._qos_interval_s == 0.25
        # lifting the throttle
        pipe.get("s").sinkpad.push_upstream_event(QosEvent(0))
        assert pipe.get("f")._qos_interval_s == 0.0

    def test_downstream_plain_rate_does_not_cancel_throttle(self, monkeypatch):
        """a second tensor_rate with NO framerate must stay silent at caps
        time, not post QosEvent(0) that cancels the upstream throttle."""
        monkeypatch.setenv("NNSTPU_FUSE", "0")
        pipe = run_pipeline(
            "videotestsrc num-buffers=2 width=4 height=4 "
            "framerate=1000/1 ! tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_filter framework=jax model=qos_id name=f ! "
            "tensor_rate framerate=2/1 throttle=true ! "
            "tensor_rate ! tensor_sink name=out")
        assert pipe.get("f")._qos_interval_s == 0.5
