"""Continuous-batching serving engine (serving/engine.py).

Correctness bar: a stream's output must be IDENTICAL whether it runs
alone through the manual prefill+decode loop or shares the engine's
batch with other streams at arbitrary admission times — per-stream
results never depend on batch composition.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    build_decode_step,
    build_prefill,
    init_params,
    make_sampler,
)
from nnstreamer_tpu.serving import ContinuousBatchingEngine  # noqa: E402

CFG = TransformerConfig(vocab=97, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, max_seq=64, dtype=jnp.float32)
PARAMS = init_params(CFG, seed=3)


def reference_greedy(prompt, n_tokens, cfg=CFG, params=PARAMS):
    """Exact-length prefill + one-at-a-time greedy decode (no padding,
    no batching) — the ground truth the engine must match."""
    prefill = jax.jit(build_prefill(cfg))
    decode = jax.jit(build_decode_step(cfg))
    tokens = jnp.asarray(np.asarray(prompt, np.int32)[None])
    logits, cache1 = prefill(params, tokens)
    out = [int(jnp.argmax(logits[0]))]
    # engine caches are batch-B; replicate slot 0 semantics with batch 1
    tok = jnp.asarray([out[0]], jnp.int32)
    pos = jnp.asarray(len(prompt), jnp.int32)
    cache = cache1
    for _ in range(n_tokens - 1):
        logits, cache = decode(params, tok, cache, pos)
        nxt = int(jnp.argmax(logits[0]))
        out.append(nxt)
        tok = jnp.asarray([nxt], jnp.int32)
        pos = pos + 1
    return out


@pytest.fixture(scope="module")
def engine():
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=3, steps_per_dispatch=4,
        temperature=0.0).start()
    yield eng
    eng.stop()


def test_single_stream_matches_manual_decode(engine):
    prompt = [5, 11, 23, 42, 7]
    got = engine.generate(prompt, max_new_tokens=13, timeout=120)
    assert got == reference_greedy(prompt, 13)


def test_bucketed_prefill_matches_exact_length(engine):
    # prompt lengths straddling a bucket edge (engine pads to 16/32)
    for prompt in ([3], [9, 2, 4] * 5, list(range(1, 18))):
        got = engine.generate(prompt, max_new_tokens=6, timeout=120)
        assert got == reference_greedy(prompt, 6), f"len={len(prompt)}"


def test_concurrent_streams_match_isolated_runs(engine):
    prompts = [[4, 8, 15], [16, 23], [42, 7, 9, 1], [2, 2, 2, 2, 2],
               [31, 59, 26, 53]]
    streams = [engine.submit(p, max_new_tokens=9) for p in prompts]
    results = [s.result(timeout=240) for s in streams]
    for p, got in zip(prompts, results):
        assert got == reference_greedy(p, 9), f"prompt={p}"


def test_more_streams_than_slots_all_complete(engine):
    # 7 submissions on 3 slots: admission must recycle slots
    prompts = [[i + 1, i + 2] for i in range(7)]
    streams = [engine.submit(p, max_new_tokens=5) for p in prompts]
    for p, s in zip(prompts, streams):
        assert s.result(timeout=240) == reference_greedy(p, 5)
    assert engine.active_streams == 0


def test_eos_truncates_stream(engine):
    prompt = [5, 11, 23, 42, 7]
    ref = reference_greedy(prompt, 12)
    eos = ref[4]  # a token the model will actually emit
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, eos_id=eos).start()
    try:
        s = eng.submit(prompt, max_new_tokens=12)
        got = s.result(timeout=120)
    finally:
        eng.stop()
    stop_at = ref.index(eos)
    assert got == ref[: stop_at + 1]
    assert s.finish_reason == "eos"


def test_length_budget_respects_cache_window():
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=1, steps_per_dispatch=4,
        temperature=0.0).start()
    try:
        prompt = list(range(1, 60))  # 59 tokens, S=64 → at most 5 new
        s = eng.submit(prompt, max_new_tokens=50)
        got = s.result(timeout=120)
    finally:
        eng.stop()
    assert len(got) == CFG.max_seq - len(prompt)
    assert s.finish_reason == "length"


def test_sampled_streams_are_deterministic_per_stream_id():
    def run():
        eng = ContinuousBatchingEngine(
            CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
            temperature=0.8, top_k=8, seed=7).start()
        try:
            a = eng.submit([5, 6, 7], max_new_tokens=8)
            b = eng.submit([9, 10], max_new_tokens=8)
            return a.result(timeout=120), b.result(timeout=120)
        finally:
            eng.stop()

    r1, r2 = run(), run()
    assert r1 == r2  # same seed + stream ids → same draws
    assert all(0 <= t < CFG.vocab for t in r1[0] + r1[1])


def test_invalid_prompts_rejected(engine):
    with pytest.raises(ValueError):
        engine.submit([], max_new_tokens=3)
    with pytest.raises(ValueError):
        engine.submit(list(range(CFG.max_seq)), max_new_tokens=3)
    with pytest.raises(ValueError):
        engine.submit([1, 2], max_new_tokens=0)


def test_stop_finishes_inflight_streams():
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=1, steps_per_dispatch=2,
        temperature=0.0).start()
    s = eng.submit([1, 2, 3], max_new_tokens=10_000_000)
    eng.stop()
    assert s.finished
    with pytest.raises(RuntimeError):
        eng.submit([1, 2], max_new_tokens=4)  # stopped engine


def test_sharded_engine_matches_unsharded():
    """Multi-chip serving: a dp=2 × tp=2 mesh engine must emit exactly
    what the single-device engine does (GSPMD may not change results)."""
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh([("dp", 2), ("tp", 2)])
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=4, steps_per_dispatch=4,
        temperature=0.0, mesh=mesh).start()
    try:
        prompts = [[4, 8, 15], [16, 23, 9], [7, 7], [1, 2, 3, 4, 5]]
        streams = [eng.submit(p, max_new_tokens=7) for p in prompts]
        results = [s.result(timeout=240) for s in streams]
    finally:
        eng.stop()
    for p, got in zip(prompts, results):
        assert got == reference_greedy(p, 7), f"prompt={p}"


def test_dp_only_mesh_serving():
    """A mesh with no tp axis (pure data-parallel serving) must work —
    param specs naming absent axes are pruned to replicated."""
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh([("dp", 2)])
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, mesh=mesh).start()
    try:
        got = eng.generate([5, 11, 23, 42, 7], max_new_tokens=6,
                           timeout=240)
    finally:
        eng.stop()
    assert got == reference_greedy([5, 11, 23, 42, 7], 6)


def test_sharded_engine_validates_divisibility():
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh([("dp", 1), ("tp", 8)])  # CFG.n_heads == 4
    with pytest.raises(ValueError):
        ContinuousBatchingEngine(CFG, PARAMS, max_streams=4, mesh=mesh)


def test_chunked_prefill_matches_exact():
    """Chunked ingestion (C=8) must be bit-identical to whole-prompt
    prefill for lengths below/at/above chunk boundaries."""
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, prefill_chunk=8).start()
    try:
        for n in (1, 7, 8, 9, 16, 20, 37):
            prompt = [(i * 13 + 5) % CFG.vocab for i in range(n)]
            got = eng.generate(prompt, max_new_tokens=6, timeout=240)
            assert got == reference_greedy(prompt, 6), f"len={n}"
    finally:
        eng.stop()


def test_chunked_prefill_interleaves_with_decode():
    """A long prompt admitted while another stream decodes: both exact
    (prefill chunks run between decode dispatches, not instead of them)."""
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=2,
        temperature=0.0, prefill_chunk=4).start()
    try:
        a = eng.submit([5, 11, 23], max_new_tokens=20)
        long_prompt = [(i * 7 + 2) % CFG.vocab for i in range(30)]
        b = eng.submit(long_prompt, max_new_tokens=8)
        ra, rb = a.result(timeout=240), b.result(timeout=240)
    finally:
        eng.stop()
    assert ra == reference_greedy([5, 11, 23], 20)
    assert rb == reference_greedy(long_prompt, 8)
    assert eng.stats["prefill_chunks"] >= 8 + 1  # 30/4 → 8 + short prompt


def test_chunked_prefill_prompt_limit():
    """The bound is ceil(n/C)*C <= S: when C divides S it equals the
    plain n < S rule (no capacity lost); otherwise the last partial
    chunk must still fit the cache."""
    # C=8 divides S=64: same capacity as the unchunked engine (63)
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=1, prefill_chunk=8).start()
    try:
        assert len(eng.generate(list(range(1, 64)), max_new_tokens=5,
                                timeout=240)) == 1  # budget S-63 = 1
    finally:
        eng.stop()
    # C=12 does not divide S=64: limit is (64//12)*12 = 60
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=1, prefill_chunk=12).start()
    try:
        with pytest.raises(ValueError):
            eng.submit(list(range(61)), max_new_tokens=2)
        assert len(eng.generate(list(range(60)), max_new_tokens=9,
                                timeout=240)) == 4  # budget S-60 = 4
    finally:
        eng.stop()
    with pytest.raises(ValueError):
        ContinuousBatchingEngine(CFG, PARAMS, prefill_chunk=CFG.max_seq)


def test_prefix_cache_exact_hit_skips_prefill():
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, prefix_cache=4).start()
    try:
        prompt = [5, 11, 23, 42]
        a = eng.generate(prompt, max_new_tokens=7, timeout=240)
        prefills_before = eng.stats["prefills"]
        b = eng.generate(prompt, max_new_tokens=7, timeout=240)
    finally:
        eng.stop()
    assert a == b == reference_greedy(prompt, 7)
    assert eng.stats["prefix_hits"] == 1
    assert eng.stats["prefix_tokens_reused"] == len(prompt)
    # the hit still counts as an admission ("prefills") but computed no
    # new prefill program — verified by exactness + the hit counter
    assert eng.stats["prefills"] == prefills_before + 1


def test_prefix_cache_extension_is_exact():
    """A ... then A+B: the warm engine's A+B output must equal a cold
    engine's — reused kv is the same array a cold prefill computes."""
    base = [7, 3, 11, 30, 2, 9]
    full = base + [14, 27, 5]
    cold = reference_greedy(full, 9)
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, prefix_cache=4).start()
    try:
        eng.generate(base, max_new_tokens=3, timeout=240)
        got = eng.generate(full, max_new_tokens=9, timeout=240)
    finally:
        eng.stop()
    assert got == cold
    assert eng.stats["prefix_hits"] == 1
    assert eng.stats["prefix_tokens_reused"] == len(base)


def test_prefix_cache_with_chunked_prefill():
    base = [(i * 13 + 5) % CFG.vocab for i in range(17)]
    full = base + [(i * 7 + 1) % CFG.vocab for i in range(9)]
    cold = reference_greedy(full, 6)
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, prefix_cache=4, prefill_chunk=8).start()
    try:
        eng.generate(base, max_new_tokens=3, timeout=240)
        chunks_before = eng.stats["prefill_chunks"]
        got = eng.generate(full, max_new_tokens=6, timeout=240)
        chunks_used = eng.stats["prefill_chunks"] - chunks_before
    finally:
        eng.stop()
    assert got == cold
    # resume at chunk boundary 16 (p=17 → base 16): 26 tokens need
    # chunks [16,24) and [24,32) — two, not ceil(26/8)=4
    assert chunks_used == 2
    assert eng.stats["prefix_tokens_reused"] == 16


def test_prefix_cache_shared_system_prompt():
    """Two DIFFERENT prompts sharing a preamble: the second reuses the
    common prefix of the first's cached kv (LCP match, not whole-entry
    match) and stays exact."""
    system = [9, 21, 33, 45, 2, 17, 8, 30]
    u1 = system + [50, 51]
    u2 = system + [60, 61, 62]
    cold_u2 = reference_greedy(u2, 8)
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, prefix_cache=4).start()
    try:
        eng.generate(u1, max_new_tokens=3, timeout=240)
        got = eng.generate(u2, max_new_tokens=8, timeout=240)
    finally:
        eng.stop()
    assert got == cold_u2
    assert eng.stats["prefix_hits"] == 1
    assert eng.stats["prefix_tokens_reused"] == len(system)


def test_prefix_cache_prompt_inside_longer_entry():
    """The new prompt is a strict PREFIX of a stored key: kv is reused
    for n-1 positions and the last position recomputes for its logits."""
    long_p = [5, 11, 23, 42, 7, 9, 14]
    short_p = long_p[:6]  # n-1 = 5 reusable, above PREFIX_MIN_REUSE
    ref = reference_greedy(short_p, 6)
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, prefix_cache=4).start()
    try:
        eng.generate(long_p, max_new_tokens=3, timeout=240)
        got = eng.generate(short_p, max_new_tokens=6, timeout=240)
    finally:
        eng.stop()
    assert got == ref
    assert eng.stats["prefix_hits"] == 1
    assert eng.stats["prefix_tokens_reused"] == len(short_p) - 1


def test_prefix_cache_exact_repeat_wins_over_longer_tie():
    """With both [1..5] and [1..3] cached, resubmitting [1..3] must take
    the zero-prefill exact path (stored logits), not the longer key."""
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=1, steps_per_dispatch=4,
        temperature=0.0, prefix_cache=4).start()
    try:
        eng.generate([1, 2, 3, 4, 5], max_new_tokens=3, timeout=240)
        first = eng.generate([1, 2, 3], max_new_tokens=3, timeout=240)
        reused_before = eng.stats["prefix_tokens_reused"]
        again = eng.generate([1, 2, 3], max_new_tokens=3, timeout=240)
        reused = eng.stats["prefix_tokens_reused"] - reused_before
    finally:
        eng.stop()
    assert first == again == reference_greedy([1, 2, 3], 3)
    assert reused == 3  # whole prompt, not len-1 via the longer key


def test_prefix_cache_validation():
    with pytest.raises(ValueError):
        ContinuousBatchingEngine(CFG, PARAMS, prefix_cache=-1)


def test_prefix_cache_lru_eviction():
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=1, steps_per_dispatch=4,
        temperature=0.0, prefix_cache=1).start()
    try:
        for p in ([1, 2], [3, 4], [5, 6]):
            eng.generate(p, max_new_tokens=3, timeout=240)
        assert len(eng._prefix) == 1
        # oldest evicted: repeating the first prompt is a miss
        eng.generate([1, 2], max_new_tokens=3, timeout=240)
        assert eng.stats["prefix_hits"] == 0
    finally:
        eng.stop()


def test_engine_invoke_stats_populated(engine):
    engine.generate([4, 4, 4], max_new_tokens=6, timeout=240)
    assert engine.invoke_stats.total_invokes >= 1
    assert engine.invoke_stats.latency_us > 0


def test_moe_model_serves_exactly():
    """A mixture-of-experts config through the whole engine path
    (prefill capture, batched decode, chunked prefill) must match the
    isolated greedy decode — MoE routing rides _block_tail everywhere."""
    moe_cfg = TransformerConfig(vocab=97, d_model=64, n_heads=4,
                                n_layers=2, d_ff=64, max_seq=64,
                                dtype=jnp.float32, num_experts=4)
    moe_params = init_params(moe_cfg, seed=6)
    prompt = [5, 11, 23, 42, 9, 1]
    ref = reference_greedy(prompt, 8, cfg=moe_cfg, params=moe_params)
    for kw in ({}, {"prefill_chunk": 4}):
        eng = ContinuousBatchingEngine(
            moe_cfg, moe_params, max_streams=2, steps_per_dispatch=4,
            temperature=0.0, **kw).start()
        try:
            got = eng.generate(prompt, max_new_tokens=8, timeout=240)
        finally:
            eng.stop()
        assert got == ref, kw


def test_min_p_sampling():
    """min_p truncation: drawn tokens always satisfy p >= min_p * p_max;
    min_p=1.0 with temperature degenerates to greedy."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 2, (1, CFG.vocab)), jnp.float32)
    probs = np.asarray(jax.nn.softmax(logits[0]))
    sample = make_sampler(CFG.vocab, temperature=1.0, min_p=0.5)
    keys = np.asarray([[1, 2]], np.uint32)
    drawn = set()
    for _ in range(64):
        tok, keys = sample(logits, jnp.asarray(keys))
        drawn.add(int(tok[0]))
        keys = np.asarray(keys)
    assert all(probs[t] >= 0.5 * probs.max() - 1e-9 for t in drawn), drawn
    # engine-level: min_p=1.0 ≡ greedy even at temperature 1
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=1, steps_per_dispatch=4,
        temperature=1.0, min_p=1.0).start()
    try:
        got = eng.generate([5, 11, 23], max_new_tokens=6, timeout=240)
    finally:
        eng.stop()
    assert got == reference_greedy([5, 11, 23], 6)


def test_logprobs_parallel_and_correct(engine):
    prompt = [5, 11, 23]
    s = engine.submit(prompt, max_new_tokens=6)
    toks = s.result(timeout=240)
    assert len(s.logprobs) == len(toks) == 6
    assert all(lp <= 0.0 for lp in s.logprobs)
    # greedy: the reported logprob is the max of the fp32 log_softmax at
    # that step — check the first (prefill-seeded) token by hand
    import jax

    from nnstreamer_tpu.models.transformer import build_prefill

    logits, _ = jax.jit(build_prefill(CFG))(
        PARAMS, jnp.asarray(np.asarray(prompt, np.int32)[None]))
    expect = float(jax.nn.log_softmax(
        logits[0].astype(jnp.float32))[toks[0]])
    assert s.logprobs[0] == pytest.approx(expect, rel=1e-5)


def test_cancel_active_stream_frees_slot():
    import dataclasses

    # large cache → budget min(max_new, S-n) ≈ 500: the engine cannot
    # length-finish in the instants between first token and cancel, so
    # the "cancelled" outcome is deterministic
    cfg = dataclasses.replace(CFG, max_seq=512)
    eng = ContinuousBatchingEngine(
        cfg, PARAMS, max_streams=1, steps_per_dispatch=2,
        temperature=0.0).start()
    try:
        s = eng.submit([1, 2, 3], max_new_tokens=500)
        for _ in s:  # first token proves the stream is admitted + live
            s.cancel()
            break
        s.result(timeout=240)
        assert s.finish_reason == "cancelled"
        assert len(s.tokens) < 500
        # the single slot must be free again: a new stream completes
        got = eng.generate([4, 5], max_new_tokens=4, timeout=240)
        assert len(got) == 4
    finally:
        eng.stop()


def test_cancel_pending_stream_never_admits():
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=1, steps_per_dispatch=2,
        temperature=0.0).start()
    try:
        blocker = eng.submit([1, 2], max_new_tokens=200)  # hogs the slot
        pending = eng.submit([3, 4], max_new_tokens=5)
        pending.cancel()
        assert pending.result(timeout=120) == []
        assert pending.finish_reason == "cancelled"
        blocker.cancel()
    finally:
        eng.stop()


def test_dispatch_failure_fails_streams_and_recovers():
    """A device failure mid-dispatch must fail in-flight streams fast
    (no hang), rebuild the donated-away cache, and keep serving new
    requests — the engine's failure-detection contract."""
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0).start()
    try:
        real = eng._dispatch
        state = {"raised": False}

        def flaky(*args):
            if not state["raised"]:
                state["raised"] = True
                raise RuntimeError("injected device failure")
            return real(*args)

        eng._dispatch = flaky
        s = eng.submit([5, 11, 23], max_new_tokens=8)
        out = s.result(timeout=240)
        assert s.finish_reason == "error: injected device failure"
        assert out == s.tokens  # whatever was emitted pre-failure
        # engine recovered: fresh request completes correctly
        got = eng.generate([4, 8, 15], max_new_tokens=5, timeout=240)
        assert got == reference_greedy([4, 8, 15], 5)
    finally:
        eng.stop()


def test_concurrent_submit_stress():
    """Hammer submit() from many threads against few slots while streams
    complete and slots recycle: every stream must finish with the right
    token count and the engine must stay consistent (no deadlock, no
    dropped request) — the reference relies on GLib locking discipline
    for its pipeline races (SURVEY §5); this is ours, exercised."""
    import threading

    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=2,
        temperature=0.0, prefix_cache=2).start()
    results, errors = {}, []

    def client(tid):
        try:
            out = []
            for i in range(3):
                prompt = [(tid * 7 + i * 3 + 1) % CFG.vocab + 1,
                          (tid + i) % CFG.vocab]
                out.append(eng.generate(prompt, max_new_tokens=4,
                                        timeout=300))
            results[tid] = out
        except Exception as e:  # noqa: BLE001 — collected for assertion
            errors.append((tid, e))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(6)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads), "stress deadlock"
    finally:
        eng.stop()
    assert not errors, errors
    assert len(results) == 6
    for tid, outs in results.items():
        for out in outs:
            assert len(out) == 4, (tid, outs)
    assert eng.active_streams == 0


def test_submit_before_start_rejected():
    eng = ContinuousBatchingEngine(CFG, PARAMS, max_streams=1)
    with pytest.raises(RuntimeError):
        eng.submit([1, 2], max_new_tokens=4)


class TestPrefixTrie:
    """O(prompt_len) LCP index replacing the linear scan
    (serving/engine.py _PrefixTrie)."""

    @staticmethod
    def _brute(keys, prompt):
        best_key, best_lcp = None, 0
        for key in keys:
            m = min(len(key), len(prompt))
            lcp = 0
            while lcp < m and key[lcp] == prompt[lcp]:
                lcp += 1
            exact = lcp == len(prompt) == len(key)
            if lcp > best_lcp or (exact and lcp >= best_lcp):
                best_key, best_lcp = key, lcp
        return best_lcp

    def test_matches_brute_force_with_eviction(self):
        import random

        from nnstreamer_tpu.serving.engine import _PrefixTrie

        rng = random.Random(7)
        trie, keys = _PrefixTrie(), []
        for step in range(400):
            if keys and rng.random() < 0.3:
                k = keys.pop(rng.randrange(len(keys)))
                trie.remove(k)
                continue
            k = tuple(rng.randrange(4) for _ in range(rng.randrange(1, 10)))
            if k not in keys:
                keys.append(k)
                trie.insert(k)
            prompt = [rng.randrange(4) for _ in range(rng.randrange(1, 12))]
            got_key, got_lcp = trie.lookup(prompt)
            want_lcp = self._brute(keys, prompt)
            assert got_lcp == want_lcp
            if got_lcp:
                # returned key really shares got_lcp tokens with prompt
                assert tuple(got_key[:got_lcp]) == tuple(prompt[:got_lcp])

    def test_exact_match_preferred(self):
        from nnstreamer_tpu.serving.engine import _PrefixTrie

        trie = _PrefixTrie()
        trie.insert((1, 2, 3, 4, 5))  # longer key covering the prompt
        trie.insert((1, 2, 3))        # exact
        key, lcp = trie.lookup([1, 2, 3])
        assert key == (1, 2, 3) and lcp == 3

    def test_lookup_cost_is_prompt_bound(self):
        """visits are bounded by prompt length, not entry count."""
        from nnstreamer_tpu.serving.engine import _PrefixTrie

        trie = _PrefixTrie()
        for i in range(512):  # disjoint first tokens: a wide, shallow trie
            trie.insert((1000 + i, 1, 2, 3))
        calls = 0
        orig_get = dict.get

        class CountingDict(dict):
            def get(self, *a):
                nonlocal calls
                calls += 1
                return orig_get(self, *a)

        # wrap every kids dict
        def wrap(node):
            node["kids"] = CountingDict(node["kids"])
            for k in node["kids"].values():
                wrap(k)

        wrap(trie.root)
        trie.lookup([1000, 1, 2, 3, 9, 9, 9, 9])
        assert calls <= 8 + 1  # one child probe per prompt token


class TestEngineRestartAfterStuckStop:
    def test_start_reaps_dead_leftover_thread(self):
        """ADVICE r2: a timed-out stop() retains _thread; once that loop
        exits, start() must reap it and spin a fresh loop (not no-op)."""
        eng = ContinuousBatchingEngine(
            CFG, PARAMS, max_streams=2, steps_per_dispatch=2,
            temperature=0.0).start()
        try:
            assert eng.generate([4, 8], max_new_tokens=2, timeout=120)
            eng.stop()
            # simulate the timed-out-stop leftover: thread ref retained
            # though the loop has exited
            dead = eng._thread if eng._thread is not None else None
            if dead is None:
                import threading

                dead = threading.Thread(target=lambda: None)
                dead.start()
                dead.join()
                eng._thread = dead
                eng._stop_evt.set()
            eng.start()  # must reap and restart, not silently no-op
            assert eng._thread is not None and eng._thread.is_alive()
            assert eng.generate([4, 8], max_new_tokens=2, timeout=120)
        finally:
            eng.stop()


def test_dp_slot_scaling_throughput():
    """Aggregate throughput must scale with dp-sharded batch slots,
    holding the mesh fixed: a dp4×tp2 engine with 8 slots vs the SAME
    mesh with 4 slots (VERDICT r3 item 6: prove the dp4 gain). The
    asserted quantity is tokens per decode dispatch — the structural
    win slot scaling buys (on real chips each dispatch costs roughly
    the same wall time, so tokens/dispatch IS the throughput gain);
    wall-clock ratios on a shared CI host are too noisy to gate on."""
    from nnstreamer_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, CFG.vocab, 6).tolist() for _ in range(8)]
    mesh = make_mesh([("dp", 4), ("tp", 2)])

    def tokens_per_dispatch(streams):
        eng = ContinuousBatchingEngine(
            CFG, PARAMS, max_streams=streams, steps_per_dispatch=8,
            temperature=0.0, mesh=mesh).start()
        try:
            # compile off the clock (each engine has its own batch shape)
            eng.generate(prompts[0], max_new_tokens=8, timeout=240)
            d0 = eng.stats["dispatches"]
            t0 = eng.stats["tokens_generated"]
            ss = [eng.submit(p, max_new_tokens=24) for p in prompts]
            total = sum(len(s.result(timeout=240)) for s in ss)
            assert total == 8 * 24
            d = eng.stats["dispatches"] - d0
            t = eng.stats["tokens_generated"] - t0
            return t / max(d, 1)
        finally:
            eng.stop()

    slots4 = tokens_per_dispatch(4)
    slots8 = tokens_per_dispatch(8)
    # 2x the dp-sharded slots → the 8 concurrent streams run in one
    # admission wave instead of two, roughly doubling the tokens each
    # dispatch delivers (tail effects eat a little of the 2x)
    assert slots8 > 1.5 * slots4, (slots8, slots4)
