"""Pipeline dot dumps (pipeline/dot.py) — the GST_DEBUG_DUMP_DOT_DIR
equivalent, including fused-region clusters."""

import os

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.cli import main as cli_main
from nnstreamer_tpu.filters.jax_backend import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType

DESC = ("videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! "
        "tensor_transform mode=typecast option=float32 ! "
        "tensor_sink name=out")


def test_to_dot_lists_elements_and_links():
    pipe = parse_launch(DESC)
    dot = pipe.to_dot()
    for name in ("videotestsrc", "tensor_converter", "tensor_transform",
                 "out"):
        assert name in dot
    assert dot.count("->") >= 3
    assert dot.strip().startswith("digraph")


@pytest.fixture
def fusible_model():
    import jax.numpy as jnp

    def fn(params, x):
        return x * params

    info = TensorsInfo([TensorInfo(dim=(4,), type=TensorType.FLOAT32)])
    register_jax_model("dot_scale", fn, jnp.asarray(2.0, jnp.float32),
                       in_info=info, out_info=info)
    yield "dot_scale"
    unregister_jax_model("dot_scale")


def test_started_dot_shows_fused_region_cluster(fusible_model):
    pipe = parse_launch(
        "appsrc name=src ! "
        "tensor_transform mode=arithmetic option=typecast:float32,add:1 ! "
        f"tensor_filter framework=jax model={fusible_model} ! "
        "tensor_sink name=out")
    pipe.start()
    try:
        dot = pipe.to_dot()
    finally:
        pipe.get("src").end_of_stream()
        pipe.stop()
    assert "subgraph cluster_" in dot
    assert "fused region" in dot


def test_env_dump_writes_file_on_start(tmp_path, monkeypatch):
    monkeypatch.setenv("NNSTPU_DUMP_DOT_DIR", str(tmp_path))
    pipe = parse_launch(DESC)
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos"
    dumps = [p for p in os.listdir(tmp_path) if p.endswith(".playing.dot")]
    assert len(dumps) == 1
    assert "digraph" in (tmp_path / dumps[0]).read_text()


def test_cli_dot_flag(tmp_path):
    out = tmp_path / "graph.dot"
    rc = cli_main(["-q", "--dot", str(out), DESC])
    assert rc == 0
    text = out.read_text()
    assert "digraph" in text and "tensor_converter" in text


def test_dump_failure_does_not_break_pipeline(monkeypatch, tmp_path):
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("")  # a FILE where a dir is needed → makedirs fails
    monkeypatch.setenv("NNSTPU_DUMP_DOT_DIR", str(blocker))
    pipe = parse_launch(DESC)
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos"
