"""Paged KV-cache allocator (serving/kvpool.py): block bookkeeping,
arena invariants, and HBM accounting — the pool in isolation, before the
engine builds continuous batching on top of it."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    build_prefill,
    init_params,
)
from nnstreamer_tpu.serving import kvpool  # noqa: E402
from nnstreamer_tpu.tensors import memory  # noqa: E402

CFG = TransformerConfig(vocab=97, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, max_seq=64, dtype=jnp.float32)
PARAMS = init_params(CFG, seed=3)
T = 8


@pytest.fixture(autouse=True)
def _no_budget():
    memory.deactivate()
    yield
    memory.deactivate()


def test_env_kill_switch(monkeypatch):
    for off in ("0", "false", "no", "off", " OFF "):
        monkeypatch.setenv("NNSTPU_PAGED_KV", off)
        assert not kvpool.paged_enabled(), off
    for on in ("1", "true", "yes", ""):
        monkeypatch.setenv("NNSTPU_PAGED_KV", on)
        assert kvpool.paged_enabled() or on == "", on
    monkeypatch.delenv("NNSTPU_PAGED_KV")
    assert kvpool.paged_enabled()  # default ON (engine gates on knob)


def test_alloc_is_all_or_nothing_and_lifo():
    pool = kvpool.BlockPool(CFG, 4, T)
    ids = pool.alloc(3)
    assert len(ids) == 3 and pool.free_blocks == 1
    assert pool.alloc(2) is None          # 1 free: all-or-nothing
    assert pool.free_blocks == 1          # failed alloc took nothing
    pool.release(ids)
    assert pool.free_blocks == 4 and pool.live_blocks() == 0
    # LIFO recycling: the most recently released block comes back first
    again = pool.alloc(1)
    assert again[0] == ids[-1]


def test_refcounts_guard_shared_blocks():
    pool = kvpool.BlockPool(CFG, 4, T)
    ids = pool.alloc(2)
    pool.retain(ids)                      # second owner (COW prefix)
    pool.release(ids)
    assert pool.live_blocks() == 2        # still held by the retainer
    pool.release(ids)
    assert pool.live_blocks() == 0
    with pytest.raises(RuntimeError):
        pool.release(ids)                 # over-release
    with pytest.raises(RuntimeError):
        pool.retain(ids)                  # retain of a dead block


def test_scatter_prefill_and_zero_block_stay_exact():
    pool = kvpool.BlockPool(CFG, 6, T)
    prefill = jax.jit(build_prefill(CFG, CFG.max_seq))
    toks = jnp.asarray(
        np.random.default_rng(0).integers(1, CFG.vocab, (1, 16)), jnp.int32)
    _, cache1 = prefill(PARAMS, toks)
    want = np.asarray(jax.tree_util.tree_leaves(cache1)[0])  # [L,2,1,S,...]
    ids = pool.alloc(2)
    pool.scatter_prefill(cache1, ids)
    got = np.asarray(jax.tree_util.tree_leaves(pool.arena)[0])
    # block i holds prompt slots [i*T, (i+1)*T)
    for i, b in enumerate(ids):
        np.testing.assert_array_equal(
            got[:, b], np.moveaxis(
                want[:, :, 0, i * T:(i + 1) * T], 1, 1).reshape(got[:, b].shape))
    # the permanent zero block is untouched (sentinel writes dropped)
    assert not np.any(got[:, pool.num_blocks])


def test_copy_block_duplicates_one_block():
    pool = kvpool.BlockPool(CFG, 6, T)
    prefill = jax.jit(build_prefill(CFG, CFG.max_seq))
    toks = jnp.asarray(
        np.random.default_rng(1).integers(1, CFG.vocab, (1, 16)), jnp.int32)
    _, cache1 = prefill(PARAMS, toks)
    src_dst = pool.alloc(2)
    pool.scatter_prefill(cache1, src_dst[:1])
    pool.copy_block(src_dst[0], src_dst[1])
    for leaf in jax.tree_util.tree_leaves(pool.arena):
        a = np.asarray(leaf)
        np.testing.assert_array_equal(a[:, src_dst[0]], a[:, src_dst[1]])


def test_reset_returns_every_block():
    pool = kvpool.BlockPool(CFG, 4, T)
    pool.alloc(3)
    pool.reset()
    assert pool.free_blocks == 4 and pool.live_blocks() == 0
    snap = pool.snapshot()
    assert snap["num_blocks"] == 4 and snap["free_blocks"] == 4
    assert snap["nbytes"] == pool.nbytes > 0


def test_arena_registers_kvcache_bytes():
    budget = memory.activate(1 << 30)
    pool = kvpool.BlockPool(CFG, 4, T)
    assert budget.snapshot()["used_by_category"].get("kvcache", 0) == \
        pool.nbytes
    del pool
    import gc

    gc.collect()
    assert budget.snapshot()["used_by_category"].get("kvcache", 0) == 0


def test_bad_sizes_rejected():
    with pytest.raises(ValueError):
        kvpool.BlockPool(CFG, 0, T)
    with pytest.raises(ValueError):
        kvpool.BlockPool(CFG, 4, 0)
