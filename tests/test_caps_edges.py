"""Edge cases of the caps negotiation value types (pipeline/caps.py).

The static verifier (analysis/verify.py) leans on the exact same
intersection engine runtime negotiation uses, so the degenerate inputs —
ranges that collapse to a point, ANY against lists, fixation of
empty-field caps — need pinned behavior.
"""

from nnstreamer_tpu.pipeline.caps import ANY, Caps, CapsList, IntRange


class TestIntRangeDegenerate:
    def test_point_range_intersect_collapses_to_scalar(self):
        # lo == hi is a single admissible value: intersecting with a
        # range that covers it must yield the scalar, not IntRange(5, 5)
        assert IntRange(5, 5).intersect(IntRange(0, 10)) == 5
        assert IntRange(0, 10).intersect(IntRange(5, 5)) == 5

    def test_point_range_intersect_point_range(self):
        assert IntRange(7, 7).intersect(IntRange(7, 7)) == 7
        assert IntRange(7, 7).intersect(IntRange(8, 8)) is None

    def test_point_range_vs_scalar(self):
        assert IntRange(5, 5).intersect(5) == 5
        assert IntRange(5, 5).intersect(6) is None

    def test_touching_ranges_collapse(self):
        # [0,5] ∩ [5,9] touches at exactly one value
        assert IntRange(0, 5).intersect(IntRange(5, 9)) == 5

    def test_point_range_contains(self):
        assert 5 in IntRange(5, 5)
        assert 4 not in IntRange(5, 5)

    def test_point_range_in_caps_field(self):
        a = Caps("other/tensors", {"num_tensors": IntRange(2, 2)})
        b = Caps("other/tensors", {"num_tensors": IntRange(1, 4)})
        merged = a.intersect(b)
        assert merged is not None and merged["num_tensors"] == 2
        assert merged.is_fixed()


class TestAnyVsList:
    def test_any_field_adopts_list(self):
        a = Caps("video/x-raw", {"format": ANY})
        b = Caps("video/x-raw", {"format": ["RGB", "GRAY8"]})
        merged = a.intersect(b)
        assert merged is not None
        assert merged["format"] == ["RGB", "GRAY8"]
        # ANY adopted a list -> still not fixed; fixate picks the head
        assert not merged.is_fixed()
        assert merged.fixate()["format"] == "RGB"

    def test_list_vs_any_symmetric(self):
        a = Caps("video/x-raw", {"format": ["RGB", "GRAY8"]})
        b = Caps("video/x-raw", {"format": ANY})
        assert a.intersect(b)["format"] == ["RGB", "GRAY8"]

    def test_any_capslist_vs_concrete(self):
        # CapsList.any() (unconstrained pad) adopts the other side whole;
        # distinct from an empty CapsList (failed negotiation)
        concrete = CapsList([Caps("other/tensors", {"num_tensors": 1})])
        merged = CapsList.any().intersect(concrete)
        assert not merged.is_empty()
        assert merged.first() == concrete.first()
        assert CapsList.any().intersect(CapsList.any()).is_any()
        assert not CapsList([], _any=False).intersect(concrete).is_any()
        assert CapsList([], _any=False).intersect(concrete).is_empty()

    def test_single_common_element_collapses(self):
        a = Caps("video/x-raw", {"format": ["RGB", "BGR"]})
        b = Caps("video/x-raw", {"format": ["GRAY8", "RGB"]})
        assert a.intersect(b)["format"] == "RGB"

    def test_disjoint_lists_empty(self):
        a = Caps("video/x-raw", {"format": ["RGB"]})
        b = Caps("video/x-raw", {"format": ["GRAY8"]})
        assert a.intersect(b) is None


class TestFixateEmptyFields:
    def test_fixate_no_fields_is_identity(self):
        c = Caps("other/tensors")
        fixed = c.fixate()
        assert fixed == c
        assert fixed.is_fixed()  # vacuously fixed: nothing unconstrained

    def test_fixate_drops_any_fields(self):
        c = Caps("other/tensors", {"format": ANY, "num_tensors": 2})
        fixed = c.fixate()
        assert "format" not in fixed
        assert fixed["num_tensors"] == 2
        assert fixed.is_fixed()

    def test_fixate_all_any_yields_empty_fields(self):
        c = Caps("other/tensors", {"format": ANY, "framerate": ANY})
        assert c.fixate().fields == {}

    def test_fixate_point_range(self):
        c = Caps("other/tensors", {"num_tensors": IntRange(3, 3)})
        assert c.fixate()["num_tensors"] == 3
