"""Real MQTT 3.1.1 framing, the reference GstMQTTMessageHdr wire layout,
SNTP clock correction, and the pubsub elements over the mqtt transport.

Reference parity: gst/mqtt/mqttsink.c + mqttsrc.c (paho MQTT transport),
mqttcommon.h:49-63 (1024-byte message header), ntputil.c (SNTP epoch),
Documentation/synchronization-in-mqtt-elements.md (base-epoch rebasing).
Protocol-level packet tests run always; the loopback tests use the
in-tree MqttBroker, which speaks the same conformant MQTT any external
broker does.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.query import mqtt as M


class TestVarlen:
    @pytest.mark.parametrize("n,encoded", [
        (0, b"\x00"),
        (127, b"\x7f"),
        (128, b"\x80\x01"),
        (16383, b"\xff\x7f"),
        (16384, b"\x80\x80\x01"),
        (268_435_455, b"\xff\xff\xff\x7f"),
    ])
    def test_spec_vectors(self, n, encoded):
        # the exact example table from MQTT 3.1.1 spec section 2.2.3
        assert M.encode_varlen(n) == encoded
        assert M.decode_varlen(encoded) == (n, len(encoded))

    def test_out_of_range(self):
        with pytest.raises(ValueError):
            M.encode_varlen(268_435_456)
        with pytest.raises(ValueError):
            M.decode_varlen(b"\xff\xff\xff\xff\x01")

    def test_truncated(self):
        with pytest.raises(ValueError):
            M.decode_varlen(b"\x80")


class TestPackets:
    def test_connect_layout(self):
        pkt = M.connect_packet("cid", keepalive=30)
        assert pkt[0] == M.CONNECT << 4
        body = pkt[2:]
        assert body[:6] == b"\x00\x04MQTT"
        assert body[6] == 4                      # protocol level 3.1.1
        assert body[7] == 0x02                   # clean session
        assert struct.unpack_from(">H", body, 8) == (30,)
        assert body[10:] == b"\x00\x03cid"

    def test_publish_parse(self):
        pkt = M.publish_packet("t/x", b"payload", retain=True)
        assert pkt[0] == (M.PUBLISH << 4) | 0x01
        _, used = M.decode_varlen(pkt, 1)
        topic, payload, retain, qos, pid = M.parse_publish(
            pkt[0] & 0x0F, pkt[1 + used:])
        assert (topic, payload, retain, qos, pid) == \
            ("t/x", b"payload", True, 0, None)

    def test_subscribe_flags(self):
        pkt = M.subscribe_packet(7, "a/+/b")
        assert pkt[0] == (M.SUBSCRIBE << 4) | 0x02  # mandatory flags
        body = pkt[2:]
        assert struct.unpack_from(">H", body) == (7,)
        assert body[2:].endswith(b"\x00")  # requested QoS0

    def test_connack(self):
        assert M.connack_packet(0)[-2:] == b"\x00\x00"
        assert M.connack_packet(5)[-1] == 5


class TestTopicMatching:
    @pytest.mark.parametrize("pattern,topic,match", [
        ("a/b", "a/b", True),
        ("a/b", "a/c", False),
        ("a/+", "a/b", True),
        ("a/+", "a/b/c", False),
        ("a/#", "a/b/c", True),
        ("#", "anything/at/all", True),
        ("a/+/c", "a/b/c", True),
        ("a/+/c", "a/b/d", False),
    ])
    def test_cases(self, pattern, topic, match):
        assert M.topic_matches(pattern, topic) is match


@pytest.fixture
def mqtt_broker():
    b = M.MqttBroker()
    yield b
    b.close()


class TestBrokerClientLoopback:
    """The skip-gated 'real broker' test of the reference plan — the
    in-tree broker IS a real MQTT broker on loopback."""

    def test_pub_sub(self, mqtt_broker):
        got = []
        sub = M.MqttClient(port=mqtt_broker.port)
        sub.subscribe("s/t", lambda t, p: got.append((t, p)))
        pub = M.MqttClient(port=mqtt_broker.port)
        pub.publish("s/t", b"data")
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [("s/t", b"data")]
        sub.close()
        pub.close()

    def test_retain_for_late_subscriber(self, mqtt_broker):
        pub = M.MqttClient(port=mqtt_broker.port)
        pub.publish("cfg/one", b"v1", retain=True)
        time.sleep(0.1)
        got = []
        sub = M.MqttClient(port=mqtt_broker.port)
        sub.subscribe("cfg/#", lambda t, p: got.append((t, p)))
        deadline = time.time() + 5
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [("cfg/one", b"v1")]
        sub.close()
        pub.close()

    def test_external_port_env(self, mqtt_broker, monkeypatch):
        """Loopback against 'an external broker' address (env-pointed),
        per the skip-gate plan: NNSTPU_TEST_MQTT_BROKER=host:port."""
        monkeypatch.setenv("NNSTPU_TEST_MQTT_BROKER",
                           f"127.0.0.1:{mqtt_broker.port}")
        import os

        host, port = os.environ["NNSTPU_TEST_MQTT_BROKER"].split(":")
        c = M.MqttClient(host, int(port))
        c.publish("env/x", b"ok")
        c.close()


class TestGstMqttHeader:
    def test_layout_byte_exact(self):
        """Offsets match the C struct (mqttcommon.h:49-63): num_mems@0,
        size_mems@8, base@136, sent@144, duration@152, dts@160, pts@168,
        caps@176; header is exactly 1024 bytes."""
        msg = M.pack_gst_mqtt_message(
            [b"abcd", b"xy"], "other/tensors,num_tensors=2",
            base_time_epoch=111, sent_time_epoch=222,
            pts=333, dts=444, duration=555)
        hdr = msg[:M.GST_MQTT_LEN_MSG_HDR]
        assert len(msg) == 1024 + 6
        assert struct.unpack_from("<I", hdr, 0) == (2,)
        assert struct.unpack_from("<QQ", hdr, 8) == (4, 2)
        assert struct.unpack_from("<q", hdr, 136) == (111,)
        assert struct.unpack_from("<q", hdr, 144) == (222,)
        assert struct.unpack_from("<Q", hdr, 152) == (555,)
        assert struct.unpack_from("<Q", hdr, 160) == (444,)
        assert struct.unpack_from("<Q", hdr, 168) == (333,)
        assert hdr[176:176 + 28] == b"other/tensors,num_tensors=2\x00"
        assert msg[1024:] == b"abcdxy"

    def test_roundtrip_and_none_times(self):
        msg = M.pack_gst_mqtt_message([b"\x01\x02"], "caps", 1, 2)
        out = M.parse_gst_mqtt_message(msg)
        assert out["mems"] == [b"\x01\x02"]
        assert out["caps_str"] == "caps"
        assert out["pts"] is None and out["dts"] is None
        assert out["duration"] is None
        assert out["base_time_epoch"] == 1

    def test_limits(self):
        with pytest.raises(ValueError, match="NUM_MEMS"):
            M.pack_gst_mqtt_message([b"x"] * 17, "", 0, 0)
        with pytest.raises(ValueError, match="caps"):
            M.pack_gst_mqtt_message([b"x"], "c" * 512, 0, 0)
        with pytest.raises(ValueError, match="Hdr"):
            M.parse_gst_mqtt_message(b"short")


class TestElementsOverMqtt:
    def test_pipeline_loopback(self, mqtt_broker):
        """sink publishes reference-format messages over real MQTT; src
        reconstructs dtype/shape from the header caps string."""
        recv = parse_launch(
            f"tensor_pubsub_src name=src broker=mqtt://127.0.0.1:"
            f"{mqtt_broker.port} sub_topic=nns/t num_buffers=3 ! "
            "tensor_sink name=out"
        )
        outs = []
        recv.get("out").connect(lambda b: outs.append(b))
        recv.start()
        time.sleep(0.3)  # let SUBSCRIBE land before publishing

        send = parse_launch(
            "appsrc name=in ! tensor_pubsub_sink name=snk "
            f"broker=mqtt://127.0.0.1:{mqtt_broker.port} pub_topic=nns/t"
        )
        send.start()
        for k in range(3):
            send.get("in").push(
                [np.full((2, 3), k, np.float32),
                 np.arange(4, dtype=np.int32)])
        send.get("in").end_of_stream()
        assert recv.wait(timeout=60).kind == "eos"
        send.stop()
        recv.stop()
        assert len(outs) == 3
        a0 = np.asarray(outs[0].tensors[0])
        assert a0.dtype == np.float32 and a0.shape == (2, 3)
        np.testing.assert_array_equal(
            np.asarray(outs[2].tensors[0]), np.full((2, 3), 2, np.float32))
        np.testing.assert_array_equal(
            np.asarray(outs[0].tensors[1]), np.arange(4, dtype=np.int32))

    def test_reference_peer_can_parse(self, mqtt_broker):
        """A raw MQTT subscriber (≙ reference mqttsrc) decodes our sink's
        payload with nothing but mqttcommon.h layout knowledge."""
        got = []
        raw = M.MqttClient(port=mqtt_broker.port)
        raw.subscribe("ref/t", lambda t, p: got.append(p))

        send = parse_launch(
            "appsrc name=in ! tensor_pubsub_sink "
            f"broker=mqtt://127.0.0.1:{mqtt_broker.port} pub_topic=ref/t"
        )
        send.start()
        send.get("in").push([np.arange(6, dtype=np.float32).reshape(2, 3)])
        deadline = time.time() + 10
        while not got and time.time() < deadline:
            time.sleep(0.02)
        send.get("in").end_of_stream()
        send.wait(timeout=30)
        send.stop()
        raw.close()
        assert got
        msg = M.parse_gst_mqtt_message(got[0])
        assert len(msg["mems"]) == 1
        np.testing.assert_array_equal(
            np.frombuffer(msg["mems"][0], np.float32), np.arange(6))
        assert "other/tensor" in msg["caps_str"]
        assert msg["base_time_epoch"] > 0


class TestBaseEpochRebasing:
    def test_offset_excludes_delivery_latency(self, mqtt_broker):
        """pts shifts by the base-epoch difference only: delaying
        delivery must not change the rebased timestamps."""
        from nnstreamer_tpu.elements.pubsub import TensorPubSubSrc

        recv = parse_launch(
            f"tensor_pubsub_src name=src broker=mqtt://127.0.0.1:"
            f"{mqtt_broker.port} sub_topic=lat/t num_buffers=2 ! "
            "tensor_sink name=out"
        )
        src = recv.get("src")
        outs = []
        recv.get("out").connect(lambda b: outs.append(b))
        recv.start()
        time.sleep(0.3)
        sender_base = src._base_epoch + 5_000_000_000  # sender 5s ahead

        pub = M.MqttClient(port=mqtt_broker.port)
        for k, delay in ((0, 0.0), (1, 0.5)):  # second frame arrives late
            time.sleep(delay)
            pub.publish("lat/t", M.pack_gst_mqtt_message(
                [np.float32(k).tobytes()], "", sender_base,
                sender_base + k, pts=k * 1000))
        assert recv.wait(timeout=30).kind == "eos"
        recv.stop()
        pub.close()
        assert [b.pts for b in outs] == \
            [0 * 1000 + 5_000_000_000, 1 * 1000 + 5_000_000_000]


class TestSntp:
    def _serve_once(self, server_offset_ns: int, delay: float = 0.0,
                    blank_recv_ts: bool = False):
        """One-shot mock NTP server; returns (port, thread)."""
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]

        def run():
            data, addr = sock.recvfrom(512)
            t_server = time.time_ns() + server_offset_ns
            if delay:
                time.sleep(delay)  # asymmetric-looking processing delay
            from nnstreamer_tpu.query.ntp import _to_ntp

            r_sec, r_frac = _to_ntp(t_server)
            x_sec, x_frac = _to_ntp(time.time_ns() + server_offset_ns)
            if blank_recv_ts:
                r_sec = r_frac = 0
            reply = struct.pack(
                ">B3x11I", 0x24, 0, 0, 0, 0, 0,
                *struct.unpack_from(">2I", data, 40),  # origin := client xmit
                r_sec, r_frac, x_sec, x_frac)
            sock.sendto(reply, addr)
            sock.close()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return port, t

    def test_offset_measured(self):
        from nnstreamer_tpu.query.ntp import sntp_offset_ns

        port, t = self._serve_once(server_offset_ns=3_000_000_000)
        off = sntp_offset_ns("127.0.0.1", port)
        t.join(5)
        assert abs(off - 3_000_000_000) < 200_000_000  # within 200ms

    def test_offset_excludes_latency(self):
        """A slow server round trip must not leak into the offset (the
        reference's transmit-timestamp-only math would be off by ~delay)."""
        from nnstreamer_tpu.query.ntp import sntp_offset_ns

        port, t = self._serve_once(server_offset_ns=0, delay=0.4)
        off = sntp_offset_ns("127.0.0.1", port, timeout=5)
        t.join(5)
        assert abs(off) < 250_000_000  # << the 400ms injected delay

    def test_corrected_epoch_fallback(self, monkeypatch):
        from nnstreamer_tpu.query import ntp

        ntp.reset_offset_cache()
        # unreachable server: falls back to the local clock, streaming on
        before = time.time_ns()
        got = ntp.corrected_epoch_ns([("127.0.0.1", 1)], timeout=0.2)
        assert got >= before
        ntp.reset_offset_cache()


class TestQoS1:
    def test_publish_packet_qos1_layout(self):
        pkt = M.publish_packet("a/b", b"xyz", qos=1, packet_id=300)
        assert pkt[0] == (M.PUBLISH << 4) | 0x02  # qos1, no dup/retain
        _, used = M.decode_varlen(pkt, 1)
        topic, payload, retain, qos, pid = M.parse_publish(
            pkt[0] & 0x0F, pkt[1 + used:])
        assert (topic, payload, qos, pid) == ("a/b", b"xyz", 1, 300)
        dup = M.publish_packet("a/b", b"xyz", qos=1, packet_id=300,
                               dup=True)
        assert dup[0] & 0x08  # DUP bit

    def test_qos1_roundtrip_with_puback(self):
        """QoS1 publish blocks until PUBACK; subscriber receives once
        (and acks the broker's QoS1 delivery)."""
        broker = M.MqttBroker()
        got = []
        try:
            sub = M.MqttClient(port=broker.port)
            sub.subscribe("q1/t", lambda t, p: got.append(p), qos=1)
            pub = M.MqttClient(port=broker.port)
            pub.publish("q1/t", b"hello-qos1", qos=1, timeout=10.0)
            deadline = time.monotonic() + 10
            while not got and time.monotonic() < deadline:
                time.sleep(0.01)
            assert got and got[0] == b"hello-qos1"
            assert not pub._unacked  # PUBACK consumed
            # broker's in-flight map drains once the subscriber acks
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                with broker._lock:
                    if not any(broker._inflight.values()):
                        break
                time.sleep(0.05)
            with broker._lock:
                assert not any(broker._inflight.values())
            pub.close(); sub.close()
        finally:
            broker.close()

    def test_qos1_retransmits_until_acked(self):
        """An unanswered QoS1 publish retransmits with DUP set."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0)); srv.listen(1)
        port = srv.getsockname()[1]
        seen = []

        def fake_broker():
            sock, _ = srv.accept()
            M.read_packet(sock)  # CONNECT
            sock.sendall(M.connack_packet(0))
            while len(seen) < 2:
                pkt = M.read_packet(sock)
                if pkt is None:
                    return
                if pkt[0] == M.PUBLISH:
                    seen.append(pkt[1])  # flags
            # ack only after the retransmission arrived
            sock.sendall(M.puback_packet(1))
            M.read_packet(sock)

        th = threading.Thread(target=fake_broker, daemon=True)
        th.start()
        c = M.MqttClient(port=port, reconnect=False)
        c.publish("t", b"x", qos=1, timeout=15.0)
        assert len(seen) >= 2
        assert not seen[0] & 0x08   # first send: DUP clear
        assert seen[-1] & 0x08      # retransmission: DUP set
        c.close(); srv.close()

    def test_reconnect_resubscribes_and_resends(self):
        """Kill the broker mid-session: the client must reconnect to the
        replacement on the same port, re-issue its subscription, and
        resend the unacked QoS1 publish."""
        broker = M.MqttBroker()
        port = broker.port
        got = []
        c = M.MqttClient(port=port, keepalive=2)
        c.subscribe("r/t", lambda t, p: got.append(p), qos=1)
        broker.close()
        time.sleep(0.1)
        broker2 = M.MqttBroker(port=port)
        try:
            deadline = time.monotonic() + 15
            while c.reconnects == 0 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert c.reconnects >= 1, "client never reconnected"
            # subscription must be live on the NEW broker
            c2 = M.MqttClient(port=port)
            c2.publish("r/t", b"after-reconnect", qos=1, timeout=10.0)
            deadline = time.monotonic() + 10
            while not got and time.monotonic() < deadline:
                time.sleep(0.01)
            assert got and got[-1] == b"after-reconnect"
            c2.close(); c.close()
        finally:
            broker2.close()

    def test_failed_latches_when_reconnect_exhausted(self):
        broker = M.MqttBroker()
        c = M.MqttClient(port=broker.port, max_reconnect_attempts=2)
        broker.close()
        assert c.failed.wait(15), "failed never latched"
        c.close()


class TestGstMqttHeaderCtypesOracle:
    def test_byte_identity_vs_c_struct(self):
        """Independent oracle: mirror the C struct (mqttcommon.h:49-63)
        with ctypes — the compiler's own offset/alignment rules — fill
        it the way mqttsink does, and require byte identity with our
        packer in both directions."""
        import ctypes as C

        GST_MQTT_MAX_NUM_MEMS = 16
        GST_MQTT_MAX_LEN_GST_CAPS_STR = 512
        GST_MQTT_LEN_MSG_HDR = 1024

        class Hdr(C.Structure):
            _fields_ = [
                ("num_mems", C.c_uint),
                ("size_mems", C.c_size_t * GST_MQTT_MAX_NUM_MEMS),
                ("base_time_epoch", C.c_int64),
                ("sent_time_epoch", C.c_int64),
                ("duration", C.c_uint64),   # GstClockTime
                ("dts", C.c_uint64),
                ("pts", C.c_uint64),
                ("gst_caps_str",
                 C.c_char * GST_MQTT_MAX_LEN_GST_CAPS_STR),
            ]

        class Msg(C.Union):
            _fields_ = [("s", Hdr),
                        ("_reserved_hdr", C.c_uint8 * GST_MQTT_LEN_MSG_HDR)]

        assert C.sizeof(Msg) == GST_MQTT_LEN_MSG_HDR

        m = Msg()
        m.s.num_mems = 2
        m.s.size_mems[0] = 4
        m.s.size_mems[1] = 2
        m.s.base_time_epoch = 111
        m.s.sent_time_epoch = 222
        m.s.duration = 555
        m.s.dts = 444
        m.s.pts = 333
        m.s.gst_caps_str = b"other/tensors,num_tensors=2"
        golden = bytes(m) + b"abcdxy"

        ours = M.pack_gst_mqtt_message(
            [b"abcd", b"xy"], "other/tensors,num_tensors=2",
            base_time_epoch=111, sent_time_epoch=222,
            pts=333, dts=444, duration=555)
        assert ours == golden  # byte-for-byte

        out = M.parse_gst_mqtt_message(golden)  # and we parse theirs
        assert out["mems"] == [b"abcd", b"xy"]
        assert out["caps_str"] == "other/tensors,num_tensors=2"
        assert (out["base_time_epoch"], out["sent_time_epoch"]) == (111,
                                                                    222)
        assert (out["pts"], out["dts"], out["duration"]) == (333, 444, 555)
