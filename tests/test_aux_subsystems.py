"""Aux subsystems: tracing, checkpoint/resume, native core, config system
(SURVEY §5 parity tests)."""

import json
import os

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch


class TestTracer:
    def test_traces_pipeline(self, tmp_path):
        from nnstreamer_tpu.utils.trace import Tracer

        pipe = parse_launch(
            "videotestsrc num-buffers=5 width=8 height=8 ! tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! fakesink"
        )
        tracer = Tracer()
        with tracer.attach(pipe):
            pipe.run(timeout=20)
        summary = tracer.summary()
        assert any("tensor_converter" in k for k in summary)
        conv = next(v for k, v in summary.items() if "tensor_converter" in k)
        assert conv["count"] == 5
        assert conv["proctime_us_avg"] > 0
        out = tmp_path / "trace.json"
        tracer.export_chrome(str(out))
        data = json.loads(out.read_text())
        assert len(data["traceEvents"]) >= 15  # 3 elements x 5 buffers

    def test_detach_restores(self):
        from nnstreamer_tpu.utils.trace import Tracer
        from nnstreamer_tpu.elements.sink import FakeSink

        s = FakeSink()
        from nnstreamer_tpu.pipeline.pipeline import Pipeline

        pipe = Pipeline().add(s)
        with Tracer().attach(pipe):
            assert "_chain_entry" in s.__dict__  # wrapped via instance attr
        assert "_chain_entry" not in s.__dict__  # detached cleanly


class TestCheckpoint:
    def test_params_roundtrip(self, tmp_path):
        from nnstreamer_tpu.utils.checkpoint import load_params, save_params
        from nnstreamer_tpu.models.transformer import (
            TransformerConfig,
            init_params,
        )
        import jax.numpy as jnp

        cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=1,
                                d_ff=32, dtype=jnp.float32)
        params = init_params(cfg)
        path = tmp_path / "m.msgpack"
        save_params(params, str(path))
        loaded = load_params(init_params(cfg, seed=1), str(path))
        np.testing.assert_array_equal(np.asarray(loaded["embed"]),
                                      np.asarray(params["embed"]))

    def test_stream_state_resume(self, tmp_path):
        """LSTM-style repo state survives a save/restore cycle (reference
        pattern: tensor_repo slots persist loop state)."""
        from nnstreamer_tpu.elements.repo import GLOBAL_REPO
        from nnstreamer_tpu.tensors.buffer import TensorBuffer
        from nnstreamer_tpu.utils.checkpoint import (
            restore_stream_state,
            save_stream_state,
        )

        GLOBAL_REPO.set("h0", TensorBuffer([np.arange(4, dtype=np.float32)]))
        path = str(tmp_path / "stream.ckpt")
        save_stream_state(path, extra={"step": 42})
        GLOBAL_REPO.remove("h0")
        assert GLOBAL_REPO.peek("h0") is None
        extra = restore_stream_state(path)
        assert extra["step"] == 42
        np.testing.assert_array_equal(GLOBAL_REPO.peek("h0")[0],
                                      np.arange(4, dtype=np.float32))

    def test_msgpack_model_via_filter(self, tmp_path):
        """Save transformer params, load via framework=jax model=.msgpack
        custom=module:<factory> (the reference's model-file pattern)."""
        import jax.numpy as jnp

        from nnstreamer_tpu.models import transformer_lm
        from nnstreamer_tpu.single import SingleShot
        from nnstreamer_tpu.utils.checkpoint import save_params

        fn, params, _, _ = transformer_lm(vocab=32, d_model=16, n_heads=2,
                                          n_layers=1, d_ff=32, seq=8,
                                          dtype=jnp.float32)
        path = tmp_path / "lm.msgpack"
        save_params(params, str(path))
        s = SingleShot(framework="jax", model=str(path),
                       custom="module:transformer_lm")
        out = s.invoke([np.zeros((1, 8), np.int32)])
        # output vocab follows the LOADED params (32), not the factory
        # template default — the checkpoint's shapes win
        assert np.asarray(out[0]).shape == (1, 8, 32)
        s.close()


class TestNative:
    def test_library_loads(self):
        from nnstreamer_tpu import native

        assert native.available()
        feats = native.cpu_features()
        assert feats["native"]

    def test_sparse_native_matches_numpy(self, rng):
        from nnstreamer_tpu import native

        for dtype in (np.float32, np.uint8, np.int64, np.float16):
            d = (rng.random(512) < 0.05).astype(dtype)
            idx, vals = native.sparse_encode_arrays(d)
            np.testing.assert_array_equal(idx, np.flatnonzero(d))
            back = native.sparse_decode_arrays(idx, vals, d.size)
            np.testing.assert_array_equal(back, d)

    def test_sparse_decode_rejects_bad_index(self):
        from nnstreamer_tpu import native

        with pytest.raises(ValueError):
            native.sparse_decode_arrays(
                np.array([999], np.uint32), np.array([1.0], np.float32), 10
            )


class TestConfig:
    def test_env_override(self, monkeypatch):
        from nnstreamer_tpu.config import Conf

        monkeypatch.setenv("NNSTREAMER_TPU_FILTER_FRAMEWORK_PRIORITY_XYZ",
                           "torch,jax")
        conf = Conf()
        assert conf.framework_priority("model.xyz") == ["torch", "jax"]

    def test_ini_file(self, tmp_path, monkeypatch):
        ini = tmp_path / "conf.ini"
        ini.write_text("[jax]\nplatform = cpu\n[filter]\npath = /opt/plugins\n")
        monkeypatch.setenv("NNSTREAMER_TPU_CONF", str(ini))
        from nnstreamer_tpu.config import Conf

        conf = Conf()
        assert conf.get("jax", "platform") == "cpu"
        assert conf.subplugin_paths("filter") == ["/opt/plugins"]

    def test_default_ext_priority(self):
        from nnstreamer_tpu.config import Conf

        assert "jax" in Conf().framework_priority("model.msgpack")
        assert "torch" in Conf().framework_priority("model.pt")


class TestPlatformProbe:
    """ensure_jax_platform skips the subprocess probe for unset/cpu presets
    and caches non-CPU probe verdicts (ADVICE r1)."""

    def test_cpu_preset_never_probes(self, monkeypatch):
        from nnstreamer_tpu.utils import platform as plat

        def boom(*a, **k):
            raise AssertionError("probe ran for a cpu preset")

        monkeypatch.setattr(plat, "probe_jax_platform", boom)
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        assert plat.ensure_jax_platform() == "cpu"

    def test_unset_preset_probes_and_caches(self, monkeypatch, tmp_path):
        """No preset still probes (plugin auto-discovery can wedge the
        same way an explicit preset can) — but only once per cache TTL."""
        from nnstreamer_tpu.utils import platform as plat

        import tempfile
        monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp_path))
        calls = []
        monkeypatch.setattr(plat, "probe_jax_platform",
                            lambda *a, **k: calls.append(1) or "cpu")
        monkeypatch.setenv("JAX_PLATFORMS", "")
        assert plat.ensure_jax_platform() == "cpu"
        assert plat.ensure_jax_platform() == "cpu"
        assert len(calls) == 1

    def test_probe_cache_roundtrip(self, monkeypatch, tmp_path):
        from nnstreamer_tpu.utils import platform as plat

        monkeypatch.setenv("TMPDIR", str(tmp_path))
        monkeypatch.delenv("NNSTPU_PROBE_NOCACHE", raising=False)
        import tempfile
        monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp_path))
        plat._probe_cache_put("faketpu", "tpu")
        assert plat._probe_cache_get("faketpu") == {"platform": "tpu"}
        # failed probes are cached too (repeated startups skip the wait)
        plat._probe_cache_put("deadtpu", None)
        assert plat._probe_cache_get("deadtpu") == {"platform": None}
        # TTL expiry invalidates
        monkeypatch.setenv("NNSTPU_PROBE_CACHE_TTL", "0")
        assert plat._probe_cache_get("faketpu") is None

    def test_cached_verdict_skips_probe(self, monkeypatch, tmp_path):
        from nnstreamer_tpu.utils import platform as plat

        import tempfile
        monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp_path))
        calls = []
        monkeypatch.setattr(plat, "probe_jax_platform",
                            lambda *a, **k: calls.append(1) or None)
        monkeypatch.setenv("JAX_PLATFORMS", "bogus_backend")
        # jax is already initialized on cpu in tests; a failed probe keeps it
        assert plat.ensure_jax_platform() == "cpu"
        assert plat.ensure_jax_platform() == "cpu"
        assert len(calls) == 1  # second call served from the cache


class TestEndToEndLatency:
    """North-star latency stat: source create() stamps, sink measures at
    materialization (BASELINE.md; reference tensor_filter.c:349-423)."""

    def test_latency_recorded_per_frame(self):
        from nnstreamer_tpu import parse_launch

        pipe = parse_launch(
            "videotestsrc num-buffers=6 width=8 height=8 ! "
            "tensor_converter ! tensor_sink name=out")
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        sink = pipe.get("out")
        assert len(sink.latencies) == 6
        p50, p99 = sink.latency_percentiles(50, 99)
        assert 0 < p50 <= p99 < 10_000

    def test_microbatched_latency_counts_batch_wait(self):
        """Aggregated buffers carry one stamp per constituent frame, so
        latency includes the batch-window wait and the count equals the
        FRAME count, not the buffer count."""
        from nnstreamer_tpu import parse_launch

        pipe = parse_launch(
            "videotestsrc num-buffers=8 width=8 height=8 ! "
            "tensor_converter ! "
            "tensor_aggregator frames-in=1 frames-out=4 frames-flush=4 "
            "frames-dim=3 concat=true ! tensor_sink name=out")
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        sink = pipe.get("out")
        assert len(sink.buffers) == 2
        assert len(sink.latencies) == 8  # per frame, not per buffer
        assert sink.latency_percentiles() is not None

    def test_mixed_stamped_unstamped_frames_stay_aligned(self):
        """Frames pushed without create stamps interleaved with stamped
        ones must not shift stamp→frame attribution: the aggregator pads
        placeholders so each emitted window reports only its own frames'
        stamps (ADVICE r4: aggregator.py stamp/window lockstep)."""
        import time

        from nnstreamer_tpu.elements.aggregator import TensorAggregator
        from nnstreamer_tpu.elements.sink import TensorSink
        from nnstreamer_tpu.tensors.buffer import TensorBuffer

        agg = TensorAggregator("agg")
        agg.set_property("frames_in", 1)
        agg.set_property("frames_out", 2)
        agg.set_property("frames_flush", 2)
        agg.set_property("frames_dim", 0)
        agg.set_property("concat", True)
        sink = TensorSink("out")
        agg.srcpad.link(sink.sinkpad)
        arr = np.zeros((1, 4), np.float32)
        t0 = time.time() - 5.0  # distinctively old stamp
        # window 1: unstamped + stamped(t0); window 2: stamped(now) x2
        agg.chain(agg.sinkpad, TensorBuffer([arr], pts=0))
        agg.chain(agg.sinkpad,
                  TensorBuffer([arr], pts=1, meta={"create_t": t0}))
        now = time.time()
        agg.chain(agg.sinkpad,
                  TensorBuffer([arr], pts=2, meta={"create_t": now}))
        agg.chain(agg.sinkpad,
                  TensorBuffer([arr], pts=3, meta={"create_t": now}))
        assert len(sink.buffers) == 2
        w1 = sink.buffers[0].meta.get("create_ts")
        w2 = sink.buffers[1].meta.get("create_ts")
        assert w1 == [t0]          # placeholder filtered, stamp not shifted
        assert w2 == [now, now]    # second window owns only its stamps

    def test_mux_latency_spans_all_streams(self):
        from nnstreamer_tpu import parse_launch

        pipe = parse_launch(
            "tensor_mux name=m sync-mode=slowest ! tensor_sink name=out "
            "videotestsrc num-buffers=3 width=4 height=4 ! "
            "tensor_converter ! m. "
            "videotestsrc num-buffers=3 width=4 height=4 ! "
            "tensor_converter ! m.")
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        sink = pipe.get("out")
        assert len(sink.latencies) == 6  # 3 muxed frames x 2 streams
