"""Cross-instance model sharing (shared-tensor-filter-key) and concurrent
pipeline execution — reference shared-model representation
(nnstreamer_plugin_api_filter.h:577-602) and multi-stream threading."""

import threading

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.api import (
    shared_model_get,
    shared_model_remove,
)
from nnstreamer_tpu.filters.jax_backend import (
    register_jax_model,
    unregister_jax_model,
)


@pytest.fixture
def shared_linear():
    import jax.numpy as jnp

    def fn(p, x):
        return x.astype(jnp.float32) * p

    register_jax_model("shared_lin", fn, jnp.float32(3.0))
    yield "shared_lin"
    unregister_jax_model("shared_lin")
    shared_model_remove("k_shared_lin")


DESC = (
    "appsrc name=src ! tensor_transform mode=typecast option=float32 ! "
    "tensor_filter framework=jax model=shared_lin name=f "
    "shared-tensor-filter-key=k_shared_lin ! tensor_sink name=sink"
)


class TestSharedModelKey:
    def test_two_instances_share_one_entry(self, shared_linear):
        pipes = [parse_launch(DESC) for _ in range(2)]
        for p in pipes:
            p.start()
        try:
            entry = shared_model_get("k_shared_lin")
            assert entry is not None
            # both filter backends hold the SAME fn object (one load)
            fws = [p.get("f").fw for p in pipes]
            assert fws[0]._fn is fws[1]._fn
            for p in pipes:
                p.get("src").push([np.full((4,), 2, np.uint8)])
                p.get("src").end_of_stream()
            for p in pipes:
                assert p.wait(timeout=30).kind == "eos"
                np.testing.assert_allclose(
                    np.asarray(p.get("sink").buffers[0][0]),
                    np.full((4,), 6.0, np.float32))
        finally:
            for p in pipes:
                p.stop()

    def test_remove_forgets_entry(self, shared_linear):
        pipe = parse_launch(DESC)
        pipe.start()
        pipe.stop()
        assert shared_model_get("k_shared_lin") is not None
        assert shared_model_remove("k_shared_lin") is True
        assert shared_model_get("k_shared_lin") is None
        assert shared_model_remove("k_shared_lin") is False


class TestConcurrentPipelines:
    def test_parallel_streams_same_model(self, shared_linear):
        """N pipelines running simultaneously in threads must each get all
        frames, in order, with correct values."""
        n_pipes, n_frames = 4, 25
        results = [None] * n_pipes

        def run(i):
            pipe = parse_launch(DESC)
            src, sink = pipe.get("src"), pipe.get("sink")
            pipe.start()
            try:
                for j in range(n_frames):
                    src.push([np.full((4,), j, np.uint8)])
                src.end_of_stream()
                msg = pipe.wait(timeout=60)
                assert msg is not None and msg.kind == "eos"
                results[i] = [float(np.asarray(b[0])[0])
                              for b in sink.buffers]
            finally:
                pipe.stop()

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(n_pipes)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        expected = [j * 3.0 for j in range(n_frames)]
        for r in results:
            assert r == expected


class TestSoak:
    def test_long_stream_fused(self, shared_linear):
        """500-frame fused stream: every frame delivered, stats sane,
        bounded sink storage respected."""
        pipe = parse_launch(
            "appsrc name=src ! tensor_transform mode=typecast "
            "option=float32 ! tensor_filter framework=jax model=shared_lin "
            "name=f ! tensor_sink name=sink max-stored=64")
        src, sink = pipe.get("src"), pipe.get("sink")
        seen = [0]
        sink.connect(lambda b: seen.__setitem__(0, seen[0] + 1))
        pipe.start()
        try:
            for j in range(500):
                src.push([np.full((8,), j % 251, np.uint8)])
            src.end_of_stream()
            msg = pipe.wait(timeout=120)
            assert msg is not None and msg.kind == "eos"
        finally:
            pipe.stop()
        assert seen[0] == 500
        assert len(sink.buffers) <= 64  # max_stored bound respected
        assert pipe.get("f").get_property("throughput") > 0
