"""Parallel ingest lanes: ordered multi-worker ingest (pipeline/lanes.py).

The contract under test: replicating the pre-queue host segment across N
worker lanes must be OBSERVABLY free — output bytes, ordering, and EOS
semantics identical to the serial path at every lane count, even when
individual lanes run with randomized per-frame delays; per-lane pool
arenas never recycle each other's slabs; ``NNSTPU_LANES=1`` restores the
exact serial code path (no executor spliced at all); and the ``nns_lane_*``
metrics surface through ``metrics_snapshot()`` and the registry.
"""

import random
import time

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.pipeline.element import Element, FlowReturn
from nnstreamer_tpu.pipeline.lanes import (
    IngestLanes,
    effective_lanes,
    plan_lane_segments,
)
from nnstreamer_tpu.pipeline.pipeline import Pipeline, SourceElement
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.pool import get_lane_pool
from nnstreamer_tpu.tensors.types import TensorsConfig

# -- helpers ------------------------------------------------------------------

GOLDEN = ("videotestsrc pattern=ball num-buffers=16 width=16 height=16 ! "
          "tensor_converter ! "
          "tensor_transform mode=arithmetic "
          "option=typecast:float32,add:-3.0 acceleration=false ! "
          "tensor_sink name=out")


class _SeqSrc(SourceElement):
    """Index-stamped 4-elem tensors; REORDER_SAFE by construction."""

    ELEMENT_NAME = "_laneseqsrc"
    REORDER_SAFE = True
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 24}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        cfg = TensorsConfig.from_arrays([np.zeros((4,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        buf = TensorBuffer(
            [np.full((4,), float(self.i), np.float32)], pts=self.i * 1000)
        self.i += 1
        return buf


class _Jitter(Element):
    """Pure transform (x*2+1) with a randomized per-frame delay: frames
    finish out of order across lanes, so in-order delivery downstream
    proves the reorder buffer, not scheduling luck."""

    ELEMENT_NAME = "_lanejitter"
    REORDER_SAFE = True
    PROPERTIES = {**Element.PROPERTIES, "max_delay_ms": 4.0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.add_src_pad("src")

    def chain(self, pad, buf):
        delay = random.uniform(0.0, self.get_property("max_delay_ms"))
        time.sleep(delay / 1e3)
        out = buf.with_tensors([t * 2.0 + 1.0 for t in buf.tensors])
        self.srcpad.push(out)
        return FlowReturn.OK


def _run_jitter_pipeline(lanes, n=24, seed=7):
    random.seed(seed)
    pipe = Pipeline(name=f"lanes-jitter-{lanes}", lanes=lanes)
    src = _SeqSrc(num_buffers=n)
    jit = _Jitter()
    from nnstreamer_tpu.elements.sink import TensorSink

    sink = TensorSink(name="out")
    pipe.add_linked(src, jit, sink)
    outs = []
    sink.connect(lambda b: outs.append(
        (b.pts, [np.asarray(t).copy() for t in b.tensors])))
    msg = pipe.run(timeout=60)
    assert msg is not None and msg.kind == "eos"
    return outs, pipe


# -- ordered reassembly under randomized per-lane delays ----------------------


class TestOrderedReassembly:
    def test_byte_equality_vs_serial_under_jitter(self):
        serial, _ = _run_jitter_pipeline(lanes=1)
        laned, pipe = _run_jitter_pipeline(lanes=4)
        assert len(pipe._lane_execs) == 1
        assert len(serial) == len(laned) == 24
        for (p1, t1), (p2, t2) in zip(serial, laned):
            assert p1 == p2
            for a, b in zip(t1, t2):
                assert a.dtype == b.dtype
                assert np.array_equal(a, b)

    def test_delivery_is_in_sequence_order(self):
        outs, _ = _run_jitter_pipeline(lanes=8, n=40)
        pts = [p for p, _ in outs]
        assert pts == sorted(pts)
        assert len(pts) == len(set(pts)) == 40

    def test_eos_drains_reorder_buffer(self):
        # large jitter + many lanes: EOS arrives while frames are still
        # in flight in lane queues and the reorder buffer — every frame
        # must still be delivered, before EOS, in order
        outs, pipe = _run_jitter_pipeline(lanes=8, n=32)
        assert len(outs) == 32
        sink = pipe.get("out")
        assert sink.eos
        ex = pipe._lane_execs[0]
        assert ex._delivered == ex._seq  # nothing stranded
        with ex._cv:
            assert ex._pending == {}


# -- lane-count parity on the golden pipeline ---------------------------------


class TestLaneCountParity:
    def _run_golden(self, lanes):
        pipe = parse_launch(GOLDEN, lanes=lanes)
        outs = []
        pipe.get("out").connect(lambda b: outs.append(
            (b.pts, [np.asarray(t).copy() for t in b.tensors])))
        msg = pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos"
        return outs

    @pytest.mark.parametrize("lanes", [2, 8])
    def test_parity_with_serial(self, lanes):
        serial = self._run_golden(1)
        laned = self._run_golden(lanes)
        assert len(serial) == len(laned) == 16
        for (p1, t1), (p2, t2) in zip(serial, laned):
            assert p1 == p2
            for a, b in zip(t1, t2):
                assert a.dtype == b.dtype and a.shape == b.shape
                assert np.array_equal(a, b)


# -- planning -----------------------------------------------------------------


class TestPlanning:
    def test_plan_covers_converter_and_transform(self):
        pipe = parse_launch(GOLDEN, lanes=2)
        plans = plan_lane_segments(pipe)
        assert len(plans) == 1
        src, segment = plans[0]
        assert src.ELEMENT_NAME == "videotestsrc"
        assert [el.ELEMENT_NAME for el in segment] == [
            "tensor_converter", "tensor_transform"]

    def test_stateful_converter_stops_replication(self):
        # frames_per_tensor=2 accumulates across frames — reorder_safe()
        # is False, the walk stops at the source, no executor splices
        desc = ("videotestsrc pattern=ball num-buffers=8 width=8 height=8 "
                "! tensor_converter frames-per-tensor=2 ! tensor_sink "
                "name=out")
        pipe = parse_launch(desc, lanes=4)
        assert plan_lane_segments(pipe) == []
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        assert pipe._lane_execs == []

    def test_queue_bounds_the_segment(self):
        desc = ("videotestsrc pattern=ball num-buffers=4 width=8 height=8 "
                "! tensor_converter ! queue ! "
                "tensor_transform mode=arithmetic option=add:1.0 "
                "acceleration=false ! tensor_sink name=out")
        pipe = parse_launch(desc, lanes=2)
        plans = plan_lane_segments(pipe)
        assert len(plans) == 1
        _, segment = plans[0]
        assert [el.ELEMENT_NAME for el in segment] == ["tensor_converter"]

    def test_serial_lane_count_splices_nothing(self):
        pipe = parse_launch(GOLDEN)  # lanes defaults to 1
        pipe.run(timeout=30)
        assert pipe._lane_execs == []


# -- env override / kill switch -----------------------------------------------


class TestEnvOverride:
    def test_kill_switch_restores_serial_path(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_LANES", "1")
        pipe = parse_launch(GOLDEN, lanes=8)
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        assert pipe._lane_execs == []
        # and the serial graph is untouched: source feeds the converter
        src = next(e for e in pipe.elements
                   if e.ELEMENT_NAME == "videotestsrc")
        assert src.srcpad.peer.element.ELEMENT_NAME == "tensor_converter"

    def test_env_forces_lane_count(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_LANES", "3")
        pipe = parse_launch(GOLDEN)
        pipe.run(timeout=30)
        assert len(pipe._lane_execs) == 1
        assert pipe._lane_execs[0].n == 3

    def test_effective_lanes_semantics(self, monkeypatch):
        monkeypatch.delenv("NNSTPU_LANES", raising=False)
        assert effective_lanes(4) == 4
        assert effective_lanes(0) == 1
        monkeypatch.setenv("NNSTPU_LANES", "2")
        assert effective_lanes(8) == 2
        monkeypatch.setenv("NNSTPU_LANES", "bogus")
        assert effective_lanes(5) == 5


# -- per-lane pool isolation --------------------------------------------------


class TestLanePoolIsolation:
    def test_lane_pools_are_distinct_arenas(self):
        p0, p1 = get_lane_pool(0), get_lane_pool(1)
        assert p0 is not p1
        assert p0 is get_lane_pool(0)  # stable per index
        assert p0.name != p1.name

    def test_no_cross_lane_slab_recycle(self):
        p0, p1 = get_lane_pool(0), get_lane_pool(1)
        p0.clear()
        p1.clear()
        a = p0.acquire((64,), np.float32)
        a[:] = 1.0
        assert p0.release(a)
        # no slab references held (release's refcount guard would drop
        # instead of recycle) — the slab must land on lane 0's free list
        del a
        assert p0.snapshot()["free"] == 1
        # lane 1 must NOT see lane 0's freed slab: its acquire allocates
        # fresh (a miss) and lane 0's arena stays untouched
        misses1 = p1.snapshot()["misses"]
        b = p1.acquire((64,), np.float32)
        assert b is not None
        assert p1.snapshot()["misses"] == misses1 + 1
        assert p0.snapshot()["free"] == 1  # lane 0's arena untouched

    def test_lanes_stage_through_their_own_pool(self):
        for k in range(2):
            get_lane_pool(k).clear()
        outs, pipe = _run_jitter_pipeline(lanes=2, n=16)
        assert len(outs) == 16
        from nnstreamer_tpu.tensors.pool import pool_enabled

        if pool_enabled():
            # both lane arenas saw traffic (16 frames round-robined)
            for k in range(2):
                snap = get_lane_pool(k).snapshot()
                assert snap["hits"] + snap["misses"] >= 8


# -- metrics ------------------------------------------------------------------


class TestLaneMetrics:
    def test_metrics_snapshot_has_lanes_section(self):
        _, pipe = _run_jitter_pipeline(lanes=4, n=20)
        snap = pipe.metrics_snapshot()
        assert "lanes" in snap
        (name, s), = snap["lanes"].items()
        assert s["lanes"] == 4
        assert s["forwarded"] == 20
        assert s["reorder_depth"] == 0
        assert s["reorder_stall_s"] >= 0.0

    def test_registry_series_exist(self):
        from nnstreamer_tpu.obs import get_registry

        _, pipe = _run_jitter_pipeline(lanes=2, n=8)
        reg = get_registry()
        labels = pipe._lane_execs[0]._obs_labels()
        assert reg.get("nns_lane_reorder_stall_seconds", **labels) \
            is not None
        assert reg.get("nns_lane_occupancy", **labels) is not None
        assert reg.get("nns_ingest_fps", **labels) is not None

    def test_serial_snapshot_has_no_lanes_section(self):
        pipe = parse_launch(GOLDEN)
        pipe.run(timeout=30)
        assert "lanes" not in pipe.metrics_snapshot()


# -- restart ------------------------------------------------------------------


class TestRestart:
    def test_splice_persists_and_state_resets_across_restart(self):
        # NOTE: core pad semantics latch `pad.eos` permanently after the
        # first EOS (see test_fuse's restart test, which pushes through a
        # persistent appsrc graph without reflowing past latched pads), so
        # a restart cannot reflow data. What the splice DOES promise across
        # stop()/start(): the executor object persists (spliced exactly
        # once, regions-style) and its per-run lane state — sequence
        # counters, reorder buffer, worker threads — resets cleanly.
        pipe = Pipeline(name="lanes-restart", lanes=2)
        src = _SeqSrc(num_buffers=6)
        jit = _Jitter(max_delay_ms=0.5)
        from nnstreamer_tpu.elements.sink import TensorSink

        sink = TensorSink(name="out")
        pipe.add_linked(src, jit, sink)
        outs = []
        sink.connect(lambda b: outs.append(float(np.asarray(
            b.tensors[0])[0])))
        assert pipe.run(timeout=30).kind == "eos"
        assert outs == [1.0, 3.0, 5.0, 7.0, 9.0, 11.0]
        assert len(pipe._lane_execs) == 1
        ex = pipe._lane_execs[0]
        assert ex._seq == 6 and ex._delivered == 6
        pipe.start()  # second cycle: splice reused, counters reset
        try:
            assert pipe._lane_execs[0] is ex  # spliced once, reused
            assert ex._seq == 0 and ex._next == 0 and ex._delivered == 0
            assert ex._pending == {}
            assert len(ex._workers) == ex.n
            assert all(t.is_alive() for t in ex._workers)
        finally:
            pipe.stop()
