"""Serving continuity (pipeline/continuity.py): zero-downtime model
swap, checkpoint/restore, and the persistent compile cache.

The contract under test, per docs/robustness.md "Serving continuity":

- ``swap_model`` drops zero frames, produces byte-identical output on
  each side of the cutover, invalidates the owning fused region exactly
  once, and composes with an active fault injector + retry policy;
- a weights-only swap re-registers the HBM residency unit under the new
  epoch key and retires the old one in the same step — no
  ``nns_mem_used_bytes`` leak, no stale unit;
- every checkpointable component (repo slots, scheduler EWMAs/knobs,
  P2 markers, flight ledger, dedup windows, residency LRU) round-trips
  through its snapshot/restore pair, including under injected faults;
- ``NNSTPU_CHECKPOINT`` / ``NNSTPU_COMPILE_CACHE`` unset means none of
  this code runs (byte-identical serving path, no files written);
- the persistent compile cache serves re-traces from disk: after
  ``jax.clear_caches()`` the same program loads with zero new XLA
  compiles, visible in ``nns_compile_cache_hits_total``.
"""

import os
import time

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.elements.repo import GLOBAL_REPO, TensorRepo
from nnstreamer_tpu.filters.jax_backend import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.obs.flight import FlightRecorder
from nnstreamer_tpu.obs.quantiles import P2Quantile
from nnstreamer_tpu.pipeline import continuity, faults
from nnstreamer_tpu.query.resilience import DedupWindow, NEW, PENDING
from nnstreamer_tpu.serving.scheduler import (
    FeedbackController,
    ServiceRateEstimator,
    SloScheduler,
)
from nnstreamer_tpu.tensors import memory
from nnstreamer_tpu.tensors.buffer import TensorBuffer

jnp = pytest.importorskip("jax.numpy")


@pytest.fixture(autouse=True)
def _clean_injectors():
    faults.deactivate()
    memory.deactivate()
    yield
    faults.deactivate()
    memory.deactivate()


def _cval(name, **labels):
    m = get_registry().get(name, **labels)
    return 0.0 if m is None else m.value


def _wait(cond, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.01)


# -- live model swap ----------------------------------------------------------


@pytest.fixture
def swap_models():
    register_jax_model("cont_a", lambda x: x + 1.0)
    register_jax_model("cont_b", lambda x: x * 3.0)
    yield "cont_a", "cont_b"
    unregister_jax_model("cont_a")
    unregister_jax_model("cont_b")


SWAP_DESC = (
    "appsrc name=src ! "
    "tensor_transform mode=arithmetic option=typecast:float32,add:0.0 ! "
    "tensor_filter framework=jax model=cont_a name=filter "
    "is-updatable=true ! tensor_sink name=sink"
)

FRAMES = [np.full((4,), float(i), np.float32) for i in range(10)]


def _run_with_swap(desc, error_policy=None):
    """Push 5 frames, fence on their arrival, swap to cont_b, push 5
    more. The pre-arrival wait makes the cutover seq deterministic so
    byte-identity per side is assertable."""
    kw = {"error_policy": error_policy} if error_policy else {}
    pipe = parse_launch(desc, **kw)
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    try:
        for f in FRAMES[:5]:
            src.push([f.copy()])
        _wait(lambda: len(sink.buffers) >= 5, what="first 5 frames")
        report = pipe.swap_model("filter", model="cont_b")
        for f in FRAMES[5:]:
            src.push([f.copy()])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    outs = [np.asarray(b.tensors[0]) for b in sink.buffers]
    return pipe, report, outs


class TestSwapModel:
    def test_zero_drop_byte_identical_each_side(self, swap_models):
        swaps0 = _cval("nns_model_swaps_total")
        pipe, report, outs = _run_with_swap(SWAP_DESC)
        assert len(outs) == len(FRAMES), "swap dropped frames"
        for i in range(5):  # old epoch: x + 1
            assert np.array_equal(outs[i], FRAMES[i] + 1.0), f"frame {i}"
        for i in range(5, 10):  # new epoch: x * 3
            assert np.array_equal(outs[i], FRAMES[i] * 3.0), f"frame {i}"
        assert report["epoch"] == 1
        assert report["invalidations"] == 1, \
            "the owning fused region must invalidate exactly once"
        assert _cval("nns_model_swaps_total") == swaps0 + 1

    def test_swap_composes_with_retry_policy(self, swap_models):
        inj = faults.activate("filter.invoke:rate=0.3", seed=11)
        _, report, outs = _run_with_swap(SWAP_DESC, error_policy="retry")
        assert inj.injected("filter.invoke") > 0, "no fault ever fired"
        assert len(outs) == len(FRAMES), "retry + swap lost frames"
        for i in range(5):
            assert np.array_equal(outs[i], FRAMES[i] + 1.0), f"frame {i}"
        for i in range(5, 10):
            assert np.array_equal(outs[i], FRAMES[i] * 3.0), f"frame {i}"
        assert report["invalidations"] == 1

    def test_second_swap_bumps_epoch(self, swap_models):
        pipe = parse_launch(SWAP_DESC)
        pipe.start()
        try:
            r1 = pipe.swap_model("filter", model="cont_b")
            r2 = pipe.swap_model("filter", model="cont_a")
        finally:
            pipe.stop()
        assert (r1["epoch"], r2["epoch"]) == (1, 2)

    def test_bad_arguments_raise(self, swap_models):
        pipe = parse_launch(SWAP_DESC)
        pipe.start()
        try:
            with pytest.raises(ValueError, match="need model"):
                pipe.swap_model("filter")
            with pytest.raises(KeyError, match="no element"):
                pipe.swap_model("nope", model="cont_b")
            with pytest.raises(TypeError, match="not a tensor_filter"):
                pipe.swap_model("sink", model="cont_b")
        finally:
            pipe.stop()


# -- weights swap under an HBM budget (residency epoch accounting) ------------


class TestWeightsSwapResidency:
    SHAPE = (64, 64)

    def _register(self):
        ballast = jnp.ones(self.SHAPE, jnp.float32) * 2.0
        register_jax_model(
            "cont_w", lambda p, x: (x.astype(jnp.float32) * p["w"][0, 0],),
            {"w": ballast})
        return int(np.prod(self.SHAPE)) * 4

    def test_swap_retires_old_unit_no_leak(self, swap_models):
        nbytes = self._register()
        try:
            acct = memory.activate(4 * nbytes)
            pipe = parse_launch(
                "appsrc name=src ! tensor_filter framework=jax "
                "model=cont_w name=filter ! tensor_sink name=sink")
            src, sink = pipe.get("src"), pipe.get("sink")
            pipe.start()
            try:
                src.push([np.full((4,), 1.0, np.float32)])
                _wait(lambda: len(sink.buffers) >= 1, what="warmup frame")
                assert np.allclose(np.asarray(sink.buffers[0].tensors[0]),
                                   2.0)
                used_before = acct.used_bytes()
                keys_before = set(acct.residency._units.keys())

                new = {"w": jnp.ones(self.SHAPE, jnp.float32) * 5.0}
                report = pipe.swap_model("filter", weights=new)

                # the old epoch's unit retired in the same step — a swap
                # must not leak nns_mem_used_bytes
                assert acct.used_bytes() == used_before
                keys_after = set(acct.residency._units.keys())
                assert report["retired_unit"] in keys_before
                assert report["retired_unit"] not in keys_after
                assert report["residency_unit"] in keys_after
                assert report["residency_unit"].endswith(":e1")

                src.push([np.full((4,), 1.0, np.float32)])
                src.end_of_stream()
                msg = pipe.wait(timeout=60)
                assert msg is not None and msg.kind == "eos", msg
                assert np.allclose(np.asarray(sink.buffers[1].tensors[0]),
                                   5.0), "new weights never took effect"
            finally:
                pipe.stop()
        finally:
            unregister_jax_model("cont_w")


# -- component state round-trips ----------------------------------------------


class TestStateRoundTrips:
    def test_p2_quantile(self):
        q = P2Quantile(0.99)
        for i in range(200):
            q.observe(float(i % 37))
        clone = P2Quantile(0.99)
        clone.restore(q.snapshot())
        assert clone.quantile() == q.quantile()
        clone.observe(1000.0)  # restored markers keep streaming

    def test_service_rate_estimator(self):
        est = ServiceRateEstimator()
        for i in range(10):
            est.observe_invoke(0.004)
            est.observe_completion(now=float(i) * 0.01)
        clone = ServiceRateEstimator()
        clone.restore(est.snapshot())
        assert clone.snapshot() == est.snapshot()
        assert clone.service_time_s() == est.service_time_s()

    def test_slo_scheduler_round_trip(self):
        sched = SloScheduler(budget_ms=50.0, name="cont-rt")
        for _ in range(20):
            sched.estimator.observe_invoke(0.004)
            sched.controller.record_completion(0.01)
        state = sched.checkpoint_state()
        clone = SloScheduler(budget_ms=50.0, name="cont-rt2")
        clone.restore_state(state)
        assert clone.estimator.snapshot() == sched.estimator.snapshot()
        got = clone.controller.snapshot()
        want = sched.controller.snapshot()
        assert got["batch_cap"] == want["batch_cap"]
        assert got["inflight"] == want["inflight"]
        assert clone._lanes_hint >= sched._lanes_hint

    def test_flight_recorder_round_trip(self):
        fr = FlightRecorder(dump_dir=None, min_samples=5)
        for seq in range(12):
            t = float(seq)
            fr.span("device", seq, t, t + 0.002)
            fr.span("sink", seq, t + 0.002, t + 0.004, e2e_s=0.004)
        state = fr.checkpoint_state()
        clone = FlightRecorder(dump_dir=None, min_samples=5)
        clone.restore_state(state)
        assert clone.checkpoint_state()["completed"] == \
            state["completed"]
        assert clone.slo_snapshot() == fr.slo_snapshot()
        assert clone.attribution() == fr.attribution()

    def test_dedup_window_round_trip_drops_pending(self):
        w = DedupWindow(size=8)
        assert w.admit(1) is NEW
        w.resolve(1, ("reply", b"one"))
        assert w.admit(2) is NEW  # left PENDING on purpose
        clone = DedupWindow(size=8)
        clone.restore(w.snapshot())
        # the resolved id replays from the restored window...
        assert clone.admit(1) == ("reply", b"one")
        # ...but the in-flight one was dropped (its invocation died with
        # the old process), so the resend re-invokes
        assert clone.admit(2) is NEW

    def test_residency_lru_order_restored_by_label(self):
        acct = memory.activate(1 << 20)
        res = acct.residency
        units = {}
        for name in ("ua", "ub", "uc"):
            units[name] = res.register(
                key=f"k:{name}", host_value=np.zeros(4),
                nbytes=16, loader=lambda h: h, label=name)
        units["ua"].value()  # LRU touch: order becomes ub, uc, ua
        state = res.checkpoint_state()
        assert state["lru"] == ["ub", "uc", "ua"]

        memory.deactivate()
        acct2 = memory.activate(1 << 20)
        res2 = acct2.residency
        # a restarted process re-registers under NEW keys (id()-based);
        # labels are the stable identity the LRU order restores by
        for name in ("ua", "ub", "uc"):
            res2.register(key=f"k2:{name}", host_value=np.zeros(4),
                          nbytes=16, loader=lambda h: h, label=name)
        res2.restore_state(state)
        assert [u.label for u in res2._units.values()] == \
            ["ub", "uc", "ua"]


# -- tensor_repo slots under injected faults (satellite: repo coverage) -------


class TestRepoCheckpoint:
    def test_slot_snapshot_restore_round_trip(self):
        repo = TensorRepo()
        repo.set("slot0", TensorBuffer([np.arange(6, dtype=np.float32)]))
        repo.set("slot1", TensorBuffer([np.ones((2, 3), np.int32)]))
        state = repo.snapshot()
        clone = TensorRepo()
        clone.restore(state)
        for slot in ("slot0", "slot1"):
            a = repo.peek(slot).tensors[0]
            b = clone.peek(slot).tensors[0]
            assert np.array_equal(np.asarray(a), np.asarray(b))
        assert clone.get("slot0", consume=True) is not None
        assert clone.peek("slot0") is None  # consume still works

    def test_snapshot_is_host_side_copy(self):
        repo = TensorRepo()
        arr = np.arange(4, dtype=np.float32)
        repo.set("s", TensorBuffer([arr]))
        state = repo.snapshot()
        arr += 100.0  # mutating the live buffer after the snapshot...
        clone = TensorRepo()
        clone.restore(state)
        # ...must not corrupt the checkpoint (np.asarray of a host
        # ndarray aliases, so this documents the aliasing boundary:
        # restore happens in a NEW process in real use)
        assert clone.peek("s") is not None

    def test_repo_pipeline_survives_faults_then_checkpoints(self,
                                                            swap_models):
        """A repo-backed recurrent loop keeps its slot through injected
        filter faults + retry, and the surviving slot checkpoints."""
        inj = faults.activate("filter.invoke:rate=0.3", seed=3)
        desc = ("appsrc name=src ! tensor_filter framework=jax "
                "model=cont_a name=f ! tee name=t ! queue ! "
                "tensor_sink name=sink  "
                "t. ! queue ! tensor_reposink slot=77")
        pipe = parse_launch(desc, error_policy="retry")
        src, sink = pipe.get("src"), pipe.get("sink")
        pipe.start()
        try:
            for f in FRAMES[:6]:
                src.push([f.copy()])
            src.end_of_stream()
            msg = pipe.wait(timeout=60)
            assert msg is not None and msg.kind == "eos", msg
        finally:
            pipe.stop()
        assert inj.injected("filter.invoke") > 0, "no fault ever fired"
        assert len(sink.buffers) == 6, "retry lost frames"
        state = GLOBAL_REPO.snapshot()
        try:
            assert "77" in state, f"slot missing from snapshot: {state.keys()}"
            # the slot holds the LAST processed frame, byte-exact
            assert np.array_equal(state["77"][0], FRAMES[5] + 1.0)
        finally:
            GLOBAL_REPO.remove("77")


# -- pipeline checkpoint / restore end-to-end ---------------------------------


class TestPipelineCheckpointRestore:
    DESC = ("videotestsrc num-buffers=8 ! "
            "tensor_converter ! queue slo-budget-ms=100 ! "
            "tensor_filter framework=jax model=cont_a name=f ! "
            "tensor_sink name=sink")

    def test_stop_writes_state_and_restore_rearms(self, swap_models,
                                                  tmp_path):
        ckpt = str(tmp_path / "ckpt")
        pipe = parse_launch(self.DESC)
        pipe.checkpoint_dir = ckpt
        msg = pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        sched_state = pipe._slo_scheduler.checkpoint_state()
        path = os.path.join(ckpt, continuity.STATE_FILE)
        assert os.path.isfile(path), "stop() did not checkpoint"

        pipe2 = parse_launch(self.DESC)
        pipe2.checkpoint_dir = ckpt
        pipe2.start()  # maybe_restore_env picks up the state file
        try:
            assert pipe2._continuity_restored
            got = pipe2._slo_scheduler.checkpoint_state()
            assert got["estimator"] == sched_state["estimator"], \
                "service-rate EWMAs did not survive the restart"
        finally:
            pipe2.stop()

    def test_explicit_checkpoint_restore_api(self, swap_models, tmp_path):
        pipe = parse_launch(self.DESC)
        pipe.start()
        try:
            path = pipe.checkpoint(str(tmp_path))
            assert os.path.isfile(path)
            applied = pipe.restore(str(tmp_path))
            assert applied["pipeline"] == pipe.name
        finally:
            pipe.stop()

    def test_version_mismatch_refuses(self, swap_models, tmp_path):
        pipe = parse_launch(self.DESC)
        pipe.start()
        try:
            pipe.checkpoint(str(tmp_path))
        finally:
            pipe.stop()
        import pickle

        path = os.path.join(str(tmp_path), continuity.STATE_FILE)
        with open(path, "rb") as f:
            state = pickle.load(f)
        state["version"] = 999
        with open(path, "wb") as f:
            pickle.dump(state, f)
        pipe2 = parse_launch(self.DESC)
        with pytest.raises(ValueError, match="state version"):
            pipe2.restore(str(tmp_path))

    def test_corrupt_checkpoint_never_fails_teardown(self, swap_models,
                                                     tmp_path,
                                                     monkeypatch):
        # an unwritable checkpoint dir must log, not raise, on stop()
        target = tmp_path / "blocked"
        target.write_text("a file where a directory must go")
        pipe = parse_launch(self.DESC)
        pipe.checkpoint_dir = str(target)
        msg = pipe.run(timeout=60)  # stop() runs inside run()
        assert msg is not None and msg.kind == "eos", msg


# -- kill switches ------------------------------------------------------------


class TestKillSwitches:
    def test_unset_env_writes_nothing(self, swap_models, tmp_path,
                                      monkeypatch):
        monkeypatch.delenv(continuity.CHECKPOINT_ENV, raising=False)
        monkeypatch.delenv(continuity.CACHE_ENV, raising=False)
        monkeypatch.chdir(tmp_path)
        pipe = parse_launch(
            "videotestsrc num-buffers=4 ! tensor_converter ! "
            "tensor_filter framework=jax model=cont_a ! fakesink")
        msg = pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        assert pipe.checkpoint_dir is None
        assert not pipe._continuity_restored
        assert list(tmp_path.iterdir()) == [], \
            "unarmed continuity wrote files"

    def test_maybe_restore_without_state_file_is_noop(self, swap_models,
                                                      tmp_path):
        pipe = parse_launch(
            "videotestsrc num-buffers=1 ! tensor_converter ! fakesink")
        pipe.checkpoint_dir = str(tmp_path)  # armed, but no state file
        assert continuity.maybe_restore_env(pipe) is None
        assert not pipe._continuity_restored

    def test_env_arms_checkpoint_on_stop(self, swap_models, tmp_path,
                                         monkeypatch):
        monkeypatch.setenv(continuity.CHECKPOINT_ENV, str(tmp_path))
        pipe = parse_launch(
            "videotestsrc num-buffers=2 ! tensor_converter ! "
            "tensor_filter framework=jax model=cont_a ! fakesink")
        msg = pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        assert os.path.isfile(
            os.path.join(str(tmp_path), continuity.STATE_FILE))
        # the armed checkpoint dir also defaulted the compile cache in
        assert continuity.compile_cache_dir() == \
            os.path.join(str(tmp_path), continuity.CACHE_SUBDIR)


# -- persistent compile cache -------------------------------------------------


class TestCompileCache:
    def test_cleared_jit_cache_reloads_from_disk(self, tmp_path_factory):
        import jax

        cache_dir = str(tmp_path_factory.mktemp("xla-cache"))
        continuity.enable_compile_cache(cache_dir)
        # idempotent re-arm is a no-op
        assert continuity.enable_compile_cache(cache_dir) == \
            os.path.abspath(cache_dir)

        # odd constants: a program no other test in this process has
        # compiled yet, so the cold trace is a genuine cache miss
        fn = jax.jit(lambda x: x * 2.125 + 7.375)
        x = jnp.arange(8, dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(fn(x)),
                                   np.arange(8) * 2.125 + 7.375)
        before = continuity.cache_stats()
        assert before["misses"] >= 1, "cold compile never hit the cache"

        jax.clear_caches()  # simulate the restarted process
        fn2 = jax.jit(lambda x: x * 2.125 + 7.375)
        np.testing.assert_allclose(np.asarray(fn2(x)),
                                   np.arange(8) * 2.125 + 7.375)
        after = continuity.cache_stats()
        assert after["hits"] > before["hits"], \
            "warm trace compiled instead of loading from the cache"

    def test_materialized_host_buffers_own_their_bytes(self):
        # warm-boot regression: a cache-deserialized fused program keeps
        # its input-output aliasing, so outputs live in donated slabs; a
        # zero-copy to_host view of one would dangle after the dispatch
        # fence. Materialization must detach from the XLA buffer.
        buf = TensorBuffer([jnp.arange(8, dtype=jnp.float32)])
        host = buf.to_host()
        v = host.tensors[0]
        assert isinstance(v, np.ndarray)
        assert v.base is None and v.flags.owndata, \
            "to_host returned a view into an XLA buffer"

    def test_manifest_written_with_region_signatures(self, swap_models,
                                                     tmp_path):
        import json

        continuity.enable_compile_cache(str(tmp_path / "cache"))
        pipe = parse_launch(
            "videotestsrc num-buffers=2 ! tensor_converter ! "
            "tensor_transform mode=arithmetic option=typecast:float32 ! "
            "tensor_filter framework=jax model=cont_a name=f ! "
            "tensor_sink name=sink")
        msg = pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        path = continuity.write_program_manifest(pipe)
        assert path is not None
        doc = json.loads(open(path).read())
        assert doc["programs"], "no fused-region signatures recorded"
        sig = doc["programs"][0]
        assert sig["signature"] and len(sig["signature"]) == 16
        assert any(m["model"] == "cont_a" for m in sig["members"])
