"""Element behavior tests (reference: unittest_plugins.cc, 7482 LoC — per
element behavior incl. transform paths and filter prop validation)."""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.pipeline.pipeline import Pipeline


def run_pipeline(desc: str, timeout=30):
    pipe = parse_launch(desc)
    msg = pipe.run(timeout=timeout)
    assert msg is not None and msg.kind == "eos", f"no EOS: {msg}"
    return pipe


class TestVideoTestSrcConverter:
    def test_video_to_tensor(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=5 width=32 height=24 ! "
            "tensor_converter ! tensor_sink name=out"
        )
        bufs = pipe.get("out").buffers
        assert len(bufs) == 5
        assert bufs[0][0].shape == (1, 24, 32, 3)
        assert bufs[0][0].dtype == np.uint8
        caps = pipe.get("out").sinkpad.caps
        assert caps["dimensions"] == "3:32:24:1"

    def test_frames_per_tensor(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=6 width=8 height=8 ! "
            "tensor_converter frames-per-tensor=3 ! tensor_sink name=out"
        )
        bufs = pipe.get("out").buffers
        assert len(bufs) == 2
        assert bufs[0][0].shape == (3, 8, 8, 3)

    def test_deterministic_frames(self):
        p1 = run_pipeline(
            "videotestsrc num-buffers=2 pattern=ball width=16 height=16 ! "
            "tensor_converter ! tensor_sink name=out"
        )
        p2 = run_pipeline(
            "videotestsrc num-buffers=2 pattern=ball width=16 height=16 ! "
            "tensor_converter ! tensor_sink name=out"
        )
        for a, b in zip(p1.get("out").buffers, p2.get("out").buffers):
            np.testing.assert_array_equal(a[0], b[0])

    def test_audio_to_tensor(self):
        pipe = run_pipeline(
            "audiotestsrc num-buffers=3 samplesperbuffer=160 ! "
            "tensor_converter ! tensor_sink name=out"
        )
        bufs = pipe.get("out").buffers
        assert len(bufs) == 3
        assert bufs[0][0].shape == (160, 1)
        assert bufs[0][0].dtype == np.int16

    def test_octet_rechunk(self, tmp_path):
        raw = np.arange(64, dtype=np.uint8).tobytes()
        f = tmp_path / "data.raw"
        f.write_bytes(raw)
        pipe = run_pipeline(
            f"filesrc location={f} blocksize=10 ! "
            "tensor_converter input-dim=16 input-type=uint8 ! "
            "tensor_sink name=out"
        )
        bufs = pipe.get("out").buffers
        assert len(bufs) == 4  # 64 bytes / 16-byte frames
        np.testing.assert_array_equal(
            np.concatenate([b[0].reshape(-1) for b in bufs]),
            np.frombuffer(raw, np.uint8),
        )


class TestTransform:
    def _run(self, mode, option, data):
        from nnstreamer_tpu.elements.transform import _TransformSpec

        return np.asarray(_TransformSpec(mode, option, accelerate=False)(data))

    def test_typecast(self):
        out = self._run("typecast", "float32", np.array([1, 2], np.uint8))
        assert out.dtype == np.float32

    def test_arithmetic_chain(self):
        out = self._run("arithmetic", "typecast:float32,add:-127.5,div:127.5",
                        np.array([255, 0], np.uint8))
        np.testing.assert_allclose(out, [1.0, -1.0])

    def test_transpose(self):
        x = np.zeros((1, 24, 32, 3))  # dims (3,32,24,1)
        out = self._run("transpose", "1:0:2:3", x)
        # dims become (32,3,24,1) → shape (1,24,3,32)
        assert out.shape == (1, 24, 3, 32)

    def test_dimchg(self):
        x = np.zeros((1, 24, 32, 3))  # dims (3,32,24,1); move dim0→dim2
        out = self._run("dimchg", "0:2", x)
        assert out.shape == (1, 3, 24, 32)  # dims (32,24,3,1)

    def test_clamp(self):
        out = self._run("clamp", "0:1", np.array([-5.0, 0.5, 7.0]))
        np.testing.assert_allclose(out, [0, 0.5, 1])

    def test_stand_default(self):
        out = self._run("stand", "default", np.arange(10, dtype=np.float32))
        assert abs(out.mean()) < 1e-5
        assert abs(out.std() - 1.0) < 1e-3

    def test_jit_path_matches_numpy(self):
        x = np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32)
        from nnstreamer_tpu.elements.transform import _TransformSpec

        a = np.asarray(_TransformSpec("arithmetic", "add:1.5,mul:2.0", True)(x))
        b = np.asarray(_TransformSpec("arithmetic", "add:1.5,mul:2.0", False)(x))
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_in_pipeline_caps_update(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_sink name=out"
        )
        caps = pipe.get("out").sinkpad.caps
        assert caps["types"] == "float32"
        assert pipe.get("out").buffers[0][0].dtype == np.float32


class TestFilterCustomEasy:
    def setup_method(self):
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("3:8:8:1", "float32")
        register_custom_easy(
            "scale2x", lambda ins: [np.asarray(ins[0]) * 2.0], info, info
        )

    def test_invoke_in_pipeline(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=3 width=8 height=8 ! tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_filter framework=custom-easy model=scale2x name=f ! "
            "tensor_sink name=out"
        )
        outs = pipe.get("out").buffers
        assert len(outs) == 3
        f = pipe.get("f")
        assert f.stats.total_invokes == 3
        assert f.get_property("latency") >= 0

    def test_shape_mismatch_rejected(self):
        pipe = parse_launch(
            "videotestsrc num-buffers=1 width=16 height=16 ! tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_filter framework=custom-easy model=scale2x ! "
            "tensor_sink"
        )
        from nnstreamer_tpu.pipeline.element import FlowError

        with pytest.raises(FlowError, match="do not match model input"):
            pipe.run(timeout=15)


class TestFilterJax:
    def test_registered_model_end_to_end(self):
        import jax.numpy as jnp
        from nnstreamer_tpu.filters.jax_backend import register_jax_model

        register_jax_model(
            "normalize8", lambda x: (x.astype(jnp.float32) / 255.0).mean(
                axis=(1, 2)
            )
        )
        pipe = run_pipeline(
            "videotestsrc num-buffers=4 width=8 height=8 ! tensor_converter ! "
            "tensor_filter framework=jax model=normalize8 name=f ! "
            "tensor_sink name=out"
        )
        outs = pipe.get("out").buffers
        assert len(outs) == 4
        assert outs[0][0].shape == (1, 3)
        assert outs[0][0].dtype == np.float32
        # negotiated caps must match eval_shape-derived info
        caps = pipe.get("out").sinkpad.caps
        assert caps["dimensions"] == "3:1"
        assert caps["types"] == "float32"

    def test_py_file_model(self, tmp_path):
        model = tmp_path / "addone.py"
        model.write_text(
            "import jax.numpy as jnp\n"
            "def get_model():\n"
            "    return lambda x: x + 1\n"
        )
        from nnstreamer_tpu.single import SingleShot

        s = SingleShot(framework="jax", model=str(model))
        out = s.invoke([np.zeros((2, 2), np.float32)])
        np.testing.assert_array_equal(np.asarray(out[0]), np.ones((2, 2)))
        s.close()

    def test_framework_auto_detect(self, tmp_path):
        model = tmp_path / "ident.py"
        model.write_text("def get_model():\n    return lambda x: x\n")
        from nnstreamer_tpu.elements.filter import detect_framework

        # .py resolves to the python backend by priority; jax also loads .py.
        assert detect_framework(str(model)) in ("python", "jax")


class TestDecoder:
    def test_image_labeling(self, tmp_path):
        labels = tmp_path / "labels.txt"
        labels.write_text("cat\ndog\nbird\n")
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        register_custom_easy(
            "always_dog",
            lambda ins: [np.array([[0.1, 0.8, 0.1]], np.float32)],
            TensorsInfo.from_str("3:8:8:1", "uint8"),
            TensorsInfo.from_str("3:1", "float32"),
        )
        pipe = run_pipeline(
            "videotestsrc num-buffers=2 width=8 height=8 ! tensor_converter ! "
            "tensor_filter framework=custom-easy model=always_dog ! "
            f"tensor_decoder mode=image_labeling option1={labels} ! "
            "tensor_sink name=out"
        )
        outs = pipe.get("out").buffers
        assert outs[0].meta["label"] == "dog"
        assert bytes(outs[0][0]).decode() == "dog"
        assert pipe.get("out").sinkpad.caps.name == "text/x-raw"

    def test_direct_video_roundtrip(self):
        pipe = run_pipeline(
            "videotestsrc num-buffers=1 width=16 height=8 ! tensor_converter ! "
            "tensor_decoder mode=direct_video ! tensor_sink name=out"
        )
        out = pipe.get("out")
        assert out.buffers[0][0].shape == (8, 16, 3)
        caps = out.sinkpad.caps
        assert caps.name == "video/x-raw"
        assert caps["width"] == 16 and caps["height"] == 8


class TestAudioModelPipeline:
    """End-to-end audio inference: the audio stream path gets a real model
    (models/audio_classifier), not just a custom-filter stand-in — same
    converter/window/filter/decoder contract the vision pipelines use."""

    def test_audio_classifier_pipeline(self):
        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model, unregister_jax_model)
        from nnstreamer_tpu.models.audio_classifier import audio_classifier

        samples = 1600  # small window keeps CPU-XLA compile snappy
        apply_fn, params, in_info, out_info = audio_classifier(
            samples=samples, num_classes=4)
        register_jax_model("kws_test", apply_fn, params,
                           in_info=in_info, out_info=out_info)
        try:
            pipe = run_pipeline(
                f"audiotestsrc num-buffers=3 samplesperbuffer={samples} ! "
                f"tensor_converter frames-per-tensor={samples} ! "
                "tensor_transform mode=arithmetic "
                "option=typecast:float32,div:32768 ! "
                "tensor_filter framework=jax model=kws_test ! "
                "tensor_decoder mode=image_labeling ! "
                "tensor_sink name=out to-host=true", timeout=120)
            outs = pipe.get("out").buffers
            assert len(outs) == 3
            for b in outs:
                # decoder output is the label text (utf8) + index in meta
                assert 0 <= int(b.meta["label_index"]) < 4
                text = np.asarray(b[0]).tobytes().decode("utf-8")
                assert text == str(b.meta["label_index"])
        finally:
            unregister_jax_model("kws_test")

    def test_audio_windowed_aggregation(self):
        """aggregator windows small audio chunks into the model's frame
        size (the reference's aggregator-before-filter audio pattern)."""
        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model, unregister_jax_model)
        from nnstreamer_tpu.models.audio_classifier import audio_classifier

        apply_fn, params, in_info, out_info = audio_classifier(
            samples=800, num_classes=3)
        register_jax_model("kws_win", apply_fn, params,
                           in_info=in_info, out_info=out_info)
        try:
            pipe = run_pipeline(
                "audiotestsrc num-buffers=8 samplesperbuffer=200 ! "
                "tensor_converter ! "
                "tensor_aggregator frames-in=200 frames-out=800 "
                "frames-dim=1 concat=true ! "
                "tensor_transform mode=arithmetic "
                "option=typecast:float32,div:32768 ! "
                "tensor_filter framework=jax model=kws_win ! "
                "tensor_sink name=out to-host=true", timeout=120)
            outs = pipe.get("out").buffers
            assert len(outs) == 2  # 8 × 200 samples → 2 × 800 windows
            assert np.asarray(outs[0][0]).reshape(-1).shape == (3,)
        finally:
            unregister_jax_model("kws_win")
