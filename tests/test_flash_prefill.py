"""Flash attention wired into prefill (round-5 VERDICT #4).

The Pallas kernel (ops/flash_attention.py) now backs the O(s²) prompt
pass: the serving engine's ``attention="auto"`` builds prefill with the
kernel (TPU, tileable shapes) and XLA attention elsewhere. Off-TPU the
kernel runs in interpret mode when forced — these tests pin exactness
against the materialized math, including a ≥2k-token prompt, so the
TPU fast path computes the same function the fallback does.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models.transformer import (
    TransformerConfig,
    build_decode_step,
    build_prefill,
    init_params,
)
from nnstreamer_tpu.ops import flash_attention

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _flash_forced(q, k, v):
    # force="pallas" runs the REAL kernel (interpret mode off-TPU), so
    # CPU CI exercises the exact program the TPU fast path compiles
    return flash_attention(q, k, v, causal=True, force="pallas")


CFG = TransformerConfig(vocab=256, d_model=64, n_heads=2, n_layers=2,
                        d_ff=128, max_seq=64, dtype=jnp.float32)


class TestPrefillExactness:
    def test_flash_prefill_matches_reference_math(self):
        params = init_params(CFG, seed=0)
        toks = jnp.asarray(
            np.random.default_rng(0).integers(1, CFG.vocab, (2, 32)),
            jnp.int32)
        ref_logits, ref_cache = build_prefill(CFG)(params, toks)
        fl_logits, fl_cache = build_prefill(
            CFG, attention_fn=_flash_forced)(params, toks)
        np.testing.assert_allclose(np.asarray(fl_logits),
                                   np.asarray(ref_logits),
                                   rtol=2e-4, atol=2e-4)
        for a, b in zip(jax.tree_util.tree_leaves(fl_cache),
                        jax.tree_util.tree_leaves(ref_cache)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_flash_prefill_greedy_continuation_token_exact(self):
        """The whole point of the numeric contract: greedy decode seeded
        by a flash prefill emits the same tokens as one seeded by the
        reference prefill."""
        params = init_params(CFG, seed=1)
        toks = jnp.asarray(
            np.random.default_rng(1).integers(1, CFG.vocab, (1, 16)),
            jnp.int32)
        step = jax.jit(build_decode_step(CFG))

        def rollout(prefill_fn, n=12):
            logits, cache = prefill_fn(params, toks)
            last = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            pos = jnp.full((1,), toks.shape[1], jnp.int32)
            out = [int(last[0])]
            for _ in range(n - 1):
                logits, cache = step(params, last, cache, pos)
                last = jnp.argmax(logits[:, :], axis=-1).astype(jnp.int32)
                pos = pos + 1
                out.append(int(last[0]))
            return out

        ref = rollout(jax.jit(build_prefill(CFG)))
        fl = rollout(jax.jit(build_prefill(CFG,
                                           attention_fn=_flash_forced)))
        assert fl == ref

    def test_flash_prefill_right_padded_lengths(self):
        """Bucket padding contract survives the kernel: padded rows'
        logits come from the true last position and match the unpadded
        prefill."""
        params = init_params(CFG, seed=2)
        rng = np.random.default_rng(2)
        true = rng.integers(1, CFG.vocab, (1, 11))
        padded = np.zeros((1, 16), np.int64)
        padded[:, :11] = true
        # s=11 does not tile — the reference path scores the exact
        # prompt; the PADDED s=16 call runs through the kernel
        exact_logits, _ = build_prefill(CFG)(
            params, jnp.asarray(true, jnp.int32))
        pad_logits, _ = build_prefill(CFG, attention_fn=_flash_forced)(
            params, jnp.asarray(padded, jnp.int32),
            jnp.asarray([11], jnp.int32))
        np.testing.assert_allclose(np.asarray(pad_logits),
                                   np.asarray(exact_logits),
                                   rtol=2e-4, atol=2e-4)


class TestLongPrompt:
    def test_2k_token_prefill_through_the_kernel(self):
        """≥2k-token prompt through the REAL kernel (interpret off-TPU):
        the long-context path the kernel exists for, verified against
        materialized attention."""
        cfg = TransformerConfig(vocab=128, d_model=64, n_heads=2,
                                n_layers=1, d_ff=64, max_seq=2048,
                                dtype=jnp.float32)
        params = init_params(cfg, seed=3)
        toks = jnp.asarray(
            np.random.default_rng(3).integers(1, cfg.vocab, (1, 2048)),
            jnp.int32)
        fl_logits, fl_cache = build_prefill(
            cfg, attention_fn=_flash_forced)(params, toks)
        ref_logits, ref_cache = build_prefill(cfg)(params, toks)
        np.testing.assert_allclose(np.asarray(fl_logits),
                                   np.asarray(ref_logits),
                                   rtol=5e-4, atol=5e-4)
        ck_fl = jax.tree_util.tree_leaves(fl_cache)[0]
        ck_ref = jax.tree_util.tree_leaves(ref_cache)[0]
        np.testing.assert_allclose(np.asarray(ck_fl), np.asarray(ck_ref),
                                   rtol=5e-4, atol=5e-4)


class TestEngineAuto:
    def test_engine_auto_equals_reference_attention(self):
        """attention='auto' (kernel on TPU, XLA fallback here) generates
        the same tokens as attention='reference'."""
        from nnstreamer_tpu.serving import ContinuousBatchingEngine

        params = init_params(CFG, seed=4)
        prompt = np.random.default_rng(4).integers(
            1, CFG.vocab, 12).tolist()
        outs = {}
        for mode in ("auto", "reference"):
            eng = ContinuousBatchingEngine(
                CFG, params, max_streams=2, steps_per_dispatch=4,
                temperature=0.0, attention=mode).start()
            try:
                outs[mode] = eng.generate(prompt, max_new_tokens=16,
                                          timeout=120)
            finally:
                eng.stop()
        assert outs["auto"] == outs["reference"]

    def test_engine_auto_k_calibrates_and_generates(self):
        """steps_per_dispatch='auto' measures rtt/step and picks a
        power-of-two K in [8,128]; tokens match a fixed-K engine."""
        from nnstreamer_tpu.serving import ContinuousBatchingEngine

        params = init_params(CFG, seed=5)
        prompt = np.random.default_rng(5).integers(
            1, CFG.vocab, 10).tolist()
        auto = ContinuousBatchingEngine(
            CFG, params, max_streams=2, steps_per_dispatch="auto",
            temperature=0.0).start()
        try:
            assert auto.K in (8, 16, 32, 64, 128)
            got = auto.generate(prompt, max_new_tokens=12, timeout=120)
        finally:
            auto.stop()
        fixed = ContinuousBatchingEngine(
            CFG, params, max_streams=2, steps_per_dispatch=4,
            temperature=0.0).start()
        try:
            want = fixed.generate(prompt, max_new_tokens=12, timeout=120)
        finally:
            fixed.stop()
        assert got == want

    def test_engine_rejects_unknown_attention(self):
        from nnstreamer_tpu.serving import ContinuousBatchingEngine

        with pytest.raises(ValueError, match="attention"):
            ContinuousBatchingEngine(CFG, init_params(CFG),
                                     attention="fast")
