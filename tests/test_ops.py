"""Pallas kernel library (nnstreamer_tpu/ops): kernels run in interpret
mode on CPU and must match their XLA reference implementations."""

import numpy as np
import pytest

import jax.numpy as jnp

from nnstreamer_tpu.ops import (
    dequantize_int8,
    flash_attention,
    normalize_u8,
    quantize_int8,
)
from nnstreamer_tpu.ops.flash_attention import attention_reference


def _qkv(b=2, s=256, h=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    ref = attention_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, force="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_flash_attention_blocked_causality():
    """Causality must hold across k-block boundaries, not just inside."""
    q, k, v = _qkv(b=1, s=256, h=1, d=16, seed=3)
    out = np.asarray(flash_attention(q, k, v, causal=True, force="pallas",
                                     block_q=64, block_k=64))
    # changing future keys must not affect earlier queries
    k2 = k.at[:, 128:].set(0.0)
    v2 = v.at[:, 128:].set(0.0)
    out2 = np.asarray(flash_attention(q, k2, v2, causal=True,
                                      force="pallas", block_q=64,
                                      block_k=64))
    np.testing.assert_allclose(out[:, :128], out2[:, :128],
                               rtol=1e-4, atol=1e-5)


def test_flash_attention_auto_fallback_ragged():
    """Non-tileable shapes silently use the reference path."""
    q, k, v = _qkv(s=100, d=24)
    out = flash_attention(q, k, v)  # auto → reference on CPU
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))


def test_normalize_u8_matches_reference():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 256, (224, 224, 3)), jnp.uint8)
    ref = np.asarray(((np.asarray(x, np.float32) - 127.5) / 127.5))
    out = normalize_u8(x, 127.5, 1 / 127.5, jnp.float32, force="pallas")
    assert out.shape == x.shape and out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)


def test_normalize_u8_bf16_output():
    x = jnp.asarray(np.arange(300) % 256, jnp.uint8).reshape(10, 30)
    out = normalize_u8(x, force="pallas")
    assert out.dtype == jnp.bfloat16 and out.shape == (10, 30)


def test_quantize_reference_roundtrip():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(scale=3.0, size=(64, 128)), jnp.float32)
    q, scale = quantize_int8(x, force="reference")
    assert q.dtype == jnp.int8
    back = dequantize_int8(q, scale)
    err = np.max(np.abs(np.asarray(back) - np.asarray(x)))
    assert err <= float(scale[0]) * 0.51


def test_quantize_pallas_roundtrip():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(scale=3.0, size=(64, 128)), jnp.float32)
    q, scale = quantize_int8(x, force="pallas")
    assert q.dtype == jnp.int8 and q.shape == x.shape
    back = dequantize_int8(q, scale)
    # stochastic dither: per-element error bounded by one quantization step
    err = np.max(np.abs(np.asarray(back) - np.asarray(x)))
    assert err <= float(scale[0]) * 1.01
