"""Reference GstTensorMetaInfo wire layout for flexible/sparse streams.

Golden-byte fixtures below are hand-derived straight from the reference
struct definition (tensor_typedef.h:283-297) and its pack/parse code
(tensor_common.c:1669-1723) and sparse payload writer
(tensor_sparse_util.c:236-240) — independent of the implementation
under test, so they prove byte-level interop both directions.
"""

import struct

import numpy as np
import pytest

from nnstreamer_tpu.tensors.meta import (
    REF_HEADER_SIZE,
    TensorMetaInfo,
    is_ref_header,
    pack_tensor,
    parse_header,
    unpack_tensor,
)
from nnstreamer_tpu.tensors.types import TensorFormat, TensorInfo

REF_VERSION = 0xDE001000  # GST_TENSOR_META_MAKE_VERSION(1, 0)


def golden_header(type_idx, dims, fmt=0, media=4, nnz=0):
    """Build the 128-byte header exactly as the C struct memcpy lays it
    out: u32 version, u32 type, u32 dim[16] zero-terminated, u32 format,
    u32 media_type, u32 nnz, zero padding."""
    words = [REF_VERSION, type_idx] + list(dims) + \
        [0] * (16 - len(dims)) + [fmt, media, nnz]
    hdr = struct.pack("<21I", *words)
    return hdr + b"\x00" * (REF_HEADER_SIZE - len(hdr))


class TestRefHeader:
    def test_pack_matches_golden_flexible(self):
        """float32 [4:3:2] flexible frame header, byte-for-byte."""
        meta = TensorMetaInfo(type="float32", dim=(4, 3, 2),
                              format=TensorFormat.FLEXIBLE)
        assert meta.pack_ref() == golden_header(7, [4, 3, 2], fmt=1)

    def test_unpack_golden(self):
        hdr = golden_header(2, [10, 5], fmt=0)  # int16 [10:5] static
        meta = TensorMetaInfo.unpack_ref(hdr)
        assert meta.type.value == "int16"
        assert meta.dim == (10, 5)
        assert meta.format is TensorFormat.STATIC
        assert meta.sparse_nnz == 0

    def test_roundtrip_sparse_header(self):
        meta = TensorMetaInfo(type="uint8", dim=(8, 8),
                              format=TensorFormat.SPARSE, sparse_nnz=5)
        back = TensorMetaInfo.unpack_ref(meta.pack_ref())
        assert back == meta
        assert meta.pack_ref() == golden_header(5, [8, 8], fmt=2, nnz=5)

    def test_sniffing(self):
        ref = golden_header(7, [2], fmt=1)
        assert is_ref_header(ref)
        native = TensorMetaInfo(type="float32", dim=(2,),
                                format=TensorFormat.FLEXIBLE).pack()
        assert not is_ref_header(native)
        m1, h1 = parse_header(ref)
        m2, h2 = parse_header(native)
        assert m1.dim == m2.dim == (2,)
        assert h1 == REF_HEADER_SIZE and h2 != REF_HEADER_SIZE

    def test_bad_version_refused(self):
        hdr = bytearray(golden_header(7, [2]))
        hdr[3] = 0x00  # break the 0xDE magic byte
        with pytest.raises(ValueError, match="version"):
            TensorMetaInfo.unpack_ref(bytes(hdr))

    def test_validate_like_reference(self):
        """gst_tensor_meta_info_validate rejections: bad type, empty
        dimension, bad format, bad media type."""
        with pytest.raises(ValueError, match="tensor_type"):
            TensorMetaInfo.unpack_ref(golden_header(10, [2]))  # _NNS_END
        with pytest.raises(ValueError, match="dimension"):
            TensorMetaInfo.unpack_ref(golden_header(7, []))
        with pytest.raises(ValueError, match="tensor_format"):
            TensorMetaInfo.unpack_ref(golden_header(7, [2], fmt=3))
        with pytest.raises(ValueError, match="media_type"):
            TensorMetaInfo.unpack_ref(golden_header(7, [2], media=9))

    def test_fp16_refused_in_ref_layout(self):
        meta = TensorMetaInfo(type="float16", dim=(2,),
                              format=TensorFormat.FLEXIBLE)
        with pytest.raises(ValueError, match="tensor_type"):
            meta.pack_ref()
        assert TensorMetaInfo.unpack(meta.pack()) == meta  # native is fine


class TestFlexibleStream:
    def test_pack_tensor_reference_layout(self):
        """A reference peer receiving our flexible tensor memory sees
        header || raw payload with its own struct layout."""
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        blob = pack_tensor(a, layout="reference")
        assert blob[:REF_HEADER_SIZE] == golden_header(7, [3, 2], fmt=1)
        assert blob[REF_HEADER_SIZE:] == a.tobytes()
        out, end = unpack_tensor(blob)
        np.testing.assert_array_equal(out, a)
        assert end == len(blob)

    def test_unpack_accepts_reference_peer_payload(self):
        """A flexible memory built by reference code (golden header +
        payload) parses through the generic unpack path."""
        a = np.arange(12, dtype=np.int32).reshape(3, 4)
        blob = golden_header(0, [4, 3], fmt=1) + a.tobytes()
        out, _ = unpack_tensor(blob)
        np.testing.assert_array_equal(out, a)

    def test_native_layout_unchanged(self):
        a = np.arange(4, dtype=np.float16)
        out, _ = unpack_tensor(pack_tensor(a))
        np.testing.assert_array_equal(out, a)


class TestSparseWire:
    def _dense(self):
        d = np.zeros((4, 4), np.float32)
        d[0, 1] = 1.5
        d[2, 3] = -2.0
        d[3, 0] = 7.0
        return d

    def test_encode_matches_reference_golden(self):
        """gst_tensor_sparse_from_dense writes header || values ||
        uint32 flat indices (tensor_sparse_util.c:236-240)."""
        from nnstreamer_tpu.elements.sparse import sparse_encode

        d = self._dense()
        flat = d.reshape(-1)
        nz = np.flatnonzero(flat).astype(np.uint32)
        golden = (golden_header(7, [4, 4], fmt=2, nnz=len(nz))
                  + flat[nz].astype(np.float32).tobytes() + nz.tobytes())
        assert sparse_encode(d, layout="reference") == golden

    def test_decode_reference_peer_payload(self):
        from nnstreamer_tpu.elements.sparse import sparse_decode

        d = self._dense()
        flat = d.reshape(-1)
        nz = np.flatnonzero(flat).astype(np.uint32)
        golden = (golden_header(7, [4, 4], fmt=2, nnz=len(nz))
                  + flat[nz].astype(np.float32).tobytes() + nz.tobytes())
        out, end = sparse_decode(golden)
        np.testing.assert_array_equal(out, d)
        assert end == len(golden)

    def test_native_layout_roundtrip(self):
        from nnstreamer_tpu.elements.sparse import (
            sparse_decode,
            sparse_encode,
        )

        d = self._dense()
        out, _ = sparse_decode(sparse_encode(d, layout="native"))
        np.testing.assert_array_equal(out, d)

    @pytest.mark.parametrize("layout", ["reference", "native"])
    def test_pipeline_enc_dec_loop(self, layout):
        from nnstreamer_tpu import parse_launch

        pipe = parse_launch(
            "videotestsrc num-buffers=2 width=4 height=4 ! "
            "tensor_converter ! "
            f"tensor_sparse_enc layout={layout} ! tensor_sparse_dec ! "
            "tensor_sink name=out")
        outs = []
        pipe.get("out").connect(lambda buf: outs.append(buf))
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos", msg
        assert len(outs) == 2
        assert np.asarray(outs[0].tensors[0]).shape == (1, 4, 4, 3)

    def test_bad_layout_refused(self):
        from nnstreamer_tpu import parse_launch
        from nnstreamer_tpu.pipeline.pipeline import FlowError

        pipe = parse_launch(
            "videotestsrc num-buffers=1 width=4 height=4 ! "
            "tensor_converter ! tensor_sparse_enc layout=bogus ! "
            "tensor_sink name=out")
        with pytest.raises(FlowError, match="unknown layout"):
            pipe.run(timeout=30)
