"""Script, pipeline-as-filter, and transformers filter backends.

Reference parity: tensor_filter_lua.cc (script-defined filters),
tensor_filter_mediapipe.cc (sub-graph as a filter), and the heavyweight
framework subplugins (tensor_filter_tensorflow.cc / _pytorch.cc) whose
TPU-native peer loads HF-format checkpoints through Flax.
"""

import json

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.api import FilterProperties
from nnstreamer_tpu.registry import FILTER, get_subplugin
from nnstreamer_tpu.tensors.types import TensorsInfo


def _run_collect(desc, sink="out"):
    pipe = parse_launch(desc)
    outs = []
    pipe.get(sink).connect(lambda b: outs.append(b))
    pipe.run(timeout=120)
    return outs


class TestScriptFilter:
    def test_inline_expression(self):
        outs = _run_collect(
            "videotestsrc num-buffers=3 width=8 height=8 ! "
            "tensor_converter ! tensor_transform mode=typecast "
            "option=float32 ! "
            'tensor_filter framework=script model="y = jnp.tanh(x) * 2.0" ! '
            "tensor_sink name=out to-host=true"
        )
        assert len(outs) == 3
        got = np.asarray(outs[0].tensors[0])
        assert got.shape == (1, 8, 8, 3)
        assert float(np.abs(got).max()) <= 2.0

    def test_multi_output_and_file(self, tmp_path):
        script = tmp_path / "split.jaxs"
        script.write_text(
            "y0 = x * 2.0\n"
            "y1 = jnp.sum(x, axis=(1, 2, 3), keepdims=False)\n"
        )
        outs = _run_collect(
            "videotestsrc num-buffers=2 width=8 height=8 ! "
            "tensor_converter ! tensor_transform mode=typecast "
            f"option=float32 ! tensor_filter framework=script "
            f"model={script} ! tensor_sink name=out to-host=true"
        )
        assert len(outs) == 2
        assert len(outs[0].tensors) == 2
        assert outs[0].tensors[1].shape == (1,)

    def test_shape_inference(self):
        f = get_subplugin(FILTER, "script")()
        f.open(FilterProperties(model="y = jnp.mean(x, axis=-1)"))
        out = f.set_input_info(TensorsInfo.from_str("4:8:8:1", "float32"))
        assert out[0].shape == (1, 8, 8)
        f.close()

    #: per-frame branch via the structured-ops surface — runs IDENTICALLY
    #: jitted (lax.cond) and interpreted (mode=host shim); the frame mean
    #: decides the branch, so different frames can take different arms
    BRANCH_SCRIPT = (
        "m = jnp.mean(x)\n"
        "y = cond(m > 0.5, lambda a: a * 2.0, lambda a: a * 0.5, x)\n"
    )

    def test_branch_script_identical_in_both_modes(self, tmp_path):
        """VERDICT r4 #8 done-criterion: a scripted filter with a
        per-frame data-dependent branch runs in BOTH modes with
        identical outputs (lua-parity semantics either way)."""
        script = tmp_path / "branch.jaxs"
        script.write_text(self.BRANCH_SCRIPT)
        results = {}
        for mode in ("", "custom=mode:host "):
            outs = _run_collect(
                "videotestsrc num-buffers=4 width=8 height=8 "
                "pattern=gradient ! tensor_converter ! "
                "tensor_transform mode=arithmetic "
                "option=typecast:float32,div:255.0 acceleration=false ! "
                f"tensor_filter framework=script model={script} {mode}! "
                "tensor_sink name=out to-host=true")
            results[mode or "device"] = [
                np.asarray(b.tensors[0]) for b in outs]
        assert len(results["device"]) == 4
        for dev, host in zip(results["device"],
                             results["custom=mode:host "]):
            np.testing.assert_allclose(dev, host, rtol=1e-6, atol=1e-7)

    def test_host_mode_arbitrary_imperative_control_flow(self):
        """mode=host is a true per-frame interpreter (reference lua
        semantics): raw Python if/while over concrete values — code that
        CANNOT trace under jit."""
        f = get_subplugin(FILTER, "script")()
        f.open(FilterProperties(
            model=(
                "total = float(np.sum(x))\n"
                "scale = 1.0\n"
                "while total * scale > 100.0:\n"
                "    scale *= 0.5\n"
                "if total < 0:\n"
                "    y = x * 0.0\n"
                "else:\n"
                "    y = x * scale\n"
            ),
            custom="mode:host"))
        info = f.set_input_info(TensorsInfo.from_str("4", "float32"))
        assert info[0].shape == (4,)
        big = np.full((4,), 100.0, np.float32)
        (out,) = f.invoke([big])
        assert float(np.sum(out)) <= 100.0
        small = np.ones((4,), np.float32)
        (out2,) = f.invoke([small])
        np.testing.assert_array_equal(out2, small)  # scale stayed 1.0
        f.close()

    def test_host_mode_structured_ops_shims(self):
        """while_loop/switch/select shims match lax semantics."""
        f = get_subplugin(FILTER, "script")()
        f.open(FilterProperties(
            model=(
                "v = while_loop(lambda v: np.sum(v) < 10.0,"
                " lambda v: v + 1.0, x)\n"
                "y0 = v\n"
                "y1 = switch(2, [lambda a: a, lambda a: a * 2,"
                " lambda a: a * 3], x)\n"
                "y2 = select(x > 1.0, x, -x)\n"
            ),
            custom="mode:host"))
        x = np.asarray([0.0, 2.0], np.float32)
        o = f.invoke([x])
        np.testing.assert_allclose(o[0], [4.0, 6.0])  # +1 until sum>=10
        np.testing.assert_allclose(o[1], [0.0, 6.0])  # branch 2: *3
        np.testing.assert_allclose(o[2], [-0.0, 2.0])
        f.close()
        # the SAME script, jitted: lax shims give the same answers
        g = get_subplugin(FILTER, "script")()
        g.open(FilterProperties(
            model=(
                "v = while_loop(lambda v: jnp.sum(v) < 10.0,"
                " lambda v: v + 1.0, x)\n"
                "y0 = v\n"
                "y1 = switch(2, [lambda a: a, lambda a: a * 2,"
                " lambda a: a * 3], x)\n"
                "y2 = select(x > 1.0, x, -x)\n"
            )))
        og = g.invoke([x])
        for a, b in zip(o, og):
            np.testing.assert_allclose(a, np.asarray(b))
        g.close()

    def test_host_mode_matches_device_dtypes(self):
        """numpy's 64-bit promotion is narrowed so both modes negotiate
        the SAME output dtypes (jnp.mean on u8 → f32 in both)."""
        info = TensorsInfo.from_str("4:4", "uint8")
        outs = {}
        for custom in (None, "mode:host"):
            f = get_subplugin(FILTER, "script")()
            f.open(FilterProperties(model="y = jnp.mean(x)",
                                    custom=custom))
            negotiated = f.set_input_info(info)
            (o,) = f.invoke([np.full((4, 4), 8, np.uint8)])
            outs[custom] = (negotiated[0].type, np.asarray(o))
            f.close()
        assert outs[None][0] == outs["mode:host"][0]  # same caps dtype
        assert outs["mode:host"][1].dtype == np.float32
        np.testing.assert_allclose(outs[None][1], outs["mode:host"][1])

    def test_host_mode_lax_spelling_works(self):
        """Device scripts written as lax.cond(...) run unchanged in
        mode=host (the shim namespace answers to both spellings)."""
        f = get_subplugin(FILTER, "script")()
        f.open(FilterProperties(
            model="y = lax.cond(np.mean(x) > 0.5,"
                  " lambda a: a * 2.0, lambda a: a * 0.5, x)",
            custom="mode:host"))
        (out,) = f.invoke([np.full((4,), 2.0, np.float32)])
        np.testing.assert_allclose(out, np.full((4,), 4.0))
        f.close()

    def test_host_mode_rejects_shape_drift(self):
        """A data-dependent output shape fails loudly at the filter, not
        downstream: host outputs are validated against negotiated caps."""
        f = get_subplugin(FILTER, "script")()
        f.open(FilterProperties(model="y = x[x > 0.0]",
                                custom="mode:host"))
        f.set_input_info(TensorsInfo.from_str("4", "float32"))  # ones probe
        with pytest.raises(ValueError, match="negotiated"):
            f.invoke([np.asarray([1.0, 0.0, 2.0, 0.0], np.float32)])
        f.close()

    def test_script_rejects_unknown_mode(self):
        f = get_subplugin(FILTER, "script")()
        with pytest.raises(ValueError, match="mode"):
            f.open(FilterProperties(model="y = x", custom="mode:gpu"))

    def test_bad_script_rejected(self):
        f = get_subplugin(FILTER, "script")()
        with pytest.raises(ValueError):
            f.open(FilterProperties(model="   "))
        f.open(FilterProperties(model="z = x"))  # no y assigned
        with pytest.raises(Exception):
            f.set_input_info(TensorsInfo.from_str("2:2", "float32"))


class TestPipelineFilter:
    def test_nested_pipeline(self):
        inner = (
            "appsrc name=in ! tensor_transform mode=arithmetic "
            "option=mul:3.0 ! tensor_sink name=out"
        )
        outs = _run_collect(
            "videotestsrc num-buffers=3 width=4 height=4 ! "
            "tensor_converter ! tensor_transform mode=typecast "
            f'option=float32 ! tensor_filter framework=pipeline '
            f'model="{inner}" ! tensor_sink name=out to-host=true'
        )
        assert len(outs) == 3

    def test_values_and_order(self):
        from nnstreamer_tpu.filters.pipeline_filter import PipelineFilter

        f = PipelineFilter()
        f.open(FilterProperties(
            model="appsrc name=in ! tensor_transform mode=arithmetic "
                  "option=add:1.0 ! tensor_sink name=out"))
        for i in range(5):
            x = np.full((2, 2), float(i), np.float32)
            (y,) = f.invoke([x])
            assert np.allclose(np.asarray(y), x + 1.0)
        f.close()

    def test_missing_ports_rejected(self):
        from nnstreamer_tpu.filters.pipeline_filter import PipelineFilter

        f = PipelineFilter()
        with pytest.raises(ValueError):
            f.open(FilterProperties(model="videotestsrc ! tensor_sink"))


class TestTransformersFilter:
    @pytest.fixture(scope="class")
    def bert_dir(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("tiny_bert")
        cfg = {
            "model_type": "bert",
            "architectures": ["BertModel"],
            "hidden_size": 32,
            "num_hidden_layers": 2,
            "num_attention_heads": 2,
            "intermediate_size": 64,
            "vocab_size": 128,
            "max_position_embeddings": 64,
            "type_vocab_size": 2,
        }
        (d / "config.json").write_text(json.dumps(cfg))
        return str(d)

    def test_flax_from_config(self, bert_dir):
        f = get_subplugin(FILTER, "transformers")()
        f.open(FilterProperties(model=bert_dir, custom="from_config:true"))
        out_info = f.set_input_info(TensorsInfo.from_str("16:2", "int32"))
        # last_hidden_state [2,16,32] + pooler [2,32]
        assert out_info[0].shape == (2, 16, 32)
        ids = np.ones((2, 16), np.int32)
        outs = f.invoke([ids])
        assert np.asarray(outs[0]).shape == (2, 16, 32)
        f.close()

    def test_in_pipeline(self, bert_dir):
        pipe = parse_launch(
            "appsrc name=src ! "
            "tensor_filter framework=transformers "
            f"model={bert_dir} custom=from_config:true ! "
            "tensor_sink name=out to-host=true"
        )
        src = pipe.get("src")
        sink = pipe.get("out")
        pipe.start()
        try:
            for _ in range(2):
                src.push([np.ones((1, 16), np.int32)])
            src.end_of_stream()
            msg = pipe.wait(timeout=120)
            assert msg is not None and msg.kind == "eos", msg
        finally:
            pipe.stop()
        assert len(sink.buffers) == 2
        assert np.asarray(sink.buffers[0].tensors[0]).shape == (1, 16, 32)

    def test_torch_backend(self, bert_dir):
        f = get_subplugin(FILTER, "transformers")()
        f.open(FilterProperties(
            model=bert_dir, custom="from_config:true,backend:torch"))
        ids = np.ones((1, 8), np.int64)
        outs = f.invoke([ids])
        assert outs[0].shape == (1, 8, 32)
        f.close()
