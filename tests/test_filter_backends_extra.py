"""Script, pipeline-as-filter, and transformers filter backends.

Reference parity: tensor_filter_lua.cc (script-defined filters),
tensor_filter_mediapipe.cc (sub-graph as a filter), and the heavyweight
framework subplugins (tensor_filter_tensorflow.cc / _pytorch.cc) whose
TPU-native peer loads HF-format checkpoints through Flax.
"""

import json

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.api import FilterProperties
from nnstreamer_tpu.registry import FILTER, get_subplugin
from nnstreamer_tpu.tensors.types import TensorsInfo


def _run_collect(desc, sink="out"):
    pipe = parse_launch(desc)
    outs = []
    pipe.get(sink).connect(lambda b: outs.append(b))
    pipe.run(timeout=120)
    return outs


class TestScriptFilter:
    def test_inline_expression(self):
        outs = _run_collect(
            "videotestsrc num-buffers=3 width=8 height=8 ! "
            "tensor_converter ! tensor_transform mode=typecast "
            "option=float32 ! "
            'tensor_filter framework=script model="y = jnp.tanh(x) * 2.0" ! '
            "tensor_sink name=out to-host=true"
        )
        assert len(outs) == 3
        got = np.asarray(outs[0].tensors[0])
        assert got.shape == (1, 8, 8, 3)
        assert float(np.abs(got).max()) <= 2.0

    def test_multi_output_and_file(self, tmp_path):
        script = tmp_path / "split.jaxs"
        script.write_text(
            "y0 = x * 2.0\n"
            "y1 = jnp.sum(x, axis=(1, 2, 3), keepdims=False)\n"
        )
        outs = _run_collect(
            "videotestsrc num-buffers=2 width=8 height=8 ! "
            "tensor_converter ! tensor_transform mode=typecast "
            f"option=float32 ! tensor_filter framework=script "
            f"model={script} ! tensor_sink name=out to-host=true"
        )
        assert len(outs) == 2
        assert len(outs[0].tensors) == 2
        assert outs[0].tensors[1].shape == (1,)

    def test_shape_inference(self):
        f = get_subplugin(FILTER, "script")()
        f.open(FilterProperties(model="y = jnp.mean(x, axis=-1)"))
        out = f.set_input_info(TensorsInfo.from_str("4:8:8:1", "float32"))
        assert out[0].shape == (1, 8, 8)
        f.close()

    def test_bad_script_rejected(self):
        f = get_subplugin(FILTER, "script")()
        with pytest.raises(ValueError):
            f.open(FilterProperties(model="   "))
        f.open(FilterProperties(model="z = x"))  # no y assigned
        with pytest.raises(Exception):
            f.set_input_info(TensorsInfo.from_str("2:2", "float32"))


class TestPipelineFilter:
    def test_nested_pipeline(self):
        inner = (
            "appsrc name=in ! tensor_transform mode=arithmetic "
            "option=mul:3.0 ! tensor_sink name=out"
        )
        outs = _run_collect(
            "videotestsrc num-buffers=3 width=4 height=4 ! "
            "tensor_converter ! tensor_transform mode=typecast "
            f'option=float32 ! tensor_filter framework=pipeline '
            f'model="{inner}" ! tensor_sink name=out to-host=true'
        )
        assert len(outs) == 3

    def test_values_and_order(self):
        from nnstreamer_tpu.filters.pipeline_filter import PipelineFilter

        f = PipelineFilter()
        f.open(FilterProperties(
            model="appsrc name=in ! tensor_transform mode=arithmetic "
                  "option=add:1.0 ! tensor_sink name=out"))
        for i in range(5):
            x = np.full((2, 2), float(i), np.float32)
            (y,) = f.invoke([x])
            assert np.allclose(np.asarray(y), x + 1.0)
        f.close()

    def test_missing_ports_rejected(self):
        from nnstreamer_tpu.filters.pipeline_filter import PipelineFilter

        f = PipelineFilter()
        with pytest.raises(ValueError):
            f.open(FilterProperties(model="videotestsrc ! tensor_sink"))


class TestTransformersFilter:
    @pytest.fixture(scope="class")
    def bert_dir(self, tmp_path_factory):
        d = tmp_path_factory.mktemp("tiny_bert")
        cfg = {
            "model_type": "bert",
            "architectures": ["BertModel"],
            "hidden_size": 32,
            "num_hidden_layers": 2,
            "num_attention_heads": 2,
            "intermediate_size": 64,
            "vocab_size": 128,
            "max_position_embeddings": 64,
            "type_vocab_size": 2,
        }
        (d / "config.json").write_text(json.dumps(cfg))
        return str(d)

    def test_flax_from_config(self, bert_dir):
        f = get_subplugin(FILTER, "transformers")()
        f.open(FilterProperties(model=bert_dir, custom="from_config:true"))
        out_info = f.set_input_info(TensorsInfo.from_str("16:2", "int32"))
        # last_hidden_state [2,16,32] + pooler [2,32]
        assert out_info[0].shape == (2, 16, 32)
        ids = np.ones((2, 16), np.int32)
        outs = f.invoke([ids])
        assert np.asarray(outs[0]).shape == (2, 16, 32)
        f.close()

    def test_in_pipeline(self, bert_dir):
        pipe = parse_launch(
            "appsrc name=src ! "
            "tensor_filter framework=transformers "
            f"model={bert_dir} custom=from_config:true ! "
            "tensor_sink name=out to-host=true"
        )
        src = pipe.get("src")
        sink = pipe.get("out")
        pipe.start()
        try:
            for _ in range(2):
                src.push([np.ones((1, 16), np.int32)])
            src.end_of_stream()
            msg = pipe.wait(timeout=120)
            assert msg is not None and msg.kind == "eos", msg
        finally:
            pipe.stop()
        assert len(sink.buffers) == 2
        assert np.asarray(sink.buffers[0].tensors[0]).shape == (1, 16, 32)

    def test_torch_backend(self, bert_dir):
        f = get_subplugin(FILTER, "transformers")()
        f.open(FilterProperties(
            model=bert_dir, custom="from_config:true,backend:torch"))
        ids = np.ones((1, 8), np.int64)
        outs = f.invoke([ids])
        assert outs[0].shape == (1, 8, 32)
        f.close()
