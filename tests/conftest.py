"""Test configuration: force CPU XLA with 8 virtual devices.

All tests run on CPU XLA (the reference's EdgeTPU `device_type:dummy`
pattern: the full framework is exercised with a software device,
tests/nnstreamer_filter_edgetpu/unittest_edgetpu.cc:30). Sharding tests get
an 8-device virtual mesh via --xla_force_host_platform_device_count.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# A TPU-tunnel sitecustomize (if present on this host) may override
# jax_platforms at interpreter boot; force the config back to CPU so tests
# never touch real accelerator tunnels.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)
