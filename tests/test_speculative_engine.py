"""Speculative decoding wired into the serving engine (speculate=K).

Drafts come from a shallow prefix slice of the target
(models/speculative.py); the target verifies every drafted position in
one chunk pass, so emitted tokens are exactly greedy-decode tokens —
speculation only changes how many positions a round advances, never the
values. That makes byte-parity with ``reference_greedy`` the whole
correctness story, in BOTH cache modes (monolithic and paged)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.serving import (  # noqa: E402
    ContinuousBatchingEngine,
    register_engine,
    unregister_engine,
)
from tests.test_serving import CFG, PARAMS, reference_greedy  # noqa: E402

PROMPTS = [[5, 11, 23, 42, 7], [4, 8, 15], [16, 23], [2, 2, 2, 2, 2]]


def spec_engine(**kw):
    kw.setdefault("max_streams", 2)
    kw.setdefault("steps_per_dispatch", 4)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("speculate", 2)
    return ContinuousBatchingEngine(CFG, PARAMS, **kw)


@pytest.mark.parametrize("block_tokens", [0, 8],
                         ids=["monolithic", "paged"])
def test_speculative_greedy_parity(block_tokens):
    eng = spec_engine(block_tokens=block_tokens).start()
    try:
        assert eng.paged == (block_tokens > 0)
        for p in PROMPTS:
            assert eng.generate(p, max_new_tokens=9, timeout=240) == \
                reference_greedy(p, 9), f"prompt={p}"
        streams = [eng.submit(p, max_new_tokens=9) for p in PROMPTS]
        conc = [s.result(timeout=240) for s in streams]
        assert eng.stats["spec_drafted"] > 0
        # at small scale the 1-layer draft tracks the 2-layer target
        # well; requiring SOME acceptance guards against a verifier
        # that silently rejects everything (== plain decode, hidden)
        assert eng.stats["spec_accepted"] > 0
    finally:
        eng.stop()
    for p, got in zip(PROMPTS, conc):
        assert got == reference_greedy(p, 9), f"prompt={p}"


def test_speculate_requires_greedy():
    with pytest.raises(ValueError, match="greedy"):
        spec_engine(temperature=0.8)


def test_set_speculate_guards():
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, temperature=0.0)
    with pytest.raises(ValueError):
        eng.set_speculate(-1)
    with pytest.raises(ValueError):
        eng.set_speculate(CFG.max_seq)
    eng.start()
    try:
        with pytest.raises(RuntimeError, match="stopped"):
            eng.set_speculate(3)
    finally:
        eng.stop()


def test_lm_serve_speculate_property_configures_engine():
    """tensor_lm_serve speculate=K reaches through to the engine at
    element start — the pipeline string is the opt-in surface."""
    engine = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0)
    register_engine("lm_spec", engine)
    server = parse_launch(
        "tensor_query_serversrc name=ssrc port=0 ! "
        "tensor_lm_serve engine=lm_spec max-new-tokens=4 "
        "speculate=2 speculate-layers=1 name=serve ! "
        "tensor_query_serversink")
    try:
        server.start()
        assert engine.speculate == 2
        assert engine._speculate_layers == 1
    finally:
        server.stop()
        unregister_engine("lm_spec")
