"""Resilient transport fabric tests — deadline propagation, idempotent
retry, circuit breaking and chaos coverage (query/resilience.py).

The loopback classes mirror test_query.py's in-process multi-node
pattern; the chaos classes drive the same split pipeline through the
deterministic fault injector and assert the exactly-once witnesses
(zero duplicate server invocations, byte-identical outputs)."""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.pipeline import faults as F
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.query import resilience as R
from nnstreamer_tpu.registry import ELEMENT, get_subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


# ---------------------------------------------------------------------------
# unit: primitives
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_deterministic_and_bounded(self):
        p1 = R.RetryPolicy(base_ms=50.0, key="k")
        p2 = R.RetryPolicy(base_ms=50.0, key="k")
        delays = [p1.delay(a) for a in range(1, 12)]
        assert delays == [p2.delay(a) for a in range(1, 12)]
        assert all(d <= R.BACKOFF_CAP_S for d in delays)
        # jitter stays within [0.5x, 1.0x] of the exponential ceiling
        assert 0.025 <= delays[0] <= 0.05

    def test_key_decorrelates(self):
        a = [R.RetryPolicy(key="a").delay(n) for n in range(1, 6)]
        b = [R.RetryPolicy(key="b").delay(n) for n in range(1, 6)]
        assert a != b

    def test_monotone_ceiling(self):
        p = R.RetryPolicy(base_ms=100.0, key="m")
        # ceilings double until the cap; jittered values never exceed it
        for attempt in range(1, 10):
            assert p.delay(attempt) <= min(
                0.1 * 2 ** (attempt - 1), R.BACKOFF_CAP_S)


class TestCircuitBreaker:
    def test_open_after_threshold_and_half_open_probe(self):
        br = R.CircuitBreaker(failures=3, reset_s=0.05, endpoint="t:1")
        assert br.state == R.CLOSED
        for _ in range(3):
            assert br.allow()
            br.record_failure()
        assert br.state == R.OPEN
        assert not br.allow()  # open: reject immediately
        time.sleep(0.06)
        assert br.allow()  # half-open probe admitted
        assert br.state == R.HALF_OPEN
        br.record_success()
        assert br.state == R.CLOSED

    def test_half_open_failure_reopens(self):
        br = R.CircuitBreaker(failures=1, reset_s=0.01, endpoint="t:2")
        br.record_failure()
        assert br.state == R.OPEN
        time.sleep(0.02)
        assert br.allow()
        br.record_failure()
        assert br.state == R.OPEN

    def test_transitions_witness(self):
        br = R.CircuitBreaker(failures=1, reset_s=0.01, endpoint="t:3")
        br.record_failure()
        time.sleep(0.02)
        br.allow()
        br.record_success()
        states = [s for _t, s in br.transitions]
        assert states == [R.OPEN, R.HALF_OPEN, R.CLOSED]


class TestDedupWindow:
    def test_new_pending_resolved_replay(self):
        w = R.DedupWindow(size=8)
        assert w.admit(1) is R.NEW
        assert w.admit(1) is R.PENDING  # in flight: duplicate dropped
        w.resolve(1, ("cmd", b"payload"))
        assert w.admit(1) == ("cmd", b"payload")  # replay, no re-invoke

    def test_forget_allows_reinvoke(self):
        w = R.DedupWindow(size=8)
        assert w.admit(5) is R.NEW
        w.forget(5)  # bad frame: admission rolled back
        assert w.admit(5) is R.NEW  # the intact resend invokes again

    def test_fifo_trim(self):
        w = R.DedupWindow(size=4)
        for i in range(10):
            w.admit(i)
        assert len(w) == 4

    def test_threaded_admits_single_new(self):
        w = R.DedupWindow(size=64)
        verdicts = []
        barrier = threading.Barrier(8)

        def racer():
            barrier.wait()
            verdicts.append(w.admit(42))

        threads = [threading.Thread(target=racer, daemon=True)
                   for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5)
        assert verdicts.count(R.NEW) == 1
        assert verdicts.count(R.PENDING) == 7


class TestEndpointStats:
    def test_cold_uses_floor(self):
        s = R.EndpointStats()
        assert s.hedge_timeout(0.25) == 0.25

    def test_p99_scaling(self):
        s = R.EndpointStats()
        for _ in range(100):
            s.observe(0.010)
        s.observe(0.200)  # one outlier
        p99 = s.p99()
        assert 0.010 <= p99 <= 0.200
        assert s.hedge_timeout(0.001) == pytest.approx(
            max(0.001, p99 * R.HEDGE_P99_FACTOR))
        assert 0.009 < s.ewma() < 0.05


class TestPendingEntry:
    def test_slack_no_deadline(self):
        e = R.PendingEntry(1, 0, {}, b"x")
        assert e.slack_s(time.monotonic()) == -1.0

    def test_slack_clamps_to_zero(self):
        now = time.monotonic()
        e = R.PendingEntry(1, 0, {}, b"x", deadline_t=now - 5.0)
        assert e.slack_s(now) == 0.0  # blown deadline → exactly 0
        e2 = R.PendingEntry(2, 0, {}, b"x", deadline_t=now + 2.0)
        assert 1.9 < e2.slack_s(now) <= 2.0


# ---------------------------------------------------------------------------
# unit: protocol extension
# ---------------------------------------------------------------------------

class TestExtendedProtocol:
    def test_ext_roundtrip(self):
        req_id, slack, body = P.unpack_ext(P.pack_ext(77, 1.5, b"abc"))
        assert (req_id, slack, body) == (77, 1.5, b"abc")

    def test_short_header_raises(self):
        with pytest.raises(P.QueryProtocolError):
            P.unpack_ext(b"\x00\x01")

    def test_classic_commands_unchanged(self):
        # the resilient extension appends commands; the classic ids the
        # native core speaks must never move
        assert [int(c) for c in (P.Cmd.REQUEST_INFO, P.Cmd.APPROVE,
                                 P.Cmd.DENY, P.Cmd.TRANSFER, P.Cmd.RESULT,
                                 P.Cmd.CLIENT_ID, P.Cmd.PING, P.Cmd.BYE)
                ] == [1, 2, 3, 4, 5, 6, 7, 8]
        assert int(P.Cmd.HELLO) == 9
        assert int(P.Cmd.TRANSFER_EX) == 10
        assert int(P.Cmd.RESULT_EX) == 11
        assert int(P.Cmd.EXPIRED) == 12


# ---------------------------------------------------------------------------
# unit: fault-injector transport sites
# ---------------------------------------------------------------------------

class TestTransportFaultSites:
    def test_new_sites_and_kinds_parse(self):
        rules = F.parse_faults(
            "query.send:rate=0.5,kind=drop;"
            "query.recv:kind=disconnect,nth=3;"
            "grpc.call:kind=corrupt,every=2;"
            "mqtt.publish:kind=drop,rate=0.1")
        assert {r.site for r in rules} == {
            "query.send", "query.recv", "grpc.call", "mqtt.publish"}

    def test_unknown_transport_kind_rejected(self):
        with pytest.raises(ValueError):
            F.parse_faults("query.send:kind=explode")

    def test_action_verdicts_deterministic(self):
        rules = F.parse_faults("query.send:rate=0.3,kind=drop")
        a = F.FaultInjector(rules, seed=9)
        b = F.FaultInjector(rules, seed=9)
        va = [a.action("query.send") for _ in range(200)]
        vb = [b.action("query.send") for _ in range(200)]
        assert va == vb
        assert "drop" in va and None in va

    def test_check_degrades_transport_kind_to_raise(self):
        rules = F.parse_faults("query.send:nth=1,kind=drop")
        fi = F.FaultInjector(rules, seed=0)
        with pytest.raises(F.InjectedFault):
            fi.check("query.send")  # a drop has no meaning mid-invoke

    def test_action_passes_raise_through(self):
        rules = F.parse_faults("grpc.call:nth=1,kind=raise")
        fi = F.FaultInjector(rules, seed=0)
        with pytest.raises(F.InjectedFault):
            fi.action("grpc.call")


# ---------------------------------------------------------------------------
# loopback: exactly-once offload
# ---------------------------------------------------------------------------

def _echo_server(reliable=True):
    """(serversrc element, worker stopper, invoke list): echoes each
    frame doubled, recording every net_req_id it actually invokes."""
    Src = get_subplugin(ELEMENT, "tensor_query_serversrc")
    src = Src(port=0, reliable=reliable)
    src.start()
    server = src.server
    stop = threading.Event()
    invokes = []

    def worker():
        while not stop.is_set():
            try:
                buf = server.incoming.get(timeout=0.2)
            except Exception:
                continue
            if buf is None:  # stop sentinel
                continue
            invokes.append(buf.meta.get("net_req_id"))
            out = TensorBuffer([t * 2 for t in buf.to_host().tensors],
                               pts=buf.pts)
            out.meta.update(buf.meta)
            server.send_result(buf.meta["query_client_id"], out)

    threading.Thread(target=worker, daemon=True).start()
    return src, stop, invokes


class TestReliableLoopback:
    def _run(self, n, client_props, fault_spec=None, seed=11):
        src, stop, invokes = _echo_server()
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(port=src.port, reliable=True, **client_props)
        outs = []
        cl.srcpad.push = lambda b: outs.append(b)
        old = F.ACTIVE
        if fault_spec:
            F.ACTIVE = F.FaultInjector(F.parse_faults(fault_spec),
                                       seed=seed)
        try:
            for i in range(n):
                cl.chain(cl.sinkpad, TensorBuffer(
                    [np.full((4,), i, dtype=np.float32)], pts=i))
            cl.handle_eos()
        finally:
            F.ACTIVE = old
            stop.set()
            server = src.server  # src.stop() nulls the handle
            cl.stop()
            src.stop()
        return outs, invokes, server

    def test_clean_run_exactly_once(self):
        outs, invokes, server = self._run(
            30, dict(max_in_flight=4, timeout=5.0))
        assert len(outs) == 30
        assert sorted(int(o.to_host().tensors[0][0]) for o in outs) == \
            [2 * i for i in range(30)]
        assert len(invokes) == 30 and len(set(invokes)) == 30

    def test_chaos_zero_loss_zero_double_invoke(self):
        """The acceptance witness: under disconnect+drop chaos every
        frame arrives byte-identical, the server invoked each request
        exactly once, and the dedup window absorbed the resends."""
        outs, invokes, server = self._run(
            120,
            dict(max_in_flight=4, timeout=0.5, max_retry=8,
                 reconnect_backoff_ms=10.0),
            fault_spec="query.send:rate=0.05,kind=disconnect;"
                       "query.recv:rate=0.05,kind=drop")
        assert len(outs) == 120  # zero loss
        assert sorted(int(o.to_host().tensors[0][0]) for o in outs) == \
            [2 * i for i in range(120)]  # byte-identical values
        assert len(invokes) - len(set(invokes)) == 0  # no double invoke
        assert server.dedup_hits > 0  # dedup actually exercised

    def test_corrupt_frames_recover_via_forget(self):
        outs, invokes, server = self._run(
            40,
            dict(max_in_flight=2, timeout=0.5, max_retry=8,
                 reconnect_backoff_ms=10.0),
            fault_spec="query.send:rate=0.1,kind=corrupt")
        assert len(outs) == 40
        assert len(invokes) - len(set(invokes)) == 0

    def test_reliable_requires_reliable_server(self):
        # classic server never echoes HELLO → a clear, early error
        Src = get_subplugin(ELEMENT, "tensor_query_serversrc")
        src = Src(port=0)  # classic
        src.start()
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(port=src.port, reliable=True, timeout=0.5, max_retry=1)
        try:
            with pytest.raises(P.QueryProtocolError):
                cl.chain(cl.sinkpad, TensorBuffer(
                    [np.zeros(2, np.float32)], pts=0))
        finally:
            cl.stop()
            src.stop()

    def test_frames_expired_is_read_only(self):
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client()
        with pytest.raises(ValueError):
            cl.set_property("frames_expired", 7)


class TestDeadlinePropagation:
    def test_blown_deadline_expires_remotely(self):
        src, stop, invokes = _echo_server()
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(port=src.port, reliable=True, propagate_deadline=True,
                    timeout=5.0)
        outs = []
        cl.srcpad.push = lambda b: outs.append(b)
        try:
            now = time.monotonic()
            live = TensorBuffer([np.ones(4, np.float32)], pts=0)
            live.meta["deadline_t"] = now + 10.0
            blown = TensorBuffer([np.ones(4, np.float32)], pts=1)
            blown.meta["deadline_t"] = now - 1.0
            cl.chain(cl.sinkpad, live)
            cl.chain(cl.sinkpad, blown)
            cl.handle_eos()
            assert len(outs) == 1  # only the live frame came back
            assert len(invokes) == 1  # the blown one never invoked
            assert src.server.remote_expired == 1
            assert cl.get_property("frames_expired") == 1
        finally:
            stop.set()
            cl.stop()
            src.stop()

    def test_no_deadline_means_negative_slack_on_wire(self):
        e = R.PendingEntry(1, 0, {}, b"")
        payload = P.pack_ext(e.req_id, e.slack_s(time.monotonic()), b"")
        _rid, slack, _b = P.unpack_ext(payload)
        assert slack < 0  # "no deadline", never "expired"

    def test_scheduler_shed_notifies_origin(self):
        src, stop, _invokes = _echo_server()
        try:
            server = src.server
            buf = TensorBuffer([np.ones(2, np.float32)], pts=0)
            buf.meta["_net_expire"] = (server, "nobody", 123)
            R.note_remote_shed(buf)  # unknown instance: counted, no send
            assert server.remote_expired == 1
        finally:
            stop.set()
            src.stop()


# ---------------------------------------------------------------------------
# byte-identity: knobs unset
# ---------------------------------------------------------------------------

class TestClassicByteIdentity:
    def test_classic_wire_bytes_unchanged(self):
        """With no resilience knobs, the client's TRANSFER payload is
        byte-for-byte the classic pack_buffer framing."""
        sent = []

        class FakeSock:
            def sendall(self, data):
                sent.append(bytes(data))

            def gettimeout(self):
                return 1.0

        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client()
        cl._sock = FakeSock()
        buf = TensorBuffer([np.arange(6, dtype=np.float32)], pts=9)
        cl._send_buf(buf)
        wire = b"".join(sent)
        hdr = P._HDR.pack(P._MAGIC, int(P.Cmd.TRANSFER),
                          len(P.pack_buffer(buf)))
        assert wire == hdr + P.pack_buffer(buf)

    def test_classic_loopback_still_lossless(self):
        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("3:8:8:1", "uint8")
        register_custom_easy(
            "double_u8_res",
            lambda ins: [(np.asarray(ins[0]) * 2).astype(np.uint8)],
            info, info,
        )
        server = parse_launch(
            "tensor_query_serversrc name=ssrc port=0 ! "
            "tensor_filter framework=custom-easy model=double_u8_res ! "
            "tensor_query_serversink")
        server.start()
        try:
            port = server.get("ssrc").port
            client = parse_launch(
                "videotestsrc num-buffers=4 width=8 height=8 "
                "pattern=gradient ! tensor_converter ! "
                f"tensor_query_client dest-host=127.0.0.1 "
                f"dest-port={port} ! tensor_sink name=out")
            msg = client.run(timeout=30)
            assert msg.kind == "eos"
            assert len(client.get("out").buffers) == 4
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# grpc: explicit close lifecycle (satellite)
# ---------------------------------------------------------------------------

class TestGrpcClientLifecycle:
    def test_close_idempotent_and_context_manager(self):
        pytest.importorskip("grpc")
        from nnstreamer_tpu.query.grpc_bridge import (
            TensorServiceClient,
            TensorServiceServer,
        )

        svc = TensorServiceServer(port=0).start()
        try:
            with TensorServiceClient(port=svc.port) as client:
                client.wait_ready(timeout=10)
            client.close()  # second close: no raise
            client.close()
            assert not hasattr(client, "__del__")
        finally:
            svc.stop()

    def test_grpc_call_fault_raises_connection_error(self):
        pytest.importorskip("grpc")
        from nnstreamer_tpu.query.grpc_bridge import (
            TensorServiceClient,
            TensorServiceServer,
        )

        svc = TensorServiceServer(port=0).start()
        old = F.ACTIVE
        F.ACTIVE = F.FaultInjector(
            F.parse_faults("grpc.call:nth=1,kind=disconnect"), seed=0)
        try:
            client = TensorServiceClient(port=svc.port)
            with pytest.raises(ConnectionError):
                client.send_stream(iter([]))
            client.close()
        finally:
            F.ACTIVE = old
            svc.stop()


# ---------------------------------------------------------------------------
# discovery under broker flap (satellite)
# ---------------------------------------------------------------------------

class TestDiscoveryFlap:
    def test_retract_mid_wait_then_readvertise(self):
        from nnstreamer_tpu.query.discovery import (
            ServerAdvertiser,
            ServerDiscovery,
        )
        from nnstreamer_tpu.query.pubsub import Broker

        broker = Broker(port=0).start()
        try:
            ad = ServerAdvertiser("127.0.0.1", broker.port, "op-flap",
                                  "10.0.0.1", 5001)
            ad.publish()
            disco = ServerDiscovery("127.0.0.1", broker.port, "op-flap")
            assert disco.wait_servers(timeout=5) == [("10.0.0.1", 5001)]
            # flap: retract, confirm gone, re-advertise, confirm back
            ad2 = ServerAdvertiser("127.0.0.1", broker.port, "op-flap",
                                   "10.0.0.1", 5001)
            ad.retract()
            deadline = time.monotonic() + 5
            while disco.wait_servers(timeout=0.2, settle=0) and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            assert disco.wait_servers(timeout=0.2, settle=0) == []
            ad2.publish()
            deadline = time.monotonic() + 5
            while not disco.wait_servers(timeout=0.2, settle=0) and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            assert disco.wait_servers(timeout=1) == [("10.0.0.1", 5001)]
            disco.close()
            ad2.retract()
        finally:
            broker.stop()

    def test_stale_ads_expire(self):
        import json

        from nnstreamer_tpu.query.discovery import (
            TOPIC_PREFIX,
            ServerDiscovery,
        )
        from nnstreamer_tpu.query.pubsub import Broker, Client

        broker = Broker(port=0).start()
        try:
            pub = Client("127.0.0.1", broker.port)
            wall_old = time.time() - 3600  # an hour-old ad
            pub.publish(
                f"{TOPIC_PREFIX}op-stale/10.0.0.9:9000",
                json.dumps({"host": "10.0.0.9", "port": 9000,
                            "ts": wall_old}).encode(),
                retain=True)
            pub.publish(
                f"{TOPIC_PREFIX}op-stale/10.0.0.8:8000",
                json.dumps({"host": "10.0.0.8", "port": 8000,
                            "ts": time.time()}).encode(),
                retain=True)
            disco = ServerDiscovery("127.0.0.1", broker.port, "op-stale",
                                    stale_s=60.0)
            assert disco.wait_servers(timeout=5) == [("10.0.0.8", 8000)]
            disco.close()
            # default (stale_s=None) keeps the classic trust-the-broker
            # behavior: both ads count
            disco2 = ServerDiscovery("127.0.0.1", broker.port, "op-stale")
            assert sorted(disco2.wait_servers(timeout=5)) == [
                ("10.0.0.8", 8000), ("10.0.0.9", 9000)]
            disco2.close()
            pub.close()
        finally:
            broker.stop()

    def test_ad_without_ts_survives_stale_filter(self):
        import json

        from nnstreamer_tpu.query.discovery import (
            TOPIC_PREFIX,
            ServerDiscovery,
        )
        from nnstreamer_tpu.query.pubsub import Broker, Client

        broker = Broker(port=0).start()
        try:
            pub = Client("127.0.0.1", broker.port)
            pub.publish(
                f"{TOPIC_PREFIX}op-nots/10.0.0.7:7000",
                json.dumps({"host": "10.0.0.7", "port": 7000}).encode(),
                retain=True)
            disco = ServerDiscovery("127.0.0.1", broker.port, "op-nots",
                                    stale_s=1.0)
            assert disco.wait_servers(timeout=5) == [("10.0.0.7", 7000)]
            disco.close()
            pub.close()
        finally:
            broker.stop()


# ---------------------------------------------------------------------------
# metrics wiring
# ---------------------------------------------------------------------------

class TestResilienceMetrics:
    def test_metric_names(self):
        m = R.metrics()
        assert set(m) == {"retries", "hedges", "dedup_hits",
                          "expired_remote"}
        g = R.breaker_gauge("h:1")
        assert g is R.breaker_gauge("h:1")  # cached per endpoint
