"""Latency-budget adaptive batching — ``tensor_aggregator
latency-budget-ms`` (round-5 VERDICT #1).

A micro-batched stream trades per-frame latency for throughput: with
batch=8 at 30 fps, a frame's p50 latency IS the batch window (~264 ms
measured in BENCH_r04). Budget mode bounds the admission wait: a window
holding frames past the budget flushes early, padded to the compiled
batch shape (meta["valid_frames"]), and the sink trims the padding.
The reference's per-frame path (tensor_filter.c:349-423) has no window
wait at all — this is the TPU-batched design matching its latency
semantics without giving up the batched MXU dispatch.
"""

import time

import numpy as np
import pytest

from nnstreamer_tpu.elements.aggregator import TensorAggregator
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def _wire(budget_ms, fout=4, fd=1):
    agg = TensorAggregator("agg")
    agg.set_property("frames_in", 1)
    agg.set_property("frames_out", fout)
    agg.set_property("frames_flush", fout)
    agg.set_property("frames_dim", fd)  # unit [1,4] → concat axis 0
    agg.set_property("concat", True)
    agg.set_property("latency_budget_ms", budget_ms)
    sink = TensorSink("out")
    agg.srcpad.link(sink.sinkpad)
    return agg, sink


def _frame(i):
    return np.full((1, 4), float(i), np.float32)


class TestPartialFlush:
    def test_watchdog_flushes_stalled_window(self):
        """Frames short of a full window flush within ~budget once the
        upstream stalls — the flusher thread, not an arrival, triggers."""
        agg, sink = _wire(budget_ms=30)
        agg.start()
        try:
            t0 = time.monotonic()
            agg.chain(agg.sinkpad, TensorBuffer(
                [_frame(0)], pts=0, meta={"create_t": t0}))
            agg.chain(agg.sinkpad, TensorBuffer(
                [_frame(1)], pts=1, meta={"create_t": t0}))
            deadline = time.monotonic() + 2.0
            while not sink.buffers and time.monotonic() < deadline:
                time.sleep(0.005)
            assert len(sink.buffers) == 1
            waited = time.monotonic() - t0
            assert waited < 0.5  # flushed by budget, not by this test's poll
            out = sink.buffers[0]
            # sink trimmed the repeat-last padding to the 2 valid frames
            assert out.tensors[0].shape == (2, 4)
            np.testing.assert_array_equal(
                out.tensors[0], np.vstack([_frame(0), _frame(1)]))
            assert out.meta["valid_frames"] == 2
            assert len(out.meta["create_ts"]) == 2
            # only the real frames got latency stamps
            assert len(sink.latencies) == 2
        finally:
            agg.stop()

    def test_unstamped_frames_use_arrival_clock(self):
        agg, sink = _wire(budget_ms=25)
        agg.start()
        try:
            agg.chain(agg.sinkpad, TensorBuffer([_frame(7)], pts=0))
            deadline = time.monotonic() + 2.0
            while not sink.buffers and time.monotonic() < deadline:
                time.sleep(0.005)
            assert len(sink.buffers) == 1
            assert sink.buffers[0].meta["valid_frames"] == 1
            assert sink.buffers[0].tensors[0].shape == (1, 4)
        finally:
            agg.stop()

    def test_saturated_stream_never_pads(self):
        """Back-to-back arrivals fill windows faster than any budget: the
        throughput path emits only full, unpadded windows."""
        agg, sink = _wire(budget_ms=50)
        agg.start()
        try:
            for i in range(8):
                agg.chain(agg.sinkpad, TensorBuffer([_frame(i)], pts=i))
            assert len(sink.buffers) == 2
            for out in sink.buffers:
                assert "valid_frames" not in out.meta
                assert out.tensors[0].shape == (4, 4)
            got = np.vstack([b.tensors[0] for b in sink.buffers])
            np.testing.assert_array_equal(
                got, np.vstack([_frame(i) for i in range(8)]))
        finally:
            agg.stop()

    def test_eos_flushes_partial_tail(self):
        """Budget mode promises every frame a bounded exit: the tail
        short of a window flushes at EOS instead of being dropped."""
        from nnstreamer_tpu.pipeline.element import EosEvent

        agg, sink = _wire(budget_ms=10_000)  # budget never fires
        for i in range(3):
            agg.chain(agg.sinkpad, TensorBuffer([_frame(i)], pts=i))
        assert not sink.buffers
        agg.sinkpad.eos = True
        agg.sink_event(agg.sinkpad, EosEvent())
        assert len(sink.buffers) == 1
        assert sink.buffers[0].meta["valid_frames"] == 3
        assert sink.buffers[0].tensors[0].shape == (3, 4)
        assert sink.eos

    def test_concat_false_partial_emits_unpadded(self):
        """concat=false has no single padded tensor to trim: the budget
        flush emits the k real unit tensors, no padding, no
        valid_frames meta."""
        from nnstreamer_tpu.pipeline.element import EosEvent

        agg, sink = _wire(budget_ms=10_000)
        agg.set_property("concat", False)
        for i in range(2):
            agg.chain(agg.sinkpad, TensorBuffer([_frame(i)], pts=i))
        agg.sinkpad.eos = True
        agg.sink_event(agg.sinkpad, EosEvent())
        assert len(sink.buffers) == 1
        out = sink.buffers[0]
        assert "valid_frames" not in out.meta
        assert len(out.tensors) == 2  # the 2 real frames, nothing extra
        np.testing.assert_array_equal(out.tensors[0], _frame(0))
        np.testing.assert_array_equal(out.tensors[1], _frame(1))

    def test_non_leading_axis_partial_emits_unpadded(self):
        """frames_dim that concatenates along a NON-leading axis (e.g.
        audio windows) cannot use the sink's axis-0 trim: the budget
        flush emits the shorter window unpadded, every sample real."""
        from nnstreamer_tpu.pipeline.element import EosEvent

        agg, sink = _wire(budget_ms=10_000, fd=0)  # [1,4] → concat axis 1
        for i in range(2):
            agg.chain(agg.sinkpad, TensorBuffer([_frame(i)], pts=i))
        agg.sinkpad.eos = True
        agg.sink_event(agg.sinkpad, EosEvent())
        assert len(sink.buffers) == 1
        out = sink.buffers[0]
        assert "valid_frames" not in out.meta
        assert out.tensors[0].shape == (1, 8)  # 2 windows of 4, no pad
        np.testing.assert_array_equal(
            out.tensors[0], np.hstack([_frame(0), _frame(1)]))

    def test_budget_off_keeps_reference_semantics(self):
        """Without a budget the partial tail stays queued (reference
        tensor_aggregator drops incomplete windows at EOS)."""
        from nnstreamer_tpu.pipeline.element import EosEvent

        agg, sink = _wire(budget_ms=0)
        for i in range(3):
            agg.chain(agg.sinkpad, TensorBuffer([_frame(i)], pts=i))
        agg.sinkpad.eos = True
        agg.sink_event(agg.sinkpad, EosEvent())
        assert not sink.buffers


class TestPipelineExactness:
    """Partial-vs-full-batch results are token-exact through a real
    jitted filter: padding rows never change the valid rows' outputs."""

    @pytest.fixture
    def rowsum_model(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model,
            unregister_jax_model,
        )

        def fn(p, x):  # [4, 8] → per-row checksum [4]
            return (jnp.sum(x * p, axis=1),)

        register_jax_model(
            "lat_budget_rowsum", fn,
            np.arange(8, dtype=np.float32) + 1.0)
        yield "lat_budget_rowsum"
        unregister_jax_model("lat_budget_rowsum")

    def _run(self, rowsum_model, frames, paced_ms):
        from nnstreamer_tpu import parse_launch

        pipe = parse_launch(
            "appsrc name=src ! "
            "tensor_aggregator frames-in=1 frames-out=4 frames-flush=4 "
            "frames-dim=1 concat=true latency-budget-ms=25 ! "
            f"tensor_filter framework=jax model={rowsum_model} ! "
            "tensor_sink name=sink")
        src, sink = pipe.get("src"), pipe.get("sink")
        pipe.start()
        try:
            for f in frames:
                src.push([f])
                if paced_ms:
                    time.sleep(paced_ms / 1e3)
            src.end_of_stream()
            msg = pipe.wait(timeout=60)
            assert msg is not None and msg.kind == "eos", msg
            return [np.asarray(b.tensors[0]) for b in sink.buffers]
        finally:
            pipe.stop()

    def test_paced_partial_equals_full_batch_math(self, rowsum_model):
        rng = np.random.default_rng(0)
        frames = [rng.standard_normal((1, 8)).astype(np.float32)
                  for _ in range(6)]
        # paced slower than the budget → partial (padded) dispatches
        outs = self._run(rowsum_model, frames, paced_ms=45)
        got = np.concatenate([o.reshape(-1) for o in outs])
        assert got.shape == (6,)  # every frame exited, no padding leaked
        want = np.concatenate(
            [f @ (np.arange(8, dtype=np.float32) + 1.0) for f in frames])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
        # and at least one dispatch really was partial
        assert len(outs) > 2

    def test_pad_device_partial_equals_host_pad(self, rowsum_model):
        """pad-device defers the zero-pad to the staging queue's
        prefetch: only k real frames cross H2D, the filter still sees
        the full-window shape, and results match the host-pad path."""
        from nnstreamer_tpu import parse_launch

        rng = np.random.default_rng(2)
        frames = [rng.standard_normal((1, 8)).astype(np.float32)
                  for _ in range(6)]
        pipe = parse_launch(
            "appsrc name=src ! "
            "tensor_aggregator frames-in=1 frames-out=4 frames-flush=4 "
            "frames-dim=1 concat=true latency-budget-ms=25 "
            "pad-device=true ! "
            "queue max-size-buffers=4 prefetch-device=true ! "
            f"tensor_filter framework=jax model={rowsum_model} ! "
            "tensor_sink name=sink")
        src, sink = pipe.get("src"), pipe.get("sink")
        pipe.start()
        try:
            # first window full (announces caps), then paced partials
            for f in frames[:4]:
                src.push([f])
            time.sleep(0.2)
            for f in frames[4:]:
                src.push([f])
                time.sleep(0.045)
            src.end_of_stream()
            msg = pipe.wait(timeout=60)
            assert msg is not None and msg.kind == "eos", msg
        finally:
            pipe.stop()
        got = np.concatenate(
            [np.asarray(b.tensors[0]).reshape(-1) for b in sink.buffers])
        assert got.shape == (6,)
        want = np.concatenate(
            [f @ (np.arange(8, dtype=np.float32) + 1.0) for f in frames])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
        # the partial really deferred its pad (k frames < window)
        assert any(b.meta.get("valid_frames") for b in sink.buffers)

    def test_burst_full_batches_unaffected(self, rowsum_model):
        rng = np.random.default_rng(1)
        frames = [rng.standard_normal((1, 8)).astype(np.float32)
                  for _ in range(8)]
        outs = self._run(rowsum_model, frames, paced_ms=0)
        got = np.concatenate([o.reshape(-1) for o in outs])
        want = np.concatenate(
            [f @ (np.arange(8, dtype=np.float32) + 1.0) for f in frames])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
