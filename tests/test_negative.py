"""Negative cases — the reference's ``*_n`` test pattern (SURVEY §5:
"negative unit tests"): invalid properties, bad options, unknown
subplugins, malformed wire data. Errors must be typed, descriptive, and
must not wedge pipelines or servers."""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.pipeline.element import FlowError


def _run(desc):
    pipe = parse_launch(desc)
    msg = pipe.run(timeout=30)
    return pipe, msg


class TestParseErrors:
    def test_unknown_element(self):
        with pytest.raises(ValueError, match="bogus_element"):
            parse_launch("bogus_element ! tensor_sink")

    def test_unknown_property_lists_valid_ones(self):
        with pytest.raises(KeyError, match="has:"):
            parse_launch("videotestsrc nonexist=1 ! tensor_sink")


class TestFilterErrors:
    def test_unknown_framework(self):
        with pytest.raises(ValueError, match="no filter backend"):
            _run("videotestsrc num-buffers=1 ! tensor_converter ! "
                 "tensor_filter framework=nope model=x ! tensor_sink")

    def test_unknown_jax_model(self):
        with pytest.raises(ValueError, match="cannot load model"):
            _run("videotestsrc num-buffers=1 ! tensor_converter ! "
                 "tensor_filter framework=jax model=missing ! tensor_sink")

    def test_filter_without_model(self):
        with pytest.raises((ValueError, FlowError)):
            _run("videotestsrc num-buffers=1 ! tensor_converter ! "
                 "tensor_filter framework=jax ! tensor_sink")

    def test_custom_unknown_name(self):
        with pytest.raises((ValueError, FlowError), match="custom"):
            _run("videotestsrc num-buffers=1 ! tensor_converter ! "
                 "tensor_filter framework=custom model=nope ! tensor_sink")


class TestTransformDecoderErrors:
    def test_bad_transform_mode(self):
        with pytest.raises(FlowError, match="unknown transform mode"):
            _run("videotestsrc num-buffers=1 ! tensor_converter ! "
                 "tensor_transform mode=wat option=1 ! tensor_sink")

    def test_bad_arithmetic_op(self):
        with pytest.raises(FlowError, match="unknown arithmetic op"):
            _run("videotestsrc num-buffers=1 ! tensor_converter ! "
                 "tensor_transform mode=arithmetic option=frobnicate:2 ! "
                 "tensor_sink")

    def test_unknown_decoder_mode(self):
        with pytest.raises(FlowError, match="no decoder subplugin"):
            _run("videotestsrc num-buffers=1 ! tensor_converter ! "
                 "tensor_decoder mode=nope ! tensor_sink")

    def test_bounding_boxes_unknown_submode(self):
        from nnstreamer_tpu.decoders.bounding_boxes import BoundingBoxes
        from nnstreamer_tpu.tensors.buffer import TensorBuffer

        dec = BoundingBoxes()
        with pytest.raises(ValueError, match="unknown mode"):
            dec.decode(TensorBuffer([np.zeros((4, 4), np.float32)]),
                       None, {"option1": "wat"})


class TestTypeErrors:
    def test_bad_dim_string(self):
        from nnstreamer_tpu.tensors.types import TensorsInfo

        with pytest.raises(ValueError):
            TensorsInfo.from_str("x:y", "uint8")

    def test_bad_type_string(self):
        from nnstreamer_tpu.tensors.types import TensorsInfo

        with pytest.raises(ValueError, match="uint99"):
            TensorsInfo.from_str("4", "uint99")

    def test_too_many_tensors(self):
        from nnstreamer_tpu.tensors.buffer import TensorBuffer
        from nnstreamer_tpu.tensors.types import NNS_TENSOR_SIZE_LIMIT

        with pytest.raises(ValueError, match="exceeds"):
            TensorBuffer([np.zeros(1)] * (NNS_TENSOR_SIZE_LIMIT + 1))


class TestRegistryErrors:
    def test_unknown_subplugin_returns_none(self):
        from nnstreamer_tpu.registry import get_subplugin

        assert get_subplugin("filter", "zzz_not_there") is None

    def test_unregister_missing_returns_false(self):
        from nnstreamer_tpu.registry import unregister_subplugin

        assert unregister_subplugin("filter", "zzz_not_there") is False


class TestProtocolRobustness:
    def test_server_survives_garbage_connection(self):
        """Garbage bytes on the query port must not kill the server; a
        well-behaved client connecting afterwards still works."""
        import socket

        from nnstreamer_tpu.filters import register_custom_easy
        from nnstreamer_tpu.tensors.types import TensorsInfo

        info = TensorsInfo.from_str("3:8:8:1", "uint8")
        register_custom_easy("passthrough_n",
                             lambda ins: [np.asarray(ins[0])], info, info)
        server = parse_launch(
            "tensor_query_serversrc name=ssrc port=0 ! "
            "tensor_filter framework=custom-easy model=passthrough_n ! "
            "tensor_query_serversink")
        server.start()
        try:
            port = server.get("ssrc").port
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            s.sendall(b"\xde\xad\xbe\xef" * 64)
            s.close()

            client = parse_launch(
                "videotestsrc num-buffers=2 width=8 height=8 ! "
                "tensor_converter ! "
                f"tensor_query_client dest-host=127.0.0.1 dest-port={port} ! "
                "tensor_sink name=out")
            msg = client.run(timeout=30)
            assert msg is not None and msg.kind == "eos", msg
            assert len(client.get("out").buffers) == 2
        finally:
            server.stop()
            from nnstreamer_tpu.filters.custom import unregister_custom_easy
            unregister_custom_easy("passthrough_n")

    def test_sparse_decode_garbage(self):
        from nnstreamer_tpu.elements.sparse import sparse_decode

        with pytest.raises((ValueError, IndexError)):
            sparse_decode(b"\x01\x02\x03")
