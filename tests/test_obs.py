"""Tests for the obs subsystem: registry primitives, Prometheus/JSON
exporters, queue-drop accounting, and property/exporter agreement.

Pipelines here use unique names — registry metric identity is
(name, labels) process-wide, so a shared pipeline/element name would
accumulate counts across tests.
"""

import json
import logging
import threading
import urllib.request

import pytest

from nnstreamer_tpu.obs import (
    LATENCY_BUCKETS_S,
    MetricsRegistry,
    MetricsServer,
    get_registry,
)
from nnstreamer_tpu.pipeline.element import Element, EosEvent, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import Pipeline, Queue, SourceElement
from nnstreamer_tpu.tensors.buffer import TensorBuffer

import numpy as np


class TestCounter:
    def test_inc_and_value(self):
        reg = MetricsRegistry()
        c = reg.counter("t_total", "help", a="1")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5

    def test_negative_inc_rejected(self):
        c = MetricsRegistry().counter("t_total")
        with pytest.raises(ValueError, match="only go up"):
            c.inc(-1)

    def test_set_total_monotonic(self):
        c = MetricsRegistry().counter("t_total")
        c.set_total(10)
        c.set_total(4)  # stale external read must not regress the counter
        assert c.value == 10


class TestGauge:
    def test_set_inc_dec(self):
        g = MetricsRegistry().gauge("t_g")
        g.set(5)
        g.inc(2)
        g.dec()
        assert g.value == 6

    def test_callback_gauge(self):
        reg = MetricsRegistry()
        g = reg.gauge("t_g", fn=lambda: 42.0)
        assert g.value == 42.0

    def test_broken_callback_reads_zero(self):
        g = MetricsRegistry().gauge("t_g", fn=lambda: 1 / 0)
        assert g.value == 0.0


class TestHistogram:
    def test_cumulative_buckets(self):
        h = MetricsRegistry().histogram("t_h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.5, 3.0, 100.0):
            h.observe(v)
        assert h.bucket_counts() == [
            (1.0, 1), (2.0, 3), (4.0, 4), (float("inf"), 5)]
        assert h.count == 5
        assert h.sum == pytest.approx(106.5)

    def test_boundary_value_lands_in_its_bucket(self):
        # le semantics: an observation equal to a bound counts under it
        h = MetricsRegistry().histogram("t_h", buckets=(1.0, 2.0))
        h.observe(1.0)
        assert h.bucket_counts()[0] == (1.0, 1)

    def test_percentile_interpolates(self):
        h = MetricsRegistry().histogram("t_h", buckets=(1.0, 2.0, 4.0))
        for _ in range(100):
            h.observe(1.5)  # all mass in the (1, 2] bucket
        # rank interpolates linearly inside the winning bucket
        assert h.percentile(50) == pytest.approx(1.5)
        assert h.percentile(100) == pytest.approx(2.0)

    def test_percentile_empty_is_none(self):
        assert MetricsRegistry().histogram("t_h").percentile(99) is None

    def test_percentile_inf_tail_is_last_bound(self):
        h = MetricsRegistry().histogram("t_h", buckets=(1.0, 2.0))
        h.observe(50.0)
        assert h.percentile(99) == 2.0

    def test_default_buckets_span_latency_range(self):
        assert LATENCY_BUCKETS_S[0] == pytest.approx(100e-6)
        assert LATENCY_BUCKETS_S[-1] == 10.0


class TestRegistry:
    def test_get_or_create_identity(self):
        reg = MetricsRegistry()
        a = reg.counter("t_total", pipeline="p", element="e")
        b = reg.counter("t_total", element="e", pipeline="p")  # order-free
        assert a is b
        assert reg.counter("t_total", pipeline="p", element="x") is not a

    def test_kind_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("t_metric", a="1")
        with pytest.raises(ValueError, match="already"):
            reg.gauge("t_metric", a="1")
        with pytest.raises(ValueError, match="already used"):
            reg.gauge("t_metric", a="2")  # same name, other labels

    def test_get_returns_none_when_absent(self):
        assert MetricsRegistry().get("nope", a="1") is None

    def test_collector_false_unregisters(self):
        reg = MetricsRegistry()
        calls = []
        reg.register_collector(lambda: calls.append(1) or False)
        reg.collect()
        reg.collect()
        assert len(calls) == 1

    def test_collector_exception_unregisters(self):
        reg = MetricsRegistry()
        reg.register_collector(lambda: 1 / 0)
        reg.collect()  # must not raise
        assert reg._collectors == []

    def test_render_prometheus_format(self):
        reg = MetricsRegistry()
        reg.counter("t_req_total", "requests", wire="nnstpu").inc(3)
        reg.histogram("t_lat_seconds", "latency",
                      buckets=(0.1, 1.0), pipeline="p").observe(0.05)
        text = reg.render_prometheus()
        assert "# HELP t_req_total requests" in text
        assert "# TYPE t_req_total counter" in text
        assert 't_req_total{wire="nnstpu"} 3' in text
        assert "# TYPE t_lat_seconds histogram" in text
        assert 't_lat_seconds_bucket{le="0.1",pipeline="p"} 1' in text
        assert 't_lat_seconds_bucket{le="+Inf",pipeline="p"} 1' in text
        assert 't_lat_seconds_sum{pipeline="p"} 0.05' in text
        assert 't_lat_seconds_count{pipeline="p"} 1' in text
        assert text.endswith("\n")

    def test_render_escapes_label_values(self):
        reg = MetricsRegistry()
        reg.counter("t_total", x='a"b\\c\nd').inc()
        line = [ln for ln in reg.render_prometheus().splitlines()
                if ln.startswith("t_total{")][0]
        assert line == 't_total{x="a\\"b\\\\c\\nd"} 1'

    def test_snapshot_is_json_able(self):
        reg = MetricsRegistry()
        reg.gauge("t_g", a="1").set(2)
        reg.histogram("t_h", buckets=(1.0,)).observe(0.5)
        snap = json.loads(json.dumps(reg.snapshot()))
        by_name = {m["name"]: m for m in snap["metrics"]}
        assert by_name["t_g"]["value"] == 2
        assert by_name["t_h"]["count"] == 1
        assert by_name["t_h"]["p50"] == pytest.approx(0.5)
        assert by_name["t_h"]["buckets"][-1][0] == "+Inf"


class TestMetricsServer:
    def test_http_exporter_end_to_end(self):
        reg = MetricsRegistry()
        reg.counter("t_req_total", "reqs", wire="x").inc(7)
        reg.histogram("t_lat_seconds", pipeline="p").observe(0.002)
        with MetricsServer(registry=reg, host="127.0.0.1", port=0) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(f"{base}/metrics") as resp:
                assert resp.headers["Content-Type"].startswith(
                    "text/plain; version=0.0.4")
                text = resp.read().decode()
            assert 't_req_total{wire="x"} 7' in text
            with urllib.request.urlopen(f"{base}/metrics.json") as resp:
                assert resp.headers["Content-Type"] == "application/json"
                snap = json.loads(resp.read())
            assert any(m["name"] == "t_lat_seconds"
                       for m in snap["metrics"])
            with urllib.request.urlopen(f"{base}/healthz") as resp:
                assert resp.read() == b"ok\n"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}/nope")

    def test_server_refreshes_collectors_per_scrape(self):
        reg = MetricsRegistry()
        state = {"v": 1.0}
        g = reg.gauge("t_g")

        def collect():
            g.set(state["v"])

        reg.register_collector(collect)
        with MetricsServer(registry=reg, host="127.0.0.1", port=0) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            assert "t_g 1" in urllib.request.urlopen(
                f"{base}/metrics").read().decode()
            state["v"] = 2.0
            assert "t_g 2" in urllib.request.urlopen(
                f"{base}/metrics").read().decode()


# -- pipeline-level instrumentation ------------------------------------------
class _NumSrc(SourceElement):
    ELEMENT_NAME = "_obsnumsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 5}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig

        cfg = TensorsConfig.from_arrays([np.zeros((1,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        buf = TensorBuffer([np.array([float(self.i)], np.float32)],
                           pts=self.i * 1000)
        self.i += 1
        return buf


class _BlockingSink(Element):
    """Blocks its first chain() until released — pins the queue worker so
    queued buffers pile up deterministically."""

    ELEMENT_NAME = "_obsblocksink"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.entered = threading.Event()
        self.release = threading.Event()
        self.count = 0

    def chain(self, pad, buf):
        self.entered.set()
        self.release.wait(timeout=10)
        self.count += 1
        return FlowReturn.OK


class TestQueueDrops:
    def test_leaky_downstream_drops_counted(self):
        pipe = Pipeline(name="obs-qdrop", fuse=False)
        q = Queue(name="q", max_size_buffers=2, leaky="downstream")
        sink = _BlockingSink(name="bs")
        pipe.add_linked(q, sink)
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        logging.getLogger("nnstreamer_tpu").addHandler(handler)
        q.start()
        try:
            mk = lambda i: TensorBuffer(  # noqa: E731
                [np.array([float(i)], np.float32)], pts=i)
            q.chain(q.sinkpads[0], mk(0))
            # worker now holds buf 0 inside the blocked sink: the queue
            # itself is empty with capacity 2
            assert sink.entered.wait(5)
            q.chain(q.sinkpads[0], mk(1))
            q.chain(q.sinkpads[0], mk(2))  # full
            for i in range(3, 6):          # each push drops the oldest
                q.chain(q.sinkpads[0], mk(i))
            drops = get_registry().get("nns_queue_drops_total",
                                       pipeline="obs-qdrop", element="q")
            assert drops is not None and drops.value == 3
            snap = q.obs_snapshot()
            assert snap["drops"] == 3
            assert snap["depth"] == 2
            # satellite: the drop is no longer silent — exactly one
            # rate-limited warning for the burst
            warns = [r for r in records
                     if r.levelno == logging.WARNING
                     and "leaky=downstream" in r.getMessage()]
            assert len(warns) == 1
        finally:
            sink.release.set()
            q.sink_event(q.sinkpads[0], EosEvent())
            q.stop()
            logging.getLogger("nnstreamer_tpu").removeHandler(handler)

    def test_depth_gauge_samples_live_queue(self):
        pipe = Pipeline(name="obs-qdepth", fuse=False)
        q = Queue(name="q", max_size_buffers=8)
        sink = _BlockingSink(name="bs")
        pipe.add_linked(q, sink)
        q.start()
        try:
            for i in range(4):
                q.chain(q.sinkpads[0], TensorBuffer(
                    [np.array([float(i)], np.float32)], pts=i))
            assert sink.entered.wait(5)
            depth = get_registry().get("nns_queue_depth",
                                       pipeline="obs-qdepth", element="q")
            assert depth is not None and depth.value == 3  # 1 in-flight
        finally:
            sink.release.set()
            q.sink_event(q.sinkpads[0], EosEvent())
            q.stop()


class TestPipelineMetrics:
    def test_metrics_snapshot_and_property_agreement(self):
        class _CountSink(Element):
            ELEMENT_NAME = "_obscountsink"

            def __init__(self, name=None, **props):
                super().__init__(name, **props)
                self.add_sink_pad("sink")
                self.count = 0

            def chain(self, pad, buf):
                self.count += 1
                return FlowReturn.OK

        src = _NumSrc(name="nsrc", num_buffers=6)
        sink = _CountSink(name="csink")
        pipe = Pipeline(name="obs-agree", fuse=False).add_linked(src, sink)
        assert pipe.run(timeout=10) is not None
        snap = pipe.metrics_snapshot()
        assert snap["pipeline"] == "obs-agree"
        s = snap["elements"]["csink"]
        assert s["invokes"] == 6
        assert s["latency_us"] == sink.get_property("latency")
        # the exporter's gauge is sampled from the same InvokeStats the
        # property reads, so the scraped value must agree exactly
        text = get_registry().render_prometheus()
        want = (f'nns_element_latency_us{{element="csink",'
                f'pipeline="obs-agree",type="_obscountsink"}} '
                f'{sink.get_property("latency")}')
        assert want in text
        assert (f'nns_element_invokes_total{{element="csink",'
                f'pipeline="obs-agree",type="_obscountsink"}} 6') in text

    def test_tensor_rate_drops_exported(self):
        from nnstreamer_tpu.elements.rate import TensorRate
        from nnstreamer_tpu.elements.sink import TensorSink

        src = _NumSrc(name="rsrc", num_buffers=10)
        rate = TensorRate(name="rate", framerate="30/1", throttle=False)
        sink = TensorSink(name="rsink")
        pipe = Pipeline(name="obs-rate", fuse=False)
        pipe.add_linked(src, rate, sink)
        assert pipe.run(timeout=10) is not None
        # pts step is 1µs, output period 1/30 s: the first frame emits,
        # the other nine land inside the same output period and drop
        assert rate.dropped == 9
        c = get_registry().get("nns_tensor_rate_dropped_total",
                               pipeline="obs-rate", element="rate")
        assert c is not None and c.value == rate.dropped
        assert pipe.metrics_snapshot()["elements"]["rate"]["drops"] == 9

    def test_sink_e2e_histogram_populated(self):
        from nnstreamer_tpu.elements.sink import TensorSink

        src = _NumSrc(name="esrc", num_buffers=5)
        sink = TensorSink(name="esink")
        pipe = Pipeline(name="obs-e2e", fuse=False).add_linked(src, sink)
        assert pipe.run(timeout=10) is not None
        h = get_registry().get("nns_sink_e2e_seconds",
                               pipeline="obs-e2e", element="esink")
        assert h is not None and h.count == len(sink.latencies) > 0
        snap = pipe.metrics_snapshot()["elements"]["esink"]
        assert "e2e_p50_ms" in snap and "e2e_p99_ms" in snap

    def test_mux_sync_wait_histogram(self):
        from nnstreamer_tpu.elements.mux import TensorMux

        src_a = _NumSrc(name="ma", num_buffers=4)
        src_b = _NumSrc(name="mb", num_buffers=4)
        mux = TensorMux(name="mux", sync_mode="nosync")
        from nnstreamer_tpu.elements.sink import TensorSink

        sink = TensorSink(name="msink")
        pipe = Pipeline(name="obs-mux", fuse=False)
        pipe.add(src_a, src_b, mux, sink)
        src_a.srcpad.link(mux.request_sink_pad())
        src_b.srcpad.link(mux.request_sink_pad())
        mux.srcpad.link(sink.sinkpads[0])
        assert pipe.run(timeout=10) is not None
        h = get_registry().get("nns_tensor_mux_sync_wait_seconds",
                               pipeline="obs-mux", element="mux")
        assert h is not None and h.count == 4
