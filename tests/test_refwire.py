"""Reference-wire tensor_query protocol (wire=nnstreamer) — byte-level
interop with ``tensor_query_common.c``'s framed TCP.

The oracle class below is a ctypes replica of the C structs
(``tensor_query_common.h:60-92``, ``tensor_meta.h:21``): every offset,
size, and padding hole the compiler would produce is asserted against
our struct codec, the MQTT-header-proof pattern applied to the query
wire. The loopback tests then drive a hand-rolled "reference client"
(raw struct bytes only — none of our helpers) through the full
REQUEST_INFO → APPROVE → TRANSFER → result round trip against both the
pure-Python and the native-epoll servers.
"""

import ctypes
import os
import socket
import struct

import numpy as np
import pytest

from nnstreamer_tpu.query import refwire as R

CAPS = "other/tensors,format=static,num_tensors=1,dimensions=4:3,types=float32"


class RefDataInfo(ctypes.Structure):
    """ctypes oracle for TensorQueryDataInfo (tensor_query_common.h:60-71):
    the compiler computes the layout; we assert ours matches."""

    _fields_ = [
        ("base_time", ctypes.c_int64),
        ("sent_time", ctypes.c_int64),
        ("duration", ctypes.c_uint64),
        ("dts", ctypes.c_uint64),
        ("pts", ctypes.c_uint64),
        ("num_mems", ctypes.c_uint32),
        ("mem_sizes", ctypes.c_uint64 * 16),
    ]


class TestCtypesOracle:
    def test_data_info_layout_matches_compiler(self):
        assert ctypes.sizeof(RefDataInfo) == R.DATA_INFO_SIZE == 176
        assert RefDataInfo.num_mems.offset == 40
        # the compiler inserts a 4-byte hole before the u64 array
        assert RefDataInfo.mem_sizes.offset == 48

    def test_data_info_bytes_identical_to_ctypes(self):
        c = RefDataInfo(base_time=123456789, sent_time=-42,
                        duration=R.CLOCK_NONE, dts=R.CLOCK_NONE,
                        pts=777, num_mems=2)
        c.mem_sizes[0] = 48
        c.mem_sizes[1] = 1024
        ours = R.pack_data_info(2, [48, 1024], pts=777, dts=None,
                                duration=None, base_time=123456789,
                                sent_time=-42)
        assert ours == bytes(c)

    def test_data_info_unpack_from_ctypes_bytes(self):
        c = RefDataInfo(base_time=1, sent_time=2, duration=3, dts=4,
                        pts=5, num_mems=1)
        c.mem_sizes[0] = 99
        info = R.unpack_data_info(bytes(c))
        assert info == dict(base_time=1, sent_time=2, duration=3, dts=4,
                            pts=5, num_mems=1, mem_sizes=[99])

    def test_client_id_is_int64(self):
        # query_client_id_t = int64_t (tensor_meta.h:21)
        assert R._CLIENT_ID.size == ctypes.sizeof(ctypes.c_int64)

    def test_cmd_is_c_enum_int(self):
        # TensorQueryCommand is a plain C enum — 4-byte int on this ABI
        assert R._CMD.size == ctypes.sizeof(ctypes.c_int)


def _ref_send(sock, cmd, body=b"", sized=False):
    """Reference-client sender built from raw structs only (the wire a
    compiled tensor_query_client.c emits)."""
    msg = struct.pack("<i", cmd)
    if sized:
        msg += struct.pack("<Q", len(body))
    sock.sendall(msg + body)


def _ref_recv_exact(sock, n):
    out = b""
    while len(out) < n:
        part = sock.recv(n - len(out))
        assert part, "server closed early"
        out += part
    return out


def _reference_client_roundtrip(src_port, sink_port, frame):
    """The exact conversation of tensor_query_client.c:377-445 +
    send/receive_buffer, framed by hand."""
    src = socket.create_connection(("127.0.0.1", src_port), timeout=10)
    # server sends CLIENT_ID first
    (cmd,) = struct.unpack("<i", _ref_recv_exact(src, 4))
    assert cmd == 6
    (client_id,) = struct.unpack("<q", _ref_recv_exact(src, 8))
    # REQUEST_INFO with our caps, NUL-terminated
    _ref_send(src, 0, CAPS.encode() + b"\0", sized=True)
    (cmd,) = struct.unpack("<i", _ref_recv_exact(src, 4))
    assert cmd == 1, f"expected APPROVE, got {cmd}"
    (clen,) = struct.unpack("<Q", _ref_recv_exact(src, 8))
    server_caps = _ref_recv_exact(src, clen).split(b"\0")[0].decode()
    # second connection: sink port claims the client id
    sink = socket.create_connection(("127.0.0.1", sink_port), timeout=10)
    _ref_send(sink, 6, struct.pack("<q", client_id))
    # TRANSFER the frame: START + DATA + END with the raw DataInfo struct
    c = RefDataInfo(base_time=0, sent_time=0, duration=R.CLOCK_NONE,
                    dts=R.CLOCK_NONE, pts=31337, num_mems=1)
    c.mem_sizes[0] = len(frame)
    _ref_send(src, 3, bytes(c))
    _ref_send(src, 4, frame, sized=True)
    _ref_send(src, 5, bytes(c))
    # result comes back on the sink connection, same framing
    (cmd,) = struct.unpack("<i", _ref_recv_exact(sink, 4))
    assert cmd == 3, f"expected TRANSFER_START, got {cmd}"
    rinfo = R.unpack_data_info(_ref_recv_exact(sink, 176))
    mems = []
    for i in range(rinfo["num_mems"]):
        (cmd,) = struct.unpack("<i", _ref_recv_exact(sink, 4))
        assert cmd == 4
        (sz,) = struct.unpack("<Q", _ref_recv_exact(sink, 8))
        mems.append(_ref_recv_exact(sink, sz))
    (cmd,) = struct.unpack("<i", _ref_recv_exact(sink, 4))
    assert cmd == 5
    _ref_recv_exact(sink, 176)
    src.close()
    sink.close()
    return client_id, server_caps, rinfo, mems


def _serve_double(server, n=1):
    """Echo server loop: result = input * 2 (host math)."""
    for _ in range(n):
        buf = server.get_buffer(timeout=10)
        assert buf is not None
        cid = buf.meta["query_client_id"]
        doubled = buf.with_tensors(
            [np.asarray(t) * 2 for t in buf.tensors])
        assert server.send_result(cid, doubled)


@pytest.mark.parametrize("pure", [True, False],
                         ids=["pure-python", "native-epoll"])
def test_reference_client_full_roundtrip(pure, monkeypatch):
    """A hand-framed reference client offloads through our server on
    both transports; tensors reconstruct per the announced caps."""
    import threading

    from nnstreamer_tpu.query.server import QueryServer

    if pure:
        monkeypatch.setenv("NNSTPU_PURE_PY_SERVER", "1")
    else:
        from nnstreamer_tpu import native

        if native.get_lib() is None:
            pytest.skip("native library unavailable")
    server = QueryServer(host="127.0.0.1", port=0, caps_str=CAPS,
                         wire="nnstreamer").start()
    if not pure:
        assert server.native, "native refwire core did not come up"
    try:
        t = threading.Thread(target=_serve_double, args=(server,),
                             daemon=True)
        t.start()
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        cid, server_caps, rinfo, mems = _reference_client_roundtrip(
            server.port, server.sink_port, x.tobytes())
        t.join(timeout=10)
        assert not t.is_alive()
        assert server_caps == CAPS
        assert len(mems) == 1
        got = np.frombuffer(mems[0], np.float32).reshape(3, 4)
        np.testing.assert_array_equal(got, x * 2)
    finally:
        server.stop()


def test_server_reconstructs_typed_tensors(monkeypatch):
    """With caps configured, raw mems surface as shaped/typed arrays
    (reference serversrc trusting its caps), not u8 blobs."""
    import threading

    from nnstreamer_tpu.query.server import QueryServer

    monkeypatch.setenv("NNSTPU_PURE_PY_SERVER", "1")
    server = QueryServer(host="127.0.0.1", port=0, caps_str=CAPS,
                         wire="nnstreamer").start()
    seen = []
    try:
        def grab():
            buf = server.get_buffer(timeout=10)
            seen.append(buf)
            server.send_result(buf.meta["query_client_id"], buf)

        t = threading.Thread(target=grab, daemon=True)
        t.start()
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        _reference_client_roundtrip(server.port, server.sink_port,
                                    x.tobytes())
        t.join(timeout=10)
    finally:
        server.stop()
    assert seen and seen[0].tensors[0].shape == (3, 4)
    assert seen[0].tensors[0].dtype == np.float32
    assert seen[0].pts == 31337


def test_server_denies_incompatible_caps(monkeypatch):
    """The reference admission test: a client announcing tensor caps
    that neither config-equal nor intersect the server's gets DENY with
    the server's caps (tensor_query_common.c:770-803); compatible and
    unparseable (be-liberal) caps are approved. Pure-Python transport —
    the native epoll core stays permissive by design."""
    from nnstreamer_tpu.query.server import QueryServer

    monkeypatch.setenv("NNSTPU_PURE_PY_SERVER", "1")
    server = QueryServer(host="127.0.0.1", port=0, caps_str=CAPS,
                         wire="nnstreamer").start()
    try:
        bad = ("other/tensors,format=static,num_tensors=1,"
               "dimensions=8:8,types=uint8")
        with pytest.raises(R.RefWireError, match="denied"):
            R.RefWireClient("127.0.0.1", server.port,
                            sink_port=server.sink_port, in_caps=bad)
        ok = R.RefWireClient("127.0.0.1", server.port,
                             sink_port=server.sink_port, in_caps=CAPS)
        assert ok.server_caps == CAPS
        ok.close()
        # non-tensor media caps deny too (reference can_intersect=false)
        with pytest.raises(R.RefWireError, match="denied"):
            R.RefWireClient("127.0.0.1", server.port,
                            sink_port=server.sink_port,
                            in_caps="video/x-raw,width=8,height=8")
        # an empty/unparseable announcement is approved (be liberal)
        empty = R.RefWireClient("127.0.0.1", server.port,
                                sink_port=server.sink_port, in_caps="")
        empty.close()
    finally:
        server.stop()


class TestElementsRefwire:
    """Full pipeline loopback: our client element offloading over
    wire=nnstreamer to our serversrc/serversink pair."""

    @pytest.fixture
    def triple_model(self):
        from nnstreamer_tpu.filters.jax_backend import (
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model("refwire_triple",
                           lambda x: (x * 3.0,), None)
        yield "refwire_triple"
        unregister_jax_model("refwire_triple")

    @pytest.mark.parametrize("pure", [True, False],
                             ids=["pure-python", "native-epoll"])
    def test_offload_pipeline(self, triple_model, pure, monkeypatch):
        import time

        from nnstreamer_tpu import parse_launch

        if pure:
            monkeypatch.setenv("NNSTPU_PURE_PY_SERVER", "1")
        else:
            from nnstreamer_tpu import native

            if native.get_lib() is None:
                pytest.skip("native library unavailable")
        server = parse_launch(
            "tensor_query_serversrc name=ssrc port=0 wire=nnstreamer "
            f"caps={CAPS} ! "
            f"tensor_filter framework=jax model={triple_model} ! "
            "queue max-size-buffers=8 materialize-host=true ! "
            "tensor_query_serversink id=0")
        server.start()
        try:
            ssrc = server.get("ssrc")
            deadline = time.monotonic() + 5
            while ssrc.server is None and time.monotonic() < deadline:
                time.sleep(0.01)
            client = parse_launch(
                "appsrc name=src ! tensor_query_client name=c "
                f"port={ssrc.port} sink-port={ssrc.result_port} "
                "wire=nnstreamer ! tensor_sink name=out")
            frames = [np.full((3, 4), i, np.float32) for i in range(4)]
            client.start()
            try:
                src = client.get("src")
                for f in frames:
                    src.push([f])
                src.end_of_stream()
                msg = client.wait(timeout=30)
                assert msg is not None and msg.kind == "eos", msg
                out = client.get("out").buffers
                assert len(out) == 4
                for i, b in enumerate(out):
                    np.testing.assert_array_equal(
                        np.asarray(b.tensors[0]), frames[i] * 3)
                    assert b.tensors[0].dtype == np.float32
            finally:
                client.stop()
        finally:
            server.stop()
