"""Shortest-slack balancer tests (query/balance.py + the client's
balance mode).

Covers the pure policy (scoring, ranking determinism, ad-load parsing
incl. the pre-fleet load-unknown compat contract), the per-endpoint RTT
stats regression (a shared EndpointStats once gave every server the
same hedge timeout), the kill switches (``balance=off`` /
``NNSTPU_FLEET=0`` keep the exact single-connection resilient path),
and the 2-replica loopback behavior: a stalled replica sheds its share
of routes to its healthy sibling.
"""

import os
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.pipeline.element import FlowError
from nnstreamer_tpu.query import balance as B
from nnstreamer_tpu.query import resilience as R
from nnstreamer_tpu.registry import ELEMENT, get_subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


# ---------------------------------------------------------------------------
# policy: parse_ad_load
# ---------------------------------------------------------------------------
class TestParseAdLoad:
    def test_pre_fleet_ad_is_load_unknown(self):
        # the exact ad shape every pre-fleet server publishes
        # (discovery.py before the load block existed) — pinned: it must
        # parse as load-unknown, not as zero load, so a mixed fleet
        # balances on RTT alone instead of favoring old replicas
        old_ad = {"host": "127.0.0.1", "port": 3000, "ts": 123.0}
        assert B.parse_ad_load(old_ad) is None

    def test_none_and_malformed(self):
        assert B.parse_ad_load(None) is None
        assert B.parse_ad_load({}) is None
        assert B.parse_ad_load({"load": "busy"}) is None
        assert B.parse_ad_load({"load": {"queue_depth": "many"}}) is None

    def test_full_block(self):
        load = B.parse_ad_load({"load": {
            "queue_depth": 3, "service_ms": 7.5,
            "slack_headroom_ms": -12.0}})
        assert load == B.EndpointLoad(queue_depth=3, service_ms=7.5,
                                      slack_headroom_ms=-12.0)

    def test_partial_block(self):
        load = B.parse_ad_load({"load": {"queue_depth": 2}})
        assert load.queue_depth == 2
        assert load.service_ms is None
        assert load.slack_headroom_ms is None


# ---------------------------------------------------------------------------
# policy: score / rank
# ---------------------------------------------------------------------------
class TestScore:
    def test_monotone_in_inflight(self):
        assert B.score(0.01, 0, None) < B.score(0.01, 1, None) \
            < B.score(0.01, 4, None)

    def test_monotone_in_queue_depth(self):
        shallow = B.EndpointLoad(queue_depth=1, service_ms=5.0)
        deep = B.EndpointLoad(queue_depth=10, service_ms=5.0)
        assert B.score(0.01, 0, shallow) < B.score(0.01, 0, deep)

    def test_negative_headroom_penalized(self):
        ok = B.EndpointLoad(queue_depth=0, service_ms=5.0,
                            slack_headroom_ms=20.0)
        over = B.EndpointLoad(queue_depth=0, service_ms=5.0,
                              slack_headroom_ms=-50.0)
        assert B.score(0.01, 0, over) - B.score(0.01, 0, ok) == \
            pytest.approx(0.05)

    def test_load_unknown_falls_back_to_rtt_and_inflight(self):
        # no load block: inflight still differentiates (converted
        # through the RTT), so join-shortest-queue survives old ads
        assert B.score(0.01, 0, None) < B.score(0.01, 3, None)

    def test_cold_endpoint_outranks_warm(self):
        # an unsampled endpoint (rtt None → DEFAULT_RTT_S) must score
        # below any realistically-warmed sibling so it gets probed
        assert B.score(None, 0, None) < B.score(0.002, 0, None)

    def test_rank_orders_and_tie_breaks_deterministically(self):
        a, b, c = ("hostA", 1), ("hostB", 2), ("hostC", 3)
        ranked = B.rank([(c, 0.01, 0, None), (a, 0.01, 0, None),
                         (b, 0.05, 0, None)])
        # equal scores (a, c) tie-break on the endpoint tuple
        assert [ep for _, ep in ranked] == [a, c, b]
        again = B.rank([(a, 0.01, 0, None), (c, 0.01, 0, None),
                        (b, 0.05, 0, None)])
        assert ranked == again


# ---------------------------------------------------------------------------
# satellite regression: per-endpoint RTT stats
# ---------------------------------------------------------------------------
class TestPerEndpointStats:
    def test_two_endpoints_get_distinct_hedge_timeouts(self):
        """Regression: _r_stats was ONE EndpointStats shared by every
        server, so a slow replica inflated the fast replica's hedge
        timer. Two endpoints with 10x different RTTs must keep
        independent stats and different hedge timeouts."""
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(reliable=True)
        try:
            fast = cl._r_stat("fast", 1000)
            slow = cl._r_stat("slow", 2000)
            assert fast is not slow
            for _ in range(R.EndpointStats.MIN_SAMPLES):
                fast.observe(0.010)
                slow.observe(0.100)
            floor = 0.001
            assert cl._r_stat("fast", 1000) is fast  # stable identity
            t_fast = fast.hedge_timeout(floor)
            t_slow = slow.hedge_timeout(floor)
            assert t_slow > t_fast * 5
        finally:
            cl.stop()


# ---------------------------------------------------------------------------
# kill switches
# ---------------------------------------------------------------------------
class TestKillSwitches:
    def test_balance_off_is_default_and_off(self):
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(reliable=True)
        try:
            assert cl.get_property("balance") == "off"
            assert not cl._balance_on()
        finally:
            cl.stop()

    def test_fleet_env_zero_disables(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_FLEET", "0")
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(reliable=True, balance="shortest-slack")
        try:
            assert not cl._balance_on()
        finally:
            cl.stop()

    def test_unknown_mode_rejected(self):
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(reliable=True, balance="round-robin")
        try:
            with pytest.raises(FlowError, match="balance"):
                cl._balance_on()
        finally:
            cl.stop()

    def test_balance_requires_reliable(self):
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(balance="shortest-slack")
        try:
            with pytest.raises(FlowError, match="reliable"):
                cl.chain(cl.sinkpad, TensorBuffer(
                    [np.zeros(2, np.float32)], pts=0))
        finally:
            cl.stop()

    def test_balance_off_never_touches_balance_state(self):
        """The byte-identical pin: with balance=off the single-server
        resilient path runs and NO balance-mode state is ever built —
        the exact PR-19 transport."""
        src, stop, invokes = _echo_server()
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(port=src.port, reliable=True, max_in_flight=2,
                    timeout=5.0)
        outs = []
        cl.srcpad.push = lambda b: outs.append(b)
        try:
            for i in range(10):
                cl.chain(cl.sinkpad, TensorBuffer(
                    [np.full((4,), i, dtype=np.float32)], pts=i))
            cl.handle_eos()
            assert len(outs) == 10
            assert sorted(int(o.to_host().tensors[0][0])
                          for o in outs) == [2 * i for i in range(10)]
            assert cl._b_channels == {}
            assert cl._b_pending == {}
            assert cl._b_discovery is None
        finally:
            stop.set()
            cl.stop()
            src.stop()


# ---------------------------------------------------------------------------
# loopback: 2 endpoints, balanced
# ---------------------------------------------------------------------------
def _echo_server(delay_s: float = 0.0):
    """(serversrc, stopper, invokes): resilient echo x2 server whose
    worker optionally sleeps ``delay_s`` per frame (a stalled replica)."""
    Src = get_subplugin(ELEMENT, "tensor_query_serversrc")
    src = Src(port=0, reliable=True)
    src.start()
    server = src.server
    stop = threading.Event()
    invokes = []

    def worker():
        while not stop.is_set():
            try:
                buf = server.get_buffer(timeout=0.1)
            except Exception:
                return
            if buf is None:
                continue
            invokes.append(buf.meta.get("net_req_id"))
            if delay_s:
                time.sleep(delay_s)
            out = TensorBuffer([t * 2 for t in buf.to_host().tensors],
                               pts=buf.pts)
            out.meta.update(buf.meta)
            server.send_result(buf.meta["query_client_id"], out)

    threading.Thread(target=worker, daemon=True).start()
    return src, stop, invokes


class TestBalancedLoopback:
    def _run_pair(self, n, delay_a=0.0, delay_b=0.0, **client_props):
        sa, stop_a, inv_a = _echo_server(delay_a)
        sb, stop_b, inv_b = _echo_server(delay_b)
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        props = dict(servers=f"127.0.0.1:{sa.port},127.0.0.1:{sb.port}",
                     reliable=True, balance="shortest-slack",
                     max_in_flight=4, timeout=5.0)
        props.update(client_props)
        cl = Client(**props)
        outs = []
        cl.srcpad.push = lambda b: outs.append(b)
        try:
            for i in range(n):
                cl.chain(cl.sinkpad, TensorBuffer(
                    [np.full((4,), i, dtype=np.float32)], pts=i))
            cl.handle_eos()
        finally:
            stop_a.set()
            stop_b.set()
            cl.stop()
            sa.stop()
            sb.stop()
        return outs, inv_a, inv_b

    def test_both_replicas_serve_exactly_once_in_order(self):
        outs, inv_a, inv_b = self._run_pair(60)
        assert len(outs) == 60
        # in-order delivery despite N channels (req_id watermark)
        assert [int(o.to_host().tensors[0][0]) for o in outs] == \
            [2 * i for i in range(60)]
        assert len(inv_a) + len(inv_b) == 60
        assert len(set(inv_a) | set(inv_b)) == 60  # no double invoke
        assert inv_a and inv_b  # both replicas actually probed

    def test_stalled_replica_sheds_routes_to_sibling(self):
        """The acceptance behavior: a 100ms stall on replica A shifts
        the bulk (>80%) of subsequent routes to healthy replica B."""
        outs, inv_a, inv_b = self._run_pair(60, delay_a=0.1)
        assert len(outs) == 60
        assert len(set(inv_a) | set(inv_b)) == 60
        assert len(inv_b) > 0.8 * 60

    def test_breaker_open_endpoint_excluded(self):
        """An endpoint whose breaker is open never appears among the
        balance candidates."""
        sa, stop_a, inv_a = _echo_server()
        sb, stop_b, inv_b = _echo_server()
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(servers=f"127.0.0.1:{sa.port},127.0.0.1:{sb.port}",
                    reliable=True, balance="shortest-slack",
                    max_in_flight=2, timeout=5.0)
        outs = []
        cl.srcpad.push = lambda b: outs.append(b)
        try:
            br = cl._r_breaker("127.0.0.1", sa.port)
            for _ in range(100):  # force open regardless of threshold
                br.record_failure()
                if not br.allow():
                    break
            assert not br.allow()
            cands = cl._b_candidates()
            eps = [ep for ep, _, _, _ in cands]
            assert ("127.0.0.1", sa.port) not in eps
            assert ("127.0.0.1", sb.port) in eps
            for i in range(10):
                cl.chain(cl.sinkpad, TensorBuffer(
                    [np.full((4,), i, dtype=np.float32)], pts=i))
            cl.handle_eos()
            assert len(outs) == 10
            assert not inv_a  # everything went to the healthy sibling
            assert len(inv_b) == 10
        finally:
            stop_a.set()
            stop_b.set()
            cl.stop()
            sa.stop()
            sb.stop()


# ---------------------------------------------------------------------------
# discovery ads: live load signal
# ---------------------------------------------------------------------------
class TestAdRefresh:
    def test_refreshed_ad_carries_load_and_old_ads_parse_unknown(self):
        from nnstreamer_tpu.query.discovery import (
            ServerAdvertiser,
            ServerDiscovery,
        )
        from nnstreamer_tpu.query.pubsub import Broker

        broker = Broker(port=0).start()
        try:
            depth = [0]
            adv = ServerAdvertiser(
                "127.0.0.1", broker.port, "adtest", "127.0.0.1", 4321,
                load_fn=lambda: {"queue_depth": depth[0],
                                 "service_ms": 5.0},
                refresh_s=0.05)
            # a pre-fleet peer on the same operation: no load block
            old = ServerAdvertiser("127.0.0.1", broker.port, "adtest",
                                   "127.0.0.1", 4322)
            disco = ServerDiscovery("127.0.0.1", broker.port, "adtest")
            try:
                adv.publish()
                old.publish()
                disco.wait_servers(timeout=5.0)
                load = disco.load("127.0.0.1", 4321)
                assert load == {"queue_depth": 0, "service_ms": 5.0}
                # the refresh loop picks up live changes
                depth[0] = 7
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    load = disco.load("127.0.0.1", 4321)
                    if load and load.get("queue_depth") == 7:
                        break
                    time.sleep(0.02)
                assert load["queue_depth"] == 7
                # compat: the old peer's ad is load-unknown, not zero
                assert disco.load("127.0.0.1", 4322) is None
                assert B.parse_ad_load(
                    {"load": disco.load("127.0.0.1", 4322)}) is None
            finally:
                adv.retract()
                old.retract()
                disco.close()
        finally:
            broker.stop()
