"""Device-residency layer: DeviceBuffer pass-through, lazy cached host
views, pinned pool slabs, transfer accounting.

The contract under test (tensors/buffer.py, pipeline/element.py entry
policy): residency must be OBSERVABLY free — outputs byte-identical to a
``NNSTPU_RESIDENT=0`` run, ordering preserved through routing elements
with device and host buffers interleaved, EOS flushes resident buffers
in flight, and the one sanctioned ``to_host()`` site materializes once
(a second call reuses the cached view, a pre-upload host view costs zero
copies).
"""

import gc

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.jax_backend import (
    is_jax_model_registered,
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.pipeline.element import (
    Element,
    EosEvent,
    FlowReturn,
    peer_device_capable,
)
from nnstreamer_tpu.pipeline.pipeline import Pipeline, Queue, SourceElement
from nnstreamer_tpu.tensors.buffer import (
    DeviceBuffer,
    TensorBuffer,
    as_device_buffer,
    transfer_snapshot,
)
from nnstreamer_tpu.tensors.pool import get_pool
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType


def _dev(arrays, host_view=None, **kw) -> DeviceBuffer:
    buf = TensorBuffer(list(arrays), **kw).to_device()
    out = as_device_buffer(buf, host_view=host_view)
    assert isinstance(out, DeviceBuffer)
    return out


def _d2h_events() -> float:
    return transfer_snapshot()["d2h_events"]


# -- lazy cached host view ----------------------------------------------------


class TestLazyToHost:
    def test_materialize_once_reuse_view(self):
        db = _dev([np.arange(8, dtype=np.float32)])
        e0 = _d2h_events()
        h1 = db.to_host()
        e1 = _d2h_events()
        h2 = db.to_host()
        e2 = _d2h_events()
        assert h1 is h2  # the cached view IS the second result
        assert e1 - e0 == 1 and e2 == e1  # exactly one D2H, ever
        assert isinstance(h1, TensorBuffer)
        assert not isinstance(h1, DeviceBuffer)
        np.testing.assert_array_equal(h1.tensors[0],
                                      np.arange(8, dtype=np.float32))

    def test_host_view_costs_zero_copies(self):
        src = np.arange(6, dtype=np.float32)
        db = _dev([src], host_view=[src])
        e0 = _d2h_events()
        h = db.to_host()
        assert h.tensors[0] is src  # the pre-upload bytes, not a copy
        assert _d2h_events() == e0

    def test_finalize_applied_once_at_to_host(self):
        calls = []

        def fin(host_buf):
            calls.append(1)
            return host_buf.with_tensors(
                [np.asarray(t) * 2 for t in host_buf.tensors])

        db = _dev([np.ones(4, np.float32)], finalize=fin)
        h1 = db.to_host()
        h2 = db.to_host()
        assert h1 is h2 and calls == [1]
        np.testing.assert_array_equal(h1.tensors[0],
                                      np.full(4, 2.0, np.float32))

    def test_replace_keeps_residency_and_drops_stale_cache(self):
        db = _dev([np.ones(4, np.float32)])
        h = db.to_host()
        r = db.replace(meta={"k": 1})
        assert isinstance(r, DeviceBuffer) and r.meta == {"k": 1}
        assert r.to_host() is not h  # cache never crosses a replace
        w = db.with_tensors([t + 1 for t in db.tensors])
        assert isinstance(w, DeviceBuffer)

    def test_disabled_never_wraps(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_RESIDENT", "0")
        buf = TensorBuffer([np.ones(4, np.float32)]).to_device()
        assert not isinstance(as_device_buffer(buf), DeviceBuffer)


# -- entry policy -------------------------------------------------------------


class _HostCollect(Element):
    """Not DEVICE_PASSTHROUGH: entry must hand it host tensors."""

    ELEMENT_NAME = "_hostcollect"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.buffers = []
        self.got_eos = False

    def chain(self, pad, buf):
        self.buffers.append(buf)
        return FlowReturn.OK

    def sink_event(self, pad, event):
        if isinstance(event, EosEvent):
            self.got_eos = True


class _DevCollect(_HostCollect):
    ELEMENT_NAME = "_devcollect"
    DEVICE_PASSTHROUGH = True


class TestEntryPolicy:
    def test_non_passthrough_entry_materializes(self):
        el = _HostCollect()
        el._chain_entry(el.sinkpads[0],
                        _dev([np.arange(3, dtype=np.float32)]))
        (got,) = el.buffers
        assert not isinstance(got, DeviceBuffer)
        assert isinstance(got.tensors[0], np.ndarray)

    def test_passthrough_entry_forwards_resident(self):
        el = _DevCollect()
        db = _dev([np.arange(3, dtype=np.float32)])
        el._chain_entry(el.sinkpads[0], db)
        assert el.buffers[0] is db

    def test_passthrough_with_pending_finalize_materializes(self):
        # DEVICE_PASSTHROUGH without HANDLES_DEFERRED must still apply a
        # pending finalize at entry — same payload as an unfused pipeline
        el = _DevCollect()
        db = _dev([np.ones(2, np.float32)],
                  finalize=lambda b: b.with_tensors(
                      [np.asarray(t) + 1 for t in b.tensors]))
        el._chain_entry(el.sinkpads[0], db)
        (got,) = el.buffers
        assert not isinstance(got, DeviceBuffer)
        np.testing.assert_array_equal(got.tensors[0],
                                      np.full(2, 2.0, np.float32))

    def test_peer_device_capable(self):
        q = Queue()
        host = _HostCollect()
        q.link(host)
        assert not peer_device_capable(q.srcpad)
        q2 = Queue()
        dev = _DevCollect()
        q2.link(dev)
        assert peer_device_capable(q2.srcpad)
        q3 = Queue()
        assert not peer_device_capable(q3.srcpad)  # unlinked


# -- routing ordering with interleaved host/device buffers --------------------


class _MixedSrc(SourceElement):
    """Frames 0..n-1; odd indices are DeviceBuffers, even stay host."""

    ELEMENT_NAME = "_mixedsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 8}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig

        cfg = TensorsConfig.from_arrays([np.zeros((1,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        buf = TensorBuffer([np.array([float(self.i)], np.float32)],
                           pts=self.i * 1000)
        if self.i % 2:
            buf = as_device_buffer(buf.to_device())
        self.i += 1
        return buf


def _values(collect):
    return [float(np.asarray(b.to_host().tensors[0])[0])
            for b in collect.buffers]


class TestRoutingInterleaved:
    def test_queue_and_tee_preserve_order_and_residency(self):
        from nnstreamer_tpu.elements.tee import Tee

        n = 8
        pipe = Pipeline("residency-tee", fuse=False)
        src = _MixedSrc(num_buffers=n)
        q = Queue(max_size_buffers=4)
        tee = Tee()
        c1, c2 = _DevCollect(), _HostCollect()
        pipe.add(src, q, tee, c1, c2)
        src.link(q)
        q.link(tee)
        tee.link(c1)
        tee.link(c2)
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos", msg
        want = [float(i) for i in range(n)]
        assert _values(c1) == want  # order survives the thread boundary
        assert _values(c2) == want
        # the passthrough branch saw residency preserved for odd frames;
        # the host branch saw everything materialized at entry
        kinds1 = [isinstance(b, DeviceBuffer) for b in c1.buffers]
        assert kinds1 == [bool(i % 2) for i in range(n)]
        assert not any(isinstance(b, DeviceBuffer) for b in c2.buffers)

    def test_mux_merges_mixed_buffers(self):
        from nnstreamer_tpu.elements.mux import TensorMux

        mux = TensorMux()
        out = _DevCollect()
        p0 = mux.request_sink_pad()
        p1 = mux.request_sink_pad()
        mux.link(out)
        host = TensorBuffer([np.array([1.0], np.float32)], pts=0)
        dev = _dev([np.array([2.0], np.float32)], pts=0)
        mux._chain_entry(p0, host)
        mux._chain_entry(p1, dev)
        (got,) = out.buffers
        vals = [float(np.asarray(t)[0]) for t in got.to_host().tensors]
        assert vals == [1.0, 2.0]


# -- end-to-end: byte equality + EOS flush ------------------------------------


DESC = (
    "videotestsrc pattern=ball num-buffers=12 width=16 height=16 ! "
    "tensor_converter ! "
    "tensor_aggregator frames-in=1 frames-out=4 frames-flush=4 "
    "frames-dim=3 concat=true ! "
    "queue max-size-buffers=4 prefetch-device=true ! "
    "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
    "tensor_filter framework=jax model=perf_smoke_sum name=filter "
    "inflight=2 ! "
    "queue max-size-buffers=8 materialize-host=true ! "
    "tensor_sink name=sink to-host=true"
)


def _register_sum_model():
    import jax.numpy as jnp

    if not is_jax_model_registered("perf_smoke_sum"):
        register_jax_model(
            "perf_smoke_sum",
            lambda x: (jnp.sum(x, axis=(1, 2, 3))[:, None],),
            None)


def _run_desc():
    _register_sum_model()
    pipe = parse_launch(DESC)
    msg = pipe.run(timeout=120)
    assert msg is not None and msg.kind == "eos", msg
    return pipe, [np.asarray(b.tensors[0]).copy()
                  for b in pipe.get("sink").buffers]


@pytest.fixture
def square_model():
    import jax.numpy as jnp

    def fn(params, x):
        return x.astype(jnp.float32) ** 2 + params

    in_info = TensorsInfo([TensorInfo(dim=(4,), type=TensorType.FLOAT32)])
    out_info = TensorsInfo([TensorInfo(dim=(4,), type=TensorType.FLOAT32)])
    register_jax_model("residency_square", fn, jnp.float32(1.0),
                       in_info=in_info, out_info=out_info)
    yield "residency_square"
    unregister_jax_model("residency_square")


class _DevSrc(SourceElement):
    """Every frame enters the pipeline already device-resident."""

    ELEMENT_NAME = "_devsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 6}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig

        cfg = TensorsConfig.from_arrays([np.zeros((4,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        buf = _dev([np.full((4,), float(self.i), np.float32)], pts=self.i)
        self.i += 1
        return buf


class TestEndToEnd:
    def test_byte_equality_vs_residency_disabled(self, monkeypatch):
        _pipe, on = _run_desc()
        monkeypatch.setenv("NNSTPU_RESIDENT", "0")
        _pipe2, off = _run_desc()
        assert len(on) == len(off) == 3  # 12 frames / batch 4
        for a, b in zip(on, off):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert a.tobytes() == b.tobytes()

    def test_eos_flushes_resident_buffers_in_flight(self, square_model):
        # window (inflight=3) never fills to force a mid-stream fence
        # before the source runs dry, and no materialize-host queue
        # drains it: resident buffers are still in flight when EOS
        # lands — every frame must still come out, in order
        from nnstreamer_tpu.elements.filter import TensorFilter
        from nnstreamer_tpu.elements.sink import TensorSink

        n = 6
        src = _DevSrc(num_buffers=n)
        filt = TensorFilter(framework="jax", model=square_model, inflight=3)
        q = Queue(max_size_buffers=8)
        sink = TensorSink(to_host=False)
        pipe = Pipeline("residency-eos", fuse=False)
        pipe.add_linked(src, filt, q, sink)
        msg = pipe.run(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        assert sink.eos
        assert len(sink.buffers) == n
        for i, b in enumerate(sink.buffers):
            np.testing.assert_allclose(
                np.asarray(b.to_host().tensors[0]),
                np.full((4,), float(i) ** 2 + 1.0, np.float32))


# -- pool pinning (the PR 3 refcount guard extended to host views) ------------


class TestPoolPinning:
    def test_release_refused_while_pinned(self):
        pool = get_pool()
        arr = pool.acquire((32,), np.float32)
        arr[:] = np.arange(32, dtype=np.float32)
        db = _dev([arr], host_view=[arr])
        # explicit release (the sink/dispatch fence path) must refuse:
        # db's cached host view still reads this slab
        assert pool.release(arr) is False
        assert pool.owns(arr)
        h = db.to_host()
        assert h.tensors[0] is arr
        np.testing.assert_array_equal(arr, np.arange(32, dtype=np.float32))
        del h, db
        gc.collect()
        # wrapper died -> unpinned; the explicit release works again
        assert pool.release(arr) is True

    def test_gc_fallback_still_recycles_after_pin(self):
        pool = get_pool()
        arr = pool.acquire((16,), np.float32)
        db = _dev([arr], host_view=[arr])
        token = id(arr)
        del db, arr
        gc.collect()
        # both wrapper and view died: no leaked pin, no leaked claim
        assert token not in pool._pinned
        assert token not in pool._out
