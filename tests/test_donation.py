"""Donation safety on the whole-graph path (pipeline/fuse.py).

The fused region's jitted program donates its input slab
(``donate_argnums``) so XLA reuses the upload buffer for outputs — but a
donated buffer is CONSUMED by the dispatch, so every path that could
touch the input again must observe the undonated pipeline's exact
behavior:

- an armed retry/degrade error policy re-invokes ``chain()`` with the
  same buffer after a fault → the region must donate a device-side
  replay copy instead of the original (zero-loss, byte-identical);
- the kill switches (``NNSTPU_FUSE=0``, ``NNSTPU_DONATE=0``,
  ``NNSTPU_POOL=0``, ``inflight=0``) must each reproduce the fully
  optimized run byte-for-byte — they exist precisely to bisect
  donation/batching-suspected corruption.
"""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.jax_backend import (
    is_jax_model_registered,
    register_jax_model,
)
from nnstreamer_tpu.pipeline import faults

DESC = (
    "videotestsrc pattern=ball num-buffers=12 width=16 height=16 ! "
    "tensor_converter ! "
    "tensor_aggregator frames-in=1 frames-out=4 frames-flush=4 "
    "frames-dim=3 concat=true ! "
    "queue max-size-buffers=4 prefetch-device=true ! "
    "tensor_transform mode=arithmetic option=typecast:float32,div:255.0 ! "
    "tensor_filter framework=jax model=donation_sum name=filter "
    "inflight={k} ! "
    "queue max-size-buffers=8 materialize-host=true ! "
    "tensor_sink name=sink to-host=true"
)


@pytest.fixture(autouse=True)
def _no_active_injector():
    faults.deactivate()
    yield
    faults.deactivate()


def _register_model():
    import jax.numpy as jnp

    if not is_jax_model_registered("donation_sum"):
        register_jax_model(
            "donation_sum",
            lambda x: (jnp.sum(x, axis=(1, 2, 3))[:, None],),
            None)


def _run(inflight: int = 2, error_policy=None):
    _register_model()
    pipe = parse_launch(DESC.format(k=inflight), error_policy=error_policy)
    msg = pipe.run(timeout=120)
    assert msg is not None and msg.kind == "eos", msg
    outs = [np.asarray(b.tensors[0]).copy()
            for b in pipe.get("sink").buffers]
    return pipe, outs


def _assert_identical(ref, got):
    assert len(got) == len(ref) == 3  # 12 frames / window 4, zero loss
    for a, b in zip(ref, got):
        assert a.shape == b.shape and a.dtype == b.dtype
        assert a.tobytes() == b.tobytes()


def test_retry_fault_replays_donated_input_losslessly():
    """ISSUE acceptance: ``NNSTPU_FAULTS=filter.invoke:rate=1,nth=3``
    with error-policy=retry on the whole-graph path. The armed retry
    policy makes the region donate a device-side REPLAY COPY instead of
    the original upload, so the supervisor's re-invocation finds the
    buffer fully intact → byte-identical zero-loss output."""
    _pipe, clean = _run()
    faults.activate("filter.invoke:rate=1,nth=3")
    pipe, faulted = _run(error_policy="retry")
    assert pipe._regions, "whole-graph path not engaged"
    inj = faults.ACTIVE
    assert inj is not None and inj.injected("filter.invoke") == 1, \
        "the nth=3 fault never fired — the path under test did not run"
    _assert_identical(clean, faulted)


def test_fuse_off_byte_identical(monkeypatch):
    """``NNSTPU_FUSE=0`` (no region, no donation, per-element dispatch)
    must reproduce the fused whole-graph run byte-for-byte."""
    _pipe, fused = _run()
    monkeypatch.setenv("NNSTPU_FUSE", "0")
    pipe_u, unfused = _run()
    assert not pipe_u._regions
    _assert_identical(fused, unfused)


def test_donation_off_byte_identical(monkeypatch):
    """``NNSTPU_DONATE=0`` compiles the same program without input
    aliasing — the donation debug switch must change nothing."""
    _pipe, donated = _run()
    monkeypatch.setenv("NNSTPU_DONATE", "0")
    pipe, plain = _run()
    assert pipe._regions and not pipe._regions[0]._donating
    _assert_identical(donated, plain)


def test_pool_off_and_inflight_zero_byte_identical(monkeypatch):
    """``NNSTPU_POOL=0`` (no slab recycling under the batched uploads)
    and ``inflight=0`` (every dispatch fenced synchronously) are the
    remaining kill switches — each must be byte-identical too."""
    _pipe, ref = _run()
    monkeypatch.setenv("NNSTPU_POOL", "0")
    _pipe2, pool_off = _run()
    _assert_identical(ref, pool_off)
    monkeypatch.delenv("NNSTPU_POOL")
    _pipe3, sync = _run(inflight=0)
    _assert_identical(ref, sync)
