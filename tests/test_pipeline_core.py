"""Unit tests for caps negotiation, pads/elements, queue, and the pipeline
scheduler (reference: unittest_common caps negotiation + gst core behavior)."""

import time

import numpy as np
import pytest

from nnstreamer_tpu.pipeline.caps import ANY, Caps, CapsList, IntRange
from nnstreamer_tpu.pipeline.element import (
    CapsEvent,
    Element,
    EosEvent,
    FlowError,
    FlowReturn,
)
from nnstreamer_tpu.pipeline.pipeline import Pipeline, Queue, SourceElement
from nnstreamer_tpu.tensors.buffer import TensorBuffer


class TestCaps:
    def test_intersect_fixed(self):
        a = Caps("other/tensors", {"num_tensors": 1, "types": "uint8"})
        b = Caps("other/tensors", {"num_tensors": 1})
        c = a.intersect(b)
        assert c is not None and c["types"] == "uint8"

    def test_intersect_mismatch(self):
        a = Caps("other/tensors", {"num_tensors": 1})
        b = Caps("other/tensors", {"num_tensors": 2})
        assert a.intersect(b) is None
        assert a.intersect(Caps("video/x-raw", {})) is None

    def test_range_and_list(self):
        a = Caps("video/x-raw", {"width": IntRange(16, 4096), "format": ["RGB", "GRAY8"]})
        b = Caps("video/x-raw", {"width": 224, "format": "RGB"})
        c = a.intersect(b)
        assert c["width"] == 224 and c["format"] == "RGB"

    def test_fixate(self):
        a = Caps("video/x-raw", {"width": IntRange(16, 4096), "format": ["RGB", "GRAY8"]})
        f = a.fixate()
        assert f.is_fixed()
        assert f["width"] == 16 and f["format"] == "RGB"

    def test_capslist_any(self):
        assert CapsList.any().intersect(CapsList([Caps("x", {})])).caps

    def test_capslist_empty_is_not_any(self):
        # regression: failed negotiation (empty) must differ from ANY
        a = CapsList([Caps("other/tensors", {})])
        b = CapsList([Caps("video/x-raw", {})])
        assert a.intersect(b).is_empty()
        assert not CapsList.any().is_empty()

    def test_link_incompatible_pads_raises(self):
        e1, e2 = Element(), Element()
        s = e1.add_src_pad(caps=CapsList([Caps("other/tensors", {})]))
        k = e2.add_sink_pad(caps=CapsList([Caps("video/x-raw", {})]))
        with pytest.raises(ValueError, match="caps do not intersect"):
            s.link(k)


class _NumSrc(SourceElement):
    """Deterministic test source: counts 0..n-1 as 1-elem float32 tensors."""

    ELEMENT_NAME = "_numsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 5}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig

        cfg = TensorsConfig.from_arrays([np.zeros((1,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        buf = TensorBuffer([np.array([float(self.i)], np.float32)],
                           pts=self.i * 1000)
        self.i += 1
        return buf


class _Collect(Element):
    ELEMENT_NAME = "_collect"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.buffers = []
        self.caps_seen = []
        self.got_eos = False

    def chain(self, pad, buf):
        self.buffers.append(buf)
        return FlowReturn.OK

    def sink_event(self, pad, event):
        if isinstance(event, CapsEvent):
            self.caps_seen.append(event.caps)
        if isinstance(event, EosEvent):
            self.got_eos = True


class TestPipeline:
    def test_push_flow_and_eos(self):
        src, sink = _NumSrc(num_buffers=7), _Collect()
        pipe = Pipeline().add_linked(src, sink)
        msg = pipe.run(timeout=10)
        assert msg is not None and msg.kind == "eos"
        assert len(sink.buffers) == 7
        assert [float(b[0][0]) for b in sink.buffers] == list(range(7))
        assert sink.got_eos
        assert sink.caps_seen and sink.caps_seen[0].name == "other/tensors"

    def test_queue_thread_boundary(self):
        src, q, sink = _NumSrc(num_buffers=20), Queue(), _Collect()
        pipe = Pipeline().add_linked(src, q, sink)
        pipe.run(timeout=10)
        assert [float(b[0][0]) for b in sink.buffers] == list(range(20))
        assert sink.got_eos

    def test_error_propagates_to_bus(self):
        class _Boom(Element):
            ELEMENT_NAME = "_boom"

            def __init__(self):
                super().__init__()
                self.add_sink_pad()

            def chain(self, pad, buf):
                raise ValueError("boom")

        pipe = Pipeline().add_linked(_NumSrc(), _Boom())
        with pytest.raises(FlowError, match="boom"):
            pipe.run(timeout=10)

    def test_element_stats_populated(self):
        src, sink = _NumSrc(num_buffers=50), _Collect()
        Pipeline().add_linked(src, sink).run(timeout=10)
        assert sink.stats.total_invokes == 50
        assert sink.get_property("latency") >= 0

    def test_property_unknown_raises(self):
        with pytest.raises(KeyError):
            _Collect().set_property("nope", 1)

    def test_property_coercion(self):
        src = _NumSrc()
        src.set_property("num_buffers", "12")
        assert src.get_property("num_buffers") == 12


def test_queue_prefetch_device_hands_off_device_arrays():
    """prefetch-device starts H2D at enqueue: the consumer side of the
    queue sees jax Arrays, so a downstream jitted call dispatches without
    paying a per-frame transfer RPC."""
    import jax
    import numpy as np

    from nnstreamer_tpu import parse_launch

    pipe = parse_launch(
        "appsrc name=src ! queue prefetch-device=true ! tensor_sink "
        "name=out to-host=false")
    seen = []
    pipe.get("out").connect(lambda b: seen.append(b))
    pipe.start()
    pipe.get("src").push([np.arange(6, dtype=np.float32)], pts=0)
    pipe.get("src").end_of_stream()
    msg = pipe.wait(timeout=30)
    pipe.stop()
    assert msg is not None and msg.kind == "eos"
    assert isinstance(seen[0][0], jax.Array)
    np.testing.assert_array_equal(np.asarray(seen[0][0]),
                                  np.arange(6, dtype=np.float32))


class TestQueueGroupedDrain:
    """materialize-host queues drain in groups (one overlapped D2H flush
    per backlog) — ordering and event serialization must survive
    grouping."""

    def test_order_preserved_under_backlog(self):
        import threading
        import time as _t

        from nnstreamer_tpu import parse_launch

        pipe = parse_launch(
            "appsrc name=a block=true ! "
            "queue max-size-buffers=64 materialize-host=true ! "
            "tensor_sink name=s to-host=true")
        got = []
        gate = threading.Event()

        def slow_cb(buf):
            gate.wait(5)  # holds the drain so a backlog builds
            got.append(int(np.asarray(buf[0])[0]))

        pipe.get("s").connect(slow_cb)
        pipe.start()
        for i in range(20):
            pipe.get("a").push([np.asarray([i], np.int32)])
        gate.set()
        pipe.get("a").end_of_stream()
        assert pipe.wait(timeout=30).kind == "eos"
        pipe.stop()
        assert got == list(range(20))

    def test_caps_event_not_overtaken(self):
        """an event queued mid-stream stays ordered relative to buffers
        even when the drain gathers groups."""
        from nnstreamer_tpu import parse_launch
        from nnstreamer_tpu.pipeline.element import CustomEvent

        pipe = parse_launch(
            "appsrc name=a ! queue max-size-buffers=64 materialize-host=true "
            "name=q ! tensor_sink name=s to-host=true")
        seen = []
        pipe.get("s").connect(lambda b: seen.append(int(np.asarray(b[0])[0])))
        orig = pipe.get("s").sink_event

        def spy(pad, ev):
            if isinstance(ev, CustomEvent):
                seen.append(ev.name)
            return orig(pad, ev)

        pipe.get("s").sink_event = spy
        pipe.start()
        pipe.get("a").push([np.asarray([0], np.int32)])
        import time as _t

        _t.sleep(0.2)  # let buffer 0 drain so the event lands mid-stream
        pipe.get("q").sinkpads[0].push_event(CustomEvent("marker"))
        pipe.get("a").push([np.asarray([1], np.int32)])
        pipe.get("a").end_of_stream()
        assert pipe.wait(timeout=30).kind == "eos"
        pipe.stop()
        assert seen.index("marker") < seen.index(1)
        assert seen.index(0) < seen.index("marker")


class TestBatchLabelDecoder:
    def test_per_row_labels(self):
        from nnstreamer_tpu.decoders.image_labeling import ImageLabeling
        from nnstreamer_tpu.tensors.buffer import TensorBuffer

        scores = np.zeros((3, 5), np.float32)
        scores[0, 2] = 1.0
        scores[1, 4] = 2.0
        scores[2, 0] = 3.0
        out = ImageLabeling().decode(TensorBuffer([scores]), None,
                                     {"option2": "batched"})
        assert out.meta["label_index"] == [2, 4, 0]
        assert out.meta["score"] == [1.0, 2.0, 3.0]
        assert out[0].tobytes().decode() == "2\n4\n0"
