"""SSAT-style golden-output pipeline tests.

The reference's primary integration harness is SSAT
(`Documentation/how-to-write-testcase.md`): shell scripts launch real
gst-launch pipelines, dump via filesink, and byte-compare against golden
files (`tests/<group>/runTest.sh`, helpers gstTest/compareAll). Same
pattern here: every case is a LAUNCH STRING (the user-facing surface, not
element objects), output is dumped by `filesink`, and the bytes are
compared against a numpy-computed golden.

Determinism: videotestsrc patterns are pure functions of (pattern, frame
index) (`elements/source.py`), so goldens are derived, not stored.
"""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch


def _src_frames(n, w, h, pattern="gradient"):
    """Reference frames exactly as videotestsrc produces them."""
    pipe = parse_launch(
        f"videotestsrc num-buffers={n} width={w} height={h} "
        f"pattern={pattern} ! tensor_converter ! tensor_sink name=out")
    msg = pipe.run(timeout=60)
    assert msg.kind == "eos"
    return [np.asarray(b[0]) for b in pipe.get("out").buffers]


def _run_golden(tmp_path, description, golden_bytes):
    out = tmp_path / "result.raw"
    pipe = parse_launch(description.format(out=out))
    msg = pipe.run(timeout=120)
    assert msg is not None and msg.kind == "eos", msg
    assert out.read_bytes() == golden_bytes  # SSAT byte-compare


def test_golden_typecast_arith(tmp_path):
    # -127.5 and /128 are exactly representable at every step, so numpy
    # and XLA produce byte-identical float32 output (SSAT needs exactness)
    frames = _src_frames(6, 16, 16)
    golden = b"".join(
        ((f.astype(np.float32) - 127.5) / 128.0).tobytes() for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=6 width=16 height=16 pattern=gradient ! "
        "tensor_converter ! tensor_transform mode=arithmetic "
        "option=typecast:float32,add:-127.5,div:128 ! "
        "filesink location={out}",
        golden)


def test_golden_transpose(tmp_path):
    # frames are (1, h, w, c); option indexes nnstreamer dims
    # (innermost-first: 0=ch 1=w 2=h 3=frame) — 0:2:1:3 swaps w/h
    frames = _src_frames(4, 12, 8)
    golden = b"".join(np.ascontiguousarray(
        f.transpose(0, 2, 1, 3)).tobytes() for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=4 width=12 height=8 pattern=gradient ! "
        "tensor_converter ! tensor_transform mode=transpose option=0:2:1:3 ! "
        "filesink location={out}",
        golden)


def test_golden_clamp(tmp_path):
    frames = _src_frames(4, 16, 16)
    golden = b"".join(np.clip(f, 64, 192).tobytes() for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=4 width=16 height=16 pattern=gradient ! "
        "tensor_converter ! tensor_transform mode=clamp option=64:192 ! "
        "filesink location={out}",
        golden)


def test_golden_mux_two_sources(tmp_path):
    """Two lock-stepped sources mux into one 2-tensor frame; filesink dumps
    both memories per frame (reference tensor_mux SSAT group)."""
    a = _src_frames(5, 8, 8, "gradient")
    b = _src_frames(5, 8, 8, "black")
    golden = b"".join(x.tobytes() + y.tobytes() for x, y in zip(a, b))
    _run_golden(
        tmp_path,
        "tensor_mux name=m sync-mode=nosync ! filesink location={out} "
        "videotestsrc num-buffers=5 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! m. "
        "videotestsrc num-buffers=5 width=8 height=8 pattern=black ! "
        "tensor_converter ! m.",
        golden)


def test_golden_aggregator(tmp_path):
    """frames-in=1 frames-out=4 along the frame dim (nnstreamer dim 3 =
    numpy axis 0 for video): every output concatenates 4 inputs
    (reference tensor_aggregator SSAT group)."""
    frames = _src_frames(8, 8, 8)
    golden = b"".join(
        np.concatenate(frames[i:i + 4], axis=0).tobytes() for i in (0, 4))
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=8 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! tensor_aggregator frames-in=1 frames-out=4 "
        "frames-flush=4 frames-dim=3 concat=true ! filesink location={out}",
        golden)


def test_golden_sparse_roundtrip(tmp_path):
    """dense → sparse_enc → sparse_dec → identical bytes (reference
    tensor_sparse SSAT group)."""
    frames = _src_frames(4, 8, 8, "ball")  # mostly-zero pattern
    golden = b"".join(f.tobytes() for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=4 width=8 height=8 pattern=ball ! "
        "tensor_converter ! tensor_sparse_enc ! tensor_sparse_dec ! "
        "filesink location={out}",
        golden)


def test_golden_demux_pick(tmp_path):
    """mux two sources then demux-pick the second back out."""
    b = _src_frames(5, 8, 8, "black")
    golden = b"".join(y.tobytes() for y in b)
    _run_golden(
        tmp_path,
        "tensor_mux name=m sync-mode=nosync ! tensor_demux tensorpick=1 ! "
        "filesink location={out} "
        "videotestsrc num-buffers=5 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! m. "
        "videotestsrc num-buffers=5 width=8 height=8 pattern=black ! "
        "tensor_converter ! m.",
        golden)


def test_golden_filter_custom_easy(tmp_path):
    """Inference in the SSAT loop: deterministic fake backend (the
    reference's custom_example_scaler pattern)."""
    from nnstreamer_tpu.filters import register_custom_easy
    from nnstreamer_tpu.tensors.types import TensorsInfo

    info = TensorsInfo.from_str("3:16:16:1", "uint8")
    register_custom_easy(
        "golden_half", lambda ins: [(np.asarray(ins[0]) // 2).astype(
            np.uint8)], info, info)
    frames = _src_frames(5, 16, 16)
    golden = b"".join((f // 2).astype(np.uint8).tobytes() for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=5 width=16 height=16 pattern=gradient ! "
        "tensor_converter ! "
        "tensor_filter framework=custom-easy model=golden_half ! "
        "filesink location={out}",
        golden)


def test_golden_multifilesrc_roundtrip(tmp_path):
    """filesrc-family ingest: raw frame files → tensors → filesink dump
    equals the concatenated inputs (reference multifilesrc SSAT groups)."""
    rng = np.random.default_rng(7)
    frames = [rng.integers(0, 255, (8, 8, 3), dtype=np.uint8)
              for _ in range(3)]
    for i, f in enumerate(frames):
        (tmp_path / f"img_{i:03d}.raw").write_bytes(f.tobytes())
    golden = b"".join(f.tobytes() for f in frames)
    _run_golden(
        tmp_path,
        f"multifilesrc location={tmp_path}/img_%03d.raw ! "
        "tensor_converter input-dim=3:8:8:1 input-type=uint8 ! "
        "filesink location={out}",
        golden)


def test_golden_clamp_out_of_range_bounds(tmp_path):
    """Bounds outside the dtype's range saturate instead of overflowing
    (option=-1:300 on uint8 ≡ 0:255 — reference typed-math semantics)."""
    frames = _src_frames(2, 8, 8)
    golden = b"".join(f.tobytes() for f in frames)  # no-op clamp
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=2 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! tensor_transform mode=clamp option=-1:300 ! "
        "filesink location={out}",
        golden)


def test_golden_dimchg(tmp_path):
    """mode=dimchg option=0:2 moves dim 0 to position 2 (reference
    tensor_transform dimchg semantics) — pure relayout, byte-exact."""
    frames = _src_frames(3, 8, 6)  # rank-4 (1, H, W, C)
    # reference dims are innermost-first (C:W:H:N): option=0:2 moves
    # ref-dim 0 (C, numpy axis -1) to ref-slot 2 (numpy axis 1)
    golden = b"".join(np.moveaxis(f, 3, 1).tobytes() for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=3 width=8 height=6 pattern=gradient ! "
        "tensor_converter ! tensor_transform mode=dimchg option=0:2 ! "
        "filesink location={out}",
        golden)


def test_golden_split_seg(tmp_path):
    """tensor_split by size spec: first segment of the channel dim."""
    frames = _src_frames(3, 8, 8)
    golden = b"".join(f[..., :1].tobytes() for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=3 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! tensor_split name=s tensorseg=1,2 "
        "dimension=0  s. ! filesink location={out}  "
        "s. ! fakesink",
        golden)


def test_golden_merge_linear(tmp_path):
    """tensor_merge mode=linear option=<dim>: two streams concatenated
    along the channel dim (reference merge SSAT groups)."""
    frames = _src_frames(3, 8, 8)
    golden = b"".join(np.concatenate([f, f], axis=-1).tobytes()
                      for f in frames)
    _run_golden(
        tmp_path,
        "tensor_merge name=m mode=linear option=0 sync-mode=slowest ! "
        "filesink location={out}  "
        "videotestsrc num-buffers=3 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! m.  "
        "videotestsrc num-buffers=3 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! m.",
        golden)


def test_golden_tensor_if_skip(tmp_path):
    """tensor_if TENSOR_AVERAGE_VALUE: gradient frames average ~127, so
    `lt 200` is TRUE and then=SKIP drops every frame — the dump is empty
    because the SKIP action ran (not because an unlinked else pad
    swallowed the data)."""
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=3 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! tensor_if compared-value=TENSOR_AVERAGE_VALUE "
        "compared-value-option=0 operator=lt supplied-value=200 "
        "then=SKIP else=PASSTHROUGH ! filesink location={out}",
        b"")


def test_golden_tensor_if_passthrough(tmp_path):
    frames = _src_frames(2, 8, 8)
    golden = b"".join(f.tobytes() for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=2 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! tensor_if compared-value=TENSOR_AVERAGE_VALUE "
        "compared-value-option=0 operator=lt supplied-value=200 "
        "then=PASSTHROUGH else=SKIP ! filesink location={out}",
        golden)


def test_golden_quant_roundtrip_exact_on_integers(tmp_path):
    """tensor_quant_enc ! dec: uint8 sources dequantize byte-exact after
    typecast back (values 0..255 scale to int8 and back losslessly only
    when the frame max is representable — gradient's 0..255/127 scale is
    NOT lossless in general, so compare against the quant math itself)."""
    frames = _src_frames(2, 8, 8)
    from nnstreamer_tpu.elements.quant import quant_decode, quant_encode

    golden = b"".join(
        quant_decode(quant_encode(f.astype(np.float32)))[0].tobytes()
        for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=2 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! tensor_transform mode=typecast "
        "option=float32 ! tensor_quant_enc ! tensor_quant_dec ! "
        "filesink location={out}",
        golden)


def test_golden_named_pad_references(tmp_path):
    """gst-launch `name.pad` syntax: split's src_0/src_1 picked by NAME
    (order-independent in the description), so segment routing follows
    the pad INDEX, not mention order."""
    frames = _src_frames(3, 8, 8)
    golden = b"".join(f[..., 1:].tobytes() for f in frames)  # 2nd seg
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=3 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! tensor_split name=s tensorseg=1,2 "
        "dimension=0  s.src_1 ! filesink location={out}  "
        "s.src_0 ! fakesink",  # referenced AFTER src_1 — still segment 0
        golden)


def test_golden_named_sink_pads_fix_mux_order(tmp_path):
    """mux sink_N references pin which input lands in which tensor slot
    regardless of description order."""
    a = _src_frames(3, 8, 8, "gradient")
    b = _src_frames(3, 8, 8, "black")
    golden = b"".join(x.tobytes() + y.tobytes() for x, y in zip(a, b))
    _run_golden(
        tmp_path,
        "tensor_mux name=m sync-mode=nosync ! filesink location={out} "
        # black listed FIRST but pinned to slot 1; gradient to slot 0
        "videotestsrc num-buffers=3 width=8 height=8 pattern=black ! "
        "tensor_converter ! m.sink_1 "
        "videotestsrc num-buffers=3 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! m.sink_0",
        golden)


def test_named_pad_reference_errors():
    from nnstreamer_tpu import parse_launch

    with pytest.raises(ValueError, match="no src pad"):
        parse_launch(
            "videotestsrc num-buffers=1 ! tensor_converter ! "
            "tensor_sink name=k  k.bogus ! fakesink")
    with pytest.raises(ValueError, match="no src pad"):
        parse_launch(  # negative index is malformed, not pads[-1]
            "videotestsrc num-buffers=1 ! tensor_converter ! "
            "tensor_split name=s tensorseg=1,2 dimension=0 "
            "s.src_-1 ! fakesink")
    with pytest.raises(ValueError, match="never linked"):
        parse_launch(  # sink_0 implied by sink_1 but nothing feeds it
            "tensor_mux name=m sync-mode=nosync ! fakesink "
            "videotestsrc num-buffers=1 ! tensor_converter ! m.sink_1")
    with pytest.raises(ValueError, match="cannot grow"):
        parse_launch(  # fixed-pad element: ValueError, like every other
            # parse failure, not a leaked NotImplementedError
            "videotestsrc num-buffers=1 ! tensor_converter ! "
            "tensor_sink name=k  k.src_3 ! fakesink")


def test_named_sink_with_growing_src_side(tmp_path):
    """tee branch ending in a NAMED mux pad: the src side must use the
    element's request-pad growth, not fail on 'no free src pad'."""
    frames = _src_frames(2, 8, 8)
    golden = b"".join(f.tobytes() + f.tobytes() for f in frames)
    _run_golden(
        tmp_path,
        "videotestsrc num-buffers=2 width=8 height=8 pattern=gradient ! "
        "tensor_converter ! tee name=t  "
        "t. ! m.sink_0  t. ! m.sink_1  "
        "tensor_mux name=m sync-mode=nosync ! filesink location={out}",
        golden)
