"""Direct TensorFlow SavedModel / frozen-GraphDef ingestion
(filters/tf_backend.py; reference tensor_filter_tensorflow.cc runs TF
in-process — here the graph stages once through TF's XLA bridge to
StableHLO and then runs as an ordinary jittable XLA callee)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.filters.tf_backend import tf_model_entry  # noqa: E402

W = np.arange(12, dtype=np.float32).reshape(3, 4)


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("tfm") / "sm"

    class M(tf.Module):
        def __init__(self):
            self.w = tf.Variable(tf.constant(W))

        @tf.function(input_signature=[tf.TensorSpec([2, 3], tf.float32)])
        def __call__(self, x):
            return {"y": tf.matmul(x, self.w) + 1.0}

    tf.saved_model.save(M(), str(d))
    return str(d)


@pytest.fixture(scope="module")
def frozen_pb(tmp_path_factory, saved_model):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    sm = tf.saved_model.load(saved_model)
    frozen = convert_variables_to_constants_v2(
        sm.signatures["serving_default"])
    d = tmp_path_factory.mktemp("tfpb")
    tf.io.write_graph(frozen.graph.as_graph_def(), str(d), "frozen.pb",
                      as_text=False)
    inp = frozen.inputs[0].name.split(":")[0]
    outp = frozen.outputs[0].name.split(":")[0]
    return str(d / "frozen.pb"), inp, outp


class TestSavedModelIngestion:
    def test_numerics_match_tf(self, saved_model):
        e = tf_model_entry(saved_model)
        x = np.random.default_rng(0).normal(size=(2, 3)).astype(np.float32)
        got = np.asarray(e["fn"](x)[0])
        np.testing.assert_allclose(got, x @ W + 1.0, rtol=1e-5)

    def test_self_describing_info(self, saved_model):
        e = tf_model_entry(saved_model)
        assert [tuple(t.dim) for t in e["in_info"]] == [(3, 2)]
        assert [tuple(t.dim) for t in e["out_info"]] == [(4, 2)]

    def test_variables_frozen_not_lifted(self, saved_model):
        """Captured tf.Variables must become module constants, not extra
        StableHLO parameters (the staged signature must match the
        tensor stream exactly)."""
        e = tf_model_entry(saved_model)
        assert len(e["in_info"]) == 1

    def test_missing_signature_pointed_error(self, saved_model):
        with pytest.raises(ValueError, match="signature"):
            tf_model_entry(saved_model, custom="signature:nope")


class TestGraphDefIngestion:
    def test_numerics_match_tf(self, frozen_pb):
        path, inp, outp = frozen_pb
        e = tf_model_entry(path, custom=f"inputname:{inp},outputname:{outp}")
        x = np.random.default_rng(1).normal(size=(2, 3)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(e["fn"](x)[0]), x @ W + 1.0,
                                   rtol=1e-5)

    def test_names_required(self, frozen_pb):
        with pytest.raises(ValueError, match="inputname"):
            tf_model_entry(frozen_pb[0])


class TestPipeline:
    def test_framework_tensorflow_golden(self, tmp_path):
        """framework=tensorflow model=<SavedModel dir> runs a golden
        pipeline end to end (VERDICT r3 item 7 done criterion)."""

        class Vision(tf.Module):
            @tf.function(input_signature=[
                tf.TensorSpec([1, 4, 4, 3], tf.uint8)])
            def __call__(self, x):
                xf = tf.cast(x, tf.float32)
                return {"mean": tf.reduce_mean(xf, axis=[1, 2, 3])}

        sm = tmp_path / "vision_sm"
        tf.saved_model.save(Vision(), str(sm))
        pipe = parse_launch(
            "videotestsrc num-buffers=3 width=4 height=4 "
            "pattern=gradient ! tensor_converter ! "
            f"tensor_filter framework=tensorflow model={sm} ! "
            "tensor_sink name=out")
        msg = pipe.run(timeout=120)
        assert msg is not None and msg.kind == "eos", msg
        outs = pipe.get("out").buffers
        assert len(outs) == 3
        from nnstreamer_tpu.elements.source import VideoTestSrc

        want = float(VideoTestSrc(width=4, height=4, pattern="gradient")
                     ._frame(0).astype(np.float32).mean())
        got = float(np.asarray(outs[0].tensors[0])[0])
        assert abs(got - want) < 1e-3

    def test_framework_jax_delegates_saved_model(self, tmp_path):
        """framework=jax with a SavedModel path ingests in-process too
        (the old recipe error only remains when TF is unavailable)."""

        class Tiny(tf.Module):
            @tf.function(input_signature=[tf.TensorSpec([1, 2],
                                                        tf.float32)])
            def __call__(self, x):
                return {"y": x * 2.0}

        sm = tmp_path / "tiny_sm"
        tf.saved_model.save(Tiny(), str(sm))
        from nnstreamer_tpu.filters.jax_backend import JaxFilter
        from nnstreamer_tpu.filters.api import FilterProperties

        f = JaxFilter()
        entry = f._load(str(sm), FilterProperties(model=str(sm)))
        np.testing.assert_allclose(
            np.asarray(entry["fn"](np.ones((1, 2), np.float32))[0]),
            [[2.0, 2.0]])


def test_custom_multi_names_survive_parsing():
    """';'-separated multi-tensor-name lists in custom must survive the
    option parser (inputname:x1;x2,outputname:y)."""
    from nnstreamer_tpu.filters.api import parse_custom

    opts = parse_custom("inputname:x1;x2,outputname:y")
    assert opts == {"inputname": "x1;x2", "outputname": "y"}


def test_dynamic_batch_pinned_by_input_info(tmp_path):
    """A SavedModel with a dynamic batch dim needs static shapes for
    XLA: the tensor_filter input property (innermost-first dims) pins
    it; without pinning the error names the remedy."""

    class Dyn(tf.Module):
        @tf.function(input_signature=[tf.TensorSpec([None, 3],
                                                    tf.float32)])
        def __call__(self, x):
            return {"y": x * 3.0}

    sm = tmp_path / "dyn_sm"
    tf.saved_model.save(Dyn(), str(sm))

    with pytest.raises(ValueError, match="dynamic|static"):
        tf_model_entry(str(sm))

    from nnstreamer_tpu.tensors.types import TensorsInfo

    e = tf_model_entry(str(sm),
                       props_in_info=TensorsInfo.from_str("3:2", "float32"))
    x = np.ones((2, 3), np.float32)
    np.testing.assert_allclose(np.asarray(e["fn"](x)[0]), x * 3.0)
