"""Prefix-cache entries as accounted, droppable HBM (satellite fix).

Before this PR the trie-backed prefix cache held device arrays that
never registered with the HBM accountant — invisible bytes the pressure
ladder could neither see nor reclaim. Now every monolithic prefix entry
registers under the ``kvcache`` category as a DROPPABLE residency unit:
eviction surrenders the bytes (on_drop condemns the key; the engine
thread reaps), and LRU turnover un-registers as entries rotate out.
(The paged engine needs none of this per-entry machinery — its entries
are refcounts on pool blocks, and the arena itself is one registered
``kvcache`` unit, covered in test_kvpool.py.)"""

import gc
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu.serving import ContinuousBatchingEngine  # noqa: E402
from nnstreamer_tpu.tensors import memory  # noqa: E402
from tests.test_serving import CFG, PARAMS, reference_greedy  # noqa: E402


@pytest.fixture(autouse=True)
def _budget():
    memory.deactivate()
    budget = memory.activate(1 << 30)
    # the budget's counters are registry-global singletons; tests
    # elsewhere assert their ABSOLUTE values, so put back every tick
    # these tests add
    flat = [budget._m["evictions"], budget._m["prefetches"],
            *budget._m["pressure"].values()]
    saved = [c.value for c in flat]
    yield budget
    memory.deactivate()
    for c, v in zip(flat, saved):
        c._value = v


def _kv_bytes(budget):
    return budget.snapshot()["used_by_category"].get("kvcache", 0)


def _prefix_units(budget):
    return [u for u in budget.residency.snapshot()["units"]
            if ":prefix" in u["label"]]


# -- the residency primitive ----------------------------------------------


def test_droppable_unit_accounting(_budget):
    dropped = []
    _budget.residency.register_droppable(
        "t:prefix:0", 1000, dropped.append, label="t:prefix")
    assert _kv_bytes(_budget) == 1000
    assert _budget.residency.evict_all() == 1000
    assert dropped == ["t:prefix:0"]      # owner told to surrender
    assert _kv_bytes(_budget) == 0
    # unregister (owner closed) releases bytes WITHOUT the callback
    _budget.residency.register_droppable(
        "t:prefix:1", 500, dropped.append, label="t:prefix")
    _budget.residency.unregister("t:prefix:1")
    assert _kv_bytes(_budget) == 0
    assert dropped == ["t:prefix:0"]


# -- the engine's prefix cache rides it -----------------------------------


PROMPT_A = [7, 3, 9, 1, 4, 6, 2, 8, 5, 11]
PROMPT_B = [13, 17, 19, 23, 29, 31, 37, 41]
PROMPT_C = [2, 4, 6, 8, 10, 12, 14, 16, 18]


def mono_engine(**kw):
    kw.setdefault("max_streams", 2)
    kw.setdefault("steps_per_dispatch", 4)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("prefix_cache", 2)
    return ContinuousBatchingEngine(CFG, PARAMS, **kw).start()


def test_prefix_entries_register_kvcache_bytes(_budget):
    eng = mono_engine()
    try:
        assert not eng.paged
        eng.generate(PROMPT_A, max_new_tokens=4, timeout=120)
        used = _kv_bytes(_budget)
        assert used > 0, "prefix entry bytes invisible to the accountant"
        units = _prefix_units(_budget)
        assert len(units) == 1
        assert units[0]["category"] == "kvcache"
        assert sum(u["nbytes"] for u in units) == used
    finally:
        eng.stop()
    # engine teardown releases the entries' accounting
    del eng
    gc.collect()


def test_lru_turnover_unregisters_bytes(_budget):
    eng = mono_engine(prefix_cache=2)
    try:
        for p in (PROMPT_A, PROMPT_B):
            eng.generate(p, max_new_tokens=4, timeout=120)
        two = _kv_bytes(_budget)
        assert len(_prefix_units(_budget)) == 2
        # third distinct prompt: capacity 2 evicts the LRU entry and its
        # bytes leave the ledger with it
        eng.generate(PROMPT_C, max_new_tokens=4, timeout=120)
        assert len(_prefix_units(_budget)) == 2
        assert len(eng._prefix) == 2
        assert _kv_bytes(_budget) <= two + max(
            u["nbytes"] for u in _prefix_units(_budget))
        # the ledger tracks exactly the live entries
        assert _kv_bytes(_budget) == sum(
            u["nbytes"] for u in _prefix_units(_budget))
    finally:
        eng.stop()


def test_pressure_eviction_drops_entries_and_serving_continues(_budget):
    eng = mono_engine()
    try:
        want = reference_greedy(PROMPT_A, 6)
        assert eng.generate(PROMPT_A, max_new_tokens=6,
                            timeout=120) == want
        assert _kv_bytes(_budget) > 0
        # pressure-ladder rung 1: the accountant revokes droppable units
        freed = _budget.residency.evict_all()
        assert freed > 0
        assert _kv_bytes(_budget) == 0    # bytes surrendered immediately
        assert eng._condemned               # reap pending, engine-side
        # serving continues — the next request both reaps the condemned
        # entry and re-decodes exactly (the cache is an optimization,
        # never a correctness dependency)
        assert eng.generate(PROMPT_A, max_new_tokens=6,
                            timeout=120) == want
        deadline = time.monotonic() + 10
        while eng._condemned and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not eng._condemned
        # the re-decode re-stored the prefix: accounted again
        assert _kv_bytes(_budget) > 0
    finally:
        eng.stop()
