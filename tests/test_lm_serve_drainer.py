"""Drainer retirement vs late completions (elements/lm_serve.py).

The framed protocol's contract is one response per request, in order.
A per-client drainer retires after ``idle_timeout`` of silence — but a
completion can land in the fifo in the window between the idle timeout
firing and the drainer unregistering itself. The old code dropped that
item (and desynced every later response for the client); the fix drains
orphans after unregistering and hands them to a fresh drainer.

``RacyQueue`` makes the window deterministic: its first blocking get()
raises Empty *after* planting the late completion, exactly the
interleaving the wild race produces."""

import queue as _queue
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.serving import (  # noqa: E402
    ContinuousBatchingEngine,
    register_engine,
    unregister_engine,
)
from nnstreamer_tpu.tensors.buffer import TensorBuffer  # noqa: E402
from tests.test_serving import CFG, PARAMS, reference_greedy  # noqa: E402


class RacyQueue(_queue.Queue):
    """First blocking get() plants ``late_item`` then raises Empty —
    the completion arrives exactly as the idle window closes."""

    def __init__(self, late_item):
        super().__init__()
        self._late = late_item
        self._raced = False
        self._lied = False

    def get(self, block=True, timeout=None):
        if block and not self._raced:
            self._raced = True
            super().put(self._late)
            raise _queue.Empty
        return super().get(block=block, timeout=timeout)

    def empty(self):
        # an empty() probe at retirement is exactly the TOCTOU the fix
        # removes: lie True once, as a real race would have it — code
        # that trusts the probe drops the item; code that drains via
        # get_nowait() delivers it
        if not self._lied:
            self._lied = True
            return True
        return super().empty()


@pytest.fixture
def race_rig():
    engine = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0).start()
    register_engine("lm_race", engine)
    pipe = parse_launch(
        "appsrc name=src ! tensor_lm_serve engine=lm_race "
        "max-new-tokens=4 idle-timeout=0.05 name=serve ! "
        "tensor_sink name=out to-host=true")
    outs = []
    pipe.get("out").connect(lambda b: outs.append(b))
    pipe.start()
    yield engine, pipe, outs
    pipe.stop()
    engine.stop()
    unregister_engine("lm_race")


def test_completion_racing_retirement_is_not_dropped(race_rig):
    engine, pipe, outs = race_rig
    serve = pipe.get("serve")
    prompt = [5, 11, 23]
    stream = engine.submit(prompt, max_new_tokens=4)
    # completed BEFORE the drainer ever sees it (poll the flag —
    # result() is one-shot and belongs to the drainer)
    deadline = time.monotonic() + 120
    while not stream.finished and time.monotonic() < deadline:
        time.sleep(0.01)
    assert stream.finished
    buf = TensorBuffer([np.asarray(prompt, np.int32)], pts=0,
                       meta={"query_client_id": 9})
    fifo = RacyQueue((stream, buf, None, time.monotonic()))
    with serve._state_lock:
        serve._fifos[9] = fifo
        serve._inflight += 1
        t = threading.Thread(target=serve._drain, args=(9, fifo),
                             daemon=True)
        serve._drainers[9] = t
    t.start()
    deadline = time.monotonic() + 30
    while not outs and time.monotonic() < deadline:
        time.sleep(0.02)
    assert outs, "late completion was dropped at drainer retirement"
    assert np.asarray(outs[0].tensors[0]).tolist() == \
        reference_greedy(prompt, 4)
    assert outs[0].meta["lm_finish_reason"] in ("eos", "length")
    # the adopting drainer retires cleanly too — no fifo leak
    deadline = time.monotonic() + 10
    while 9 in serve._fifos and time.monotonic() < deadline:
        time.sleep(0.02)
    assert 9 not in serve._fifos and 9 not in serve._drainers


def test_retirement_hammering_answers_every_request(race_rig):
    """Stochastic cousin: requests spaced ~one idle window apart, so
    retirement and arrival interleave constantly. Every request must
    still get exactly one in-order response."""
    engine, pipe, outs = race_rig
    serve = pipe.get("serve")
    prompts = [[4, 8, 15], [16, 23], [42, 7, 9, 1], [2, 2], [9, 9, 9],
               [13, 2], [31, 5], [1, 2, 3]]
    for i, p in enumerate(prompts):
        serve._chain_entry(serve.sinkpads[0], TensorBuffer(
            [np.asarray(p, np.int32)], pts=i,
            meta={"query_client_id": 7}))
        time.sleep(0.05)  # ~= idle-timeout: maximal retirement churn
    deadline = time.monotonic() + 120
    while len(outs) < len(prompts) and time.monotonic() < deadline:
        time.sleep(0.05)
    assert len(outs) == len(prompts)
    got = [np.asarray(b.tensors[0]).tolist() for b in outs]
    assert got == [reference_greedy(p, 4) for p in prompts]
