"""Tooling and decoder additions: confchk, element-restriction allowlist,
text overlay, ov-person-detection decoder mode."""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.cli import main as cli_main
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def test_confchk_runs(capsys):
    assert cli_main(["--confchk"]) == 0
    out = capsys.readouterr().out
    assert "tensor_filter" in out and "jax" in out
    assert "element restriction : disabled" in out


def test_element_restriction_allowlist(monkeypatch):
    from nnstreamer_tpu.config import ENV_PREFIX, get_conf

    monkeypatch.setenv(f"{ENV_PREFIX}ELEMENT-RESTRICTION_ENABLE", "true")
    monkeypatch.setenv(f"{ENV_PREFIX}ELEMENT-RESTRICTION_RESTRICTED_ELEMENTS",
                       "videotestsrc,tensor_converter,fakesink")
    get_conf(refresh=True)
    try:
        # allowed chain parses
        parse_launch("videotestsrc num-buffers=1 ! tensor_converter ! "
                     "fakesink")
        # tensor_transform is not in the allowlist
        with pytest.raises(ValueError, match="allowlist"):
            parse_launch("videotestsrc ! tensor_transform mode=typecast "
                         "option=float32 ! fakesink")
    finally:
        monkeypatch.delenv(f"{ENV_PREFIX}ELEMENT-RESTRICTION_ENABLE")
        get_conf(refresh=True)


def test_draw_text_overlay():
    from nnstreamer_tpu.decoders.overlay import draw_text, text_extent

    img = np.zeros((20, 80, 4), np.uint8)
    draw_text(img, 1, 1, "AB 9", color=(255, 0, 0, 255))
    assert img[:, :, 0].sum() > 0          # pixels rendered in red channel
    assert img[:, :, 1].sum() == 0
    w, h = text_extent("AB 9")
    assert h == 7 and w == 4 * 6 - 1
    # out-of-bounds rendering must not crash
    draw_text(img, 76, 18, "XYZ")


def test_bounding_boxes_ov_person_mode():
    from nnstreamer_tpu.registry import DECODER, get_subplugin

    dec = get_subplugin(DECODER, "bounding_boxes")()
    rows = np.array([
        [0, 1, 0.95, 0.10, 0.20, 0.40, 0.60],
        [0, 1, 0.50, 0.50, 0.50, 0.90, 0.90],   # below 0.8 threshold
        [-1, 0, 0.0, 0, 0, 0, 0],                # end marker
        [0, 1, 0.99, 0.0, 0.0, 1.0, 1.0],        # after end: ignored
    ], np.float32).reshape(1, 1, 4, 7)
    buf = TensorBuffer([rows])
    out = dec.decode(buf, None, {"option1": "ov-person-detection",
                                 "option4": "100:100", "option7": "meta"})
    dets = out.meta["detections"]
    assert len(dets) == 1
    assert dets[0]["score"] == pytest.approx(0.95)
    # box is [y1, x1, y2, x2]
    assert dets[0]["box"] == pytest.approx([0.2, 0.1, 0.6, 0.4])


def test_bounding_boxes_overlay_labels(tmp_path):
    """Overlay mode renders label text pixels beyond the box outline."""
    from nnstreamer_tpu.registry import DECODER, get_subplugin

    labels = tmp_path / "labels.txt"
    labels.write_text("bg\nperson\n")
    dec = get_subplugin(DECODER, "bounding_boxes")()
    rows = np.array([[0, 1, 0.9, 0.2, 0.3, 0.8, 0.9]],
                    np.float32).reshape(1, 1, 1, 7)
    out = dec.decode(TensorBuffer([rows]), None,
                     {"option1": "ov-person-detection",
                      "option2": str(labels), "option4": "100:100"})
    overlay = out.tensors[0]
    assert overlay.shape == (100, 100, 4)
    box_only = 2 * (80 - 20) + 2 * (60 - 30) + 4  # rough outline pixel count
    assert (overlay[:, :, 1] == 255).sum() > box_only  # text adds pixels


def test_scaffold_generates_working_subplugins(tmp_path, monkeypatch):
    """--scaffold output must be discoverable via the external search path
    and runnable in a pipeline unmodified (reference codegen tool parity)."""
    import numpy as np

    from nnstreamer_tpu import parse_launch
    from nnstreamer_tpu.cli import scaffold

    for kind in ("filter", "decoder", "converter"):
        assert scaffold(kind, "genx", str(tmp_path)) == 0
        assert (tmp_path / f"nnstreamer_tpu_{kind}_genx.py").exists()
    # duplicate refuses
    assert scaffold("filter", "genx", str(tmp_path)) == 2
    assert scaffold("bogus", "x", str(tmp_path)) == 2
    assert scaffold("filter", "bad name!", str(tmp_path)) == 2

    monkeypatch.setenv("NNSTREAMER_TPU_FILTER_PATH", str(tmp_path))
    monkeypatch.setenv("NNSTREAMER_TPU_DECODER_PATH", str(tmp_path))
    from nnstreamer_tpu.config import get_conf
    get_conf(refresh=True)

    pipe = parse_launch(
        "appsrc name=src ! tensor_transform mode=typecast option=float32 ! "
        "tensor_filter framework=genx model=unused ! "
        "tensor_decoder mode=genx ! tensor_sink name=sink")
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    try:
        src.push([np.ones((4, 4), np.uint8)])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    assert len(sink.buffers) == 1
    np.testing.assert_allclose(np.asarray(sink.buffers[0][0]),
                               np.ones((4, 4), np.float32))


def test_scaffold_edge_names(tmp_path):
    """Keyword / digit-leading / import-shadowing names must still produce
    importable files with valid class names (code-review regression)."""
    import ast

    from nnstreamer_tpu.cli import scaffold

    for kind, name in (("decoder", "none"), ("filter", "_1a"),
                       ("decoder", "caps")):
        assert scaffold(kind, name, str(tmp_path)) == 0
        src = (tmp_path / f"nnstreamer_tpu_{kind}_{name}.py").read_text()
        tree = ast.parse(src)  # would raise SyntaxError for class None/1a
        cls_names = [n.name for n in ast.walk(tree)
                     if isinstance(n, ast.ClassDef)]
        assert cls_names and cls_names[0] not in ("None", "Caps")


def test_bounding_boxes_ov_face_is_ov_person_codepath():
    """The reference routes ov-face-detection through the IDENTICAL code
    path as ov-person-detection — one branch for both modes at caps
    check (tensordec-boundingbox.c:793-794) and at decode (:1307-1308),
    same [7,N,1,1] row format (image_id, label, conf, x_min, y_min,
    x_max, y_max), same 0.8 confidence threshold, same early-exit at
    image_id < 0. Our alias must therefore decode byte-identical
    vectors identically under both mode names."""
    from nnstreamer_tpu.registry import DECODER, get_subplugin

    rows = np.array([
        [0, 1, 0.95, 0.10, 0.20, 0.40, 0.60],
        [0, 2, 0.81, 0.05, 0.05, 0.15, 0.25],
        [0, 1, 0.79, 0.50, 0.50, 0.90, 0.90],   # below 0.8 threshold
        [-1, 0, 0.0, 0, 0, 0, 0],                # end marker
        [0, 1, 0.99, 0.0, 0.0, 1.0, 1.0],        # after end: ignored
    ], np.float32).reshape(1, 1, 5, 7)
    outs = {}
    for mode in ("ov-person-detection", "ov-face-detection"):
        dec = get_subplugin(DECODER, "bounding_boxes")()
        out = dec.decode(TensorBuffer([rows.copy()]), None,
                         {"option1": mode, "option4": "672:384",
                          "option7": "meta"})
        outs[mode] = out.meta["detections"]
    assert outs["ov-face-detection"] == outs["ov-person-detection"]
    dets = outs["ov-face-detection"]
    assert len(dets) == 2  # threshold + early-exit applied

    # cross-check against the reference's pixel math
    # (_get_persons_ov, tensordec-boundingbox.c:1075-1112):
    #   x = x_min*w, y = y_min*h, width = (x_max-x_min)*w,
    #   height = (y_max-y_min)*h, for w=672 h=384
    y1, x1, y2, x2 = dets[0]["box"]
    assert (int(x1 * 672), int(y1 * 384)) == (67, 76)
    assert (int((x2 - x1) * 672), int((y2 - y1) * 384)) == (201, 153)


def test_config_allowed_elements_api(monkeypatch):
    """Conf.allowed_elements: off -> None; on -> parsed set, accepting
    the reference's space-separated allowed-elements format."""
    from nnstreamer_tpu.config import ENV_PREFIX, get_conf

    assert get_conf(refresh=True).allowed_elements() is None
    monkeypatch.setenv(f"{ENV_PREFIX}ELEMENT-RESTRICTION_ENABLE", "true")
    monkeypatch.setenv(
        f"{ENV_PREFIX}ELEMENT-RESTRICTION_ALLOWED_ELEMENTS",
        "videotestsrc tensor_converter tee,queue")  # mixed separators
    try:
        allowed = get_conf(refresh=True).allowed_elements()
        assert allowed == {"videotestsrc", "tensor_converter", "tee",
                           "queue"}
        with pytest.raises(ValueError, match="allowlist"):
            parse_launch("videotestsrc ! tensor_transform mode=typecast "
                         "option=float32 ! fakesink")
    finally:
        monkeypatch.delenv(f"{ENV_PREFIX}ELEMENT-RESTRICTION_ENABLE")
        get_conf(refresh=True)
