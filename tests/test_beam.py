"""Beam search (models/beam.py).

The load-bearing check is teacher-forced re-scoring: every returned
hypothesis's score must equal the sum of its tokens' log-probabilities
under an independent full-forward pass — that catches parent-gather and
cache-reorder bugs that shape checks cannot.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu.models.beam import BeamSearcher  # noqa: E402
from nnstreamer_tpu.models.transformer import build_forward  # noqa: E402
from tests.test_serving import (  # noqa: E402 — SAME model as the greedy
    # reference, so width-1 comparison can't silently diverge
    CFG,
    PARAMS,
    reference_greedy,
)

FWD = jax.jit(build_forward(CFG))  # hoisted: one compile for all rescores


def rescore(prompt, seq):
    """Teacher-forced sum of the emitted tokens' log-probs."""
    fwd = FWD
    toks = jnp.asarray(np.concatenate(
        [np.asarray(prompt, np.int32), np.asarray(seq, np.int32)])[None])
    logp = jax.nn.log_softmax(
        fwd(PARAMS, toks)[0].astype(jnp.float32), axis=-1)
    n = len(prompt)
    return float(sum(logp[n + j - 1, seq[j]] for j in range(len(seq))))


def test_width_one_is_greedy():
    prompt = [5, 11, 23, 42]
    bs = BeamSearcher(CFG, PARAMS, beam_width=1, max_new=10)
    seqs, scores = bs.search(prompt)
    assert seqs.shape == (1, 10)
    assert seqs[0].tolist() == reference_greedy(prompt, 10)


def test_scores_match_teacher_forced_rescoring():
    prompt = [7, 3, 11, 30]
    bs = BeamSearcher(CFG, PARAMS, beam_width=4, max_new=8)
    seqs, scores = bs.search(prompt)
    assert list(scores) == sorted(scores, reverse=True)
    for seq, score in zip(seqs, scores):
        assert score == pytest.approx(rescore(prompt, seq.tolist()),
                                      abs=2e-3), seq
    # the best beam must score at least as well as pure greedy
    greedy = reference_greedy(prompt, 8)
    assert scores[0] >= rescore(prompt, greedy) - 2e-3


def test_beams_are_distinct_hypotheses():
    bs = BeamSearcher(CFG, PARAMS, beam_width=4, max_new=6)
    seqs, _ = bs.search([9, 21, 33])
    assert len({tuple(s) for s in seqs.tolist()}) == len(seqs)


def test_eos_freezes_beam():
    prompt = [5, 11, 23, 42]
    greedy = reference_greedy(prompt, 8)
    eos = greedy[2]  # a token the search will actually emit
    bs = BeamSearcher(CFG, PARAMS, beam_width=3, max_new=8, eos_id=eos)
    seqs, scores = bs.search(prompt)
    for seq in seqs.tolist():
        if eos in seq:
            first = seq.index(eos)
            assert all(t == eos for t in seq[first:]), seq
    # frozen score == rescore of the pre-EOS prefix plus the EOS itself
    best = seqs[0].tolist()
    if eos in best:
        upto = best.index(eos) + 1
        assert scores[0] == pytest.approx(
            rescore(prompt, best[:upto]), abs=2e-3)


def test_validation():
    # capacity boundary: n = S - max_new + 1 is EXACTLY admissible
    with pytest.raises(ValueError):
        BeamSearcher(CFG, PARAMS, beam_width=0)
    with pytest.raises(ValueError):
        BeamSearcher(CFG, PARAMS, beam_width=CFG.vocab + 1)
    bs = BeamSearcher(CFG, PARAMS, beam_width=2, max_new=10)
    with pytest.raises(ValueError):
        bs.search(list(range(1, CFG.max_seq)))  # no room for max_new
    n_edge = CFG.max_seq - 10 + 1  # last decode write lands on slot S-1
    seqs, _ = bs.search(list(range(1, n_edge + 1)))
    assert seqs.shape == (2, 10)
