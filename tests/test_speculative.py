"""Speculative decoding (models/speculative.py) + chunked KV decode.

The hard invariant: speculative greedy output is TOKEN-IDENTICAL to
target-only greedy decode — speculation may only change the schedule.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu.models.speculative import (  # noqa: E402
    SpeculativeDecoder,
    build_speculative_round,
    draft_from_target,
)
from nnstreamer_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    build_chunk_decode,
    build_decode_step,
    build_prefill,
    init_params,
)

TARGET = TransformerConfig(vocab=128, d_model=64, n_heads=4, n_layers=3,
                           d_ff=128, max_seq=96, dtype=jnp.float32)
DRAFT = TransformerConfig(vocab=128, d_model=32, n_heads=2, n_layers=1,
                          d_ff=64, max_seq=96, dtype=jnp.float32)
T_PARAMS = init_params(TARGET, seed=1)
D_PARAMS = init_params(DRAFT, seed=2)


def target_greedy(prompt, n_tokens, cfg=TARGET, params=T_PARAMS):
    prefill = jax.jit(build_prefill(cfg))
    decode = jax.jit(build_decode_step(cfg))
    logits, cache = prefill(params,
                            jnp.asarray(np.asarray(prompt, np.int32)[None]))
    out = [int(jnp.argmax(logits[0]))]
    tok = jnp.asarray([out[0]], jnp.int32)
    pos = jnp.asarray(len(prompt), jnp.int32)
    for _ in range(n_tokens - 1):
        logits, cache = decode(params, tok, cache, pos)
        out.append(int(jnp.argmax(logits[0])))
        tok = jnp.asarray([out[-1]], jnp.int32)
        pos = pos + 1
    return out


def test_chunk_decode_matches_sequential_steps():
    """One c-token chunk pass == c single-token steps (logits + cache)."""
    prefill = jax.jit(build_prefill(TARGET))
    decode = jax.jit(build_decode_step(TARGET))
    chunk = jax.jit(build_chunk_decode(TARGET))
    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    _, cache_a = prefill(T_PARAMS, prompt)
    _, cache_b = prefill(T_PARAMS, prompt)
    toks = jnp.asarray([[9, 2, 6, 5]], jnp.int32)
    chunk_logits, cache_a = chunk(T_PARAMS, toks, cache_a, 5)
    seq_logits = []
    for i in range(4):
        lg, cache_b = decode(T_PARAMS, toks[:, i], cache_b,
                             jnp.asarray(5 + i, jnp.int32))
        seq_logits.append(lg)
    seq_logits = jnp.stack(seq_logits, axis=1)
    np.testing.assert_allclose(np.asarray(chunk_logits),
                               np.asarray(seq_logits), rtol=2e-5,
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(cache_a), np.asarray(cache_b),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("gamma", [1, 3, 5])
def test_speculative_matches_target_greedy(gamma):
    prompt = [7, 21, 9, 63, 2]
    ref = target_greedy(prompt, 24)
    dec = SpeculativeDecoder(TARGET, T_PARAMS, DRAFT, D_PARAMS,
                             gamma=gamma)
    assert dec.generate(prompt, max_new_tokens=24) == ref
    assert dec.stats["rounds"] >= 1


def test_perfect_draft_accepts_everything():
    """Draft == target: every round must emit γ+1 tokens — exercising the
    full-acceptance path (incl. the d_γ draft-cache write)."""
    prompt = [5, 8, 13]
    ref = target_greedy(prompt, 21)
    dec = SpeculativeDecoder(TARGET, T_PARAMS, TARGET, T_PARAMS, gamma=4)
    got = dec.generate(prompt, max_new_tokens=21)
    assert got == ref
    assert dec.mean_accepted == pytest.approx(5.0)  # γ+1 per round


def test_speculative_respects_cache_window():
    """Generation stops before a round's writes would spill past S."""
    prompt = list(range(1, 80))  # 79 of S=96
    dec = SpeculativeDecoder(TARGET, T_PARAMS, DRAFT, D_PARAMS, gamma=6)
    got = dec.generate(prompt, max_new_tokens=64)
    ref = target_greedy(prompt, len(got))
    assert got == ref
    assert 1 <= len(got) < 64


def test_self_speculative_draft_matches_target_greedy():
    """Depth-pruned draft (target's first layer + shared embed) must
    still be exact — and typically accepts more than a random draft."""
    d_cfg, d_params = draft_from_target(TARGET, T_PARAMS, 1)
    prompt = [11, 3, 77, 19]
    ref = target_greedy(prompt, 20)
    dec = SpeculativeDecoder(TARGET, T_PARAMS, d_cfg, d_params, gamma=3,
                             rounds_per_dispatch=3)
    assert dec.generate(prompt, max_new_tokens=20) == ref
    assert dec.mean_accepted >= 1.0


def test_fused_generation_matches_target_greedy():
    """The single-program while_loop path (fused=True) must be exact too,
    and report acceptance stats."""
    prompt = [7, 21, 9, 63, 2]
    ref = target_greedy(prompt, 24)
    dec = SpeculativeDecoder(TARGET, T_PARAMS, DRAFT, D_PARAMS, gamma=3)
    got = dec.generate(prompt, max_new_tokens=24, fused=True)
    assert got == ref
    assert dec.stats["dispatches"] == 1
    assert dec.stats["rounds"] >= 1
    # window-limited fused run stays exact as well
    long_prompt = list(range(1, 80))
    got2 = dec.generate(long_prompt, max_new_tokens=64, fused=True)
    assert got2 == target_greedy(long_prompt, len(got2))
    assert 1 <= len(got2) < 64


def test_multi_round_dispatch_counts():
    """R rounds per dispatch: host syncs = ceil(rounds / R)."""
    prompt = [2, 4, 6]
    dec = SpeculativeDecoder(TARGET, T_PARAMS, DRAFT, D_PARAMS, gamma=2,
                             rounds_per_dispatch=4)
    got = dec.generate(prompt, max_new_tokens=16)
    assert got == target_greedy(prompt, 16)
    assert dec.stats["dispatches"] <= dec.stats["rounds"]
    assert dec.stats["rounds"] <= dec.stats["dispatches"] * 4


def test_moe_target_speculative_exact():
    """MoE target + depth-pruned MoE draft: chunk verify must route
    experts identically to sequential decode (exactness holds)."""
    moe = TransformerConfig(vocab=128, d_model=64, n_heads=4, n_layers=2,
                            d_ff=64, max_seq=96, dtype=jnp.float32,
                            num_experts=4)
    moe_params = init_params(moe, seed=9)
    d_cfg, d_params = draft_from_target(moe, moe_params, 1)
    dec = SpeculativeDecoder(moe, moe_params, d_cfg, d_params, gamma=3)
    prompt = [7, 21, 9]
    assert dec.generate(prompt, max_new_tokens=15) == target_greedy(
        prompt, 15, cfg=moe, params=moe_params)


def test_config_validation():
    with pytest.raises(ValueError):
        build_speculative_round(
            TARGET,
            TransformerConfig(vocab=64, d_model=32, n_heads=2,
                              n_layers=1, d_ff=64), gamma=2)
    with pytest.raises(ValueError):
        build_speculative_round(TARGET, DRAFT, gamma=0)
    dec = SpeculativeDecoder(TARGET, T_PARAMS, DRAFT, D_PARAMS, gamma=2)
    with pytest.raises(ValueError):
        dec.generate([], max_new_tokens=4)
    with pytest.raises(ValueError):
        draft_from_target(TARGET, T_PARAMS, 0)
