"""Overlap layer: dispatch window, ingest buffer pool, batch-drain queues.

The contract under test (pipeline/dispatch.py, tensors/pool.py, the Queue
drain loop): pipelining host and device work must be OBSERVABLY free —
per-frame outputs and their ordering are byte-identical at every
``inflight`` setting, EOS flushes a non-empty window, recycled staging
buffers never alias live data, and list hand-offs preserve per-buffer
semantics (stats, ordering, events serialized).
"""

import gc
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.jax_backend import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.pipeline.dispatch import POOL_STASH_META, DispatchWindow
from nnstreamer_tpu.pipeline.element import Element, EosEvent, FlowReturn
from nnstreamer_tpu.pipeline.pipeline import Pipeline, Queue, SourceElement
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.pool import BufferPool, _size_class, get_pool
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType


# -- shared helpers -----------------------------------------------------------


class _NumSrc(SourceElement):
    """Counts 0..n-1 as 1-elem float32 tensors."""

    ELEMENT_NAME = "_numsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 5}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig

        cfg = TensorsConfig.from_arrays([np.zeros((1,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        buf = TensorBuffer([np.array([float(self.i)], np.float32)],
                           pts=self.i * 1000)
        self.i += 1
        return buf


class _Collect(Element):
    ELEMENT_NAME = "_collect"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.buffers = []
        self.got_eos = False

    def chain(self, pad, buf):
        self.buffers.append(buf)
        return FlowReturn.OK

    def sink_event(self, pad, event):
        if isinstance(event, EosEvent):
            self.got_eos = True


@pytest.fixture
def linear_model():
    import jax.numpy as jnp

    w = jnp.full((4, 3), 0.5, jnp.float32)

    def fn(params, x):
        return x.astype(jnp.float32) @ params

    in_info = TensorsInfo([TensorInfo(dim=(4, 8), type=TensorType.FLOAT32)])
    out_info = TensorsInfo([TensorInfo(dim=(3, 8), type=TensorType.FLOAT32)])
    register_jax_model("overlap_linear", fn, w, in_info=in_info,
                       out_info=out_info)
    yield "overlap_linear"
    unregister_jax_model("overlap_linear")


# -- buffer pool --------------------------------------------------------------


class TestBufferPool:
    def test_size_classes(self):
        assert _size_class(1) == 256
        assert _size_class(256) == 256
        assert _size_class(257) == 512
        assert _size_class(4096) == 4096
        assert _size_class(4097) == 8192

    def test_alignment(self):
        p = BufferPool(align=64)
        for shape, dt in (((7,), np.uint8), ((3, 5), np.float32),
                          ((1, 224, 224, 3), np.uint8)):
            a = p.acquire(shape, dt)
            assert a.ctypes.data % 64 == 0
            assert a.shape == shape and a.dtype == np.dtype(dt)

    def test_reuse_after_release(self):
        p = BufferPool()
        a = p.acquire((8, 8), np.float32)
        addr = a.ctypes.data
        assert p.owns(a)
        assert p.release(a) is True
        assert not p.owns(a)
        del a
        b = p.acquire((16, 16), np.uint8)  # same 256B class, new shape
        assert p.hits == 1 and p.misses == 1
        assert b.ctypes.data == addr  # the recycled slab, re-derived

    def test_double_release_rejected(self):
        p = BufferPool()
        a = p.acquire((4,), np.float32)
        assert p.release(a) is True
        assert p.release(a) is False
        assert p.snapshot()["free"] == 1  # not freed twice

    def test_gc_fallback_recycles(self):
        p = BufferPool()
        a = p.acquire((4,), np.float32)
        del a
        gc.collect()
        snap = p.snapshot()
        assert snap["outstanding"] == 0 and snap["free"] == 1
        b = p.acquire((4,), np.float32)
        assert p.hits == 1
        del b

    def test_gc_fallback_never_aliases_derived_view(self):
        """numpy collapses view chains: ``a[None].base`` is the SLAB,
        not the pool-tracked view — so the tracked view can die (and its
        finalizer fire) while a derived view downstream still reads the
        memory. The slab must NOT re-enter circulation."""
        p = BufferPool()
        a = p.acquire((4,), np.float32)
        a[:] = 7.0
        derived = a[None]  # base collapses to the slab
        del a
        gc.collect()
        assert p.snapshot()["free"] == 0  # pinned by the derived view
        b = p.acquire((4,), np.float32)
        b[:] = 0.0
        np.testing.assert_array_equal(
            derived[0], np.full(4, 7.0, np.float32))

    def test_release_never_aliases_derived_view(self):
        """Even an explicit release must not recycle a slab that a
        derived view elsewhere (tee branch, app callback) still reads —
        pool ownership ends, but the slab falls back to plain GC."""
        p = BufferPool()
        a = p.acquire((4,), np.float32)
        a[:] = 7.0
        derived = a[None]
        assert p.release(a) is True
        assert p.snapshot()["free"] == 0  # dropped, not recycled
        b = p.acquire((4,), np.float32)
        b[:] = 0.0
        np.testing.assert_array_equal(
            derived[0], np.full(4, 7.0, np.float32))

    def test_stale_finalizer_cannot_double_free(self):
        """Explicit release detaches the GC finalizer: when the view dies
        later, its slab must not be freed a second time (a fresh acquire
        could reuse id(view), and a stale finalizer firing against the
        new registration would recycle live memory)."""
        p = BufferPool()
        a = p.acquire((4,), np.float32)
        p.release(a)
        del a
        gc.collect()
        assert p.snapshot()["free"] == 1

    def test_reuse_does_not_alias_outstanding(self):
        """Without release, a second acquire must NOT hand out the same
        memory the first view still owns."""
        p = BufferPool()
        a = p.acquire((8,), np.float32)
        b = p.acquire((8,), np.float32)
        a[:], b[:] = 1.0, 2.0
        assert a.ctypes.data != b.ctypes.data
        np.testing.assert_array_equal(a, np.full(8, 1.0, np.float32))

    def test_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_POOL", "0")
        p = BufferPool()
        a = p.acquire((4,), np.float32)
        assert not p.owns(a)
        assert p.hits == p.misses == 0

    def test_max_per_class_bounds_freelist(self):
        p = BufferPool(max_per_class=2)
        views = [p.acquire((4,), np.float32) for _ in range(4)]
        for v in views:
            p.release(v)
        assert p.snapshot()["free"] == 2


# -- dispatch window ----------------------------------------------------------


class _WindowOwner(Element):
    ELEMENT_NAME = "_winowner"
    PROPERTIES = {**Element.PROPERTIES, "inflight": 2}


class TestDispatchWindow:
    def _mk(self, inflight):
        owner = _WindowOwner(inflight=inflight)
        return owner, DispatchWindow(owner)

    def test_admit_bounds_window(self):
        import jax.numpy as jnp

        _owner, w = self._mk(2)
        for i in range(5):
            w.admit([jnp.full((4,), i)])
            assert len(w) <= 2
        assert len(w) == 2

    def test_inflight_zero_is_synchronous(self):
        import jax.numpy as jnp

        _owner, w = self._mk(0)
        w.admit([jnp.zeros((4,))])
        assert len(w) == 0

    def test_drain_empties_window(self):
        import jax.numpy as jnp

        _owner, w = self._mk(8)
        for i in range(5):
            w.admit([jnp.full((2,), i)])
        assert len(w) == 5  # never hit the limit
        w.drain()
        assert len(w) == 0

    def test_fence_releases_stash(self):
        import jax.numpy as jnp

        pool = get_pool()
        staged = pool.acquire((4,), np.float32)
        _owner, w = self._mk(1)
        w.admit([jnp.zeros((4,))], stash=[staged])
        assert pool.owns(staged)  # still outstanding inside the window
        w.drain()
        assert not pool.owns(staged)  # fence proved dispatch done

    def test_snapshot_reports_limits(self):
        import jax.numpy as jnp

        _owner, w = self._mk(3)
        w.admit([jnp.zeros((2,))])
        snap = w.snapshot()
        assert snap["inflight_now"] == 1
        assert snap["inflight_limit"] == 3


# -- queue opt-ins × deferred finalize ---------------------------------------


class _DeferredProbe(Element):
    """HANDLES_DEFERRED sink recording placement and finalize state at
    arrival, then materializing (so finalize correctness is also
    checked)."""

    ELEMENT_NAME = "_defprobe"
    HANDLES_DEFERRED = True

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.arrived = []   # (finalize_pending, on_device) at chain entry
        self.values = []

    def chain(self, pad, buf):
        self.arrived.append((buf.finalize is not None, buf.on_device()))
        host = buf.to_host()
        self.values.append(np.asarray(host.tensors[0]).copy())
        return FlowReturn.OK


class _FinalizeSrc(SourceElement):
    """Pushes buffers carrying a deferred finalize that doubles the
    payload — the fused-region deferred-stage pattern in miniature."""

    ELEMENT_NAME = "_finsrc"
    PROPERTIES = {**SourceElement.PROPERTIES, "num_buffers": 4,
                  "device": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.i = 0

    def negotiate(self):
        from nnstreamer_tpu.tensors.types import TensorsConfig

        cfg = TensorsConfig.from_arrays([np.zeros((2,), np.float32)])
        self.srcpad.set_caps(cfg.to_caps())

    def create(self):
        if self.i >= self.get_property("num_buffers"):
            return None
        arr = np.full((2,), float(self.i), np.float32)
        if self.get_property("device"):
            import jax.numpy as jnp

            arr = jnp.asarray(arr)
        buf = TensorBuffer([arr], pts=self.i).replace(
            finalize=lambda b: b.with_tensors(
                [np.asarray(t) * 2 for t in b.tensors]))
        self.i += 1
        return buf


def _run_finalize_pipe(queue_props, n=4, device=False):
    src = _FinalizeSrc(num_buffers=n, device=device)
    q = Queue(**queue_props)
    probe = _DeferredProbe()
    pipe = Pipeline().add_linked(src, q, probe)
    msg = pipe.run(timeout=30)
    assert msg is not None and msg.kind == "eos"
    return probe


class TestQueueOptIns:
    def test_plain_queue_keeps_finalize_lazy(self):
        probe = _run_finalize_pipe({})
        # queue is HANDLES_DEFERRED passthrough: finalize arrives intact
        assert all(pending for pending, _dev in probe.arrived)
        for i, v in enumerate(probe.values):
            np.testing.assert_array_equal(v, np.full((2,), 2.0 * i))

    def test_materialize_host_applies_finalize_at_queue(self):
        probe = _run_finalize_pipe({"materialize_host": True}, device=True)
        assert all(not pending and not dev
                   for pending, dev in probe.arrived)
        for i, v in enumerate(probe.values):
            np.testing.assert_array_equal(v, np.full((2,), 2.0 * i))

    def test_prefetch_device_keeps_finalize_and_moves_payload(self):
        probe = _run_finalize_pipe({"prefetch_device": True})
        assert all(pending and dev for pending, dev in probe.arrived)
        for i, v in enumerate(probe.values):
            np.testing.assert_array_equal(v, np.full((2,), 2.0 * i))

    def test_prefetch_host_preserves_results(self):
        probe = _run_finalize_pipe({"prefetch_host": True}, device=True)
        for i, v in enumerate(probe.values):
            np.testing.assert_array_equal(v, np.full((2,), 2.0 * i))

    def test_prefetch_device_stamps_pool_stash(self):
        """A pool-owned host array crossing a prefetch-device queue must
        ride on as a stash claim (released downstream at the fence), not
        be recycled while the H2D may still read it."""
        pool = get_pool()

        class _PoolSrc(_NumSrc):
            ELEMENT_NAME = "_poolsrc"

            def create(self):
                if self.i >= self.get_property("num_buffers"):
                    return None
                arr = pool.acquire((1,), np.float32)
                arr[0] = float(self.i)
                self.i += 1
                return TensorBuffer([arr], pts=self.i * 1000)

        src = _PoolSrc(num_buffers=3)
        q = Queue(prefetch_device=True)
        probe = _DeferredProbe()
        pipe = Pipeline().add_linked(src, q, probe)
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        assert len(probe.values) == 3
        # every buffer was uploaded and carries its staging-array claim
        assert all(dev for _pending, dev in probe.arrived)


# -- batch drain --------------------------------------------------------------


class _ListCollect(Element):
    """HANDLES_LIST consumer recording list vs single hand-offs; the
    first chain call stalls briefly so a backlog builds behind it."""

    ELEMENT_NAME = "_listcollect"
    HANDLES_LIST = True

    def __init__(self, name=None, stall_s=0.0, **props):
        super().__init__(name, **props)
        self.add_sink_pad("sink")
        self.values = []
        self.list_sizes = []
        self.singles = 0
        self._stall_s = stall_s
        self._stalled = False

    def _maybe_stall(self):
        if self._stall_s and not self._stalled:
            self._stalled = True
            time.sleep(self._stall_s)

    def chain(self, pad, buf):
        self._maybe_stall()
        self.singles += 1
        self.values.append(float(np.asarray(buf.tensors[0])[0]))
        return FlowReturn.OK

    def chain_list(self, pad, bufs):
        self._maybe_stall()
        self.list_sizes.append(len(bufs))
        for b in bufs:
            self.values.append(float(np.asarray(b.tensors[0])[0]))
        return FlowReturn.OK


class TestBatchDrain:
    def test_backlog_drains_as_ordered_list(self):
        n = 40
        src = _NumSrc(num_buffers=n)
        q = Queue(max_size_buffers=n)
        sink = _ListCollect(stall_s=0.3)
        pipe = Pipeline().add_linked(src, q, sink)
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        assert sink.values == [float(i) for i in range(n)]
        # the stall built a backlog → at least one multi-buffer hand-off
        assert sink.list_sizes and max(sink.list_sizes) > 1

    def test_drain_batch_1_disables_gathering(self):
        n = 20
        src = _NumSrc(num_buffers=n)
        q = Queue(max_size_buffers=n, drain_batch=1)
        sink = _ListCollect(stall_s=0.2)
        pipe = Pipeline().add_linked(src, q, sink)
        pipe.run(timeout=30)
        assert sink.values == [float(i) for i in range(n)]
        assert sink.list_sizes == [] and sink.singles == n

    def test_non_list_peer_gets_per_buffer_chain(self):
        n = 30
        src = _NumSrc(num_buffers=n)
        q = Queue(max_size_buffers=n)
        sink = _Collect()
        pipe = Pipeline().add_linked(src, q, sink)
        pipe.run(timeout=30)
        vals = [float(b.tensors[0][0]) for b in sink.buffers]
        assert vals == [float(i) for i in range(n)]
        assert sink.got_eos

    def test_list_handoff_keeps_invoke_stats_per_buffer(self):
        n = 24
        src = _NumSrc(num_buffers=n)
        q = Queue(max_size_buffers=n)
        sink = _ListCollect(stall_s=0.2)
        pipe = Pipeline().add_linked(src, q, sink)
        pipe.run(timeout=30)
        # a list of k buffers must count as k invokes, not 1
        assert sink.stats.total_invokes == n

    def test_drain_size_metric_recorded(self):
        n = 32
        src = _NumSrc(num_buffers=n)
        q = Queue(max_size_buffers=n)
        sink = _ListCollect(stall_s=0.3)
        pipe = Pipeline().add_linked(src, q, sink)
        pipe.run(timeout=30)
        snap = q.obs_snapshot()
        assert snap.get("drain_size_p50") is not None


# -- inflight semantics through a real filter pipeline ------------------------


FILTER_DESC = (
    "appsrc name=src ! "
    "tensor_transform mode=arithmetic option=typecast:float32,mul:2.0 ! "
    "tensor_filter framework=jax model={m} name=filter inflight={k} ! "
    "tensor_sink name=sink"
)


def _run_filter(desc, frames, fuse):
    pipe = parse_launch(desc)
    pipe._fuse = fuse
    pipe.start()
    try:
        src = pipe.get("src")
        for f in frames:
            src.push([f.copy()])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    return pipe, [np.asarray(b.tensors[0])
                  for b in pipe.get("sink").buffers]


class TestInflight:
    @pytest.mark.parametrize("fuse", [False, True])
    def test_results_byte_identical_inflight_1_vs_2(self, linear_model,
                                                    fuse):
        frames = [np.random.default_rng(i).integers(0, 9, (8, 4))
                  .astype(np.uint8) for i in range(8)]
        _p1, out1 = _run_filter(FILTER_DESC.format(m=linear_model, k=1),
                                frames, fuse)
        _p2, out2 = _run_filter(FILTER_DESC.format(m=linear_model, k=2),
                                frames, fuse)
        assert len(out1) == len(out2) == len(frames)
        for a, b in zip(out1, out2):
            assert a.tobytes() == b.tobytes()  # bytes AND order

    @pytest.mark.parametrize("fuse", [False, True])
    def test_eos_flushes_non_empty_window(self, linear_model, fuse):
        # window deeper than the frame count: nothing ever forces a
        # fence mid-stream, so EOS alone must deliver every result
        frames = [np.full((8, 4), i, np.uint8) for i in range(3)]
        pipe, out = _run_filter(FILTER_DESC.format(m=linear_model, k=16),
                                frames, fuse)
        assert len(out) == 3
        for i, a in enumerate(out):
            np.testing.assert_allclose(
                a, np.full((8, 3), i * 2 * 0.5 * 4, np.float32))

    def test_region_adopts_member_inflight(self, linear_model):
        pipe, _ = _run_filter(FILTER_DESC.format(m=linear_model, k=5),
                              [np.ones((8, 4), np.uint8)] * 2, fuse=True)
        assert pipe._regions
        assert int(pipe._regions[0].get_property("inflight")) == 5

    def test_metrics_snapshot_exposes_overlap_series(self, linear_model):
        pipe, _ = _run_filter(FILTER_DESC.format(m=linear_model, k=2),
                              [np.ones((8, 4), np.uint8)] * 4, fuse=False)
        snap = pipe.metrics_snapshot()
        filt = snap["elements"]["filter"]
        assert filt["inflight_limit"] == 2
        assert "inflight_now" in filt
        assert "pool" in snap  # process-wide ingest pool surfaced
        for key in ("hits", "misses", "outstanding", "hit_rate"):
            assert key in snap["pool"]


class TestSourcePooling:
    def test_videotestsrc_ball_uses_pool(self):
        before = get_pool().snapshot()
        pipe = parse_launch(
            "videotestsrc pattern=ball num-buffers=6 width=32 height=32 ! "
            "tensor_converter ! tensor_sink name=sink")
        msg = pipe.run(timeout=30)
        assert msg is not None and msg.kind == "eos"
        after = get_pool().snapshot()
        assert (after["hits"] + after["misses"]) > \
            (before["hits"] + before["misses"])
        assert len(pipe.get("sink").buffers) == 6
