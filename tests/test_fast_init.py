"""fast_init — shape-based parameter materialization (models/_init.py).

The zoo factories must initialize in ~ms (not run the un-jitted forward:
flax ``init`` took ~34 s for MobileNetV2 on a 1-core host) while keeping
the exact variable-tree structure flax would produce and staying
deterministic across processes (crc32 path keying, not salted hash()).
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models._init import fast_init


def _tiny_model():
    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Conv(8, (3, 3), use_bias=True)(x)
            x = nn.BatchNorm(use_running_average=True)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x.mean(axis=(1, 2)))

    return M()


def test_same_tree_as_flax_init():
    m = _tiny_model()
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, 8, 8, 3))
    ref = m.init(rng, x)
    fast = fast_init(m.init, rng, x)
    ref_paths = jax.tree_util.tree_flatten_with_path(ref)[0]
    fast_paths = jax.tree_util.tree_flatten_with_path(fast)[0]
    assert len(ref_paths) == len(fast_paths)
    for (rp, rv), (fp, fv) in zip(ref_paths, fast_paths):
        assert rp == fp
        assert rv.shape == fv.shape
        assert rv.dtype == fv.dtype


def test_statistics_and_specials():
    m = _tiny_model()
    v = fast_init(m.init, jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)))
    bs = v["batch_stats"]["BatchNorm_0"]
    assert np.all(np.asarray(bs["mean"]) == 0)
    assert np.all(np.asarray(bs["var"]) == 1)
    p = v["params"]
    assert np.all(np.asarray(p["BatchNorm_0"]["scale"]) == 1)
    assert np.all(np.asarray(p["Conv_0"]["bias"]) == 0)
    k = np.asarray(p["Conv_0"]["kernel"])
    assert k.std() > 0  # actually random
    fan_in = k.shape[0] * k.shape[1] * k.shape[2]
    assert abs(k.std() - 1 / np.sqrt(fan_in)) < 0.5 / np.sqrt(fan_in)


def test_deterministic_in_process():
    m = _tiny_model()
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, 8, 8, 3))
    a = fast_init(m.init, rng, x, seed=7)
    b = fast_init(m.init, rng, x, seed=7)
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))
    c = fast_init(m.init, rng, x, seed=8)
    assert any(
        not np.array_equal(np.asarray(la), np.asarray(lc))
        for la, lc in zip(jax.tree_util.tree_leaves(a),
                          jax.tree_util.tree_leaves(c))
    )


def test_deterministic_across_processes():
    # hash() is salted per-process; crc32 keying must not be. Fingerprint a
    # kernel in a fresh interpreter (different PYTHONHASHSEED) and compare.
    prog = (
        "import os; os.environ['JAX_PLATFORMS']='cpu';"
        "import jax, jax.numpy as jnp, numpy as np;"
        "import flax.linen as nn;"
        "from nnstreamer_tpu.models._init import fast_init\n"
        "class M(nn.Module):\n"
        "    @nn.compact\n"
        "    def __call__(self, x):\n"
        "        return nn.Dense(4)(x)\n"
        "v = fast_init(M().init, jax.random.PRNGKey(0), jnp.zeros((1, 3)))\n"
        "print(float(np.asarray(v['params']['Dense_0']['kernel']).sum()))"
    )
    import os

    env = dict(os.environ, PYTHONHASHSEED="12345")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        env=env, check=True, cwd=os.path.dirname(os.path.dirname(__file__)),
    )
    remote = float(out.stdout.strip().splitlines()[-1])

    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    v = fast_init(M().init, jax.random.PRNGKey(0), jnp.zeros((1, 3)))
    local = float(np.asarray(v["params"]["Dense_0"]["kernel"]).sum())
    assert abs(local - remote) < 1e-6
