"""int8-quantized KV cache (models/transformer._Int8KVCodec).

Claims under test: half the cache bytes, bounded numeric drift vs the
exact cache, and internal consistency (chunk vs sequential, prefill vs
decode) is EXACT — quantization error must be a property of the cache
content, not of which code path filled it.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    build_chunk_decode,
    build_decode_step,
    build_prefill,
    init_cache,
    init_params,
)
from nnstreamer_tpu.serving import ContinuousBatchingEngine  # noqa: E402

CFG = TransformerConfig(vocab=128, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, max_seq=48, dtype=jnp.float32)
PARAMS = init_params(CFG, seed=2)


def test_q8_cache_halves_bytes():
    import dataclasses

    bf16 = dataclasses.replace(CFG, dtype=jnp.bfloat16)
    raw = init_cache(bf16, batch=2)
    q8 = init_cache(bf16, batch=2, kv_codec="int8")
    raw_bytes = raw.nbytes
    q8_bytes = sum(x.nbytes for x in jax.tree.leaves(q8))
    # int8 values are half of bf16; scales add 4/dh per element
    assert q8_bytes < raw_bytes * (0.5 + 4 / bf16.head_dim + 0.05)
    assert q8["q"].dtype == jnp.int8


def _run_steps(decode, cache, tokens, start):
    logits_all = []
    tok = jnp.asarray([tokens[0]], jnp.int32)
    pos = jnp.asarray(start, jnp.int32)
    for t in tokens[1:] + [0]:
        logits, cache = decode(PARAMS, tok, cache, pos)
        logits_all.append(logits)
        tok = jnp.asarray([t], jnp.int32)
        pos = pos + 1
    return jnp.stack(logits_all, 1), cache


def test_q8_decode_close_to_exact():
    prefill = jax.jit(build_prefill(CFG))
    prefill_q = jax.jit(build_prefill(CFG, kv_codec="int8"))
    decode = jax.jit(build_decode_step(CFG))
    decode_q = jax.jit(build_decode_step(CFG, kv_codec="int8"))
    prompt = jnp.asarray([[7, 3, 11, 30, 2]], jnp.int32)
    l0, cache = prefill(PARAMS, prompt)
    l0q, cache_q = prefill_q(PARAMS, prompt)
    np.testing.assert_allclose(np.asarray(l0q), np.asarray(l0),
                               rtol=0.05, atol=0.05 * float(
                                   jnp.abs(l0).max()))
    toks = [9, 14, 27, 5, 18, 40]
    la, _ = _run_steps(decode, cache, toks, 5)
    lb, _ = _run_steps(decode_q, cache_q, toks, 5)
    # bounded drift: int8 per-vector absmax keeps logits within a few
    # percent of the exact cache on every step
    err = float(jnp.max(jnp.abs(la - lb)))
    ref = float(jnp.max(jnp.abs(la)))
    assert err < 0.08 * ref, (err, ref)


def test_q8_chunk_matches_sequential_q8_exactly():
    """Same cache content → same quantization: the chunk path and the
    step path must agree bitwise given identical inputs."""
    prefill_q = jax.jit(build_prefill(CFG, kv_codec="int8"))
    decode_q = jax.jit(build_decode_step(CFG, kv_codec="int8"))
    chunk_q = jax.jit(build_chunk_decode(CFG, kv_codec="int8"))
    prompt = jnp.asarray([[3, 1, 4]], jnp.int32)
    _, cache_a = prefill_q(PARAMS, prompt)
    _, cache_b = prefill_q(PARAMS, prompt)
    toks = jnp.asarray([[9, 2, 6, 5]], jnp.int32)
    cl, cache_a = chunk_q(PARAMS, toks, cache_a, 3)
    seq = []
    for i in range(4):
        lg, cache_b = decode_q(PARAMS, toks[:, i], cache_b,
                               jnp.asarray(3 + i, jnp.int32))
        seq.append(lg)
    np.testing.assert_allclose(np.asarray(cl),
                               np.asarray(jnp.stack(seq, 1)),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(cache_a["q"]),
                                  np.asarray(cache_b["q"]))


def test_engine_with_q8_cache_generates_deterministically():
    def run(**kw):
        eng = ContinuousBatchingEngine(
            CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
            temperature=0.0, **kw).start()
        try:
            return eng.generate([5, 11, 23], max_new_tokens=8,
                                timeout=240)
        finally:
            eng.stop()

    q1, q2 = run(kv_quant="int8"), run(kv_quant="int8")
    assert q1 == q2 and len(q1) == 8
    exact = run()
    # the first token comes from un-quantized prefill activations and is
    # bit-identical; later tokens read the int8 cache where argmax may
    # legitimately flip within the drift bound — exactness is not the
    # int8 contract
    assert q1[:1] == exact[:1]


def test_engine_q8_with_chunked_prefill():
    eng = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0, kv_quant="int8", prefill_chunk=4).start()
    try:
        got = eng.generate([(i * 5 + 1) % CFG.vocab for i in range(11)],
                           max_new_tokens=6, timeout=240)
    finally:
        eng.stop()
    assert len(got) == 6 and all(0 <= t < CFG.vocab for t in got)


def test_bad_codec_rejected():
    with pytest.raises(ValueError):
        init_cache(CFG, 1, kv_codec="int4")
