"""Distributed LM serving: tensor_lm_serve over the query transport
(elements/lm_serve.py) — prompts in over framed TCP, batched decode in
the shared engine, completions routed back per client."""

import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from nnstreamer_tpu import parse_launch  # noqa: E402
from nnstreamer_tpu.models.transformer import (  # noqa: E402
    TransformerConfig,
    init_params,
)
from nnstreamer_tpu.serving import (  # noqa: E402
    ContinuousBatchingEngine,
    register_engine,
    unregister_engine,
)
from tests.test_serving import reference_greedy  # noqa: E402

CFG = TransformerConfig(vocab=97, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, max_seq=64, dtype=jnp.float32)
PARAMS = init_params(CFG, seed=3)


@pytest.fixture
def lm_server():
    engine = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=3, steps_per_dispatch=4,
        temperature=0.0).start()
    register_engine("lm_test", engine)
    server = parse_launch(
        "tensor_query_serversrc name=ssrc port=0 ! "
        "tensor_lm_serve engine=lm_test max-new-tokens=6 ! "
        "tensor_query_serversink")
    server.start()
    yield server.get("ssrc").port
    server.stop()
    engine.stop()
    unregister_engine("lm_test")


def _client(port, prompts, results, idx, max_in_flight=1):
    pipe = parse_launch(
        f"appsrc name=src ! tensor_query_client dest-host=127.0.0.1 "
        f"dest-port={port} timeout=120 max-in-flight={max_in_flight} ! "
        "tensor_sink name=out to-host=true")
    outs = []
    pipe.get("out").connect(lambda b: outs.append(b))
    pipe.start()
    try:
        src = pipe.get("src")
        for p in prompts:
            src.push([np.asarray(p, np.int32)])
        src.end_of_stream()
        msg = pipe.wait(timeout=240)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    results[idx] = [np.asarray(b.tensors[0]).tolist() for b in outs]


def test_single_client_completion_matches_greedy(lm_server):
    results = {}
    _client(lm_server, [[5, 11, 23]], results, 0)
    assert results[0] == [reference_greedy([5, 11, 23], 6,
                                           cfg=CFG, params=PARAMS)]


def test_pipelined_requests_keep_fifo_order(lm_server):
    prompts = [[4, 8, 15], [16, 23], [42, 7, 9, 1]]
    results = {}
    _client(lm_server, prompts, results, 0, max_in_flight=3)
    assert results[0] == [reference_greedy(p, 6, cfg=CFG, params=PARAMS)
                          for p in prompts]


def test_concurrent_clients_share_the_batch(lm_server):
    prompts = {0: [[9, 9, 9]], 1: [[13, 2]], 2: [[1, 2, 3, 4]]}
    results = {}
    threads = [threading.Thread(target=_client,
                                args=(lm_server, prompts[i], results, i))
               for i in prompts]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    for i, ps in prompts.items():
        assert results[i] == [reference_greedy(p, 6, cfg=CFG,
                                               params=PARAMS)
                              for p in ps], f"client {i}"


def test_per_request_budget_rides_the_wire(lm_server):
    """A second int32 tensor in the request is that prompt's generation
    budget — payload, so it survives the framed protocol."""
    pipe = parse_launch(
        f"appsrc name=src ! tensor_query_client dest-host=127.0.0.1 "
        f"dest-port={lm_server} timeout=120 ! "
        "tensor_sink name=out to-host=true")
    outs = []
    pipe.get("out").connect(lambda b: outs.append(b))
    pipe.start()
    try:
        src = pipe.get("src")
        src.push([np.asarray([5, 11, 23], np.int32),
                  np.asarray([3], np.int32)])
        src.end_of_stream()
        msg = pipe.wait(timeout=240)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    assert np.asarray(outs[0].tensors[0]).tolist() == \
        reference_greedy([5, 11, 23], 3, cfg=CFG, params=PARAMS)


def test_malformed_request_gets_error_response_server_survives(lm_server):
    """An invalid prompt must yield the order-keeping -1 response and
    leave the server serving (a bad request is not a DoS)."""
    results = {}
    # over-long prompt (>= engine cache length, engine rejects) then a
    # valid one, same connection: responses must be [-1] then the real
    # completion, in order
    _client(lm_server, [list(range(1, CFG.max_seq + 2)), [5, 11, 23]],
            results, 0, max_in_flight=2)
    assert results[0] == [[-1],
                          reference_greedy([5, 11, 23], 6,
                                           cfg=CFG, params=PARAMS)]
    # server still healthy for a fresh connection
    _client(lm_server, [[13, 2]], results, 1)
    assert results[1] == [reference_greedy([13, 2], 6,
                                           cfg=CFG, params=PARAMS)]
    # valid THEN invalid: the error response must not overtake the valid
    # request's completion (order-matched protocol)
    _client(lm_server, [[5, 11, 23], list(range(1, CFG.max_seq + 2))],
            results, 2, max_in_flight=2)
    assert results[2] == [reference_greedy([5, 11, 23], 6,
                                           cfg=CFG, params=PARAMS),
                          [-1]]


def test_idle_drainers_retire():
    engine = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0).start()
    register_engine("lm_idle", engine)
    server = parse_launch(
        "tensor_query_serversrc name=ssrc port=0 ! "
        "tensor_lm_serve engine=lm_idle max-new-tokens=4 "
        "idle-timeout=0.3 name=serve ! tensor_query_serversink")
    server.start()
    try:
        results = {}
        _client(server.get("ssrc").port, [[3, 4]], results, 0)
        assert len(results[0]) == 1
        serve = server.get("serve")
        import time

        deadline = time.monotonic() + 10
        while serve._drainers and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not serve._drainers and not serve._fifos
    finally:
        server.stop()
        engine.stop()
        unregister_engine("lm_idle")


def test_server_stop_cancels_inflight_engine_streams():
    """Stopping the server pipeline must cancel abandoned engine work —
    the shared engine's slots free instead of decoding to dead streams."""
    engine = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=1, steps_per_dispatch=2,
        temperature=0.0).start()
    register_engine("lm_stop", engine)
    server = parse_launch(
        "tensor_query_serversrc name=ssrc port=0 ! "
        "tensor_lm_serve engine=lm_stop max-new-tokens=60 name=serve ! "
        "tensor_query_serversink")
    server.start()
    try:
        # direct submit through the element intake (no client needed):
        # queue a long request then stop mid-flight
        serve = server.get("serve")
        from nnstreamer_tpu.tensors.buffer import TensorBuffer

        serve._chain_entry(serve.sinkpads[0], TensorBuffer(
            [np.asarray([1, 2, 3], np.int32)], pts=0,
            meta={"query_client_id": 0}))
    finally:
        server.stop()
    import time

    deadline = time.monotonic() + 30
    while engine.active_streams and time.monotonic() < deadline:
        time.sleep(0.05)
    assert engine.active_streams == 0  # slot freed by cancellation
    engine.stop()
    unregister_engine("lm_stop")


def test_completion_carries_logprobs_tensor(lm_server):
    pipe = parse_launch(
        f"appsrc name=src ! tensor_query_client dest-host=127.0.0.1 "
        f"dest-port={lm_server} timeout=120 ! "
        "tensor_sink name=out to-host=true")
    outs = []
    pipe.get("out").connect(lambda b: outs.append(b))
    pipe.start()
    try:
        pipe.get("src").push([np.asarray([5, 11, 23], np.int32)])
        pipe.get("src").end_of_stream()
        msg = pipe.wait(timeout=240)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    toks = np.asarray(outs[0].tensors[0])
    lps = np.asarray(outs[0].tensors[1])
    assert lps.dtype == np.float32 and lps.shape == toks.shape
    assert np.all(lps <= 0.0)


def test_serve_element_records_request_latency():
    engine = ContinuousBatchingEngine(
        CFG, PARAMS, max_streams=2, steps_per_dispatch=4,
        temperature=0.0).start()
    register_engine("lm_stats", engine)
    server = parse_launch(
        "tensor_query_serversrc name=ssrc port=0 ! "
        "tensor_lm_serve engine=lm_stats max-new-tokens=4 name=serve ! "
        "tensor_query_serversink")
    server.start()
    try:
        results = {}
        _client(server.get("ssrc").port, [[3, 4]], results, 0)
        serve = server.get("serve")
        assert serve.get_property("latency") > 0  # element-standard prop
        assert serve.request_stats.total_invokes == 1
    finally:
        server.stop()
        engine.stop()
        unregister_engine("lm_stats")


def test_unregistered_engine_fails_start():
    pipe = parse_launch(
        "tensor_query_serversrc name=ssrc port=0 ! "
        "tensor_lm_serve engine=nope ! tensor_query_serversink")
    with pytest.raises(Exception):
        pipe.start()
    pipe.stop()
