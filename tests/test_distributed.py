"""Distributed observability plane (obs/distributed.py): EX2 wire
framing, dt1 HELLO negotiation + kill switch, skew-anchored remote-span
splicing, fleet metrics federation, and the cross-process Perfetto
export."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu.obs import distributed as dist
from nnstreamer_tpu.obs import timeline as TL
from nnstreamer_tpu.obs.flight import FlightRecorder
from nnstreamer_tpu.obs.quantiles import P2Quantile
from nnstreamer_tpu.obs.registry import MetricsRegistry
from nnstreamer_tpu.obs.server import MetricsServer
from nnstreamer_tpu.query import protocol as P
from nnstreamer_tpu.registry import ELEMENT, get_subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------
class TestExt2Framing:
    def test_roundtrip(self):
        blob = b'{"v":1,"total":0.01}'
        body = b"classic-buffer-bytes"
        payload = P.pack_ext2(7, 1.5, 0xDEADBEEF, 1234.5, blob, body)
        req_id, slack, tid, stamp, got_blob, rest = P.unpack_ext2(payload)
        assert (req_id, slack, tid, stamp) == (7, 1.5, 0xDEADBEEF, 1234.5)
        assert got_blob == blob and rest == body

    def test_empty_blob(self):
        payload = P.pack_ext2(1, -1.0, 0, 0.0, b"", b"body")
        _, _, _, _, blob, rest = P.unpack_ext2(payload)
        assert blob == b"" and rest == b"body"

    def test_short_header_raises(self):
        with pytest.raises(P.QueryProtocolError):
            P.unpack_ext2(b"\x00" * 8)

    def test_truncated_blob_raises(self):
        payload = P.pack_ext2(1, -1.0, 0, 0.0, b"x" * 64, b"")
        with pytest.raises(P.QueryProtocolError):
            P.unpack_ext2(payload[:-40])

    def test_new_commands_do_not_disturb_classic_ids(self):
        # the classic command ids are a wire contract with pre-16 peers
        assert P.Cmd.TRANSFER_EX2 == 13
        assert P.Cmd.RESULT_EX2 == 14

    def test_span_blob_roundtrip(self):
        blob = dist.pack_span_blob({"device": 0.004, "queue_wait": 0.001},
                                   0.006, 100.5, 100.506, "edge-1:3000")
        doc = dist.unpack_span_blob(blob)
        assert doc["total"] == 0.006
        assert doc["stages"]["device"] == 0.004
        assert doc["endpoint"] == "edge-1:3000"

    def test_span_blob_garbage_is_empty(self):
        assert dist.unpack_span_blob(b"") == {}
        assert dist.unpack_span_blob(b"\xff\xfe not json") == {}
        assert dist.unpack_span_blob(b"[1,2]") == {}


# ---------------------------------------------------------------------------
# feature negotiation + kill switch
# ---------------------------------------------------------------------------
class TestNegotiation:
    def test_default_on(self, monkeypatch):
        monkeypatch.delenv("NNSTPU_DIST_TRACE", raising=False)
        assert dist.enabled()
        assert dist.hello_offer() == ":dt1"

    @pytest.mark.parametrize("v", ["0", "false", "no", "off", "False"])
    def test_kill_switch(self, monkeypatch, v):
        monkeypatch.setenv("NNSTPU_DIST_TRACE", v)
        assert not dist.enabled()
        assert dist.hello_offer() == ""

    def test_parse_features_skips_window_digits(self):
        assert dist.parse_features("64:dt1") == frozenset({"dt1"})
        assert dist.parse_features("512") == frozenset()
        assert "dt1" in dist.parse_features("dt1:zz9")

    def test_hello_accepts(self):
        assert dist.hello_accepts(b"ok:dt1")
        assert not dist.hello_accepts(b"ok")
        assert not dist.hello_accepts(b"\xff\xfe")


# ---------------------------------------------------------------------------
# the splice (skew-anchoring rule)
# ---------------------------------------------------------------------------
class TestSpliceRemote:
    def _splice(self, span, sent_t=10.0, recv_t=10.1, sent_wall=None):
        tl = TL.Timeline()
        dist.splice_remote(tl, 42, sent_t, recv_t,
                           sent_wall if sent_wall is not None else 0.0,
                           span)
        return tl.frame_stages(42)

    def test_stages_tile_the_rtt_window_exactly(self):
        got = self._splice({"total": 0.06, "endpoint": "s",
                            "stages": {"device": 0.04,
                                       "queue_wait": 0.01}})
        assert set(got) == set(TL.DIST_STAGES)
        assert sum(got.values()) == pytest.approx(0.1, abs=1e-9)
        assert got["remote_device"] == pytest.approx(0.04)
        assert got["remote_queue"] == pytest.approx(0.01)
        assert got["remote_other"] == pytest.approx(0.01)

    def test_wall_split_used_when_inside_window(self):
        # remote clock ~in sync: recv_wall - sent_wall = 30ms of the
        # 40ms wire time goes to hop_send
        got = self._splice({"total": 0.06, "recv_wall": 1000.030},
                           sent_wall=1000.0)
        assert got["hop_send"] == pytest.approx(0.030)
        assert got["hop_recv"] == pytest.approx(0.010)

    def test_skewed_wall_falls_back_to_symmetric(self):
        # remote clock 3 minutes off: the forward delta lands outside
        # the wire window, so raw clocks are never trusted
        got = self._splice({"total": 0.06, "recv_wall": 1180.0},
                           sent_wall=1000.0)
        assert got["hop_send"] == pytest.approx(got["hop_recv"])

    def test_overreported_remote_total_clamped_to_rtt(self):
        # remote claims more time than the whole RTT: a clock artifact;
        # the splice never exceeds the client's own window
        got = self._splice({"total": 5.0, "endpoint": "s",
                            "stages": {"device": 4.0}})
        assert sum(got.values()) == pytest.approx(0.1, abs=1e-9)

    def test_overreported_stages_scaled_into_total(self):
        got = self._splice({"total": 0.05,
                            "stages": {"device": 0.08,
                                       "queue_wait": 0.02}})
        assert got["remote_device"] == pytest.approx(0.04)
        assert got["remote_queue"] == pytest.approx(0.01)
        assert got["remote_other"] == pytest.approx(0.0, abs=1e-9)

    def test_degenerate_windows_are_noops(self):
        tl = TL.Timeline()
        dist.splice_remote(tl, 1, 10.0, 10.0, 0.0, {"total": 1.0})
        dist.splice_remote(tl, None, 10.0, 11.0, 0.0, {"total": 1.0})
        dist.splice_remote(None, 1, 10.0, 11.0, 0.0, {"total": 1.0})
        assert tl.frame_stages(1) == {}

    def test_flight_recorder_accumulates_dist_stages(self):
        # DIST_STAGES are members of STAGES, so the flight recorder's
        # quantiles/attribution track them with zero extra wiring
        fr = FlightRecorder()
        dist.splice_remote(fr, 42, 10.0, 10.1, 0.0,
                           {"total": 0.06, "stages": {"device": 0.05}})
        assert fr.frame_stages(42)["remote_device"] == pytest.approx(0.05)
        fr.span("sink", 42, 10.1, 10.101, track="io", e2e_s=0.101)
        assert fr._q["remote_device"]["p50"].count == 1


# ---------------------------------------------------------------------------
# loopback: EX2 end-to-end
# ---------------------------------------------------------------------------
def _echo_server(delay_s=0.0):
    Src = get_subplugin(ELEMENT, "tensor_query_serversrc")
    src = Src(port=0, reliable=True)
    src.start()
    server = src.server
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            try:
                buf = server.incoming.get(timeout=0.2)
            except Exception:
                continue
            if buf is None:
                continue
            if delay_s:
                time.sleep(delay_s)
            out = TensorBuffer([t * 2 for t in buf.to_host().tensors],
                               pts=buf.pts)
            out.meta.update(buf.meta)
            server.send_result(buf.meta["query_client_id"], out)

    threading.Thread(target=worker, daemon=True).start()
    return src, stop


class TestLoopbackTrace:
    def _run(self, n=6, delay_s=0.0, **client_props):
        src, stop = _echo_server(delay_s=delay_s)
        Client = get_subplugin(ELEMENT, "tensor_query_client")
        cl = Client(port=src.port, reliable=True, timeout=5.0,
                    **client_props)
        outs = []
        cl.srcpad.push = lambda b: outs.append(b)
        try:
            for i in range(n):
                buf = TensorBuffer([np.full((4,), i, np.float32)], pts=i)
                buf.meta[TL.TRACE_SEQ_META] = 1000 + i
                cl.chain(cl.sinkpad, buf)
            cl.handle_eos()
        finally:
            stop.set()
            srv = src.server
            cl.stop()
            src.stop()
        return outs, srv

    def test_dist_stages_reconcile_with_rtt(self):
        fr = FlightRecorder()
        old = TL.ACTIVE
        TL.ACTIVE = fr
        try:
            outs, _ = self._run(n=6, delay_s=0.01)
        finally:
            TL.ACTIVE = old
        assert len(outs) == 6
        got = fr.frame_stages(1003)
        remote = sum(v for k, v in got.items() if k.startswith("remote_"))
        wire = got.get("hop_send", 0.0) + got.get("hop_recv", 0.0)
        # the spliced stages tile the observed RTT: the 10ms remote
        # delay must be attributed remotely, not to the wire
        assert remote >= 0.008
        assert remote + wire > 0.009

    def test_kill_switch_speaks_classic_ex(self, monkeypatch):
        monkeypatch.setenv("NNSTPU_DIST_TRACE", "0")
        sent_cmds = []
        real_send = P.send_msg

        def spy(sock, cmd, payload=b""):
            sent_cmds.append((cmd, payload))
            return real_send(sock, cmd, payload)

        monkeypatch.setattr(P, "send_msg", spy)
        outs, srv = self._run(n=3)
        assert len(outs) == 3
        transfers = [(c, p) for c, p in sent_cmds
                     if c in (P.Cmd.TRANSFER_EX, P.Cmd.TRANSFER_EX2)]
        assert transfers and all(c is P.Cmd.TRANSFER_EX
                                 for c, _ in transfers)
        hello = [p for c, p in sent_cmds if c is P.Cmd.HELLO]
        # byte-level: the HELLO payload carries no feature suffix
        assert hello and b"dt1" not in hello[0]
        assert not srv._dt1_instances

    def test_armed_speaks_ex2(self, monkeypatch):
        monkeypatch.delenv("NNSTPU_DIST_TRACE", raising=False)
        sent_cmds = []
        real_send = P.send_msg

        def spy(sock, cmd, payload=b""):
            sent_cmds.append(cmd)
            return real_send(sock, cmd, payload)

        monkeypatch.setattr(P, "send_msg", spy)
        outs, _ = self._run(n=3)
        assert len(outs) == 3
        assert P.Cmd.TRANSFER_EX2 in sent_cmds
        assert P.Cmd.TRANSFER_EX not in sent_cmds


# ---------------------------------------------------------------------------
# fleet metrics federation
# ---------------------------------------------------------------------------
def _replica(counter_v, gauge_v, samples, burn=None):
    """A real /metrics.json endpoint: registry + quantile states."""
    reg = MetricsRegistry()
    reg.counter("nns_query_requests_total", "req", wire="nnstpu")\
        .inc(counter_v)
    reg.gauge("nns_queue_depth", "depth").set(gauge_v)
    q50, q99 = P2Quantile(0.5), P2Quantile(0.99)
    for x in samples:
        q50.observe(float(x))
        q99.observe(float(x))

    def extra():
        out = {"quantiles": {"e2e": {"p50": q50.snapshot(),
                                     "p99": q99.snapshot()}}}
        if burn:
            out["slo"] = {"burn": burn}
        return out

    return MetricsServer(registry=reg, host="127.0.0.1", port=0,
                         snapshot_fn=extra).start()


class TestFederation:
    def test_merge_rules(self, rng):
        a_samples = rng.uniform(0.010, 0.030, 500)
        b_samples = rng.uniform(0.020, 0.040, 500)
        a = _replica(100, 3.0, a_samples,
                     burn={"fast": 0.5, "slow": 0.1})
        b = _replica(250, 7.0, b_samples)
        try:
            fed = dist.FederatedMetrics(
                endpoints=[("127.0.0.1", a.port), ("127.0.0.1", b.port)])
            view = fed.collect()
        finally:
            a.stop()
            b.stop()
        # counters sum across replicas per series
        reqs = [c for c in view["counters"]
                if c["name"] == "nns_query_requests_total"]
        assert len(reqs) == 1 and reqs[0]["value"] == 350.0
        # gauges stay per-instance (averaging a gauge lies)
        depths = {g["labels"]["instance"]: g["value"]
                  for g in view["gauges"]
                  if g["name"] == "nns_queue_depth"}
        assert sorted(depths.values()) == [3.0, 7.0]
        # P2 marker-merge tracks the pooled distribution, not either
        # replica's own quantiles
        pooled = np.concatenate([a_samples, b_samples])
        q = view["quantiles"]["e2e"]
        assert q["count"] == 1000
        assert abs(q["p50_ms"] - np.percentile(pooled, 50) * 1e3) <= 4.0
        assert abs(q["p99_ms"] - np.percentile(pooled, 99) * 1e3) <= 5.0
        # burn windows stay per endpoint
        assert [b_ for b_ in view["burn"].values()] == \
            [{"fast": 0.5, "slow": 0.1}]
        assert all(st["ok"] for st in view["endpoints"].values())

    def test_down_endpoint_reported_not_fatal(self):
        a = _replica(5, 1.0, [0.01])
        try:
            fed = dist.FederatedMetrics(
                endpoints=[("127.0.0.1", a.port), ("127.0.0.1", 1)],
                timeout=0.5)
            view = fed.collect()
        finally:
            a.stop()
        ups = view["endpoints"]
        assert ups[f"127.0.0.1:{a.port}"]["ok"]
        assert not ups["127.0.0.1:1"]["ok"]
        text = fed.render_prometheus()
        assert 'nns_fleet_endpoint_up{instance="127.0.0.1:1"} 0' in text

    def test_prometheus_view(self):
        a = _replica(5, 1.0, np.full(100, 0.02),
                     burn={"fast": 2.0, "slow": 1.5})
        try:
            fed = dist.FederatedMetrics(
                endpoints=[("127.0.0.1", a.port)])
            text = fed.render_prometheus()
        finally:
            a.stop()
        assert "nns_fleet_nns_query_requests_total" in text
        assert 'nns_fleet_stage_p99_ms{stage="e2e"}' in text
        assert 'nns_fleet_burn_rate{instance=' in text

    def test_fleet_routes_on_metrics_server(self):
        a = _replica(5, 1.0, [0.01, 0.02])
        fed = dist.FederatedMetrics(endpoints=[("127.0.0.1", a.port)])
        front = MetricsServer(registry=MetricsRegistry(),
                              host="127.0.0.1", port=0,
                              federation=fed).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{front.port}/fleet/metrics.json",
                    timeout=5) as r:
                view = json.loads(r.read().decode())
            assert view["counters"][0]["value"] == 5.0
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{front.port}/fleet/metrics",
                    timeout=5) as r:
                assert b"nns_fleet_endpoint_up" in r.read()
        finally:
            front.stop()
            a.stop()

    def test_metrics_json_extra_sections(self):
        # satellite: /metrics.json exposes the same slo/attribution
        # sections metrics_snapshot() returns in-process
        srv = MetricsServer(
            registry=MetricsRegistry(), host="127.0.0.1", port=0,
            snapshot_fn=lambda: {"slo": {"stages": {}},
                                 "attribution": {"frames": 0},
                                 "ignored": 1}).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics.json",
                    timeout=5) as r:
                snap = json.loads(r.read().decode())
        finally:
            srv.stop()
        assert snap["slo"] == {"stages": {}}
        assert snap["attribution"] == {"frames": 0}
        assert "ignored" not in snap

    def test_discovery_metrics_endpoints(self):
        from nnstreamer_tpu.query.discovery import (
            ServerAdvertiser,
            ServerDiscovery,
        )
        from nnstreamer_tpu.query.pubsub import Broker

        broker = Broker(port=0).start()
        try:
            ad = ServerAdvertiser("127.0.0.1", broker.port, "fleet-op",
                                  "10.0.0.5", 3000, metrics_port=9090)
            ad.publish()
            legacy = ServerAdvertiser("127.0.0.1", broker.port,
                                      "fleet-op", "10.0.0.6", 3000)
            legacy.publish()
            disco = ServerDiscovery("127.0.0.1", broker.port, "fleet-op")
            try:
                servers = disco.wait_servers(timeout=5.0)
                assert len(servers) == 2
                # only the ad that carries a metrics_port is scrapable
                assert disco.metrics_endpoints() == [("10.0.0.5", 9090)]
            finally:
                disco.close()
            ad.retract()
            legacy.retract()
        finally:
            broker.stop()


# ---------------------------------------------------------------------------
# Perfetto export: per-endpoint process tracks + cross-process flows
# ---------------------------------------------------------------------------
class TestChromeExport:
    def test_endpoint_spans_get_their_own_pid(self):
        tl = TL.Timeline()
        tl.span("device", 1, 10.000, 10.004, track="exec")
        dist.splice_remote(tl, 1, 10.004, 10.104, 0.0,
                           {"total": 0.06, "endpoint": "edge-b:3000",
                            "stages": {"device": 0.05}})
        doc = tl.to_chrome()
        events = doc["traceEvents"]
        procs = {e["args"]["name"]: e["pid"] for e in events
                 if e.get("name") == "process_name"}
        assert procs.get("nnstreamer_tpu") == 1
        assert "endpoint edge-b:3000" in procs
        remote_pid = procs["endpoint edge-b:3000"]
        assert remote_pid != 1
        by_name = {e["name"]: e for e in events if e.get("ph") == "X"}
        assert by_name["device"]["pid"] == 1
        assert by_name["remote_device"]["pid"] == remote_pid
        # the hop spans are the local wire view: they stay on pid 1
        assert by_name["hop_send"]["pid"] == 1

    def test_flow_chain_crosses_processes(self):
        tl = TL.Timeline()
        tl.span("device", 7, 10.000, 10.004, track="exec")
        dist.splice_remote(tl, 7, 10.004, 10.104, 0.0,
                           {"total": 0.06, "endpoint": "edge-b:3000",
                            "stages": {"device": 0.05}})
        events = tl.to_chrome()["traceEvents"]
        flow = [e for e in events if e.get("cat") == "frame"
                and e.get("id") == 7]
        assert [e["ph"] for e in flow] == \
            ["s"] + ["t"] * (len(flow) - 2) + ["f"]
        assert len({e["pid"] for e in flow}) == 2  # crosses the boundary
