"""Region fusion (pipeline/fuse.py): fused pipelines must be
indistinguishable from unfused ones except for speed.

Mirrors the reference's guarantee that element composition is semantics-
preserving regardless of scheduling (queues, threads); here the scheduling
change is "one XLA program instead of N dispatches".
"""

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.jax_backend import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.pipeline.fuse import FusedRegion
from nnstreamer_tpu.tensors.types import TensorInfo, TensorsInfo, TensorType


@pytest.fixture
def linear_model():
    import jax.numpy as jnp

    w = jnp.full((4, 3), 0.5, jnp.float32)

    def fn(params, x):
        return x.astype(jnp.float32) @ params

    in_info = TensorsInfo([TensorInfo(dim=(4, 8), type=TensorType.FLOAT32)])
    out_info = TensorsInfo([TensorInfo(dim=(3, 8), type=TensorType.FLOAT32)])
    register_jax_model("fuse_linear", fn, w, in_info=in_info,
                       out_info=out_info)
    yield "fuse_linear"
    unregister_jax_model("fuse_linear")


DESC = (
    "appsrc name=src ! "
    "tensor_transform mode=arithmetic option=typecast:float32,mul:2.0 ! "
    "tensor_filter framework=jax model={m} name=filter ! "
    "tensor_sink name=sink"
)


def _run(desc, frames, fuse=True):
    pipe = parse_launch(desc)
    pipe._fuse = fuse
    src = pipe.get("src")
    sink = pipe.get("sink")
    pipe.start()
    try:
        for f in frames:
            src.push([f.copy()])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    return pipe, [np.asarray(b.tensors[0]) for b in sink.buffers]


def test_fused_matches_unfused(linear_model):
    frames = [np.random.default_rng(i).integers(0, 9, (8, 4)).astype(np.uint8)
              for i in range(5)]
    pipe_f, out_f = _run(DESC.format(m=linear_model), frames, fuse=True)
    pipe_u, out_u = _run(DESC.format(m=linear_model), frames, fuse=False)
    assert pipe_f._regions and isinstance(pipe_f._regions[0], FusedRegion)
    assert pipe_f._regions[0].members[0].ELEMENT_NAME == "tensor_transform"
    assert not pipe_u._regions
    assert len(out_f) == len(out_u) == 5
    for a, b in zip(out_f, out_u):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_fused_region_math(linear_model):
    frames = [np.ones((8, 4), np.uint8)] * 3
    pipe, out = _run(DESC.format(m=linear_model), frames, fuse=True)
    region = pipe._regions[0]
    assert len(region.members) == 2
    # result = (x*2) @ 0.5 → each output element sums 4 * 2 * 0.5 = 4
    np.testing.assert_allclose(out[0], np.full((8, 3), 4.0, np.float32))


def test_throttled_filter_not_fused(linear_model):
    desc = DESC.format(m=linear_model).replace(
        "name=filter", "name=filter throttle=100000")
    frames = [np.ones((8, 4), np.uint8)] * 2
    pipe, _ = _run(desc, frames, fuse=True)
    # transform alone is a 1-element run → no region spliced
    assert not pipe._regions


def test_member_stats_stay_live(linear_model):
    frames = [np.ones((8, 4), np.uint8)] * 6
    pipe, _ = _run(DESC.format(m=linear_model), frames, fuse=True)
    assert pipe.get("filter").get_property("throughput") > 0


def test_custom_event_consume_semantics(linear_model):
    """Events consumed by a member (reload_model) must not leak downstream;
    events no member consumes must arrive downstream — same as unfused."""
    from nnstreamer_tpu.pipeline.element import CustomEvent

    pipe = parse_launch(
        "appsrc name=src ! tensor_transform mode=typecast option=float32 ! "
        "tensor_filter framework=jax model=fuse_linear name=filter "
        "is-updatable=true ! tensor_sink name=sink"
    )
    sink = pipe.get("sink")
    seen = []
    orig = sink.sink_event

    def spy(pad, event):
        if isinstance(event, CustomEvent):
            seen.append(event.name)
        return orig(pad, event)

    sink.sink_event = spy
    pipe.start()
    try:
        region = pipe._regions[0]
        region._event_entry(region.sinkpad, CustomEvent("app_event", {}))
        region._event_entry(region.sinkpad,
                            CustomEvent("reload_model", {}))
        assert seen == ["app_event"]
    finally:
        pipe.stop()


def test_restart_reuses_region_safely(linear_model):
    """stop()/start() must re-pull backend state instead of reusing the
    program traced over the closed backend."""
    frames = [np.ones((8, 4), np.uint8)] * 2
    desc = DESC.format(m=linear_model)
    pipe = parse_launch(desc)
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    src.push([frames[0].copy()])
    src.end_of_stream()
    assert pipe.wait(timeout=60).kind == "eos"
    pipe.stop()
    first = np.asarray(sink.buffers[-1].tensors[0])

    pipe.start()  # backend re-opened; region must rebuild
    src.push([frames[1].copy()])
    src.end_of_stream()
    assert pipe.wait(timeout=60).kind == "eos"
    pipe.stop()
    second = np.asarray(sink.buffers[-1].tensors[0])
    np.testing.assert_allclose(first, second)


def test_sharded_filter_not_fused(linear_model):
    """Batch-sharded filters keep their NamedSharding placement → unfused."""
    desc = DESC.format(m=linear_model).replace(
        "name=filter", "name=filter custom=sharding:batch")
    frames = [np.ones((8, 4), np.uint8)] * 2
    pipe, out = _run(desc, frames, fuse=True)
    assert not pipe._regions
    np.testing.assert_allclose(out[0], np.full((8, 3), 4.0, np.float32))


def test_runtime_throttle_unsplices(linear_model):
    """Enabling throttle on a PLAYING fused filter must fall back to the
    member chain (QoS dropping resumes), not kill the pipeline."""
    pipe = parse_launch(DESC.format(m=linear_model))
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    try:
        frame = np.ones((8, 4), np.uint8)
        src.push([frame.copy()])
        sink.wait(1)
        region = pipe._regions[0]
        assert not region._dead
        pipe.get("filter").set_property("throttle", 1000000)
        src.push([frame.copy()])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        assert region._dead  # unspliced, stream survived
        np.testing.assert_allclose(
            np.asarray(sink.buffers[-1].tensors[0]),
            np.full((8, 3), 4.0, np.float32))
    finally:
        pipe.stop()


def test_params_only_reload_keeps_executable(linear_model):
    """Same model fn + new params must swap consts without re-jitting."""
    import jax.numpy as jnp

    from nnstreamer_tpu.filters import jax_backend

    fn = jax_backend._registered["fuse_linear"]["fn"]
    pipe = parse_launch(
        "appsrc name=src ! tensor_transform mode=typecast option=float32 ! "
        "tensor_filter framework=jax model=fuse_linear name=filter "
        "is-updatable=true ! tensor_sink name=sink"
    )
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    try:
        frame = np.ones((8, 4), np.uint8)
        src.push([frame.copy()])
        sink.wait(1)
        region = pipe._regions[0]
        jitted_before = region._trace_cache[1]

        register_jax_model("fuse_linear", fn,
                           jnp.full((4, 3), 2.0, jnp.float32))
        pipe.get("filter").reload_model()
        src.push([frame.copy()])
        src.end_of_stream()
        assert pipe.wait(timeout=60).kind == "eos"
        assert region._trace_cache[1] is jitted_before  # no re-jit
        np.testing.assert_allclose(
            np.asarray(sink.buffers[-1].tensors[0]),
            np.full((8, 3), 8.0, np.float32))
    finally:
        pipe.stop()


def test_reload_inside_region(linear_model):
    """reload via the member filter must invalidate the compiled region."""
    import jax.numpy as jnp

    register_jax_model("fuse_linear2",
                       lambda p, x: x.astype(jnp.float32) @ p,
                       jnp.full((4, 3), 1.0, jnp.float32))
    pipe = parse_launch(
        "appsrc name=src ! "
        "tensor_transform mode=typecast option=float32 ! "
        "tensor_filter framework=jax model=fuse_linear name=filter "
        "is-updatable=true ! tensor_sink name=sink"
    )
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    try:
        assert pipe._regions
        frame = np.ones((8, 4), np.uint8)
        src.push([frame.copy()])
        sink.wait(1)
        before = np.asarray(sink.buffers[-1].tensors[0])

        pipe.get("filter").reload_model("fuse_linear2")
        src.push([frame.copy()])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
        after = np.asarray(sink.buffers[-1].tensors[0])
        np.testing.assert_allclose(before, np.full((8, 3), 2.0))
        np.testing.assert_allclose(after, np.full((8, 3), 4.0))
    finally:
        pipe.stop()
        unregister_jax_model("fuse_linear2")


# -- fused decoders (device kernel + deferred host finalize) -----------------

DEC_DESC = (
    "appsrc name=src ! "
    "tensor_transform mode=arithmetic option=typecast:float32,mul:2.0 ! "
    "tensor_filter framework=jax model={m} name=filter ! "
    "tensor_decoder mode=image_labeling {opts} ! "
    "tensor_sink name=sink to-host=true"
)


def _run_dec(frames, fuse, opts=""):
    pipe = parse_launch(DEC_DESC.format(m="fuse_linear", opts=opts))
    pipe._fuse = fuse
    src, sink = pipe.get("src"), pipe.get("sink")
    pipe.start()
    try:
        for f in frames:
            src.push([f.copy()])
        src.end_of_stream()
        msg = pipe.wait(timeout=60)
        assert msg is not None and msg.kind == "eos", msg
    finally:
        pipe.stop()
    return pipe, list(sink.buffers)


def test_fused_decoder_matches_unfused(linear_model):
    frames = [np.random.default_rng(i).integers(0, 9, (8, 4)).astype(np.uint8)
              for i in range(4)]
    pipe_f, out_f = _run_dec(frames, fuse=True)
    pipe_u, out_u = _run_dec(frames, fuse=False)
    # the decoder joined the region (and terminates it)
    assert pipe_f._regions
    members = pipe_f._regions[0].members
    assert members[-1].ELEMENT_NAME == "tensor_decoder"
    assert len(out_f) == len(out_u) == 4
    for a, b in zip(out_f, out_u):
        # finalize already applied by the sink's to_host
        assert a.finalize is None
        assert a.meta["label_index"] == b.meta["label_index"]
        assert a.meta["label"] == b.meta["label"]
        np.testing.assert_allclose(a.meta["score"], b.meta["score"],
                                   rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))


def test_fused_decoder_labels_file(linear_model, tmp_path):
    labels = tmp_path / "labels.txt"
    names = [f"class{i}" for i in range(24)]
    labels.write_text("\n".join(names) + "\n")
    frames = [np.eye(8, 4, k=-1).astype(np.uint8) * 9]
    _, out = _run_dec(frames, fuse=True, opts=f"option1={labels}")
    assert out[0].meta["label"] in names
    assert bytes(np.asarray(out[0][0])).decode() == out[0].meta["label"]


def test_buffer_finalize_applied_once():
    from nnstreamer_tpu.tensors.buffer import TensorBuffer

    calls = []

    def fin(buf):
        calls.append(1)
        return buf.replace(meta={**buf.meta, "done": True})

    b = TensorBuffer([np.arange(4)], finalize=fin)
    h = b.to_host()
    assert h.meta.get("done") and h.finalize is None
    h2 = h.to_host()
    assert len(calls) == 1 and h2.meta.get("done")


def test_deferred_finalize_materializes_before_downstream_elements(
        linear_model, tmp_path):
    """A finalize-pending buffer must materialize before any element that
    consumes payload (here filesink), so downstream work never runs on the
    pre-finalize device scalars (code-review regression)."""
    out_f = tmp_path / "fused.bin"
    out_u = tmp_path / "unfused.bin"
    frames = [np.random.default_rng(7).integers(0, 9, (8, 4)).astype(np.uint8)]
    for fuse, path in ((True, out_f), (False, out_u)):
        pipe = parse_launch(
            "appsrc name=src ! "
            "tensor_transform mode=arithmetic option=typecast:float32,mul:2.0 ! "
            f"tensor_filter framework=jax model={linear_model} ! "
            "tensor_decoder mode=image_labeling ! "
            f"queue ! filesink location={path}")
        pipe._fuse = fuse
        src = pipe.get("src")
        pipe.start()
        try:
            src.push([frames[0].copy()])
            src.end_of_stream()
            msg = pipe.wait(timeout=60)
            assert msg is not None and msg.kind == "eos", msg
        finally:
            pipe.stop()
    data_f, data_u = out_f.read_bytes(), out_u.read_bytes()
    assert data_f == data_u  # label text, not raw argmax scalars
    assert data_f.decode().isdigit()


def test_fused_decoder_to_host_false_still_finalized(linear_model):
    """to_host=false must not leak pre-finalize scalars to the app
    (code-review regression): the sink applies a pending finalize always."""
    frames = [np.ones((8, 4), np.uint8)]
    pipe, out = _run_dec(frames, fuse=True, opts="")
    pipe2 = parse_launch(DEC_DESC.format(m=linear_model, opts="").replace(
        "to-host=true", "to-host=false"))
    src, sink = pipe2.get("src"), pipe2.get("sink")
    pipe2.start()
    try:
        src.push([frames[0].copy()])
        src.end_of_stream()
        assert pipe2.wait(timeout=60).kind == "eos"
    finally:
        pipe2.stop()
    a, b = out[0], sink.buffers[0]
    assert b.finalize is None and b.meta["label"] == a.meta["label"]
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
