"""Mesh-sharded serving plane (parallel/serve.py + the `mesh=` element
property): spec grammar, plan caching, batch placement (zero-copy
matched hand-offs, counted reshards), the matched-sharding contract at
device-passthrough boundaries, SLO admission quantum alignment,
mesh-wide batch forming, per-shard HBM residency, and sharded
swap_model continuity.

Everything here runs on the 8-device virtual CPU mesh the test
conftest forces (--xla_force_host_platform_device_count=8) — the same
configuration the CI mesh smoke uses.
"""

import time

import numpy as np
import pytest

from nnstreamer_tpu import parse_launch
from nnstreamer_tpu.filters.jax_backend import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.obs import get_registry
from nnstreamer_tpu.obs import timeline as _timeline
from nnstreamer_tpu.parallel import serve
from nnstreamer_tpu.parallel.serve import (
    MeshPlan,
    MeshShardingError,
    canonical_spec,
    get_mesh_plan,
    parse_mesh_spec,
    place_batch,
)
from nnstreamer_tpu.serving.scheduler import SloScheduler
from nnstreamer_tpu.tensors import memory

jax = pytest.importorskip("jax")
jnp = pytest.importorskip("jax.numpy")


def _wait(cond, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.01)


# -- spec grammar and plans ---------------------------------------------------


class TestMeshSpec:
    def test_parse_simple(self):
        assert parse_mesh_spec("dp4") == [("dp", 4)]
        assert parse_mesh_spec("dp2xtp2") == [("dp", 2), ("tp", 2)]

    def test_parse_wildcard(self):
        assert parse_mesh_spec("dp*") == [("dp", -1)]
        assert parse_mesh_spec("dp") == [("dp", -1)]  # bare axis = rest
        assert parse_mesh_spec("tp2xdp-1") == [("tp", 2), ("dp", -1)]

    @pytest.mark.parametrize("bad", ["", "qq4", "dp0", "4dp",
                                     "dp4q", "dp4xdp2"])
    def test_malformed_is_plan_time_error(self, bad):
        with pytest.raises(MeshShardingError):
            parse_mesh_spec(bad)

    def test_canonical(self):
        assert canonical_spec("DP8") == canonical_spec("dp8")

    def test_plan_cached_and_counts_shards(self):
        a = get_mesh_plan("dp8")
        b = get_mesh_plan("dp8")
        assert a is b, "plans must cache per canonical spec"
        assert a.shard_count == 8 and a.dp_size == 8
        mixed = get_mesh_plan("dp2xtp2")
        assert mixed.shard_count == 4 and mixed.dp_size == 2

    def test_sharding_for_ragged_batch_falls_back(self):
        plan = get_mesh_plan("dp8")
        full = np.zeros((8, 4), np.float32)
        ragged = np.zeros((3, 4), np.float32)
        assert plan.sharding_for(full) == plan.batched()
        assert plan.sharding_for(ragged) == plan.replicated()


# -- batch placement (the zero-copy contract) ---------------------------------


class TestPlaceBatch:
    def test_matched_device_array_moves_zero_bytes(self):
        plan = get_mesh_plan("dp8")
        x = np.ones((8, 4), np.float32)
        r0 = serve.reshard_bytes_total()
        placed = place_batch(x, plan)
        assert placed.sharding == plan.batched()
        again = place_batch(placed, plan)
        assert again is placed, "matched hand-off must be a no-op"
        assert serve.reshard_bytes_total() == r0, \
            "matched placements must not count as reshards"

    def test_mismatched_device_array_counts_reshard(self):
        plan8 = get_mesh_plan("dp8")
        plan2 = get_mesh_plan("dp2")
        x = place_batch(np.ones((8, 4), np.float32), plan8)
        r0 = serve.reshard_bytes_total()
        moved = place_batch(x, plan2)
        assert moved.sharding == plan2.batched()
        assert serve.reshard_bytes_total() == r0 + x.nbytes, \
            "a cross-mesh bounce must count its bytes"

    def test_ragged_batch_places_replicated(self):
        plan = get_mesh_plan("dp8")
        placed = place_batch(np.ones((3, 4), np.float32), plan)
        assert placed.sharding == plan.replicated()


# -- chained sharded regions: matched boundaries ------------------------------


@pytest.fixture
def chain_models():
    register_jax_model("mesh_sv_a", lambda x: (x * 2.0,))
    register_jax_model("mesh_sv_b", lambda x: (x + 1.0,))
    yield "mesh_sv_a", "mesh_sv_b"
    unregister_jax_model("mesh_sv_a")
    unregister_jax_model("mesh_sv_b")


CHAIN_DESC = (
    "appsrc name=src ! "
    "tensor_filter framework=jax model=mesh_sv_a name=fa mesh=dp8 ! "
    "queue max-size-buffers=4 ! "
    "tensor_filter framework=jax model=mesh_sv_b name=fb mesh=dp8 ! "
    "tensor_sink name=sink to-host=true"
)


class TestChainedShardedRegions:
    def _run(self, desc, frames=4):
        pipe = parse_launch(desc)
        src, sink = pipe.get("src"), pipe.get("sink")
        pipe.start()
        try:
            for i in range(frames):
                src.push([np.full((8, 4), float(i), np.float32)])
            src.end_of_stream()
            msg = pipe.wait(timeout=120)
            assert msg is not None and msg.kind == "eos", msg
        finally:
            pipe.stop()
        return pipe, [np.asarray(b.tensors[0]) for b in sink.buffers]

    def test_zero_reshard_across_matched_boundary(self, chain_models):
        r0 = serve.reshard_bytes_total()
        pipe, outs = self._run(CHAIN_DESC)
        assert len(outs) == 4
        for i, o in enumerate(outs):
            assert np.array_equal(o, np.full((8, 4), i * 2.0 + 1.0,
                                             np.float32))
        assert serve.reshard_bytes_total() == r0, (
            "two chained dp8 regions must hand the batch off without "
            "moving a byte")

    def test_shard_count_gauge_and_meta_stamp(self, chain_models):
        pipe, _ = self._run(CHAIN_DESC)
        g = get_registry().get("nns_shard_count",
                               pipeline=pipe.name, filter="fa")
        assert g is not None and float(g.value) == 8.0
        last = pipe.get("sink").buffers[-1]
        assert last.meta.get(serve.MESH_SPEC_META) == "dp8", \
            "sharded region output must carry its mesh-spec meta"

    def test_shard_span_recorded(self, chain_models):
        tl = _timeline.activate()
        try:
            self._run(CHAIN_DESC)
            names = {ev["name"] for ev in tl.to_chrome()["traceEvents"]}
        finally:
            _timeline.deactivate()
        assert "shard" in names, \
            "the placement wait must surface as its own ledger stage"

    def test_mismatched_boundary_is_plan_time_error(self, chain_models):
        desc = CHAIN_DESC.replace("model=mesh_sv_b name=fb mesh=dp8",
                                  "model=mesh_sv_b name=fb mesh=dp2xtp2")
        pipe = parse_launch(desc)
        try:
            with pytest.raises(MeshShardingError, match="fa.*fb|reshard"):
                pipe.start()
        finally:
            pipe.stop()

    def test_mixed_specs_in_one_region_rejected(self, chain_models):
        desc = CHAIN_DESC.replace("queue max-size-buffers=4 ! ", "")
        desc = desc.replace("model=mesh_sv_b name=fb mesh=dp8",
                            "model=mesh_sv_b name=fb mesh=dp4")
        pipe = parse_launch(desc)
        try:
            with pytest.raises(MeshShardingError):
                pipe.start()
        finally:
            pipe.stop()


# -- admission quantum + mesh-wide batch forming ------------------------------


class TestMeshQuantum:
    def test_scheduler_batch_cap_rounds_to_quantum(self):
        sched = SloScheduler(budget_ms=50.0)
        sched.note_mesh(8)
        sched.controller.batch_cap = 21
        assert sched.batch_cap() == 16, "cap rounds DOWN to a dp multiple"
        sched.controller.batch_cap = 3
        assert sched.batch_cap() == 8, "cap never rounds below one window"
        assert sched.snapshot()["mesh_quantum"] == 8

    def test_scheduler_quantum_one_is_identity(self):
        sched = SloScheduler(budget_ms=50.0)
        cap = sched.batch_cap()
        sched.note_mesh(1)
        assert sched.batch_cap() == cap

    def test_aggregator_rounds_frames_out_up(self):
        from nnstreamer_tpu.elements.aggregator import TensorAggregator

        agg = TensorAggregator("agg", frames_out=12)
        agg.note_mesh_quantum(8)
        assert int(agg.get_property("frames_out")) == 16
        agg.note_mesh_quantum(8)  # idempotent once aligned
        assert int(agg.get_property("frames_out")) == 16

    def test_aggregator_passthrough_untouched(self):
        from nnstreamer_tpu.elements.aggregator import TensorAggregator

        agg = TensorAggregator("agg", frames_out=1)
        agg.note_mesh_quantum(8)
        assert int(agg.get_property("frames_out")) == 1, \
            "per-frame service must stay per-frame"

    def test_pipeline_start_aligns_batch_former(self, chain_models):
        pipe = parse_launch(
            "appsrc name=src ! "
            "tensor_aggregator name=agg frames-in=1 frames-out=6 "
            "frames-dim=1 concat=true ! "
            "tensor_filter framework=jax model=mesh_sv_a mesh=dp8 ! "
            "tensor_sink name=sink to-host=true")
        src = pipe.get("src")
        pipe.start()
        try:
            assert int(pipe.get("agg").get_property("frames_out")) == 8, \
                "start() must round the former's window to the dp fan-out"
            for i in range(8):
                src.push([np.full((1, 4), float(i), np.float32)])
            src.end_of_stream()
            msg = pipe.wait(timeout=120)
            assert msg is not None and msg.kind == "eos", msg
        finally:
            pipe.stop()
        outs = [np.asarray(b.tensors[0])
                for b in pipe.get("sink").buffers]
        assert len(outs) == 1 and outs[0].shape == (8, 4)
        assert np.array_equal(
            outs[0], np.arange(8, dtype=np.float32)[:, None]
            .repeat(4, 1) * 2.0)


# -- per-shard HBM residency + sharded swap continuity ------------------------


@pytest.fixture(autouse=True)
def _clean_accountant():
    memory.deactivate()
    yield
    memory.deactivate()


class TestPerShardResidency:
    SHAPE = (64, 64)

    def _register(self, name, scale):
        w = jnp.ones(self.SHAPE, jnp.float32) * scale
        register_jax_model(
            name, lambda p, x: (x.astype(jnp.float32) * p["w"][0, 0],),
            {"w": w})
        return int(np.prod(self.SHAPE)) * 4

    def test_weights_account_once_per_shard(self):
        nbytes = self._register("mesh_sv_w", 2.0)
        try:
            acct = memory.activate(64 * nbytes)
            pipe = parse_launch(
                "appsrc name=src ! tensor_filter framework=jax "
                "model=mesh_sv_w name=filter mesh=dp8 ! "
                "tensor_sink name=sink to-host=true")
            src, sink = pipe.get("src"), pipe.get("sink")
            pipe.start()
            try:
                src.push([np.full((8, 4), 1.0, np.float32)])
                _wait(lambda: len(sink.buffers) >= 1, what="warm frame")
                assert acct._used.get("weights", 0) == 8 * nbytes, (
                    "a replicated dp8 placement is a full weight copy "
                    "per chip — nns_mem_used_bytes must count all 8")
                shard_keys = [k for k in acct.residency._units
                              if ":shard" in k]
                assert len(shard_keys) == 8
                src.end_of_stream()
                msg = pipe.wait(timeout=120)
                assert msg is not None and msg.kind == "eos", msg
            finally:
                pipe.stop()
        finally:
            unregister_jax_model("mesh_sv_w")

    def test_sharded_swap_retires_group_one_rejit_zero_drops(self):
        nbytes = self._register("mesh_sv_w", 2.0)
        try:
            acct = memory.activate(64 * nbytes)
            pipe = parse_launch(
                "appsrc name=src ! tensor_filter framework=jax "
                "model=mesh_sv_w name=filter mesh=dp8 ! "
                "tensor_sink name=sink to-host=true")
            src, sink = pipe.get("src"), pipe.get("sink")
            pipe.start()
            try:
                for i in range(5):
                    src.push([np.full((8, 4), float(i), np.float32)])
                _wait(lambda: len(sink.buffers) >= 5, what="first 5")
                used_before = acct.used_bytes()
                keys_before = {k for k in acct.residency._units
                               if ":shard" in k}
                assert len(keys_before) == 8

                new = {"w": jnp.ones(self.SHAPE, jnp.float32) * 5.0}
                report = pipe.swap_model("filter", weights=new)

                assert acct.used_bytes() == used_before, \
                    "per-shard swap must retire the whole old group"
                keys_after = {k for k in acct.residency._units
                              if ":shard" in k}
                assert len(keys_after) == 8
                assert keys_before.isdisjoint(keys_after)
                assert all(":e1:" in k for k in keys_after), \
                    "new group must be keyed by the bumped epoch"
                assert report["residency_unit"].endswith(":e1")

                src.push([np.full((8, 4), 1.0, np.float32)])
                _wait(lambda: len(sink.buffers) >= 6, what="post-swap")
                fw = pipe.get("filter").fw
                jitted_after_swap = fw._jitted
                assert jitted_after_swap is not None
                for i in range(4):
                    src.push([np.full((8, 4), float(i), np.float32)])
                src.end_of_stream()
                msg = pipe.wait(timeout=120)
                assert msg is not None and msg.kind == "eos", msg
                assert fw._jitted is jitted_after_swap, (
                    "a params-only sharded swap re-jits exactly once, "
                    "not per frame")
            finally:
                pipe.stop()
            outs = [np.asarray(b.tensors[0]) for b in sink.buffers]
            assert len(outs) == 10, "swap dropped frames"
            for i in range(5):  # old epoch: x * 2
                assert np.array_equal(
                    outs[i], np.full((8, 4), i * 2.0, np.float32))
            assert np.array_equal(outs[5],
                                  np.full((8, 4), 5.0, np.float32))
            for i, o in enumerate(outs[6:]):  # new epoch: x * 5
                assert np.array_equal(
                    o, np.full((8, 4), i * 5.0, np.float32))
        finally:
            unregister_jax_model("mesh_sv_w")


class TestPlacementAccounting:
    def test_place_params_registers_pinned_bytes(self):
        from jax.sharding import PartitionSpec as P

        from nnstreamer_tpu.parallel.mesh import make_mesh

        acct = memory.activate(1 << 30)
        mesh = make_mesh([("dp", 8)])
        params = {"w": np.ones((16, 16), np.float32)}
        placed = serve.place_params(params, mesh, {"w": P()},
                                    label="test:pinned")
        used = acct._used.get("weights", 0)
        assert used >= 8 * params["w"].nbytes, (
            "a replicated placement occupies every chip; the accountant "
            "must see the full multi-chip footprint")
        pinned = [u for u in acct.residency.snapshot()["units"]
                  if u["pinned"]]
        assert pinned, "external placements adopt as pinned units"
        del placed
        import gc

        gc.collect()
        assert acct._used.get("weights", 0) < used, \
            "dropping the placement must release its adopted bytes"
