"""protobuf decoder — tensors → serialized protobuf messages.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-protobuf.c`` (117 LoC)
with the ``Tensors`` message from ``nnstreamer.proto``:43-49. We build the
equivalent message dynamically with ``google.protobuf`` (descriptor_pb2) so
no generated code is shipped; the schema mirrors the reference's:

    message Tensor { string name=1; int32 type=2; repeated uint32
                     dimension=3; bytes data=4; }
    message Tensors { uint32 num_tensor=1; repeated Tensor tensor=2; }
"""

from __future__ import annotations

import threading

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import TensorInfo, TensorType

_TYPE_ORDER = list(TensorType)
_lock = threading.Lock()
_msgs = None


def _get_messages():
    """Build Tensor/Tensors message classes once (dynamic descriptor)."""
    global _msgs
    with _lock:
        if _msgs is not None:
            return _msgs
        from google.protobuf import descriptor_pb2, descriptor_pool, \
            message_factory

        fdp = descriptor_pb2.FileDescriptorProto()
        fdp.name = "nnstreamer_tpu_tensors.proto"
        fdp.package = "nnstreamer_tpu"
        t = fdp.message_type.add()
        t.name = "Tensor"
        f = t.field.add(); f.name = "name"; f.number = 1; \
            f.type = f.TYPE_STRING; f.label = f.LABEL_OPTIONAL
        f = t.field.add(); f.name = "type"; f.number = 2; \
            f.type = f.TYPE_INT32; f.label = f.LABEL_OPTIONAL
        f = t.field.add(); f.name = "dimension"; f.number = 3; \
            f.type = f.TYPE_UINT32; f.label = f.LABEL_REPEATED
        f = t.field.add(); f.name = "data"; f.number = 4; \
            f.type = f.TYPE_BYTES; f.label = f.LABEL_OPTIONAL
        ts = fdp.message_type.add()
        ts.name = "Tensors"
        f = ts.field.add(); f.name = "num_tensor"; f.number = 1; \
            f.type = f.TYPE_UINT32; f.label = f.LABEL_OPTIONAL
        f = ts.field.add(); f.name = "tensor"; f.number = 2; \
            f.type = f.TYPE_MESSAGE; f.label = f.LABEL_REPEATED; \
            f.type_name = ".nnstreamer_tpu.Tensor"
        pool = descriptor_pool.DescriptorPool()
        fd = pool.Add(fdp)
        tensor_cls = message_factory.GetMessageClass(
            fd.message_types_by_name["Tensor"])
        tensors_cls = message_factory.GetMessageClass(
            fd.message_types_by_name["Tensors"])
        _msgs = (tensor_cls, tensors_cls)
        return _msgs


def encode_protobuf(buf: TensorBuffer) -> bytes:
    Tensor, Tensors = _get_messages()
    msg = Tensors()
    host = buf.to_host()
    msg.num_tensor = host.num_tensors
    for t in host.tensors:
        info = TensorInfo.from_array(t)
        tm = msg.tensor.add()
        tm.type = _TYPE_ORDER.index(info.type)
        tm.dimension.extend(info.dim)
        tm.data = np.ascontiguousarray(t).tobytes()
    return msg.SerializeToString()


def decode_protobuf(blob: bytes) -> TensorBuffer:
    Tensor, Tensors = _get_messages()
    msg = Tensors()
    msg.ParseFromString(bytes(blob))
    tensors = []
    for tm in msg.tensor:
        ttype = _TYPE_ORDER[tm.type]
        shape = tuple(reversed(list(tm.dimension)))
        tensors.append(np.frombuffer(tm.data,
                                     ttype.np_dtype).reshape(shape))
    return TensorBuffer(tensors)


@subplugin(DECODER, "protobuf")
class ProtobufDecoder:
    def out_caps(self, config, options) -> Caps:
        return Caps("application/octet-stream", {"encoding": "protobuf"})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        blob = encode_protobuf(buf)
        return buf.with_tensors([np.frombuffer(blob, np.uint8)])
