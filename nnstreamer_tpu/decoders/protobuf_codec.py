"""protobuf decoder — tensors → serialized protobuf messages.

Reference: ``ext/nnstreamer/extra/nnstreamer_protobuf.cc`` with the
``Tensors`` message from ``ext/nnstreamer/include/nnstreamer.proto:26-41``.
The message classes are built dynamically with ``google.protobuf``
(descriptor_pb2) so no generated code is shipped, but the schema is
**byte-for-byte wire compatible** with the reference's::

    message Tensor  { string name=1; Tensor_type type=2;
                      repeated uint32 dimension=3; bytes data=4; }
    message Tensors { uint32 num_tensor=1; frame_rate fr=2
                      {int32 rate_n=1; int32 rate_d=2};
                      repeated Tensor tensor=3; Tensor_format format=4; }

(enums ride as varints, so declaring them int32 here is wire-identical;
``tests/test_codecs.py`` proves both directions against pb2 code protoc
generates from the reference's own .proto.)

Wire-format constraints inherited from the reference:

- **rank-4 normalizing**: the reference writes exactly
  ``NNS_TENSOR_RANK_LIMIT == 4`` dimension entries, 1-padded
  (nnstreamer_protobuf.cc:95-97, tensor_common.c:1294-1295), and its
  parser reads exactly 4 back — so decode yields rank-4 shapes (leading
  1-axes), and rank>4 tensors are refused (a reference peer would
  silently mis-size them; use flexbuf for rank>4).
- the reference ``Tensor_type`` enum has no fp16/bf16 — those are
  refused with a pointed error (typecast first).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import (
    Fraction,
    TensorFormat,
    TensorInfo,
)
from nnstreamer_tpu.tensors import wire

_lock = threading.Lock()
_msgs = None


def _get_messages():
    """Build Tensor/Tensors message classes once (dynamic descriptor)."""
    global _msgs
    with _lock:
        if _msgs is not None:
            return _msgs
        from google.protobuf import descriptor_pb2, descriptor_pool, \
            message_factory

        fdp = descriptor_pb2.FileDescriptorProto()
        fdp.name = "nnstreamer_tpu_tensors.proto"
        fdp.package = "nnstreamer.protobuf"
        fdp.syntax = "proto3"
        t = fdp.message_type.add()
        t.name = "Tensor"
        f = t.field.add(); f.name = "name"; f.number = 1; \
            f.type = f.TYPE_STRING; f.label = f.LABEL_OPTIONAL
        f = t.field.add(); f.name = "type"; f.number = 2; \
            f.type = f.TYPE_INT32; f.label = f.LABEL_OPTIONAL
        f = t.field.add(); f.name = "dimension"; f.number = 3; \
            f.type = f.TYPE_UINT32; f.label = f.LABEL_REPEATED
        f = t.field.add(); f.name = "data"; f.number = 4; \
            f.type = f.TYPE_BYTES; f.label = f.LABEL_OPTIONAL
        ts = fdp.message_type.add()
        ts.name = "Tensors"
        fr = ts.nested_type.add()
        fr.name = "frame_rate"
        f = fr.field.add(); f.name = "rate_n"; f.number = 1; \
            f.type = f.TYPE_INT32; f.label = f.LABEL_OPTIONAL
        f = fr.field.add(); f.name = "rate_d"; f.number = 2; \
            f.type = f.TYPE_INT32; f.label = f.LABEL_OPTIONAL
        f = ts.field.add(); f.name = "num_tensor"; f.number = 1; \
            f.type = f.TYPE_UINT32; f.label = f.LABEL_OPTIONAL
        f = ts.field.add(); f.name = "fr"; f.number = 2; \
            f.type = f.TYPE_MESSAGE; f.label = f.LABEL_OPTIONAL; \
            f.type_name = ".nnstreamer.protobuf.Tensors.frame_rate"
        f = ts.field.add(); f.name = "tensor"; f.number = 3; \
            f.type = f.TYPE_MESSAGE; f.label = f.LABEL_REPEATED; \
            f.type_name = ".nnstreamer.protobuf.Tensor"
        f = ts.field.add(); f.name = "format"; f.number = 4; \
            f.type = f.TYPE_INT32; f.label = f.LABEL_OPTIONAL
        pool = descriptor_pool.DescriptorPool()
        fd = pool.Add(fdp)
        tensor_cls = message_factory.GetMessageClass(
            fd.message_types_by_name["Tensor"])
        tensors_cls = message_factory.GetMessageClass(
            fd.message_types_by_name["Tensors"])
        _msgs = (tensor_cls, tensors_cls)
        return _msgs


def encode_protobuf(buf: TensorBuffer, rate: Optional[Fraction] = None,
                    fmt: TensorFormat = TensorFormat.STATIC) -> bytes:
    """Serialize a frame the way nnstreamer_protobuf.cc:44-130 does:
    ``fr`` always present (rate 0/1 when unknown), exactly 4 dimension
    entries per tensor, 1-padded."""
    Tensor, Tensors = _get_messages()
    msg = Tensors()
    host = buf.to_host()
    msg.num_tensor = host.num_tensors
    msg.fr.rate_n, msg.fr.rate_d = wire.rate_pair(rate)
    msg.format = wire.ref_format_index(fmt)
    names = buf.meta.get("tensor_names") or []
    for i, t in enumerate(host.tensors):
        info = TensorInfo.from_array(t)
        tm = msg.tensor.add()
        tm.name = str(names[i]) if i < len(names) and names[i] else ""
        tm.type = wire.ref_type_index(info, "protobuf", "mode=nnstpu-flex")
        tm.dimension.extend(wire.ref_dims(info, "protobuf",
                                          "mode=nnstpu-flex"))
        tm.data = np.ascontiguousarray(t).tobytes()
    return msg.SerializeToString()


def decode_protobuf(blob: bytes) -> TensorBuffer:
    """Parse a reference-format ``Tensors`` payload. Shapes keep the
    rank-4 wire dims (like the reference's parser,
    nnstreamer_protobuf.cc:160-176); framerate / format / tensor names
    land in ``buf.meta``."""
    Tensor, Tensors = _get_messages()
    msg = Tensors()
    msg.ParseFromString(bytes(blob))
    tensors = []
    names = []
    for tm in msg.tensor:
        ttype = wire.ref_type_from_index(tm.type, "protobuf")
        shape = tuple(reversed(list(tm.dimension)))
        tensors.append(np.frombuffer(tm.data,
                                     ttype.np_dtype).reshape(shape))
        names.append(tm.name or None)
    meta = {}
    if msg.fr.rate_n:
        meta["framerate"] = Fraction(msg.fr.rate_n, msg.fr.rate_d or 1)
    meta["format"] = wire.ref_format_from_index(msg.format,
                                                "protobuf").value
    if any(names):
        meta["tensor_names"] = names
    return TensorBuffer(tensors, meta=meta)


@subplugin(DECODER, "protobuf")
class ProtobufDecoder:
    def out_caps(self, config, options) -> Caps:
        return Caps("application/octet-stream", {"encoding": "protobuf"})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        rate = config.rate if config is not None and config.rate.num else None
        fmt = config.format if config is not None else TensorFormat.STATIC
        blob = encode_protobuf(buf, rate=rate, fmt=fmt)
        return buf.with_tensors([np.frombuffer(blob, np.uint8)])
