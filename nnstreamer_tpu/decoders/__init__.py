"""L5 decoder subplugins (reference ext/nnstreamer/tensor_decoder/)."""
