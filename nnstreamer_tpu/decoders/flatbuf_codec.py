"""flatbuf decoder/codec — tensors ↔ FlatBuffers ``Tensors`` tables.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-flatbuf.cc`` (211 LoC)
/ ``tensor_converter_flatbuf.cc`` (168 LoC) with the schema from
``ext/nnstreamer/include/nnstreamer.fbs``:

    table Tensor  { name:string; type:Tensor_type = NNS_END;
                    dimension:[uint32]; data:[ubyte]; }
    table Tensors { num_tensor:int; fr:frame_rate(struct);
                    tensor:[Tensor]; format:Tensor_format; }

Built directly with the ``flatbuffers`` runtime Builder/Table APIs — no
flatc-generated code is shipped; slot numbers follow schema declaration
order (field n ↦ vtable offset 4+2n). ``tests/test_codecs.py`` cross-
checks the slot ids, enum order, and defaults against the reference's
own ``.fbs`` text (and against flatc-generated accessors when flatc is
installed).

Invariants a reference peer relies on (tensor_converter_flatbuf.cc:
89-125 dereferences them unconditionally): ``fr`` and per-tensor
``name`` are always present, and ``dimension`` has exactly
``NNS_TENSOR_RANK_LIMIT == 4`` entries (tensordec-flatbuf.cc:126 writes
all four; the converter reads all four back). Reference wire
constraints (type enum without fp16/bf16, rank-4 1-padded dims) come
from ``tensors.wire`` like the protobuf/flexbuf codecs.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import CONVERTER, DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors import wire
from nnstreamer_tpu.tensors.types import (
    Fraction,
    TensorFormat,
    TensorInfo,
)

#: schema default for Tensor.type is NNS_END (nnstreamer.fbs:41) — the
#: value right past the last real dtype, i.e. "absent/invalid".
_TYPE_DEFAULT = wire.REF_TYPE_COUNT

try:
    import flatbuffers
    from flatbuffers import number_types as _N

    _HAVE_FLATBUFFERS = True
except ImportError:
    _HAVE_FLATBUFFERS = False


def _require():
    if not _HAVE_FLATBUFFERS:
        raise RuntimeError("flatbuf codec requires the 'flatbuffers' "
                           "package, which failed to import")


def encode_flatbuf(buf: TensorBuffer, rate: Optional[Fraction] = None,
                   fmt: TensorFormat = TensorFormat.STATIC) -> bytes:
    """Serialize a frame the way tensordec-flatbuf.cc:115-149 does:
    per-tensor [name ""-defaulted, type, 4 wire dims, data], then the
    root table with fr always present (0/1 when the rate is unknown)."""
    _require()
    b = flatbuffers.Builder(1024)
    host = buf.to_host()
    names = buf.meta.get("tensor_names") or []
    tensor_offs = []
    for i, t in enumerate(host.tensors):
        info = TensorInfo.from_array(t)
        type_idx = wire.ref_type_index(info, "flatbuf", "mode=nnstpu-flex")
        dims = wire.ref_dims(info, "flatbuf", "mode=nnstpu-flex")
        data_off = b.CreateByteVector(np.ascontiguousarray(t).tobytes())
        b.StartVector(4, len(dims), 4)
        for d in reversed(dims):
            b.PrependUint32(d)
        dim_off = b.EndVector()
        name_off = b.CreateString(str(names[i])
                                  if i < len(names) and names[i] else "")
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(0, name_off, 0)
        b.PrependInt32Slot(1, type_idx, _TYPE_DEFAULT)
        b.PrependUOffsetTRelativeSlot(2, dim_off, 0)
        b.PrependUOffsetTRelativeSlot(3, data_off, 0)
        tensor_offs.append(b.EndObject())
    b.StartVector(4, len(tensor_offs), 4)
    for off in reversed(tensor_offs):
        b.PrependUOffsetTRelative(off)
    vec_off = b.EndVector()
    rate_n, rate_d = wire.rate_pair(rate)
    b.StartObject(4)
    b.PrependInt32Slot(0, host.num_tensors, 0)
    # frame_rate struct is stored inline in the table and is always
    # present — the reference converter dereferences fr() blindly
    b.Prep(4, 8)
    b.PrependInt32(rate_d)
    b.PrependInt32(rate_n)
    b.PrependStructSlot(1, b.Offset(), 0)
    b.PrependUOffsetTRelativeSlot(2, vec_off, 0)
    b.PrependInt32Slot(3, wire.ref_format_index(fmt), 0)
    b.Finish(b.EndObject())
    return bytes(b.Output())


def decode_flatbuf(blob: bytes) -> TensorBuffer:
    """Parse a reference-format ``Tensors`` flatbuffer the way
    tensor_converter_flatbuf.cc:89-125 does (num_tensor-driven loop,
    4 wire dims kept as rank-4 shapes); framerate / format / names land
    in ``buf.meta``."""
    _require()
    data = bytearray(blob)
    root = flatbuffers.encode.Get(_N.UOffsetTFlags.packer_type, data, 0)
    tab = flatbuffers.Table(data, root)
    n_off = tab.Offset(4)  # slot 0: num_tensor
    num = tab.Get(_N.Int32Flags, n_off + tab.Pos) if n_off else 0
    if not 0 < num <= wire.REF_SIZE_LIMIT:
        raise ValueError(f"flatbuf codec: num_tensor {num} outside the "
                         f"reference range [1, {wire.REF_SIZE_LIMIT}]")
    f_off = tab.Offset(10)  # slot 3: format
    fmt_idx = tab.Get(_N.Int32Flags, f_off + tab.Pos) if f_off else 0
    fmt = wire.ref_format_from_index(fmt_idx, "flatbuf")
    meta = {"format": fmt.value}
    fr_off = tab.Offset(6)  # slot 1: frame_rate struct (inline)
    if fr_off:
        rate_n = tab.Get(_N.Int32Flags, fr_off + tab.Pos)
        rate_d = tab.Get(_N.Int32Flags, fr_off + tab.Pos + 4)
        if rate_n:
            meta["framerate"] = Fraction(rate_n, rate_d or 1)
    tensors, names = [], []
    vec = tab.Offset(8)  # slot 2: tensor vector
    if not vec or tab.VectorLen(vec) < num:
        raise ValueError("flatbuf codec: tensor vector shorter than "
                         "num_tensor")
    base = tab.Vector(vec)
    for i in range(num):
        sub_pos = tab.Indirect(base + i * 4)
        sub = flatbuffers.Table(data, sub_pos)
        name_off = sub.Offset(4)  # slot 0: name
        name = sub.String(name_off + sub.Pos).decode() if name_off else ""
        t_off = sub.Offset(6)  # slot 1: type
        # an absent field means the schema default NNS_END — invalid,
        # same as any other out-of-range value
        type_idx = sub.Get(_N.Int32Flags, t_off + sub.Pos) if t_off \
            else _TYPE_DEFAULT
        ttype = wire.ref_type_from_index(type_idx, "flatbuf")
        d_off = sub.Offset(8)  # slot 2: dimension
        dims = []
        if d_off:
            dn = sub.VectorLen(d_off)
            dbase = sub.Vector(d_off)
            dims = [sub.Get(_N.Uint32Flags, dbase + j * 4)
                    for j in range(dn)]
        b_off = sub.Offset(10)  # slot 3: data
        if b_off:
            start = sub.Vector(b_off)
            length = sub.VectorLen(b_off)
            raw = bytes(data[start:start + length])
        else:
            raw = b""
        shape = tuple(reversed(dims))
        tensors.append(np.frombuffer(raw, ttype.np_dtype).reshape(shape))
        names.append(name or None)
    if any(names):
        meta["tensor_names"] = names
    return TensorBuffer(tensors, meta=meta)


@subplugin(DECODER, "flatbuf")
class FlatbufDecoder:
    """tensors → serialized flatbuffer (other/flatbuf-tensor stream)."""

    def out_caps(self, config, options) -> Caps:
        return Caps("other/flatbuf-tensor")

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        rate = config.rate if config is not None and config.rate.num else None
        fmt = config.format if config is not None else TensorFormat.STATIC
        blob = encode_flatbuf(buf, rate=rate, fmt=fmt)
        return buf.with_tensors(
            [np.frombuffer(blob, np.uint8)])


@subplugin(CONVERTER, "flatbuf")
class FlatbufConverter:
    """serialized flatbuffer stream → other/tensors."""

    def get_out_config(self, caps):
        return None

    def convert(self, buf: TensorBuffer, in_caps) -> TensorBuffer:
        blob = np.ascontiguousarray(buf.to_host()[0]).tobytes()
        out = decode_flatbuf(blob)
        return out.replace(pts=buf.pts, meta={**out.meta, **buf.meta})
