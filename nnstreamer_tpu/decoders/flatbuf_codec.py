"""flatbuf decoder/codec — tensors ↔ FlatBuffers ``Tensors`` tables.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-flatbuf.cc`` (211 LoC)
/ ``tensor_converter_flatbuf.cc`` (168 LoC) with the schema from
``ext/nnstreamer/include/nnstreamer.fbs``:

    table Tensor  { name:string; type:Tensor_type; dimension:[uint32];
                    data:[ubyte]; }
    table Tensors { num_tensor:int; fr:frame_rate(struct);
                    tensor:[Tensor]; format:Tensor_format; }

Built directly with the ``flatbuffers`` runtime Builder/Table APIs — no
flatc-generated code is shipped; slot numbers follow schema declaration
order (field n ↦ vtable offset 4+2n).
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import CONVERTER, DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.types import TensorInfo, TensorType

_TYPE_ORDER = list(TensorType)

try:
    import flatbuffers
    from flatbuffers import number_types as _N

    _HAVE_FLATBUFFERS = True
except ImportError:
    _HAVE_FLATBUFFERS = False


def _require():
    if not _HAVE_FLATBUFFERS:
        raise RuntimeError("flatbuf codec requires the 'flatbuffers' "
                           "package, which failed to import")


def encode_flatbuf(buf: TensorBuffer, rate=None) -> bytes:
    _require()
    b = flatbuffers.Builder(1024)
    host = buf.to_host()
    tensor_offs = []
    for t in host.tensors:
        info = TensorInfo.from_array(t)
        data_off = b.CreateByteVector(np.ascontiguousarray(t).tobytes())
        dims = list(info.dim)
        b.StartVector(4, len(dims), 4)
        for d in reversed(dims):
            b.PrependUint32(d)
        dim_off = b.EndVector()
        name_off = b.CreateString("")
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(0, name_off, 0)
        b.PrependInt32Slot(1, _TYPE_ORDER.index(info.type), len(_TYPE_ORDER))
        b.PrependUOffsetTRelativeSlot(2, dim_off, 0)
        b.PrependUOffsetTRelativeSlot(3, data_off, 0)
        tensor_offs.append(b.EndObject())
    b.StartVector(4, len(tensor_offs), 4)
    for off in reversed(tensor_offs):
        b.PrependUOffsetTRelative(off)
    vec_off = b.EndVector()
    b.StartObject(4)
    b.PrependInt32Slot(0, host.num_tensors, 0)
    if rate is not None:
        # frame_rate struct is stored inline in the table; accepts the
        # framework Fraction (.num/.den) or the stdlib one
        num = getattr(rate, "num", None)
        den = getattr(rate, "den", None)
        if num is None:
            num, den = rate.numerator, rate.denominator
        b.Prep(4, 8)
        b.PrependInt32(int(den))
        b.PrependInt32(int(num))
        b.PrependStructSlot(1, b.Offset(), 0)
    b.PrependUOffsetTRelativeSlot(2, vec_off, 0)
    b.PrependInt32Slot(3, 0, 0)  # NNS_TENSOR_FORAMT_STATIC
    b.Finish(b.EndObject())
    return bytes(b.Output())


def decode_flatbuf(blob: bytes) -> TensorBuffer:
    _require()
    data = bytearray(blob)
    root = flatbuffers.encode.Get(_N.UOffsetTFlags.packer_type, data, 0)
    tab = flatbuffers.Table(data, root)
    tensors = []
    vec = tab.Offset(8)  # slot 2: tensor vector
    if vec:
        n = tab.VectorLen(vec)
        base = tab.Vector(vec)
        for i in range(n):
            sub_pos = tab.Indirect(base + i * 4)
            sub = flatbuffers.Table(data, sub_pos)
            t_off = sub.Offset(6)  # slot 1: type
            # an absent field means the schema default, enum value 0 =
            # NNS_INT32 — external flatc encoders omit default fields
            type_idx = sub.Get(_N.Int32Flags, t_off + sub.Pos) if t_off \
                else 0
            ttype = _TYPE_ORDER[type_idx]
            d_off = sub.Offset(8)  # slot 2: dimension
            dims = []
            if d_off:
                dn = sub.VectorLen(d_off)
                dbase = sub.Vector(d_off)
                dims = [sub.Get(_N.Uint32Flags, dbase + j * 4)
                        for j in range(dn)]
            b_off = sub.Offset(10)  # slot 3: data
            if b_off:
                start = sub.Vector(b_off)
                length = sub.VectorLen(b_off)
                raw = bytes(data[start:start + length])
            else:
                raw = b""
            shape = tuple(reversed(dims))
            tensors.append(np.frombuffer(raw, ttype.np_dtype).reshape(shape))
    return TensorBuffer(tensors)


@subplugin(DECODER, "flatbuf")
class FlatbufDecoder:
    """tensors → serialized flatbuffer (other/flatbuf-tensor stream)."""

    def out_caps(self, config, options) -> Caps:
        return Caps("other/flatbuf-tensor")

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        blob = encode_flatbuf(buf, rate=getattr(config, "rate", None))
        return buf.with_tensors(
            [np.frombuffer(blob, np.uint8)])


@subplugin(CONVERTER, "flatbuf")
class FlatbufConverter:
    """serialized flatbuffer stream → other/tensors."""

    def get_out_config(self, caps):
        return None

    def convert(self, buf: TensorBuffer, in_caps) -> TensorBuffer:
        blob = np.ascontiguousarray(buf.to_host()[0]).tobytes()
        out = decode_flatbuf(blob)
        return out.replace(pts=buf.pts, meta=dict(buf.meta))
