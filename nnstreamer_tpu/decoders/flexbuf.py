"""flexbuf decoder — tensors → FlexBuffers byte stream (reference wire
format).

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-flexbuf.cc:26-35``
documents the layout and :138-167 builds it::

    Map {
      "num_tensors" : UInt   | number of tensors
      "rate_n"      : Int    | framerate numerator
      "rate_d"      : Int    | framerate denominator
      "format"      : Int    | tensor_format (static=0/flexible=1/sparse=2)
      "tensor_#"    : Vector | [ name   : String,
                                 type   : Int  (reference tensor_type enum),
                                 dim    : TypedVector of
                                          NNS_TENSOR_RANK_LIMIT(=4) ints,
                                 data   : Blob ]
    }

``encode_flexbuf``/``decode_flexbuf`` speak exactly that, via
``flatbuffers.flexbuffers`` — a reference flexbuf peer
(tensor_converter mode=flexbuf / tensor_decoder mode=flexbuf) can
exchange streams with us; ``tests/test_codecs.py`` cross-proves it the
way the protobuf suite does.

Wire constraints inherited from the reference (same as the protobuf
codec): exactly 4 dimension entries, 1-padded, innermost-first
(tensor_converter_flexbuf.cc:131-134 reads exactly
NNS_TENSOR_RANK_LIMIT back); the reference tensor_type enum
(tensor_typedef.h:154-166) has no fp16/bf16, so those are refused.

The framework's own compact framing (u32 count, i64 pts, per-tensor
flex header + payload — supports rank>4, fp16/bf16, and carries pts) is
kept under mode ``nnstpu-flex``; the query protocol and gRPC bridge
ride it (``encode_flex``/``decode_flex``).
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.meta import pack_tensor, unpack_tensor
from nnstreamer_tpu.tensors.types import (
    Fraction,
    TensorFormat,
    TensorInfo,
)
from nnstreamer_tpu.tensors import wire


def encode_flexbuf(buf: TensorBuffer, rate: Optional[Fraction] = None,
                   fmt: TensorFormat = TensorFormat.STATIC) -> bytes:
    """Serialize a frame the way tensordec-flexbuf.cc:138-168 does —
    same map keys, same per-tensor vector slot order, 4 dims 1-padded."""
    from flatbuffers import flexbuffers

    host = buf.to_host()
    names = buf.meta.get("tensor_names") or []
    rate_n, rate_d = wire.rate_pair(rate)
    fbb = flexbuffers.Builder()
    with fbb.Map():
        fbb.Key("num_tensors")
        fbb.UInt(host.num_tensors)
        fbb.Key("rate_n")
        fbb.Int(rate_n)
        fbb.Key("rate_d")
        fbb.Int(rate_d)
        fbb.Key("format")
        fbb.Int(wire.ref_format_index(fmt))
        for i, t in enumerate(host.tensors):
            info = TensorInfo.from_array(t)
            type_idx = wire.ref_type_index(info, "flexbuf",
                                           "mode=nnstpu-flex")
            dims = wire.ref_dims(info, "flexbuf", "mode=nnstpu-flex")
            fbb.Key(f"tensor_{i}")
            with fbb.Vector():
                fbb.String(str(names[i])
                           if i < len(names) and names[i] else "")
                fbb.Int(type_idx)
                fbb.TypedVectorFromElements(dims)
                fbb.Blob(np.ascontiguousarray(t).tobytes())
    return bytes(fbb.Finish())


def decode_flexbuf(blob: bytes) -> TensorBuffer:
    """Parse a reference-format flexbuf payload the way
    tensor_converter_flexbuf.cc:107-141 does. Shapes keep the rank-4
    wire dims; framerate / format / tensor names land in ``buf.meta``."""
    from flatbuffers import flexbuffers

    root = flexbuffers.GetRoot(bytes(blob))
    if not root.IsMap:
        raise ValueError("flexbuf codec: payload root is not a map")
    m = root.AsMap
    num = m["num_tensors"].AsInt
    if not 0 < num <= wire.REF_SIZE_LIMIT:
        raise ValueError(f"flexbuf codec: num_tensors {num} outside the "
                         f"reference range [1, {wire.REF_SIZE_LIMIT}]")
    rate_n = m["rate_n"].AsInt
    rate_d = m["rate_d"].AsInt
    fmt = wire.ref_format_from_index(m["format"].AsInt, "flexbuf")
    tensors, names = [], []
    for i in range(num):
        vec = m[f"tensor_{i}"].AsVector
        name = vec[0].AsString
        ttype = wire.ref_type_from_index(vec[1].AsInt, "flexbuf")
        dims = [d.AsInt for d in vec[2].AsTypedVector]
        data = bytes(vec[3].AsBlob)
        shape = tuple(reversed(dims))
        tensors.append(np.frombuffer(data, ttype.np_dtype).reshape(shape))
        names.append(name or None)
    meta = {"format": fmt.value}
    if rate_n:
        meta["framerate"] = Fraction(rate_n, rate_d or 1)
    if any(names):
        meta["tensor_names"] = names
    return TensorBuffer(tensors, meta=meta)


# ---------------------------------------------------------------------------
# Framework-native compact framing ("nnstpu-flex")
# ---------------------------------------------------------------------------

_HDR = struct.Struct("<Iq")


def encode_flex(buf: TensorBuffer) -> bytes:
    """Framework-native framing: u32 num_tensors, i64 pts, then
    per-tensor flex header (``tensors.meta``) + payload. Unlike the
    reference flexbuf format it carries pts and supports rank>4 and
    fp16/bf16 — the query protocol and gRPC bridge use it."""
    host = buf.to_host()
    parts = [_HDR.pack(host.num_tensors,
                       -1 if buf.pts is None else buf.pts)]
    parts += [pack_tensor(t) for t in host.tensors]
    return b"".join(parts)


def decode_flex(blob: bytes) -> TensorBuffer:
    n, pts = _HDR.unpack_from(blob)
    offset = _HDR.size
    tensors = []
    for _ in range(n):
        arr, offset = unpack_tensor(blob, offset)
        tensors.append(arr)
    return TensorBuffer(tensors, pts=None if pts < 0 else pts)


@subplugin(DECODER, "flexbuf")
class FlexBufDecoder:
    """tensors → reference-format FlexBuffers byte stream."""

    def out_caps(self, config, options) -> Caps:
        return Caps("application/octet-stream", {"encoding": "flexbuf"})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        rate = config.rate if config is not None and config.rate.num else None
        fmt = config.format if config is not None else TensorFormat.STATIC
        blob = encode_flexbuf(buf, rate=rate, fmt=fmt)
        return buf.with_tensors([np.frombuffer(blob, np.uint8)])


@subplugin(DECODER, "nnstpu-flex")
class NnstpuFlexDecoder:
    """tensors → framework-native compact flex framing."""

    def out_caps(self, config, options) -> Caps:
        return Caps("application/octet-stream", {"encoding": "nnstpu-flex"})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        blob = encode_flex(buf)
        return buf.with_tensors([np.frombuffer(blob, np.uint8)])
