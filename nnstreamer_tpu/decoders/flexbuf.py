"""flexbuf decoder — tensors → serialized self-describing byte stream.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-flexbuf.c`` (230 LoC)
serializes tensors with FlexBuffers. Our wire format is the framework's
own flex-header framing (``tensors.meta``): u32 num_tensors, i64 pts, then
per-tensor header+payload — compact, schema-free, and identical to what
the query protocol uses, so flexbuf-encoded streams interoperate with
every other serialized path in the framework. The matching converter
(``converters.flexbuf``) reverses it.
"""

from __future__ import annotations

import struct

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer
from nnstreamer_tpu.tensors.meta import pack_tensor, unpack_tensor

_HDR = struct.Struct("<Iq")


def encode_flex(buf: TensorBuffer) -> bytes:
    host = buf.to_host()
    parts = [_HDR.pack(host.num_tensors,
                       -1 if buf.pts is None else buf.pts)]
    parts += [pack_tensor(t) for t in host.tensors]
    return b"".join(parts)


def decode_flex(blob: bytes) -> TensorBuffer:
    n, pts = _HDR.unpack_from(blob)
    offset = _HDR.size
    tensors = []
    for _ in range(n):
        arr, offset = unpack_tensor(blob, offset)
        tensors.append(arr)
    return TensorBuffer(tensors, pts=None if pts < 0 else pts)


@subplugin(DECODER, "flexbuf")
class FlexBufDecoder:
    def out_caps(self, config, options) -> Caps:
        return Caps("application/octet-stream", {"encoding": "flexbuf"})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        blob = encode_flex(buf)
        return buf.with_tensors([np.frombuffer(blob, np.uint8)])
