"""image_segment decoder — segmentation logits → class-colored video.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-imagesegment.c``
(660 LoC): per-pixel argmax over class maps → colored RGBA frame
(tflite-deeplab mode).
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def _palette(n: int) -> np.ndarray:
    """Deterministic label colors (the PASCAL-VOC bit-twiddling palette)."""
    pal = np.zeros((n, 3), np.uint8)
    for i in range(n):
        c, r, g, b = i, 0, 0, 0
        for j in range(8):
            r |= ((c >> 0) & 1) << (7 - j)
            g |= ((c >> 1) & 1) << (7 - j)
            b |= ((c >> 2) & 1) << (7 - j)
            c >>= 3
        pal[i] = (r, g, b)
    return pal


@subplugin(DECODER, "image_segment")
class ImageSegment:
    def out_caps(self, config, options) -> Caps:
        fields = {"format": "RGBA"}
        if config is not None and config.info.is_valid():
            dim = config.info[0].dim  # (C, W, H, N)
            fields.update(width=dim[1], height=dim[2])
        return Caps("video/x-raw", fields)

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        seg = np.asarray(buf[0])
        if seg.ndim == 4:
            seg = seg[0]               # (H, W, C)
        if seg.ndim == 3 and seg.shape[2] > 1:
            labels = seg.argmax(axis=2)
        else:
            labels = seg.reshape(seg.shape[0], seg.shape[1]).astype(int)
        return self._emit(buf, labels)

    def _emit(self, buf: TensorBuffer, labels: np.ndarray) -> TensorBuffer:
        pal = _palette(int(labels.max()) + 1)
        rgb = pal[labels]
        alpha = np.where(labels > 0, 192, 0).astype(np.uint8)[..., None]
        return buf.with_tensors(
            [np.concatenate([rgb, alpha], axis=2)]
        ).replace(meta={**buf.meta, "segment_labels": labels})

    # -- fused-region split (elements/decoder.py device_stage) ---------------
    def device_kernel(self, options):
        """Device half: per-pixel argmax inside the fused program — an
        [H, W] int32 class map leaves the device instead of [H, W, C]
        float logits (C× less D2H traffic; palette/alpha stay host-side)."""
        import jax.numpy as jnp

        def fn(consts, tensors):
            seg = tensors[0]
            if seg.ndim == 4:
                seg = seg[0]
            if seg.ndim == 3 and seg.shape[2] > 1:
                labels = jnp.argmax(seg, axis=2)
            else:
                labels = seg.reshape(seg.shape[0], seg.shape[1])
            return [labels.astype(jnp.int32)]

        return None, fn

    def host_finalize(self, host_buf: TensorBuffer, config, options
                      ) -> TensorBuffer:
        labels = np.asarray(host_buf[0]).astype(int)
        return self._emit(host_buf, labels)
