"""pose_estimation decoder — keypoint heatmaps → skeleton keypoints.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-pose.c`` (824 LoC):
consumes PoseNet heatmaps (+offsets), finds per-keypoint argmax, refines
with offsets, outputs either an overlay or keypoint metadata.

Options: option1 = video WIDTH:HEIGHT (overlay size), option2 = "meta"
for structured output only, option3 = score threshold.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer

# COCO keypoint skeleton edges (for overlay drawing)
EDGES = [(0, 1), (0, 2), (1, 3), (2, 4), (5, 6), (5, 7), (7, 9), (6, 8),
         (8, 10), (5, 11), (6, 12), (11, 12), (11, 13), (13, 15), (12, 14),
         (14, 16)]


def decode_pose(heatmaps: np.ndarray, offsets=None, threshold: float = 0.3):
    """heatmaps [H, W, K] (+optional offsets [H, W, 2K]) → list of
    {keypoint, y, x, score} with y/x normalized to [0,1]."""
    H, W, K = heatmaps.shape
    out = []
    for k in range(K):
        hm = heatmaps[:, :, k]
        idx = np.unravel_index(np.argmax(hm), hm.shape)
        score = float(hm[idx])
        y, x = float(idx[0]), float(idx[1])
        if offsets is not None:
            y += float(offsets[idx[0], idx[1], k])
            x += float(offsets[idx[0], idx[1], K + k])
        out.append({
            "keypoint": k,
            "y": y / max(H - 1, 1),
            "x": x / max(W - 1, 1),
            "score": score,
            "visible": score >= threshold,
        })
    return out


def draw_pose(width: int, height: int, keypoints) -> np.ndarray:
    img = np.zeros((height, width, 4), np.uint8)
    pts = {}
    for kp in keypoints:
        if not kp["visible"]:
            continue
        xi = int(np.clip(kp["x"] * (width - 1), 0, width - 1))
        yi = int(np.clip(kp["y"] * (height - 1), 0, height - 1))
        pts[kp["keypoint"]] = (yi, xi)
        img[max(0, yi - 1):yi + 2, max(0, xi - 1):xi + 2] = \
            [255, 0, 0, 255]
    for a, b in EDGES:
        if a in pts and b in pts:
            (y1, x1), (y2, x2) = pts[a], pts[b]
            n = max(abs(y2 - y1), abs(x2 - x1), 1)
            ys = np.linspace(y1, y2, n + 1).astype(int)
            xs = np.linspace(x1, x2, n + 1).astype(int)
            img[ys, xs] = [0, 255, 0, 255]
    return img


@subplugin(DECODER, "pose_estimation")
class PoseEstimation:
    def _opts(self, options):
        size = (options.get("option1") or "257:257").split(":")
        return dict(width=int(size[0]), height=int(size[1]),
                    meta_only=(options.get("option2") == "meta"),
                    threshold=float(options.get("option3") or 0.3))

    def out_caps(self, config, options) -> Caps:
        o = self._opts(options)
        if o["meta_only"]:
            return Caps("other/tensors", {"format": "flexible"})
        return Caps("video/x-raw", {"format": "RGBA", "width": o["width"],
                                    "height": o["height"]})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        o = self._opts(options)
        heat = np.asarray(buf[0], np.float32)
        offs = np.asarray(buf[1], np.float32) if buf.num_tensors > 1 \
            else None
        if heat.ndim == 4 and heat.shape[0] > 1:
            # batched heatmaps (mux'd multi-stream invoke): per-frame
            # keypoint lists — nothing silently dropped
            kps = [decode_pose(heat[b],
                               None if offs is None else offs[b],
                               o["threshold"])
                   for b in range(heat.shape[0])]
        else:
            if heat.ndim == 4:
                heat = heat[0]
            if offs is not None and offs.ndim == 4:
                offs = offs[0]
            kps = decode_pose(heat, offs, o["threshold"])
        return self._emit(buf, kps, o)

    def _emit(self, buf: TensorBuffer, kps, o) -> TensorBuffer:
        meta = {**buf.meta, "keypoints": kps}
        batched = bool(kps) and isinstance(kps[0], list)
        if o["meta_only"]:
            frames = kps if batched else [kps]
            flat = np.asarray(
                [[[kp["y"], kp["x"], kp["score"]] for kp in fr]
                 for fr in frames], np.float32)
            if not batched:
                flat = flat[0]
            return buf.with_tensors([flat]).replace(meta=meta)
        if batched:
            # overlay caps declare ONE video frame; a batched overlay
            # needs a demux upstream — refuse rather than emit frames a
            # caps-respecting consumer would silently drop
            raise ValueError(
                "pose_estimation: batched heatmaps require option2=meta "
                "(overlay output is single-frame; demux the stream first)")
        return buf.with_tensors(
            [draw_pose(o["width"], o["height"], kps)]
        ).replace(meta=meta)

    # -- fused-region split (elements/decoder.py device_stage) ---------------
    def device_kernel(self, options):
        """Device half of decode(): per-keypoint heatmap argmax (+offset
        refinement) inside the fused XLA program — [K, 3] (y, x, score)
        rows leave the device instead of full heatmaps."""
        import jax.numpy as jnp

        def one(heat, offs):
            """[H,W,K](+[H,W,2K]) → [K,3] (y, x, score), all on device."""
            H, W, K = heat.shape
            flat = heat.reshape(-1, K)
            j = jnp.argmax(flat, axis=0)                      # [K]
            score = jnp.take_along_axis(flat, j[None, :], axis=0)[0]
            ys = (j // W).astype(jnp.float32)
            xs = (j % W).astype(jnp.float32)
            if offs is not None:
                offs_flat = offs.reshape(-1, offs.shape[-1])
                kk = jnp.arange(K)
                ys = ys + offs_flat[j, kk]
                xs = xs + offs_flat[j, K + kk]
            y = ys / max(H - 1, 1)
            x = xs / max(W - 1, 1)
            return jnp.stack([y, x, score], axis=1)

        def fn(consts, tensors):
            heat = tensors[0].astype(jnp.float32)
            offs = tensors[1].astype(jnp.float32) if len(tensors) > 1 \
                else None
            if heat.ndim == 4 and heat.shape[0] > 1:
                # batched heatmaps (mux'd multi-stream invoke): one [K,3]
                # block per frame — nothing silently dropped
                import jax

                if offs is not None:
                    return [jax.vmap(one, in_axes=(0, 0))(heat, offs)]
                return [jax.vmap(lambda h: one(h, None))(heat)]
            if heat.ndim == 4:  # B==1: squeeze, matching the host path
                heat = heat[0]
                offs = None if offs is None else offs[0]
            return [one(heat, offs)]

        return None, fn

    def host_finalize(self, host_buf: TensorBuffer, config, options
                      ) -> TensorBuffer:
        o = self._opts(options)
        arr = np.asarray(host_buf[0], np.float32)

        def to_kps(rows):
            return [{
                "keypoint": k,
                "y": float(r[0]),
                "x": float(r[1]),
                "score": float(r[2]),
                "visible": float(r[2]) >= o["threshold"],
            } for k, r in enumerate(rows)]

        if arr.ndim == 3:  # batched: per-frame keypoint lists
            kps = [to_kps(frame) for frame in arr]
        else:
            kps = to_kps(arr.reshape(-1, 3))
        return self._emit(host_buf, kps, o)
