"""octet_stream decoder — tensors → raw byte stream.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-octetstream.c`` (130
LoC): concatenates tensor payloads into application/octet-stream bytes.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


@subplugin(DECODER, "octet_stream")
class OctetStream:
    def out_caps(self, config, options) -> Caps:
        return Caps("application/octet-stream", {})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        blob = b"".join(
            np.ascontiguousarray(np.asarray(t)).tobytes() for t in buf.tensors
        )
        return buf.with_tensors([np.frombuffer(blob, np.uint8)])
