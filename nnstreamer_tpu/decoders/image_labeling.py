"""image_labeling decoder — classification scores → text label.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-imagelabel.c`` (271
LoC): argmax over the score tensor, label looked up from the option1 labels
file, output ``text/x-raw``.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def load_labels(path: str):
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


@subplugin(DECODER, "image_labeling")
class ImageLabeling:
    def __init__(self):
        self._labels = None
        self._labels_path = None

    def _get_labels(self, options):
        path = options.get("option1")
        if path and path != self._labels_path:
            self._labels = load_labels(path)
            self._labels_path = path
        return self._labels

    def out_caps(self, config, options) -> Caps:
        return Caps("text/x-raw", {"format": "utf8"})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        scores = np.asarray(buf[0]).reshape(-1)
        idx = int(np.argmax(scores))
        labels = self._get_labels(options)
        text = labels[idx] if labels and idx < len(labels) else str(idx)
        out = np.frombuffer(text.encode("utf-8"), np.uint8)
        return buf.with_tensors([out]).replace(
            meta={**buf.meta, "label_index": idx, "label": text,
                  "score": float(scores[idx])}
        )
