"""image_labeling decoder — classification scores → text label.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-imagelabel.c`` (271
LoC): argmax over the score tensor, label looked up from the option1 labels
file, output ``text/x-raw``.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def load_labels(path: str):
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


@subplugin(DECODER, "image_labeling")
class ImageLabeling:
    def __init__(self):
        self._labels = None
        self._labels_path = None

    def _get_labels(self, options):
        path = options.get("option1")
        if path and path != self._labels_path:
            self._labels = load_labels(path)
            self._labels_path = path
        return self._labels

    def out_caps(self, config, options) -> Caps:
        return Caps("text/x-raw", {"format": "utf8"})

    @staticmethod
    def _batched(options) -> bool:
        """option2=batched: rows of tensor[0] are separate frames (an
        upstream tensor_aggregator micro-batch) — one label per row. The
        default keeps reference semantics: argmax over the whole tensor
        (a 2-D score tensor is ONE frame, tensordec-imagelabel.c)."""
        return str(options.get("option2", "")).strip().lower() in (
            "batched", "batch", "per-row")

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        scores = np.asarray(buf[0])
        if self._batched(options) and scores.ndim >= 2:
            flat = scores.reshape(scores.shape[0], -1)
            idxs = np.argmax(flat, axis=-1)
            tops = flat[np.arange(flat.shape[0]), idxs]
            return self._emit(buf, idxs.tolist(), tops.tolist(), options)
        flat = scores.reshape(-1)
        idx = int(np.argmax(flat))
        return self._emit(buf, idx, float(flat[idx]), options)

    def _emit(self, buf, idx, score, options) -> TensorBuffer:
        labels = self._get_labels(options)

        def name(i):
            return labels[i] if labels and i < len(labels) else str(i)

        if isinstance(idx, list):
            texts = [name(int(i)) for i in idx]
            out = np.frombuffer("\n".join(texts).encode("utf-8"), np.uint8)
            return buf.with_tensors([out]).replace(
                meta={**buf.meta, "label_index": [int(i) for i in idx],
                      "label": texts, "score": [float(s) for s in score]}
            )
        text = name(int(idx))
        out = np.frombuffer(text.encode("utf-8"), np.uint8)
        return buf.with_tensors([out]).replace(
            meta={**buf.meta, "label_index": int(idx), "label": text,
                  "score": float(score)}
        )

    # -- fused-region split (elements/decoder.py device_stage) ---------------
    def device_kernel(self, options):
        """Device half: argmax + top score stay in the XLA program, so only
        per-frame scalars ever cross the tunnel instead of the full score
        tensor (one pair per batch row with option2=batched)."""
        import jax.numpy as jnp

        batched = self._batched(options)

        def fn(consts, tensors):
            s = tensors[0]
            rows = s.reshape(s.shape[0], -1) if batched and s.ndim >= 2 \
                else s.reshape(1, -1)
            return [jnp.argmax(rows, axis=-1).astype(jnp.int32),
                    jnp.max(rows, axis=-1).astype(jnp.float32)]

        return None, fn

    def host_finalize(self, host_buf: TensorBuffer, config, options
                      ) -> TensorBuffer:
        idxs = np.asarray(host_buf[0]).reshape(-1)
        scores = np.asarray(host_buf[1]).reshape(-1)
        if idxs.size > 1:
            return self._emit(host_buf, idxs.tolist(), scores.tolist(),
                              options)
        return self._emit(host_buf, int(idxs[0]), float(scores[0]), options)
