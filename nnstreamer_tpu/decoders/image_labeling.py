"""image_labeling decoder — classification scores → text label.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-imagelabel.c`` (271
LoC): argmax over the score tensor, label looked up from the option1 labels
file, output ``text/x-raw``.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def load_labels(path: str):
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


@subplugin(DECODER, "image_labeling")
class ImageLabeling:
    def __init__(self):
        self._labels = None
        self._labels_path = None

    def _get_labels(self, options):
        path = options.get("option1")
        if path and path != self._labels_path:
            self._labels = load_labels(path)
            self._labels_path = path
        return self._labels

    def out_caps(self, config, options) -> Caps:
        return Caps("text/x-raw", {"format": "utf8"})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        scores = np.asarray(buf[0]).reshape(-1)
        idx = int(np.argmax(scores))
        return self._emit(buf, idx, float(scores[idx]), options)

    def _emit(self, buf, idx: int, score: float, options) -> TensorBuffer:
        labels = self._get_labels(options)
        text = labels[idx] if labels and idx < len(labels) else str(idx)
        out = np.frombuffer(text.encode("utf-8"), np.uint8)
        return buf.with_tensors([out]).replace(
            meta={**buf.meta, "label_index": idx, "label": text,
                  "score": score}
        )

    # -- fused-region split (elements/decoder.py device_stage) ---------------
    def device_kernel(self, options):
        """Device half: argmax + top score stay in the XLA program, so only
        two scalars ever cross the tunnel instead of the full score tensor."""
        import jax.numpy as jnp

        def fn(consts, tensors):
            scores = tensors[0].reshape(-1)
            return [jnp.argmax(scores).astype(jnp.int32),
                    jnp.max(scores).astype(jnp.float32)]

        return None, fn

    def host_finalize(self, host_buf: TensorBuffer, config, options
                      ) -> TensorBuffer:
        idx = int(host_buf[0])
        score = float(host_buf[1])
        return self._emit(host_buf, idx, score, options)
