"""direct_video decoder — tensor → raw video frames.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-directvideo.c`` (377
LoC): reinterpret a uint8 tensor of dim (C,W,H,N) as video/x-raw frames.
"""

from __future__ import annotations

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer

_FMT = {1: "GRAY8", 3: "RGB", 4: "RGBA"}


@subplugin(DECODER, "direct_video")
class DirectVideo:
    def out_caps(self, config, options) -> Caps:
        fields = {}
        if config is not None and config.info.is_valid():
            dim = config.info[0].dim  # (C, W, H, N)
            ch = dim[0]
            if ch not in _FMT:
                raise ValueError(f"direct_video: {ch} channels unsupported")
            fields = {
                "format": options.get("option1", _FMT[ch]).upper() or _FMT[ch],
                "width": dim[1] if len(dim) > 1 else 1,
                "height": dim[2] if len(dim) > 2 else 1,
            }
            if config.rate.num > 0:
                fields["framerate"] = str(config.rate)
        return Caps("video/x-raw", fields)

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        arr = np.asarray(buf[0])  # shape (N,H,W,C)
        if arr.ndim == 4 and arr.shape[0] == 1:
            arr = arr[0]
        return buf.with_tensors([np.ascontiguousarray(arr.astype(np.uint8))])
