"""python3 decoder — user-script decoders.

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-python3.cc`` (405 LoC):
loads a user script whose class implements getOutCaps/decode. Here the
script (option1) defines::

    class Decoder:
        def out_caps(self, config, options): ...   # optional
        def decode(self, buf, config, options): ...
"""

from __future__ import annotations

import importlib.util
import os
import sys

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


@subplugin(DECODER, "python3")
class Python3Decoder:
    def __init__(self):
        self._obj = None
        self._path = None

    def _load(self, options):
        path = options.get("option1")
        if not path:
            raise ValueError("python3 decoder: option1=<script.py> required")
        if self._obj is None or path != self._path:
            if not os.path.isfile(path):
                raise FileNotFoundError(f"python3 decoder: {path!r}")
            spec = importlib.util.spec_from_file_location(
                f"nnstreamer_tpu_pydec_{os.path.basename(path).replace('.', '_')}",
                path,
            )
            mod = importlib.util.module_from_spec(spec)
            sys.modules[spec.name] = mod
            spec.loader.exec_module(mod)
            cls = getattr(mod, "Decoder", None)
            if cls is None:
                raise ValueError(
                    f"python3 decoder: {path!r} must define class Decoder"
                )
            self._obj = cls()
            self._path = path
        return self._obj

    def out_caps(self, config, options) -> Caps:
        obj = self._load(options)
        if hasattr(obj, "out_caps"):
            return obj.out_caps(config, options)
        return Caps("other/tensors", {"format": "flexible"})

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        return self._load(options).decode(buf, config, options)
