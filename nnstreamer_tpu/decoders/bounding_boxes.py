"""bounding_boxes decoder — detection tensors → boxes (+ overlay video).

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c``
(1427 LoC) — modes mobilenet-ssd (anchor-decode + NMS), -postprocess
(pre-decoded boxes), yolov5/yolov8 (tensordec-boundingbox.c:128-139).
Output: either RGBA overlay video (reference behavior) or, with
``option7=meta``, the raw box list in buffer meta (TPU pipelines usually
want the structured result, not pixels).

Options (mirroring the reference's option1..N):
  option1: mode — mobilenet-ssd | mobilenet-ssd-postprocess | yolov5
  option2: labels file
  option3: score threshold (default 0.5)        [reference: custom props]
  option4: video WIDTH:HEIGHT for overlay scaling (default 300:300)
  option5: iou threshold for NMS (default 0.5)
  option7: "meta" → no overlay, boxes in meta only
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def nms(boxes: np.ndarray, scores: np.ndarray, iou_thresh: float = 0.5,
        max_out: int = 100) -> List[int]:
    """Greedy non-max suppression; boxes [N,4] as (y1,x1,y2,x2)."""
    order = np.argsort(-scores)
    keep: List[int] = []
    while order.size and len(keep) < max_out:
        i = order[0]
        keep.append(int(i))
        if order.size == 1:
            break
        rest = order[1:]
        yy1 = np.maximum(boxes[i, 0], boxes[rest, 0])
        xx1 = np.maximum(boxes[i, 1], boxes[rest, 1])
        yy2 = np.minimum(boxes[i, 2], boxes[rest, 2])
        xx2 = np.minimum(boxes[i, 3], boxes[rest, 3])
        inter = np.maximum(0, yy2 - yy1) * np.maximum(0, xx2 - xx1)
        area_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        area_r = (boxes[rest, 2] - boxes[rest, 0]) * \
            (boxes[rest, 3] - boxes[rest, 1])
        iou = inter / np.maximum(area_i + area_r - inter, 1e-9)
        order = rest[iou <= iou_thresh]
    return keep


def decode_ssd(box_enc: np.ndarray, scores: np.ndarray,
               anchors: np.ndarray, score_thresh: float,
               iou_thresh: float) -> List[dict]:
    """Anchor-relative SSD decode (reference mobilenet-ssd mode math):
    box_enc [A,4] as (ty,tx,th,tw) vs anchors [A,4] (cy,cx,h,w)."""
    cy = box_enc[:, 0] / 10.0 * anchors[:, 2] + anchors[:, 0]
    cx = box_enc[:, 1] / 10.0 * anchors[:, 3] + anchors[:, 1]
    h = np.exp(box_enc[:, 2] / 5.0) * anchors[:, 2]
    w = np.exp(box_enc[:, 3] / 5.0) * anchors[:, 3]
    boxes = np.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], axis=1)
    probs = 1.0 / (1.0 + np.exp(-scores))  # sigmoid scores
    out = []
    for cls in range(1, probs.shape[1]):  # class 0 = background
        mask = probs[:, cls] >= score_thresh
        if not mask.any():
            continue
        cls_boxes, cls_scores = boxes[mask], probs[mask, cls]
        for i in nms(cls_boxes, cls_scores, iou_thresh):
            out.append({
                "class": cls,
                "score": float(cls_scores[i]),
                "box": [float(v) for v in cls_boxes[i]],  # y1,x1,y2,x2 ∈[0,1]
            })
    out.sort(key=lambda d: -d["score"])
    return out


def draw_boxes(width: int, height: int, detections: List[dict]
               ) -> np.ndarray:
    """RGBA overlay frame (transparent except box outlines) — the
    reference's output form for compositing over video."""
    img = np.zeros((height, width, 4), np.uint8)
    for det in detections:
        y1, x1, y2, x2 = det["box"]
        xi1, yi1 = int(np.clip(x1 * width, 0, width - 1)), \
            int(np.clip(y1 * height, 0, height - 1))
        xi2, yi2 = int(np.clip(x2 * width, 0, width - 1)), \
            int(np.clip(y2 * height, 0, height - 1))
        color = np.array([0, 255, 0, 255], np.uint8)
        img[yi1:yi2 + 1, xi1] = color
        img[yi1:yi2 + 1, xi2] = color
        img[yi1, xi1:xi2 + 1] = color
        img[yi2, xi1:xi2 + 1] = color
        label = det.get("label")
        if label:
            from nnstreamer_tpu.decoders.overlay import draw_text

            draw_text(img, xi1 + 2, max(yi1 - 9, 0), str(label),
                      color=(0, 255, 0, 255))
    return img


#: device-path caps: greedy NMS keeps at most this many boxes per class /
#: in total (fixed shapes for XLA; the host path is unbounded)
DEVICE_K_PER_CLASS = 32
DEVICE_K_TOTAL = 100

#: padding sentinel in device-path score slots. Distinct from a legitimate
#: score of exactly 0 (possible in -postprocess mode with option3=0);
#: sigmoid-derived scores are always > 0 so any value < 0 is safe.
PAD_SCORE = -1.0


def _jax_nms(boxes, scores, iou_thresh, k):
    """Greedy NMS with static output size: (indices [k], scores [k]).

    Same selection rule as :func:`nms` (suppress iou > thresh); entries
    whose score is :data:`PAD_SCORE` are padding. ``scores`` must already
    have invalid rows set to PAD_SCORE. Runs as a ``fori_loop`` so the
    whole decode stays one XLA program."""
    import jax.numpy as jnp
    from jax import lax

    def body(i, state):
        left, keep_i, keep_s = state
        j = jnp.argmax(left)
        s = left[j]
        keep_i = keep_i.at[i].set(j.astype(jnp.int32))
        # pool exhausted → argmax lands on a PAD_SCORE entry: keep padding
        keep_s = keep_s.at[i].set(jnp.where(s > PAD_SCORE / 2, s, PAD_SCORE))
        b = boxes[j]
        yy1 = jnp.maximum(b[0], boxes[:, 0])
        xx1 = jnp.maximum(b[1], boxes[:, 1])
        yy2 = jnp.minimum(b[2], boxes[:, 2])
        xx2 = jnp.minimum(b[3], boxes[:, 3])
        inter = jnp.maximum(0.0, yy2 - yy1) * jnp.maximum(0.0, xx2 - xx1)
        area_b = (b[2] - b[0]) * (b[3] - b[1])
        areas = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        iou = inter / jnp.maximum(area_b + areas - inter, 1e-9)
        left = jnp.where(iou > iou_thresh, PAD_SCORE, left).at[j].set(
            PAD_SCORE)
        return left, keep_i, keep_s

    init = (scores, jnp.zeros((k,), jnp.int32),
            jnp.full((k,), PAD_SCORE, jnp.float32))
    _, keep_i, keep_s = lax.fori_loop(0, k, body, init)
    return keep_i, keep_s


def _rows_topk(boxes, cls_ids, scores, k_total):
    """Select the k_total highest-scoring (box, class, score) rows and pack
    them as [k_total, 6] = (y1,x1,y2,x2,class,score); score==PAD_SCORE is
    padding."""
    import jax.numpy as jnp
    from jax import lax

    top_s, top_i = lax.top_k(scores, min(k_total, scores.shape[0]))
    sel = boxes[top_i]
    cls = cls_ids[top_i].astype(jnp.float32)
    return jnp.concatenate(
        [sel, cls[:, None], top_s[:, None]], axis=1)


@subplugin(DECODER, "bounding_boxes")
class BoundingBoxes:
    def __init__(self):
        self._labels = None
        self._anchors = None
        self._warned_saturated = False

    #: legacy names and same-format aliases (reference bb_modes[],
    #: tensordec-boundingbox.c:157-166: tflite-ssd/tf-ssd are the old names;
    #: ov-face-detection shares the ov-person row format end to end)
    MODE_ALIASES = {
        "tflite-ssd": "mobilenet-ssd",
        "tf-ssd": "mobilenet-ssd-postprocess",
        "ov-face-detection": "ov-person-detection",
    }

    def _opts(self, options: Dict[str, str]) -> dict:
        size = (options.get("option4") or "300:300").split(":")
        mode = options.get("option1", "mobilenet-ssd")
        return dict(
            mode=self.MODE_ALIASES.get(mode, mode),
            labels_path=options.get("option2"),
            score_thresh=float(options.get("option3") or 0.5),
            width=int(size[0]), height=int(size[1]),
            iou_thresh=float(options.get("option5") or 0.5),
            meta_only=(options.get("option7") == "meta"),
        )

    def out_caps(self, config, options) -> Caps:
        o = self._opts(options)
        if o["meta_only"]:
            return Caps("other/tensors", {"format": "flexible"})
        return Caps("video/x-raw", {"format": "RGBA", "width": o["width"],
                                    "height": o["height"]})

    def _get_anchors(self, num_anchors: int, image_size: int) -> np.ndarray:
        if self._anchors is None or self._anchors.shape[0] != num_anchors:
            from nnstreamer_tpu.models.ssd_mobilenet import anchor_grid

            self._anchors = anchor_grid(image_size)
            if self._anchors.shape[0] != num_anchors:
                raise ValueError(
                    f"bounding_boxes: anchor grid {self._anchors.shape[0]} "
                    f"!= model anchors {num_anchors}"
                )
        return self._anchors

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        o = self._opts(options)
        mode = o["mode"]
        if mode == "mobilenet-ssd":
            box_enc = np.asarray(buf[0], np.float32)
            scores = np.asarray(buf[1], np.float32)
            if box_enc.ndim == 3:  # [N, A, 4] batch of 1
                box_enc, scores = box_enc[0], scores[0]
            anchors = self._get_anchors(box_enc.shape[0], o["width"])
            dets = decode_ssd(box_enc, scores, anchors,
                              o["score_thresh"], o["iou_thresh"])
        elif mode == "mobilenet-ssd-postprocess":
            # already-decoded boxes [A,4] + scores [A] + classes [A]
            boxes = np.asarray(buf[0], np.float32).reshape(-1, 4)
            scores = np.asarray(buf[1], np.float32).reshape(-1)
            classes = (np.asarray(buf[2]).reshape(-1).astype(int)
                       if buf.num_tensors > 2 else np.ones(len(scores), int))
            mask = scores >= o["score_thresh"]
            dets = [{"class": int(c), "score": float(s),
                     "box": [float(v) for v in b]}
                    for b, s, c in zip(boxes[mask], scores[mask],
                                       classes[mask])]
        elif mode == "yolov5":
            # [A, 5+classes]: cx,cy,w,h,objectness,class-scores
            pred = np.asarray(buf[0], np.float32)
            if pred.ndim == 3:
                pred = pred[0]
            obj = 1 / (1 + np.exp(-pred[:, 4]))
            cls_p = 1 / (1 + np.exp(-pred[:, 5:])) * obj[:, None]
            best = cls_p.argmax(axis=1)
            score = cls_p[np.arange(len(best)), best]
            mask = score >= o["score_thresh"]
            cx, cy, w, h = (pred[mask, i] for i in range(4))
            boxes = np.stack([cy - h / 2, cx - w / 2, cy + h / 2,
                              cx + w / 2], axis=1)
            keep = nms(boxes, score[mask], o["iou_thresh"])
            bi, ci = np.flatnonzero(mask), best[mask]
            dets = [{"class": int(ci[i]), "score": float(score[mask][i]),
                     "box": [float(v) for v in boxes[i]]} for i in keep]
        elif mode == "ov-person-detection":
            # OpenVINO person-detection-retail: [1,1,N,7] rows of
            # (image_id, label, conf, x_min, y_min, x_max, y_max),
            # normalized corners; stream ends at image_id < 0
            # (reference tensordec-boundingbox.c OV_PERSON_DETECTION_*,
            # default threshold 0.8)
            rows = np.asarray(buf[0], np.float32).reshape(-1, 7)
            thresh = float(options.get("option3") or 0.8)
            dets = []
            for r in rows:
                if r[0] < 0:
                    break
                if r[2] < thresh:
                    continue
                dets.append({"class": int(r[1]), "score": float(r[2]),
                             "box": [float(r[4]), float(r[3]),
                                     float(r[6]), float(r[5])]})
        else:
            raise ValueError(f"bounding_boxes: unknown mode {mode!r}")

        return self._emit(buf, dets, o)

    def _emit(self, buf: TensorBuffer, dets: List[dict], o: dict
              ) -> TensorBuffer:
        if self._labels is None and o["labels_path"]:
            from nnstreamer_tpu.decoders.image_labeling import load_labels

            self._labels = load_labels(o["labels_path"])
        if self._labels:
            for d in dets:
                if d["class"] < len(self._labels):
                    d["label"] = self._labels[d["class"]]

        meta = {**buf.meta, "detections": dets}
        if o["meta_only"]:
            flat = np.asarray(
                [[d["box"][0], d["box"][1], d["box"][2], d["box"][3],
                  d["class"], d["score"]] for d in dets], np.float32
            ).reshape(-1, 6) if dets else np.zeros((0, 6), np.float32)
            return buf.with_tensors([flat]).replace(meta=meta)
        overlay = draw_boxes(o["width"], o["height"], dets)
        return buf.with_tensors([overlay]).replace(meta=meta)

    # -- fused-region split (elements/decoder.py device_stage) ---------------
    def device_kernel(self, options):
        """Device half of decode(): anchor decode + sigmoid + per-class
        greedy NMS + global top-k, entirely inside the fused XLA program —
        only [DEVICE_K_TOTAL, 6] rows ever leave the device. The host path
        (decode()) is unbounded; the device path caps detections at
        DEVICE_K_PER_CLASS per class / DEVICE_K_TOTAL total."""
        import jax
        import jax.numpy as jnp

        o = self._opts(options)
        mode = o["mode"]
        thresh, iou_t = o["score_thresh"], o["iou_thresh"]

        if mode == "mobilenet-ssd":
            from nnstreamer_tpu.models.ssd_mobilenet import anchor_grid

            anchors = jnp.asarray(anchor_grid(o["width"]), jnp.float32)

            def fn(consts, tensors):
                anc = consts
                box_enc = tensors[0].astype(jnp.float32)
                scores = tensors[1].astype(jnp.float32)
                if box_enc.ndim == 3:  # [N,A,4] batch — host uses image 0
                    box_enc, scores = box_enc[0], scores[0]
                box_enc = box_enc.reshape(-1, 4)
                scores = scores.reshape(box_enc.shape[0], -1)
                cy = box_enc[:, 0] / 10.0 * anc[:, 2] + anc[:, 0]
                cx = box_enc[:, 1] / 10.0 * anc[:, 3] + anc[:, 1]
                h = jnp.exp(box_enc[:, 2] / 5.0) * anc[:, 2]
                w = jnp.exp(box_enc[:, 3] / 5.0) * anc[:, 3]
                boxes = jnp.stack([cy - h / 2, cx - w / 2,
                                   cy + h / 2, cx + w / 2], axis=1)
                probs = jax.nn.sigmoid(scores)

                def per_class(cls_probs):
                    s = jnp.where(cls_probs >= thresh, cls_probs, PAD_SCORE)
                    return _jax_nms(boxes, s, iou_t, DEVICE_K_PER_CLASS)

                # class 0 = background (host decode_ssd skips it too)
                idx, sc = jax.vmap(per_class, in_axes=1)(probs[:, 1:])
                n_cls = idx.shape[0]
                cls_ids = jnp.broadcast_to(
                    jnp.arange(1, n_cls + 1)[:, None], idx.shape)
                flat_boxes = boxes[idx.reshape(-1)]
                return [_rows_topk(flat_boxes, cls_ids.reshape(-1),
                                   sc.reshape(-1), DEVICE_K_TOTAL)]

            return anchors, fn

        if mode == "yolov5":
            def fn(consts, tensors):
                pred = tensors[0].astype(jnp.float32)
                if pred.ndim == 3:  # [N,A,C] batch — host uses image 0
                    pred = pred[0]
                pred = pred.reshape(-1, pred.shape[-1])
                obj = jax.nn.sigmoid(pred[:, 4])
                cls_p = jax.nn.sigmoid(pred[:, 5:]) * obj[:, None]
                best = jnp.argmax(cls_p, axis=1)
                score = jnp.max(cls_p, axis=1)
                score = jnp.where(score >= thresh, score, PAD_SCORE)
                cx, cy, w, h = (pred[:, i] for i in range(4))
                boxes = jnp.stack([cy - h / 2, cx - w / 2,
                                   cy + h / 2, cx + w / 2], axis=1)
                idx, sc = _jax_nms(boxes, score, iou_t, DEVICE_K_TOTAL)
                return [jnp.concatenate(
                    [boxes[idx], best[idx].astype(jnp.float32)[:, None],
                     sc[:, None]], axis=1)]

            return None, fn

        if mode == "mobilenet-ssd-postprocess":
            def fn(consts, tensors):
                boxes = tensors[0].reshape(-1, 4).astype(jnp.float32)
                scores = tensors[1].reshape(-1).astype(jnp.float32)
                if len(tensors) > 2:
                    classes = tensors[2].reshape(-1).astype(jnp.float32)
                else:
                    classes = jnp.ones_like(scores)
                masked = jnp.where(scores >= thresh, scores, PAD_SCORE)
                k = min(DEVICE_K_TOTAL, masked.shape[0])
                _, top_i = jax.lax.top_k(masked, k)
                # host path emits in anchor order — restore it
                top_i = jnp.sort(top_i)
                return [jnp.concatenate(
                    [boxes[top_i], classes[top_i][:, None],
                     masked[top_i][:, None]], axis=1)]

            return None, fn

        return None  # ov-person-detection: host-only semantics

    def host_finalize(self, host_buf: TensorBuffer, config, options
                      ) -> TensorBuffer:
        o = self._opts(options)
        rows = np.asarray(host_buf[0], np.float32).reshape(-1, 6)
        dets = [{"class": int(r[4]), "score": float(r[5]),
                 "box": [float(r[0]), float(r[1]), float(r[2]), float(r[3])]}
                for r in rows if r[5] > PAD_SCORE / 2]
        if len(dets) >= DEVICE_K_TOTAL and not self._warned_saturated:
            self._warned_saturated = True
            from nnstreamer_tpu.log import get_logger

            get_logger("decoders.bounding_boxes").warning(
                "device top-k saturated (all %d rows valid): dense scenes "
                "may be truncated vs the unbounded host path — raise "
                "DEVICE_K_TOTAL or disable fusion for exact results",
                DEVICE_K_TOTAL)
        return self._emit(host_buf, dets, o)
