"""bounding_boxes decoder — detection tensors → boxes (+ overlay video).

Reference: ``ext/nnstreamer/tensor_decoder/tensordec-boundingbox.c``
(1427 LoC) — modes mobilenet-ssd (anchor-decode + NMS), -postprocess
(pre-decoded boxes), yolov5/yolov8 (tensordec-boundingbox.c:128-139).
Output: either RGBA overlay video (reference behavior) or, with
``option7=meta``, the raw box list in buffer meta (TPU pipelines usually
want the structured result, not pixels).

Options (mirroring the reference's option1..N):
  option1: mode — mobilenet-ssd | mobilenet-ssd-postprocess | yolov5
  option2: labels file
  option3: score threshold (default 0.5)        [reference: custom props]
  option4: video WIDTH:HEIGHT for overlay scaling (default 300:300)
  option5: iou threshold for NMS (default 0.5)
  option7: "meta" → no overlay, boxes in meta only
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from nnstreamer_tpu.pipeline.caps import Caps
from nnstreamer_tpu.registry import DECODER, subplugin
from nnstreamer_tpu.tensors.buffer import TensorBuffer


def nms(boxes: np.ndarray, scores: np.ndarray, iou_thresh: float = 0.5,
        max_out: int = 100) -> List[int]:
    """Greedy non-max suppression; boxes [N,4] as (y1,x1,y2,x2)."""
    order = np.argsort(-scores)
    keep: List[int] = []
    while order.size and len(keep) < max_out:
        i = order[0]
        keep.append(int(i))
        if order.size == 1:
            break
        rest = order[1:]
        yy1 = np.maximum(boxes[i, 0], boxes[rest, 0])
        xx1 = np.maximum(boxes[i, 1], boxes[rest, 1])
        yy2 = np.minimum(boxes[i, 2], boxes[rest, 2])
        xx2 = np.minimum(boxes[i, 3], boxes[rest, 3])
        inter = np.maximum(0, yy2 - yy1) * np.maximum(0, xx2 - xx1)
        area_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        area_r = (boxes[rest, 2] - boxes[rest, 0]) * \
            (boxes[rest, 3] - boxes[rest, 1])
        iou = inter / np.maximum(area_i + area_r - inter, 1e-9)
        order = rest[iou <= iou_thresh]
    return keep


def decode_ssd(box_enc: np.ndarray, scores: np.ndarray,
               anchors: np.ndarray, score_thresh: float,
               iou_thresh: float) -> List[dict]:
    """Anchor-relative SSD decode (reference mobilenet-ssd mode math):
    box_enc [A,4] as (ty,tx,th,tw) vs anchors [A,4] (cy,cx,h,w)."""
    cy = box_enc[:, 0] / 10.0 * anchors[:, 2] + anchors[:, 0]
    cx = box_enc[:, 1] / 10.0 * anchors[:, 3] + anchors[:, 1]
    h = np.exp(box_enc[:, 2] / 5.0) * anchors[:, 2]
    w = np.exp(box_enc[:, 3] / 5.0) * anchors[:, 3]
    boxes = np.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], axis=1)
    probs = 1.0 / (1.0 + np.exp(-scores))  # sigmoid scores
    out = []
    for cls in range(1, probs.shape[1]):  # class 0 = background
        mask = probs[:, cls] >= score_thresh
        if not mask.any():
            continue
        cls_boxes, cls_scores = boxes[mask], probs[mask, cls]
        for i in nms(cls_boxes, cls_scores, iou_thresh):
            out.append({
                "class": cls,
                "score": float(cls_scores[i]),
                "box": [float(v) for v in cls_boxes[i]],  # y1,x1,y2,x2 ∈[0,1]
            })
    out.sort(key=lambda d: -d["score"])
    return out


def draw_boxes(width: int, height: int, detections: List[dict]
               ) -> np.ndarray:
    """RGBA overlay frame (transparent except box outlines) — the
    reference's output form for compositing over video."""
    img = np.zeros((height, width, 4), np.uint8)
    for det in detections:
        y1, x1, y2, x2 = det["box"]
        xi1, yi1 = int(np.clip(x1 * width, 0, width - 1)), \
            int(np.clip(y1 * height, 0, height - 1))
        xi2, yi2 = int(np.clip(x2 * width, 0, width - 1)), \
            int(np.clip(y2 * height, 0, height - 1))
        color = np.array([0, 255, 0, 255], np.uint8)
        img[yi1:yi2 + 1, xi1] = color
        img[yi1:yi2 + 1, xi2] = color
        img[yi1, xi1:xi2 + 1] = color
        img[yi2, xi1:xi2 + 1] = color
        label = det.get("label")
        if label:
            from nnstreamer_tpu.decoders.overlay import draw_text

            draw_text(img, xi1 + 2, max(yi1 - 9, 0), str(label),
                      color=(0, 255, 0, 255))
    return img


@subplugin(DECODER, "bounding_boxes")
class BoundingBoxes:
    def __init__(self):
        self._labels = None
        self._anchors = None

    def _opts(self, options: Dict[str, str]) -> dict:
        size = (options.get("option4") or "300:300").split(":")
        return dict(
            mode=options.get("option1", "mobilenet-ssd"),
            labels_path=options.get("option2"),
            score_thresh=float(options.get("option3") or 0.5),
            width=int(size[0]), height=int(size[1]),
            iou_thresh=float(options.get("option5") or 0.5),
            meta_only=(options.get("option7") == "meta"),
        )

    def out_caps(self, config, options) -> Caps:
        o = self._opts(options)
        if o["meta_only"]:
            return Caps("other/tensors", {"format": "flexible"})
        return Caps("video/x-raw", {"format": "RGBA", "width": o["width"],
                                    "height": o["height"]})

    def _get_anchors(self, num_anchors: int, image_size: int) -> np.ndarray:
        if self._anchors is None or self._anchors.shape[0] != num_anchors:
            from nnstreamer_tpu.models.ssd_mobilenet import anchor_grid

            self._anchors = anchor_grid(image_size)
            if self._anchors.shape[0] != num_anchors:
                raise ValueError(
                    f"bounding_boxes: anchor grid {self._anchors.shape[0]} "
                    f"!= model anchors {num_anchors}"
                )
        return self._anchors

    def decode(self, buf: TensorBuffer, config, options) -> TensorBuffer:
        o = self._opts(options)
        mode = o["mode"]
        if mode == "mobilenet-ssd":
            box_enc = np.asarray(buf[0], np.float32)
            scores = np.asarray(buf[1], np.float32)
            if box_enc.ndim == 3:  # [N, A, 4] batch of 1
                box_enc, scores = box_enc[0], scores[0]
            anchors = self._get_anchors(box_enc.shape[0], o["width"])
            dets = decode_ssd(box_enc, scores, anchors,
                              o["score_thresh"], o["iou_thresh"])
        elif mode == "mobilenet-ssd-postprocess":
            # already-decoded boxes [A,4] + scores [A] + classes [A]
            boxes = np.asarray(buf[0], np.float32).reshape(-1, 4)
            scores = np.asarray(buf[1], np.float32).reshape(-1)
            classes = (np.asarray(buf[2]).reshape(-1).astype(int)
                       if buf.num_tensors > 2 else np.ones(len(scores), int))
            mask = scores >= o["score_thresh"]
            dets = [{"class": int(c), "score": float(s),
                     "box": [float(v) for v in b]}
                    for b, s, c in zip(boxes[mask], scores[mask],
                                       classes[mask])]
        elif mode == "yolov5":
            # [A, 5+classes]: cx,cy,w,h,objectness,class-scores
            pred = np.asarray(buf[0], np.float32)
            if pred.ndim == 3:
                pred = pred[0]
            obj = 1 / (1 + np.exp(-pred[:, 4]))
            cls_p = 1 / (1 + np.exp(-pred[:, 5:])) * obj[:, None]
            best = cls_p.argmax(axis=1)
            score = cls_p[np.arange(len(best)), best]
            mask = score >= o["score_thresh"]
            cx, cy, w, h = (pred[mask, i] for i in range(4))
            boxes = np.stack([cy - h / 2, cx - w / 2, cy + h / 2,
                              cx + w / 2], axis=1)
            keep = nms(boxes, score[mask], o["iou_thresh"])
            bi, ci = np.flatnonzero(mask), best[mask]
            dets = [{"class": int(ci[i]), "score": float(score[mask][i]),
                     "box": [float(v) for v in boxes[i]]} for i in keep]
        elif mode == "ov-person-detection":
            # OpenVINO person-detection-retail: [1,1,N,7] rows of
            # (image_id, label, conf, x_min, y_min, x_max, y_max),
            # normalized corners; stream ends at image_id < 0
            # (reference tensordec-boundingbox.c OV_PERSON_DETECTION_*,
            # default threshold 0.8)
            rows = np.asarray(buf[0], np.float32).reshape(-1, 7)
            thresh = float(options.get("option3") or 0.8)
            dets = []
            for r in rows:
                if r[0] < 0:
                    break
                if r[2] < thresh:
                    continue
                dets.append({"class": int(r[1]), "score": float(r[2]),
                             "box": [float(r[4]), float(r[3]),
                                     float(r[6]), float(r[5])]})
        else:
            raise ValueError(f"bounding_boxes: unknown mode {mode!r}")

        if self._labels is None and o["labels_path"]:
            from nnstreamer_tpu.decoders.image_labeling import load_labels

            self._labels = load_labels(o["labels_path"])
        if self._labels:
            for d in dets:
                if d["class"] < len(self._labels):
                    d["label"] = self._labels[d["class"]]

        meta = {**buf.meta, "detections": dets}
        if o["meta_only"]:
            flat = np.asarray(
                [[d["box"][0], d["box"][1], d["box"][2], d["box"][3],
                  d["class"], d["score"]] for d in dets], np.float32
            ).reshape(-1, 6) if dets else np.zeros((0, 6), np.float32)
            return buf.with_tensors([flat]).replace(meta=meta)
        overlay = draw_boxes(o["width"], o["height"], dets)
        return buf.with_tensors([overlay]).replace(meta=meta)
